/*
Copyright (c) 2012-2015 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "include.h"
#include "version.h"
#include "net/server.h"
#include "ssdb/ssdb.h"
#include "util/app.h"
#include "serv.h"

#define APP_NAME "ssdb-server"
#define APP_VERSION SSDB_VERSION

class MyApplication : public Application
{
public:
	virtual void usage(int argc, char **argv);
	virtual void welcome();
	virtual void run();
};

void MyApplication::welcome(){
	fprintf(stderr, "%s %s\n", APP_NAME, APP_VERSION);
	fprintf(stderr, "Copyright (c) 2012-2015 ssdb.io\n");
	fprintf(stderr, "\n");
}

void MyApplication::usage(int argc, char **argv){
	printf("Usage:\n");
	printf("    %s [-d] /path/to/ssdb.conf [-s start|stop|restart]\n", argv[0]);
	printf("Options:\n");
	printf("    -d    run as daemon\n");
	printf("    -s    option to start|stop|restart the server\n");
	printf("    -h    show this message\n");
}

void MyApplication::run(){
	Options option;
	option.load(*conf);

	std::string data_db_dir = app_args.work_dir + "/data";
	std::string meta_db_dir = app_args.work_dir + "/meta";

	log_info("ssdb-server %s", APP_VERSION);
	log_info("conf_file        : %s", app_args.conf_file.c_str());
	log_info("log_level        : %s", Logger::shared()->level_name().c_str());
	log_info("log_output       : %s", Logger::shared()->output_name().c_str());
	log_info("log_rotate_size  : %" PRId64, Logger::shared()->rotate_size());

	log_info("main_db          : %s", data_db_dir.c_str());
	log_info("meta_db          : %s", meta_db_dir.c_str());
	log_info("cache_size       : %d MB", option.cache_size);
	log_info("block_size       : %d KB", option.block_size);
	log_info("write_buffer     : %d MB", option.write_buffer_size);
	log_info("max_open_files   : %d", option.max_open_files);
	log_info("compaction_speed : %d MB/s", option.compaction_speed);
	log_info("compression      : %s", option.compression.c_str());
	log_info("binlog           : %s", option.binlog? "yes" : "no");
	log_info("binlog_capacity  : %d", option.binlog_capacity);
	log_info("sync_speed       : %d MB/s", conf->get_num("replication.sync_speed"));

	SSDB *data_db = NULL;
	SSDB *meta_db = NULL;
	data_db = SSDB::open(option, data_db_dir);
	if(!data_db){
		log_fatal("could not open data db: %s", data_db_dir.c_str());
		fprintf(stderr, "could not open data db: %s\n", data_db_dir.c_str());
		exit(1);
	}

	meta_db = SSDB::open(Options(), meta_db_dir);
	if(!meta_db){
		log_fatal("could not open meta db: %s", meta_db_dir.c_str());
		fprintf(stderr, "could not open meta db: %s\n", meta_db_dir.c_str());
		exit(1);
	}

	NetworkServer *net = NULL;	
	SSDBServer *server;
	net = NetworkServer::init(*conf);
	server = new SSDBServer(data_db, meta_db, *conf, net);
	
	log_info("pidfile: %s, pid: %d", app_args.pidfile.c_str(), (int)getpid());
	log_info("ssdb server started.");
	net->serve();
	
	delete net;
	delete server;
	delete meta_db;
	delete data_db;

	log_info("%s exit.", APP_NAME);
}

int main(int argc, char **argv){
	MyApplication app;
	return app.main(argc, argv);
}
