/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "binlog.h"
#include "const.h"
#include "../include.h"
#include "../util/log.h"
#include "../util/strings.h"
#include <map>

/* Binlog */

Binlog::Binlog(uint64_t seq, char type, char cmd, const leveldb::Slice &key){
	buf.append((char *)(&seq), sizeof(uint64_t));
	buf.push_back(type);
	buf.push_back(cmd);
	buf.append(key.data(), key.size());
}

uint64_t Binlog::seq() const{
	return *((uint64_t *)(buf.data()));
}

char Binlog::type() const{
	return buf[sizeof(uint64_t)];
}

char Binlog::cmd() const{
	return buf[sizeof(uint64_t) + 1];
}

const Bytes Binlog::key() const{
	return Bytes(buf.data() + HEADER_LEN, buf.size() - HEADER_LEN);
}

int Binlog::load(const Bytes &s){
	if(s.size() < HEADER_LEN){
		return -1;
	}
	buf.assign(s.data(), s.size());
	return 0;
}

int Binlog::load(const leveldb::Slice &s){
	if(s.size() < HEADER_LEN){
		return -1;
	}
	buf.assign(s.data(), s.size());
	return 0;
}

int Binlog::load(const std::string &s){
	if(s.size() < HEADER_LEN){
		return -1;
	}
	buf.assign(s.data(), s.size());
	return 0;
}

std::string Binlog::dumps() const{
	std::string str;
	if(buf.size() < HEADER_LEN){
		return str;
	}
	char buf[20];
	snprintf(buf, sizeof(buf), "%" PRIu64 " ", this->seq());
	str.append(buf);

	switch(this->type()){
		case BinlogType::NOOP:
			str.append("noop ");
			break;
		case BinlogType::SYNC:
			str.append("sync ");
			break;
		case BinlogType::MIRROR:
			str.append("mirror ");
			break;
		case BinlogType::COPY:
			str.append("copy ");
			break;
		case BinlogType::CTRL:
			str.append("control ");
			break;
	}
	switch(this->cmd()){
		case BinlogCommand::NONE:
			str.append("none ");
			break;
		case BinlogCommand::KSET:
			str.append("set ");
			break;
		case BinlogCommand::KDEL:
			str.append("del ");
			break;
		case BinlogCommand::HSET:
			str.append("hset ");
			break;
		case BinlogCommand::HDEL:
			str.append("hdel ");
			break;
		case BinlogCommand::ZSET:
			str.append("zset ");
			break;
		case BinlogCommand::ZDEL:
			str.append("zdel ");
			break;
		case BinlogCommand::BEGIN:
			str.append("begin ");
			break;
		case BinlogCommand::END:
			str.append("end ");
			break;
		case BinlogCommand::QPUSH_BACK:
			str.append("qpush_back ");
			break;
		case BinlogCommand::QPUSH_FRONT:
			str.append("qpush_front ");
			break;
		case BinlogCommand::QPOP_BACK:
			str.append("qpop_back ");
			break;
		case BinlogCommand::QPOP_FRONT:
			str.append("qpop_front ");
			break;
		case BinlogCommand::QSET:
			str.append("qset ");
			break;
	}
	Bytes b = this->key();
	str.append(hexmem(b.data(), b.size()));
	return str;
}


/* SyncLogQueue */

static inline std::string encode_seq_key(uint64_t seq){
	seq = big_endian(seq);
	std::string ret;
	ret.push_back(DataType::SYNCLOG);
	ret.append((char *)&seq, sizeof(seq));
	return ret;
}

static inline uint64_t decode_seq_key(const leveldb::Slice &key){
	uint64_t seq = 0;
	if(key.size() == (sizeof(uint64_t) + 1) && key.data()[0] == DataType::SYNCLOG){
		seq = *((uint64_t *)(key.data() + 1));
		seq = big_endian(seq);
	}
	return seq;
}

BinlogQueue::BinlogQueue(leveldb::DB *db, bool enabled, int capacity){
	this->db = db;
	this->min_seq_ = 0;
	this->last_seq = 0;
	this->tran_seq = 0;
	this->capacity = capacity;
	this->enabled = enabled;
	
	Binlog log;
	if(this->find_last(&log) == 1){
		this->last_seq = log.seq();
	}
	// 下面这段代码是可能性能非常差!
	//if(this->find_next(0, &log) == 1){
	//	this->min_seq_ = log.seq();
	//}
	if(this->last_seq > this->capacity){
		this->min_seq_ = this->last_seq - this->capacity;
	}else{
		this->min_seq_ = 0;
	}
	if(this->find_next(this->min_seq_, &log) == 1){
		this->min_seq_ = log.seq();
	}
	if(this->enabled){
		log_info("binlogs capacity: %d, min: %" PRIu64 ", max: %" PRIu64 ",",
			this->capacity, this->min_seq_, this->last_seq);
		// 这个方法有性能问题
		// 但是, 如果不执行清理, 如果将 capacity 修改大, 可能会导致主从同步问题
		//this->clean_obsolete_binlogs();
	}

	// start cleaning thread
	if(this->enabled){
		thread_quit = false;
		pthread_t tid;
		int err = pthread_create(&tid, NULL, &BinlogQueue::log_clean_thread_func, this);
		if(err != 0){
			log_fatal("can't create thread: %s", strerror(err));
			exit(0);
		}
	}
}

BinlogQueue::~BinlogQueue(){
	if(this->enabled){
		thread_quit = true;
		for(int i=0; i<100; i++){
			if(thread_quit == false){
				break;
			}
			usleep(10 * 1000);
		}
	}
	db = NULL;
}

std::string BinlogQueue::stats() const{
	std::string s;
	s.append("    capacity : " + str(capacity) + "\n");
	s.append("    min_seq  : " + str(min_seq_) + "\n");
	s.append("    max_seq  : " + str(last_seq) + "");
	return s;
}

void BinlogQueue::begin(){
	tran_seq = last_seq;
	batch.Clear();
}

void BinlogQueue::rollback(){
	tran_seq = 0;
}

leveldb::Status BinlogQueue::commit(){
	leveldb::WriteOptions write_opts;
	leveldb::Status s = db->Write(write_opts, &batch);
	if(s.ok()){
		last_seq = tran_seq;
		tran_seq = 0;
	}
	return s;
}

void BinlogQueue::add_log(char type, char cmd, const leveldb::Slice &key){
	if(!enabled){
		return;
	}
	tran_seq ++;
	Binlog log(tran_seq, type, cmd, key);
	batch.Put(encode_seq_key(tran_seq), log.repr());
}

void BinlogQueue::add_log(char type, char cmd, const std::string &key){
	if(!enabled){
		return;
	}
	leveldb::Slice s(key);
	this->add_log(type, cmd, s);
}

// leveldb put
void BinlogQueue::Put(const leveldb::Slice& key, const leveldb::Slice& value){
	batch.Put(key, value);
}

// leveldb delete
void BinlogQueue::Delete(const leveldb::Slice& key){
	batch.Delete(key);
}
	
int BinlogQueue::find_next(uint64_t next_seq, Binlog *log) const{
	if(this->get(next_seq, log) == 1){
		return 1;
	}
	uint64_t ret = 0;
	std::string key_str = encode_seq_key(next_seq);
	leveldb::ReadOptions iterate_options;
	leveldb::Iterator *it = db->NewIterator(iterate_options);
	it->Seek(key_str);
	if(it->Valid()){
		leveldb::Slice key = it->key();
		if(decode_seq_key(key) != 0){
			leveldb::Slice val = it->value();
			if(log->load(val) == -1){
				ret = -1;
			}else{
				ret = 1;
			}
		}
	}
	delete it;
	return ret;
}

int BinlogQueue::find_last(Binlog *log) const{
	uint64_t ret = 0;
	std::string key_str = encode_seq_key(UINT64_MAX);
	leveldb::ReadOptions iterate_options;
	leveldb::Iterator *it = db->NewIterator(iterate_options);
	it->Seek(key_str);
	if(!it->Valid()){
		// Iterator::prev requires Valid, so we seek to last
		it->SeekToLast();
	}else{
		// UINT64_MAX is not used 
		it->Prev();
	}
	if(it->Valid()){
		leveldb::Slice key = it->key();
		if(decode_seq_key(key) != 0){
			leveldb::Slice val = it->value();
			if(log->load(val) == -1){
				ret = -1;
			}else{
				ret = 1;
			}
		}
	}
	delete it;
	return ret;
}

int BinlogQueue::get(uint64_t seq, Binlog *log) const{
	std::string val;
	leveldb::Status s = db->Get(leveldb::ReadOptions(), encode_seq_key(seq), &val);
	if(s.ok()){
		if(log->load(val) != -1){
			return 1;
		}
	}
	return 0;
}

int BinlogQueue::update(uint64_t seq, char type, char cmd, const std::string &key){
	Binlog log(seq, type, cmd, key);
	leveldb::Status s = db->Put(leveldb::WriteOptions(), encode_seq_key(seq), log.repr());
	if(s.ok()){
		return 0;
	}
	return -1;
}

int BinlogQueue::del(uint64_t seq){
	leveldb::Status s = db->Delete(leveldb::WriteOptions(), encode_seq_key(seq));
	if(!s.ok()){
		return -1;
	}
	return 0;
}

void BinlogQueue::flush(){
	del_range(this->min_seq_, this->last_seq);
}

int BinlogQueue::del_range(uint64_t start, uint64_t end){
	while(start <= end){
		leveldb::WriteBatch batch;
		for(int count = 0; start <= end && count < 1000; start++, count++){
			batch.Delete(encode_seq_key(start));
		}
		leveldb::Status s = db->Write(leveldb::WriteOptions(), &batch);
		if(!s.ok()){
			return -1;
		}
	}
	return 0;
}

void* BinlogQueue::log_clean_thread_func(void *arg){
	BinlogQueue *logs = (BinlogQueue *)arg;
	
	while(!logs->thread_quit){
		if(!logs->db){
			break;
		}
		assert(logs->last_seq >= logs->min_seq_);

		if(logs->last_seq - logs->min_seq_ < logs->capacity + 10000){
			usleep(50 * 1000);
			continue;
		}
		
		uint64_t start = logs->min_seq_;
		uint64_t end = logs->last_seq - logs->capacity;
		logs->del_range(start, end);
		logs->min_seq_ = end + 1;
		log_info("clean %d logs[%" PRIu64 " ~ %" PRIu64 "], %d left, max: %" PRIu64 "",
			end-start+1, start, end, logs->last_seq - logs->min_seq_ + 1, logs->last_seq);
	}
	log_debug("binlog clean_thread quit");
	
	logs->thread_quit = false;
	return (void *)NULL;
}

// 因为老版本可能产生了断续的binlog
// 例如, binlog-1 存在, 但后面的被删除了, 然后到 binlog-100000 时又开始存在.
void BinlogQueue::clean_obsolete_binlogs(){
	std::string key_str = encode_seq_key(this->min_seq_);
	leveldb::ReadOptions iterate_options;
	leveldb::Iterator *it = db->NewIterator(iterate_options);
	it->Seek(key_str);
	if(it->Valid()){
		it->Prev();
	}
	uint64_t count = 0;
	while(it->Valid()){
		leveldb::Slice key = it->key();
		uint64_t seq = decode_seq_key(key);
		if(seq == 0){
			break;
		}
		this->del(seq);
		
		it->Prev();
		count ++;
	}
	delete it;
	if(count > 0){
		log_info("clean_obsolete_binlogs: %" PRIu64, count);
	}
}

// TESTING, slow, so not used
void BinlogQueue::merge(){
	std::map<std::string, uint64_t> key_map;
	uint64_t start = min_seq_;
	uint64_t end = last_seq;
	int reduce_count = 0;
	int total = 0;
	total = end - start + 1;
	(void)total; // suppresses warning
	log_trace("merge begin");
	for(; start <= end; start++){
		Binlog log;
		if(this->get(start, &log) == 1){
			if(log.type() == BinlogType::NOOP){
				continue;
			}
			std::string key = log.key().String();
			std::map<std::string, uint64_t>::iterator it = key_map.find(key);
			if(it != key_map.end()){
				uint64_t seq = it->second;
				this->update(seq, BinlogType::NOOP, BinlogCommand::NONE, "");
				//log_trace("merge update %" PRIu64 " to NOOP", seq);
				reduce_count ++;
			}
			key_map[key] = log.seq();
		}
	}
	log_trace("merge reduce %d of %d binlogs", reduce_count, total);
}
