/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef SSDB_BINLOG_H_
#define SSDB_BINLOG_H_

#include <string>
#include "leveldb/db.h"
#include "leveldb/options.h"
#include "leveldb/slice.h"
#include "leveldb/status.h"
#include "leveldb/write_batch.h"
#include "../util/thread.h"
#include "../util/bytes.h"


class Binlog{
private:
	std::string buf;
	static const unsigned int HEADER_LEN = sizeof(uint64_t) + 2;
public:
	Binlog(){}
	Binlog(uint64_t seq, char type, char cmd, const leveldb::Slice &key);
		
	int load(const Bytes &s);
	int load(const leveldb::Slice &s);
	int load(const std::string &s);

	uint64_t seq() const;
	char type() const;
	char cmd() const;
	const Bytes key() const;

	const char* data() const{
		return buf.data();
	}
	int size() const{
		return (int)buf.size();
	}
	const std::string repr() const{
		return this->buf;
	}
	std::string dumps() const;
};

// circular queue
class BinlogQueue{
private:
	leveldb::DB *db;
	uint64_t min_seq_;
	uint64_t last_seq;
	uint64_t tran_seq;
	int capacity;
	leveldb::WriteBatch batch;

	volatile bool thread_quit;
	static void* log_clean_thread_func(void *arg);
	int del(uint64_t seq);
	// [start, end] includesive
	int del_range(uint64_t start, uint64_t end);
	
	void clean_obsolete_binlogs();
	void merge();
	bool enabled;
public:
	Mutex mutex;

	BinlogQueue(leveldb::DB *db, bool enabled=true, int capacity=20000000);
	~BinlogQueue();
	void begin();
	void rollback();
	leveldb::Status commit();
	// leveldb put
	void Put(const leveldb::Slice& key, const leveldb::Slice& value);
	// leveldb delete
	void Delete(const leveldb::Slice& key);
	void add_log(char type, char cmd, const leveldb::Slice &key);
	void add_log(char type, char cmd, const std::string &key);
		
	int get(uint64_t seq, Binlog *log) const;
	int update(uint64_t seq, char type, char cmd, const std::string &key);
		
	void flush();
		
	/** @returns
	 1 : log.seq greater than or equal to seq
	 0 : not found
	 -1: error
	 */
	int find_next(uint64_t seq, Binlog *log) const;
	int find_last(Binlog *log) const;
	
	uint64_t min_seq() const{
		return min_seq_;
	}
	uint64_t max_seq() const{
		return last_seq;
	}
		
	std::string stats() const;
};

class Transaction{
private:
	BinlogQueue *logs;
public:
	Transaction(BinlogQueue *logs){
		this->logs = logs;
		logs->mutex.lock();
		logs->begin();
	}
	
	~Transaction(){
		// it is safe to call rollback after commit
		logs->rollback();
		logs->mutex.unlock();
	}
};


#endif
