/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef SSDB_CONST_H_
#define SSDB_CONST_H_

static const int SSDB_SCORE_WIDTH		= 9;
static const int SSDB_KEY_LEN_MAX		= 255;

class DataType{
public:
	static const char SYNCLOG	= 1;
	static const char KV		= 'k';
	static const char HASH		= 'h'; // hashmap(sorted by key)
	static const char HSIZE		= 'H';
	static const char ZSET		= 's'; // key => score
	static const char ZSCORE	= 'z'; // key|score => ""
	static const char ZSIZE		= 'Z';
	static const char QUEUE		= 'q';
	static const char QSIZE		= 'Q';
	static const char MIN_PREFIX = HASH;
	static const char MAX_PREFIX = ZSET;
};

class BinlogType{
public:
	static const char NOOP		= 0;
	static const char SYNC		= 1;
	static const char MIRROR	= 2;
	static const char COPY		= 3;
	static const char CTRL		= 4;
};

class BinlogCommand{
public:
	static const char NONE  = 0;
	static const char KSET  = 1;
	static const char KDEL  = 2;
	static const char HSET  = 3;
	static const char HDEL  = 4;
	static const char ZSET  = 5;
	static const char ZDEL  = 6;

	static const char QPUSH_BACK	= 10;
	static const char QPUSH_FRONT	= 11;
	static const char QPOP_BACK		= 12;
	static const char QPOP_FRONT	= 13;
	static const char QSET			= 14;
	
	static const char BEGIN  = 7;
	static const char END    = 8;
};

#endif
