/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "iterator.h"
#include "t_kv.h"
#include "t_hash.h"
#include "t_zset.h"
#include "t_queue.h"
#include "../util/log.h"
#include "../util/config.h"
#include "leveldb/iterator.h"

Iterator::Iterator(leveldb::Iterator *it,
		const std::string &end,
		uint64_t limit,
		Direction direction)
{
	this->it = it;
	this->end = end;
	this->limit = limit;
	this->is_first = true;
	this->direction = direction;
}

Iterator::~Iterator(){
	delete it;
}

Bytes Iterator::key(){
	leveldb::Slice s = it->key();
	return Bytes(s.data(), s.size());
}

Bytes Iterator::val(){
	leveldb::Slice s = it->value();
	return Bytes(s.data(), s.size());
}

bool Iterator::skip(uint64_t offset){
	while(offset-- > 0){
		if(this->next() == false){
			return false;
		}
	}
	return true;
}

bool Iterator::next(){
	if(limit == 0){
		return false;
	}
	if(is_first){
		is_first = false;
	}else{
		if(direction == FORWARD){
			it->Next();
		}else{
			it->Prev();
		}
	}

	if(!it->Valid()){
		// make next() safe to be called after previous return false.
		limit = 0;
		return false;
	}
	if(direction == FORWARD){
		if(!end.empty() && it->key().compare(end) > 0){
			limit = 0;
			return false;
		}
	}else{
		if(!end.empty() && it->key().compare(end) < 0){
			limit = 0;
			return false;
		}
	}
	limit --;
	return true;
}


/* KV */

KIterator::KIterator(Iterator *it){
	this->it = it;
	this->return_val_ = true;
}

KIterator::~KIterator(){
	delete it;
}

void KIterator::return_val(bool onoff){
	this->return_val_ = onoff;
}

bool KIterator::next(){
	while(it->next()){
		Bytes ks = it->key();
		Bytes vs = it->val();
		//dump(ks.data(), ks.size(), "z.next");
		//dump(vs.data(), vs.size(), "z.next");
		if(ks.data()[0] != DataType::KV){
			return false;
		}
		if(decode_kv_key(ks, &this->key) == -1){
			continue;
		}
		if(return_val_){
			this->val.assign(vs.data(), vs.size());
		}
		return true;
	}
	return  false;
}

/* HASH */

HIterator::HIterator(Iterator *it, const Bytes &name){
	this->it = it;
	this->name.assign(name.data(), name.size());
	this->return_val_ = true;
}

HIterator::~HIterator(){
	delete it;
}

void HIterator::return_val(bool onoff){
	this->return_val_ = onoff;
}

bool HIterator::next(){
	while(it->next()){
		Bytes ks = it->key();
		Bytes vs = it->val();
		//dump(ks.data(), ks.size(), "z.next");
		//dump(vs.data(), vs.size(), "z.next");
		if(ks.data()[0] != DataType::HASH){
			return false;
		}
		std::string n;
		if(decode_hash_key(ks, &n, &key) == -1){
			continue;
		}
		if(n != this->name){
			return false;
		}
		if(return_val_){
			this->val.assign(vs.data(), vs.size());
		}
		return true;
	}
	return false;
}

/* ZSET */

ZIterator::ZIterator(Iterator *it, const Bytes &name){
	this->it = it;
	this->name.assign(name.data(), name.size());
}

ZIterator::~ZIterator(){
	delete it;
}
		
bool ZIterator::skip(uint64_t offset){
	while(offset-- > 0){
		if(this->next() == false){
			return false;
		}
	}
	return true;
}

bool ZIterator::next(){
	while(it->next()){
		Bytes ks = it->key();
		//Bytes vs = it->val();
		//dump(ks.data(), ks.size(), "z.next");
		//dump(vs.data(), vs.size(), "z.next");
		if(ks.data()[0] != DataType::ZSCORE){
			return false;
		}
		if(decode_zscore_key(ks, NULL, &key, &score) == -1){
			continue;
		}
		return true;
	}
	return false;
}
