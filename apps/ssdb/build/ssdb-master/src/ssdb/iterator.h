/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef SSDB_ITERATOR_H_
#define SSDB_ITERATOR_H_

#include <inttypes.h>
#include <string>
#include "../util/bytes.h"

namespace leveldb{
	class Iterator;
}

class Iterator{
public:
	enum Direction{
		FORWARD, BACKWARD
	};
	Iterator(leveldb::Iterator *it,
			const std::string &end,
			uint64_t limit,
			Direction direction=Iterator::FORWARD);
	~Iterator();
	bool skip(uint64_t offset);
	bool next();
	Bytes key();
	Bytes val();
private:
	leveldb::Iterator *it;
	std::string end;
	uint64_t limit;
	bool is_first;
	int direction;
};


class KIterator{
public:
	std::string key;
	std::string val;

	KIterator(Iterator *it);
	~KIterator();
	void return_val(bool onoff);
	bool next();
private:
	Iterator *it;
	bool return_val_;
};


class HIterator{
public:
	std::string name;
	std::string key;
	std::string val;

	HIterator(Iterator *it, const Bytes &name);
	~HIterator();
	void return_val(bool onoff);
	bool next();
private:
	Iterator *it;
	bool return_val_;
};


class ZIterator{
public:
	std::string name;
	std::string key;
	std::string score;

	ZIterator(Iterator *it, const Bytes &name);
	~ZIterator();
	bool skip(uint64_t offset);
	bool next();
private:
	Iterator *it;
};


#endif
