/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "options.h"
#include "../util/strings.h"

#ifdef NDEBUG
	static const int LOG_QUEUE_SIZE  = 20 * 1000 * 1000;
#else
	static const int LOG_QUEUE_SIZE  = 10000;
#endif

Options::Options(){
	Config c;
	this->load(c);
}

void Options::load(const Config &conf){
	cache_size = (size_t)conf.get_num("leveldb.cache_size");
	max_open_files = (size_t)conf.get_num("leveldb.max_open_files");
	write_buffer_size = (size_t)conf.get_num("leveldb.write_buffer_size");
	block_size = (size_t)conf.get_num("leveldb.block_size");
	compaction_speed = conf.get_num("leveldb.compaction_speed");
	compression = conf.get_str("leveldb.compression");
	std::string binlog = conf.get_str("replication.binlog");
	binlog_capacity = (size_t)conf.get_num("replication.binlog.capacity");

	strtolower(&compression);
	if(compression != "no"){
		compression = "yes";
	}
	strtolower(&binlog);
	if(binlog != "yes"){
		this->binlog = false;
	}else{
		this->binlog = true;
	}
	if(binlog_capacity <= 0){
		binlog_capacity = LOG_QUEUE_SIZE;
	}

	if(cache_size <= 0){
		cache_size = 16;
	}
	if(write_buffer_size <= 0){
		write_buffer_size = 16;
	}
	if(block_size <= 0){
		block_size = 16;
	}
	if(max_open_files <= 0){
		max_open_files = cache_size / 1024 * 300;
		if(max_open_files < 500){
			max_open_files = 500;
		}
		if(max_open_files > 1000){
			max_open_files = 1000;
		}
	}
}
