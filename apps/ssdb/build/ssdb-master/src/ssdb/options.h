/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef SSDB_OPTION_H_
#define SSDB_OPTION_H_

#include "../util/config.h"

class Options
{
public:
	Options();
	~Options(){}
	
	void load(const Config &conf);

	size_t cache_size;
	size_t max_open_files;
	size_t write_buffer_size;
	size_t block_size;
	int compaction_speed;
	std::string compression;
	bool binlog;
	size_t binlog_capacity;
};

#endif
