/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef SSDB_H_
#define SSDB_H_

#include <vector>
#include <string>
#include "const.h"
#include "options.h"
#include "iterator.h"

class Bytes;
class Config;

class SSDB{
public:
	SSDB(){}
	virtual ~SSDB(){};
	static SSDB* open(const Options &opt, const std::string &base_dir);
	
	virtual int flushdb() = 0;

	// return (start, end], not include start
	virtual Iterator* iterator(const std::string &start, const std::string &end, uint64_t limit) = 0;
	virtual Iterator* rev_iterator(const std::string &start, const std::string &end, uint64_t limit) = 0;

	//void flushdb();
	virtual uint64_t size() = 0;
	virtual std::vector<std::string> info() = 0;
	virtual void compact() = 0;
	virtual int key_range(std::vector<std::string> *keys) = 0;

	/* raw operates */

	// repl: whether to sync this operation to slaves
	virtual int raw_set(const Bytes &key, const Bytes &val) = 0;
	virtual int raw_del(const Bytes &key) = 0;
	virtual int raw_get(const Bytes &key, std::string *val) = 0;

	/* key value */

	virtual int set(const Bytes &key, const Bytes &val, char log_type=BinlogType::SYNC) = 0;
	virtual int setnx(const Bytes &key, const Bytes &val, char log_type=BinlogType::SYNC) = 0;
	virtual int del(const Bytes &key, char log_type=BinlogType::SYNC) = 0;
	// -1: error, 1: ok, 0: value is not an integer or out of range
	virtual int incr(const Bytes &key, int64_t by, int64_t *new_val, char log_type=BinlogType::SYNC) = 0;
	virtual int multi_set(const std::vector<Bytes> &kvs, int offset=0, char log_type=BinlogType::SYNC) = 0;
	virtual int multi_del(const std::vector<Bytes> &keys, int offset=0, char log_type=BinlogType::SYNC) = 0;
	virtual int setbit(const Bytes &key, int bitoffset, int on, char log_type=BinlogType::SYNC) = 0;
	virtual int getbit(const Bytes &key, int bitoffset) = 0;
	
	virtual int get(const Bytes &key, std::string *val) = 0;
	virtual int getset(const Bytes &key, std::string *val, const Bytes &newval, char log_type=BinlogType::SYNC) = 0;
	// return (start, end]
	virtual KIterator* scan(const Bytes &start, const Bytes &end, uint64_t limit) = 0;
	virtual KIterator* rscan(const Bytes &start, const Bytes &end, uint64_t limit) = 0;

	/* hash */

	virtual int hset(const Bytes &name, const Bytes &key, const Bytes &val, char log_type=BinlogType::SYNC) = 0;
	virtual int hdel(const Bytes &name, const Bytes &key, char log_type=BinlogType::SYNC) = 0;
	// -1: error, 1: ok, 0: value is not an integer or out of range
	virtual int hincr(const Bytes &name, const Bytes &key, int64_t by, int64_t *new_val, char log_type=BinlogType::SYNC) = 0;

	virtual int64_t hsize(const Bytes &name) = 0;
	virtual int64_t hclear(const Bytes &name) = 0;
	virtual int hget(const Bytes &name, const Bytes &key, std::string *val) = 0;
	virtual int hlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
			std::vector<std::string> *list) = 0;
	virtual int hrlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
			std::vector<std::string> *list) = 0;
	virtual HIterator* hscan(const Bytes &name, const Bytes &start, const Bytes &end, uint64_t limit) = 0;
	virtual HIterator* hrscan(const Bytes &name, const Bytes &start, const Bytes &end, uint64_t limit) = 0;

	/* zset */

	virtual int zset(const Bytes &name, const Bytes &key, const Bytes &score, char log_type=BinlogType::SYNC) = 0;
	virtual int zdel(const Bytes &name, const Bytes &key, char log_type=BinlogType::SYNC) = 0;
	// -1: error, 1: ok, 0: value is not an integer or out of range
	virtual int zincr(const Bytes &name, const Bytes &key, int64_t by, int64_t *new_val, char log_type=BinlogType::SYNC) = 0;
	
	virtual int64_t zsize(const Bytes &name) = 0;
	/**
	 * @return -1: error; 0: not found; 1: found
	 */
	virtual int zget(const Bytes &name, const Bytes &key, std::string *score) = 0;
	virtual int64_t zrank(const Bytes &name, const Bytes &key) = 0;
	virtual int64_t zrrank(const Bytes &name, const Bytes &key) = 0;
	virtual ZIterator* zrange(const Bytes &name, uint64_t offset, uint64_t limit) = 0;
	virtual ZIterator* zrrange(const Bytes &name, uint64_t offset, uint64_t limit) = 0;
	/**
	 * scan by score, but won't return @key if key.score=score_start.
	 * return (score_start, score_end]
	 */
	virtual ZIterator* zscan(const Bytes &name, const Bytes &key,
			const Bytes &score_start, const Bytes &score_end, uint64_t limit) = 0;
	virtual ZIterator* zrscan(const Bytes &name, const Bytes &key,
			const Bytes &score_start, const Bytes &score_end, uint64_t limit) = 0;
	virtual int zlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
			std::vector<std::string> *list) = 0;
	virtual int zrlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
			std::vector<std::string> *list) = 0;
	virtual int64_t zfix(const Bytes &name) = 0;
	
	virtual int64_t qsize(const Bytes &name) = 0;
	// @return 0: empty queue, 1: item peeked, -1: error
	virtual int qfront(const Bytes &name, std::string *item) = 0;
	// @return 0: empty queue, 1: item peeked, -1: error
	virtual int qback(const Bytes &name, std::string *item) = 0;
	// @return -1: error, other: the new length of the queue
	virtual int64_t qpush_front(const Bytes &name, const Bytes &item, char log_type=BinlogType::SYNC) = 0;
	virtual int64_t qpush_back(const Bytes &name, const Bytes &item, char log_type=BinlogType::SYNC) = 0;
	// @return 0: empty queue, 1: item popped, -1: error
	virtual int qpop_front(const Bytes &name, std::string *item, char log_type=BinlogType::SYNC) = 0;
	virtual int qpop_back(const Bytes &name, std::string *item, char log_type=BinlogType::SYNC) = 0;
	virtual int qfix(const Bytes &name) = 0;
	virtual int qlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
			std::vector<std::string> *list) = 0;
	virtual int qrlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
			std::vector<std::string> *list) = 0;
	virtual int qslice(const Bytes &name, int64_t offset, int64_t limit,
			std::vector<std::string> *list) = 0;
	virtual int qget(const Bytes &name, int64_t index, std::string *item) = 0;
	virtual int qset(const Bytes &name, int64_t index, const Bytes &item, char log_type=BinlogType::SYNC) = 0;
	virtual int qset_by_seq(const Bytes &name, uint64_t seq, const Bytes &item, char log_type=BinlogType::SYNC) = 0;
};


#endif
