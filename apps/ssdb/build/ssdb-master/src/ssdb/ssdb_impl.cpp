/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "ssdb_impl.h"
#include "leveldb/env.h"
#include "leveldb/iterator.h"
#include "leveldb/cache.h"
#include "leveldb/filter_policy.h"

#include "iterator.h"
#include "t_kv.h"
#include "t_hash.h"
#include "t_zset.h"
#include "t_queue.h"

SSDBImpl::SSDBImpl(){
	ldb = NULL;
	binlogs = NULL;
}

SSDBImpl::~SSDBImpl(){
	if(binlogs){
		delete binlogs;
	}
	if(ldb){
		delete ldb;
	}
	if(options.block_cache){
		delete options.block_cache;
	}
	if(options.filter_policy){
		delete options.filter_policy;
	}
}

SSDB* SSDB::open(const Options &opt, const std::string &dir){
	SSDBImpl *ssdb = new SSDBImpl();
	ssdb->options.create_if_missing = true;
	ssdb->options.max_open_files = opt.max_open_files;
	ssdb->options.filter_policy = leveldb::NewBloomFilterPolicy(10);
	ssdb->options.block_cache = leveldb::NewLRUCache(opt.cache_size * 1048576);
	ssdb->options.block_size = opt.block_size * 1024;
	ssdb->options.write_buffer_size = opt.write_buffer_size * 1024 * 1024;
	ssdb->options.compaction_speed = opt.compaction_speed;
	if(opt.compression == "yes"){
		ssdb->options.compression = leveldb::kSnappyCompression;
	}else{
		ssdb->options.compression = leveldb::kNoCompression;
	}

	leveldb::Status status;

	status = leveldb::DB::Open(ssdb->options, dir, &ssdb->ldb);
	if(!status.ok()){
		log_error("open db failed: %s", status.ToString().c_str());
		goto err;
	}
	ssdb->binlogs = new BinlogQueue(ssdb->ldb, opt.binlog, opt.binlog_capacity);

	return ssdb;
err:
	if(ssdb){
		delete ssdb;
	}
	return NULL;
}

int SSDBImpl::flushdb(){
	Transaction trans(binlogs);
	int ret = 0;
	bool stop = false;
	while(!stop){
		leveldb::Iterator *it;
		leveldb::ReadOptions iterate_options;
		iterate_options.fill_cache = false;
		leveldb::WriteOptions write_opts;

		it = ldb->NewIterator(iterate_options);
		it->SeekToFirst();
		for(int i=0; i<10000; i++){
			if(!it->Valid()){
				stop = true;
				break;
			}
			//log_debug("%s", hexmem(it->key().data(), it->key().size()).c_str());
			leveldb::Status s = ldb->Delete(write_opts, it->key());
			if(!s.ok()){
				log_error("del error: %s", s.ToString().c_str());
				stop = true;
				ret = -1;
				break;
			}
			it->Next();
		}
		delete it;
	}
	binlogs->flush();
	return ret;
}

Iterator* SSDBImpl::iterator(const std::string &start, const std::string &end, uint64_t limit){
	leveldb::Iterator *it;
	leveldb::ReadOptions iterate_options;
	iterate_options.fill_cache = false;
	it = ldb->NewIterator(iterate_options);
	it->Seek(start);
	if(it->Valid() && it->key() == start){
		it->Next();
	}
	return new Iterator(it, end, limit);
}

Iterator* SSDBImpl::rev_iterator(const std::string &start, const std::string &end, uint64_t limit){
	leveldb::Iterator *it;
	leveldb::ReadOptions iterate_options;
	iterate_options.fill_cache = false;
	it = ldb->NewIterator(iterate_options);
	it->Seek(start);
	if(!it->Valid()){
		it->SeekToLast();
	}else{
		it->Prev();
	}
	return new Iterator(it, end, limit, Iterator::BACKWARD);
}

/* raw operates */

int SSDBImpl::raw_set(const Bytes &key, const Bytes &val){
	leveldb::WriteOptions write_opts;
	leveldb::Status s = ldb->Put(write_opts, slice(key), slice(val));
	if(!s.ok()){
		log_error("set error: %s", s.ToString().c_str());
		return -1;
	}
	return 1;
}

int SSDBImpl::raw_del(const Bytes &key){
	leveldb::WriteOptions write_opts;
	leveldb::Status s = ldb->Delete(write_opts, slice(key));
	if(!s.ok()){
		log_error("del error: %s", s.ToString().c_str());
		return -1;
	}
	return 1;
}

int SSDBImpl::raw_get(const Bytes &key, std::string *val){
	leveldb::ReadOptions opts;
	opts.fill_cache = false;
	leveldb::Status s = ldb->Get(opts, slice(key), val);
	if(s.IsNotFound()){
		return 0;
	}
	if(!s.ok()){
		log_error("get error: %s", s.ToString().c_str());
		return -1;
	}
	return 1;
}

uint64_t SSDBImpl::size(){
	std::string s = "A";
	std::string e(1, 'z' + 1);
	leveldb::Range ranges[1];
	ranges[0] = leveldb::Range(s, e);
	uint64_t sizes[1];
	ldb->GetApproximateSizes(ranges, 1, sizes);
	return sizes[0];
}

std::vector<std::string> SSDBImpl::info(){
	//  "leveldb.num-files-at-level<N>" - return the number of files at level <N>,
	//     where <N> is an ASCII representation of a level number (e.g. "0").
	//  "leveldb.stats" - returns a multi-line string that describes statistics
	//     about the internal operation of the DB.
	//  "leveldb.sstables" - returns a multi-line string that describes all
	//     of the sstables that make up the db contents.
	std::vector<std::string> info;
	std::vector<std::string> keys;
	/*
	for(int i=0; i<7; i++){
		char buf[128];
		snprintf(buf, sizeof(buf), "leveldb.num-files-at-level%d", i);
		keys.push_back(buf);
	}
	*/
	keys.push_back("leveldb.stats");
	//keys.push_back("leveldb.sstables");

	for(size_t i=0; i<keys.size(); i++){
		std::string key = keys[i];
		std::string val;
		if(ldb->GetProperty(key, &val)){
			info.push_back(key);
			info.push_back(val);
		}
	}

	return info;
}

void SSDBImpl::compact(){
	ldb->CompactRange(NULL, NULL);
}

int SSDBImpl::key_range(std::vector<std::string> *keys){
	int ret = 0;
	std::string kstart, kend;
	std::string hstart, hend;
	std::string zstart, zend;
	std::string qstart, qend;
	
	Iterator *it;
	
	it = this->iterator(encode_kv_key(""), "", 1);
	if(it->next()){
		Bytes ks = it->key();
		if(ks.data()[0] == DataType::KV){
			std::string n;
			if(decode_kv_key(ks, &n) == -1){
				ret = -1;
			}else{
				kstart = n;
			}
		}
	}
	delete it;
	
	it = this->rev_iterator(encode_kv_key("\xff"), "", 1);
	if(it->next()){
		Bytes ks = it->key();
		if(ks.data()[0] == DataType::KV){
			std::string n;
			if(decode_kv_key(ks, &n) == -1){
				ret = -1;
			}else{
				kend = n;
			}
		}
	}
	delete it;
	
	it = this->iterator(encode_hsize_key(""), "", 1);
	if(it->next()){
		Bytes ks = it->key();
		if(ks.data()[0] == DataType::HSIZE){
			std::string n;
			if(decode_hsize_key(ks, &n) == -1){
				ret = -1;
			}else{
				hstart = n;
			}
		}
	}
	delete it;
	
	it = this->rev_iterator(encode_hsize_key("\xff"), "", 1);
	if(it->next()){
		Bytes ks = it->key();
		if(ks.data()[0] == DataType::HSIZE){
			std::string n;
			if(decode_hsize_key(ks, &n) == -1){
				ret = -1;
			}else{
				hend = n;
			}
		}
	}
	delete it;
	
	it = this->iterator(encode_zsize_key(""), "", 1);
	if(it->next()){
		Bytes ks = it->key();
		if(ks.data()[0] == DataType::ZSIZE){
			std::string n;
			if(decode_zsize_key(ks, &n) == -1){
				ret = -1;
			}else{
				zstart = n;
			}
		}
	}
	delete it;
	
	it = this->rev_iterator(encode_zsize_key("\xff"), "", 1);
	if(it->next()){
		Bytes ks = it->key();
		if(ks.data()[0] == DataType::ZSIZE){
			std::string n;
			if(decode_zsize_key(ks, &n) == -1){
				ret = -1;
			}else{
				zend = n;
			}
		}
	}
	delete it;
	
	it = this->iterator(encode_qsize_key(""), "", 1);
	if(it->next()){
		Bytes ks = it->key();
		if(ks.data()[0] == DataType::QSIZE){
			std::string n;
			if(decode_qsize_key(ks, &n) == -1){
				ret = -1;
			}else{
				qstart = n;
			}
		}
	}
	delete it;
	
	it = this->rev_iterator(encode_qsize_key("\xff"), "", 1);
	if(it->next()){
		Bytes ks = it->key();
		if(ks.data()[0] == DataType::QSIZE){
			std::string n;
			if(decode_qsize_key(ks, &n) == -1){
				ret = -1;
			}else{
				qend = n;
			}
		}
	}
	delete it;

	keys->push_back(kstart);
	keys->push_back(kend);
	keys->push_back(hstart);
	keys->push_back(hend);
	keys->push_back(zstart);
	keys->push_back(zend);
	keys->push_back(qstart);
	keys->push_back(qend);
	
	return ret;
}
