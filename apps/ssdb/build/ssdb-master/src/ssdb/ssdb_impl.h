/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef SSDB_IMPL_H_
#define SSDB_IMPL_H_

#include "leveldb/db.h"
#include "leveldb/slice.h"
#include "../util/log.h"
#include "../util/config.h"

#include "ssdb.h"
#include "binlog.h"
#include "iterator.h"
#include "t_kv.h"
#include "t_hash.h"
#include "t_zset.h"
#include "t_queue.h"

inline
static leveldb::Slice slice(const Bytes &b){
	return leveldb::Slice(b.data(), b.size());
}

class SSDBImpl : public SSDB
{
private:
	friend class SSDB;
	leveldb::DB* ldb;
	leveldb::Options options;
	
	SSDBImpl();
public:
	BinlogQueue *binlogs;
	
	virtual ~SSDBImpl();

	virtual int flushdb();

	// return (start, end], not include start
	virtual Iterator* iterator(const std::string &start, const std::string &end, uint64_t limit);
	virtual Iterator* rev_iterator(const std::string &start, const std::string &end, uint64_t limit);

	//void flushdb();
	virtual uint64_t size();
	virtual std::vector<std::string> info();
	virtual void compact();
	virtual int key_range(std::vector<std::string> *keys);
	
	/* raw operates */

	// repl: whether to sync this operation to slaves
	virtual int raw_set(const Bytes &key, const Bytes &val);
	virtual int raw_del(const Bytes &key);
	virtual int raw_get(const Bytes &key, std::string *val);

	/* key value */

	virtual int set(const Bytes &key, const Bytes &val, char log_type=BinlogType::SYNC);
	virtual int setnx(const Bytes &key, const Bytes &val, char log_type=BinlogType::SYNC);
	virtual int del(const Bytes &key, char log_type=BinlogType::SYNC);
	// -1: error, 1: ok, 0: value is not an integer or out of range
	virtual int incr(const Bytes &key, int64_t by, int64_t *new_val, char log_type=BinlogType::SYNC);
	virtual int multi_set(const std::vector<Bytes> &kvs, int offset=0, char log_type=BinlogType::SYNC);
	virtual int multi_del(const std::vector<Bytes> &keys, int offset=0, char log_type=BinlogType::SYNC);
	virtual int setbit(const Bytes &key, int bitoffset, int on, char log_type=BinlogType::SYNC);
	virtual int getbit(const Bytes &key, int bitoffset);
	
	virtual int get(const Bytes &key, std::string *val);
	virtual int getset(const Bytes &key, std::string *val, const Bytes &newval, char log_type=BinlogType::SYNC);
	// return (start, end]
	virtual KIterator* scan(const Bytes &start, const Bytes &end, uint64_t limit);
	virtual KIterator* rscan(const Bytes &start, const Bytes &end, uint64_t limit);

	/* hash */

	virtual int hset(const Bytes &name, const Bytes &key, const Bytes &val, char log_type=BinlogType::SYNC);
	virtual int hdel(const Bytes &name, const Bytes &key, char log_type=BinlogType::SYNC);
	// -1: error, 1: ok, 0: value is not an integer or out of range
	virtual int hincr(const Bytes &name, const Bytes &key, int64_t by, int64_t *new_val, char log_type=BinlogType::SYNC);
	//int multi_hset(const Bytes &name, const std::vector<Bytes> &kvs, int offset=0, char log_type=BinlogType::SYNC);
	//int multi_hdel(const Bytes &name, const std::vector<Bytes> &keys, int offset=0, char log_type=BinlogType::SYNC);

	virtual int64_t hsize(const Bytes &name);
	virtual int64_t hclear(const Bytes &name);
	virtual int hget(const Bytes &name, const Bytes &key, std::string *val);
	virtual int hlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
			std::vector<std::string> *list);
	virtual int hrlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
			std::vector<std::string> *list);
	virtual HIterator* hscan(const Bytes &name, const Bytes &start, const Bytes &end, uint64_t limit);
	virtual HIterator* hrscan(const Bytes &name, const Bytes &start, const Bytes &end, uint64_t limit);

	/* zset */

	virtual int zset(const Bytes &name, const Bytes &key, const Bytes &score, char log_type=BinlogType::SYNC);
	virtual int zdel(const Bytes &name, const Bytes &key, char log_type=BinlogType::SYNC);
	// -1: error, 1: ok, 0: value is not an integer or out of range
	virtual int zincr(const Bytes &name, const Bytes &key, int64_t by, int64_t *new_val, char log_type=BinlogType::SYNC);
	//int multi_zset(const Bytes &name, const std::vector<Bytes> &kvs, int offset=0, char log_type=BinlogType::SYNC);
	//int multi_zdel(const Bytes &name, const std::vector<Bytes> &keys, int offset=0, char log_type=BinlogType::SYNC);
	
	virtual int64_t zsize(const Bytes &name);
	/**
	 * @return -1: error; 0: not found; 1: found
	 */
	virtual int zget(const Bytes &name, const Bytes &key, std::string *score);
	virtual int64_t zrank(const Bytes &name, const Bytes &key);
	virtual int64_t zrrank(const Bytes &name, const Bytes &key);
	virtual ZIterator* zrange(const Bytes &name, uint64_t offset, uint64_t limit);
	virtual ZIterator* zrrange(const Bytes &name, uint64_t offset, uint64_t limit);
	/**
	 * scan by score, but won't return @key if key.score=score_start.
	 * return (score_start, score_end]
	 */
	virtual ZIterator* zscan(const Bytes &name, const Bytes &key,
			const Bytes &score_start, const Bytes &score_end, uint64_t limit);
	virtual ZIterator* zrscan(const Bytes &name, const Bytes &key,
			const Bytes &score_start, const Bytes &score_end, uint64_t limit);
	virtual int zlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
			std::vector<std::string> *list);
	virtual int zrlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
			std::vector<std::string> *list);
	virtual int64_t zfix(const Bytes &name);
	
	virtual int64_t qsize(const Bytes &name);
	// @return 0: empty queue, 1: item peeked, -1: error
	virtual int qfront(const Bytes &name, std::string *item);
	// @return 0: empty queue, 1: item peeked, -1: error
	virtual int qback(const Bytes &name, std::string *item);
	// @return -1: error, other: the new length of the queue
	virtual int64_t qpush_front(const Bytes &name, const Bytes &item, char log_type=BinlogType::SYNC);
	virtual int64_t qpush_back(const Bytes &name, const Bytes &item, char log_type=BinlogType::SYNC);
	// @return 0: empty queue, 1: item popped, -1: error
	virtual int qpop_front(const Bytes &name, std::string *item, char log_type=BinlogType::SYNC);
	virtual int qpop_back(const Bytes &name, std::string *item, char log_type=BinlogType::SYNC);
	virtual int qfix(const Bytes &name);
	virtual int qlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
			std::vector<std::string> *list);
	virtual int qrlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
			std::vector<std::string> *list);
	virtual int qslice(const Bytes &name, int64_t offset, int64_t limit,
			std::vector<std::string> *list);
	virtual int qget(const Bytes &name, int64_t index, std::string *item);
	virtual int qset(const Bytes &name, int64_t index, const Bytes &item, char log_type=BinlogType::SYNC);
	virtual int qset_by_seq(const Bytes &name, uint64_t seq, const Bytes &item, char log_type=BinlogType::SYNC);

private:
	int64_t _qpush(const Bytes &name, const Bytes &item, uint64_t front_or_back_seq, char log_type=BinlogType::SYNC);
	int _qpop(const Bytes &name, std::string *item, uint64_t front_or_back_seq, char log_type=BinlogType::SYNC);
};

#endif
