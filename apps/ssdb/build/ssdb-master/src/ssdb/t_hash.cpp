/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "t_hash.h"

static int hset_one(SSDBImpl *ssdb, const Bytes &name, const Bytes &key, const Bytes &val, char log_type);
static int hdel_one(SSDBImpl *ssdb, const Bytes &name, const Bytes &key, char log_type);
static int incr_hsize(SSDBImpl *ssdb, const Bytes &name, int64_t incr);

/**
 * @return -1: error, 0: item updated, 1: new item inserted
 */
int SSDBImpl::hset(const Bytes &name, const Bytes &key, const Bytes &val, char log_type){
	Transaction trans(binlogs);

	int ret = hset_one(this, name, key, val, log_type);
	if(ret >= 0){
		if(ret > 0){
			if(incr_hsize(this, name, ret) == -1){
				return -1;
			}
		}
		leveldb::Status s = binlogs->commit();
		if(!s.ok()){
			return -1;
		}
	}
	return ret;
}

int SSDBImpl::hdel(const Bytes &name, const Bytes &key, char log_type){
	Transaction trans(binlogs);

	int ret = hdel_one(this, name, key, log_type);
	if(ret >= 0){
		if(ret > 0){
			if(incr_hsize(this, name, -ret) == -1){
				return -1;
			}
		}
		leveldb::Status s = binlogs->commit();
		if(!s.ok()){
			return -1;
		}
	}
	return ret;
}

int SSDBImpl::hincr(const Bytes &name, const Bytes &key, int64_t by, int64_t *new_val, char log_type){
	Transaction trans(binlogs);

	std::string old;
	int ret = this->hget(name, key, &old);
	if(ret == -1){
		return -1;
	}else if(ret == 0){
		*new_val = by;
	}else{
		*new_val = str_to_int64(old) + by;
		if(errno != 0){
			return 0;
		}
	}

	ret = hset_one(this, name, key, str(*new_val), log_type);
	if(ret == -1){
		return -1;
	}
	if(ret >= 0){
		if(ret > 0){
			if(incr_hsize(this, name, ret) == -1){
				return -1;
			}
		}
		leveldb::Status s = binlogs->commit();
		if(!s.ok()){
			return -1;
		}
	}
	return 1;
}

int64_t SSDBImpl::hsize(const Bytes &name){
	std::string size_key = encode_hsize_key(name);
	std::string val;
	leveldb::Status s;

	s = ldb->Get(leveldb::ReadOptions(), size_key, &val);
	if(s.IsNotFound()){
		return 0;
	}else if(!s.ok()){
		return -1;
	}else{
		if(val.size() != sizeof(uint64_t)){
			return 0;
		}
		int64_t ret = *(int64_t *)val.data();
		return ret < 0? 0 : ret;
	}
}

int64_t SSDBImpl::hclear(const Bytes &name){
	int64_t count = 0;
	while(1){
		HIterator *it = this->hscan(name, "", "", 1000);
		int num = 0;
		while(it->next()){
			int ret = this->hdel(name, it->key);
			if(ret == -1){
				delete it;
				return 0;
			}
			num ++;
		};
		delete it;

		if(num == 0){
			break;
		}
		count += num;
	}
	return count;
}

int SSDBImpl::hget(const Bytes &name, const Bytes &key, std::string *val){
	std::string dbkey = encode_hash_key(name, key);
	leveldb::Status s = ldb->Get(leveldb::ReadOptions(), dbkey, val);
	if(s.IsNotFound()){
		return 0;
	}
	if(!s.ok()){
		log_error("%s", s.ToString().c_str());
		return -1;
	}
	return 1;
}

HIterator* SSDBImpl::hscan(const Bytes &name, const Bytes &start, const Bytes &end, uint64_t limit){
	std::string key_start, key_end;

	key_start = encode_hash_key(name, start);
	if(!end.empty()){
		key_end = encode_hash_key(name, end);
	}
	//dump(key_start.data(), key_start.size(), "scan.start");
	//dump(key_end.data(), key_end.size(), "scan.end");

	return new HIterator(this->iterator(key_start, key_end, limit), name);
}

HIterator* SSDBImpl::hrscan(const Bytes &name, const Bytes &start, const Bytes &end, uint64_t limit){
	std::string key_start, key_end;

	key_start = encode_hash_key(name, start);
	if(start.empty()){
		key_start.append(1, 255);
	}
	if(!end.empty()){
		key_end = encode_hash_key(name, end);
	}
	//dump(key_start.data(), key_start.size(), "scan.start");
	//dump(key_end.data(), key_end.size(), "scan.end");

	return new HIterator(this->rev_iterator(key_start, key_end, limit), name);
}

static void get_hnames(Iterator *it, std::vector<std::string> *list){
	while(it->next()){
		Bytes ks = it->key();
		if(ks.data()[0] != DataType::HSIZE){
			break;
		}
		std::string n;
		if(decode_hsize_key(ks, &n) == -1){
			continue;
		}
		list->push_back(n);
	}
}

int SSDBImpl::hlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
		std::vector<std::string> *list){
	std::string start;
	std::string end;
	
	start = encode_hsize_key(name_s);
	if(!name_e.empty()){
		end = encode_hsize_key(name_e);
	}
	
	Iterator *it = this->iterator(start, end, limit);
	get_hnames(it, list);
	delete it;
	return 0;
}

int SSDBImpl::hrlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
		std::vector<std::string> *list){
	std::string start;
	std::string end;
	
	start = encode_hsize_key(name_s);
	if(name_s.empty()){
		start.append(1, 255);
	}
	if(!name_e.empty()){
		end = encode_hsize_key(name_e);
	}
	
	Iterator *it = this->rev_iterator(start, end, limit);
	get_hnames(it, list);
	delete it;
	return 0;
}

// returns the number of newly added items
static int hset_one(SSDBImpl *ssdb, const Bytes &name, const Bytes &key, const Bytes &val, char log_type){
	if(name.empty() || key.empty()){
		log_error("empty name or key!");
		return -1;
	}
	if(name.size() > SSDB_KEY_LEN_MAX ){
		log_error("name too long! %s", hexmem(name.data(), name.size()).c_str());
		return -1;
	}
	if(key.size() > SSDB_KEY_LEN_MAX){
		log_error("key too long! %s", hexmem(key.data(), key.size()).c_str());
		return -1;
	}
	int ret = 0;
	std::string dbval;
	if(ssdb->hget(name, key, &dbval) == 0){ // not found
		std::string hkey = encode_hash_key(name, key);
		ssdb->binlogs->Put(hkey, slice(val));
		ssdb->binlogs->add_log(log_type, BinlogCommand::HSET, hkey);
		ret = 1;
	}else{
		if(dbval != val){
			std::string hkey = encode_hash_key(name, key);
			ssdb->binlogs->Put(hkey, slice(val));
			ssdb->binlogs->add_log(log_type, BinlogCommand::HSET, hkey);
		}
		ret = 0;
	}
	return ret;
}

static int hdel_one(SSDBImpl *ssdb, const Bytes &name, const Bytes &key, char log_type){
	if(name.size() > SSDB_KEY_LEN_MAX ){
		log_error("name too long! %s", hexmem(name.data(), name.size()).c_str());
		return -1;
	}
	if(key.size() > SSDB_KEY_LEN_MAX){
		log_error("key too long! %s", hexmem(key.data(), key.size()).c_str());
		return -1;
	}
	std::string dbval;
	if(ssdb->hget(name, key, &dbval) == 0){
		return 0;
	}

	std::string hkey = encode_hash_key(name, key);
	ssdb->binlogs->Delete(hkey);
	ssdb->binlogs->add_log(log_type, BinlogCommand::HDEL, hkey);
	
	return 1;
}

static int incr_hsize(SSDBImpl *ssdb, const Bytes &name, int64_t incr){
	int64_t size = ssdb->hsize(name);
	size += incr;
	std::string size_key = encode_hsize_key(name);
	if(size == 0){
		ssdb->binlogs->Delete(size_key);
	}else{
		ssdb->binlogs->Put(size_key, leveldb::Slice((char *)&size, sizeof(int64_t)));
	}
	return 0;
}
