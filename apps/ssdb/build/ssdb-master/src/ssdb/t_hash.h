/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef SSDB_HASH_H_
#define SSDB_HASH_H_

#include "ssdb_impl.h"

inline static
std::string encode_hsize_key(const Bytes &name){
	std::string buf;
	buf.append(1, DataType::HSIZE);
	buf.append(name.data(), name.size());
	return buf;
}

inline static
int decode_hsize_key(const Bytes &slice, std::string *name){
	Decoder decoder(slice.data(), slice.size());
	if(decoder.skip(1) == -1){
		return -1;
	}
	if(decoder.read_data(name) == -1){
		return -1;
	}
	return 0;
}

inline static
std::string encode_hash_key(const Bytes &name, const Bytes &key){
	std::string buf;
	buf.append(1, DataType::HASH);
	buf.append(1, (uint8_t)name.size());
	buf.append(name.data(), name.size());
	buf.append(1, '=');
	buf.append(key.data(), key.size());
	return buf;
}

inline static
int decode_hash_key(const Bytes &slice, std::string *name, std::string *key){
	Decoder decoder(slice.data(), slice.size());
	if(decoder.skip(1) == -1){
		return -1;
	}
	if(decoder.read_8_data(name) == -1){
		return -1;
	}
	if(decoder.skip(1) == -1){
		return -1;
	}
	if(decoder.read_data(key) == -1){
		return -1;
	}
	return 0;
}

#endif
