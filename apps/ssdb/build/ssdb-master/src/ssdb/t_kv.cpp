/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "t_kv.h"

int SSDBImpl::multi_set(const std::vector<Bytes> &kvs, int offset, char log_type){
	Transaction trans(binlogs);

	std::vector<Bytes>::const_iterator it;
	it = kvs.begin() + offset;
	for(; it != kvs.end(); it += 2){
		const Bytes &key = *it;
		if(key.empty()){
			log_error("empty key!");
			return 0;
			//return -1;
		}
		const Bytes &val = *(it + 1);
		std::string buf = encode_kv_key(key);
		binlogs->Put(buf, slice(val));
		binlogs->add_log(log_type, BinlogCommand::KSET, buf);
	}
	leveldb::Status s = binlogs->commit();
	if(!s.ok()){
		log_error("multi_set error: %s", s.ToString().c_str());
		return -1;
	}
	return (kvs.size() - offset)/2;
}

int SSDBImpl::multi_del(const std::vector<Bytes> &keys, int offset, char log_type){
	Transaction trans(binlogs);

	std::vector<Bytes>::const_iterator it;
	it = keys.begin() + offset;
	for(; it != keys.end(); it++){
		const Bytes &key = *it;
		std::string buf = encode_kv_key(key);
		binlogs->Delete(buf);
		binlogs->add_log(log_type, BinlogCommand::KDEL, buf);
	}
	leveldb::Status s = binlogs->commit();
	if(!s.ok()){
		log_error("multi_del error: %s", s.ToString().c_str());
		return -1;
	}
	return keys.size() - offset;
}

int SSDBImpl::set(const Bytes &key, const Bytes &val, char log_type){
	if(key.empty()){
		log_error("empty key!");
		//return -1;
		return 0;
	}
	Transaction trans(binlogs);

	std::string buf = encode_kv_key(key);
	binlogs->Put(buf, slice(val));
	binlogs->add_log(log_type, BinlogCommand::KSET, buf);
	leveldb::Status s = binlogs->commit();
	if(!s.ok()){
		log_error("set error: %s", s.ToString().c_str());
		return -1;
	}
	return 1;
}

int SSDBImpl::setnx(const Bytes &key, const Bytes &val, char log_type){
	if(key.empty()){
		log_error("empty key!");
		//return -1;
		return 0;
	}
	Transaction trans(binlogs);

	std::string tmp;
	int found = this->get(key, &tmp);
	if(found != 0){
		return 0;
	}
	std::string buf = encode_kv_key(key);
	binlogs->Put(buf, slice(val));
	binlogs->add_log(log_type, BinlogCommand::KSET, buf);
	leveldb::Status s = binlogs->commit();
	if(!s.ok()){
		log_error("set error: %s", s.ToString().c_str());
		return -1;
	}
	return 1;
}

int SSDBImpl::getset(const Bytes &key, std::string *val, const Bytes &newval, char log_type){
	if(key.empty()){
		log_error("empty key!");
		//return -1;
		return 0;
	}
	Transaction trans(binlogs);

	int found = this->get(key, val);
	std::string buf = encode_kv_key(key);
	binlogs->Put(buf, slice(newval));
	binlogs->add_log(log_type, BinlogCommand::KSET, buf);
	leveldb::Status s = binlogs->commit();
	if(!s.ok()){
		log_error("set error: %s", s.ToString().c_str());
		return -1;
	}
	return found;
}


int SSDBImpl::del(const Bytes &key, char log_type){
	Transaction trans(binlogs);

	std::string buf = encode_kv_key(key);
	binlogs->Delete(buf);
	binlogs->add_log(log_type, BinlogCommand::KDEL, buf);
	leveldb::Status s = binlogs->commit();
	if(!s.ok()){
		log_error("del error: %s", s.ToString().c_str());
		return -1;
	}
	return 1;
}

int SSDBImpl::incr(const Bytes &key, int64_t by, int64_t *new_val, char log_type){
	Transaction trans(binlogs);

	std::string old;
	int ret = this->get(key, &old);
	if(ret == -1){
		return -1;
	}else if(ret == 0){
		*new_val = by;
	}else{
		*new_val = str_to_int64(old) + by;
		if(errno != 0){
			return 0;
		}
	}

	std::string buf = encode_kv_key(key);
	binlogs->Put(buf, str(*new_val));
	binlogs->add_log(log_type, BinlogCommand::KSET, buf);

	leveldb::Status s = binlogs->commit();
	if(!s.ok()){
		log_error("del error: %s", s.ToString().c_str());
		return -1;
	}
	return 1;
}

int SSDBImpl::get(const Bytes &key, std::string *val){
	std::string buf = encode_kv_key(key);

	leveldb::Status s = ldb->Get(leveldb::ReadOptions(), buf, val);
	if(s.IsNotFound()){
		return 0;
	}
	if(!s.ok()){
		log_error("get error: %s", s.ToString().c_str());
		return -1;
	}
	return 1;
}

KIterator* SSDBImpl::scan(const Bytes &start, const Bytes &end, uint64_t limit){
	std::string key_start, key_end;
	key_start = encode_kv_key(start);
	if(end.empty()){
		key_end = "";
	}else{
		key_end = encode_kv_key(end);
	}
	//dump(key_start.data(), key_start.size(), "scan.start");
	//dump(key_end.data(), key_end.size(), "scan.end");

	return new KIterator(this->iterator(key_start, key_end, limit));
}

KIterator* SSDBImpl::rscan(const Bytes &start, const Bytes &end, uint64_t limit){
	std::string key_start, key_end;

	key_start = encode_kv_key(start);
	if(start.empty()){
		key_start.append(1, 255);
	}
	if(!end.empty()){
		key_end = encode_kv_key(end);
	}
	//dump(key_start.data(), key_start.size(), "scan.start");
	//dump(key_end.data(), key_end.size(), "scan.end");

	return new KIterator(this->rev_iterator(key_start, key_end, limit));
}

int SSDBImpl::setbit(const Bytes &key, int bitoffset, int on, char log_type){
	if(key.empty()){
		log_error("empty key!");
		return 0;
	}
	Transaction trans(binlogs);
	
	std::string val;
	int ret = this->get(key, &val);
	if(ret == -1){
		return -1;
	}
	
	int len = bitoffset / 8;
	int bit = bitoffset % 8;
	if(len >= val.size()){
		val.resize(len + 1, 0);
	}
	int orig = val[len] & (1 << bit);
	if(on == 1){
		val[len] |= (1 << bit);
	}else{
		val[len] &= ~(1 << bit);
	}

	std::string buf = encode_kv_key(key);
	binlogs->Put(buf, val);
	binlogs->add_log(log_type, BinlogCommand::KSET, buf);
	leveldb::Status s = binlogs->commit();
	if(!s.ok()){
		log_error("set error: %s", s.ToString().c_str());
		return -1;
	}
	return orig;
}

int SSDBImpl::getbit(const Bytes &key, int bitoffset){
	std::string val;
	int ret = this->get(key, &val);
	if(ret == -1){
		return -1;
	}
	
	int len = bitoffset / 8;
	int bit = bitoffset % 8;
	if(len >= val.size()){
		return 0;
	}
	return (val[len] & (1 << bit)) == 0? 0 : 1;
}


