/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef SSDB_KV_H_
#define SSDB_KV_H_

#include "ssdb_impl.h"

static inline
std::string encode_kv_key(const Bytes &key){
	std::string buf;
	buf.append(1, DataType::KV);
	buf.append(key.data(), key.size());
	return buf;
}

static inline
int decode_kv_key(const Bytes &slice, std::string *key){
	Decoder decoder(slice.data(), slice.size());
	if(decoder.skip(1) == -1){
		return -1;
	}
	if(decoder.read_data(key) == -1){
		return -1;
	}
	return 0;
}

#endif
