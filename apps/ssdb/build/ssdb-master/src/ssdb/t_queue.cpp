/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "t_queue.h"

static int qget_by_seq(leveldb::DB* db, const Bytes &name, uint64_t seq, std::string *val){
	std::string key = encode_qitem_key(name, seq);
	leveldb::Status s;

	s = db->Get(leveldb::ReadOptions(), key, val);
	if(s.IsNotFound()){
		return 0;
	}else if(!s.ok()){
		log_error("Get() error!");
		return -1;
	}else{
		return 1;
	}
}

static int qget_uint64(leveldb::DB* db, const Bytes &name, uint64_t seq, uint64_t *ret){
	std::string val;
	*ret = 0;
	int s = qget_by_seq(db, name, seq, &val);
	if(s == 1){
		if(val.size() != sizeof(uint64_t)){
			return -1;
		}
		*ret = *(uint64_t *)val.data();
	}
	return s;
}

static int qdel_one(SSDBImpl *ssdb, const Bytes &name, uint64_t seq){
	std::string key = encode_qitem_key(name, seq);
	leveldb::Status s;

	ssdb->binlogs->Delete(key);
	return 0;
}

static int qset_one(SSDBImpl *ssdb, const Bytes &name, uint64_t seq, const Bytes &item){
	std::string key = encode_qitem_key(name, seq);
	leveldb::Status s;

	ssdb->binlogs->Put(key, slice(item));
	return 0;
}

static int64_t incr_qsize(SSDBImpl *ssdb, const Bytes &name, int64_t incr){
	int64_t size = ssdb->qsize(name);
	if(size == -1){
		return -1;
	}
	size += incr;
	if(size <= 0){
		ssdb->binlogs->Delete(encode_qsize_key(name));
		qdel_one(ssdb, name, QFRONT_SEQ);
		qdel_one(ssdb, name, QBACK_SEQ);
	}else{
		ssdb->binlogs->Put(encode_qsize_key(name), leveldb::Slice((char *)&size, sizeof(size)));
	}
	return size;
}

/****************/

int64_t SSDBImpl::qsize(const Bytes &name){
	std::string key = encode_qsize_key(name);
	std::string val;

	leveldb::Status s;
	s = ldb->Get(leveldb::ReadOptions(), key, &val);
	if(s.IsNotFound()){
		return 0;
	}else if(!s.ok()){
		log_error("Get() error!");
		return -1;
	}else{
		if(val.size() != sizeof(uint64_t)){
			return -1;
		}
		return *(int64_t *)val.data();
	}
}

// @return 0: empty queue, 1: item peeked, -1: error
int SSDBImpl::qfront(const Bytes &name, std::string *item){
	int ret = 0;
	uint64_t seq;
	ret = qget_uint64(this->ldb, name, QFRONT_SEQ, &seq);
	if(ret == -1){
		return -1;
	}
	if(ret == 0){
		return 0;
	}
	ret = qget_by_seq(this->ldb, name, seq, item);
	return ret;
}

// @return 0: empty queue, 1: item peeked, -1: error
int SSDBImpl::qback(const Bytes &name, std::string *item){
	int ret = 0;
	uint64_t seq;
	ret = qget_uint64(this->ldb, name, QBACK_SEQ, &seq);
	if(ret == -1){
		return -1;
	}
	if(ret == 0){
		return 0;
	}
	ret = qget_by_seq(this->ldb, name, seq, item);
	return ret;
}

int SSDBImpl::qset_by_seq(const Bytes &name, uint64_t seq, const Bytes &item, char log_type){
	Transaction trans(binlogs);
	uint64_t min_seq, max_seq;
	int ret;
	int64_t size = this->qsize(name);
	if(size == -1){
		return -1;
	}
	ret = qget_uint64(this->ldb, name, QFRONT_SEQ, &min_seq);
	if(ret == -1){
		return -1;
	}
	max_seq = min_seq + size;
	if(seq < min_seq || seq > max_seq){
		return 0;
	}

	ret = qset_one(this, name, seq, item);
	if(ret == -1){
		return -1;
	}

	std::string buf = encode_qitem_key(name, seq);
	binlogs->add_log(log_type, BinlogCommand::QSET, buf);

	leveldb::Status s = binlogs->commit();
	if(!s.ok()){
		log_error("Write error!");
		return -1;
	}
	return 1;
}

// return: 0: index out of range, -1: error, 1: ok
int SSDBImpl::qset(const Bytes &name, int64_t index, const Bytes &item, char log_type){
	Transaction trans(binlogs);
	int64_t size = this->qsize(name);
	if(size == -1){
		return -1;
	}
	if(index >= size || index < -size){
		return 0;
	}
	
	int ret;
	uint64_t seq;
	if(index >= 0){
		ret = qget_uint64(this->ldb, name, QFRONT_SEQ, &seq);
		seq += index;
	}else{
		ret = qget_uint64(this->ldb, name, QBACK_SEQ, &seq);
		seq += index + 1;
	}
	if(ret == -1){
		return -1;
	}
	if(ret == 0){
		return 0;
	}

	ret = qset_one(this, name, seq, item);
	if(ret == -1){
		return -1;
	}

	//log_info("qset %s %" PRIu64 "", hexmem(name.data(), name.size()).c_str(), seq);
	std::string buf = encode_qitem_key(name, seq);
	binlogs->add_log(log_type, BinlogCommand::QSET, buf);
	
	leveldb::Status s = binlogs->commit();
	if(!s.ok()){
		log_error("Write error!");
		return -1;
	}
	return 1;
}

int64_t SSDBImpl::_qpush(const Bytes &name, const Bytes &item, uint64_t front_or_back_seq, char log_type){
	Transaction trans(binlogs);

	int ret;
	// generate seq
	uint64_t seq;
	ret = qget_uint64(this->ldb, name, front_or_back_seq, &seq);
	if(ret == -1){
		return -1;
	}
	// update front and/or back
	if(ret == 0){
		seq = QITEM_SEQ_INIT;
		ret = qset_one(this, name, QFRONT_SEQ, Bytes(&seq, sizeof(seq)));
		if(ret == -1){
			return -1;
		}
		ret = qset_one(this, name, QBACK_SEQ, Bytes(&seq, sizeof(seq)));
	}else{
		seq += (front_or_back_seq == QFRONT_SEQ)? -1 : +1;
		ret = qset_one(this, name, front_or_back_seq, Bytes(&seq, sizeof(seq)));
	}
	if(ret == -1){
		return -1;
	}
	if(seq <= QITEM_MIN_SEQ || seq >= QITEM_MAX_SEQ){
		log_info("queue is full, seq: %" PRIu64 " out of range", seq);
		return -1;
	}
	
	// prepend/append item
	ret = qset_one(this, name, seq, item);
	if(ret == -1){
		return -1;
	}

	std::string buf = encode_qitem_key(name, seq);
	if(front_or_back_seq == QFRONT_SEQ){
		binlogs->add_log(log_type, BinlogCommand::QPUSH_FRONT, buf);
	}else{
		binlogs->add_log(log_type, BinlogCommand::QPUSH_BACK, buf);
	}
	
	// update size
	int64_t size = incr_qsize(this, name, +1);
	if(size == -1){
		return -1;
	}

	leveldb::Status s = binlogs->commit();
	if(!s.ok()){
		log_error("Write error! %s", s.ToString().c_str());
		return -1;
	}
	return size;
}

int64_t SSDBImpl::qpush_front(const Bytes &name, const Bytes &item, char log_type){
	return _qpush(name, item, QFRONT_SEQ, log_type);
}

int64_t SSDBImpl::qpush_back(const Bytes &name, const Bytes &item, char log_type){
	return _qpush(name, item, QBACK_SEQ, log_type);
}

int SSDBImpl::_qpop(const Bytes &name, std::string *item, uint64_t front_or_back_seq, char log_type){
	Transaction trans(binlogs);
	
	int ret;
	uint64_t seq;
	ret = qget_uint64(this->ldb, name, front_or_back_seq, &seq);
	if(ret == -1){
		return -1;
	}
	if(ret == 0){
		return 0;
	}
	
	ret = qget_by_seq(this->ldb, name, seq, item);
	if(ret == -1){
		return -1;
	}
	if(ret == 0){
		return 0;
	}

	// delete item
	ret = qdel_one(this, name, seq);
	if(ret == -1){
		return -1;
	}

	if(front_or_back_seq == QFRONT_SEQ){
		binlogs->add_log(log_type, BinlogCommand::QPOP_FRONT, name.String());
	}else{
		binlogs->add_log(log_type, BinlogCommand::QPOP_BACK, name.String());
	}

	// update size
	int64_t size = incr_qsize(this, name, -1);
	if(size == -1){
		return -1;
	}
		
	// update front
	if(size > 0){
		seq += (front_or_back_seq == QFRONT_SEQ)? +1 : -1;
		//log_debug("seq: %" PRIu64 ", ret: %d", seq, ret);
		ret = qset_one(this, name, front_or_back_seq, Bytes(&seq, sizeof(seq)));
		if(ret == -1){
			return -1;
		}
	}
		
	leveldb::Status s = binlogs->commit();
	if(!s.ok()){
		log_error("Write error! %s", s.ToString().c_str());
		return -1;
	}
	return 1;
}

// @return 0: empty queue, 1: item popped, -1: error
int SSDBImpl::qpop_front(const Bytes &name, std::string *item, char log_type){
	return _qpop(name, item, QFRONT_SEQ, log_type);
}

int SSDBImpl::qpop_back(const Bytes &name, std::string *item, char log_type){
	return _qpop(name, item, QBACK_SEQ, log_type);
}

static void get_qnames(Iterator *it, std::vector<std::string> *list){
	while(it->next()){
		Bytes ks = it->key();
		//dump(ks.data(), ks.size());
		if(ks.data()[0] != DataType::QSIZE){
			break;
		}
		std::string n;
		if(decode_qsize_key(ks, &n) == -1){
			continue;
		}
		list->push_back(n);
	}
}

int SSDBImpl::qlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
		std::vector<std::string> *list){
	std::string start;
	std::string end;
	
	start = encode_qsize_key(name_s);
	if(!name_e.empty()){
		end = encode_qsize_key(name_e);
	}
	
	Iterator *it = this->iterator(start, end, limit);
	get_qnames(it, list);
	delete it;
	return 0;
}

int SSDBImpl::qrlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
		std::vector<std::string> *list){
	std::string start;
	std::string end;
	
	start = encode_qsize_key(name_s);
	if(name_s.empty()){
		start.append(1, 255);
	}
	if(!name_e.empty()){
		end = encode_qsize_key(name_e);
	}
	
	Iterator *it = this->rev_iterator(start, end, limit);
	get_qnames(it, list);
	delete it;
	return 0;
}

int SSDBImpl::qfix(const Bytes &name){
	Transaction trans(binlogs);
	std::string key_s = encode_qitem_key(name, QITEM_MIN_SEQ - 1);
	std::string key_e = encode_qitem_key(name, QITEM_MAX_SEQ);

	bool error = false;
	uint64_t seq_min = 0;
	uint64_t seq_max = 0;
	uint64_t count = 0;
	Iterator *it = this->iterator(key_s, key_e, QITEM_MAX_SEQ);
	while(it->next()){
		//dump(it->key().data(), it->key().size());
		if(seq_min == 0){
			if(decode_qitem_key(it->key(), NULL, &seq_min) == -1){
				// or just delete it?
				error = true;
				break;
			}
		}
		if(decode_qitem_key(it->key(), NULL, &seq_max) == -1){
			error = true;
			break;
		}
		count ++;
	}
	delete it;
	if(error){
		return -1;
	}
	
	if(count == 0){
		this->binlogs->Delete(encode_qsize_key(name));
		qdel_one(this, name, QFRONT_SEQ);
		qdel_one(this, name, QBACK_SEQ);
	}else{
		this->binlogs->Put(encode_qsize_key(name), leveldb::Slice((char *)&count, sizeof(count)));
		qset_one(this, name, QFRONT_SEQ, Bytes(&seq_min, sizeof(seq_min)));
		qset_one(this, name, QBACK_SEQ, Bytes(&seq_max, sizeof(seq_max)));
	}
		
	leveldb::Status s = binlogs->commit();
	if(!s.ok()){
		log_error("Write error!");
		return -1;
	}
	return 0;
}

int SSDBImpl::qslice(const Bytes &name, int64_t begin, int64_t end,
		std::vector<std::string> *list)
{
	int ret;
	uint64_t seq_begin, seq_end;
	if(begin >= 0 && end >= 0){
		uint64_t tmp_seq;
		ret = qget_uint64(this->ldb, name, QFRONT_SEQ, &tmp_seq);
		if(ret != 1){
			return ret;
		}
		seq_begin = tmp_seq + begin;
		seq_end = tmp_seq + end;
	}else if(begin < 0 && end < 0){
		uint64_t tmp_seq;
		ret = qget_uint64(this->ldb, name, QBACK_SEQ, &tmp_seq);
		if(ret != 1){
			return ret;
		}
		seq_begin = tmp_seq + begin + 1;
		seq_end = tmp_seq + end + 1;
	}else{
		uint64_t f_seq, b_seq;
		ret = qget_uint64(this->ldb, name, QFRONT_SEQ, &f_seq);
		if(ret != 1){
			return ret;
		}
		ret = qget_uint64(this->ldb, name, QBACK_SEQ, &b_seq);
		if(ret != 1){
			return ret;
		}
		if(begin >= 0){
			seq_begin = f_seq + begin;
		}else{
			seq_begin = b_seq + begin + 1;
		}
		if(end >= 0){
			seq_end = f_seq + end;
		}else{
			seq_end = b_seq + end + 1;
		}
	}
	
	for(; seq_begin <= seq_end; seq_begin++){
		std::string item;
		ret = qget_by_seq(this->ldb, name, seq_begin, &item);
		if(ret == -1){
			return -1;
		}
		if(ret == 0){
			return 0;
		}
		list->push_back(item);
	}
	return 0;
}

int SSDBImpl::qget(const Bytes &name, int64_t index, std::string *item){
	int ret;
	uint64_t seq;
	if(index >= 0){
		ret = qget_uint64(this->ldb, name, QFRONT_SEQ, &seq);
		seq += index;
	}else{
		ret = qget_uint64(this->ldb, name, QBACK_SEQ, &seq);
		seq += index + 1;
	}
	if(ret == -1){
		return -1;
	}
	if(ret == 0){
		return 0;
	}
	
	ret = qget_by_seq(this->ldb, name, seq, item);
	return ret;
}
