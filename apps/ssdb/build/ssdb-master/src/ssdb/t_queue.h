/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef SSDB_QUEUE_H_
#define SSDB_QUEUE_H_

#include "ssdb_impl.h"

const uint64_t QFRONT_SEQ = 2;
const uint64_t QBACK_SEQ  = 3;
const uint64_t QITEM_MIN_SEQ = 10000;
const uint64_t QITEM_MAX_SEQ = 9223372036854775807ULL;
const uint64_t QITEM_SEQ_INIT = QITEM_MAX_SEQ/2;

inline static
std::string encode_qsize_key(const Bytes &name){
	std::string buf;
	buf.append(1, DataType::QSIZE);
	buf.append(name.data(), name.size());
	return buf;
}

inline static
int decode_qsize_key(const Bytes &slice, std::string *name){
	Decoder decoder(slice.data(), slice.size());
	if(decoder.skip(1) == -1){
		return -1;
	}
	if(decoder.read_data(name) == -1){
		return -1;
	}
	return 0;
}

inline static
std::string encode_qitem_key(const Bytes &name, uint64_t seq){
	std::string buf;
	buf.append(1, DataType::QUEUE);
	buf.append(1, (uint8_t)name.size());
	buf.append(name.data(), name.size());
	seq = big_endian(seq);
	buf.append((char *)&seq, sizeof(uint64_t));
	return buf;
}

inline static
int decode_qitem_key(const Bytes &slice, std::string *name, uint64_t *seq){
	Decoder decoder(slice.data(), slice.size());
	if(decoder.skip(1) == -1){
		return -1;
	}
	if(decoder.read_8_data(name) == -1){
		return -1;
	}
	if(decoder.read_uint64(seq) == -1){
		return -1;
	}
	*seq = big_endian(*seq);
	return 0;
}

#endif
