/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include <limits.h>
#include "../include.h"
#include "t_zset.h"

static const char *SSDB_SCORE_MIN		= "-9223372036854775808";
static const char *SSDB_SCORE_MAX		= "+9223372036854775807";

static int zset_one(SSDBImpl *ssdb, const Bytes &name, const Bytes &key, const Bytes &score, char log_type);
static int zdel_one(SSDBImpl *ssdb, const Bytes &name, const Bytes &key, char log_type);
static int incr_zsize(SSDBImpl *ssdb, const Bytes &name, int64_t incr);

/**
 * @return -1: error, 0: item updated, 1: new item inserted
 */
int SSDBImpl::zset(const Bytes &name, const Bytes &key, const Bytes &score, char log_type){
	Transaction trans(binlogs);

	int ret = zset_one(this, name, key, score, log_type);
	if(ret >= 0){
		if(ret > 0){
			if(incr_zsize(this, name, ret) == -1){
				return -1;
			}
		}
		leveldb::Status s = binlogs->commit();
		if(!s.ok()){
			log_error("zset error: %s", s.ToString().c_str());
			return -1;
		}
	}
	return ret;
}

int SSDBImpl::zdel(const Bytes &name, const Bytes &key, char log_type){
	Transaction trans(binlogs);

	int ret = zdel_one(this, name, key, log_type);
	if(ret >= 0){
		if(ret > 0){
			if(incr_zsize(this, name, -ret) == -1){
				return -1;
			}
		}
		leveldb::Status s = binlogs->commit();
		if(!s.ok()){
			log_error("zdel error: %s", s.ToString().c_str());
			return -1;
		}
	}
	return ret;
}

int SSDBImpl::zincr(const Bytes &name, const Bytes &key, int64_t by, int64_t *new_val, char log_type){
	Transaction trans(binlogs);

	std::string old;
	int ret = this->zget(name, key, &old);
	if(ret == -1){
		return -1;
	}else if(ret == 0){
		*new_val = by;
	}else{
		*new_val = str_to_int64(old) + by;
	}

	ret = zset_one(this, name, key, str(*new_val), log_type);
	if(ret == -1){
		return -1;
	}
	if(ret >= 0){
		if(ret > 0){
			if(incr_zsize(this, name, ret) == -1){
				return -1;
			}
		}
		leveldb::Status s = binlogs->commit();
		if(!s.ok()){
			log_error("zset error: %s", s.ToString().c_str());
			return -1;
		}
	}
	return 1;
}

int64_t SSDBImpl::zsize(const Bytes &name){
	std::string size_key = encode_zsize_key(name);
	std::string val;
	leveldb::Status s;

	s = ldb->Get(leveldb::ReadOptions(), size_key, &val);
	if(s.IsNotFound()){
		return 0;
	}else if(!s.ok()){
		return -1;
	}else{
		if(val.size() != sizeof(uint64_t)){
			return 0;
		}
		int64_t ret = *(int64_t *)val.data();
		return ret < 0? 0 : ret;
	}
}

int SSDBImpl::zget(const Bytes &name, const Bytes &key, std::string *score){
	std::string buf = encode_zset_key(name, key);
	leveldb::Status s = ldb->Get(leveldb::ReadOptions(), buf, score);
	if(s.IsNotFound()){
		return 0;
	}
	if(!s.ok()){
		log_error("zget error: %s", s.ToString().c_str());
		return -1;
	}
	return 1;
}

static ZIterator* ziterator(
	SSDBImpl *ssdb,
	const Bytes &name, const Bytes &key_start,
	const Bytes &score_start, const Bytes &score_end,
	uint64_t limit, Iterator::Direction direction)
{
	if(direction == Iterator::FORWARD){
		std::string start, end;
		if(score_start.empty()){
			start = encode_zscore_key(name, key_start, SSDB_SCORE_MIN);
		}else{
			start = encode_zscore_key(name, key_start, score_start);
		}
		if(score_end.empty()){
			end = encode_zscore_key(name, "\xff", SSDB_SCORE_MAX);
		}else{
			end = encode_zscore_key(name, "\xff", score_end);
		}
		return new ZIterator(ssdb->iterator(start, end, limit), name);
	}else{
		std::string start, end;
		if(score_start.empty()){
			start = encode_zscore_key(name, key_start, SSDB_SCORE_MAX);
		}else{
			if(key_start.empty()){
				start = encode_zscore_key(name, "\xff", score_start);
			}else{
				start = encode_zscore_key(name, key_start, score_start);
			}
		}
		if(score_end.empty()){
			end = encode_zscore_key(name, "", SSDB_SCORE_MIN);
		}else{
			end = encode_zscore_key(name, "", score_end);
		}
		return new ZIterator(ssdb->rev_iterator(start, end, limit), name);
	}
}

int64_t SSDBImpl::zrank(const Bytes &name, const Bytes &key){
	ZIterator *it = ziterator(this, name, "", "", "", INT_MAX, Iterator::FORWARD);
	uint64_t ret = 0;
	while(true){
		if(it->next() == false){
			ret = -1;
			break;
		}
		if(key == it->key){
			break;
		}
		ret ++;
	}
	delete it;
	return ret;
}

int64_t SSDBImpl::zrrank(const Bytes &name, const Bytes &key){
	ZIterator *it = ziterator(this, name, "", "", "", INT_MAX, Iterator::BACKWARD);
	uint64_t ret = 0;
	while(true){
		if(it->next() == false){
			ret = -1;
			break;
		}
		if(key == it->key){
			break;
		}
		ret ++;
	}
	delete it;
	return ret;
}

ZIterator* SSDBImpl::zrange(const Bytes &name, uint64_t offset, uint64_t limit){
	if(offset + limit > limit){
		limit = offset + limit;
	}
	ZIterator *it = ziterator(this, name, "", "", "", limit, Iterator::FORWARD);
	it->skip(offset);
	return it;
}

ZIterator* SSDBImpl::zrrange(const Bytes &name, uint64_t offset, uint64_t limit){
	if(offset + limit > limit){
		limit = offset + limit;
	}
	ZIterator *it = ziterator(this, name, "", "", "", limit, Iterator::BACKWARD);
	it->skip(offset);
	return it;
}

ZIterator* SSDBImpl::zscan(const Bytes &name, const Bytes &key,
		const Bytes &score_start, const Bytes &score_end, uint64_t limit)
{
	std::string score;
	// if only key is specified, load its value
	if(!key.empty() && score_start.empty()){
		this->zget(name, key, &score);
	}else{
		score = score_start.String();
	}
	return ziterator(this, name, key, score, score_end, limit, Iterator::FORWARD);
}

ZIterator* SSDBImpl::zrscan(const Bytes &name, const Bytes &key,
		const Bytes &score_start, const Bytes &score_end, uint64_t limit)
{
	std::string score;
	// if only key is specified, load its value
	if(!key.empty() && score_start.empty()){
		this->zget(name, key, &score);
	}else{
		score = score_start.String();
	}
	return ziterator(this, name, key, score, score_end, limit, Iterator::BACKWARD);
}

static void get_znames(Iterator *it, std::vector<std::string> *list){
	while(it->next()){
		Bytes ks = it->key();
		//dump(ks.data(), ks.size());
		if(ks.data()[0] != DataType::ZSIZE){
			break;
		}
		std::string n;
		if(decode_zsize_key(ks, &n) == -1){
			continue;
		}
		list->push_back(n);
	}
}

int SSDBImpl::zlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
		std::vector<std::string> *list){
	std::string start;
	std::string end;
	
	start = encode_zsize_key(name_s);
	if(!name_e.empty()){
		end = encode_zsize_key(name_e);
	}
	
	Iterator *it = this->iterator(start, end, limit);
	get_znames(it, list);
	delete it;
	return 0;
}

int SSDBImpl::zrlist(const Bytes &name_s, const Bytes &name_e, uint64_t limit,
		std::vector<std::string> *list){
	std::string start;
	std::string end;

	start = encode_zsize_key(name_s);
	if(name_s.empty()){
		start.append(1, 255);
	}
	if(!name_e.empty()){
		end = encode_zsize_key(name_e);
	}

	Iterator *it = this->rev_iterator(start, end, limit);
	get_znames(it, list);
	delete it;
	return 0;
}

static std::string filter_score(const Bytes &score){
	int64_t s = score.Int64();
	return str(s);
}

// returns the number of newly added items
static int zset_one(SSDBImpl *ssdb, const Bytes &name, const Bytes &key, const Bytes &score, char log_type){
	if(name.empty() || key.empty()){
		log_error("empty name or key!");
		return 0;
		//return -1;
	}
	if(name.size() > SSDB_KEY_LEN_MAX ){
		log_error("name too long!");
		return -1;
	}
	if(key.size() > SSDB_KEY_LEN_MAX){
		log_error("key too long!");
		return -1;
	}
	std::string new_score = filter_score(score);
	std::string old_score;
	int found = ssdb->zget(name, key, &old_score);
	if(found == 0 || old_score != new_score){
		std::string k0, k1, k2;

		if(found){
			// delete zscore key
			k1 = encode_zscore_key(name, key, old_score);
			ssdb->binlogs->Delete(k1);
		}

		// add zscore key
		k2 = encode_zscore_key(name, key, new_score);
		ssdb->binlogs->Put(k2, "");

		// update zset
		k0 = encode_zset_key(name, key);
		ssdb->binlogs->Put(k0, new_score);
		ssdb->binlogs->add_log(log_type, BinlogCommand::ZSET, k0);

		return found? 0 : 1;
	}
	return 0;
}

static int zdel_one(SSDBImpl *ssdb, const Bytes &name, const Bytes &key, char log_type){
	if(name.size() > SSDB_KEY_LEN_MAX ){
		log_error("name too long!");
		return -1;
	}
	if(key.size() > SSDB_KEY_LEN_MAX){
		log_error("key too long!");
		return -1;
	}
	std::string old_score;
	int found = ssdb->zget(name, key, &old_score);
	if(found != 1){
		return 0;
	}

	std::string k0, k1;
	// delete zscore key
	k1 = encode_zscore_key(name, key, old_score);
	ssdb->binlogs->Delete(k1);

	// delete zset
	k0 = encode_zset_key(name, key);
	ssdb->binlogs->Delete(k0);
	ssdb->binlogs->add_log(log_type, BinlogCommand::ZDEL, k0);

	return 1;
}

static int incr_zsize(SSDBImpl *ssdb, const Bytes &name, int64_t incr){
	int64_t size = ssdb->zsize(name);
	size += incr;
	std::string size_key = encode_zsize_key(name);
	if(size == 0){
		ssdb->binlogs->Delete(size_key);
	}else{
		ssdb->binlogs->Put(size_key, leveldb::Slice((char *)&size, sizeof(int64_t)));
	}
	return 0;
}

int64_t SSDBImpl::zfix(const Bytes &name){
	Transaction trans(binlogs);
	std::string it_start, it_end;
	Iterator *it;
	leveldb::Status s;
	int64_t size = 0;
	int64_t old_size;

	it_start = encode_zscore_key(name, "", SSDB_SCORE_MIN);
	it_end = encode_zscore_key(name, "\xff", SSDB_SCORE_MAX);
	it = this->iterator(it_start, it_end, UINT64_MAX);
	size = 0;
	while(it->next()){
		Bytes ks = it->key();
		//Bytes vs = it->val();
		//dump(ks.data(), ks.size(), "z.next");
		//dump(vs.data(), vs.size(), "z.next");
		if(ks.data()[0] != DataType::ZSCORE){
			break;
		}
		std::string name2, key, score;
		if(decode_zscore_key(ks, &name2, &key, &score) == -1){
			size = -1;
			break;
		}
		if(name != name2){
			break;
		}
		size ++;
		
		std::string buf = encode_zset_key(name, key);
		std::string score2;
		s = ldb->Get(leveldb::ReadOptions(), buf, &score2);
		if(!s.ok() && !s.IsNotFound()){
			log_error("zget error: %s", s.ToString().c_str());
			size = -1;
			break;
		}
		if(s.IsNotFound() || score != score2){
			log_info("fix incorrect zset item, name: %s, key: %s, score: %s",
				hexmem(name.data(), name.size()).c_str(),
				hexmem(key.data(), key.size()).c_str(),
				hexmem(score.data(), score.size()).c_str()
				);
			s = ldb->Put(leveldb::WriteOptions(), buf, score);
			if(!s.ok()){
				log_error("db error! %s", s.ToString().c_str());
				size = -1;
				break;
			}
		}
	}
	delete it;
	if(size == -1){
		return -1;
	}

	old_size = this->zsize(name);
	if(old_size == -1){
		return -1;
	}
	if(old_size != size){
		log_info("fix zsize, name: %s, size: %" PRId64 " => %" PRId64,
			hexmem(name.data(), name.size()).c_str(), old_size, size);
		std::string size_key = encode_zsize_key(name);
		if(size == 0){
			s = ldb->Delete(leveldb::WriteOptions(), size_key);
		}else{
			s = ldb->Put(leveldb::WriteOptions(), size_key, leveldb::Slice((char *)&size, sizeof(int64_t)));
		}
	}
	
	//////////////////////////////////////////

	it_start = encode_zset_key(name, "");
	it_end = encode_zset_key(name.String() + "\xff", "");
	it = this->iterator(it_start, it_end, UINT64_MAX);
	size = 0;
	while(it->next()){
		Bytes ks = it->key();
		//Bytes vs = it->val();
		//dump(ks.data(), ks.size(), "z.next");
		//dump(vs.data(), vs.size(), "z.next");
		if(ks.data()[0] != DataType::ZSET){
			break;
		}
		std::string name2, key;
		if(decode_zset_key(ks, &name2, &key) == -1){
			size = -1;
			break;
		}
		if(name != name2){
			break;
		}
		size ++;
		Bytes score = it->val();
		
		std::string buf = encode_zscore_key(name, key, score);
		std::string score2;
		s = ldb->Get(leveldb::ReadOptions(), buf, &score2);
		if(!s.ok() && !s.IsNotFound()){
			log_error("zget error: %s", s.ToString().c_str());
			size = -1;
			break;
		}
		if(s.IsNotFound()){
			log_info("fix incorrect zset score, name: %s, key: %s, score: %s",
				hexmem(name.data(), name.size()).c_str(),
				hexmem(key.data(), key.size()).c_str(),
				hexmem(score.data(), score.size()).c_str()
				);
			s = ldb->Put(leveldb::WriteOptions(), buf, "");
			if(!s.ok()){
				log_error("db error! %s", s.ToString().c_str());
				size = -1;
				break;
			}
		}
	}
	delete it;
	if(size == -1){
		return -1;
	}

	old_size = this->zsize(name);
	if(old_size == -1){
		return -1;
	}
	if(old_size != size){
		log_info("fix zsize, name: %s, size: %" PRId64 " => %" PRId64,
			hexmem(name.data(), name.size()).c_str(), old_size, size);
		std::string size_key = encode_zsize_key(name);
		if(size == 0){
			s = ldb->Delete(leveldb::WriteOptions(), size_key);
		}else{
			s = ldb->Put(leveldb::WriteOptions(), size_key, leveldb::Slice((char *)&size, sizeof(int64_t)));
		}
	}
	
	//////////////////////////////////////////
	
	return size;
}
