/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef SSDB_ZSET_H_
#define SSDB_ZSET_H_

#include "ssdb_impl.h"

#define encode_score(s) big_endian((uint64_t)(s))
#define decode_score(s) big_endian((uint64_t)(s))

static inline
std::string encode_zsize_key(const Bytes &name){
	std::string buf;
	buf.append(1, DataType::ZSIZE);
	buf.append(name.data(), name.size());
	return buf;
}

inline static
int decode_zsize_key(const Bytes &slice, std::string *name){
	Decoder decoder(slice.data(), slice.size());
	if(decoder.skip(1) == -1){
		return -1;
	}
	if(decoder.read_data(name) == -1){
		return -1;
	}
	return 0;
}

static inline
std::string encode_zset_key(const Bytes &name, const Bytes &key){
	std::string buf;
	buf.append(1, DataType::ZSET);
	buf.append(1, (uint8_t)name.size());
	buf.append(name.data(), name.size());
	buf.append(1, (uint8_t)key.size());
	buf.append(key.data(), key.size());
	return buf;
}

static inline
int decode_zset_key(const Bytes &slice, std::string *name, std::string *key){
	Decoder decoder(slice.data(), slice.size());
	if(decoder.skip(1) == -1){
		return -1;
	}
	if(decoder.read_8_data(name) == -1){
		return -1;
	}
	if(decoder.read_8_data(key) == -1){
		return -1;
	}
	return 0;
}

// type, len, key, score, =, val
static inline
std::string encode_zscore_key(const Bytes &key, const Bytes &val, const Bytes &score){
	std::string buf;
	buf.append(1, DataType::ZSCORE);
	buf.append(1, (uint8_t)key.size());
	buf.append(key.data(), key.size());

	int64_t s = score.Int64();
	if(s < 0){
		buf.append(1, '-');
	}else{
		buf.append(1, '=');
	}
	s = encode_score(s);

	buf.append((char *)&s, sizeof(int64_t));
	buf.append(1, '=');
	buf.append(val.data(), val.size());
	return buf;
}

static inline
int decode_zscore_key(const Bytes &slice, std::string *name, std::string *key, std::string *score){
	Decoder decoder(slice.data(), slice.size());
	if(decoder.skip(1) == -1){
		return -1;
	}
	if(decoder.read_8_data(name) == -1){
		return -1;
	}
	if(decoder.skip(1) == -1){
		return -1;
	}
	int64_t s;
	if(decoder.read_int64(&s) == -1){
		return -1;
	}else{
		if(score != NULL){
			s = decode_score(s);
			score->assign(str(s));
		}
	}
	if(decoder.skip(1) == -1){
		return -1;
	}
	if(decoder.read_data(key) == -1){
		return -1;
	}
	return 0;
}

#endif
