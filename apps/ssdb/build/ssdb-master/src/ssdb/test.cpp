/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include <string>
#include "ssdb.h"
#include "../util/log.h"
#include "../util/config.h"

int main(int argc, char **argv){
	set_log_level(Logger::LEVEL_TRACE);
	std::string work_dir = "./tmp/a";
	Options opt;
	opt.compression = "no";

	SSDB *ssdb = NULL;
	ssdb = SSDB::open(opt, work_dir);
	if(!ssdb){
		log_fatal("could not open work_dir: %s", work_dir.c_str());
		fprintf(stderr, "could not open work_dir: %s\n", work_dir.c_str());
		exit(1);
	}
	std::string key, val;
	key = "a";
	
	val.append(1024 * 1024, 'a');
	ssdb->raw_set("tmp", val);
	ssdb->compact();

	uint64_t size;
	size = ssdb->size();
	log_debug("dbsize: %d", size);


	ssdb->get(key, &val);
	int num = str_to_int(val) + 1;
	ssdb->set(key, str(num));
	ssdb->get(key, &val);
	
	log_debug("%s", val.c_str());
	delete ssdb;
}
