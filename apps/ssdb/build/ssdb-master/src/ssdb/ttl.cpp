/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include <pthread.h>
#include <time.h>
#include "../include.h"
#include "../util/log.h"
#include "ttl.h"

#define EXPIRATION_LIST_KEY "\xff\xff\xff\xff\xff|EXPIRE_LIST|KV"
#define BATCH_SIZE    1000

ExpirationHandler::ExpirationHandler(SSDB *ssdb){
	this->ssdb = ssdb;
	this->thread_quit = false;
	this->list_name = EXPIRATION_LIST_KEY;
	this->first_timeout = 0;
	this->start();
}

ExpirationHandler::~ExpirationHandler(){
	Locking l(&this->mutex);
	this->stop();
	ssdb = NULL;
}

void ExpirationHandler::start(){
	thread_quit = false;
	pthread_t tid;
	int err = pthread_create(&tid, NULL, &ExpirationHandler::thread_func, this);
	if(err != 0){
		log_fatal("can't create thread: %s", strerror(err));
		exit(0);
	}
}

void ExpirationHandler::stop(){
	thread_quit = true;
	for(int i=0; i<100; i++){
		if(!thread_quit){
			break;
		}
		usleep(10 * 1000);
	}
}

int ExpirationHandler::set_ttl(const Bytes &key, int64_t ttl){
	int64_t expired = time_ms() + ttl * 1000;
	char data[30];
	int size = snprintf(data, sizeof(data), "%" PRId64, expired);
	if(size <= 0){
		log_error("snprintf return error!");
		return -1;
	}

	int ret = ssdb->zset(this->list_name, key, Bytes(data, size));
	if(ret == -1){
		return -1;
	}
	if(expired < first_timeout){
		first_timeout = expired;
	}
	std::string s_key = key.String();
	if(!fast_keys.empty() && expired <= fast_keys.max_score()){
		fast_keys.add(s_key, expired);
		if(fast_keys.size() > BATCH_SIZE){
			fast_keys.pop_back();
		}
	}else{
		fast_keys.del(s_key);
		//log_debug("don't put in fast_keys");
	}
	
	return 0;
}

int ExpirationHandler::del_ttl(const Bytes &key){
	// 这样用是有 bug 的, 虽然 fast_keys 为空, 不代表整个 ttl 队列为空
	// if(!this->fast_keys.empty()){
	if(first_timeout != INT64_MAX){
		fast_keys.del(key.String());
		ssdb->zdel(this->list_name, key);
	}
	return 0;
}

int64_t ExpirationHandler::get_ttl(const Bytes &key){
	std::string score;
	if(ssdb->zget(this->list_name, key, &score) == 1){
		int64_t ex = str_to_int64(score);
		return (ex - time_ms())/1000;
	}
	return -1;
}

void ExpirationHandler::load_expiration_keys_from_db(int num){
	ZIterator *it;
	it = ssdb->zscan(this->list_name, "", "", "", num);
	int n = 0;
	while(it->next()){
		n ++;
		std::string &key = it->key;
		int64_t score = str_to_int64(it->score);
		if(score < 2000000000){
			// older version compatible
			score *= 1000;
		}
		fast_keys.add(key, score);
	}
	delete it;
	log_debug("load %d keys into fast_keys", n);
}

void ExpirationHandler::expire_loop(){
	Locking l(&this->mutex);
	if(!this->ssdb){
		return;
	}

	if(this->fast_keys.empty()){
		this->load_expiration_keys_from_db(BATCH_SIZE);
		if(this->fast_keys.empty()){
			this->first_timeout = INT64_MAX;
			return;
		}
	}
	
	int64_t score;
	std::string key;
	if(this->fast_keys.front(&key, &score)){
		this->first_timeout = score;
		
		if(score <= time_ms()){
			log_debug("expired %s", key.c_str());
			ssdb->del(key);
			ssdb->zdel(this->list_name, key);
			this->fast_keys.pop_front();
		}
	}
}

void* ExpirationHandler::thread_func(void *arg){
	ExpirationHandler *handler = (ExpirationHandler *)arg;
	
	while(!handler->thread_quit){
		if(handler->first_timeout > time_ms()){
			usleep(10 * 1000);
			continue;
		}
		handler->expire_loop();
	}
	
	log_debug("ExpirationHandler thread quit");
	handler->thread_quit = false;
	return (void *)NULL;
}
