/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef SSDB_TTL_H_
#define SSDB_TTL_H_

#include "ssdb.h"
#include "../util/thread.h"
#include "../util/sorted_set.h"
#include <string>

class ExpirationHandler
{
public:
	Mutex mutex;

	ExpirationHandler(SSDB *ssdb);
	~ExpirationHandler();

	// "In Redis 2.6 or older the command returns -1 if the key does not exist
	// or if the key exist but has no associated expire. Starting with Redis 2.8.."
	// I stick to Redis 2.6
	int64_t get_ttl(const Bytes &key);
	// The caller must hold mutex before calling set/del functions
	int del_ttl(const Bytes &key);
	int set_ttl(const Bytes &key, int64_t ttl);

private:
	SSDB *ssdb;
	volatile bool thread_quit;
	std::string list_name;
	int64_t first_timeout;
	SortedSet fast_keys;

	void start();
	void stop();
	void expire_loop();
	static void* thread_func(void *arg);
	void load_expiration_keys_from_db(int num);
};

#endif
