#include "app.h"
#include "log.h"
#include "file.h"
#include "config.h"
#include "daemon.h"
#include "strings.h"
#include <stdio.h>

int Application::main(int argc, char **argv){
	conf = NULL;

	welcome();
	parse_args(argc, argv);
	init();

	write_pid();
	run();
	remove_pidfile();
	
	delete conf;
	return 0;
}

void Application::usage(int argc, char **argv){
	printf("Usage:\n");
	printf("    %s [-d] /path/to/app.conf [-s start|stop|restart]\n", argv[0]);
	printf("Options:\n");
	printf("    -d    run as daemon\n");
	printf("    -s    option to start|stop|restart the server\n");
	printf("    -h    show this message\n");
}

void Application::parse_args(int argc, char **argv){
	for(int i=1; i<argc; i++){
		std::string arg = argv[i];
		if(arg == "-d"){
			app_args.is_daemon = true;
		}else if(arg == "-v"){
			exit(0);
		}else if(arg == "-h"){
			usage(argc, argv);
			exit(0);
		}else if(arg == "-s"){
			if(argc > i + 1){
				i ++;
				app_args.start_opt = argv[i];
			}else{
				usage(argc, argv);
				exit(1);
			}
			if(app_args.start_opt != "start" && app_args.start_opt != "stop" && app_args.start_opt != "restart"){
				usage(argc, argv);
				fprintf(stderr, "Error: bad argument: '%s'\n", app_args.start_opt.c_str());
				exit(1);
			}
		}else{
			app_args.conf_file = argv[i];
		}
	}

	if(app_args.conf_file.empty()){
		usage(argc, argv);
		exit(1);
	}
}

void Application::init(){
	if(!is_file(app_args.conf_file.c_str())){
		fprintf(stderr, "'%s' is not a file or not exists!\n", app_args.conf_file.c_str());
		exit(1);
	}
	conf = Config::load(app_args.conf_file.c_str());
	if(!conf){
		fprintf(stderr, "error loading conf file: '%s'\n", app_args.conf_file.c_str());
		exit(1);
	}
	{
		std::string conf_dir = real_dirname(app_args.conf_file.c_str());
		if(chdir(conf_dir.c_str()) == -1){
			fprintf(stderr, "error chdir: %s\n", conf_dir.c_str());
			exit(1);
		}
	}

	app_args.pidfile = conf->get_str("pidfile");

	if(app_args.start_opt == "stop"){
		kill_process();
		exit(0);
	}
	if(app_args.start_opt == "restart"){
		if(file_exists(app_args.pidfile)){
			kill_process();
		}
	}
	
	check_pidfile();
	
	{ // logger
		std::string log_output;
		std::string log_level_;
		int64_t log_rotate_size;

		log_level_ = conf->get_str("logger.level");
		strtolower(&log_level_);
		if(log_level_.empty()){
			log_level_ = "debug";
		}
		int level = Logger::get_level(log_level_.c_str());
		log_rotate_size = conf->get_int64("logger.rotate.size");
		log_output = conf->get_str("logger.output");
		if(log_output == ""){
			log_output = "stdout";
		}
		if(log_open(log_output.c_str(), level, true, log_rotate_size) == -1){
			fprintf(stderr, "error opening log file: %s\n", log_output.c_str());
			exit(1);
		}
	}

	app_args.work_dir = conf->get_str("work_dir");
	if(app_args.work_dir.empty()){
		app_args.work_dir = ".";
	}
	if(!is_dir(app_args.work_dir.c_str())){
		fprintf(stderr, "'%s' is not a directory or not exists!\n", app_args.work_dir.c_str());
		exit(1);
	}

	// WARN!!!
	// deamonize() MUST be called before any thread is created!
	if(app_args.is_daemon){
		daemonize();
	}
}

int Application::read_pid(){
	if(app_args.pidfile.empty()){
		return -1;
	}
	std::string s;
	file_get_contents(app_args.pidfile, &s);
	if(s.empty()){
		return -1;
	}
	return str_to_int(s);
}

void Application::write_pid(){
	if(!app_args.is_daemon){
		return;
	}
	if(app_args.pidfile.empty()){
		return;
	}
	int pid = (int)getpid();
	std::string s = str(pid);
	int ret = file_put_contents(app_args.pidfile, s);
	if(ret == -1){
		log_error("Failed to write pidfile '%s'(%s)", app_args.pidfile.c_str(), strerror(errno));
		exit(1);
	}
}

void Application::check_pidfile(){
	if(!app_args.is_daemon){
		return;
	}
	if(app_args.pidfile.size()){
		if(access(app_args.pidfile.c_str(), F_OK) == 0){
			fprintf(stderr, "Fatal error!\nPidfile %s already exists!\n"
				"Kill the running process before you run this command,\n"
				"or use '-s restart' option to restart the server.\n",
				app_args.pidfile.c_str());
			exit(1);
		}
	}
}

void Application::remove_pidfile(){
	if(!app_args.is_daemon){
		return;
	}
	if(app_args.pidfile.size()){
		remove(app_args.pidfile.c_str());
	}
}

void Application::kill_process(){
	int pid = read_pid();
	if(pid == -1){
		fprintf(stderr, "could not read pidfile: %s(%s)\n", app_args.pidfile.c_str(), strerror(errno));
		exit(1);
	}
	if(kill(pid, 0) == -1 && errno == ESRCH){
		fprintf(stderr, "process: %d not running\n", pid);
		remove_pidfile();
		return;
	}
	int ret = kill(pid, SIGTERM);
	if(ret == -1){
		fprintf(stderr, "could not kill process: %d(%s)\n", pid, strerror(errno));
		exit(1);
	}
	
	while(file_exists(app_args.pidfile)){
		usleep(100 * 1000);
	}
}

