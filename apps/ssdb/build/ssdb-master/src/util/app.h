#ifndef UTIL_APP_H
#define UTIL_APP_H

#include <string>

class Config;

class Application{
public:
	Application(){};
	virtual ~Application(){};

	int main(int argc, char **argv);
	
	virtual void usage(int argc, char **argv);
	virtual void welcome() = 0;
	virtual void run() = 0;

protected:
	struct AppArgs{
		bool is_daemon;
		std::string pidfile;
		std::string conf_file;
		std::string work_dir;
		std::string start_opt;

		AppArgs(){
			is_daemon = false;
			start_opt = "start";
		}
	};

	Config *conf;
	AppArgs app_args;
	
private:
	void parse_args(int argc, char **argv);
	void init();

	int read_pid();
	void write_pid();
	void check_pidfile();
	void remove_pidfile();
	void kill_process();
};

#endif
