/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "bytes.h"

Buffer::Buffer(int total){
	size_ = 0;
	total_ = total;
	buf = (char *)malloc(total);
	data_ = buf;
}

Buffer::~Buffer(){
	free(buf);
}

void Buffer::nice(){
	// 保证不改变后半段的数据, 以便使已生成的 Bytes 不失效.
	if(size_ == 0 || data_ - buf > total_/2){
		if(size_ > 0){
			memcpy(buf, data_, size_);
		}
		data_ = buf;
	}
}

void Buffer::shrink(int total){
	if(total <= 0){
		total = 8 * 1024;
	}
	int offset = data_ - buf;
	if(offset + size_ > total){ // 要求的空间太小, 停止
		return;
	}
	
	total_ = total;
	buf = (char *)realloc(buf, total);
	data_ = buf + offset;
}

int Buffer::grow(){ // 扩大缓冲区
	int n;
	if(total_ < 8 * 1024){
		n = 8 * 1024;
	}else if(total_ < 512 * 1024){
		n = 8 * total_;
	}else{
		n = 2 * total_;
	}
	//log_debug("Buffer resize %d => %d", total_, n);
	char *p = (char *)realloc(buf, n);
	if(p == NULL){
		return -1;
	}
	data_ = p + (data_ - buf);
	buf = p;
	total_ = n;
	return total_;
}

std::string Buffer::stats() const{
	char str[1024 * 32];
	str[0] = '\n';
	sprintf(str, "total: %d, data: %d, size: %d, slot: %d",
		total_, (int)(data_ - buf), size_, (int)(slot() - buf));
	return std::string(str);
}

int Buffer::read_record(Bytes *s){
	char *head = this->data();
	char *body = (char *)memchr(head, '\n', this->size_);
	if(body == NULL){
		return 0;
	}
	body ++;

	int head_len = body - head;
	if(head[0] < '0' || head[0] > '9'){
		return -1;
	}

	char head_str[20];
	if(head_len + 1 > (int)sizeof(head_str)){
		return -1;
	}
	memcpy(head_str, head, head_len - 1); // no '\n'
	head_str[head_len - 1] = '\0';

	int body_len = atoi(head_str);
	if(body_len < 0){
		return -1;
	}

	char *p = body + body_len;
	if(this->size_ >= head_len + body_len + 1){
		if(p[0] == '\n'){
			this->decr(head_len + body_len + 1);
			*s = Bytes(body, body_len);
			return 1;
		}else if(p[0] == '\r'){
			if(this->size_ >= head_len + body_len + 2){
				if(p[1] == '\n'){
					this->decr(head_len + body_len + 2);
					*s = Bytes(body, body_len);
					return 1;
				}else{
					return -1;
				}
			}
		}else{
			return -1;
		}
	}
	return 0;
}

int Buffer::append_record(const Bytes &s){
	// 16 is the maximum length of literal string of s.size()
	int size = 16 + s.size() + 1;
	while(size > this->space()){
		if(this->grow() == -1){
			return -1;
		}
	}

	char len[16];
	int num = snprintf(len, sizeof(len), "%d\n", (int)s.size());

	char *p = this->slot();
	memcpy(p, len, num);
	p += num;

	memcpy(p, s.data(), s.size());
	p += s.size();

	*p = '\n';
	p += 1;
	this->size_ += (num + s.size() + 1);
	return (num + s.size() + 1);
}

int Buffer::append(char c){
	while(1 > this->space()){
		if(this->grow() == -1){
			return -1;
		}
	}

	*(this->slot()) = c;
	size_ += 1;
	return 1;
}

int Buffer::append(const void *p, int size){
	while(size > this->space()){
		if(this->grow() == -1){
			return -1;
		}
	}

	memcpy(this->slot(), p, size);
	size_ += size;
	return size;
}

int Buffer::append(const char *p){
	return this->append(p, strlen(p));
}

int Buffer::append(const Bytes &s){
	return this->append(s.data(), s.size());
}
