/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef UTIL_BYTES_H_
#define UTIL_BYTES_H_

#include "strings.h"

// readonly
// to replace std::string
class Bytes{
	private:
		const char *data_;
		int size_;
	public:
		Bytes(){
			data_ = "";
			size_ = 0;
		}

		Bytes(void *data, int size){
			data_ = (char *)data;
			size_ = size;
		}

		Bytes(const char *data, int size){
			data_ = data;
			size_ = size;
		}

		Bytes(const std::string &str){
			data_ = str.data();
			size_ = (int)str.size();
		}

		Bytes(const char *str){
			data_ = str;
			size_ = (int)strlen(str);
		}

		const char* data() const{
			return data_;
		}

		bool empty() const{
			return size_ == 0;
		}

		int size() const{
			return size_;
		}

		int compare(const Bytes &b) const{
			const int min_len = (size_ < b.size_) ? size_ : b.size_;
			int r = memcmp(data_, b.data_, min_len);
			if(r == 0){
				if (size_ < b.size_) r = -1;
				else if (size_ > b.size_) r = +1;
			}
			return r;
		}

		std::string String() const{
			return std::string(data_, size_);
		}

		int Int() const{
			return str_to_int(data_, size_);
		}

		int64_t Int64() const{
			return str_to_int64(data_, size_);
		}

		uint64_t Uint64() const{
			return str_to_uint64(data_, size_);
		}

		double Double() const{
			return str_to_double(data_, size_);
		}
};

inline
bool operator==(const Bytes &x, const Bytes &y){
	return ((x.size() == y.size()) &&
			(memcmp(x.data(), y.data(), x.size()) == 0));
}

inline
bool operator!=(const Bytes &x, const Bytes &y){
	return !(x == y);
}

inline
bool operator>(const Bytes &x, const Bytes &y){
	return x.compare(y) > 0;
}

inline
bool operator>=(const Bytes &x, const Bytes &y){
	return x.compare(y) >= 0;
}

inline
bool operator<(const Bytes &x, const Bytes &y){
	return x.compare(y) < 0;
}

inline
bool operator<=(const Bytes &x, const Bytes &y){
	return x.compare(y) <= 0;
}



class Buffer{
	private:
		char *buf;
		char *data_;
		int size_;
		int total_;
	public:
		Buffer(int total);
		~Buffer();

		// 缓冲区大小
		int total() const{
			return total_;
		}

		bool empty() const{
			return size_ == 0;
		}

		// 数据
		char* data() const{
			return data_;
		}

		// 数据大小
		int size() const{
			return size_;
		}

		// 指向空闲处
		char* slot() const{
			return data_ + size_;
		}

		int space() const{
			return total_ - (int)(data_ - buf) - size_;
		}

		void incr(int num){
			size_ += num;
		}

		void decr(int num){
			size_ -= num;
			data_ += num;
		}

		// 保证不改变后半段的数据, 以便使已生成的 Bytes 不失效.
		void nice();
		// 扩大缓冲区
		int grow();
		// 缩小缓冲区, 如果指定的 total 太小超过数据范围, 或者不合理, 则不会缩小
		void shrink(int total=0);

		std::string stats() const;
		int read_record(Bytes *s);

		int append(char c);
		int append(const char *p);
		int append(const void *p, int size);
		int append(const Bytes &s);

		int append_record(const Bytes &s);
};


class Decoder{
private:
	const char *p;
	int size;
	Decoder(){}
public:
	Decoder(const char *p, int size){
		this->p = p;
		this->size = size;
	}
	int skip(int n){
		if(size < n){
			return -1;
		}
		p += n;
		size -= n;
		return n;
	}
	int read_int64(int64_t *ret){
		if(size_t(size) < sizeof(int64_t)){
			return -1;
		}
		if(ret){
			*ret = *(int64_t *)p;
		}
		p += sizeof(int64_t);
		size -= sizeof(int64_t);
		return sizeof(int64_t);
	}
	int read_uint64(uint64_t *ret){
		if(size_t(size) < sizeof(uint64_t)){
			return -1;
		}
		if(ret){
			*ret = *(uint64_t *)p;
		}
		p += sizeof(uint64_t);
		size -= sizeof(uint64_t);
		return sizeof(uint64_t);
	}
	int read_data(std::string *ret){
		int n = size;
		if(ret){
			ret->assign(p, size);
		}
		p += size;
		size = 0;
		return n;
	}
	int read_8_data(std::string *ret=NULL){
		if(size < 1){
			return -1;
		}
		int len = (uint8_t)p[0];
		p += 1;
		size -= 1;
		if(size < len){
			return -1;
		}
		if(ret){
			ret->assign(p, len);
		}
		p += len;
		size -= len;
		return 1 + len;
	}
};

#endif

