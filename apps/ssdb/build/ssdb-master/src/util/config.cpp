/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "log.h"
#include "config.h"
#include "strings.h"

inline static
int is_kv_seperator(int ch){
	return (ch == '=') || (ch == ':');
}

Config* Config::load(const char *filename){
	FILE *fp = NULL;
	int lineno = 0;

	if(strcmp(filename, "stdout") == 0){
		fp = stdin;
	}else{
		fp = fopen(filename, "r");
		if(!fp){
			log_error("error opening file '%s': %s", filename, strerror(errno));
			return NULL;
		}
	}

	Config *root = new Config("root", "");
	Config *cfg = root;
	int last_indent = 0;
	char buf[CONFIG_MAX_LINE];
	while(fgets(buf, sizeof(buf), fp)){
		lineno++;

		buf[strlen(buf) - 1] = '\0'; /* 去除 '\n' */
		if(is_empty_str(buf)){
			continue;
		}

		/* 有效行以 \t* 开头 */
		int indent = strspn(buf, "\t");
		char *key = buf + indent;

		if(*key == '#'){
			cfg->add_child("#", key + 1, lineno);
			continue;
		}
		if(indent <= last_indent){
			for(int i = indent; i <= last_indent; i++){
				/* 第一个配置时, 此条件为真 */
				if(cfg != root){
					cfg = cfg->parent;
				}
			}
		}else if(indent > last_indent + 1){
			log_error("invalid indent line(%d)", lineno);
			goto err;
		}
		
		if(isspace(*key)){
			log_error("invalid line(%d): unexpected whitespace char '%c'", lineno, *key);
			goto err;
		}

		char *val = key;
		/* 跳过键名 */
		while(*val && !is_kv_seperator(*val)){
			val++;
		}
		if(*val == '\0'){
			log_error("invalid line(%d): %s, expecting ':' or '='", lineno, *val);
			goto err;
		}else if(!is_kv_seperator(*val)){
			log_error("invalid line(%d): unexpected char '%c', expecting ':' or '='", lineno, *val);
			goto err;
		}
		*val++ = '\0';

		/* key 或者 value 的前后空白字符会被过滤 */
		key = trim(key);
		val = trim(val);

		cfg = cfg->add_child(key, val, lineno);
		if(cfg == NULL){
			goto err;
		}

		last_indent = indent;
	}
	if(ferror(fp)){
		log_error("error while reading file %s", filename);
		goto err;
	}
	fclose(fp);
	return root;
err:
	if(root){
		delete root;
	}
	if(fp && fp != stdin){
		fclose(fp);
	}
	return NULL;
}

Config::Config(const char *key, const char *val){
	this->parent = NULL;
	this->depth = 0;
	if(key){
		this->key = key;
	}
	if(val){
		this->val = val;
	}
};

Config::~Config(){
	//log_trace("%*sfree %s(%d)", depth*4, "", this->key.c_str(), this->children.size());
	for(int i = 0; i < (int)children.size(); i++){
		delete children[i];
	}
}

Config* Config::build_key_path(const char *key){
	char path[CONFIG_MAX_LINE];
	Config *conf = this;
	Config *c;

	snprintf(path, CONFIG_MAX_LINE, "%s", key);

	char *f, *fs; /* field, field seperator */
	f = fs = path;
	while(1){
		switch(*fs++){
			case '.':
			case '/':
				*(fs - 1) = '\0';
				c = (Config *)conf->find_child(f);
				if(c == NULL){
					c = conf->add_child(f);
				}
				conf = c;
				f = fs;
				break;
			case '\0':
				c = (Config *)conf->find_child(f);
				if(c == NULL){
					c = conf->add_child(f);
				}
				return c;
			default:
				break;
		}
	}
}

Config* Config::set(const char *key, const char *val){
	Config *c = this->build_key_path(key);
	c->val = val;
	log_trace("%*s'%s' : '%s'", depth*4, "", this->key.c_str(), key);
	return c;
}

Config* Config::add_child(const char *key, const char *val, int lineno){
	log_trace("add_child: %s", key);
	Config *c = new Config(key, val);
	c->parent = this;
	c->depth  = this->depth + 1;
	children.push_back(c);
	return c;
}

const Config* Config::find_child(const char *key) const{
	int i = (int)children.size()-1;
	for(; i >= 0; i--){
		if(children[i]->key == key){
			return children[i];
		}
	}
	return NULL;
}

const Config* Config::get(const char *key) const{
	char path[CONFIG_MAX_LINE];
	const Config *conf = this;

	snprintf(path, CONFIG_MAX_LINE, "%s", key);

	char *f, *fs; /* field, field seperator */
	f = fs = path;
	while(conf){
		switch(*fs++){
			case '.':
			case '/':
				*(fs - 1) = '\0';
				conf = conf->find_child(f);
				f = fs;
				break;
			case '\0':
				conf = conf->find_child(f);
				return conf;
			default:
				break;
		}
	}
	return conf;
}

int Config::num() const{
	return atoi(this->val.c_str());
}

const char* Config::str() const{
	return this->val.c_str();
}

int Config::get_num(const char *key) const{
	const Config *c = this->get(key);
	if(!c){
		return 0;
	}
	return c->num();
}

int64_t Config::get_int64(const char *key) const{
	const Config *c = this->get(key);
	if(!c){
		return 0;
	}
	return str_to_int64(c->val);
}

const char* Config::get_str(const char *key) const{
	const Config *c = this->get(key);
	if(!c){
		return "";
	}
	return c->str();
}

int Config::save(FILE *fp) const{
	for(int i = 0; i < (int)children.size(); i++){
		Config *c = children[i];
		for(int j=0; j<this->depth; j++){
			fputc('\t', fp);
		}

		if(c->is_comment()){
			fprintf(fp, "#%s\n", c->val.c_str());
		}else{
			fprintf(fp, "%s: %s\n", c->key.c_str(), c->val.c_str());
		}
		c->save(fp);
	}
	return 0;
}

int Config::save(const char *filename) const{
	FILE *fp;

	if(strcmp(filename, "stdout") == 0){
		fp = stdout;
	}else if(strcmp(filename, "stderr") == 0){
		fp = stderr;
	}else{
		fp = fopen(filename, "w");
		if(!fp){
			log_error("error opening file '%s': %s", filename, strerror(errno));
			return -1;
		}
	}
	this->save(fp);
	if(fp && fp != stdout && fp != stderr){
		fclose(fp);
	}
	return 0;
}

