/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef UTIL__CONFIG_H
#define UTIL__CONFIG_H

/*
语法定义:
	空白字符为 '\t \r\n'(制表符, 空格, 回车, 换行)
	忽略只包含空白字符的行
	有效行以 '\t*' 开头
	注释行以 '\t*#' 开头
	key 和 value 之间可以用等号'='或者冒号':'分隔
	key 不包含任何空白字符, 两端的空白字符被忽略
	value 两端的空白字符被忽略
	配置项可以有包含关系, 用一个 TAB 缩进表示父子关系

配置读取:
	用键名获取子配置项
	用斜杠'/'或者句号'.'分隔的配置项路径获取配置项
	把配置项的值作为整形(int)返回
	把配置项的值作为字符串(char *)返回
*/

#include <string>
#include <vector>
#include <stdint.h>

#define CONFIG_MAX_LINE		4096

/* special filenames: stdin, stdout, stderr */
class Config{
	private:
		Config *parent;
		int depth;

		Config* build_key_path(const char *key);
		Config* add_child(const char *key, const char *val="", int lineno=0);
		const Config* find_child(const char *key) const;
	public:
		Config(const char *key=NULL, const char *val=NULL);
		~Config();

		static Config* load(const char *filename);
		int save(FILE *fp) const;
		int save(const char *filename) const;

		std::vector<Config *> children;
		std::string key;
		std::string val;

		Config* set(const char *key, const char *val);
		const Config* get(const char *key) const;
		int num() const;
		int get_num(const char *key) const;
		int64_t get_int64(const char *key) const;
		const char* str() const;
		const char* get_str(const char *key) const;

		bool is_comment() const{
			return key[0] == '#';
		}
		std::string ToString() const{
			return key + ": " + val;
		}
};

#endif

/*
配置文件示例:

# this is a comment

author : ideawu
	url: http://www.ideawu.net

proxy :
	php =
		host = 127.0.0.1
		port = 8088
	py :
		host = 127.0.0.1
		port = 8080

cgi =
	pl = /usr/bin/perl

应用程序示例:

#include <stdio.h>
#include "config.h"

int main(int argc, char **argv){
	struct config *cfg, *c;

	cfg = cfg_load_file("cfg_test.conf");
	if(!cfg){
		return 0;
	}

	printf("\n");
	printf("proxy.php.host = %s\n", cfg_getstr(cfg, "proxy.php.host"));
	printf("proxy.php.port = %d\n", cfg_getnum(cfg, "proxy.php.port"));
	printf("cgi.pl = %s\n", cfg_getstr(cfg, "cgi.pl"));
	printf("\n");

	c = cfg_get(cfg, "author");
	printf("author: %s\n", cfg_str(c));
	printf("url: %s\n", cfg_getstr(c, "url"));
	printf("\n");

	cfg_free(cfg);
	return 0;
}

*/
