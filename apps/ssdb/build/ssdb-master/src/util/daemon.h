/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef UTIL_DAEMON_H
#define UTIL_DAEMON_H

int daemonize(const char *dir=NULL){
	switch(fork()){
		case -1:
			return -1;
		case 0:
			break;
		default:
			exit(0);
	}
	if(setsid() == -1){
		exit(0);
	}
	if(dir != NULL){
		if(chdir(dir) == -1){
			exit(0);
		}
	}

	if(close(STDIN_FILENO) == -1){
		exit(0);
	}
	if(close(STDOUT_FILENO) == -1){
		exit(0);
	}
	if(close(STDERR_FILENO) == -1){
		exit(0);
	}

	int fd = open("/dev/null", O_RDWR, 0);
	if(fd == -1){
		exit(0);
	}
	if(dup2(fd, STDIN_FILENO) == -1){
		exit(0);
	}
	if(dup2(fd, STDOUT_FILENO) == -1){
		exit(0);
	}
	if(dup2(fd, STDERR_FILENO) == -1){
		exit(0);
	}

	return 0;
}

#endif
