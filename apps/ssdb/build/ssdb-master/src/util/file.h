/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef UTIL_FILE_H_
#define UTIL_FILE_H_

#include <sys/types.h>
#include <sys/stat.h>
#include <unistd.h>
#include <string>

static inline
bool file_exists(const std::string &filename){
	struct stat st;
	return stat(filename.c_str(), &st) == 0;
}

static inline
bool is_dir(const std::string &filename){
	struct stat st;
	if(stat(filename.c_str(), &st) == -1){
		return false;
	}
	return (bool)S_ISDIR(st.st_mode);
}

static inline
bool is_file(const std::string &filename){
	struct stat st;
	if(stat(filename.c_str(), &st) == -1){
		return false;
	}
	return (bool)S_ISREG(st.st_mode);
}

// return number of bytes read
static inline
int file_get_contents(const std::string &filename, std::string *content){
	char buf[8192];
	FILE *fp = fopen(filename.c_str(), "rb");
	if(!fp){
		return -1;
	}
	int ret = 0;
	while(!feof(fp) && !ferror(fp)){
		int n = fread(buf, 1, sizeof(buf), fp);
		if(n > 0){
			ret += n;
			content->append(buf, n);
		}
	}
	fclose(fp);
	return ret;
}

// return number of bytes written
static inline
int file_put_contents(const std::string &filename, const std::string &content){
	FILE *fp = fopen(filename.c_str(), "wb");
	if(!fp){
		return -1;
	}
	int ret = fwrite(content.data(), 1, content.size(), fp);
	fclose(fp);
	return ret == (int)content.size()? ret : -1;
}

#endif
