/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef ICOMET_IPFILTER_H
#define ICOMET_IPFILTER_H

#include <string>
#include <set>

// filter ip address
class IpFilter{
private:
	
	bool check_hit(const std::set<std::string> &m, const std::string &ip){
		if(m.empty()){
			return false;
		}
		std::set<std::string>::const_iterator it;
		it = m.upper_bound(ip);
		if(it == m.end()){
			return false;
		}
		const std::string &prefix = *it;

		int len = prefix.size() - 1;
		if(prefix[len] == '='){
			return prefix.compare(0, len, ip) == 0;
		}else if(ip.size() > len){
			return ip.compare(0, len, prefix, 0, len) == 0;
		}
		return false;
	}
	
	bool is_full_ip(const std::string &ip_prefix){
		int n = 0;
		for(int i=0; i<(int)ip_prefix.size(); i++){
			if(ip_prefix[i] == '.'){
				n ++;
			}
		}
		return n == 3;
	}

public:
	bool allow_all;
	std::set<std::string> deny;
	std::set<std::string> allow;

	IpFilter(){
		allow_all = true;
	}
	
	void add_allow(const std::string &ip_prefix){
		if(ip_prefix == "all" || ip_prefix == "*"){
			allow_all = true;
		}else{
			allow_all = false;
			// '@' and '=' is greater than any char in ip
			std::string prefix = ip_prefix + (is_full_ip(ip_prefix)? "=" : "@");
			allow.insert(prefix);
		}
	}

	void del_allow(const std::string &ip_prefix){
		if(ip_prefix == "all" || ip_prefix == "*"){
			allow_all = false;
		}else{
			std::string prefix = ip_prefix + (is_full_ip(ip_prefix)? "=" : "@");
			allow.erase(prefix);
		}
	}
	
	void add_deny(const std::string &ip_prefix){
		if(ip_prefix == "all" || ip_prefix == "*"){
			// nothing
		}else{
			// deny_all is always true
			// '@' and '=' is greater than any char in ip
			std::string prefix = ip_prefix + (is_full_ip(ip_prefix)? "=" : "@");
			deny.insert(prefix);
		}
	}

	void del_deny(const std::string &ip_prefix){
		if(ip_prefix == "all" || ip_prefix == "*"){
			// nothing
		}else{
			std::string prefix = ip_prefix + (is_full_ip(ip_prefix)? "=" : "@");
			deny.erase(prefix);
		}
	}
	
	bool check_pass(const std::string &ip){
		// check specified allow/deny
		if(check_hit(deny, ip)){
			return false;
		}
		if(check_hit(allow, ip)){
			return true;
		}
		if(allow_all){
			return true;
		}else{
			return false;
		}
	}
};

#endif
