#ifndef UTIL_LINE_H
#define UTIL_LINE_H

#include <inttypes.h>
#include <string>
#include "strings.h"

class LineEncoder{
public:
	int write(const std::string &data){
		val.append(str_escape(data));
		val.append("\n");
		return 0;
	}
	
	int write(int data){
		return this->write(::str(data));
	}
	
	int write(int64_t data){
		return this->write(::str(data));
	}
	
	std::string str(){
		return val;
	}
private:
	std::string val;
};

class LineDecoder{
public:
	LineDecoder(const std::string &s){
		spos = 0;
		epos = 0;
		buf = s.data();
		len = (int)s.size();
	}
	
	int readline(std::string *ret){
		return this->read(ret);
	}
	
	int read(std::string *ret){
		while(epos < len && buf[epos] != '\n'){
			epos ++;
		}
		if(epos >= len || buf[epos] != '\n'){
			return -1;
		}
		std::string line(&buf[spos], epos - spos);
		spos = epos + 1;
		epos = spos;
		*ret = str_unescape(line);
		return (int)ret->size();
	}
	
	int read(int *ret){
		std::string line;
		if(this->read(&line) == -1){
			return -1;
		}
		*ret = str_to_int(line);
		return 0;
	}
		
	int read(int64_t *ret){
		std::string line;
		if(this->read(&line) == -1){
			return -1;
		}
		*ret = str_to_int64(line);
		return 0;
	}
	
public:
	const char *buf;
	int len;
	int spos;
	int epos;
};

#endif
