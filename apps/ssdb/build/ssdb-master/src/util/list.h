/*
Copyright (c) 2012-2014 The icomet Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef UTIL_LIST_H
#define UTIL_LIST_H

template <class T>
class LinkedList{
public:
	class Iterator{
	private:
		T p;
	public:
		friend class LinkedList;
		
		T next(){
			T ret = p;
			if(p){
				p = p->next;
			}
			return ret;
		}
	};
	friend class Iterator;
public:
	int size;
	T head;
	T tail;
	
	LinkedList(){
		size = 0;
		head = NULL;
		tail = NULL;
	}
	
	Iterator iterator(){
		Iterator it;
		it.p = this->head;
		return it;
	}
	
	bool empty() const{
		return size == 0;
	}
	
	void remove(T t){
		this->size --;
		if(t->prev){
			t->prev->next = t->next;
		}
		if(t->next){
			t->next->prev = t->prev;
		}
		if(this->head == t){
			this->head = t->next;
		}
		if(this->tail == t){
			this->tail = t->prev;
		}
	}
	
	T pop_front(){
		T t = this->head;
		this->remove(t);
		return t;
	}

	void push_back(T t){
		this->size ++;
		t->prev = this->tail;
		t->next = NULL;
		if(this->tail){
			this->tail->next = t;
		}else{ // both head and tail is empty
			this->head = t;
		}
		this->tail = t;
	}
};


#endif
