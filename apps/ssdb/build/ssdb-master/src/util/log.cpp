/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "log.h"
#include <algorithm>

static Logger logger;

int log_open(FILE *fp, int level, bool is_threadsafe){
	return logger.open(fp, level, is_threadsafe);
}

int log_open(const char *filename, int level, bool is_threadsafe, uint64_t rotate_size){
	return logger.open(filename, level, is_threadsafe, rotate_size);
}

int log_level(){
	return logger.level();
}

void set_log_level(int level){
	logger.set_level(level);
}

void set_log_level(const char *s){
	std::string ss(s);
	std::transform(ss.begin(), ss.end(), ss.begin(), ::tolower);
	int level = Logger::LEVEL_DEBUG;
	if(ss == "fatal"){
		level = Logger::LEVEL_FATAL;
	}else if(ss == "error"){
		level = Logger::LEVEL_ERROR;
	}else if(ss == "warn"){
		level = Logger::LEVEL_WARN;
	}else if(ss == "info"){
		level = Logger::LEVEL_INFO;
	}else if(ss == "debug"){
		level = Logger::LEVEL_DEBUG;
	}else if(ss == "trace"){
		level = Logger::LEVEL_TRACE;
	}
	logger.set_level(level);
}

int log_write(int level, const char *fmt, ...){
	va_list ap;
	va_start(ap, fmt);
	int ret = logger.logv(level, fmt, ap);
	va_end(ap);
	return ret;
}

/*****/

Logger* Logger::shared(){
	return &logger;
}

Logger::Logger(){
	fp = stdout;
	level_ = LEVEL_DEBUG;
	mutex = NULL;

	filename[0] = '\0';
	rotate_size_ = 0;
	stats.w_curr = 0;
	stats.w_total = 0;
}

Logger::~Logger(){
	if(mutex){
		pthread_mutex_destroy(mutex);
		free(mutex);
	}
	this->close();
}

std::string Logger::level_name(){
	switch(level_){
		case Logger::LEVEL_FATAL:
			return "fatal";
		case Logger::LEVEL_ERROR:
			return "error";
		case Logger::LEVEL_WARN:
			return "warn";
		case Logger::LEVEL_INFO:
			return "info";
		case Logger::LEVEL_DEBUG:
			return "debug";
		case Logger::LEVEL_TRACE:
			return "trace";
	}
	return "";
}

std::string Logger::output_name(){
	return filename;
}

uint64_t Logger::rotate_size(){
	return rotate_size_;
}

void Logger::threadsafe(){
	if(mutex){
		pthread_mutex_destroy(mutex);
		free(mutex);
		mutex = NULL;
	}
	mutex = (pthread_mutex_t *)malloc(sizeof(pthread_mutex_t));
	pthread_mutex_init(mutex, NULL);
}

int Logger::open(FILE *fp, int level, bool is_threadsafe){
	this->fp = fp;
	this->level_ = level;
	if(is_threadsafe){
		this->threadsafe();
	}
	return 0;
}

int Logger::open(const char *filename, int level, bool is_threadsafe, uint64_t rotate_size){
	if(strlen(filename) > PATH_MAX - 20){
		fprintf(stderr, "log filename too long!");
		return -1;
	}
	this->level_ = level;
	this->rotate_size_ = rotate_size;
	strcpy(this->filename, filename);

	FILE *fp;
	if(strcmp(filename, "stdout") == 0){
		fp = stdout;
	}else if(strcmp(filename, "stderr") == 0){
		fp = stderr;
	}else{
		fp = fopen(filename, "a");
		if(fp == NULL){
			return -1;
		}

		struct stat st;
		int ret = fstat(fileno(fp), &st);
		if(ret == -1){
			fprintf(stderr, "fstat log file %s error!", filename);
			return -1;
		}else{
			stats.w_curr = st.st_size;
		}
	}
	return this->open(fp, level, is_threadsafe);
}

void Logger::close(){
	if(fp != stdin && fp != stdout){
		fclose(fp);
	}
}

void Logger::rotate(){
	fclose(fp);
	char newpath[PATH_MAX];
	time_t time;
	struct timeval tv;
	struct tm *tm, tm_tmp;
	gettimeofday(&tv, NULL);
	time = tv.tv_sec;
	tm = localtime_r(&time, &tm_tmp);
	sprintf(newpath, "%s.%04d%02d%02d-%02d%02d%02d",
		this->filename,
		tm->tm_year + 1900, tm->tm_mon + 1, tm->tm_mday,
		tm->tm_hour, tm->tm_min, tm->tm_sec);

	//printf("rename %s => %s\n", this->filename, newpath);
	int ret = rename(this->filename, newpath);
	if(ret == -1){
		return;
	}
	fp = fopen(this->filename, "a");
	if(fp == NULL){
		return;
	}
	stats.w_curr = 0;
}

int Logger::get_level(const char *levelname){
	if(strcmp("trace", levelname) == 0){
		return LEVEL_TRACE;
	}
	if(strcmp("debug", levelname) == 0){
		return LEVEL_DEBUG;
	}
	if(strcmp("info", levelname) == 0){
		return LEVEL_INFO;
	}
	if(strcmp("warn", levelname) == 0){
		return LEVEL_WARN;
	}
	if(strcmp("error", levelname) == 0){
		return LEVEL_ERROR;
	}
	if(strcmp("fatal", levelname) == 0){
		return LEVEL_FATAL;
	}
	if(strcmp("none", levelname) == 0){
		return LEVEL_NONE;
	}
	return LEVEL_DEBUG;
}

inline static const char* get_level_name(int level){
	switch(level){
		case Logger::LEVEL_FATAL:
			return "[FATAL] ";
		case Logger::LEVEL_ERROR:
			return "[ERROR] ";
		case Logger::LEVEL_WARN:
			return "[WARN ] ";
		case Logger::LEVEL_INFO:
			return "[INFO ] ";
		case Logger::LEVEL_DEBUG:
			return "[DEBUG] ";
		case Logger::LEVEL_TRACE:
			return "[TRACE] ";
	}
	return "";
}

#define LEVEL_NAME_LEN	8
#define LOG_BUF_LEN		4096

int Logger::logv(int level, const char *fmt, va_list ap){
	if(logger.level_ < level){
		return 0;
	}

	char buf[LOG_BUF_LEN];
	int len;
	char *ptr = buf;

	time_t time;
	struct timeval tv;
	struct tm *tm, tm_tmp;
	gettimeofday(&tv, NULL);
	time = tv.tv_sec;
	tm = localtime_r(&time, &tm_tmp);
	/* %3ld 在数值位数超过3位的时候不起作用, 所以这里转成int */
	len = sprintf(ptr, "%04d-%02d-%02d %02d:%02d:%02d.%03d ",
		tm->tm_year + 1900, tm->tm_mon + 1, tm->tm_mday,
		tm->tm_hour, tm->tm_min, tm->tm_sec, (int)(tv.tv_usec/1000));
	if(len < 0){
		return -1;
	}
	ptr += len;

	memcpy(ptr, get_level_name(level), LEVEL_NAME_LEN);
	ptr += LEVEL_NAME_LEN;

	int space = sizeof(buf) - (ptr - buf) - 10;
	len = vsnprintf(ptr, space, fmt, ap);
	if(len < 0){
		return -1;
	}
	ptr += len > space? space : len;
	*ptr++ = '\n';
	*ptr = '\0';

	len = ptr - buf;
	if(this->mutex){
		pthread_mutex_lock(this->mutex);
	}
	fwrite(buf, len, 1, this->fp);
	fflush(this->fp);

	stats.w_curr += len;
	stats.w_total += len;
	if(rotate_size_ > 0 && stats.w_curr > rotate_size_){
		this->rotate();
	}
	if(this->mutex){
		pthread_mutex_unlock(this->mutex);
	}

	return len;
}

int Logger::trace(const char *fmt, ...){
	va_list ap;
	va_start(ap, fmt);
	int ret = logger.logv(Logger::LEVEL_TRACE, fmt, ap);
	va_end(ap);
	return ret;
}

int Logger::debug(const char *fmt, ...){
	va_list ap;
	va_start(ap, fmt);
	int ret = logger.logv(Logger::LEVEL_DEBUG, fmt, ap);
	va_end(ap);
	return ret;
}

int Logger::info(const char *fmt, ...){
	va_list ap;
	va_start(ap, fmt);
	int ret = logger.logv(Logger::LEVEL_INFO, fmt, ap);
	va_end(ap);
	return ret;
}

int Logger::warn(const char *fmt, ...){
	va_list ap;
	va_start(ap, fmt);
	int ret = logger.logv(Logger::LEVEL_WARN, fmt, ap);
	va_end(ap);
	return ret;
}

int Logger::error(const char *fmt, ...){
	va_list ap;
	va_start(ap, fmt);
	int ret = logger.logv(Logger::LEVEL_ERROR, fmt, ap);
	va_end(ap);
	return ret;
}

int Logger::fatal(const char *fmt, ...){
	va_list ap;
	va_start(ap, fmt);
	int ret = logger.logv(Logger::LEVEL_FATAL, fmt, ap);
	va_end(ap);
	return ret;
}
