/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef UTIL_LOG_H
#define UTIL_LOG_H

#include <inttypes.h>
#include <unistd.h>
#include <stdarg.h>
#include <stdio.h>
#include <stdint.h>
#include <stdlib.h>
#include <limits.h>
#include <errno.h>
#include <string.h>
#include <math.h>
#include <fcntl.h>
#include <assert.h>
#include <signal.h>
#include <time.h>
#include <sys/time.h>
#include <sys/types.h>
#include <sys/stat.h>
#include <pthread.h>
#include <string>

class Logger{
	public:
		static const int LEVEL_NONE		= (-1);
		static const int LEVEL_MIN		= 0;
		static const int LEVEL_FATAL	= 0;
		static const int LEVEL_ERROR	= 1;
		static const int LEVEL_WARN		= 2;
		static const int LEVEL_INFO		= 3;
		static const int LEVEL_DEBUG	= 4;
		static const int LEVEL_TRACE	= 5;
		static const int LEVEL_MAX		= 5;

		static int get_level(const char *levelname);
		
		static Logger* shared();
		
		std::string level_name();
		std::string output_name();
		uint64_t rotate_size();
	private:
		FILE *fp;
		char filename[PATH_MAX];
		int level_;
		pthread_mutex_t *mutex;

		uint64_t rotate_size_;
		struct{
			uint64_t w_curr;
			uint64_t w_total;
		}stats;

		void rotate();
		void threadsafe();
	public:
		Logger();
		~Logger();

		int level(){
			return level_;
		}

		void set_level(int level){
			this->level_ = level;
		}

		int open(FILE *fp, int level=LEVEL_DEBUG, bool is_threadsafe=false);
		int open(const char *filename, int level=LEVEL_DEBUG,
			bool is_threadsafe=false, uint64_t rotate_size=0);
		void close();

		int logv(int level, const char *fmt, va_list ap);

		int trace(const char *fmt, ...);
		int debug(const char *fmt, ...);
		int info(const char *fmt, ...);
		int warn(const char *fmt, ...);
		int error(const char *fmt, ...);
		int fatal(const char *fmt, ...);
};


int log_open(FILE *fp, int level=Logger::LEVEL_DEBUG, bool is_threadsafe=false);
int log_open(const char *filename, int level=Logger::LEVEL_DEBUG,
	bool is_threadsafe=false, uint64_t rotate_size=0);
int log_level();
void set_log_level(int level);
void set_log_level(const char *s);
int log_write(int level, const char *fmt, ...);


#ifndef IOS
	#ifdef NDEBUG
		#define log_trace(fmt, args...) do{}while(0)
	#else
		#define log_trace(fmt, args...)	\
			log_write(Logger::LEVEL_TRACE, "%s(%d): " fmt, __FILE__, __LINE__, ##args)
	#endif

	#define log_debug(fmt, args...)	\
		log_write(Logger::LEVEL_DEBUG, "%s(%d): " fmt, __FILE__, __LINE__, ##args)
	#define log_info(fmt, args...)	\
		log_write(Logger::LEVEL_INFO,  "%s(%d): " fmt, __FILE__, __LINE__, ##args)
	#define log_warn(fmt, args...)	\
		log_write(Logger::LEVEL_WARN,  "%s(%d): " fmt, __FILE__, __LINE__, ##args)
	#define log_error(fmt, args...)	\
		log_write(Logger::LEVEL_ERROR, "%s(%d): " fmt, __FILE__, __LINE__, ##args)
	#define log_fatal(fmt, args...)	\
		log_write(Logger::LEVEL_FATAL, "%s(%d): " fmt, __FILE__, __LINE__, ##args)
#else
	#define log_trace(fmt, args...) do{}while(0)
	#define log_debug(fmt, args...) do{}while(0)
	#define log_info(fmt, args...) do{}while(0)
	#define log_warn(fmt, args...) do{}while(0)
	#define log_error(fmt, args...) do{}while(0)
	#define log_fatal(fmt, args...) do{}while(0)
#endif

#endif
