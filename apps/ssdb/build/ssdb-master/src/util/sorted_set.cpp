/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "sorted_set.h"

int SortedSet::size() const{
	return (int)sorted_set.size();
}

int SortedSet::add(const std::string &key, int64_t score){
	int ret;
	std::map<std::string, std::set<Item>::iterator>::iterator it;
	
	it = existed.find(key);
	if(it == existed.end()){
		// new item
		ret = 1;
	}else{
		ret = 0;
		std::set<Item>::iterator it2 = it->second;
		const Item &item = *it2;
		if(item.score == score){
			// not updated
			return 0;
		}
		// remove existing item
		sorted_set.erase(it2);
	}
	
	Item item;
	item.key = key;
	item.score = score;
	
	std::pair<std::set<Item>::iterator, bool> p = sorted_set.insert(item);
	existed[key] = p.first;
	
	return ret;
}

int SortedSet::del(const std::string &key){
	int ret;
	std::map<std::string, std::set<Item>::iterator>::iterator it;
	
	it = existed.find(key);
	if(it == existed.end()){
		// new item
		ret = 0;
	}else{
		ret = 1;
		sorted_set.erase(it->second);
		existed.erase(it);
	}
	return ret;
}

int SortedSet::front(std::string *key, int64_t *score) const{
	std::set<Item>::iterator it2 = sorted_set.begin();
	if(it2 == sorted_set.end()){
		return 0;
	}
	const Item &item = *it2;
	*key = item.key;
	if(score){
		*score = item.score;
	}
	return 1;
}

int SortedSet::back(std::string *key, int64_t *score) const{
	std::set<Item>::reverse_iterator it2 = sorted_set.rbegin();
	if(it2 == sorted_set.rend()){
		return 0;
	}
	const Item &item = *it2;
	*key = item.key;
	if(score){
		*score = item.score;
	}
	return 1;
}

int64_t SortedSet::max_score() const{
	int64_t score = 0;
	std::string key;
	this->back(&key, &score);
	return score;
}


int SortedSet::pop_front(){
	if(sorted_set.empty()){
		return 0;
	}
	std::set<Item>::iterator it = sorted_set.begin();
	const Item &item = *it;
	existed.erase(item.key);
	sorted_set.erase(it);
	return 1;
}

int SortedSet::pop_back(){
	if(sorted_set.empty()){
		return 0;
	}
	std::set<Item>::iterator it = sorted_set.end();
	it --;
	const Item &item = *it;
	existed.erase(item.key);
	sorted_set.erase(it);
	return 1;
}
