/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef UTIL_SORTED_SET_H
#define UTIL_SORTED_SET_H

#include <inttypes.h>
#include <string>
#include <map>
#include <set>

class SortedSet
{
public:
	bool empty() const{
		return size() == 0;
	}
	int size() const;
	int add(const std::string &key, int64_t score);
	// 0: not found, 1: found and deleted
	int del(const std::string &key);
	// the first item is copied into key if SortedSet not empty
	int front(std::string *key, int64_t *score=NULL) const;
	int back(std::string *key, int64_t *score=NULL) const;
	int64_t max_score() const;
	int pop_front();
	int pop_back();
	
	/*
	class Iterator
	{
	public:
		bool next();
		const std::string& key();
		int64_t score();
	};
	
	Iterator begin();
	*/

private:
	struct Item
	{
		std::string key;
		int64_t score;
		
		bool operator<(const Item& b) const{
			return this->score < b.score
				|| (this->score == b.score && this->key < b.key);
		}
	};
	
	std::map<std::string, std::set<Item>::iterator> existed;
	std::set<Item> sorted_set;
};


/*
TODO: HashedWheel
Each item is linked in two list, one is slot list, the other
one is total list.
*/
/*
template <class T>
class SortedList
{
public:
	void add(const T data, int64_t score);
	T front();
	void pop_front();

	class Item
	{
	public:
		int64_t score;
		Item *prev;
		Item *next;
		//Item *slot_prev;
		//Item *slot_next;
		T data;
	};
};
*/

#endif
