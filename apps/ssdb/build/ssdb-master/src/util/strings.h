/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef UTIL_STRING_H
#define UTIL_STRING_H

#include <unistd.h>
#include <string.h>
#include <errno.h>
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <inttypes.h>
#include <string>
#include <algorithm>


inline static
int is_empty_str(const char *str){
	const char *p = str;
	while(*p && isspace(*p)){
		p++;
	}
	return *p == '\0';
}

/* 返回左边不包含空白字符的字符串的指针 */
inline static
char *ltrim(const char *str){
	const char *p = str;
	while(*p && isspace(*p)){
		p++;
	}
	return (char *)p;
}

/* 返回指向字符串结尾的指针, 会修改字符串内容 */
inline static
char *rtrim(char *str){
	char *p;
	p = str + strlen(str) - 1;
	while(p >= str && isspace(*p)){
		p--;
	}
	*(++p) = '\0';
	return p;
}

/* 返回左边不包含空白字符的字符串的指针 */
inline static
char *trim(char *str){
	char *p;
	p = ltrim(str);
	rtrim(p);
	return p;
}

inline static
void strtolower(std::string *str){
	std::transform(str->begin(), str->end(), str->begin(), ::tolower);
}

inline static
void strtoupper(std::string *str){
	std::transform(str->begin(), str->end(), str->begin(), ::toupper);
}

inline static
std::string real_dirname(const char *filepath){
	std::string dir;
	if(filepath[0] != '/'){
		char buf[1024];
		char *p = getcwd(buf, sizeof(buf));
		if(p != NULL){
			dir.append(p);
		}
		dir.append("/");
	}

	const char *p = strrchr(filepath, '/');
	if(p != NULL){
		dir.append(filepath, p - filepath);
	}
	return dir;
}

inline static
std::string str_escape(const char *s, int size){
	static const char *hex = "0123456789abcdef";
	std::string ret;
	for(int i=0; i<size; i++){
		char c = s[i];
		switch(c){
			case '\r':
				ret.append("\\r");
				break;
			case '\n':
				ret.append("\\n");
				break;
			case '\t':
				ret.append("\\t");
				break;
			case '\\':
				ret.append("\\\\");
				break;
			case ' ':
				ret.push_back(c);
				break;
			default:
				if(c >= '!' && c <= '~'){
					ret.push_back(c);
				}else{
					ret.append("\\x");
					unsigned char d = c;
					ret.push_back(hex[d >> 4]);
					ret.push_back(hex[d & 0x0f]);
				}
				break;
		}
	}
	return ret;
}

inline static
std::string str_escape(const std::string &s){
	return str_escape(s.data(), (int)s.size());
}

inline static
int hex_int(char c){
	if(c >= '0' && c <= '9'){
		return c - '0';
	}else{
		return c - 'a' + 10;
	}
}

inline static
std::string str_unescape(const char *s, int size){
	std::string ret;
	for(int i=0; i<size; i++){
		char c = s[i];
		if(c != '\\'){
			ret.push_back(c);
		}else{
			if(i >= size - 1){
				continue;
			}
			char c2 = s[++i];
			switch(c2){
				case 'a':
					ret.push_back('\a');
					break;
				case 'b':
					ret.push_back('\b');
					break;
				case 'f':
					ret.push_back('\f');
					break;
				case 'v':
					ret.push_back('\v');
					break;
				case 'r':
					ret.push_back('\r');
					break;
				case 'n':
					ret.push_back('\n');
					break;
				case 't':
					ret.push_back('\t');
					break;
				case '\\':
					ret.push_back('\\');
					break;
				case 'x':
					if(i < size - 2){
						char c3 = s[++i];
						char c4 = s[++i];
						ret.push_back((char)((hex_int(c3) << 4) + hex_int(c4)));
					}
					break;
				default:
					ret.push_back(c2);
					break;
			}
		}
	}
	return ret;
}

inline static
std::string str_unescape(const std::string &s){
	return str_unescape(s.data(), (int)s.size());
}

inline static
std::string hexmem(const void *p, int size){
	return str_escape((char *)p, size);
	/*
	std::string ret;
	char buf[4];
	for(int i=0; i<size; i++){
		char c = ((char *)p)[i];
		if(isalnum(c) || isprint(c)){
			ret.append(1, c);
		}else{
			switch(c){
				case '\r':
					ret.append("\\r", 2);
					break;
				case '\n':
					ret.append("\\n", 2);
					break;
				default:
					sprintf(buf, "\\%02x", (unsigned char)c);
					ret.append(buf, 3);
			}
		}
	}
	return ret;
	*/
}

// TODO: mem_printf("%5c%d%s", p, size);
static inline
void dump(const void *p, int size, const char *msg = NULL){
	if(msg == NULL){
		printf("dump <");
	}else{
		printf("%s <", msg);
	}
	std::string s = hexmem(p, size);
	printf("%s>\n", s.c_str());
}


static inline
std::string str(const char *s){
	return std::string(s);
}

static inline
std::string str(int v){
	char buf[21] = {0};
	snprintf(buf, sizeof(buf), "%d", v);
	return std::string(buf);
}

static inline
std::string str(int64_t v){
	char buf[21] = {0};
	snprintf(buf, sizeof(buf), "%" PRId64 "", v);
	return std::string(buf);
}

static inline
std::string str(uint64_t v){
	char buf[21] = {0};
	snprintf(buf, sizeof(buf), "%" PRIu64 "", v);
	return std::string(buf);
}

static inline
std::string str(double v){
	char buf[21] = {0};
	if(v - floor(v) == 0){
		snprintf(buf, sizeof(buf), "%.0f", v);
	}else{
		snprintf(buf, sizeof(buf), "%f", v);
	}
	return std::string(buf);
}

static inline
std::string str(float v){
	return str((double)v);
}

// all str_to_xx methods set errno on error

static inline
int str_to_int(const std::string &str){
	const char *start = str.c_str();
	char *end;
	int ret = (int)strtol(start, &end, 10);
	// the WHOLE string must be string represented integer
	if(*end == '\0' && size_t(end - start) == str.size()){
		errno = 0;
	}else{
		// strtoxx do not set errno all the time!
		if(errno == 0){
			errno = EINVAL;
		}
	}
	return ret;
}

static inline
int str_to_int(const char *p, int size){
	return str_to_int(std::string(p, size));
}

static inline
int64_t str_to_int64(const std::string &str){
	const char *start = str.c_str();
	char *end;
	int64_t ret = (int64_t)strtoll(start, &end, 10);
	// the WHOLE string must be string represented integer
	if(*end == '\0' && size_t(end - start) == str.size()){
		errno = 0;
	}else{
		// strtoxx do not set errno all the time!
		if(errno == 0){
			errno = EINVAL;
		}
	}
	return ret;
}

static inline
int64_t str_to_int64(const char *p, int size){
	return str_to_int64(std::string(p, size));
}

static inline
uint64_t str_to_uint64(const std::string &str){
	const char *start = str.c_str();
	char *end;
	uint64_t ret = (uint64_t)strtoull(start, &end, 10);
	// the WHOLE string must be string represented integer
	if(*end == '\0' && size_t(end - start) == str.size()){
		errno = 0;
	}else{
		// strtoxx do not set errno all the time!
		if(errno == 0){
			errno = EINVAL;
		}
	}
	return ret;
}

static inline
uint64_t str_to_uint64(const char *p, int size){
	return str_to_uint64(std::string(p, size));
}

static inline
double str_to_double(const char *p, int size){
	return atof(std::string(p, size).c_str());
}

static inline
std::string substr(const std::string &str, int start, int size){
	if(start < 0){
		start = (int)str.size() + start;
	}
	if(size < 0){
		// 忽略掉 abs(size) 个字节
		size = ((int)str.size() + size) - start;
	}
	if(start < 0 || size_t(start) >= str.size() || size < 0){
		return "";
	}
	return str.substr(start, size);
}

static inline
std::string str_slice(const std::string &str, int start, int end){
	if(start < 0){
		start = (int)str.size() + start;
	}
	int size;
	if(end < 0){
		size = ((int)str.size() + end + 1) - start;
	}else{
		size = end - start + 1;
	}
	if(start < 0 || size_t(start) >= str.size() || size < 0){
		return "";
	}
	return str.substr(start, size);
}

static inline
int bitcount(const char *p, int size){
	int n = 0;
	for(int i=0; i<size; i++){
		unsigned char c = (unsigned char)p[i];
		while(c){
			n += c & 1;
			c = c >> 1;
		}
	}
	return n;
}

// is big endia. TODO: auto detect
#if 0
	#define big_endian(v) (v)
#else
	static inline
	uint16_t big_endian(uint16_t v){
		return (v>>8) | (v<<8);
	}

	static inline
	uint32_t big_endian(uint32_t v){
		return (v >> 24) | ((v >> 8) & 0xff00) | ((v << 8) & 0xff0000) | (v << 24);
	}

	static inline
	uint64_t big_endian(uint64_t v){
		uint32_t h = v >> 32;
		uint32_t l = v & 0xffffffffull;
		return big_endian(h) | ((uint64_t)big_endian(l) << 32);
	}
#endif


#endif
