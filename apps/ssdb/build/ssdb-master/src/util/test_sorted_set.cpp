/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include <stdio.h>
#include <stdlib.h>
#include <time.h>
#include <vector>
#include "log.h"
#include "sorted_set.h"
#include "bytes.h"

int main(int argc, char **argv){	
	SortedSet zset;

	std::vector<std::string> keys;
	for(int i='a'; i<='z'; i++){
		char buf[10];
		snprintf(buf, sizeof(buf), "%c", i);
		keys.push_back(buf);
	}
	
	log_debug("");
	srand(time(NULL));
	for(int i=0; i<1000 * 1000; i++){
		std::string &key = keys[rand() % keys.size()];
		zset.add(key, rand()%30 - 15);
	}
	log_debug("");
	
	std::string key;
	int64_t score;
	int n = 0;
	while(zset.front(&key, &score)){
		printf("%s : %4lld\n", key.c_str(), score);
		zset.pop_front();
		n ++;
	}
	log_debug("%d", n);
	
	{
		Buffer bs(8192);
		bs.append_record("a");
		bs.append_record("bs");
		dump(bs.data(), bs.size());
	
		Bytes s;
		bs.read_record(&s);
		dump(s.data(), s.size());
		bs.read_record(&s);
		dump(s.data(), s.size());
	}
	
	return 0;
}
