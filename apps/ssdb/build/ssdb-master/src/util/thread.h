/*
Copyright (c) 2012-2014 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#ifndef UTIL_THREAD_H_
#define UTIL_THREAD_H_

#include <unistd.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <errno.h>
#include <pthread.h>
#include <queue>
#include <vector>

class Mutex{
	private:
		pthread_mutex_t mutex;
	public:
		Mutex(){
			pthread_mutex_init(&mutex, NULL);
		}
		~Mutex(){
			pthread_mutex_destroy(&mutex);
		}
		void lock(){
			pthread_mutex_lock(&mutex);
		}
		void unlock(){
			pthread_mutex_unlock(&mutex);
		}
};

class Locking{
	private:
		Mutex *mutex;
		// No copying allowed
		Locking(const Locking&);
		void operator=(const Locking&);
	public:
		Locking(Mutex *mutex){
			this->mutex = mutex;
			this->mutex->lock();
		}
		~Locking(){
			this->mutex->unlock();
		}

};

/*
class Semaphore {
	private:
		pthread_cond_t cond;
		pthread_mutex_t mutex;
	public:
		Semaphore(Mutex* mu){
			pthread_cond_init(&cond, NULL);
			pthread_mutex_init(&mutex, NULL);
		}
		~CondVar(){
			pthread_cond_destroy(&cond);
			pthread_mutex_destroy(&mutex);
		}
		void wait();
		void signal();
};
*/


// Thread safe queue
template <class T>
class Queue{
	private:
		pthread_cond_t cond;
		pthread_mutex_t mutex;
		std::queue<T> items;
	public:
		Queue();
		~Queue();

		bool empty();
		int size();
		int push(const T item);
		// TODO: with timeout
		int pop(T *data);
};


// Selectable queue, multi writers, single reader
template <class T>
class SelectableQueue{
	private:
		int fds[2];
		pthread_mutex_t mutex;
		std::queue<T> items;
	public:
		SelectableQueue();
		~SelectableQueue();
		int fd(){
			return fds[0];
		}
		int size();
		// multi writer
		int push(const T item);
		// single reader
		int pop(T *data);
};

template<class W, class JOB>
class WorkerPool{
	public:
		class Worker{
			public:
				Worker(){};
				Worker(const std::string &name);
				virtual ~Worker(){}
				int id;
				virtual void init(){}
				virtual void destroy(){}
				virtual int proc(JOB job) = 0;
			private:
			protected:
				std::string name;
		};
	private:
		std::string name;
		Queue<JOB> jobs;
		SelectableQueue<JOB> results;

		int num_workers;
		std::vector<pthread_t> tids;
		bool started;

		struct run_arg{
			int id;
			WorkerPool *tp;
		};
		static void* _run_worker(void *arg);
	public:
		WorkerPool(const char *name="");
		~WorkerPool();

		int fd(){
			return results.fd();
		}
		
		int start(int num_workers);
		int stop();
		
		int push(JOB job);
		int pop(JOB *job);
};





template <class T>
Queue<T>::Queue(){
	pthread_cond_init(&cond, NULL);
	pthread_mutex_init(&mutex, NULL);
}

template <class T>
Queue<T>::~Queue(){
	pthread_cond_destroy(&cond);
	pthread_mutex_destroy(&mutex);
}

template <class T>
bool Queue<T>::empty(){
	bool ret = false;
	if(pthread_mutex_lock(&mutex) != 0){
		return -1;
	}
	ret = items.empty();
	pthread_mutex_unlock(&mutex);
	return ret;
}

template <class T>
int Queue<T>::size(){
	int ret = -1;
	if(pthread_mutex_lock(&mutex) != 0){
		return -1;
	}
	ret = items.size();
	pthread_mutex_unlock(&mutex);
	return ret;
}

template <class T>
int Queue<T>::push(const T item){
	if(pthread_mutex_lock(&mutex) != 0){
		return -1;
	}
	{
		items.push(item);
	}
	pthread_mutex_unlock(&mutex);
	pthread_cond_signal(&cond);
	return 1;
}

template <class T>
int Queue<T>::pop(T *data){
	if(pthread_mutex_lock(&mutex) != 0){
		return -1;
	}
	{
		// 必须放在循环中, 因为 pthread_cond_wait 可能抢不到锁而被其它处理了
		while(items.empty()){
			//fprintf(stderr, "%d wait\n", pthread_self());
			if(pthread_cond_wait(&cond, &mutex) != 0){
				//fprintf(stderr, "%s %d -1!\n", __FILE__, __LINE__);
				return -1;
			}
			//fprintf(stderr, "%d wait 2\n", pthread_self());
		}
		*data = items.front();
		//fprintf(stderr, "%d job: %d\n", pthread_self(), (int)*data);
		items.pop();
	}
	if(pthread_mutex_unlock(&mutex) != 0){
		//fprintf(stderr, "error!\n");
		return -1;
	}
		//fprintf(stderr, "%d wait end 2, job: %d\n", pthread_self(), (int)*data);
	return 1;
}


template <class T>
SelectableQueue<T>::SelectableQueue(){
	if(pipe(fds) == -1){
		fprintf(stderr, "create pipe error\n");
		exit(0);
	}
	pthread_mutex_init(&mutex, NULL);
}

template <class T>
SelectableQueue<T>::~SelectableQueue(){
	pthread_mutex_destroy(&mutex);
	close(fds[0]);
	close(fds[1]);
}

template <class T>
int SelectableQueue<T>::push(const T item){
	if(pthread_mutex_lock(&mutex) != 0){
		return -1;
	}
	{
		items.push(item);
	}
	if(::write(fds[1], "1", 1) == -1){
		fprintf(stderr, "write fds error\n");
		exit(0);
	}
	pthread_mutex_unlock(&mutex);
	return 1;
}

template <class T>
int SelectableQueue<T>::size(){
	int ret = 0;
	pthread_mutex_lock(&mutex);
	ret = items.size();
	pthread_mutex_unlock(&mutex);
	return ret;
}

template <class T>
int SelectableQueue<T>::pop(T *data){
	int n, ret = 1;
	char buf[1];

	while(1){
		n = ::read(fds[0], buf, 1);
		if(n < 0){
			if(errno == EINTR){
				continue;
			}else{
				return -1;
			}
		}else if(n == 0){
			ret = -1;
		}else{
			if(pthread_mutex_lock(&mutex) != 0){
				return -1;
			}
			{
				if(items.empty()){
					fprintf(stderr, "%s %d error!\n", __FILE__, __LINE__);
					pthread_mutex_unlock(&mutex);
					return -1;
				}
				*data = items.front();
				items.pop();
			}
			pthread_mutex_unlock(&mutex);
		}
		break;
	}
	return ret;
}



template<class W, class JOB>
WorkerPool<W, JOB>::WorkerPool(const char *name){
	this->name = name;
	this->started = false;
}

template<class W, class JOB>
WorkerPool<W, JOB>::~WorkerPool(){
	if(started){
		stop();
	}
}

template<class W, class JOB>
int WorkerPool<W, JOB>::push(JOB job){
	return this->jobs.push(job);
}

template<class W, class JOB>
int WorkerPool<W, JOB>::pop(JOB *job){
	return this->results.pop(job);
}

template<class W, class JOB>
void* WorkerPool<W, JOB>::_run_worker(void *arg){
	struct run_arg *p = (struct run_arg*)arg;
	int id = p->id;
	WorkerPool *tp = p->tp;
	delete p;

	W w(tp->name);
	Worker *worker = (Worker *)&w;
	worker->id = id;
	worker->init();
	while(1){
		JOB job;
		if(tp->jobs.pop(&job) == -1){
			fprintf(stderr, "jobs.pop error\n");
			::exit(0);
			break;
		}
		worker->proc(job);
		if(tp->results.push(job) == -1){
			fprintf(stderr, "results.push error\n");
			::exit(0);
			break;
		}
	}
	worker->destroy();
	return (void *)NULL;
}

template<class W, class JOB>
int WorkerPool<W, JOB>::start(int num_workers){
	this->num_workers = num_workers;
	if(started){
		return 0;
	}
	int err;
	pthread_t tid;
	for(int i=0; i<num_workers; i++){
		struct run_arg *arg = new run_arg();
		arg->id = i;
		arg->tp = this;

		err = pthread_create(&tid, NULL, &WorkerPool::_run_worker, arg);
		if(err != 0){
			fprintf(stderr, "can't create thread: %s\n", strerror(err));
		}else{
			tids.push_back(tid);
		}
	}
	started = true;
	return 0;
}

template<class W, class JOB>
int WorkerPool<W, JOB>::stop(){
	// TODO: notify works quit and wait
	for(int i=0; i<tids.size(); i++){
#ifdef OS_ANDROID
#else
		pthread_cancel(tids[i]);
#endif
	}
	started = false;
	return 0;
}



#if 0
class MyWorker : public WorkerPool<MyWorker, int>::Worker{
	public:
		int proc(int *job){
			*job = (id + 1) * 100000 + *job;
			return 0;
		}
};

int main(){
	int num_jobs = 1000;
	WorkerPool<MyWorker, int> tp(10);
	tp.start();
	for(int i=0; i<num_jobs; i++){
		//usleep(200 * 1000);
		//printf("job: %d\n", i);
		tp.push_job(i);
	}
	printf("add end\n");
	for(int i=0; i<num_jobs; i++){
		int job;
		tp.pop_result(&job);
		printf("result: %d, %d\n", i, job);
	}
	printf("end\n");
	//tp.stop();
	return 0;
}
#endif

#endif


