#ifndef SSDB_DEPS_H
#ifndef SSDB_VERSION
#define SSDB_VERSION "1.9.4"
#endif
#endif
