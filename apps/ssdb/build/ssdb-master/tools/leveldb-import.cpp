/*
Copyright (c) 2012-2015 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "include.h"

#include <string>
#include <vector>

#include "leveldb/db.h"
#include "leveldb/options.h"
#include "leveldb/slice.h"
#include "leveldb/iterator.h"

#include "net/link.h"
#include "util/log.h"
#include "util/file.h"
#include "util/strings.h"

void welcome(){
	printf("leveldb-import - Import existing leveldb into ssdb\n");
	printf("Copyright (c) 2013-2015 ssdb.io\n");
	printf("\n");
}

void usage(int argc, char **argv){
	printf("Usage:\n");
	printf("    %s ip port input_folder\n", argv[0]);
	printf("\n");
	printf("Options:\n");
	printf("    ip - ssdb server ip address\n");
	printf("    port - ssdb server port number\n");
	printf("    input_folder - local leveldb folder\n");
}

int main(int argc, char **argv){
	welcome();

	set_log_level(Logger::LEVEL_MIN);

	if(argc <= 3){
		usage(argc, argv);
		return 0;
	}
	char *ip = argv[1];
	int port = atoi(argv[2]);
	char *input_folder = argv[3];

	if(!file_exists(input_folder)){
		printf("input_folder[%s] not exists!\n", input_folder);
		return 0;
	}

	std::string data_dir = "";
	data_dir.append(input_folder);

	// connect to server
	Link *link = Link::connect(ip, port);
	if(link == NULL){
		printf("error connecting to server!\n");
		return 0;
	}

	leveldb::DB* db;
	leveldb::Options options;
	leveldb::Status status;
	//options.create_if_missing = true;
	status = leveldb::DB::Open(options, data_dir.c_str(), &db);
	if(!status.ok()){
		printf("open leveldb: %s error!\n", input_folder);
		return 0;
	}

	printf("importing data...\n");
	leveldb::Iterator *it;
	it = db->NewIterator(leveldb::ReadOptions());
	int save_count = 0;
	for(it->SeekToFirst(); it->Valid(); it->Next()){
		std::string key = it->key().ToString();
		std::string val = it->value().ToString();
		
		const std::vector<Bytes> *req = link->request("set", key, val);
		if(req == NULL){
			printf("error\n");
			exit(0);
		}else{
			if(req->at(0) != "ok"){
				printf("server response error: %s\n", req->at(0).String().c_str());
				exit(0);
			}
		}
		save_count ++;
	}
	printf("importing done.\n");
	printf("\n");
	printf("total %d item(s) imported.\n", save_count);

	delete link;
	delete db;
	return 0;
}
