<?php
/**
 * Copyright (c) 2014, ideawu
 * All rights reserved.
 * @author: ideawu
 * @link: http://www.ideawu.com/
 *
 * PHP script for importing Redis data into SSDB.
 */

function usage(){
	global $argv;
	echo "Usage:\n";
	echo "    php {$argv[0]} redis_host redis_port redis_db ssdb_host ssdb_port\n";
	echo "\n";
}

if(count($argv) != 6){
	usage();
	die();
}

echo "This script will only copy entries with types in (STRING, HASH, ZSET, LIST)\n";
echo "ZSET scores are converted to intergers from floating numbers.\n";
echo "Do you want to continue? [y/n](y) ";
$line = fgets(STDIN);
$line = trim($line);
if($line == 'n' || $line == 'N'){
	echo "Operation cancelled\n";
	die();
}

$r_host = $argv[1];
$r_port = $argv[2];
$r_db   = $argv[3];
$s_host = $argv[4];
$s_port = $argv[5];

$redis = new Redis();
$ret = $redis->connect($r_host, $r_port);
if($ret === false){
	echo "ERROR: could not connect to Redis server!\n";
	die();
}
$redis->select($r_db);

$ssdb = new Redis();
$ret = $ssdb->connect($s_host, $s_port);
if($ret === false){
	echo "ERROR: could not connect to SSDB server!\n";
	die();
}


echo "\nCopying data from Redis($r_host:{$r_port}[$r_db]) to SSDB($s_host, $s_port)...\n";
if(scan_command_available()){
	echo "Using SCAN.\n";
}else{
	echo "Using KEYS.\n";
}


$count = 0;
$total = 0;
$entries = 0;

echo "==============\n";
// check if phpredis and redis-server supports SCAN
if(scan_command_available()){
	$total = $redis->dbsize();
	$it = NULL;
	$redis->setOption(Redis::OPT_SCAN, Redis::SCAN_RETRY);
	while($keys = $redis->scan($it)){
		copy_keys($keys);
	}
}else{
	$keys = $redis->keys('*');
	$total = count($keys);
	copy_keys($keys);
}
echo date('Y-m-d H:i:s') . " $total keys, $entries entries copied.\n";
echo "==============\n";
echo "Done.\n";
echo "\n";


function copy_keys($keys){
	global $redis, $ssdb, $count, $total, $entries;

	foreach($keys as $key){
		copy_key($key);
		if(++$count % 100 == 1){
			echo date('Y-m-d H:i:s') . " $count/$total entries: $entries\n";
		}
	}
}

function copy_key($key){
	global $redis, $ssdb, $count, $total, $entries;

	$type = $redis->type($key);
	switch($type){
		case Redis::REDIS_STRING:
			$val = $redis->get($key);
			$ssdb->set($key, $val);
			$entries ++;
			break;
		case Redis::REDIS_LIST:
			$list = $redis->lRange($key, 0, -1);
			foreach($list as $val){
				$ssdb->rPush($key, $val);
				$entries ++;
			}
			break;
		case Redis::REDIS_HASH:
			$hash = $redis->hGetAll($key);
			foreach($hash as $k=>$v){
				$ssdb->hset($key, $k, $v);
				$entries ++;
			}
			break;
		case Redis::REDIS_ZSET:
			$zset = $redis->zRange($key, 0, -1, true);
			foreach($zset as $val=>$score){
				$ssdb->zAdd($key, $score, $val);
				$entries ++;
			}
			break;
	}
}

function scan_command_available(){
	global $redis;

	if(method_exists($redis, 'scan')){
		$info = $redis->info();
		$redis_version = $info['redis_version'];
		$ps = explode('.', $redis_version);
		if(count($ps) > 2){
			$n = $ps[0] * 10 + $ps[1];
			if($n >= 28){
				return true;
			}
		}
	}
	return false;
}

