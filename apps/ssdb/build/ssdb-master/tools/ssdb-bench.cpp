/*
Copyright (c) 2012-2015 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <errno.h>
#include <string>
#include <vector>
#include <map>
#include "net/link.h"
#include "net/fde.h"
#include "util/log.h"
#include "version.h"

#include "../src/include.h"

struct Data
{
	std::string key;
	std::string val;
	std::string num;
};

std::map<std::string, Data *> *ds;
Fdevents *fdes;
std::vector<Link *> *free_links;


void welcome(){
	printf("ssdb-bench - SSDB benchmark tool, %s\n", SSDB_VERSION);
	printf("Copyright (c) 2013-2015 ssdb.io\n");
	printf("\n");
}

void usage(int argc, char **argv){
	printf("Usage:\n");
	printf("    %s [ip] [port] [requests] [clients]\n", argv[0]);
	printf("\n");
	printf("Options:\n");
	printf("    ip          server ip (default 127.0.0.1)\n");
	printf("    port        server port (default 8888)\n");
	printf("    requests    Total number of requests (default 10000)\n");
	printf("    clients     Number of parallel connections (default 50)\n");
	printf("\n");
}

void init_data(int num){
	srand(time(NULL));
	ds = new std::map<std::string, Data *>();
	while(ds->size() < num){
		Data *d = new Data();
		char buf[1024];

		int n = rand();
		snprintf(buf, sizeof(buf), "%d", n);
		d->num = buf;
		snprintf(buf, sizeof(buf), "k%010d", n);
		d->key = buf;
		snprintf(buf, sizeof(buf), "v%0100d", n);
		d->val = buf;
		ds->insert(make_pair(d->key, d));
	}
}

void init_links(int num, const char *ip, int port){
	fdes = new Fdevents();
	free_links = new std::vector<Link *>();

	for(int i=0; i<num; i++){
		Link *link = Link::connect(ip, port);
		if(!link){
			fprintf(stderr, "connect error! %s\n", strerror(errno));
			exit(0);
		}
		fdes->set(link->fd(), FDEVENT_IN, 0, link);
		free_links->push_back(link);
	}
}

void send_req(Link *link, const std::string &cmd, const Data *d){
	if(cmd == "set"){
		link->send(cmd, d->key, d->val);
	}else if(cmd == "get"){
		link->send(cmd, d->key);
	}else if(cmd == "del"){
		link->send(cmd, d->key);
	}else if(cmd == "hset"){
		link->send(cmd, "TEST", d->key, d->val);
	}else if(cmd == "hget"){
		link->send(cmd, "TEST", d->key);
	}else if(cmd == "hdel"){
		link->send(cmd, "TEST", d->key);
	}else if(cmd == "zset"){
		link->send(cmd, "TEST", d->key, d->num);
	}else if(cmd == "zget"){
		link->send(cmd, "TEST", d->key);
	}else if(cmd == "zdel"){
		link->send(cmd, "TEST", d->key);
	}else if(cmd == "qpush"){
		link->send(cmd, "TEST", d->key);
	}else if(cmd == "qpop"){
		link->send(cmd, "TEST");
	}else{
		log_error("bad command!");
		exit(0);
	}
	link->flush();
}

void bench(std::string cmd){
	int total = (int)ds->size();
	int finished = 0;
	int num_sent = 0;
	
	printf("========== %s ==========\n", cmd.c_str());

	std::map<std::string, Data *>::iterator it;
	it = ds->begin();
	
	double stime = millitime();
	while(1){
		while(!free_links->empty()){
			if(num_sent == total){
				break;
			}
			num_sent ++;

			Link *link = free_links->back();
			free_links->pop_back();
			
			send_req(link, cmd, it->second);
			it ++;
		}

		const Fdevents::events_t *events;
		events = fdes->wait(50);
		if(events == NULL){
			log_error("events.wait error: %s", strerror(errno));
			break;
		}

		for(int i=0; i<(int)events->size(); i++){
			const Fdevent *fde = events->at(i);
			Link *link = (Link *)fde->data.ptr;

			int len = link->read();
			if(len <= 0){
				log_error("fd: %d, read: %d, delete link", link->fd(), len);
				exit(0);
			}

			const std::vector<Bytes> *resp = link->recv();
			if(resp == NULL){
				log_error("error");
				break;
			}else if(resp->empty()){
				continue;
			}else{
				if(resp->at(0) != "ok"){
					log_error("bad response: %s", resp->at(0).String().c_str());
					exit(0);
				}
				free_links->push_back(link);
				finished ++;
				if(finished == total){
					double etime = millitime();
					double ts = (stime == etime)? 1 : (etime - stime);
					double speed = total / ts;
					printf("qps: %d, time: %.3f s\n", (int)speed, ts);
					return;
				}
			}
		}
	}
}

int main(int argc, char **argv){
	const char *ip = "127.0.0.1";
	int port = 8888;
	int requests = 10000;
	int clients = 50;

	welcome();
	usage(argc, argv);
	for(int i=1; i<argc; i++){
		if(strcmp("-v", argv[i]) == 0){
			exit(0);
		}
	}
	if(argc > 1){
		ip = argv[1];
	}
	if(argc > 2){
		port = atoi(argv[2]);
	}
	if(argc > 3){
		requests = atoi(argv[3]);
	}
	if(argc > 4){
		clients = atoi(argv[4]);
	}

	//printf("preparing data...\n");
	init_data(requests);
	//printf("preparing links...\n");
	init_links(clients, ip, port);

	bench("set");
	bench("get");
	bench("del");

	bench("hset");
	bench("hget");
	bench("hdel");

	bench("zset");
	bench("zget");
	bench("zdel");

	bench("qpush");
	bench("qpop");
	
	printf("\n");

	return 0;
}

