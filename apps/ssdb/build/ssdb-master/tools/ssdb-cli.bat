@echo off
%~dp0..\deps\cpy\cpy.bat %~dp0\ssdb-cli.cpy %1 %2 %3 %4 %5 %6 %7 %8 %9
