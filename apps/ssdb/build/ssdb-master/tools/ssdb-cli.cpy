import thread, re, time, socket;
import getopt, shlex;
import datetime;
import ssdb_cli.*;
sys.path.append('./api/python');
sys.path.append('../api/python');
sys.path.append('/usr/local/ssdb/api/python');
import SSDB.*;

try{
	import readline;
}catch(Exception e){
}

escape_data = false;

function welcome(){
	sys.stderr.write('ssdb (cli) - ssdb command line tool.\n');
	sys.stderr.write('Copyright (c) 2012-2016 ssdb.io\n');
	sys.stderr.write('\n');
	sys.stderr.write("'h' or 'help' for help, 'q' to quit.\n");
	sys.stderr.write('\n');
}

function show_command_help(){
	print '';
	print '# display ssdb-server status';
	print '	info';
	print '# escape/do not escape response data';
	print '	: escape yes|no';
	print '# export/import';
	print '	export [-i] out_file';
	print '		-i	interactive mode';
	print '	import in_file';
	print '';
	print 'see http://ssdb.io/docs/php/ for commands details';
	print '';
	print 'press \'q\' and Enter to quit.';
	print '';
}

function usage(){
	print '';
	print 'Usage:';
	print '	ssdb-cli [-h] [HOST] [-p] [PORT]';
	print '';
	print 'Options:';
	print '	-h 127.0.0.1';
	print '		ssdb server hostname/ip address';
	print '	-p 8888';
	print '		ssdb server port';
	print '	-v --help';
	print '		show this message';
	print '	-n [info, dbsize, replication, write_read]';
	print '		choose nagios probe';
	print '	-w INT';
	print '		set nagios WARN level';
	print '	-c INT';
	print '		set nagios CRITICAL level';
	print '';
	print 'Examples:';
	print '	ssdb-cli';
	print '	ssdb-cli 8888';
	print '	ssdb-cli 127.0.0.1 8888';
	print '	ssdb-cli -h 127.0.0.1 -p 8888';
	print '	ssdb-cli -h 127.0.0.1 -p 8888 -n dbsize -w 500000 -c 600000';
	print '	ssdb-cli -h 127.0.0.1 -p 8888 -n replication';
	print '	ssdb-cli -h 127.0.0.1 -p 8888 -n write_read';
	print '	ssdb-cli -n info';
}

function repr_data(s){
	gs = globals();
	if(gs['escape_data'] == false){
		return s;
	}
	ret = str(s).encode('string-escape');
	return ret;
}

function timespan(stime){
	etime = datetime.datetime.now();
	ts = etime - stime;
	time_consume = ts.seconds + ts.microseconds/1000000.;
	return time_consume;
}

host = '';
port = '';
opt = '';
args = [];
run_nagios = false;

foreach(sys.argv[1 ..] as arg){
	if(opt == '' && arg.startswith('-')){
		opt = arg;
		if(arg == '--help' || arg == '--h' || arg == '-v'){
			usage();
			exit(0);
		}
	}else{
		switch(opt){
			case '-h':
				host = arg;
				opt = '';
				break;
			case '-p':
				port = arg;
				opt = '';
				break;
			// nagios
			case '-n':
			case '-w':
			case '-c':
				run_nagios = true;
				opt = '';
				break;
			default:
				args.append(arg);
				break;
		}
	}
}

if(host == ''){
	host = '127.0.0.1';
	foreach(args as arg){
		if(!re.match('^[0-9]+$', arg)){
			host = arg;
			break;
		}
	}
}
if(port == ''){
	port = '8888';
	foreach(args as arg){
		if(re.match('^[0-9]+$', arg)){
			port = arg;
			break;
		}
	}
}

try{
	port = int(port);
}catch(Exception e){
	sys.stderr.write(sprintf('Invalid argument port: ', port));
	usage();
	sys.exit(0);
}

try{
	link = new SSDB(host, port);
}catch(socket.error e){
	sys.stderr.write(sprintf('Failed to connect to: %s:%d\n', host, port));
	sys.stderr.write(sprintf('Connection error: %s\n', str(e)));
	sys.exit(0);
}

if(run_nagios){
	nagios.run(link, sys.argv[1 ..]);
	exit(0);
}

welcome();
if(sys.stdin.isatty()){
	util.show_version(link);
}


password = false;

function request_with_retry(cmd, args=null){
	gs = globals();
	link = gs['link'];
	password = gs['password'];
	
	if(!args){
		args = [];
	}
	
	retry = 0;
	max_retry = 5;
	while(true){
		resp = link.request(cmd, args);
		if(resp.code == 'disconnected'){
			link.close();
			sleep = retry;
			if(sleep > 3){
				sleep = 3;
			}
			time.sleep(sleep);
			retry ++;
			if(retry > max_retry){
				sys.stderr.write('cannot connect to server, give up...\n');
				break;
			}
			sys.stderr.write(sprintf('[%d/%d] reconnecting to server... ', retry, max_retry));
			try{
				link = new SSDB(host, port);
				gs['link'] = link;
				sys.stderr.write('done.\n');
			}catch(socket.error e){
				sys.stderr.write(sprintf('Connect error: %s\n', str(e)));
				continue;
			}
			if(password){
				ret = link.request('auth', [password]);
			}
		}else{
			return resp;
		}
	}
	return null;
}

while(true){
	line = '';
	c = sprintf('ssdb %s:%s> ', host, str(port));
	b = sys.stdout;
	sys.stdout = sys.stderr;
	try{
		line = raw_input(c);
	}catch(Exception e){
		break;
	}
	sys.stdout = b;
	
	if(line == ''){
		continue;
	}
	line = line.strip();
	if(line == 'q' || line == 'quit'){
		sys.stderr.write('bye.\n');
		break;
	}
	if(line == 'h' || line == 'help'){
		show_command_help();
		continue;
	}

	try{
		ps = shlex.split(line);
	}catch(Exception e){
		sys.stderr.write(sprintf('error: %s\n', str(e)));
		continue;
	}
	if(len(ps) == 0){
		continue;
	}

	for(i=0; i<len(ps); i++){
		ps[i] = ps[i].decode('string-escape');
	}
	
	cmd = ps[0].lower();
	if(cmd.startswith(':')){
		ps[0] = cmd[1 ..];
		cmd = ':';
		args = ps;
	}else{
		args = ps[1 .. ];
	}
	if(cmd == ':'){
		op = '';
		if(len(args) > 0){
			op = args[0];
		}
		if(op != 'escape'){
			sys.stderr.write("Bad setting!\n");
			continue;
		}
		yn = 'yes';
		if(len(args) > 1){
			yn = args[1];
		}
		gs = globals();
		if(yn == 'yes'){
			gs['escape_data'] = true;
			sys.stderr.write("  Escape response\n");
		}else if(yn == 'no' || yn == 'none'){
			gs['escape_data'] = false;
			sys.stderr.write("  No escape response\n");
		}else{
			sys.stderr.write("  Usage: escape yes|no\n");
		}
		continue;
	}
	if(cmd == 'v'){
		util.show_version(link);
		continue;
	}
	if(cmd == 'auth'){
		if(len(args) == 0){
			sys.stderr.write('Usage: auth password\n');
			continue;
		}
		password = args[0];
	}
	if(cmd == 'export'){
		exporter.run(link, args);
		continue;
	}
	if(cmd == 'import'){
		if(len(args) < 1){
			sys.stderr.write('Usage: import in_file\n');
			continue;
		}
		filename = args[0];
		importer.run(link, filename);
		continue;
	}
	
	try{
		if(cmd == 'flushdb'){
			resp = request_with_retry('ping');
			if(!resp){
				throw new Exception('error');
			}
			if(resp.code != 'ok'){
				throw new Exception(resp.message);
			}
			
			stime = datetime.datetime.now();
			if(len(args) == 0){
				flushdb.flushdb(link, '');
			}else{
				flushdb.flushdb(link, args[0]);
			}
			sys.stderr.write(sprintf('(%.3f sec)\n', timespan(stime)));
			continue;
		}
	}catch(Exception e){
		sys.stderr.write("error! - " + str(e) + "\n");
		continue;
	}

	stime = datetime.datetime.now();
	resp = request_with_retry(cmd, args);
	if(resp == null){
		sys.stderr.write("error!\n");
		continue;
	}

	time_consume = timespan(stime);

	if(!resp.ok()){
		if(resp.not_found()){
			sys.stderr.write('not_found\n');
		}else{
			s = resp.code;
			if(resp.message){
				s += ': ' + str(resp.message);
			}
			sys.stderr.write(str(s) + '\n');
		}
		sys.stderr.write(sprintf('(%.3f sec)\n', time_consume));
	}else{
		skip = false;
		switch(cmd){
			case 'ping':
			case 'qset':
			case 'compact':
			case 'auth':
			case 'set':
			case 'setx':
			case 'zset':
			case 'hset':
			case 'del':
			case 'zdel':
				skip = true;
				printf(str(resp.code) + '\n');
				break;
			case 'info':
				skip = true;
				is_val = false;
				for(i=1; i<len(resp.data); i++){
					s = resp.data[i];
					if(is_val){
						s = '	' + s.replace('\n', '\n	');
					}
					print s;
					is_val = !is_val;
				}
				sys.stderr.write(sprintf('%d result(s) (%.3f sec)\n', len(resp.data), time_consume));
				break;
		}
		if(skip){
			sys.stderr.write(sprintf('(%.3f sec)\n', time_consume));
			continue;
		}

		switch(resp.type){
			case 'none':
				printf(str(resp.data) + '\n');
				break;
			case 'val':
				if(resp.code == 'ok'){
					printf(str(resp.data) + '\n');
				}else{
					if(resp.data){
						print repr_data(resp.code), repr_data(resp.data);
					}else{
						print repr_data(resp.code);
					}
				}
				break;
			case 'list':
				sys.stderr.write(sprintf('  %15s\n', 'key'));
				sys.stderr.write('-' * 17 + '\n');
				foreach(resp.data as k){
					printf('  %15s\n', repr_data(k));
				}
				sys.stderr.write(sprintf('%d result(s) (%.3f sec)\n', len(resp.data), time_consume));
				break;
			case 'map':
				sys.stderr.write(sprintf('%-15s %s\n', 'key', 'value'));
				sys.stderr.write('-' * 25 + '\n');
				foreach(resp.data['index'] as k){
					v = resp.data['items'][k];
					printf('  %-15s: %s\n', repr_data(repr_data(k)), v);
				}
				sys.stderr.write(sprintf('%d result(s) (%.3f sec)\n', len(resp.data['index']), time_consume));
				break;
		}
		sys.stderr.write(sprintf('(%.3f sec)\n', time_consume));
	}
}

