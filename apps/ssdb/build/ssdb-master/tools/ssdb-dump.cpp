/*
Copyright (c) 2012-2015 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "include.h"
#include <sys/types.h>
#include <sys/stat.h>

#include <string>
#include <vector>

#include "leveldb/db.h"
#include "leveldb/options.h"
#include "leveldb/slice.h"
#include "leveldb/iterator.h"

#include "include.h"
#include "ssdb/const.h"
#include "net/link.h"
#include "util/log.h"
#include "util/file.h"
#include "util/strings.h"

struct Config {
	std::string ip;
	int port;
	bool hasauth;
	std::string auth;
	std::string output_folder;
};

template<class T>
static std::string serialize_req(T &req){
	std::string ret;
	char buf[50];
	for(int i=0; i<req.size(); i++){
		if(i >= 5 && i < req.size() - 1){
			sprintf(buf, "[%d more...]", (int)req.size() - i - 1);
			ret.append(buf);
			break;
		}
		if(((req[0] == "get" || req[0] == "set") && i == 1) || req[i].size() < 30){
			std::string h = hexmem(req[i].data(), req[i].size());
			ret.append(h);
		}else{
			sprintf(buf, "[%d bytes]", (int)req[i].size());
			ret.append(buf);
		}
		if(i < req.size() - 1){
			ret.append(" ");
		}
	}
	return ret;
}

void welcome(){
	printf("ssdb-dump - SSDB backup command\n");
	printf("Copyright (c) 2012-2015 ssdb.io\n");
	printf("\n");
}

void usage(int argc, char **argv){
	printf("Usage:\n"
		"\n"
		"    %s -o output_folder\n"
		"    %s ip port output_folder\n"
		"\n"
		"Options:\n"
		"    -h <ip/hostname>   Server IP address/hostname (default: 127.0.0.1).\n"
		"    -p <port>          Server port (default: 8888).\n"
		"    -a <password>      Password to use when connecting to the server.\n"
		"    -o <output_folder> local backup folder that will be created.\n"
		"\n",
		argv[0], argv[0]);
	exit(1);   
}

int parse_options(Config *config, int argc, char **argv){
	int i;
	for(i = 1; i < argc; i++) {
		bool lastarg = i==argc-1;
		if(!strcmp(argv[i],"-h") && !lastarg){
			config->ip = argv[++i];
		}else if(!strcmp(argv[i], "-h") && lastarg){
			usage(argc, argv);
		}else if(!strcmp(argv[i], "-p") && !lastarg){
			config->port = atoi(argv[++i]);
		}else if(!strcmp(argv[i], "-a") && !lastarg){
			config->hasauth = true;
			config->auth = argv[++i];
		}else if(!strcmp(argv[i], "-o") && !lastarg){
			config->output_folder = argv[++i];
		}else{
			if(argv[i][0] == '-'){
				fprintf(stderr,
					"Unrecognized option or bad number of args for: '%s'\n",
					argv[i]);
					exit(1);
			}else{
				/* Likely the command name, stop here. */
				break;
			}
		}
	}
	return i;
}

int main(int argc, char **argv){
	welcome();
	set_log_level(Logger::LEVEL_MIN);

	Config config;
	config.ip = "127.0.0.1";
	config.port = 8888;
	config.hasauth = false;
    
	int firstarg = parse_options(&config, argc, argv);
	if(firstarg == 1 && firstarg + 3 <= argc){
		// compatibale with old style arguments
		config.ip = argv[firstarg + 0];
		config.port = atoi(argv[firstarg + 1]);
		config.output_folder = argv[firstarg + 2];
	}

	if(config.output_folder.empty()){
		fprintf(stderr, "ERROR: -o <output_folder> is required!\n");
		usage(argc, argv);
		exit(1);
	}
    
	if(file_exists(config.output_folder.c_str())){
		fprintf(stderr, "ERROR: output_folder[%s] exists!\n", config.output_folder.c_str());
		exit(1);
	}
	if(mkdir(config.output_folder.c_str(), 0777) == -1){
		fprintf(stderr, "ERROR: error create backup directory!\n");
		exit(1);
	}

	std::string data_dir = "";
	data_dir.append(config.output_folder);
	data_dir.append("/data");
	
	{
		std::string meta_dir = "";
		meta_dir.append(config.output_folder);
		meta_dir.append("/meta");

		int ret;
		ret = mkdir(meta_dir.c_str(), 0755);
		if(ret == -1){
			fprintf(stderr, "ERROR: error creating meta dir\n");
			exit(1);
		}
	}

	// connect to server
	Link *link = Link::connect(config.ip.c_str(), config.port);
	if(link == NULL){
		fprintf(stderr, "ERROR: error connecting to server: %s:%d!\n", config.ip.c_str(), config.port);
		exit(1);
	}
	if(config.hasauth){
		const std::vector<Bytes> *resp = link->request("auth", config.auth.c_str());
		if(resp == NULL || resp->at(0) != "ok"){
			fprintf(stderr, "ERROR: auth error!\n");
			exit(1);
		}
	}
	link->send("dump", "A", "", "-1");
	link->flush();

	leveldb::DB* db;
	leveldb::Options options;
	leveldb::Status status;
	options.create_if_missing = true;
	options.write_buffer_size = 32 * 1024 * 1024;
	options.compression = leveldb::kSnappyCompression;

	status = leveldb::DB::Open(options, data_dir.c_str(), &db);
	if(!status.ok()){
		fprintf(stderr, "ERROR: open leveldb: %s error!\n", config.output_folder.c_str());
		exit(1);
	}

	int64_t dump_count = 0;
	while(1){
		const std::vector<Bytes> *req = link->recv();
		if(req == NULL){
			fprintf(stderr, "recv error\n");
			fprintf(stderr, "ERROR: failed to dump data!\n");
			exit(1);
		}else if(req->empty()){
			int len = link->read();
			if(len <= 0){
				fprintf(stderr, "read error: %s\n", strerror(errno));
				fprintf(stderr, "ERROR: failed to dump data!\n");
				exit(1);
			}
		}else{
			Bytes cmd = req->at(0);
			if(cmd == "begin"){
				printf("recv begin...\n");
			}else if(cmd == "end"){
				printf("received %" PRId64 " entry(s)\n", dump_count);
				printf("recv end\n\n");
				break;
			}else if(cmd == "set"){
				/*
				std::string s = serialize_req(*req);
				printf("%s\n", s.c_str());
				*/

				if(req->size() != 3){
					fprintf(stderr, "invalid set params!\n");
					fprintf(stderr, "ERROR: failed to dump data!\n");
					exit(1);
				}
				Bytes key = req->at(1);
				Bytes val = req->at(2);
				if(key.size() == 0 || key.data()[0] == DataType::SYNCLOG){
					continue;
				}
				
				leveldb::Slice k(key.data(), key.size());
				leveldb::Slice v(val.data(), val.size());
				status = db->Put(leveldb::WriteOptions(), k, v);
				//printf("set %s %s\n", str_escape(key.data(), key.size()).c_str(), str_escape(val.data(), val.size()).c_str());
				if(!status.ok()){
					fprintf(stderr, "put leveldb error!\n");
					fprintf(stderr, "ERROR: failed to dump data!\n");
					exit(1);
				}

				dump_count ++;
				if((int)log10(dump_count - 1) != (int)log10(dump_count) || (dump_count > 0 && dump_count % 100000 == 0)){
					printf("received %" PRId64 " entry(s)\n", dump_count);
				}
			}else{
				fprintf(stderr, "error: unknown command %s\n", std::string(cmd.data(), cmd.size()).c_str());
				fprintf(stderr, "ERROR: failed to dump data!\n");
				exit(1);
			}
		}
	}
	printf("total dumped %" PRId64 " entry(s)\n", dump_count);

	{
		std::string val;
		if(db->GetProperty("leveldb.stats", &val)){
			printf("%s\n", val.c_str());
		}
	}

	printf("compacting data...\n");
	db->CompactRange(NULL, NULL);
	
	{
		std::string val;
		if(db->GetProperty("leveldb.stats", &val)){
			printf("%s\n", val.c_str());
		}
	}

	printf("backup has been made to folder: %s\n", config.output_folder.c_str());
	
	delete link;
	delete db;
	return 0;
}
