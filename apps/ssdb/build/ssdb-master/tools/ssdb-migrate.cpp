#include <stdio.h>
#include <stdlib.h>
#include <string>
#include <vector>
#include "util/log.h"
#include "util/strings.h"
#include "SSDB_client.h"

#define BATCH_SIZE 100

ssdb::Client *src = NULL;
ssdb::Client *dst = NULL;

void welcome(){
	printf("ssdb-migrate - SSDB server migration tool\n");
	printf("Copyright (c) 2012-2015 ssdb.io\n");
	printf("\n");
}

void usage(int argc, char **argv){
	printf("Usage:\n"
		"    %s type src_ip src_port dst_ip dst_port limit\n"
		"\n"
		"Options:\n"
		"    type      Supported values: KV\n"
		"    src_ip    IP addr of the SSDB server to move data from, example: 127.0.0.1\n"
		"    src_port  Port number of source SSDB server\n"
		"    src_ip    IP addr of the SSDB server to move data to, example: 127.0.0.1\n"
		"    dst_port  Port number of destination SSDB server\n"
		"    limit     Approximated number of keys to be moved, example: 1000\n"
		"    -h        Show this message"
		"\n"
		"Example:\n"
		"    %s KV 127.0.0.1 8887 127.0.0.1 8889 13\n"
		"\n",
		argv[0], argv[0]);
	exit(1);   
}

struct AppArgs{
	std::string type;
	std::string src_ip;
	int src_port;
	std::string dst_ip;
	int dst_port;
	int limit;
};

void parse_args(AppArgs *args, int argc, char **argv){
	if(argc < 7){
		usage(argc, argv);
	}
	for(int i=1; i<argc; i++){
		if(std::string("-h") == argv[i]){
			usage(argc, argv);
		}
		if(argv[i][0] == '-'){
			fprintf(stderr, "ERROR: Invalid argument: %s!\n", argv[i]);
			exit(1);   
		}
	}
	args->type = argv[1];
	args->src_ip = argv[2];
	args->src_port = str_to_int(argv[3]);
	args->dst_ip = argv[4];
	args->dst_port = str_to_int(argv[5]);
	args->limit = str_to_int(argv[6]);
	if(args->type != "KV"){
		fprintf(stderr, "ERROR: only type of KV is supported!\n");
		exit(1);   
	}
	if(args->limit <= 0){
		fprintf(stderr, "ERROR: invalid limit option!\n");
		exit(1);   
	}
}

struct KeyRange{
	std::string start;
	std::string end;
	
	KeyRange(){
	}
	
	KeyRange(const std::string &start, const std::string &end){
		this->start = start;
		this->end = end;
	}
	
	std::string str(){
		char buf[1024];
		snprintf(buf, sizeof(buf), "(\"%s\", \"%s\"]", str_escape(start).c_str(), str_escape(end).c_str());
		return std::string(buf);
	}
};

int move_key(const std::string &key){
	std::string val;
	ssdb::Status s;
	s = src->get(key, &val);
	if(s.not_found()){
		return 0;
	}
	if(!s.ok()){
		log_error("src server error! %s", s.code().c_str());
		return -1;
	}
	s = dst->set(key, val);
	if(!s.ok()){
		log_error("dst server error! %s", s.code().c_str());
		return -1;
	}
	s = src->del(key);
	if(!s.ok()){
		log_error("src server error! %s", s.code().c_str());
		return -1;
	}
	return 1;
}

int move_range(const std::string &min_key, const std::string &max_key, int limit, std::string *moved_max_key){
	// get key range
	std::vector<std::string> keys;
	ssdb::Status s;
	s = src->keys(min_key, max_key, limit, &keys);
	if(!s.ok()){
		log_error("response error: %s", s.code().c_str());
		return -1;
	}
	if(keys.empty()){
		return 0;
	}
	if(moved_max_key){
		*moved_max_key = keys[keys.size() - 1];

		// lock key range
		log_info("lock range %s", KeyRange(min_key, *moved_max_key).str().c_str());
		const std::vector<std::string>* resp;
		resp = src->request("set_kv_range", *moved_max_key, max_key);
		if(!resp || resp->empty() || resp->at(0) != "ok"){
			log_error("src server set_kv_range error!");
			return -1;
		}
	}

	// move key range
	for(int i=0; i<(int)keys.size(); i++){
		const std::string &key = keys[i];
		if(move_key(key) == -1){
			log_fatal("move key %s error! %s", key.c_str(), s.code().c_str());
			exit(1);   
		}
	}
	
	return (int)keys.size();
}

ssdb::Client* init_client(const std::string &ip, int port){
	ssdb::Client *client = ssdb::Client::connect(ip, port);
	if(client == NULL){
		log_error("fail to connect to server!");
		return NULL;
	}

	const std::vector<std::string>* resp;
	resp = client->request("ignore_key_range");
	if(!resp || resp->empty() || resp->at(0) != "ok"){
		log_error("src server ignore_key_range error!");
		delete client;
		return NULL;
	}
	return client;
}

int get_key_range(ssdb::Client *client, KeyRange *range){
	const std::vector<std::string>* resp;
	resp = client->request("get_kv_range");
	if(!resp || resp->size() < 3 || resp->at(0) != "ok"){
		log_error("get_kv_range error!");
		return -1;
	}
	range->start = resp->at(1);
	range->end = resp->at(2);
	return 0;
}

int set_key_range(ssdb::Client *client, const KeyRange &range){
	const std::vector<std::string>* resp;
	resp = client->request("set_kv_range", range.start, range.end);
	if(!resp || resp->empty() || resp->at(0) != "ok"){
		log_error("server set_kv_range error!");
		return -1;
	}
	return 0;
}

void check_version(ssdb::Client *client){
	const std::vector<std::string>* resp;
	resp = client->request("version");
	if(!resp || resp->size() < 2 || resp->at(0) != "ok"){
		fprintf(stderr, "ERROR: ssdb-server 1.9.0 or higher is required!\n");
		exit(1);
	}
}

int main(int argc, char **argv){
	welcome();
	AppArgs args;
	parse_args(&args, argc, argv);

	src = init_client(args.src_ip, args.src_port);
	if(src == NULL){
		log_error("fail to connect to server!");
		return 0;
	}
	dst = init_client(args.dst_ip, args.dst_port);
	if(dst == NULL){
		log_error("fail to connect to server!");
		return 0;
	}
	check_version(src);
	check_version(dst);
	
	KeyRange src_range;
	if(get_key_range(src, &src_range) == -1){
		return -1;
	}
	log_info("old src %s", src_range.str().c_str());
	
	KeyRange dst_range;
	if(get_key_range(dst, &dst_range) == -1){
		return -1;
	}
	log_info("old dst %s", dst_range.str().c_str());

	for(int i=0; i<args.limit; i+=BATCH_SIZE){
		int num = BATCH_SIZE;
		if(args.limit - i < BATCH_SIZE){
			num = args.limit - i;
		}
		
		// move data
		int ret;
		std::string moved_max_key;
		ret = move_range(src_range.start, src_range.end, num, &moved_max_key);
		if(ret == -1){
			log_fatal("move_range error!");
			exit(1);   
		}
		if(ret == 0){
			continue;
		}
		log_debug("moved %d key(s)", ret);
		while(ret == num){
			// check again, make sure there is not key inserted before we lock range
			ret = move_range(src_range.start, moved_max_key, num, NULL);
			if(ret == -1){
				log_fatal("move_range error!");
				exit(1);   
			}
		}
	
		KeyRange new_src_range(moved_max_key, src_range.end);
		KeyRange new_dst_range(dst_range.start, moved_max_key);
	
		log_info("src %s => %s", src_range.str().c_str(), new_src_range.str().c_str());
		log_info("dst %s => %s", dst_range.str().c_str(), new_dst_range.str().c_str());
	
		// update key range
		if(set_key_range(src, new_src_range) == -1){
			log_fatal("src server set_kv_range error!");
			exit(1);   
		}
		if(set_key_range(dst, new_dst_range) == -1){
			log_fatal("dst server set_kv_range error!");
			exit(1);   
		}
	}
	
	delete src;
	delete dst;
	return 0;
}
