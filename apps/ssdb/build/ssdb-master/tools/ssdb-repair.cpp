/*
Copyright (c) 2012-2015 The SSDB Authors. All rights reserved.
Use of this source code is governed by a BSD-style license that can be
found in the LICENSE file.
*/
#include "include.h"

#include <string>
#include <vector>

#include "leveldb/db.h"
#include "leveldb/env.h"
#include "leveldb/options.h"
#include "leveldb/slice.h"
#include "leveldb/iterator.h"

#include "util/log.h"
#include "util/file.h"
#include "util/strings.h"

void welcome(){
	printf("ssdb-repair - SSDB repair tool\n");
	printf("Copyright (c) 2013-2015 ssdb.io\n");
	printf("\n");
}

void usage(int argc, char **argv){
	printf("Usage:\n");
	printf("    %s leveldb_folder\n", argv[0]);
	printf("\n");
}

int main(int argc, char **argv){
	welcome();

	set_log_level(Logger::LEVEL_MIN);

	if(argc <= 1){
		usage(argc, argv);
		return 0;
	}
	std::string leveldb_folder(argv[1]);

	if(!file_exists(leveldb_folder.c_str())){
		printf("leveldb_folder[%s] not exists!\n", leveldb_folder.c_str());
		return 0;
	}
	
	leveldb::Status status;
	
	leveldb::Logger *logger;
	status = leveldb::Env::Default()->NewLogger("repair.log", &logger);
	if(!status.ok()){
		printf("logger error!\n");
		return 0;
	}
	printf("writing repair log into: repair.log\n");

	leveldb::Options options;
	options.info_log = logger;
	status = leveldb::RepairDB(leveldb_folder.c_str(), options);
	if(!status.ok()){
		printf("repair leveldb: %s error!\n", leveldb_folder.c_str());
		return 0;
	}
	
	printf("leveldb repaired.\n");

	return 0;
}
