#!/bin/sh
#
# chkconfig: 2345 64 36
# description: SSDB startup scripts
#
ssdb_root=/usr/local/ssdb
ssdb_bin=$ssdb_root/ssdb-server
# each config file for one instance
# configs="/data/ssdb_data/test/ssdb.conf /data/ssdb_data/test2/ssdb.conf"
configs="/data/ssdb_data/test/ssdb.conf"

 
if [ -f /etc/rc.d/init.d/functions ]; then
	. /etc/rc.d/init.d/functions
fi
 
start() {
	for conf in $configs; do
		$ssdb_bin $conf -s restart -d
	done
}
 
stop() {
	for conf in $configs; do
		$ssdb_bin $conf -s stop -d
	done
}
 
# See how we were called.
case "$1" in
    start)
        start
        ;;
    stop)
        stop
        ;;
    restart)
        stop
        start
        ;;
    *)
        echo $"Usage: $0 {start|stop|restart}"
        ;;
esac
exit $RETVAL
