import util.*;

function kv_node_list(resp, time_consume){
	len_index = 0;
	count = 0;
	while(len(resp.data) > len_index){
		kv_len = int(resp.data[len_index]);
		if(kv_len < 6){
			printf('bad response!\n');
			break;
		}
		if(len(resp.data) >= len_index + kv_len){
			count += 1;
			id      = resp.data[len_index + 1];
			status  = resp.data[len_index + 2];
			range_s = resp.data[len_index + 3];
			range_e = resp.data[len_index + 4];
			ip      = resp.data[len_index + 5];
			port    = resp.data[len_index + 6];
			
			status_text = 'UNKNOWN';
			if(status == '0'){
				status_text = 'INIT';
			}else if(status == '1'){
				status_text = 'SERVING';
			}
			
			printf('id: %s\n', id);
			printf('    status: %s\n', status_text);
			printf('    range:  (\"%s\", \"%s\"]\n', range_s.encode('string-escape'), range_e.encode('string-escape'));
			printf('    ip:     %s\n', ip);
			printf('    port:   %s\n', port);
		}
		len_index += 6 + 1;
	}
	sys.stderr.write(sprintf('%d result(s) (%.3f sec)\n', count, time_consume));
}
