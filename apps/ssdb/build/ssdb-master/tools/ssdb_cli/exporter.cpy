import util.*;

fp = null;
progress = 0;
read_size = 0;
total_size = 0;

function write_line(params){
	gs = globals();
	foreach(params as k=>v){
		params[k] = str(v).encode('string-escape');
	}
	line = str('\t').join(params) + '\n';
	gs['read_size'] += len(line);
	gs['fp'].write(line);
}

function show_progress(){
	gs = globals();
	progress = gs['progress'];
	read_size = gs['read_size'];
	total_size = gs['total_size'];

	progress_2 = int(float(read_size)/total_size * 100);
	if(progress_2 - progress >= 5 || read_size == total_size){
		gs['progress'] = progress_2;
		printf("%2d%%\n", progress_2);
	}
}

function my_readline(c){
	if(c == null){
		c = '';
	}
	try{
		return raw_input(c);
	}catch(Exception e){
	}
	return '';
}

function run(link, args){
	gs = globals();

	kstart = '';
	kend = '';
	hstart = '';
	hend = '';
	zstart = '';
	zend = '';
	qstart = '';
	qend = '';

	output_file = false;
	interactive = false;
	foreach(args as arg){
		if(arg == '-i'){
			interactive = true;
		}else{
			output_file = arg;
		}
	}
	if(output_file == false){
		sys.stderr.write('Usage: export [-i] out_file\n');
		return;
	}
	if(os.path.exists(output_file)){
		print 'Error: ' + output_file + ' already exists!';
		return;
	}

	if(interactive){
		printf("input KV range[start, end]: \n");
		kstart = my_readline('  start(inclusive, default none): ');
		kend   = my_readline('    end(inclusive, default none): ');
		printf("input HASH range: \n");
		hstart = my_readline('  start(inclusive, default none): ');
		hend   = my_readline('    end(inclusive, default none): ');
		printf("input ZSET range: \n");
		zstart = my_readline('  start(inclusive, default none): ');
		zend   = my_readline('    end(inclusive, default none): ');
		printf("input QUEUE range: \n");
		qstart = my_readline('  start(inclusive, default none): ');
		qend   = my_readline('    end(inclusive, default none): ');
	}
	
	gs['fp'] = open(output_file, 'w');
	
	gs = globals();
	gs['total_size'] = dbsize(link);

	if(gs['total_size'] <= 0){
		gs['total_size'] = 1;
	}
	gs['total_size'] *= 1024 * 1024;

	// KV
	ls = new SSDB_kv_scan(link);
	ls.set_range(kstart, kend);
	// by default, ssdb's iterator is start-exclusive,
	r = link.request('get', [ls.key]);
	if(r.ok()){
		write_line(['set', ls.key, r.data]);
	}
	while(ls.next()){
		show_progress();
		write_line(['set', ls.key, ls.val]);
	}

	// HASH
	ls = new SSDB_hash_list(link);
	ls.set_range(hstart, hend);
	scan = new SSDB_hash_scan(link);
	scan.name = ls.key;
	while(scan.next()){
		show_progress();
		write_line(['hset', ls.key, scan.key, scan.val]);
	}
	while(ls.next()){
		scan = new SSDB_hash_scan(link);
		scan.name = ls.key;
		while(scan.next()){
			show_progress();
			write_line(['hset', ls.key, scan.key, scan.val]);
		}
	}

	// ZSET
	ls = new SSDB_zset_list(link);
	ls.set_range(zstart, zend);
	scan = new SSDB_zset_scan(link);
	scan.name = ls.key;
	while(scan.next()){
		show_progress();
		write_line(['zset', ls.key, scan.key, scan.val]);
	}
	while(ls.next()){
		scan = new SSDB_zset_scan(link);
		scan.name = ls.key;
		while(scan.next()){
			show_progress();
			write_line(['zset', ls.key, scan.key, scan.val]);
		}
	}

	// QUEUE
	ls = new SSDB_queue_list(link);
	ls.set_range(qstart, qend);
	scan = new SSDB_queue_scan(link);
	scan.name = ls.key;
	while(scan.next()){
		show_progress();
		write_line(['qpush', ls.key, scan.val]);
	}
	while(ls.next()){
		scan = new SSDB_queue_scan(link);
		scan.name = ls.key;
		while(scan.next()){
			show_progress();
			write_line(['qpush', ls.key, scan.val]);
		}
	}
	
	if(gs['fp']){
		gs['fp'].close();
	}

	gs['read_size'] = gs['total_size'];
	show_progress();
	print 'done.';
}
