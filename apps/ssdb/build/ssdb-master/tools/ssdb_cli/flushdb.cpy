function hclear(link, hname, verbose=true){
	ret = 0;
	r = link.request('hclear', [hname]);
	try{
		ret = r.data;
	}catch(Exception e){
	}
	return ret;
}

function zclear(link, zname, verbose=true){
	ret = 0;
	r = link.request('zclear', [zname]);
	try{
		ret = r.data;
	}catch(Exception e){
	}
	return ret;
}

function qclear(link, zname, verbose=true){
	ret = 0;
	r = link.request('qclear', [zname]);
	try{
		ret = r.data;
	}catch(Exception e){
	}
	return ret;
}
function flushdb(link, data_type){
	resp = link.request('info');
	for(i=1; i<len(resp.data); i+=2){
		if(resp.data[i] == 'replication'){
			throw new Exception('flushdb is not allowed when replication is in use!');
		}
	}

	printf('\n');
	printf('============================ DANGER! ============================\n');
	printf('This operation is DANGEROUS and is not recoverable, if you\n');
	printf('really want to flush the whole db(delete ALL data in ssdb server),\n');
	printf('input \'yes\' and press Enter, or just press Enter to cancel\n');
	printf('\n');
	printf('flushdb will break replication states, you must fully understand\n');
	printf('the RISK before you doing this!\n');
	printf('\n');
	printf('> flushdb? ');
	
	line = sys.stdin.readline().strip();
	if(line != 'yes'){
		printf('Operation cancelled.\n\n');
		return;
	}

	print 'Begin to flushdb...\n';

	if(data_type == ''){
		resp = link.request('flushdb', []);
		if(resp.code != 'ok' && resp.code != 'client_error'){
			throw new Exception(resp.message);
		}
	}
	
	batch = 1000;
	
	d_kv = 0;
	if(data_type == '' || data_type == 'kv'){
		while(true){
			resp = link.request('keys', ['', '', batch]);
			if(len(resp.data) == 0){
				break;
			}
			d_kv += len(resp.data);
			link.request('multi_del', resp.data);
			printf('delete[kv  ] %d key(s).\n', d_kv);
		}
	}
	
	d_hash = 0;
	d_hkeys = 0;
	if(data_type == '' || data_type == 'hash'){
		while(true){
			resp = link.request('hlist', ['', '', batch]);
			if(len(resp.data) == 0){
				break;
			}
			last_num = 0;
			foreach(resp.data as hname){
				d_hash += 1;
				deleted_num = hclear(link, hname, false);
				d_hkeys += deleted_num;
				if(d_hkeys - last_num >= batch){
					last_num = d_hkeys;
					printf('delete[hash] %d hash(s), %d key(s).\n', d_hash, d_hkeys);
				}
			}
			if(d_hkeys - last_num >= batch){
				printf('delete[hash] %d hash(s), %d key(s).\n', d_hash, d_hkeys);
			}
		}
		printf('delete[hash] %d hash(s), %d key(s).\n', d_hash, d_hkeys);
	}
	
	d_zset = 0;
	d_zkeys = 0;
	if(data_type == '' || data_type == 'zset'){
		while(true){
			resp = link.request('zlist', ['', '', batch]);
			if(len(resp.data) == 0){
				break;
			}
			last_num = 0;
			foreach(resp.data as zname){
				d_zset += 1;
				deleted_num = zclear(link, zname, false);
				d_zkeys += deleted_num;
				if(d_zkeys - last_num >= batch){
					last_num = d_zkeys;
					printf('delete[zset] %d zset(s), %d key(s).\n', d_zset, d_zkeys);
				}
			}
			if(d_zkeys - last_num >= batch){
				printf('delete[zset] %d zset(s), %d key(s).\n', d_zset, d_zkeys);
			}
		}
		printf('delete[zset] %d zset(s), %d key(s).\n', d_zset, d_zkeys);
	}
	
	d_list = 0;
	d_lkeys = 0;
	if(data_type == '' || data_type == 'list'){
		while(true){
			resp = link.request('qlist', ['', '', batch]);
			if(len(resp.data) == 0){
				break;
			}
			last_num = 0;
			foreach(resp.data as zname){
				d_list += 1;
				deleted_num = qclear(link, zname, false);
				d_lkeys += deleted_num;
				if(d_zkeys - last_num >= batch){
					last_num = d_lkeys;
					printf('delete[list] %d list(s), %d key(s).\n', d_list, d_lkeys);
				}
			}
			if(d_lkeys - last_num >= batch){
				printf('delete[list] %d list(s), %d key(s).\n', d_list, d_lkeys);
			}
		}
		printf('delete[list] %d list(s), %d key(s).\n', d_list, d_lkeys);
	}

	printf('\n');
	printf('===== flushdb stats =====\n');
	if(data_type == '' || data_type == 'kv'){
		printf('[kv]   %8d key(s).\n', d_kv);
	}
	if(data_type == '' || data_type == 'hash'){
		printf('[hash] %8d hash(s), %8d key(s).\n', d_hash, d_hkeys);
	}
	if(data_type == '' || data_type == 'zset'){
		printf('[zset] %8d zset(s), %8d key(s).\n', d_zset, d_zkeys);
	}
	if(data_type == '' || data_type == 'list'){
		printf('[list] %8d list(s), %8d key(s).\n', d_list, d_lkeys);
	}
	printf('\n');
	
	printf('clear binlog\n');
	link.request('clear_binlog');
	printf('\n');

	printf('compacting...\n');
	link.request('compact');
	printf('done.\n');
	printf('\n');
}
