function run(link, filename){
	if(!os.path.exists(filename)){
		print 'Error: ' + filename + ' not exists!';
		return;
	}
	total_size = os.path.getsize(filename);
	if(total_size == 0){
		total_size = 1;
	}

	progress = 0;
	read_size = 0;
	fp = open(filename, 'r');
	lineno = 0;
	foreach(fp as line){
		lineno ++;
		read_size += len(line);
		progress_2 = int(float(read_size)/total_size * 100);
		if(progress_2 - progress >= 5 || read_size == total_size){
			progress = progress_2;
			printf("%2d%%\n", progress_2);
		}
		
		ps = line.strip().split('\t');
		if(len(ps) < 2){
			print 'Error: bad format at line ' + str(lineno) + ', abort!';
			return;
		}
		cmd = ps[0].lower();
		foreach(ps as k=>v){
			ps[k] = str(v).decode('string-escape');
		}
		
		link.request(cmd, ps[ 1 ..]);
	}
	print 'done.';
}
