nagios_probe = '';
nagios_warn = 85;
nagios_critical = 95;

function run(link, cli_args){
	gs = globals();
	opt = '';
	foreach(cli_args as arg){
		if(opt == '' && arg.startswith('-')){
			opt = arg;
		}else{
			switch(opt){
				case '-n':
					opt = '';
					gs['nagios_probe'] = arg;
					break;
				case '-w':
					gs['nagios_warn'] = arg;
					opt = '';
					break;
				case '-c':
					gs['nagios_critical'] = arg;
					opt = '';
					break;
				default: 
					# ignore args '-h host -p port'
					opt = '';
					break;
			}
		}
	}
	try{
		resp = link.request('info', []);
		if(nagios_probe == 'info'){
			nagios_info(resp);
		}
		if(nagios_probe == 'dbsize'){
			nagios_dbsize(resp);
		}
		if(nagios_probe == 'replication'){
			nagios_replication(resp);
		}
		if(nagios_probe == 'write_read'){
			nagios_write_read(link);
		}
		# Possible future checks:
		# - check if binlogs.max_seq == replication.client.last_seq
		# - does total_calls is growing
	}catch(Exception e){
		sys.stderr.write(str(e) + '\n');
	}
	#sys.stderr.write('exit\n');
	exit(0);
}

function nagios_info(resp){
	is_val = false;
	for(i=1; i<len(resp.data); i++){
		s = resp.data[i];
		if(is_val){
			s = '	' + s.replace('\n', '\n	');
		}
		print s;
		is_val = !is_val;
	}
}

function nagios_probe_check(resp){
	next_val = false;
	ret = '';
	for(i=1; i<len(resp.data); i++){
		s = resp.data[i];
		if(next_val){
			s = s.replace('\n', '\n	');
			next_val = !next_val;
			#print s;
			ret += s;
		}
		if(s == nagios_probe){
			next_val = !next_val;
		}
	}
	return ret;
}

function nagios_dbsize(resp){
	dbsize = nagios_probe_check(resp);
	if(long(dbsize) > long(nagios_critical)){
		print 'CRITICAL: dbsize ' + str(dbsize) + ' larger than ' + str(nagios_critical);
		exit(2);
	}else if(long(dbsize) > long(nagios_warn)){
		print 'WARN: dbsize ' + str(dbsize) + ' larger than ' + str(nagios_warn);
		exit(1);
	}else{
		print 'OK: dbsize ' + str(dbsize) + ' less than ' + str(nagios_critical);
		exit(0);
	}
}

function nagios_replication(resp){
	replication = nagios_probe_check(resp);
	replication = replication.replace('slaveof', '\nslaveof');
	if(replication.find('DISCONNECTED') > 0 ){
		print 'CRITICAL: ' + replication;
		exit(2);
	}else if(replication.find('COPY') > 0 || replication.find('INIT') > 0 || replication.find('OUT_OF_SYNC') > 0){
		print 'WARN: ' + replication;
		exit(1);
	}else if(replication.find('SYNC') > 0){
		print 'OK: ' + replication;
		exit(0);
	}else{
		print 'WARN, is replication configured? Status: ' + replication;
		exit(1);
	}
}

function nagios_write_read(link){
	test_key = 'write_read_test_key';
	resp = link.request('set', ['nagiostest', test_key]);
	#print resp;
	resp = link.request('get', ['nagiostest']);
	#print resp;
	if (resp.data == test_key){
		print 'OK: ' + resp.data;
		exit(0);
	}else{
		print 'WRITE_READ failed: ' + resp.data;
		exit(2);
	}
}
