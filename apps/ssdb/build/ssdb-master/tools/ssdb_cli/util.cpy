
function show_version(link){
	try{
		resp = link.request('info', []);
		sys.stderr.write(resp.data[0] + ' ' + resp.data[2] + '\n\n');
	}catch(Exception e){
	}
}

function dbsize(link){
	resp = link.request('info', []);
	foreach(resp.data as k=>v){
		if(v != 'leveldb.stats'){
			continue;
		}
		s = resp.data[k + 1];
		lines = s.strip().split('\n');
		lines = lines[ 3 ..];
		size = 0;
		foreach(lines as line){
			ps = line.split();
			size += int(ps[2]);
		}
		return size;
	}
	return 0;
}

class SSDB_iterator_base
{
	public link;
	public finish = false;
	public batch = 2;
	public index = [];
	public key = '';
	public val = '';
	public end = '';
	
	function init(link){
		this.link = link;
	}
	
	function seek(s){
		this.key = s;
	}
	
	function set_range(s, e=''){
		this.key = s;
		this.end = e;
	}
}


class SSDB_kv_scan extends SSDB_iterator_base
{
	public items = [];

	function init(link){
	}
	
	function next(){
		if(this.finish){
			return false;
		}
		if(len(this.index) == 0){
			resp = this.link.request('scan', [this.key, this.end, this.batch]);
			if(len(resp.data['index']) == 0){
				this.finish = true;
				return false;
			}
			this.index = resp.data['index'];
			this.items = resp.data['items'];
		}
		this.key = this.index.pop(0);
		this.val = this.items[this.key];
		return true;
	}
}

/*
scan = new SSDB_kv_scan();
while(kvs.next()){
	print scan.key, scan.val;
}
*/

class SSDB_hash_list extends SSDB_iterator_base
{
	function init(link){
	}
	
	function next(){
		if(this.finish){
			return false;
		}
		if(len(this.index) == 0){
			resp = this.link.request('hlist', [this.key, this.end, this.batch]);
			if(len(resp.data) == 0){
				this.finish = true;
				return false;
			}
			this.index = resp.data;
		}
		this.key = this.index.pop(0);
		return true;
	}
}


class SSDB_zset_list extends SSDB_iterator_base
{
	function init(link){
	}
	
	function next(){
		if(this.finish){
			return false;
		}
		if(len(this.index) == 0){
			resp = this.link.request('zlist', [this.key, this.end, this.batch]);
			if(len(resp.data) == 0){
				this.finish = true;
				return false;
			}
			this.index = resp.data;
		}
		this.key = this.index.pop(0);
		return true;
	}
}

/*
kvs = new SSDB_zset_list();
while(kvs.next()){
	print kvs.name;
}
*/


class SSDB_queue_list extends SSDB_iterator_base
{
	function init(link){
	}
	
	function next(){
		if(this.finish){
			return false;
		}
		if(len(this.index) == 0){
			resp = this.link.request('qlist', [this.key, this.end, this.batch]);
			if(len(resp.data) == 0){
				this.finish = true;
				return false;
			}
			this.index = resp.data;
		}
		this.key = this.index.pop(0);
		return true;
	}
}



class SSDB_hash_scan extends SSDB_iterator_base
{
	public name = '';
	public items = [];
	
	function init(link){
	}
	
	function next(){
		if(this.finish){
			return false;
		}
		if(len(this.index) == 0){
			resp = this.link.request('hscan', [this.name, this.key, '', this.batch]);
			if(len(resp.data['index']) == 0){
				this.finish = true;
				return false;
			}
			this.index = resp.data['index'];
			this.items = resp.data['items'];
		}
		this.key = this.index.pop(0);
		this.val = this.items[this.key];
		return true;
	}
}

/*
scan = new SSDB_hash_scan('n');
while(scan.next()){
	print scan.key, scan.val;
}
*/



class SSDB_zset_scan extends SSDB_iterator_base
{
	public name = '';
	public items = [];
	
	function init(link){
	}
	
	function next(){
		if(this.finish){
			return false;
		}
		if(len(this.index) == 0){
			resp = this.link.request('zscan', [this.name, this.key, this.val, '', this.batch]);
			if(len(resp.data['index']) == 0){
				this.finish = true;
				return false;
			}
			this.index = resp.data['index'];
			this.items = resp.data['items'];
		}
		this.key = this.index.pop(0);
		this.val = this.items[this.key];
		return true;
	}
}

/*
scan = new SSDB_zset_scan('n');
while(scan.next()){
	print scan.key, scan.val;
}
*/



class SSDB_queue_scan extends SSDB_iterator_base
{
	public items = [];
	public offset = 0;
	
	function init(link){
	}
	
	function next(){
		if(this.finish){
			return false;
		}
		if(len(this.index) == 0){
			resp = this.link.request('qrange', [this.name, this.offset, this.batch]);
			if(len(resp.data) == 0){
				this.finish = true;
				return false;
			}
			this.index = resp.data;
		}
		this.key = this.offset;
		this.val = this.index.pop(0);
		this.offset += 1;
		return true;
	}
}

/*
scan = new SSDB_queue_scan('q');
while(scan.next()){
	print scan.item;
}
*/
