<?php
$host = '127.0.0.1';
$port = 8888;
$sock = @stream_socket_client("$host:$port", $errno, $errstr);
$s = "3\r\nget\n1\r\nk\r\n\r\n";
$s .= str_replace("\r\n", "\n", $s);

for($i=0; $i<strlen($s); $i++){
	fwrite($sock, $s[$i]);
	fflush($sock);
	usleep(100 * 1000);
	printf("write %d byte(s)\n", $i+1);
}


