<?php
/**
 * Copyright (c) 2012, ideawu
 * All rights reserved.
 * @author: ideawu
 * @link: http://www.ideawu.com/
 *
 * unit test.
 */

include(dirname(__FILE__) . '/../api/php/SSDB.php');

class SSDBTest extends UnitTest{
	private $ssdb;

	function __construct(){
		$host = '127.0.0.1';
		$port = 8888;
		$this->ssdb = new SimpleSSDB($host, $port);
		$this->ssdb->auth('very-strong-password-11111111111111111');
		$this->clear();
	}

	function clear(){
		$ssdb = $this->ssdb;
		$deleted = 0;
		while(1){
			$ret = $ssdb->scan('TEST_', 'TEST_'.pack('C', 255), 1000);
			if(!$ret){
				break;
			}
			foreach($ret as $k=>$v){
				$ssdb->del($k);
				$deleted += 1;
			}
		}
		while(1){
			$names = $ssdb->hlist('TEST_', 'TEST_'.pack('C', 255), 1000);
			if(!$names){
				break;
			}
			foreach($names as $name){
				$deleted += $ssdb->hclear($name);
				$ret = $ssdb->hsize($name);
				$this->assert($ret == 0);
			}
		}
		while(1){
			$names = $ssdb->zlist('TEST_', 'TEST_'.pack('C', 255), 1000);
			if(!$names){
				break;
			}
			foreach($names as $name){
				$deleted += $ssdb->zclear($name);
				$ret = $ssdb->zsize($name);
				$this->assert($ret == 0);
			}
		}
		while(1){
			$names = $ssdb->qlist('TEST_', 'TEST_'.pack('C', 255), 1000);
			if(!$names){
				break;
			}
			foreach($names as $name){
				$deleted += $ssdb->qclear($name);
				$ret = $ssdb->qsize($name);
				$this->assert($ret == 0);
			}
		}
		if($deleted > 0){
			echo "clear $deleted\n";
		}
	}

	function test_kv(){
		$ssdb = $this->ssdb;
		$val = str_repeat(mt_rand(), mt_rand(1, 100));
		
		$ssdb->del('TEST_a');
		$ret = $ssdb->ttl('TEST_a');
		$this->assert($ret === -1);
		$ret = $ssdb->expire('TEST_a', 10);
		$this->assert($ret === 0);
		$ssdb->set('TEST_a', $val);
		$ret = $ssdb->expire('TEST_a', 10);
		$this->assert($ret === 1);
		
		$ssdb->setx('TEST_a', $val, 1);
		$ret = $this->ssdb->get('TEST_a');
		$this->assert($ret === $val);
		usleep(1.5 * 1000 * 1000);
		$ret = $this->ssdb->get('TEST_a');
		$this->assert($ret === null);

		$ssdb->set('TEST_a', $val);
		$ssdb->set('TEST_b', $val);
		
		$ret = $this->ssdb->get('TEST_a');
		$this->assert($ret === $val);

		$ret = $ssdb->scan('TEST_', 'TEST_'.pack('C', 255), 10);
		$this->assert(count($ret) == 2);
		$ret = $ssdb->scan('TEST_a', 'TEST_'.pack('C', 255), 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->scan('TEST_b', 'TEST_'.pack('C', 255), 10);
		$this->assert(count($ret) == 0);
		$ret = $ssdb->scan('TEST_', 'TEST_a', 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->scan('TEST_', 'TEST_b', 10);
		$this->assert(count($ret) == 2);

		$ret = $ssdb->rscan('TEST_'.pack('C', 255), 'TEST_', 10);
		$this->assert(count($ret) == 2);
		$ret = $ssdb->rscan('TEST_b', 'TEST_'.pack('C', 0), 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->rscan('TEST_a', 'TEST_'.pack('C', 0), 10);
		$this->assert(count($ret) == 0);
		$ret = $ssdb->rscan('TEST_'.pack('C', 255), 'TEST_a', 10);
		$this->assert(count($ret) == 2);
		$ret = $ssdb->rscan('TEST_'.pack('C', 255), 'TEST_b', 10);
		$this->assert(count($ret) == 1);

		$ret = $ssdb->keys('TEST_', 'TEST_'.pack('C', 255), 10);
		$this->assert(count($ret) == 2);

		$kvs = array();
		for($i=0; $i<5; $i++){
			$kvs['TEST_' . $i] = $i;
			$ssdb->multi_set($kvs);
			$ret = $ssdb->multi_get(array_keys($kvs));
			$this->assert(count($ret) == count($kvs));
			$ret = $ssdb->multi_del(array_keys($kvs));
			$ret = $ssdb->multi_get(array_keys($kvs));
			$this->assert(count($ret) == 0);
		}

		$ret = $ssdb->exists('TEST_a');
		$this->assert($ret === true);
		$ssdb->del('TEST_a');
		$ret = $ssdb->exists('TEST_a');
		$this->assert($ret === false);
		$ret = $ssdb->get('TEST_a');
		$this->assert($ret === null);
		$ssdb->del('TEST_b');
		
		$ssdb->del('TEST_a');
		$ret = $ssdb->setnx('TEST_a', 'a');
		$this->assert($ret === 1);
		$ret = $ssdb->setnx('TEST_a', 't');
		$this->assert($ret === 0);
		$ret = $ssdb->get('TEST_a');
		$this->assert($ret === 'a');
		
		$ssdb->del('TEST_a');
		$ret = $ssdb->getset('TEST_a', 'a');
		$this->assert($ret === null);
		$ret = $ssdb->getset('TEST_a', 'b');
		$this->assert($ret === 'a');
		$ret = $ssdb->get('TEST_a');
		$this->assert($ret === 'b');

		$key = 'TEST_a';
		$ssdb->del($key);
		$ret = $ssdb->setbit($key, 8, 1);
		$this->assert($ret === 0);
		$ret = $ssdb->setbit($key, 8, 1);
		$this->assert($ret === 1);
		$ret = $ssdb->countbit($key, 0, 1);
		$this->assert($ret === 0);
		$ret = $ssdb->countbit($key, 0, 2);
		$this->assert($ret === 1);
		$ret = $ssdb->countbit($key, 0);
		$this->assert($ret === 1);
		$ret = $ssdb->strlen($key);
		$this->assert($ret === 2);
		$val = '0123456789';
		$ssdb->set($key, $val);
		$this->assert($ssdb->substr($key, 0, 1) === substr($val, 0, 1));
		$this->assert($ssdb->substr($key, -1, -1) === substr($val, -1, -1));
		$this->assert($ssdb->substr($key, 0, -1) === substr($val, 0, -1));
		$this->assert($ssdb->substr($key, -1, -2) === substr($val, -1, -2));
		$this->assert($ssdb->substr($key, -2, -1) === substr($val, -2, -1));
		$this->assert($ssdb->substr($key, -2, 2) === substr($val, -2, 2));
	}
	
	function test_queue(){
		$ssdb = $this->ssdb;
		$name = "TEST_" . str_repeat(mt_rand(), mt_rand(1, 3));
		$key = "TEST_" . str_repeat(mt_rand(), mt_rand(1, 3));
		$val = str_repeat(mt_rand(), mt_rand(1, 30));
				
		for($i=0; $i<7; $i++){
			$size = $ssdb->qpush($name, $i);
			$this->assert($size === $i + 1);
		}
		$size = $ssdb->qpush($name, array(7,8,9));
		$this->assert($size == 10);
		
		$ret = $ssdb->qget($name, 3);
		$this->assert($ret == 3);
		$ret = $ssdb->qslice($name, 0, -1);
		for($i=0; $i<10; $i++){
			$this->assert($ret[$i] == $i);
		}
		$ret = $ssdb->qsize($name);
		$this->assert($ret === 10);
		$ret = $ssdb->qfront($name);
		$this->assert($ret == 0);
		$ret = $ssdb->qback($name);
		$this->assert($ret == 9);
		for($i=0; $i<10; $i++){
			$ret = $ssdb->qpop($name);
			if($ret != $i){
				$this->assert(false);
				break;
			}
		}

		$ret = $ssdb->qfront($name);
		$this->assert($ret === null);
		$ret = $ssdb->qback($name);
		$this->assert($ret === null);
		
		$ssdb->qpush_back($name, 0);
		$ssdb->qpush_front($name, 9);
		$ret = $ssdb->qfront($name);
		$this->assert($ret == 9);
		$ret = $ssdb->qback($name);
		$this->assert($ret == 0);

		$ssdb->qclear($name);
		for($i=0; $i<7; $i++){
			$size = $ssdb->qpush_back($name, $i);
		}
		$ret = $ssdb->qpop_front($name, 2);
		$this->assert(is_array($ret));
		$this->assert(count($ret) == 2);
		$this->assert($ret[0] == 0);
		$this->assert($ret[1] == 1);
		
		$ret = $ssdb->qpop_back($name, 2);
		$this->assert(is_array($ret));
		$ret = $ssdb->qpop($name, 2);
		$this->assert(is_array($ret));

		$ssdb->qclear($name);
		for($i=0; $i<3; $i++){
			$ssdb->qpush_back($name, $i);
		}

		$ret = $ssdb->qset($name, 0, 'www');
		$this->assert($ret !== false);
		$ret = $ssdb->qset($name, 9990, 'www');
		$this->assert($ret === false);
		$ret = $ssdb->qget($name, 0);
		$this->assert($ret === 'www');

		$ret = $ssdb->qtrim_front($name, 2);
		$this->assert($ret === 2);
		$ret = $ssdb->qtrim_back($name, 2);
		$this->assert($ret === 1);
	}

	function test_hash(){
		$ssdb = $this->ssdb;
		$name = "TEST_" . mt_rand();
		$key = "TEST_" . mt_rand();
		$val = str_repeat(mt_rand(), mt_rand(1, 30));

		$ret = $ssdb->hsize($name);
		$this->assert($ret === 0);

		$ret = $ssdb->multi_hset($name, array('a' => 1, 'a' => 2));
		$this->assert($ret == 1);
		$ret = $ssdb->multi_hdel($name, array('a', 'a'));
		$this->assert($ret == 1);

		$ret = $ssdb->hset($name, $key, $val);
		$ret = $ssdb->hexists($name, $key);
		$this->assert($ret);
		$ret = $ssdb->hget($name, $key);
		$this->assert($ret === $val);

		$ret = $ssdb->hsize($name);
		$this->assert($ret === 1);
		$ret = $ssdb->hscan($name, '', '', 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->hrscan($name, '', '', 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->hkeys($name, '', '', 10);
		$this->assert(count($ret) == 1);

		$ret = $ssdb->hdel($name, $key);
		$ret = $ssdb->hsize($name);
		$this->assert($ret === 0);
		$ret = $ssdb->hscan($name, '', '', 10);
		$this->assert(count($ret) == 0);
		$ret = $ssdb->hrscan($name, '', '', 10);
		$this->assert(count($ret) == 0);
		$ret = $ssdb->hkeys($name, '', '', 10);
		$this->assert(count($ret) == 0);

		$ret = $ssdb->hset($name, 'a', $val);
		$ret = $ssdb->hset($name, 'b', $val);
		$ret = $ssdb->hscan($name, '', '', 10);
		$this->assert(count($ret) == 2);
		foreach($ret as $k=>$v){
			$this->assert($v === $val);
		}
		$ret = $ssdb->hscan($name, '', 'a', 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->hscan($name, '', 'b', 10);
		$this->assert(count($ret) == 2);
		$ret = $ssdb->hrscan($name, '', 'b', 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->hrscan($name, '', 'a', 10);
		$this->assert(count($ret) == 2);

		$ret = $ssdb->hscan($name, 'a', '', 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->hscan($name, 'b', '', 10);
		$this->assert(count($ret) == 0);
		$ret = $ssdb->hrscan($name, '', '', 10);
		$this->assert(count($ret) == 2);
		$ret = $ssdb->hrscan($name, 'b', '', 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->hrscan($name, 'a', '', 10);
		$this->assert(count($ret) == 0);
		$ret = $ssdb->hkeys($name, '', '', 10);
		$this->assert(count($ret) == 2);
		$ret = $ssdb->hkeys($name, 'a', '', 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->hkeys($name, 'b', '', 10);
		$this->assert(count($ret) == 0);
		$ret = $ssdb->hdel($name, 'a');
		$ret = $ssdb->hdel($name, 'b');

		$ssdb->hset("TEST_a", 'a', 1);
		$ssdb->hset("TEST_b", 'a', 1);
		$ssdb->hset("TEST_c", 'a', 1);
		$ret = $ssdb->hlist("TEST_a", "TEST_b", 100);
		$this->assert(count($ret) == 1);
		$this->assert($ret[0] == "TEST_b");

		$ret = $ssdb->hexists('TEST_a', 'a');
		$this->assert($ret === true);
		$ssdb->hdel('TEST_a', 'a');
		$ret = $ssdb->hexists('TEST_a', 'a');
		$this->assert($ret === false);
		$ret = $ssdb->hget('TEST_a', 'a');
		$this->assert($ret === null);
	}

	function test_zset(){
		$ssdb = $this->ssdb;
		$name = "TEST_" . mt_rand();
		$key = "TEST_" . mt_rand();
		$val = mt_rand();

		$ret = $ssdb->zsize($name);
		$this->assert($ret === 0);

		$ret = $ssdb->multi_zset($name, array('a' => 1, 'a' => 2));
		$this->assert($ret == 1);
		$ret = $ssdb->multi_zdel($name, array('a', 'a'));
		$this->assert($ret == 1);

		$ret = $ssdb->zset($name, $key, $val);
		$ret = $ssdb->zexists($name, $key);
		$this->assert($ret);
		$ret = $ssdb->zget($name, $key);
		$this->assert($ret === $val);

		$ret = $ssdb->zsize($name);
		$this->assert($ret === 1);
		$ret = $ssdb->zscan($name, '', '', '', 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->zrscan($name, '', '', '', 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->zkeys($name, '', '', '', 10);
		$this->assert(count($ret) == 1);

		$ret = $ssdb->zdel($name, $key);
		$ret = $ssdb->zsize($name);
		$this->assert($ret === 0);
		$ret = $ssdb->zscan($name, '', '', '', 10);
		$this->assert(count($ret) == 0);
		$ret = $ssdb->zrscan($name, '', '', '', 10);
		$this->assert(count($ret) == 0);
		$ret = $ssdb->zkeys($name, '', '', '', 10);
		$this->assert(count($ret) == 0);

		$ret = $ssdb->zset($name, 'a', $val);
		$ret = $ssdb->zset($name, 'b', $val);

		$ret = $ssdb->zrank($name, 'aaaaaaaa');
		$this->assert($ret === null);
		$ret = $ssdb->zrank($name, 'a');
		$this->assert($ret != -1);
		$ret = $ssdb->zrrank($name, 'a');
		$this->assert($ret != -1);

		$ret = $ssdb->zrange($name, 0, 10);
		$this->assert(count($ret) == 2);
		$ret = $ssdb->zrrange($name, 0, 10);
		$this->assert(count($ret) == 2);

		$ret = $ssdb->zscan($name, '', '', '', 10);
		$this->assert(count($ret) == 2);
		foreach($ret as $k=>$v){
			$this->assert($v == $val);
		}
		$ret = $ssdb->zscan($name, 'a', '', '', 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->zscan($name, 'b', '', '', 10);
		$this->assert(count($ret) == 0);
		$ret = $ssdb->zrscan($name, '', '', '', 10);
		$this->assert(count($ret) == 2);
		$ret = $ssdb->zrscan($name, 'b', $val, '', 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->zrscan($name, 'a', $val, '', 10);
		$this->assert(count($ret) == 0);
		$ret = $ssdb->zkeys($name, '', '', '', 10);
		$this->assert(count($ret) == 2);
		$ret = $ssdb->zkeys($name, 'a', $val, '', 10);
		$this->assert(count($ret) == 1);
		$ret = $ssdb->zkeys($name, 'b', $val, '', 10);
		$this->assert(count($ret) == 0);
		$ret = $ssdb->zdel($name, 'a');
		$ret = $ssdb->zdel($name, 'b');

		$ssdb->zset("TEST_a", 'a', 1);
		$ssdb->zset("TEST_b", 'a', 1);
		$ssdb->zset("TEST_c", 'a', 1);
		$ret = $ssdb->zlist("TEST_a", "TEST_b", 100);
		$this->assert(count($ret) == 1);
		$this->assert($ret[0] == "TEST_b");

		$ret = $ssdb->zexists('TEST_a', 'a');
		$this->assert($ret === true);
		$ssdb->zdel('TEST_a', 'a');
		$ret = $ssdb->zexists('TEST_a', 'a');
		$this->assert($ret === false);
		$ret = $ssdb->zget('TEST_a', 'a');
		$this->assert($ret === null);
		
		$ssdb->zclear($name);
		$ssdb->request('multi_zset', $name, 'a', '1', 'b', '2', 'c', '3', 'd', '4', 'e', '5');
		$ret = $ssdb->zcount($name, 2, 4);
		$this->assert($ret === 3);
		$ret = $ssdb->zsum($name, 2, 4);
		$this->assert($ret === 9);
		$ret = $ssdb->zavg($name, 2, 3);
		$this->assert($ret === 2.5);
		$ret = $ssdb->zRemRangeByScore($name, 4, 5);
		$this->assert($ret === 2);
		$ret = $ssdb->zRemRangeByRank($name, 1, 2);
		$this->assert($ret === 2);

		$ssdb->zclear($name);
		for($i=0; $i<10; $i++){
			$ssdb->zset($name, $i, $i);
		}
		$ret = $ssdb->zscan($name, '', 3, 10, 1);
		$vals = array_values($ret);
		$this->assert($vals[0] === 3);
		$ret = $ssdb->zscan($name, '3', 3, 10, 1);
		$vals = array_values($ret);
		$this->assert($vals[0] === 4);

		$ret = $ssdb->zrscan($name, '', 3, 1, 1);
		$vals = array_values($ret);
		$this->assert($vals[0] === 3);
		$ret = $ssdb->zrscan($name, '3', 3, 1, 1);
		$vals = array_values($ret);
		$this->assert($vals[0] === 2);

		$ssdb->zclear($name);
		for($i=0; $i<10; $i++){
			$ssdb->zset($name, $i, $i);
		}
		$ret = $ssdb->zpop_front($name, 2);
		$keys = array_keys($ret);
		$vals = array_values($ret);
		$this->assert($keys[0] === 0 && $vals[0] === 0);
		$this->assert($keys[1] === 1 && $vals[1] === 1);
		$ret = $ssdb->zpop_back($name, 2);
		$keys = array_keys($ret);
		$vals = array_values($ret);
		$this->assert($keys[0] === 9 && $vals[0] === 9);
		$this->assert($keys[1] === 8 && $vals[1] === 8);
	}
}

class UnitTest{
	private $result = array(
			'passed' => 0,
			'failed' => 0,
			'tests' => array(
				),
			);

	function run(){
		$class_name = get_class($this);
		$methods = get_class_methods($class_name);
		foreach($methods as $method){
			if(strpos($method, 'test_') === 0){
				$this->$method();
			}
		}
		$this->report();
		$this->clear();
	}

	function report(){
		$res = $this->result;
		printf("passed: %3d, failed: %3d\n", $res['passed'], $res['failed']);
		foreach($res['tests'] as $test){
			if($test[0] === false){
				printf("    Failed: %s:%d %s() %s\n", $test[2], $test[3], $test[1], $test[4]);
			}
		}
		if($res['failed']){
			printf("passed: %3d, failed: %3d\n", $res['passed'], $res['failed']);
		}
	}

	function assert($val, $desc=''){
		if($val === true){
			$this->result['passed'] ++;
		}else{
			$val = false;
			$this->result['failed'] ++;
		}
		$bt = debug_backtrace(false);
		$func = $bt[1]['function'];
		$file = basename($bt[1]['file']);
		$line = $bt[0]['line'];
		$this->result['tests'][] = array(
				$val, $func, $file, $line, $desc
				);
	}

}


$test = new SSDBTest();
$test->run();

