"""apus_tpu — a TPU-native replicated-state-machine (RSM) framework.

A from-scratch framework with the capabilities of hku-systems/apus (APUS:
"fast and scalable paxos on RDMA"): it makes unmodified server applications
fault-tolerant by interposing on their socket syscalls and committing every
client request through a DARE-style quorum-replicated log.  Where APUS
replicates with one-sided RDMA verbs over InfiniBand
(reference: src/dare/dare_ibv_rc.c), this framework executes the replication
data plane on TPUs with JAX/XLA:

- replica log tails are HBM-resident fixed-width slot arrays sharded over a
  ``replica`` mesh axis (`apus_tpu.ops.logplane`),
- the leader's one-sided log scatter is an ICI collective inside a single
  jitted commit step, and the quorum-ACK spin-poll of the reference
  (dare_ibv_rc.c:1650-1758) becomes a ``psum`` over a replica-axis vote mask
  (`apus_tpu.ops.commit`),
- membership, election, recovery and elastic reconfiguration run on a
  host-side control plane (`apus_tpu.core`, `apus_tpu.proxy`), with the
  native syscall interposer/proxy in C++ under ``native/``.

Layout (mirrors SURVEY.md §7):
    core/      pure, deterministic protocol logic (log, SID/term, CID
               membership, election, commit/pruning rules)
    ops/       jitted JAX device steps (commit, vote, heartbeat) + pallas
    parallel/  transport abstraction, mesh helpers, in-process simulator
    models/    replicated state machines (KVS, app-replay)
    proxy/     host runtime: request capture/replay bridge to native proxy
    utils/     config, timing, logging
"""

__version__ = "0.1.0"
