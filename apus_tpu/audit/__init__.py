"""Consistency audit plane: history capture + linearizability checking.

The reference argues its read/lease safety; this package PROVES ours on
live histories.  ``history`` records every client op's invoke/response
wall-clock interval (serial and pipelined paths alike) into a
lock-cheap ring with JSONL export; ``linear`` checks the captured
history for linearizability against the KVS model — per-key partitioned
(P-compositionality) Wing&Gong search with memoized state hashing,
ambiguous (maybe-applied) ops handled Porcupine-style.  The chaos
campaigns (``benchmarks/fuzz.py --check-linear``, ``benchmarks/soak.py
--audit``) run the checker over histories captured under crash +
network + disk-fault schedules, turning "no stale reads" from an
argument into a checked property.
"""

from apus_tpu.audit.history import HistoryRecorder  # noqa: F401
from apus_tpu.audit.linear import (AuditResult, check_history,  # noqa: F401
                                   resolve_undecided)
