"""History capture: per-op invoke/response intervals for the checker.

``HistoryRecorder`` is the client-side tap: ``ApusClient`` (serial and
``pipeline*`` paths) reports each op's invocation and completion, and
the recorder keeps ``(clt_id, req_id, op, key, value, status, t0, t1)``
in a bounded ring.  Design points that matter for soundness:

- **One interval per op, across retries.**  A failover retry reuses the
  same ``req_id`` and the server-side dedup (core.epdb) makes it
  exactly-once, so the whole retry chain is ONE operation whose
  interval spans first send to final reply — exactly what the recorder
  captures by keying open ops on ``(clt_id, req_id)``.
- **Timeouts are ambiguous (maybe-applied).**  An op that timed out may
  have been applied (the ack was lost) or not, at any time after its
  invocation — the checker treats its response time as +infinity and
  its effect as optional.  Ops still open at export time (client died
  mid-op) are exported the same way.
- **Lock-cheap.**  One lock, tiny critical sections, a
  ``deque(maxlen=capacity)`` ring for completed ops.  When the ring
  overwrites (capacity exceeded) the history is no longer complete and
  the checker's verdict is advisory — ``dropped`` counts this and the
  campaigns size the ring so it never happens.

Wall-clock note: intervals come from ONE process clock
(``time.monotonic``), so every client thread feeding a recorder must
run in the same process — true for all campaigns.  Widening an
interval is sound (fewer real-time constraints); the recorder never
narrows one.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Optional

#: Client-op wire codes (runtime.client; duplicated to keep this module
#: import-light — asserted equal in tests/test_audit.py).
OP_CLT_WRITE = 16
OP_CLT_READ = 17


#: ops whose OBSERVED REPLY constrains the search (read-modify-write:
#: the reply pins the pre-state) — stored in the event's "ret" field
RMW_OPS = ("incr", "getset", "sadd", "srem")
#: read ops — observed value stored in "value", like "get"
READ_OPS = ("get", "smembers")

_TAG_OPS = {b"P": "put", b"G": "get", b"D": "delete", b"C": "incr",
            b"X": "getset", b"SA": "sadd", b"SR": "srem",
            b"SM": "smembers"}


def decode_kvs(data: bytes) -> Optional[tuple[str, bytes, bytes]]:
    """Decode a KVS wire command (models.kvs) into ``(op, key, value)``
    with op in {"put", "get", "delete"} or a typed RDT op; None for
    non-KVS payloads."""
    try:
        tag = data[:2] if data[:1] == b"S" else data[:1]
        op = _TAG_OPS.get(tag)
        if op is None:
            return None
        hdr = len(tag)
        klen_s, rest = data[hdr:].split(b":", 1)
        klen = int(klen_s)
        key, payload = rest[:klen], rest[klen:]
    except (ValueError, IndexError):
        return None
    if op in ("get", "delete", "smembers") and payload:
        return None
    return op, key, payload


class HistoryRecorder:
    """Bounded ring of completed client ops + open-op table."""

    def __init__(self, capacity: int = 1 << 16, clock=time.monotonic):
        self.capacity = capacity
        self.clock = clock
        self._lock = threading.Lock()
        self._done: collections.deque = collections.deque(maxlen=capacity)
        self._open: dict[tuple[int, int], dict] = {}
        #: completed events lost to ring overwrite (history incomplete)
        self.dropped = 0

    # -- client-facing capture --------------------------------------------

    def invoke(self, clt_id: int, req_id: int, op_code: int,
               data: bytes) -> None:
        """Record the invocation of a raw client op (wire payload);
        non-KVS payloads are kept as op="other" (not checkable, but
        still exported so the history shows them)."""
        kv = decode_kvs(data)
        if kv is None:
            op, key, value = "other", b"", b""
        else:
            op, key, value = kv
        if op_code == OP_CLT_READ and op not in ("get", "other"):
            op = "other"            # a write command sent as a read
        self.invoke_kv(clt_id, req_id, op, key, value)

    def invoke_kv(self, clt_id: int, req_id: int, op: str, key: bytes,
                  value: bytes = b"") -> None:
        """Direct capture for app-level harnesses (e.g. the soak's
        SET/GET stream, which never speaks the KVS wire format)."""
        ev = {"clt": clt_id, "req": req_id, "op": op,
              "key": key, "value": value if op not in READ_OPS
              else None,
              "status": "ambiguous", "t0": self.clock(), "t1": None}
        with self._lock:
            self._open[(clt_id, req_id)] = ev

    def invoke_txn(self, clt_id: int, req_id: int,
                   cmds: "list[bytes]") -> None:
        """Record an atomic multi-key transaction invocation: ONE
        event whose ``subs`` are the decoded sub-ops (applied — or
        not — as ONE atomic multi-sub-op action; the strict-
        serializability checker treats it so).  Internal fresh-req_id
        retries after deterministic aborts stay inside this one
        interval — aborted attempts never applied anywhere."""
        subs = []
        for c in cmds:
            kv = decode_kvs(c)
            if kv is None:
                subs.append({"op": "other", "key": b"", "value": b""})
            else:
                subs.append({"op": kv[0], "key": kv[1],
                             "value": kv[2]})
        ev = {"clt": clt_id, "req": req_id, "op": "txn", "key": b"",
              "value": None, "subs": subs, "rets": None,
              "status": "ambiguous", "t0": self.clock(), "t1": None}
        with self._lock:
            self._open[(clt_id, req_id)] = ev

    def complete_txn(self, clt_id: int, req_id: int, status: str,
                     rets: "Optional[list]" = None) -> None:
        """Close an open transaction; ``rets`` is the per-sub reply
        list on "ok" (the reads' observed values constrain the
        checker)."""
        t1 = self.clock()
        with self._lock:
            ev = self._open.pop((clt_id, req_id), None)
            if ev is None:
                return
            ev["status"] = status
            ev["t1"] = t1
            if status == "ok" and rets is not None:
                ev["rets"] = list(rets)
            if len(self._done) == self._done.maxlen:
                self.dropped += 1
            self._done.append(ev)

    def complete(self, clt_id: int, req_id: int, status: str,
                 reply: Optional[bytes] = None) -> None:
        """Close an open op.  ``status``: "ok" (reply is the observed
        value for gets), "ambiguous" (timed out — maybe applied), or
        "error" (server refused; maybe applied for writes)."""
        t1 = self.clock()
        with self._lock:
            ev = self._open.pop((clt_id, req_id), None)
            if ev is None:
                return
            ev["status"] = status
            ev["t1"] = t1
            if status == "ok":
                if ev["op"] in READ_OPS:
                    ev["value"] = reply if reply is not None else b""
                elif ev["op"] in RMW_OPS:
                    ev["ret"] = reply if reply is not None else b""
            if len(self._done) == self._done.maxlen:
                self.dropped += 1
            self._done.append(ev)

    # -- export ------------------------------------------------------------

    def events(self) -> list[dict]:
        """Snapshot: completed ops + still-open ops (as ambiguous,
        t1=None -> +inf in the checker), in no particular order — the
        checker sorts by t0."""
        with self._lock:
            return [dict(e) for e in self._done] + \
                   [dict(e) for e in self._open.values()]

    def dump_jsonl(self, path: str) -> int:
        """Write one JSON object per op.  Keys/values are latin-1
        mapped (lossless byte<->codepoint) so arbitrary bytes survive
        the JSON roundtrip."""
        evs = self.events()
        with open(path, "w") as f:
            for e in evs:
                f.write(json.dumps(encode_event(e)) + "\n")
        return len(evs)

    @staticmethod
    def load_jsonl(path: str) -> list[dict]:
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(decode_event(json.loads(line)))
        return out


def encode_event(e: dict) -> dict:
    out = dict(e)
    out["key"] = e["key"].decode("latin-1")
    out["value"] = None if e["value"] is None \
        else e["value"].decode("latin-1")
    if e.get("ret") is not None:
        out["ret"] = e["ret"].decode("latin-1")
    if e.get("subs") is not None:
        out["subs"] = [{"op": s["op"],
                        "key": s["key"].decode("latin-1"),
                        "value": s["value"].decode("latin-1")}
                       for s in e["subs"]]
    if e.get("rets") is not None:
        out["rets"] = [r.decode("latin-1") for r in e["rets"]]
    return out


def decode_event(e: dict) -> dict:
    out = dict(e)
    out["key"] = e["key"].encode("latin-1")
    out["value"] = None if e.get("value") is None \
        else e["value"].encode("latin-1")
    if e.get("ret") is not None:
        out["ret"] = e["ret"].encode("latin-1")
    if e.get("subs") is not None:
        out["subs"] = [{"op": s["op"],
                        "key": s["key"].encode("latin-1"),
                        "value": s["value"].encode("latin-1")}
                       for s in e["subs"]]
    if e.get("rets") is not None:
        out["rets"] = [r.encode("latin-1") for r in e["rets"]]
    return out
