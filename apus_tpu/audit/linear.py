"""Strict-serializability checker for captured KVS histories.

Algorithm: Wing & Gong's linearization search with the two standard
refinements Porcupine popularized —

- **P-compositionality**: the KVS model is a product of independent
  per-key registers, and a history is linearizable iff each per-key
  sub-history is (Herlihy & Wing's locality theorem), so the search is
  partitioned by key.  This turns one exponential search over N ops
  into many small ones, and a violation names its key.
- **Memoized state hashing**: a search node is (set of linearized ops,
  register value); revisiting an equivalent node via a different
  linearization order is pruned.  The done-set is a bitmask, so the
  memo key is an (int, bytes) pair.

**Transactions + typed ops (PR 12)**: multi-key transactions break
per-KEY partitioning — a txn is one atomic action on several keys at
once — but locality still applies at the granularity of the objects
the operations actually touch.  The checker therefore partitions keys
into CONNECTED COMPONENTS under "co-occur in some transaction": each
component is one composite object, checked by a generalized search
whose state is the component's key->value map and whose events are
atomic multi-sub-op actions (a single-key op is a 1-sub event; a
transaction is an N-sub event whose reads observe earlier same-txn
writes).  Strict serializability of the whole history = linearizability
of each component's sub-history (Herlihy & Wing, with components as
the objects) — and keys in NO transaction with only register ops keep
riding the original per-key fast path.  Typed replicated-data-type
ops (INCR/GETSET/SADD/SREM/SMEMBERS) are modeled by the SAME
``models.kvs.eval_subop`` the state machine executes, so the model
and the implementation cannot drift; a read-modify-write's observed
reply pins its pre-state (two INCRs both returning 1 is a lost
update — no valid order exists — and is REJECTED).

Ambiguity (Knossos/Porcupine "info" ops): an op whose ack was lost —
client timeout, crash mid-op, server error on a write — MAY have been
applied at any time after its invocation, or never.  Its response time
is +infinity (it real-time-precedes nothing) and linearizing it is
optional: the search succeeds once every CERTAIN op is linearized.
Ambiguous reads carry no information and are dropped.

Lease-served reads need no special casing here: the capture layer
records the client-observed interval, and a stale lease read (served
after a newer write was acked elsewhere) shows up as a read whose
observed value cannot be placed in any valid order — exactly the
violation class PR 3's lease machinery must never produce.

On violation the checker shrinks to a MINIMAL failing window (verified
at every step: each candidate window is re-checked, so the reported
window genuinely fails on its own).  Front-shrinking switches the
initial register value to "unknown" (the first read pins it), so a
window is never called a violation merely because its initial write
was shrunk away.

CLI: ``python -m apus_tpu.audit.linear history.jsonl`` re-checks an
exported history (the repro workflow printed by the campaigns).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

INF = float("inf")

#: Sentinel for "initial register value unknown" (front-shrunk windows).
_UNKNOWN = object()


@dataclasses.dataclass
class Violation:
    key: bytes
    #: minimal failing window (event dicts, sorted by t0) — verified
    #: non-linearizable on its own
    window: list
    #: True when the window was checked under an unknown initial value
    #: (front-shrunk); False when it starts at history start with the
    #: fresh-store initial value
    unknown_init: bool
    t_lo: float
    t_hi: float

    def describe(self) -> str:
        lines = [f"linearizability violation on key {self.key!r}: "
                 f"{len(self.window)} ops in "
                 f"[{self.t_lo:.6f}, {self.t_hi:.6f}]"
                 + (" (any initial value)" if self.unknown_init else "")]
        for e in self.window:
            t1 = e.get("t1")
            if e["op"] == "txn":
                subs = ", ".join(
                    f"{s['op']}({s['key']!r}"
                    + (f", {s['value']!r}" if s.get("value") else "")
                    + ")" for s in (e.get("subs") or []))
                body = f"txn[{subs}]" + (
                    f" rets={e['rets']!r}" if e.get("rets") is not None
                    else "")
            else:
                body = (f"{e['op']}({e['key']!r}"
                        + (f", {e['value']!r}"
                           if e.get("value") is not None else "")
                        + ")"
                        + (f" ret={e['ret']!r}"
                           if e.get("ret") is not None else ""))
            lines.append(
                f"  clt={e['clt']} req={e['req']} {body} "
                f"status={e['status']} "
                f"[{e['t0']:.6f}, {'inf' if t1 is None else f'{t1:.6f}'}]")
        return "\n".join(lines)


@dataclasses.dataclass
class AuditResult:
    ok: bool
    ops_checked: int
    keys: int
    violations: list
    #: keys whose search exhausted the node budget (no verdict — not
    #: counted as violations, but not proven clean either)
    undecided: list
    #: non-KVS ops skipped + ambiguous/error reads dropped
    skipped: int

    def describe(self) -> str:
        if self.ok and not self.undecided:
            return (f"linearizable: {self.ops_checked} ops over "
                    f"{self.keys} keys, 0 violations")
        parts = [v.describe() for v in self.violations]
        if self.undecided:
            parts.append(f"undecided keys (search budget): "
                         f"{self.undecided!r}")
        return "\n".join(parts) or "undecided"


# -- per-key search ---------------------------------------------------------

def _search(ops: list[tuple], init, max_nodes: int) -> str:
    """One Wing&Gong search.  ``ops``: (is_write, value, t0, t1,
    certain) sorted by t0; ``init``: initial register value (bytes) or
    _UNKNOWN.  Returns "ok" | "fail" | "undecided".

    The per-node frontier scan exploits the t0 sort: a pending op j
    can only disqualify a LATER-invoked candidate i (t1_j < t0_i
    needs t0_j <= t1_j < t0_i), so scanning pending ops in t0 order
    with a running min of their response times finds exactly the
    minimal ops, and the scan stops at the first op invoked after
    that running min — per-node cost is the CONCURRENCY window (plus
    still-pending ambiguous ops), not the history length.  ``lo``
    (first not-yet-linearized index) rides in the node so the scan
    skips the linearized prefix without walking the mask."""
    n = len(ops)
    if n == 0:
        return "ok"
    certain_mask = 0
    for i, o in enumerate(ops):
        if o[4]:
            certain_mask |= 1 << i
    if certain_mask == 0:
        return "ok"
    seen = {(0, init)}
    stack = [(0, 0, init)]
    nodes = 0
    while stack:
        mask, lo, state = stack.pop()
        if mask & certain_mask == certain_mask:
            return "ok"
        nodes += 1
        if nodes > max_nodes:
            return "undecided"
        while lo < n and (mask >> lo) & 1:
            lo += 1
        # Minimal pending ops (nothing pending really-precedes them).
        cands = []
        min_ret = INF
        i = lo
        while i < n:
            if not (mask >> i) & 1:
                o = ops[i]
                if o[2] > min_ret:
                    break               # sorted t0: no candidates beyond
                cands.append(i)
                if o[3] < min_ret:
                    min_ret = o[3]
            i += 1
        # Push ambiguous candidates first, certain ones last (LIFO pops
        # certain first): on a clean history the greedy certain-only
        # chain reaches the goal without ever popping the maybe-applied
        # branches, so ambiguity costs pushes, not exploration.
        for i in sorted(cands, key=lambda j: (ops[j][4], -ops[j][2])):
            is_write, value, _t0, _t1, _c = ops[i]
            if is_write:
                ns = value
            else:
                if state is _UNKNOWN:
                    ns = value          # first read pins the register
                elif state != value:
                    continue            # read can't observe this state
                else:
                    ns = state
            key = (mask | (1 << i), ns)
            if key not in seen:
                seen.add(key)
                stack.append((mask | (1 << i), lo, ns))
    return "fail"


def _to_search_ops(events: list[dict]) -> list[tuple]:
    """Event dicts -> search tuples, applying the ambiguity rules.
    Returns a list SORTED by t0; drops information-free ops."""
    out = []
    for e in events:
        op = e["op"]
        status = e["status"]
        t1 = e["t1"] if e.get("t1") is not None else INF
        if op in ("put", "delete"):
            # A delete is a write of the absent value; KVS reads of an
            # absent key observe b"", so absent IS b"" in the model.
            value = e["value"] if op == "put" else b""
            certain = status == "ok"
            out.append((True, value, e["t0"],
                        t1 if certain else INF, certain))
        elif op == "get":
            if status != "ok":
                continue                # no observation: no constraint
            out.append((False, e["value"] if e["value"] is not None
                        else b"", e["t0"], t1, True))
    out.sort(key=lambda o: (o[2], o[3]))
    return out


def _shrink(events: list[dict], init: bytes,
            max_nodes: int) -> tuple[list[dict], bool]:
    """Minimal failing window for a key that failed the main check.
    Every candidate is re-verified, so the returned window genuinely
    fails standalone.  Returns (window_events, unknown_init)."""
    evs = sorted(events, key=lambda e: e["t0"])

    def fails(sub: list[dict], ini) -> bool:
        return _search(_to_search_ops(sub), ini, max_nodes) == "fail"

    # Shrink from the end, geometrically (histories can be thousands of
    # ops; one-by-one would cost O(n) searches): halve the removal step
    # whenever the smaller window stops failing.
    step = max(1, len(evs) // 2)
    while len(evs) > 1:
        if len(evs) - step >= 1 and fails(evs[:-step], init):
            evs = evs[:-step]
        elif step > 1:
            step //= 2
        else:
            break
    # Shrink from the front the same way; any window not anchored at
    # history start must hold under ANY initial value or it is an
    # artifact of the dropped prefix.
    unknown = False
    step = max(1, len(evs) // 2)
    while len(evs) > 1:
        if len(evs) - step >= 1 and fails(evs[step:], _UNKNOWN):
            evs = evs[step:]
            unknown = True
        elif step > 1:
            step //= 2
        else:
            break
    return evs, unknown


# -- generalized (component) search: transactions + typed ops ---------------

#: register ops the per-key fast path understands
_REGISTER_OPS = ("put", "get", "delete")
#: typed read-modify-write ops: observed reply ("ret") pins pre-state
_RMW_OPS = ("incr", "getset", "sadd", "srem")
_READ_OPS = ("get", "smembers")
_ALL_OPS = _REGISTER_OPS + _RMW_OPS + ("smembers",)


def _event_subs(e: dict):
    """Normalize an event to its sub-op list [(op, key, arg, obs)]
    with obs the OBSERVED reply constraint (None = unconstrained), or
    None for an event the checker cannot model."""
    op = e["op"]
    if op == "txn":
        subs = e.get("subs") or []
        rets = e.get("rets")
        out = []
        for i, s in enumerate(subs):
            sop = s["op"]
            if sop not in _ALL_OPS:
                return None
            obs = rets[i] if (rets is not None and i < len(rets)) \
                else None
            if sop in ("put", "delete"):
                obs = None              # replies carry no information
            out.append((sop, s["key"], s["value"], obs))
        return out
    if op not in _ALL_OPS:
        return None
    if op in _READ_OPS:
        return [(op, e["key"], b"", e.get("value"))]
    if op in _RMW_OPS:
        return [(op, e["key"], e["value"], e.get("ret"))]
    return [(op, e["key"], e["value"], None)]


def _encode_sub(sop: str, key: bytes, arg) -> bytes:
    from apus_tpu.models import kvs
    if sop == "put":
        return kvs.encode_put(key, arg or b"")
    if sop == "get":
        return kvs.encode_get(key)
    if sop == "delete":
        return kvs.encode_delete(key)
    if sop == "incr":
        try:
            delta = int(arg) if arg else 1
        except ValueError:
            delta = 1
        return kvs.encode_incr(key, delta)
    if sop == "getset":
        return kvs.encode_getset(key, arg or b"")
    if sop == "sadd":
        return kvs.encode_sadd(key, arg or b"")
    if sop == "srem":
        return kvs.encode_srem(key, arg or b"")
    return kvs.encode_smembers(key)


def _transition(state: dict, subs, check_obs: bool):
    """Apply one atomic event's subs in order over ``state`` (a dict
    key -> bytes | _UNKNOWN).  Semantics come from the SAME
    ``models.kvs.eval_subop`` the state machine runs.  Returns the new
    state dict, or None when a certain observation contradicts it.
    _UNKNOWN values (front-shrunk windows) are pinned by reads and
    conservatively widened otherwise — lenient handling can only make
    a reported minimal window larger, never create a false
    violation."""
    from apus_tpu.models.kvs import eval_subop
    st = dict(state)
    for sop, key, arg, obs in subs:
        cur = st.get(key, b"")
        if cur is _UNKNOWN:
            if sop == "get":
                if check_obs and obs is not None:
                    st[key] = obs       # first read pins the register
                continue
            if sop == "smembers":
                if check_obs and obs is not None:
                    st[key] = obs       # canonical encoding pins it
                continue
            if sop in ("put", "getset"):
                st[key] = arg or b""
                continue
            if sop == "delete":
                st[key] = b""
                continue
            if sop == "incr":
                # Pinned by the observed new value when we have one;
                # otherwise the result is any int — stays unknown.
                if check_obs and obs is not None \
                        and obs != b"!notint":
                    st[key] = obs
                continue
            # sadd/srem on unknown membership: stays unknown (partial
            # set knowledge is not tracked; shrink-only leniency).
            continue
        try:
            _k, reply, write = eval_subop(
                lambda k, _s=st: (_s.get(k, b"")
                                  if _s.get(k, b"") is not _UNKNOWN
                                  else b""),
                _encode_sub(sop, key, arg))
        except ValueError:
            continue
        if check_obs and obs is not None and reply != obs:
            return None
        if write is not None:
            st[key] = write[1] if write[0] == "P" else b""
    return st


def _to_general_events(events: list[dict]):
    """Event dicts -> [(subs, t0, t1, certain, event)] sorted by t0,
    applying the ambiguity rules: certain = completed "ok"
    (observations checked); timed-out/errored events with any write
    sub are optional maybe-applied (observations ignored); ambiguous
    pure-read events carry no information and are dropped."""
    out = []
    for e in events:
        subs = _event_subs(e)
        if subs is None:
            continue
        certain = e["status"] == "ok"
        if not certain and all(s[0] in _READ_OPS for s in subs):
            continue
        t1 = e["t1"] if (certain and e.get("t1") is not None) else INF
        out.append((subs, e["t0"], t1, certain, e))
    out.sort(key=lambda o: (o[1], o[2]))
    return out


def _state_key(st: dict, keys: tuple) -> tuple:
    return tuple(st.get(k, b"") for k in keys)


def _general_search(gevents, comp_keys: tuple, init,
                    max_nodes: int) -> str:
    """Wing&Gong over atomic multi-sub-op events; state = the
    component's key->value map.  Frontier scan identical to the
    register search (t0-sorted, running min-response cutoff, ``lo``
    skips the linearized prefix).  ``init``: bytes (every key starts
    there — fresh store) or _UNKNOWN (front-shrunk windows)."""
    n = len(gevents)
    if n == 0:
        return "ok"
    certain_mask = 0
    for i, g in enumerate(gevents):
        if g[3]:
            certain_mask |= 1 << i
    if certain_mask == 0:
        return "ok"
    init_state = {k: init for k in comp_keys}
    seen = {(0, _state_key(init_state, comp_keys))}
    stack = [(0, 0, init_state)]
    nodes = 0
    while stack:
        mask, lo, state = stack.pop()
        if mask & certain_mask == certain_mask:
            return "ok"
        nodes += 1
        if nodes > max_nodes:
            return "undecided"
        while lo < n and (mask >> lo) & 1:
            lo += 1
        cands = []
        min_ret = INF
        i = lo
        while i < n:
            if not (mask >> i) & 1:
                g = gevents[i]
                if g[1] > min_ret:
                    break
                cands.append(i)
                if g[2] < min_ret:
                    min_ret = g[2]
            i += 1
        for i in sorted(cands, key=lambda j: (gevents[j][3],
                                              -gevents[j][1])):
            subs, _t0, _t1, certain, _e = gevents[i]
            ns = _transition(state, subs, check_obs=certain)
            if ns is None:
                continue
            key = (mask | (1 << i), _state_key(ns, comp_keys))
            if key not in seen:
                seen.add(key)
                stack.append((mask | (1 << i), lo, ns))
    return "fail"


def _shrink_general(events: list[dict], comp_keys: tuple, init,
                    max_nodes: int) -> tuple[list[dict], bool]:
    """Minimal failing window over a component's events — the same
    verified geometric shrink as the register path, with the all-keys
    _UNKNOWN initial state for front-shrunk windows."""
    evs = sorted(events, key=lambda e: e["t0"])

    def fails(sub: list[dict], ini) -> bool:
        return _general_search(_to_general_events(sub), comp_keys,
                               ini, max_nodes) == "fail"

    step = max(1, len(evs) // 2)
    while len(evs) > 1:
        if len(evs) - step >= 1 and fails(evs[:-step], init):
            evs = evs[:-step]
        elif step > 1:
            step //= 2
        else:
            break
    unknown = False
    step = max(1, len(evs) // 2)
    while len(evs) > 1:
        if len(evs) - step >= 1 and fails(evs[step:], _UNKNOWN):
            evs = evs[step:]
            unknown = True
        elif step > 1:
            step //= 2
        else:
            break
    return evs, unknown


def _classify(events: list[dict]):
    """Partition the history: (plain {key: [events]}, components
    [(keys_tuple, [events])], checked, skipped).  A key rides the
    per-key register fast path iff NO transaction touches it and
    every op on it is put/get/delete; keys co-occurring in a
    transaction union into one component (the composite object the
    locality theorem applies to), and a key with typed RDT ops forms
    at least a singleton component."""
    parent: dict[bytes, bytes] = {}

    def find(k: bytes) -> bytes:
        while parent.get(k, k) != k:
            parent[k] = parent.get(parent[k], parent[k])
            k = parent[k]
        return k

    def union(a: bytes, b: bytes) -> None:
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[max(ra, rb)] = min(ra, rb)

    key_events: dict[bytes, list] = {}
    txn_events: list[dict] = []
    general_keys: set[bytes] = set()
    skipped = 0
    checked = 0
    for e in events:
        op = e["op"]
        if op == "txn":
            subs = _event_subs(e)
            if subs is None or not subs:
                skipped += 1
                continue
            keys = sorted({s[1] for s in subs})
            for k in keys:
                parent.setdefault(k, k)
                general_keys.add(k)
            for k in keys[1:]:
                union(keys[0], k)
            txn_events.append(e)
            checked += 1
            continue
        if op not in _ALL_OPS:
            skipped += 1
            continue
        if op in _READ_OPS and e["status"] != "ok":
            skipped += 1
            continue
        key_events.setdefault(e["key"], []).append(e)
        if op not in _REGISTER_OPS:
            parent.setdefault(e["key"], e["key"])
            general_keys.add(e["key"])
        checked += 1
    plain: dict[bytes, list] = {}
    comp_keys: dict[bytes, list] = {}
    for k in general_keys:
        comp_keys.setdefault(find(k), []).append(k)
    comp_of: dict[bytes, bytes] = {}
    for root, ks in comp_keys.items():
        for k in ks:
            comp_of[k] = root
    for k, evs in key_events.items():
        root = comp_of.get(k)
        if root is None:
            plain[k] = evs
    comps: list[tuple] = []
    for root in sorted(comp_keys):
        ks = tuple(sorted(comp_keys[root]))
        evs = [e for k in ks for e in key_events.get(k, [])]
        evs += [e for e in txn_events
                if comp_of.get(_event_subs(e)[0][1]) == root]
        comps.append((ks, sorted(evs, key=lambda e: e["t0"])))
    return plain, comps, checked, skipped


# -- public API -------------------------------------------------------------

def check_history(events: list[dict], initial: bytes = b"",
                  max_nodes_per_key: int = 500_000) -> AuditResult:
    """Check a captured history (HistoryRecorder.events() /
    load_jsonl() shape) for strict serializability: per-key register
    search for keys no transaction touches, component-wise generalized
    search (transactions as atomic multi-sub-op events, typed RDT
    semantics from models.kvs.eval_subop) for the rest.  ``initial``
    is the fresh-store register value (b"" — a KVS get of a
    never-written key observes the empty value)."""
    plain, comps, checked, skipped = _classify(events)
    violations: list[Violation] = []
    undecided: list[bytes] = []
    nkeys = len(plain)
    for key, evs in sorted(plain.items()):
        ops = _to_search_ops(evs)
        verdict = _search(ops, initial, max_nodes_per_key)
        if verdict == "undecided":
            undecided.append(key)
            continue
        if verdict == "ok":
            continue
        window, unknown = _shrink(evs, initial, max_nodes_per_key)
        window = sorted(window, key=lambda e: e["t0"])
        t_hi = max((e["t1"] for e in window
                    if e.get("t1") is not None), default=INF)
        violations.append(Violation(
            key=key, window=window, unknown_init=unknown,
            t_lo=window[0]["t0"], t_hi=t_hi))
    for ks, evs in comps:
        nkeys += len(ks)
        rep = ks[0]
        verdict = _general_search(_to_general_events(evs), ks,
                                  initial, max_nodes_per_key)
        if verdict == "undecided":
            undecided.append(rep)
            continue
        if verdict == "ok":
            continue
        window, unknown = _shrink_general(evs, ks, initial,
                                          max_nodes_per_key)
        window = sorted(window, key=lambda e: e["t0"])
        t_hi = max((e["t1"] for e in window
                    if e.get("t1") is not None), default=INF)
        violations.append(Violation(
            key=rep, window=window, unknown_init=unknown,
            t_lo=window[0]["t0"], t_hi=t_hi))
    return AuditResult(ok=not violations, ops_checked=checked,
                       keys=nkeys, violations=violations,
                       undecided=undecided, skipped=skipped)


def resolve_undecided(events: list[dict], res: AuditResult,
                      initial: bytes = b"",
                      max_nodes_per_key: int = 8_000_000) -> AuditResult:
    """Offline retry of an AuditResult's UNDECIDED keys with a raised
    search budget (the known-environmental campaign flake: under
    full-suite load the per-key search can exhaust its node budget on a
    perfectly clean history — that is a missing VERDICT, not a
    violation, and must be reported as such, retried harder, and only
    escalated on a real failure).  Returns a merged result: retried
    keys that now verify drop off the undecided list; ones that fail
    become real violations; survivors stay undecided (the caller
    reports them distinctly and does NOT fail on them)."""
    if not res.undecided:
        return res
    plain, comps, _checked, _skipped = _classify(events)
    want = set(res.undecided)
    violations = list(res.violations)
    still: list[bytes] = []
    for key in res.undecided:
        if key in plain:
            evs = plain[key]
            verdict = _search(_to_search_ops(evs), initial,
                              max_nodes_per_key)
            if verdict == "ok":
                continue
            if verdict == "undecided":
                still.append(key)
                continue
            window, unknown = _shrink(evs, initial,
                                      max_nodes_per_key)
        else:
            unit = next(((ks, evs) for ks, evs in comps
                         if ks and ks[0] == key), None)
            if unit is None:
                continue              # classification moved; benign
            ks, evs = unit
            verdict = _general_search(_to_general_events(evs), ks,
                                      initial, max_nodes_per_key)
            if verdict == "ok":
                continue
            if verdict == "undecided":
                still.append(key)
                continue
            window, unknown = _shrink_general(evs, ks, initial,
                                              max_nodes_per_key)
        window = sorted(window, key=lambda e: e["t0"])
        t_hi = max((e["t1"] for e in window
                    if e.get("t1") is not None), default=INF)
        violations.append(Violation(
            key=key, window=window, unknown_init=unknown,
            t_lo=window[0]["t0"], t_hi=t_hi))
    del want
    return dataclasses.replace(res, ok=not violations,
                               violations=violations, undecided=still)


def check_jsonl(path: str, **kwargs) -> AuditResult:
    from apus_tpu.audit.history import HistoryRecorder
    return check_history(HistoryRecorder.load_jsonl(path), **kwargs)


def main(argv: Optional[list] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m apus_tpu.audit.linear",
        description="Re-check an exported history (campaign repro).")
    ap.add_argument("history", help="JSONL path (HistoryRecorder dump)")
    ap.add_argument("--max-nodes", type=int, default=500_000)
    args = ap.parse_args(argv)
    res = check_jsonl(args.history, max_nodes_per_key=args.max_nodes)
    print(res.describe())
    return 0 if res.ok and not res.undecided else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
