"""Linearizability checker for captured KVS histories.

Algorithm: Wing & Gong's linearization search with the two standard
refinements Porcupine popularized —

- **P-compositionality**: the KVS model is a product of independent
  per-key registers, and a history is linearizable iff each per-key
  sub-history is (Herlihy & Wing's locality theorem), so the search is
  partitioned by key.  This turns one exponential search over N ops
  into many small ones, and a violation names its key.
- **Memoized state hashing**: a search node is (set of linearized ops,
  register value); revisiting an equivalent node via a different
  linearization order is pruned.  The done-set is a bitmask, so the
  memo key is an (int, bytes) pair.

Ambiguity (Knossos/Porcupine "info" ops): an op whose ack was lost —
client timeout, crash mid-op, server error on a write — MAY have been
applied at any time after its invocation, or never.  Its response time
is +infinity (it real-time-precedes nothing) and linearizing it is
optional: the search succeeds once every CERTAIN op is linearized.
Ambiguous reads carry no information and are dropped.

Lease-served reads need no special casing here: the capture layer
records the client-observed interval, and a stale lease read (served
after a newer write was acked elsewhere) shows up as a read whose
observed value cannot be placed in any valid order — exactly the
violation class PR 3's lease machinery must never produce.

On violation the checker shrinks to a MINIMAL failing window (verified
at every step: each candidate window is re-checked, so the reported
window genuinely fails on its own).  Front-shrinking switches the
initial register value to "unknown" (the first read pins it), so a
window is never called a violation merely because its initial write
was shrunk away.

CLI: ``python -m apus_tpu.audit.linear history.jsonl`` re-checks an
exported history (the repro workflow printed by the campaigns).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

INF = float("inf")

#: Sentinel for "initial register value unknown" (front-shrunk windows).
_UNKNOWN = object()


@dataclasses.dataclass
class Violation:
    key: bytes
    #: minimal failing window (event dicts, sorted by t0) — verified
    #: non-linearizable on its own
    window: list
    #: True when the window was checked under an unknown initial value
    #: (front-shrunk); False when it starts at history start with the
    #: fresh-store initial value
    unknown_init: bool
    t_lo: float
    t_hi: float

    def describe(self) -> str:
        lines = [f"linearizability violation on key {self.key!r}: "
                 f"{len(self.window)} ops in "
                 f"[{self.t_lo:.6f}, {self.t_hi:.6f}]"
                 + (" (any initial value)" if self.unknown_init else "")]
        for e in self.window:
            t1 = e.get("t1")
            lines.append(
                f"  clt={e['clt']} req={e['req']} {e['op']}"
                f"({e['key']!r}"
                + (f", {e['value']!r}" if e.get("value") is not None
                   else "")
                + f") status={e['status']} "
                f"[{e['t0']:.6f}, {'inf' if t1 is None else f'{t1:.6f}'}]")
        return "\n".join(lines)


@dataclasses.dataclass
class AuditResult:
    ok: bool
    ops_checked: int
    keys: int
    violations: list
    #: keys whose search exhausted the node budget (no verdict — not
    #: counted as violations, but not proven clean either)
    undecided: list
    #: non-KVS ops skipped + ambiguous/error reads dropped
    skipped: int

    def describe(self) -> str:
        if self.ok and not self.undecided:
            return (f"linearizable: {self.ops_checked} ops over "
                    f"{self.keys} keys, 0 violations")
        parts = [v.describe() for v in self.violations]
        if self.undecided:
            parts.append(f"undecided keys (search budget): "
                         f"{self.undecided!r}")
        return "\n".join(parts) or "undecided"


# -- per-key search ---------------------------------------------------------

def _search(ops: list[tuple], init, max_nodes: int) -> str:
    """One Wing&Gong search.  ``ops``: (is_write, value, t0, t1,
    certain) sorted by t0; ``init``: initial register value (bytes) or
    _UNKNOWN.  Returns "ok" | "fail" | "undecided".

    The per-node frontier scan exploits the t0 sort: a pending op j
    can only disqualify a LATER-invoked candidate i (t1_j < t0_i
    needs t0_j <= t1_j < t0_i), so scanning pending ops in t0 order
    with a running min of their response times finds exactly the
    minimal ops, and the scan stops at the first op invoked after
    that running min — per-node cost is the CONCURRENCY window (plus
    still-pending ambiguous ops), not the history length.  ``lo``
    (first not-yet-linearized index) rides in the node so the scan
    skips the linearized prefix without walking the mask."""
    n = len(ops)
    if n == 0:
        return "ok"
    certain_mask = 0
    for i, o in enumerate(ops):
        if o[4]:
            certain_mask |= 1 << i
    if certain_mask == 0:
        return "ok"
    seen = {(0, init)}
    stack = [(0, 0, init)]
    nodes = 0
    while stack:
        mask, lo, state = stack.pop()
        if mask & certain_mask == certain_mask:
            return "ok"
        nodes += 1
        if nodes > max_nodes:
            return "undecided"
        while lo < n and (mask >> lo) & 1:
            lo += 1
        # Minimal pending ops (nothing pending really-precedes them).
        cands = []
        min_ret = INF
        i = lo
        while i < n:
            if not (mask >> i) & 1:
                o = ops[i]
                if o[2] > min_ret:
                    break               # sorted t0: no candidates beyond
                cands.append(i)
                if o[3] < min_ret:
                    min_ret = o[3]
            i += 1
        # Push ambiguous candidates first, certain ones last (LIFO pops
        # certain first): on a clean history the greedy certain-only
        # chain reaches the goal without ever popping the maybe-applied
        # branches, so ambiguity costs pushes, not exploration.
        for i in sorted(cands, key=lambda j: (ops[j][4], -ops[j][2])):
            is_write, value, _t0, _t1, _c = ops[i]
            if is_write:
                ns = value
            else:
                if state is _UNKNOWN:
                    ns = value          # first read pins the register
                elif state != value:
                    continue            # read can't observe this state
                else:
                    ns = state
            key = (mask | (1 << i), ns)
            if key not in seen:
                seen.add(key)
                stack.append((mask | (1 << i), lo, ns))
    return "fail"


def _to_search_ops(events: list[dict]) -> list[tuple]:
    """Event dicts -> search tuples, applying the ambiguity rules.
    Returns a list SORTED by t0; drops information-free ops."""
    out = []
    for e in events:
        op = e["op"]
        status = e["status"]
        t1 = e["t1"] if e.get("t1") is not None else INF
        if op in ("put", "delete"):
            # A delete is a write of the absent value; KVS reads of an
            # absent key observe b"", so absent IS b"" in the model.
            value = e["value"] if op == "put" else b""
            certain = status == "ok"
            out.append((True, value, e["t0"],
                        t1 if certain else INF, certain))
        elif op == "get":
            if status != "ok":
                continue                # no observation: no constraint
            out.append((False, e["value"] if e["value"] is not None
                        else b"", e["t0"], t1, True))
    out.sort(key=lambda o: (o[2], o[3]))
    return out


def _shrink(events: list[dict], init: bytes,
            max_nodes: int) -> tuple[list[dict], bool]:
    """Minimal failing window for a key that failed the main check.
    Every candidate is re-verified, so the returned window genuinely
    fails standalone.  Returns (window_events, unknown_init)."""
    evs = sorted(events, key=lambda e: e["t0"])

    def fails(sub: list[dict], ini) -> bool:
        return _search(_to_search_ops(sub), ini, max_nodes) == "fail"

    # Shrink from the end, geometrically (histories can be thousands of
    # ops; one-by-one would cost O(n) searches): halve the removal step
    # whenever the smaller window stops failing.
    step = max(1, len(evs) // 2)
    while len(evs) > 1:
        if len(evs) - step >= 1 and fails(evs[:-step], init):
            evs = evs[:-step]
        elif step > 1:
            step //= 2
        else:
            break
    # Shrink from the front the same way; any window not anchored at
    # history start must hold under ANY initial value or it is an
    # artifact of the dropped prefix.
    unknown = False
    step = max(1, len(evs) // 2)
    while len(evs) > 1:
        if len(evs) - step >= 1 and fails(evs[step:], _UNKNOWN):
            evs = evs[step:]
            unknown = True
        elif step > 1:
            step //= 2
        else:
            break
    return evs, unknown


# -- public API -------------------------------------------------------------

def check_history(events: list[dict], initial: bytes = b"",
                  max_nodes_per_key: int = 500_000) -> AuditResult:
    """Check a captured history (HistoryRecorder.events() /
    load_jsonl() shape) for linearizability against the per-key KVS
    register model.  ``initial`` is the fresh-store register value
    (b"" — a KVS get of a never-written key observes the empty
    value)."""
    by_key: dict[bytes, list[dict]] = {}
    skipped = 0
    checked = 0
    for e in events:
        if e["op"] not in ("put", "get", "delete"):
            skipped += 1
            continue
        if e["op"] == "get" and e["status"] != "ok":
            skipped += 1
            continue
        by_key.setdefault(e["key"], []).append(e)
        checked += 1
    violations: list[Violation] = []
    undecided: list[bytes] = []
    for key, evs in sorted(by_key.items()):
        ops = _to_search_ops(evs)
        verdict = _search(ops, initial, max_nodes_per_key)
        if verdict == "undecided":
            undecided.append(key)
            continue
        if verdict == "ok":
            continue
        window, unknown = _shrink(evs, initial, max_nodes_per_key)
        window = sorted(window, key=lambda e: e["t0"])
        t_hi = max((e["t1"] for e in window
                    if e.get("t1") is not None), default=INF)
        violations.append(Violation(
            key=key, window=window, unknown_init=unknown,
            t_lo=window[0]["t0"], t_hi=t_hi))
    return AuditResult(ok=not violations, ops_checked=checked,
                       keys=len(by_key), violations=violations,
                       undecided=undecided, skipped=skipped)


def resolve_undecided(events: list[dict], res: AuditResult,
                      initial: bytes = b"",
                      max_nodes_per_key: int = 8_000_000) -> AuditResult:
    """Offline retry of an AuditResult's UNDECIDED keys with a raised
    search budget (the known-environmental campaign flake: under
    full-suite load the per-key search can exhaust its node budget on a
    perfectly clean history — that is a missing VERDICT, not a
    violation, and must be reported as such, retried harder, and only
    escalated on a real failure).  Returns a merged result: retried
    keys that now verify drop off the undecided list; ones that fail
    become real violations; survivors stay undecided (the caller
    reports them distinctly and does NOT fail on them)."""
    if not res.undecided:
        return res
    by_key: dict[bytes, list[dict]] = {}
    want = set(res.undecided)
    for e in events:
        if e["op"] not in ("put", "get", "delete"):
            continue
        if e["op"] == "get" and e["status"] != "ok":
            continue
        if e["key"] in want:
            by_key.setdefault(e["key"], []).append(e)
    violations = list(res.violations)
    still: list[bytes] = []
    for key in res.undecided:
        evs = by_key.get(key, [])
        verdict = _search(_to_search_ops(evs), initial,
                          max_nodes_per_key)
        if verdict == "ok":
            continue
        if verdict == "undecided":
            still.append(key)
            continue
        window, unknown = _shrink(evs, initial, max_nodes_per_key)
        window = sorted(window, key=lambda e: e["t0"])
        t_hi = max((e["t1"] for e in window
                    if e.get("t1") is not None), default=INF)
        violations.append(Violation(
            key=key, window=window, unknown_init=unknown,
            t_lo=window[0]["t0"], t_hi=t_hi))
    return dataclasses.replace(res, ok=not violations,
                               violations=violations, undecided=still)


def check_jsonl(path: str, **kwargs) -> AuditResult:
    from apus_tpu.audit.history import HistoryRecorder
    return check_history(HistoryRecorder.load_jsonl(path), **kwargs)


def main(argv: Optional[list] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m apus_tpu.audit.linear",
        description="Re-check an exported history (campaign repro).")
    ap.add_argument("history", help="JSONL path (HistoryRecorder dump)")
    ap.add_argument("--max-nodes", type=int, default=500_000)
    args = ap.parse_args(argv)
    res = check_jsonl(args.history, max_nodes_per_key=args.max_nodes)
    print(res.describe())
    return 0 if res.ok and not res.undecided else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
