"""Pure protocol core: deterministic, transport-agnostic consensus logic.

This is the layer the reference never separated out: in APUS the protocol
rules live inline in src/dare/dare_server.c (election, commit, apply,
pruning) entangled with RDMA posting code.  Here they are pure functions
and small state classes so they can be (a) property-tested without hardware,
(b) lowered onto the JAX device plane, and (c) driven by the host control
plane.
"""

from apus_tpu.core.types import EntryType, Role, ServerType
from apus_tpu.core.sid import Sid
from apus_tpu.core.cid import Cid, CidState
from apus_tpu.core.log import LogEntry, SlotLog
from apus_tpu.core.quorum import quorum_size, have_majority, commit_index

__all__ = [
    "EntryType", "Role", "ServerType", "Sid", "Cid", "CidState",
    "LogEntry", "SlotLog", "quorum_size", "have_majority", "commit_index",
    "Node", "NodeConfig",
]


def __getattr__(name):
    # Node imports the transport abstraction, which imports core.log —
    # resolve lazily to keep `from apus_tpu.core import Node` working.
    if name in ("Node", "NodeConfig"):
        from apus_tpu.core import node
        return getattr(node, name)
    raise AttributeError(name)
