"""Configuration identifier (CID): group membership + joint-consensus resize.

Parity with the reference's membership model (dare_config.h:17-45):
a configuration is ``{epoch, size[2], state, bitmask}`` where ``state``
implements a joint-consensus-style resize:

- STABLE:   one group of ``size[0]`` servers; single majority.
- EXTENDED: the group grew to ``size[1]`` slots, but agreement is still
  against the *old* majority only (new slots don't vote yet).
- TRANSIT:  both the old-size and new-size majorities must agree
  (dual-majority; cf. wait_for_majority j-loop dare_ibv_rc.c:2799-2957).

Transitions (dare_ibv_ud.c:1024-1037, dare_server.c:1888-1930):
add server into empty slot: bitmask bit set (no resize needed);
add server when full: STABLE -> EXTENDED (epoch+1) -> on commit TRANSIT
-> on commit STABLE with size=new size.  Remove: bit cleared, and if it
was the highest slot the group can later shrink.
"""

from __future__ import annotations

import dataclasses
import enum

from apus_tpu.core.types import MAX_SERVER_COUNT


class CidState(enum.IntEnum):
    STABLE = 0
    EXTENDED = 1
    TRANSIT = 2


@dataclasses.dataclass(frozen=True)
class Cid:
    epoch: int = 0
    state: CidState = CidState.STABLE
    size: int = 0          # current agreed group size
    new_size: int = 0      # target size during EXTENDED/TRANSIT resize
    bitmask: int = 0       # bit i set => slot i is an active member

    # -- queries ----------------------------------------------------------

    def contains(self, idx: int) -> bool:
        return bool(self.bitmask >> idx & 1)

    def members(self) -> list[int]:
        return [i for i in range(MAX_SERVER_COUNT) if self.contains(i)]

    @property
    def group_size(self) -> int:
        return self.size

    @property
    def extended_group_size(self) -> int:
        """Size including not-yet-voting slots (EXTENDED/TRANSIT resize)."""
        return self.new_size if self.state != CidState.STABLE else self.size

    def majorities(self) -> tuple[int, ...]:
        """Quorum thresholds that must *all* be met to agree.

        STABLE/EXTENDED: single majority of ``size`` (EXTENDED agreement is
        against the old majority only, dare_config.h:19-21).  TRANSIT: both
        old-size and new-size majorities (dual-majority).
        """
        first = self.size // 2 + 1
        if self.state == CidState.TRANSIT:
            return (first, self.new_size // 2 + 1)
        return (first,)

    def empty_slot(self) -> int | None:
        """Lowest inactive slot below the extended size, if any."""
        for i in range(self.extended_group_size):
            if not self.contains(i):
                return i
        return None

    # -- transitions ------------------------------------------------------

    def with_server(self, idx: int) -> "Cid":
        return dataclasses.replace(self, bitmask=self.bitmask | (1 << idx))

    def without_server(self, idx: int) -> "Cid":
        return dataclasses.replace(self, bitmask=self.bitmask & ~(1 << idx))

    def extend(self, new_size: int) -> "Cid":
        """STABLE -> EXTENDED with a larger slot count (epoch bump)."""
        if self.state != CidState.STABLE:
            raise ValueError("can only extend a STABLE configuration")
        if not self.size < new_size <= MAX_SERVER_COUNT:
            raise ValueError(f"bad new size {new_size}")
        return dataclasses.replace(self, epoch=self.epoch + 1,
                                   state=CidState.EXTENDED, new_size=new_size)

    def to_transit(self) -> "Cid":
        if self.state != CidState.EXTENDED:
            raise ValueError("TRANSIT requires EXTENDED")
        return dataclasses.replace(self, epoch=self.epoch + 1,
                                   state=CidState.TRANSIT)

    def stabilize(self) -> "Cid":
        """TRANSIT -> STABLE at the new size."""
        if self.state != CidState.TRANSIT:
            raise ValueError("stabilize requires TRANSIT")
        return dataclasses.replace(self, epoch=self.epoch + 1,
                                   state=CidState.STABLE,
                                   size=self.new_size, new_size=0)

    def abort_extend(self) -> "Cid":
        """EXTENDED -> STABLE at the OLD size, dropping every new slot
        (epoch bump).  The clean-abort arm of the resize ladder: a
        joiner that dies before catching up would otherwise pin the
        configuration in EXTENDED forever (TRANSIT waits for its acks,
        auto-removal refuses non-STABLE configs).  Safe under the
        EXTENDED agreement rule — new slots never voted, so reverting
        to the old member set changes no quorum anybody counted."""
        if self.state != CidState.EXTENDED:
            raise ValueError("abort_extend requires EXTENDED")
        return dataclasses.replace(
            self, epoch=self.epoch + 1, state=CidState.STABLE,
            new_size=0, bitmask=self.bitmask & ((1 << self.size) - 1))

    @staticmethod
    def initial(size: int) -> "Cid":
        return Cid(epoch=0, state=CidState.STABLE, size=size,
                   bitmask=(1 << size) - 1)

    def __repr__(self) -> str:
        return (f"Cid(e{self.epoch} {self.state.name} n={self.size}"
                f"{'->' + str(self.new_size) if self.new_size else ''}"
                f" mask={self.bitmask:b})")


def equal_membership(a: Cid, b: Cid) -> bool:
    return (a.epoch, a.state, a.size, a.new_size, a.bitmask) == \
           (b.epoch, b.state, b.size, b.new_size, b.bitmask)
