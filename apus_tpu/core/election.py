"""Pure election rules (vote granting, up-to-date checks, timeouts).

Parity targets in the reference: start_election (dare_server.c:1264-1322),
poll_vote_requests' up-to-date log check (dare_server.c:1591-1652),
poll_vote_count (dare_server.c:1327-1518), randomized election timeout
(random_election_timeout, dare_server.c:1237) and the adaptive heartbeat
timeout (to_adjust_cb, dare_server.c:763-817).
"""

from __future__ import annotations

import dataclasses

from apus_tpu.core.sid import Sid


@dataclasses.dataclass(frozen=True)
class VoteRequest:
    """Candidate's vote request (the vote_req[] ctrl slot payload,
    ctrl_data_t dare_server.h:123-140).

    ``prevote`` marks a PreVote probe (Raft §9.6, an addition over the
    reference): the would-be candidate asks whether it COULD win at
    ``sid.term`` without anyone adopting that term.  Pre-grants are
    non-binding and cause no voter state change, so a partitioned or
    flapping replica can never inflate cluster terms or depose a healthy
    leader — the failure mode the reference leaves to its adaptive
    timeouts to avoid."""

    sid_word: int          # candidate SID [term|0|idx]
    last_idx: int          # determinant of candidate's last log entry
    last_term: int
    cid_epoch: int
    prevote: bool = False

    @property
    def sid(self) -> Sid:
        return Sid.unpack(self.sid_word)


def log_up_to_date(cand_last_idx: int, cand_last_term: int,
                   own_last_idx: int, own_last_term: int) -> bool:
    """Raft/DARE up-to-date rule: candidate's log must not be behind ours
    (term first, then index; dare_server.c:1591-1652)."""
    if cand_last_term != own_last_term:
        return cand_last_term > own_last_term
    return cand_last_idx >= own_last_idx


def should_grant(req: VoteRequest, own_sid: Sid,
                 own_last_idx: int, own_last_term: int,
                 known_leader: bool, lease_guard: bool = False) -> bool:
    """Whether a voter grants ``req``.

    - never vote backwards in term;
    - within our current term, never switch votes (own_sid.idx records whom
      we adopted; a same-term request from a different candidate is refused);
    - ignore candidates while we believe a leader is alive
      (dare_server.c:1535 — mitigates disruptive servers);
    - with ``lease_guard`` (leader read leases enabled, Raft §6.4):
      refuse real votes at ANY term while the leader is alive — the
      lease's safety rests on "no quorum can elect before every lease
      quorum member has been silent for hb_timeout", which the
      term-bounded refusal alone does not give (a candidate holding
      stale pre-grants may request a higher-term vote the instant the
      leader recovers);
    - candidate log must be up-to-date.
    """
    cand = req.sid
    if cand.term < own_sid.term:
        return False
    if cand.term == own_sid.term and (known_leader or cand.idx != own_sid.idx):
        return False
    if known_leader and (lease_guard or cand.term <= own_sid.term):
        return False
    return log_up_to_date(req.last_idx, req.last_term,
                          own_last_idx, own_last_term)


def best_vote_request(requests: list[VoteRequest]) -> VoteRequest | None:
    """Among simultaneous requests pick the highest (term, idx) SID
    (best-SID scan, dare_server.c:1558-1575)."""
    if not requests:
        return None
    return max(requests, key=lambda r: (r.sid.term, r.last_term, r.last_idx,
                                        -r.sid.idx))


def random_election_timeout(rng, low: float, high: float) -> float:
    """Uniform in [low, high) (dare_server.c:1237)."""
    return low + (high - low) * rng.random()


class AdaptiveTimeout:
    """Adaptive heartbeat-timeout estimator (to_adjust_cb analog,
    dare_server.c:763-817).

    Starts from a base timeout and grows it whenever a false positive is
    observed (a heartbeat arrived, but later than the current timeout
    would have tolerated), until the false-positive rate drops below
    ``fp_target``; then freezes.
    """

    def __init__(self, base: float, growth: float = 1.2,
                 fp_target: float = 1e-4, min_samples: int = 100):
        self.timeout = base
        self.growth = growth
        self.fp_target = fp_target
        self.min_samples = min_samples
        self.samples = 0
        self.false_positives = 0
        self.frozen = False

    def observe(self, hb_gap: float) -> None:
        if self.frozen:
            return
        self.samples += 1
        if hb_gap > self.timeout:
            self.false_positives += 1
            self.timeout *= self.growth
        # Freeze only once the sample count can actually attest a rate
        # below fp_target: 0 fps in 100 samples says nothing about a
        # 1e-4 target — freezing there would lock the base timeout in
        # before the first expected false positive could ever occur.
        need = max(self.min_samples, int(round(1.0 / self.fp_target)))
        if (self.samples >= need and
                self.false_positives / self.samples < self.fp_target):
            self.frozen = True
