"""Client-endpoint database: dedup + pending linearizable reads.

Parity with the reference's ep_db (dare_ep_db.c, dare_ep_db.h:20-46):
an rbtree of non-member endpoints keyed by LID, deduplicating join and
client requests via ``last_req_id``/``committed`` and holding pending
linearizable reads (``wait_for_idx``) that are answered only after the
commit index passes the registration point AND leadership has been
re-verified (ep_dp_reply_read_req dare_ep_db.c:132-161,
rc_verify_leadership dare_ibv_rc.c:1182-1280).

Redesign notes:
- keyed by ``clt_id`` (a stable client/session id) rather than IB LID;
- dedup state is *derived from the replicated log* on apply, so a new
  leader reconstructs it and client retries stay exactly-once across
  failovers (the reference gets this implicitly because commands carry
  ``req_id``/``clt_id`` in the log entry, dare_log.h:38-40);
- dedup is EXACT over a sliding window of the last ``WINDOW`` applied
  req_ids per client, not merely monotone.  A pipelined client's
  stream legally applies with HOLES: an elastic MIGRATING bounce (or a
  leader change mid-burst) makes the client retry op N individually
  while ops N+1.. from the same burst commit first, and a reply to a
  cross-group op consumes a req_id this group never sees at all.  The
  reference's monotone rule (``req_id <= last_req_id`` => duplicate)
  would answer such a retry from the cache of a DIFFERENT, later
  request — acking a write that never applied (a lost update, caught
  as a stale read by the linearizability checker; churn seed 9480).
  An in-window req_id that was never applied here is a hole and
  re-enters admission fresh; only an exact hit answers from cache.
- the committed reply is cached per applied request in the window so a
  duplicate of an already-committed request is answered without
  re-executing it — with ITS OWN reply, never a later request's.

Requests below the window floor (``last_req_id - WINDOW``) cannot be
classified exactly any more; they conservatively answer from the
highwater cache, as the reference does.  That path is unreachable for
live clients: a client only ever retries ops inside its in-flight
pipeline window (<= 64 ops, ApusClient.pipeline_window), far smaller
than WINDOW.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class Endpoint:
    """One client endpoint (dare_ep_t analog, dare_ep_db.h:20-31)."""

    clt_id: int
    last_req_id: int = 0          # highest req_id APPLIED for this client
    last_idx: int = 0             # log index of that request
    last_reply: Optional[bytes] = None
    # join-request dedup (used by the membership service)
    committed: bool = False
    #: exact applied window: req_id -> (idx, reply) for every applied
    #: request above the eviction floor (EndpointDB.WINDOW wide)
    applied: dict = dataclasses.field(default_factory=dict)
    #: req_ids <= evict_floor have been evicted from ``applied``
    evict_floor: int = 0


@dataclasses.dataclass
class DupHit:
    """Exact-window duplicate: the applied request's OWN idx/reply.
    Field names mirror Endpoint so dedup consumers (Node.submit, the
    apply path, the txn plane) read either shape identically."""

    last_req_id: int
    last_idx: int
    last_reply: Optional[bytes]


@dataclasses.dataclass
class PendingRead:
    """A registered linearizable read (wait_for_idx analog)."""

    clt_id: int
    req_id: int
    data: bytes
    wait_idx: int                 # answer only once apply >= wait_idx
    registered_at: float = 0.0    # tick clock at registration
    done: bool = False
    error: bool = False           # query raised: answered as an error
    reply: Optional[bytes] = None
    #: Follower-lease read (Node.follower_read): served from a
    #: follower's local applied state while its commit-index-bounded
    #: lease is live; ``refused`` resolves the handle when the lease
    #: lapses/invalidates — the client handler answers NOT_LEADER with
    #: a hint and the client falls back to the leader.
    flr: bool = False
    refused: bool = False
    #: Hash bucket of a follower read's key (core.node._read_bucket):
    #: served under a bucket-scoped lease only while the granted read
    #: set covers it.  None = no bucket discipline (bucket leases off);
    #: -1 = unroutable payload (full-set leases only).
    bucket: "int | None" = None


class EndpointDB:
    """In-memory endpoint table (std dict replaces the kernel rbtree the
    reference vendors, utils/rbtree/)."""

    #: Exact-dedup span: per client, the last WINDOW applied req_ids
    #: are tracked individually (reply cached per request).  Must
    #: exceed any client's maximum in-flight pipeline depth so a
    #: retried op is never below the floor (64 in ApusClient; 16x
    #: headroom).  The native plane's reply cache uses the same span.
    WINDOW = 1024

    def __init__(self) -> None:
        self._eps: dict[int, Endpoint] = {}

    def search(self, clt_id: int) -> Optional[Endpoint]:
        return self._eps.get(clt_id)

    def insert(self, clt_id: int) -> Endpoint:
        ep = self._eps.get(clt_id)
        if ep is None:
            ep = Endpoint(clt_id)
            self._eps[clt_id] = ep
        return ep

    def erase(self, clt_id: int) -> None:
        self._eps.pop(clt_id, None)

    def __len__(self) -> int:
        return len(self._eps)

    # -- write dedup ------------------------------------------------------

    def duplicate_of_applied(self, clt_id: int, req_id: int) \
            -> "Optional[DupHit | Endpoint]":
        """If (clt_id, req_id) itself was already applied, return its
        cached idx/reply (a :class:`DupHit`); else None.  An in-window
        req_id below the highwater that was NOT applied is a hole
        (bounced/re-routed out of a pipelined burst) and is NOT a
        duplicate — answering it from a later request's cache would
        ack a write that never happened.  Below the window floor the
        highwater endpoint answers conservatively (ancient duplicate;
        unreachable for live clients, see module docstring)."""
        ep = self._eps.get(clt_id)
        if ep is None:
            return None
        hit = ep.applied.get(req_id)
        if hit is not None:
            idx, reply = hit
            return DupHit(req_id, idx, reply)
        if 0 < req_id <= ep.evict_floor and req_id <= ep.last_req_id:
            return ep
        return None

    def note_applied(self, clt_id: int, req_id: int, idx: int,
                     reply: Optional[bytes]) -> None:
        """Record an applied request (called from the apply path, so every
        replica — and any future leader — has identical dedup state)."""
        ep = self.insert(clt_id)
        if req_id > ep.evict_floor:
            ep.applied[req_id] = (idx, reply)
        if req_id >= ep.last_req_id:
            ep.last_req_id = req_id
            ep.last_idx = idx
            ep.last_reply = reply
            ep.committed = True
            floor = req_id - self.WINDOW
            if floor > ep.evict_floor:
                if floor - ep.evict_floor > 3 * self.WINDOW:
                    # Huge highwater jump: rebuild instead of walking
                    # the gap one req_id at a time.
                    ep.applied = {r: v for r, v in ep.applied.items()
                                  if r > floor}
                else:
                    for r in range(ep.evict_floor + 1, floor + 1):
                        ep.applied.pop(r, None)
                ep.evict_floor = floor

    # -- snapshot support --------------------------------------------------

    def dump(self) -> list:
        """Dedup state for inclusion in snapshots: without it, a
        duplicate request straddling a snapshot boundary (first instance
        inside, retry after) would double-apply on the installer.  Each
        record carries the FULL applied window — the highwater alone
        would turn every in-window hole into a false duplicate on the
        installer (exactly the monotone-rule bug this class fixes)."""
        return [(ep.clt_id, ep.last_req_id, ep.last_idx, ep.last_reply,
                 sorted((r, iv[0], iv[1])
                        for r, iv in ep.applied.items()))
                for ep in self._eps.values()]

    def load(self, entries: list) -> None:
        for rec in entries:
            if len(rec) >= 5:
                clt_id, req_id, idx, reply, window = rec[:5]
            else:                 # legacy 4-tuple record (no window)
                clt_id, req_id, idx, reply = rec[:4]
                window = [(req_id, idx, reply)] if req_id else []
            for r, i, rep in window:
                self.note_applied(clt_id, r, i, rep)
            # Join-only endpoints (committed flag, no applied window)
            # and the highwater itself when the window list is empty.
            if req_id:
                self.note_applied(clt_id, req_id, idx, reply)
            else:
                self.insert(clt_id)
