"""Client-endpoint database: dedup + pending linearizable reads.

Parity with the reference's ep_db (dare_ep_db.c, dare_ep_db.h:20-46):
an rbtree of non-member endpoints keyed by LID, deduplicating join and
client requests via ``last_req_id``/``committed`` and holding pending
linearizable reads (``wait_for_idx``) that are answered only after the
commit index passes the registration point AND leadership has been
re-verified (ep_dp_reply_read_req dare_ep_db.c:132-161,
rc_verify_leadership dare_ibv_rc.c:1182-1280).

Redesign notes:
- keyed by ``clt_id`` (a stable client/session id) rather than IB LID;
- dedup state is *derived from the replicated log* on apply, so a new
  leader reconstructs it and client retries stay exactly-once across
  failovers (the reference gets this implicitly because commands carry
  ``req_id``/``clt_id`` in the log entry, dare_log.h:38-40);
- the last committed reply is cached per endpoint so a duplicate of an
  already-committed request is answered without re-executing it.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class Endpoint:
    """One client endpoint (dare_ep_t analog, dare_ep_db.h:20-31)."""

    clt_id: int
    last_req_id: int = 0          # highest req_id APPLIED for this client
    last_idx: int = 0             # log index of that request
    last_reply: Optional[bytes] = None
    # join-request dedup (used by the membership service)
    committed: bool = False


@dataclasses.dataclass
class PendingRead:
    """A registered linearizable read (wait_for_idx analog)."""

    clt_id: int
    req_id: int
    data: bytes
    wait_idx: int                 # answer only once apply >= wait_idx
    registered_at: float = 0.0    # tick clock at registration
    done: bool = False
    error: bool = False           # query raised: answered as an error
    reply: Optional[bytes] = None
    #: Follower-lease read (Node.follower_read): served from a
    #: follower's local applied state while its commit-index-bounded
    #: lease is live; ``refused`` resolves the handle when the lease
    #: lapses/invalidates — the client handler answers NOT_LEADER with
    #: a hint and the client falls back to the leader.
    flr: bool = False
    refused: bool = False


class EndpointDB:
    """In-memory endpoint table (std dict replaces the kernel rbtree the
    reference vendors, utils/rbtree/)."""

    def __init__(self) -> None:
        self._eps: dict[int, Endpoint] = {}

    def search(self, clt_id: int) -> Optional[Endpoint]:
        return self._eps.get(clt_id)

    def insert(self, clt_id: int) -> Endpoint:
        ep = self._eps.get(clt_id)
        if ep is None:
            ep = Endpoint(clt_id)
            self._eps[clt_id] = ep
        return ep

    def erase(self, clt_id: int) -> None:
        self._eps.pop(clt_id, None)

    def __len__(self) -> int:
        return len(self._eps)

    # -- write dedup ------------------------------------------------------

    def duplicate_of_applied(self, clt_id: int,
                             req_id: int) -> Optional[Endpoint]:
        """If (clt_id, req_id) was already applied, return the endpoint
        (whose cached reply answers the duplicate); else None.  Client
        req_ids are per-client monotone, as in the reference
        (handle_server_join_request dedup, dare_ibv_ud.c:988-1006)."""
        ep = self._eps.get(clt_id)
        if ep is not None and req_id <= ep.last_req_id:
            return ep
        return None

    def note_applied(self, clt_id: int, req_id: int, idx: int,
                     reply: Optional[bytes]) -> None:
        """Record an applied request (called from the apply path, so every
        replica — and any future leader — has identical dedup state)."""
        ep = self.insert(clt_id)
        if req_id >= ep.last_req_id:
            ep.last_req_id = req_id
            ep.last_idx = idx
            ep.last_reply = reply
            ep.committed = True

    # -- snapshot support --------------------------------------------------

    def dump(self) -> list[tuple[int, int, int, Optional[bytes]]]:
        """Dedup state for inclusion in snapshots: without it, a
        duplicate request straddling a snapshot boundary (first instance
        inside, retry after) would double-apply on the installer."""
        return [(ep.clt_id, ep.last_req_id, ep.last_idx, ep.last_reply)
                for ep in self._eps.values()]

    def load(self, entries: list[tuple[int, int, int, Optional[bytes]]]) \
            -> None:
        for clt_id, req_id, idx, reply in entries:
            self.note_applied(clt_id, req_id, idx, reply)
