"""The replicated log, redesigned TPU-first as fixed-width slots.

The reference log is a 64 MB byte-addressed circular buffer of
variable-length entries with wrap-around splitting and offset arithmetic
(dare_log.h:76-103, entry splitting dare_ibv_rc.c:1532-1545, tail scans
dare_log.h:402-457).  None of that survives contact with XLA: dynamic
byte offsets mean dynamic shapes.

Redesign: the log is ``n_slots`` fixed-width slots and a log *index* is a
monotonically increasing integer; entry ``idx`` lives in slot
``idx % n_slots``.  Offsets head/apply/commit/tail/end collapse into four
absolute indices (``tail`` is just ``end - 1``), every "offset comparison"
helper of the reference (log_offset_end_distance, log_is_offset_larger,
dare_log.h:249-282) becomes integer comparison, and the device mirror of
this structure is a pair of dense arrays ``[n_slots, slot_bytes] u8`` +
``[n_slots, META] i32`` with O(1) static-shape addressing
(see apus_tpu.ops.logplane).

Oversized requests (up to MAX_REQUEST_BYTES, message.h:7) are segmented
across consecutive slots by the proxy layer and reassembled on apply
(see apus_tpu.core.segment).

Invariants (checked by ``check()``)::

    head <= apply <= commit <= end          (index order)
    end - head <= n_slots                   (capacity)
    terms are non-decreasing in [head, end)
    idx stored in slot equals the absolute index

Pruning keeps the reference's P1-P3 properties (dare_server.c:2004-2023):
the head only advances to an index that every live replica has applied,
via HEAD entries that are themselves committed through the log.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

from apus_tpu.core.cid import Cid
from apus_tpu.core.types import DEFAULT_LOG_SLOTS, EntryType


@dataclasses.dataclass
class LogEntry:
    """One log record (parity with dare_log_entry_t, dare_log.h:33-47).

    ``data`` is opaque bytes for CSM entries; CONFIG entries carry a Cid in
    ``cid``; HEAD entries carry the new head index in ``head``.  The
    reference's in-entry ``reply[13]`` ack bytes (remotely written by
    followers, dare_ibv_rc.c:1828-1863) become ``ack_mask`` — on the device
    plane this is the psum'd vote bitmask, not remotely-poked memory.
    """

    idx: int
    term: int
    req_id: int = 0
    clt_id: int = 0
    type: EntryType = EntryType.CSM
    data: bytes = b""
    cid: Optional[Cid] = None
    head: int = 0
    ack_mask: int = 0

    def determinant(self) -> tuple[int, int]:
        """(idx, term) — uniquely identifies the entry for log adjustment
        (parity with dare_log_entry_det_t, dare_log.h:51-56)."""
        return (self.idx, self.term)


class LogFullError(RuntimeError):
    pass


class SlotLog:
    """Fixed-slot replicated log with absolute-index offsets."""

    def __init__(self, n_slots: int = DEFAULT_LOG_SLOTS, first_idx: int = 1):
        if n_slots <= 0:
            raise ValueError("n_slots must be positive")
        self.n_slots = n_slots
        # Absolute indices.  Entry indices start at 1 (reference:
        # log_append_entry assigns idx = last+1 starting from 1,
        # dare_log.h:488), so a fresh log has head=apply=commit=end=1.
        self.head = first_idx
        self.apply = first_idx
        self.commit = first_idx
        self.end = first_idx
        self._slots: list[Optional[LogEntry]] = [None] * n_slots
        #: Entry-placement observer (``callable(entry)``), fired on BOTH
        #: entry paths — leader ``append`` and follower ``write`` — the
        #: one choke point every entry crosses to enter this log.  The
        #: per-bucket follower-lease machinery (core.node) hangs its
        #: bucket-footprint tracking here; None costs nothing.
        self.on_entry = None

    # -- basic queries ----------------------------------------------------

    def __len__(self) -> int:
        return self.end - self.head

    @property
    def is_empty(self) -> bool:
        return self.end == self.head

    @property
    def is_full(self) -> bool:
        return self.end - self.head >= self.n_slots

    def near_full(self, headroom: int = 2) -> bool:
        """Full up to a reserve of ``headroom`` slots.  Client-entry
        appends stop HERE, not at is_full: a log driven completely full
        would have no slot left for the HEAD (pruning) entry that frees
        space — a permanent wedge (pruning itself appends,
        log_pruning dare_server.c:1996-2067)."""
        return self.end - self.head >= self.n_slots - headroom

    @property
    def tail(self) -> int:
        """Index of the last entry (or head-1 if empty)."""
        return self.end - 1

    def slot_of(self, idx: int) -> int:
        return idx % self.n_slots

    def get(self, idx: int) -> Optional[LogEntry]:
        if not self.head <= idx < self.end:
            return None
        e = self._slots[self.slot_of(idx)]
        assert e is None or e.idx == idx, f"slot holds {e and e.idx}, want {idx}"
        return e

    def last_entry(self) -> Optional[LogEntry]:
        return self.get(self.end - 1)

    def last_determinant(self) -> tuple[int, int]:
        e = self.last_entry()
        return e.determinant() if e else (self.end - 1, 0)

    def entries(self, start: int, stop: Optional[int] = None) -> Iterable[LogEntry]:
        stop = self.end if stop is None else min(stop, self.end)
        for i in range(max(start, self.head), stop):
            e = self.get(i)
            if e is not None:
                yield e

    # -- append / write ---------------------------------------------------

    def append(self, term: int, req_id: int = 0, clt_id: int = 0,
               type: EntryType = EntryType.CSM, data: bytes = b"",
               cid: Optional[Cid] = None, head: int = 0) -> int:
        """Leader-side append (parity with log_append_entry,
        dare_log.h:466-558).  Returns the new entry's index."""
        if self.is_full:
            raise LogFullError(f"log full: head={self.head} end={self.end}")
        idx = self.end
        entry = LogEntry(idx=idx, term=term, req_id=req_id, clt_id=clt_id,
                         type=type, data=data, cid=cid, head=head)
        self._slots[self.slot_of(idx)] = entry
        self.end = idx + 1
        if self.on_entry is not None:
            self.on_entry(entry)
        return idx

    def write(self, entry: LogEntry) -> None:
        """Follower-side placement of a replicated entry at its index
        (the receive side of the leader's one-sided log write,
        update_remote_logs dare_ibv_rc.c:1460-1644).  The caller is
        responsible for having adjusted ``end`` to ``entry.idx`` first."""
        if entry.idx != self.end:
            raise ValueError(f"non-contiguous write: idx={entry.idx} end={self.end}")
        if self.is_full:
            raise LogFullError("follower log full")
        self._slots[self.slot_of(entry.idx)] = entry
        self.end = entry.idx + 1
        if self.on_entry is not None:
            self.on_entry(entry)

    def truncate(self, new_end: int) -> None:
        """Discard entries >= new_end (log adjustment SET_END step,
        dare_ibv_rc.c:1292-1451).  Committed entries are never discarded."""
        if new_end < self.commit:
            raise ValueError(f"cannot truncate committed entries "
                             f"(new_end={new_end} < commit={self.commit})")
        if new_end < self.end:
            for i in range(new_end, self.end):
                self._slots[self.slot_of(i)] = None
            self.end = new_end

    # -- offset advancement ----------------------------------------------

    def advance_commit(self, new_commit: int) -> int:
        """Monotonic commit advance; clamped to end."""
        self.commit = min(max(self.commit, new_commit), self.end)
        return self.commit

    def advance_apply(self, new_apply: int) -> int:
        self.apply = min(max(self.apply, new_apply), self.commit)
        return self.apply

    def advance_head(self, new_head: int) -> None:
        """Prune entries below new_head (log_pruning, dare_server.c:1996-2067).

        P1: only applied entries are pruned (new_head <= apply).
        P2/P3 (every live replica has applied them; HEAD entry committed
        first) are enforced by the caller (Node.maybe_prune)."""
        if new_head > self.apply:
            raise ValueError(f"pruning unapplied entries: {new_head} > {self.apply}")
        for i in range(self.head, new_head):
            self._slots[self.slot_of(i)] = None
        self.head = max(self.head, new_head)

    def reset(self, first_idx: int) -> None:
        """Re-base an (effectively discarded) log at ``first_idx`` —
        snapshot installation: everything below is covered by the
        snapshot, everything at/above will be re-replicated (the
        reference sets log->apply to the snapshot's last-entry offset
        after rc_recover_sm, dare_server.c:657-704)."""
        self.head = self.apply = self.commit = self.end = first_idx
        self._slots = [None] * self.n_slots

    # -- log adjustment (NC-buffer algorithm) -----------------------------

    def nc_determinants(self) -> list[tuple[int, int]]:
        """Determinants of all not-committed entries (the NC-buffer the
        leader reads during adjustment, log_entries_to_nc_buf
        dare_log.h:339-359)."""
        return [e.determinant() for e in self.entries(self.commit)]

    def find_divergence(self, remote_nc: list[tuple[int, int]],
                        remote_commit: int) -> int:
        """Leader-side: first index at which the remote log diverges from
        ours (log_find_remote_end_offset, dare_log.h:367-394).  The remote
        should truncate to the returned index and we replicate from there."""
        expect = remote_commit
        for (idx, term) in remote_nc:
            assert idx == expect, "NC determinants must be contiguous"
            local = self.get(idx)
            if local is None or local.term != term:
                return idx
            expect = idx + 1
        return expect

    # -- invariant check (for property tests) ------------------------------

    def check(self) -> None:
        assert self.head <= self.apply <= self.commit <= self.end, \
            (self.head, self.apply, self.commit, self.end)
        assert self.end - self.head <= self.n_slots
        prev_term = 0
        for i in range(self.head, self.end):
            e = self._slots[self.slot_of(i)]
            assert e is not None and e.idx == i, f"hole/mismatch at {i}"
            assert e.term >= prev_term, "terms must be non-decreasing"
            prev_term = e.term

    def __repr__(self) -> str:
        return (f"SlotLog(h={self.head} a={self.apply} c={self.commit} "
                f"e={self.end}/{self.n_slots})")
