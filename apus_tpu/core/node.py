"""The replica state machine: roles, election, replication, commit, apply.

This is the pure-logic re-expression of the reference's event loop server
(dare_server.c — election :1264-1743, commit :1751-1790, apply :1815-1974,
pruning :1996-2122, heartbeats :822-993, failure counting :1189-1227).
It owns no I/O: all remote effects go through a one-sided
``Transport`` and all timing comes from the caller-supplied clock, so the
same class runs under the deterministic simulator, the host control plane,
and (for the data plane) delegates the commit math to the jitted device
step.

Differences from the reference, by design (TPU-first):
- the log is fixed-width slots addressed by absolute index
  (apus_tpu.core.log), so "log adjustment" degenerates to an integer
  divergence search instead of a 4-step offset FSM
  (cf. dare_ibv_rc.c:1292-1451);
- fencing is explicit ``(granted_to, fence_term)`` on the log region
  instead of QP resets (cf. dare_ibv_rc.c:2156-2255) — the same predicate
  the jitted commit step evaluates as a term mask;
- commit is computed from per-replica ack *indices* (match-index form),
  which is exactly the psum-able quantity of the device plane, rather
  than per-entry remotely-poked reply bytes (cf. dare_ibv_rc.c:1650-1758).
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional

from apus_tpu.core.cid import Cid, CidState
from apus_tpu.core.election import (AdaptiveTimeout, VoteRequest,
                                    best_vote_request,
                                    random_election_timeout, should_grant)
from apus_tpu.core.epdb import EndpointDB, PendingRead
from apus_tpu.core.log import LogEntry, SlotLog
from apus_tpu.core.quorum import have_majority, quorum_size
from apus_tpu.core.sid import AtomicSid, Sid
from apus_tpu.core.types import (DEFAULT_LOG_SLOTS, MAX_SERVER_COUNT,
                                 PERMANENT_FAILURE, EntryType, Role)
from apus_tpu.core import segment
from apus_tpu.models.sm import (REFUSED_REPLY_PREFIX, Snapshot,
                                StateMachine)
from apus_tpu.obs.metrics import MetricsRegistry
from apus_tpu.parallel.transport import (Region, Regions, Transport,
                                         WriteResult)


@dataclasses.dataclass
class NodeConfig:
    """Timing + sizing knobs (nodes.local.cfg analog, config-dare.c:5-44)."""

    idx: int
    n_slots: int = DEFAULT_LOG_SLOTS
    hb_period: float = 0.010          # leader heartbeat period (10 ms DEBUG)
    hb_timeout: float = 0.050         # follower: declare leader dead after
    elect_low: float = 0.100          # election timeout range (100-300 ms)
    elect_high: float = 0.300
    prune_period: float = 0.500       # leader pruning cadence
    apply_report_period: float = 0.050
    max_batch: int = 64               # entries per replication write
    seed: int = 0
    # Failure detector: a dead peer is removed after PERMANENT_FAILURE
    # failures counted at most once per fail_window (the reference's
    # CTRL-QP errors surface only after RDMA retry exhaustion —
    # seconds — so its 2-strike rule means "continuously dead for a
    # while", never "mid crash-restart cycle"; the default matches
    # ClusterSpec.fail_window).
    auto_remove: bool = True
    fail_window: float = 0.500
    # Adaptive failure detector (to_adjust_cb analog,
    # dare_server.c:763-817): grow hb_timeout from observed heartbeat
    # gaps until the false-positive rate is negligible, then freeze.
    # Keeps GIL-jittery deployments from spurious elections without
    # hand-tuning hb_timeout per environment.
    adaptive_timeout: bool = True
    # Recovery start: a restarted/joining replica must not campaign
    # before making contact with the group — its stale log cannot win,
    # but its vote requests bump terms and depose live leaders in a
    # self-sustaining storm (each deposition delays the catch-up that
    # would end it).  The reference runs recovery before election
    # participation for the same reason (dare_server.c:738-745).  A
    # fallback timeout preserves liveness when the whole group restarts.
    recovery_start: bool = False
    # Record segmentation (core.segment): commands larger than this are
    # split into chunk entries at submit and reassembled at apply, so
    # the reference's full 87,380 B request envelope (message.h:7) fits
    # the fixed-slot device plane (DeviceCommitRunner.max_data_bytes is
    # the sizing contract).  0 disables splitting (payloads ride whole,
    # device-ineligible when oversized).
    seg_chunk: int = 0
    # Leader read lease (the Hermes-style local-read optimization):
    # while the lease holds, linearizable reads are answered from the
    # leader's applied state WITHOUT the per-read majority round
    # (_verify_leadership).  Renewal: a heartbeat round whose writes a
    # quorum acknowledged — with the ack's echoed SID proving the peer
    # was still at our term — extends the lease to round-start +
    # hb_timeout * (1 - lease_margin).  Safety (proven under the
    # FaultPlane e2e): the peer server stamps _last_hb_seen at HB
    # delivery, and EVERY voter — lease_guard is unconditional, so a
    # config-skewed voter cannot void the leader's lease — refuses
    # real votes while within hb_timeout of a heartbeat, so any new
    # leader's election happens
    # >= round-start + hb_timeout — after every lease granted from that
    # round expired.  lease_margin absorbs clock-RATE drift between the
    # replicas' monotonic clocks over the (tiny) lease window plus
    # scheduling skew.
    read_lease: bool = True
    lease_margin: float = 0.2
    # Follower read leases (the read scale-out half of the Hermes
    # design point: writes invalidate, reads are local EVERYWHERE).
    # The leader grants a follower a commit-index-bounded read lease in
    # reply to the follower's own request (OP_FLR_LEASE, piggybacking
    # the quorum-acked heartbeat machinery):
    #
    # - ANCHORING (the delayed-grant trap): the follower's validity
    #   window starts at its own REQUEST-SEND stamp, never at grant
    #   delivery — the leader's conservative window (anchored at its
    #   RECEIPT of the request, which real-time-follows the send) then
    #   always outlives the follower's, regardless of wire delay.  A
    #   delivery-anchored lease would let a delayed grant outlive every
    #   guard (the same trap the leader lease avoids by anchoring at
    #   round START).
    # - DURATION: the remaining leader-lease window (_lease_until -
    #   now), so every follower window nests inside the leader's own
    #   lease — and the UNCONDITIONAL vote-refusal lease guard
    #   (should_grant lease_guard) that proves no election completes
    #   inside the leader's lease therefore proves it for every
    #   outstanding follower lease too.  That nesting IS the "elections
    #   cannot complete inside any follower lease window" extension.
    # - INVALIDATION (writes): while a granted window is live on the
    #   leader's clock, commit does not advance past an index the
    #   grantee has not acked (_advance_commit blocker rule) — the
    #   Hermes write-invalidation, expressed on the log.  A paused or
    #   partitioned lease holder therefore stalls commit for at most
    #   one lease duration, after which it is cut out.
    # - SERVING (follower side): a local read is served only while the
    #   fresh-clock lease is live, at the SAME term and config epoch it
    #   was granted, the config is STABLE, and applied state covers
    #   max(grant's commit floor, the follower's log end at read
    #   registration) — the floor covers everything committed before
    #   the grant, the log-end gate covers everything whose commit
    #   required our ack during the window.
    follower_read_leases: bool = True
    # Bucket-granular follower leases (Hermes proper: per-KEY write
    # invalidation, quantized to the elastic plane's 840 hash buckets).
    # A follower's lease request carries the bucket set its flowing
    # reads actually touch; the grant binds to that set, and commit
    # only waits for a holder's ack on entries whose written buckets
    # INTERSECT one of its live granted sets — a slow holder reading
    # cold keys no longer stalls every write in the group, and a
    # hot-key write stream no longer gates every cold-key follower
    # read behind its apply.  The follower serve rule narrows the same
    # way: a bucket-b read waits on max(grant floor, b's own log tail)
    # instead of the whole log end (see follower_read).  False =
    # whole-log gating (the pre-bucket behavior, kept as the measured
    # baseline: APUS_FLR_BUCKETS=0).
    flr_bucket_leases: bool = True
    #: Deliberately-broken lease for the planted-stale-read harness
    #: (set from APUS_FLR_PLANT by the daemon; NEVER in production):
    #: "expiry" skips the fresh-clock expiry check, "epoch" skips the
    #: config-epoch fence, "bucket" skips the granted-read-set
    #: membership check (serves a bucket the grant never covered) —
    #: matched by SUBSTRING so plants compose ("bucket,expiry" holds
    #: the lease open while the bucket check is the bypassed guard).
    #: Each makes the audit plane's checker the only thing standing
    #: between the bug and a stale read, which is exactly what the
    #: harness proves it catches.
    flr_plant: str = ""


#: Sentinel bucket for reads whose payload has no routable key (non-KVS
#: query shapes): they can only be served under a FULL-set lease.
BUCKET_UNROUTABLE = -1


def entry_bucket_footprint(e: "LogEntry"):
    """Bucket footprint of a log entry — the hash buckets its APPLY can
    write — for the per-bucket follower-lease invalidation rule.

    Returns a frozenset of buckets (possibly empty: the entry writes
    nothing, e.g. NOOP/HEAD blanks or pure reads) or ``None`` =
    UNKNOWN, which callers must treat as "touches every bucket"
    (conservative: commit then waits for every live lease holder,
    exactly the whole-log rule).  Unknown covers CONFIG entries,
    migration records, segment chunk envelopes, and every transaction
    record except TM — a TC install mutates keys the record itself
    does not name, so only the self-contained TM batch (all sub-op
    keys in the payload) and plain single-key commands are exact.
    Supersets are always safe; only a MISSING written bucket would be
    a correctness bug."""
    if e.type in (EntryType.NOOP, EntryType.HEAD):
        return frozenset()
    if e.type != EntryType.CSM or not e.data:
        return None
    data = e.data
    if data[:1] == b"T" and data[:2] != b"TM":
        return None
    from apus_tpu.models.kvs import cmd_is_read, decode_keys
    from apus_tpu.runtime.router import bucket_of_key
    try:
        keys = decode_keys(data)
    except Exception:                                    # noqa: BLE001
        return None
    if keys is None:
        return None
    if not keys:
        # Keyless-but-parsed: nothing here writes a routable key.
        return frozenset() if cmd_is_read(data) else None
    return frozenset(bucket_of_key(k) for k in keys)


@dataclasses.dataclass
class PendingJoin:
    """A join request in flight (CONFIG entry appended, awaiting apply);
    the handle the membership service waits on before sending the
    CFG_REPLY analog (handle_server_join_request -> ud_send_clt_reply,
    dare_ibv_ud.c:972-1068, :1451-1498)."""

    addr: str
    slot: int
    entry_idx: Optional[int] = None
    done: bool = False
    #: The CONFIG entry applied but the slot is NOT in the applied
    #: configuration (a resize abort raced the join): the handler must
    #: answer "retry", never "admitted" — a joiner told "admitted at
    #: slot s" after the abort would boot straight into exclusion.
    refused: bool = False


@dataclasses.dataclass
class PendingRequest:
    """A client request waiting for commit (tailq element analog,
    message.h:5-23)."""

    req_id: int
    clt_id: int
    data: bytes
    idx: Optional[int] = None         # log index once appended
    reply: Optional[bytes] = None     # SM reply once applied
    #: Earlier chunk payloads of a segmented record (core.segment),
    #: consumed by _drain_pending ahead of ``data`` (the final chunk).
    chunks: Optional[list[bytes]] = None


class Node:
    """One replica.  Drive with ``tick(now)``; submit requests with
    ``submit``; read committed results from the state machine."""

    def __init__(self, cfg: NodeConfig, cid: Cid, sm: StateMachine,
                 transport: Transport):
        self.cfg = cfg
        self.idx = cfg.idx
        self.cid = cid
        self.sm = sm
        self.t = transport
        self.log = SlotLog(cfg.n_slots)
        self.regions = Regions()          # our remotely-writable memory
        self.sid = AtomicSid(Sid.pack(0, False, cfg.idx))
        self.role = Role.FOLLOWER
        self.rng = random.Random(cfg.seed * 1000003 + cfg.idx)

        # timers
        self._last_hb_seen = 0.0
        #: True once ANY group traffic reached us this incarnation (a
        #: leader heartbeat or a candidate's vote round) — an evicted
        #: replica receives neither, so the daemon's boot-time exclusion
        #: probe keys off this instead of heartbeat AGE (whose initial
        #: value is a future-stamped election grace).
        self.group_contact = False
        self._hb_timeout = cfg.hb_timeout
        self._hb_adapt = (AdaptiveTimeout(cfg.hb_timeout)
                          if cfg.adaptive_timeout else None)
        self._next_hb_send = 0.0
        self._election_deadline: Optional[float] = None
        self._prevote_deadline: Optional[float] = None
        self._next_prune = 0.0
        self._next_apply_report = 0.0

        # leader state
        self._peer_applied: dict[int, tuple] = {} # last applied det read
        self._next_idx: dict[int, int] = {}       # per-follower next entry
        self._commit_sent: dict[int, int] = {}    # lazy remote-commit writes
        self._adjusted: dict[int, bool] = {}      # log adjustment done?
        self._ack_progress: dict[int, tuple] = {} # stale-match detection
        self._fail_count: dict[int, int] = {}     # CTRL failure counter
        self._fail_last: dict[int, float] = {}    # last counted failure time
        self._pending_head: Optional[int] = None  # HEAD entry in flight
        self._term_start_idx = 0                  # idx of our term's blank entry
        self._term_blank_pending = False          # deferred by a full log

        # client requests + endpoint db (dare_ep_db.c analog)
        self._pending: list[PendingRequest] = []
        self._inflight: dict[tuple[int, int], PendingRequest] = {}
        self._pending_reads: list[PendingRead] = []
        self.epdb = EndpointDB()
        # Segmented-record reassembly (core.segment): apply-side chunk
        # buffer, deterministic across replicas.
        self._seg = segment.Reassembler()
        # Leadership proofs are ordered by a registration COUNTER, not
        # the tick clock: a proof stamped at tick-time T could tie with
        # a read registered between ticks and be mistaken for "after".
        self._reg_seq = 0
        self._leader_verified_seq = -1
        self.committed_upcalls: list[LogEntry] = []   # drained by runtime
        # Applied CONFIG entries for the runtime (peer-table updates on
        # join/resize; the CFG_REPLY + poll_config_entries analog).
        self.config_upcalls: list[LogEntry] = []
        # In-flight join requests by joiner address (ep_db join dedup
        # analog, dare_ep_db.h:20-31 / handle_server_join_request).
        self._pending_joins: dict[str, PendingJoin] = {}
        # Why the last handle_join returned None while we WERE leader —
        # the membership service reads it (under the same lock) to
        # answer a typed refusal instead of a misleading NOT_LEADER
        # that sends the joiner hint-chasing a leader it already found.
        self.last_join_refusal: Optional[str] = None
        # In-flight operator-initiated removals (OP_LEAVE) by slot,
        # resolved when their CONFIG entry applies.
        self._pending_leaves: dict[int, PendingJoin] = {}
        # Graceful-leave drain: set by the runtime once OUR removal is
        # committed cluster-wide — this replica stops voting/acking and
        # never campaigns again (the runtime exits it cleanly).
        self.draining = False
        # Incarnation fencing (removed-member hygiene): ``incarnation``
        # is the epoch of the CONFIG that admitted THIS tenancy of our
        # slot (0 for initial members; joiners adopt the admission
        # cid's epoch), sent with every outbound ctrl write on the live
        # wire.  ``fence_epochs[slot]`` is the epoch of the latest
        # applied CONFIG that REMOVED that slot; the peer server drops
        # inbound ctrl writes whose incarnation is below it — so a
        # stale ex-member's REP_ACK/vote can never be credited to the
        # slot's next occupant (nor count while the slot is empty).
        # Deterministic replicated state: derived from applied CONFIG
        # entries, carried by snapshots (Snapshot.fence) for installers
        # that skip the entries.
        self.incarnation = 0
        self.fence_epochs: dict[int, int] = {}
        # Applied member addresses (from join CONFIG payloads): lets a
        # retried join whose reply was lost be answered idempotently
        # instead of admitting the same address into a second slot.
        self._member_addrs: dict[str, int] = {}
        # Installed snapshots awaiting the runtime (persistence must
        # record them or a restart would replay a store missing the
        # snapshot prefix).
        self.snapshot_upcalls: list[tuple[Snapshot, list]] = []
        # (snap, ep_dump, cid, member_addrs) — valid while snap.last_idx+1
        # >= log.head (see make_snapshot).
        self._snap_cache: Optional[tuple[Snapshot, list, Cid, dict]] = None
        self._snap_stream_cache: Optional[tuple] = None
        # Background snapshot streaming (runtime deployments set
        # async_snap_push=True): a chunked push takes seconds at deep
        # history, and running it inline would hold THIS replica's tick
        # thread — heartbeats included — for the duration.  A push
        # thread per target peer runs the stream (the transport is
        # peer-locked and the chunk reads are generation-fenced preads,
        # both thread-safe); the tick loop consumes completions.  The
        # sim keeps the inline path (deterministic, no threads).
        self.async_snap_push = False
        # Spool dir for INBOUND snapshot streams (resumable partial
        # assembly; see onesided._snap_spool_path).  The daemon points
        # it at its durable-store dir so a partial transfer survives a
        # receiver restart; None = tempfile (in-process clusters:
        # resumable only within this process).
        self.snap_spool_dir: Optional[str] = None
        self._snap_pushing: set[int] = set()
        #: peer -> (term_at_start, result, pushed_last_idx, push_gen)
        self._snap_push_done: dict[int, tuple] = {}
        # Wedge watchdog for background pushes: a stream to a peer that
        # died mid-transfer normally errors out within a few bounded
        # chunk roundtrips, but the push SLOT must never be held
        # hostage by a pathological stall — while a peer is in
        # _snap_pushing the tick thread skips it entirely, so a wedged
        # thread would silently stop replication to that slot's next
        # incarnation forever.  After SNAP_PUSH_STALL_S the slot is
        # abandoned: the generation bumps (the late completion is
        # ignored) and normal adjustment resumes.
        self._snap_push_started: dict[int, float] = {}
        self._snap_push_gen: dict[int, int] = {}
        # Determinant of the last applied entry — the snapshot anchor
        # (snapshot_t.last_entry analog, dare_log.h:107-112); survives
        # pruning, unlike log.get(apply-1).
        self._applied_det: tuple[int, int] = (0, 0)
        # True while a TRANSIT CONFIG entry is in flight (guards against
        # re-appending it every tick during EXTENDED catch-up).
        self._transit_pending = False
        self._known_leader: Optional[int] = None
        # Device-plane handoff: when True, the commit decision is owned
        # by the jitted device quorum (runtime.device_plane) and the
        # host ack-quorum rule stands down — mirroring how the
        # reference's commit is owned by the RDMA reply scan
        # (dare_ibv_rc.c:1650-1758), with the host path kept as the
        # fallback the driver can re-enable.
        self.external_commit = False
        # First log index covered by the device plane (set by the
        # driver alongside external_commit).  For covered spans the
        # leader's TCP writes carry only the commit offset — entry
        # bodies travel via the device scatter + follower shard drain —
        # unless a peer's ack stalls (drain not landing: diverged
        # follower, no driver, wedged runner), in which case TCP entry
        # shipping resumes for that peer.  This mirrors the reference's
        # split: entries via RDMA data plane, commit offsets lazily
        # written (dare_ibv_rc.c:1760-1826).
        self.device_covered_from: Optional[int] = None
        self._drain_wait: dict[int, tuple] = {}
        # Election-time log reconciliation (set by the device-plane
        # driver): called before this node grants a real vote or
        # campaigns, so its host log first absorbs every entry its
        # device shard holds.  Without this, a voter whose host log
        # trails its shard could elect a leader lacking device-committed
        # entries — the device quorum attests SHARD placement, so the
        # shard must count as the log for election up-to-dateness
        # (exactly as the reference's recovery reads the same memory
        # its RDMA writes landed in, rc_recover_log dare_ibv_rc.c:726).
        self.pre_election_hook = None
        # EXTENDED-resize stall watchdog: (new-slot ack snapshot, since)
        # — drives the clean abort in _maybe_advance_resize.
        self._resize_stall: Optional[tuple] = None
        # Contact gate for recovery starts (see NodeConfig.recovery_start).
        self._await_contact = cfg.recovery_start
        self._contact_deadline: Optional[float] = None
        self._now = 0.0                     # last tick clock (sim-safe)
        # Fresh clock for SAFETY-side time checks (lease validity).
        # The tick-start stamp ``_now`` goes stale exactly when it
        # matters: the heartbeat fan-out blocks on wire roundtrips with
        # the node lock yielded — precisely while an isolated leader's
        # ctrl writes time out — and a stale (smaller) clock makes
        # ``now < _lease_until`` pass MORE easily, not less.  Live
        # deployments install the daemon's per-process clock here
        # (ReplicaDaemon sets its SkewClock — real monotonic unless the
        # adversarial-time nemesis skews it; utils/clock.py); the
        # deterministic sim leaves it None and the single-threaded tick
        # clock is exact.
        self.clock: Optional[Callable[[], float]] = None
        # Leader read lease (NodeConfig.read_lease): valid while
        # fresh-now < _lease_until.  Renewed by quorum-acked heartbeat
        # rounds in _send_heartbeats; cleared on any role change.
        self._lease_until = -1.0
        # Monotone count of completed linearizable reads (lease or
        # verified) — the daemon's wake predicate keys off it so a
        # served read always wakes its waiting handler even when
        # apply/role are otherwise unchanged that tick.  Follower-lease
        # reads AND their refusals bump it too (both resolve a parked
        # handler).
        self.reads_done = 0
        # -- follower read leases (NodeConfig.follower_read_leases) ----
        # Leader side: peer -> list of live granted WINDOWS, each
        # ``(until, buckets)`` with ``until`` the conservative expiry
        # on OUR fresh clock (receipt-anchored + margin, so it
        # real-time-outlives the grantee's own window under
        # margin-bounded rate drift) and ``buckets`` the granted READ
        # SET (frozenset of hash buckets; None = every bucket — the
        # whole-log grant shape).  While any window is live,
        # _advance_commit requires the grantee's ack before passing an
        # entry whose written buckets intersect that window's set (the
        # per-key Hermes write invalidation, quantized to buckets).  A
        # LIST because renewals may narrow/shift the set while an
        # older window is still live at the holder — every live
        # window's set keeps binding until its own expiry.  Pruned by
        # time only — membership changes must keep blocking until
        # expiry or a not-yet-aware removed holder could serve stale.
        self._fgrants: dict[int, list] = {}
        # peer -> fresh-clock stamp of the last commit advance its
        # missing ack held back.  Liveness guard: a holder that blocks
        # commit is refused RENEWAL until it catches up, so a peer
        # whose inbound link died (asymmetric partition: our entries
        # dropped, its requests arriving) stalls writes for at most ONE
        # lease window instead of renewing itself into a permanent
        # write outage.
        self._flr_blocked_at: dict[int, float] = {}
        # Follower side: the currently-held lease tuple.  All adopted
        # atomically from one grant; validity is _flease_ok.
        self._flease_until = -1.0
        self._flease_term = -1
        self._flease_epoch = -1
        self._flease_floor = 0
        self._flease_dur = 0.0
        # Granted read set of the held lease (frozenset of buckets;
        # None = every bucket).  A read is served under the lease only
        # when its key's bucket is IN this set — the leader's commit
        # rule only waited for our ack on those buckets' writes.
        self._flease_buckets = None
        # Demand tracking for the NEXT lease request: bucket -> fresh-
        # clock stamp of the last follower read that wanted it.  The
        # request ships the recently-wanted set as a 105-byte bitmap
        # (runtime.flr); entries idle past FLR_WANT_WINDOW decay out.
        # A read with no routable key forces full-set requests for a
        # want-window (it can only be served under a full-set lease).
        self._flr_want: dict[int, float] = {}
        self._flr_want_full_until = -1.0
        # Set by runtimes whose serve path cannot check per-key bucket
        # membership (the native data plane's C read gate): leases are
        # then requested FULL-set, trading back the per-bucket commit
        # relief for native-path serving.
        self.flr_full_buckets = False
        # Entry-placement bucket tails, BOTH roles (fed by the
        # SlotLog.on_entry hook): bucket -> end-like index just past
        # the last log entry whose footprint touches it, and the same
        # for UNKNOWN-footprint entries (which count for every
        # bucket).  The follower serve rule for a bucket-b read waits
        # on max(grant floor, _bucket_tails[b], _bucket_tail_all)
        # instead of the whole log end — a hot-key write stream no
        # longer gates cold-key follower reads behind its apply.
        # Over-approximation is safe (truncated entries leave a stale
        # high tail: the read just waits longer); a missing tail for a
        # log-resident write would be the bug, and the hook fires on
        # every entry path (append AND follower write).
        self._bucket_tails: dict[int, int] = {}
        self._bucket_tail_all = 0
        # idx -> footprint cache for the leader's commit-cap walk over
        # (commit, end] (computing footprints per tick would re-parse
        # every uncommitted payload); pruned below commit lazily.
        self._entry_buckets: dict[int, object] = {}
        self._entry_buckets_prunes = 0
        # Leader per-bucket COMMIT floors for bucket-scoped grants:
        # bucket -> end-like index just past the last committed entry
        # touching it (same shape for unknown-footprint entries), fed
        # incrementally from the commit cursor below.  A grant for
        # read set S carries floor = max over S — with a hot writer
        # OUTSIDE S, a cold-bucket grant's floor stays at the last
        # cold write instead of chasing the hot commit index.
        self._bucket_commits: dict[int, int] = {}
        self._bucket_commit_all = 0
        self._bucket_commit_cursor = 0
        if cfg.flr_bucket_leases:
            self.log.on_entry = self._note_entry_buckets
        # Reads parked on the lease (serve once applied covers them).
        self._flr_pending: list[PendingRead] = []
        # Lease-keeping is LAZY: requested only while follower reads
        # are actually flowing (hot window), so idle clusters and
        # leader-only workloads pay nothing.
        self._flr_hot_until = -1.0
        self._flr_next_req = 0.0
        self._flr_req_inflight = False
        self._flr_noted = False       # flight-recorder grant/lapse edge
        # Fresh-leadership commit hold-off (see become_leader): commit
        # may not advance before this stamp, so follower-lease windows
        # granted by an unknown predecessor expire first.
        self._flr_holdoff_until = -1.0
        #: Wire hook installed by the runtime (runtime.flr): callable
        #: (leader_idx) -> grant dict or None, one bounded roundtrip
        #: with the node lock yielded on the wire.  None on the
        #: deterministic sim — follower leases then never engage.
        self.lease_requester = None

        # -- multi-group (Multi-Raft) seams --------------------------------
        # Consensus-group id of this node within its daemon (0 = the
        # primary group; purely informational for logging/obs — the
        # protocol itself is group-oblivious, the runtime demuxes).
        self.gid = 0
        # Coalesced-heartbeat sink, installed by the multi-group
        # runtime (runtime/groupset.py): when set, _send_heartbeats
        # REGISTERS this group's round with the daemon-level coalescer
        # — one OP_HB_MULTI frame per peer then carries every group's
        # (term, commit, lease) vector, and the coalescer calls back
        # into hb_round_finish with the per-peer results.  None (the
        # default, and always on single-group daemons and the sim)
        # keeps the direct per-peer ctrl-write fan-out below.
        self.hb_sink = None

        # stats (observability, §5.5): a dict-compatible view over a
        # metrics registry (apus_tpu.obs.metrics) — private by default;
        # the daemon swaps in its shared ObsHub registry via attach_obs
        # so every counter is scrapeable through OP_METRICS.  The view
        # keeps every legacy ``stats[...]`` consumer working.
        self.obs = None
        self.stats = MetricsRegistry().view("node")
        for k in ("elections", "commits", "applied", "votes_granted",
                  "hb_sent", "entries_replicated"):
            self.stats.setdefault(k, 0)
        # Lease flight-recorder edge tracking (grant/lapse transitions
        # only — per-renewal notes would flood the ring at HB rate).
        self._lease_noted = False

    # ------------------------------------------------------------------
    # public api
    # ------------------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        s = self.sid.sid
        return self.role == Role.LEADER and s.leader and s.idx == self.idx

    @property
    def current_term(self) -> int:
        return self.sid.sid.term

    @property
    def leader_hint(self) -> Optional[int]:
        return self._known_leader

    # -- observability hooks (apus_tpu.obs) ---------------------------

    def bump(self, name: str, n: int = 1) -> None:
        """Increment a node_* counter (the one-call spelling the
        metrics drift lint tracks; see scripts/check_metrics.py)."""
        self.stats.bump(name, n)

    def attach_obs(self, hub) -> None:
        """Adopt a shared ObsHub: the stats view rebinds onto the hub's
        registry (carrying any pre-attach counts), and span/flight
        recording engages.  Called once by the daemon, before ticking;
        sim nodes never call it and stay clock-pure."""
        old = self.stats
        self.obs = hub
        self.stats = hub.registry.view("node")
        for k, v in old.items():
            if v:
                self.stats[k] = v

    def _note(self, category: str, msg: str = "", **fields) -> None:
        """Flight-recorder note (no-op without a hub)."""
        if self.obs is not None:
            self.obs.flight.note(category, msg, **fields)

    def _spans(self):
        return self.obs.spans if self.obs is not None else None

    def submit(self, req_id: int, clt_id: int, data: bytes) -> Optional[PendingRequest]:
        """Enqueue a client request (leader only).  Returns a handle whose
        ``idx`` is set once appended; committed when log.commit > idx.

        Exactly-once: duplicates of an applied (clt_id, req_id) are
        answered from the endpoint DB's cached reply, and duplicates of
        an in-flight request return the existing handle — so client
        retries across timeouts/failovers never double-append
        (ep_db dedup analog, dare_ep_db.h:20-31).  Client req_ids must be
        per-client monotone."""
        if not self.is_leader:
            return None
        ep = self.epdb.duplicate_of_applied(clt_id, req_id)
        if ep is not None:
            return PendingRequest(req_id, clt_id, data, idx=ep.last_idx,
                                  reply=ep.last_reply or b"")
        key = (clt_id, req_id)
        existing = self._inflight.get(key)
        if existing is not None:
            return existing
        pr = PendingRequest(req_id, clt_id, data)
        if self.cfg.seg_chunk > 0 and len(data) > self.cfg.seg_chunk:
            parts = segment.split(data, self.cfg.seg_chunk,
                                  clt_id, req_id)
            pr.chunks, pr.data = parts[:-1], parts[-1]
            self.bump("seg_split")
        else:
            # Magic-prefix escape runs UNCONDITIONALLY (even with
            # splitting disabled): the apply path treats any MAGIC-
            # prefixed payload as a chunk envelope, so a colliding raw
            # payload must always be wrapped or apply would parse
            # garbage out of it.
            wrapped = segment.maybe_wrap(data, clt_id, req_id)
            if wrapped is not None:
                pr.data = wrapped
        self._pending.append(pr)
        self._inflight[key] = pr
        return pr

    def read(self, req_id: int, clt_id: int, data: bytes,
             min_wait_idx: int = 0) -> Optional[PendingRead]:
        """Register a linearizable read (leader only): answered once
        every entry committed before registration is applied AND
        leadership has been re-verified against a majority
        (ud_clt_answer_read_request + wait_for_idx,
        dare_ibv_ud.c:1424-1449, dare_ep_db.c:132-161).

        ``min_wait_idx`` raises the apply floor beyond the read-index
        rule: the pipelined-burst hook passes the log index just past a
        burst's earlier writes, giving reads program-order
        (read-your-write) semantics WITHIN a burst."""
        if not self.is_leader:
            return None
        # Read-index rule: a fresh leader's commit may lag the cluster
        # until its own term's blank entry commits — wait for at least
        # that entry so the read reflects every previously-committed
        # write (Raft §8 read-only optimization; the reference gets this
        # from poll_config_entries before answering, dare_server.c:1399).
        wait_idx = max(self.log.commit, self._term_start_idx + 1,
                       min_wait_idx)
        self._reg_seq += 1
        rr = PendingRead(clt_id, req_id, data, wait_idx=wait_idx,
                         registered_at=self._reg_seq)
        # Lease fast path: everything committed before registration is
        # already applied AND the read lease holds — answer from local
        # state NOW, no majority round, no tick wait.  Validity MUST be
        # checked against a fresh clock (_fresh_now), never the
        # tick-start stamp: a stale (smaller) clock would let an
        # expired lease keep passing ``now < _lease_until`` — and the
        # stamp freezes exactly when the leader is isolated and its
        # tick stalls in heartbeat write timeouts.
        if self.log.apply >= wait_idx and self._lease_valid(self._fresh_now()):
            try:
                rr.reply = self.sm.query(data)
            except Exception:
                rr.reply = None
                rr.error = True
            rr.done = True
            self.reads_done += 1
            self.bump("lease_reads")
            return rr
        self._pending_reads.append(rr)
        return rr

    def _lease_valid(self, now: float) -> bool:
        """Leader read lease currently held (see NodeConfig.read_lease)."""
        return (self.cfg.read_lease and self.role == Role.LEADER
                and now < self._lease_until)

    def _fresh_now(self) -> float:
        """Freshest available clock (see ``self.clock``): the daemon's
        real monotonic clock when installed, else the last tick stamp
        (deterministic sim, where the tick clock is exact)."""
        return self._now if self.clock is None else self.clock()

    # -- follower read leases (NodeConfig.follower_read_leases) --------

    def _flr_enabled(self) -> bool:
        return self.cfg.read_lease and self.cfg.follower_read_leases

    def _note_entry_buckets(self, e: "LogEntry") -> None:
        """SlotLog.on_entry hook (every entry path, both roles): track
        bucket tails + the leader walk's footprint cache."""
        bs = entry_bucket_footprint(e)
        end = e.idx + 1
        self._entry_buckets[e.idx] = bs
        if bs is None:
            if end > self._bucket_tail_all:
                self._bucket_tail_all = end
        else:
            for b in bs:
                if end > self._bucket_tails.get(b, 0):
                    self._bucket_tails[b] = end
        # Lazy cache pruning: entries below commit never enter the cap
        # walk again (the grant-floor walk reads the log directly).
        self._entry_buckets_prunes += 1
        if self._entry_buckets_prunes >= 1024:
            self._entry_buckets_prunes = 0
            c = self.log.commit
            for idx in [i for i in self._entry_buckets if i < c]:
                del self._entry_buckets[idx]

    def _entry_footprint(self, idx: int):
        """Cached footprint of the entry at ``idx`` (None = unknown =
        every bucket; a missing entry is unknown too)."""
        try:
            return self._entry_buckets[idx]
        except KeyError:
            e = self.log.get(idx)
            bs = entry_bucket_footprint(e) if e is not None else None
            self._entry_buckets[idx] = bs
            return bs

    def _advance_bucket_commits(self) -> None:
        """Advance the leader's per-bucket commit floors to the current
        commit index (incremental walk from the cursor; pruned history
        below the log head counts for every bucket — it is all applied,
        so its floor contribution is <= head anyway)."""
        c = self.log.commit
        cur = self._bucket_commit_cursor
        if cur >= c:
            return
        if cur < self.log.head:
            self._bucket_commit_all = max(self._bucket_commit_all,
                                          self.log.head)
            cur = self.log.head
        for e in self.log.entries(cur, c):
            bs = self._entry_footprint(e.idx)
            end = e.idx + 1
            if bs is None:
                self._bucket_commit_all = max(self._bucket_commit_all,
                                              end)
            else:
                for b in bs:
                    if end > self._bucket_commits.get(b, 0):
                        self._bucket_commits[b] = end
        self._bucket_commit_cursor = c

    def _grant_floor(self, buckets) -> int:
        """Commit floor for a grant with read set ``buckets`` (None =
        every bucket): everything committed to those buckets so far.
        The whole-log shape is simply ``log.commit``; a bucket-scoped
        grant floors at the last committed write TOUCHING its set, so
        an unrelated hot-key write stream stops dragging cold-bucket
        grant floors (and with them every cold follower read's apply
        wait) along behind it."""
        if buckets is None or not self.cfg.flr_bucket_leases:
            return self.log.commit
        self._advance_bucket_commits()
        floor = self._bucket_commit_all
        for b in buckets:
            f = self._bucket_commits.get(b, 0)
            if f > floor:
                floor = f
        return floor

    def grant_follower_lease(self, peer: int, incarnation: int = 0,
                             buckets=None) -> Optional[dict]:
        """Leader side of OP_FLR_LEASE (called under the node lock by
        the lease wire op): grant ``peer`` a commit-index-bounded read
        lease nested inside our own leader lease, or refuse (None).

        The returned ``dur`` is the REMAINING leader-lease window; the
        requester anchors it at its own request-send stamp, so its
        window ends before ours does in real time (send precedes our
        receipt), and ours is already proven to end before any election
        can complete (lease_guard quorum intersection).  Our
        conservative tracking window starts at receipt and adds the
        lease margin, covering the grantee's rate drift."""
        if not (self.is_leader and self._flr_enabled()):
            return None
        if self.draining or self.external_commit:
            # Device-owned commit bypasses the host ack rule the
            # blocker invalidation hangs off — no grants while the
            # device quorum owns commit (outstanding ones are capped
            # via flr_commit_cap until they expire).
            self.bump("flr_grant_refusals")
            return None
        if self.cid.state != CidState.STABLE \
                or not self.cid.contains(peer) or peer == self.idx:
            self.bump("flr_grant_refusals")
            return None
        if incarnation < self.fence_epochs.get(peer, 0):
            # Stale ex-occupant of the slot: its reads must bounce to
            # the leader like everything else it sends.
            self.bump("flr_grant_refusals")
            return None
        fnow = self._fresh_now()
        if not self._lease_valid(fnow):
            self.bump("flr_grant_refusals")
            return None
        # Fresh-leadership read-index rule, applied to GRANTS: until
        # our term-start blank entry COMMITS, our commit index may lag
        # entries the previous term committed (we hold them — election
        # restriction — but cannot know they committed).  A floor
        # taken in that window can sit BELOW a client-acked write the
        # grantee never replicated, and the grantee's
        # end-at-registration guard would not cover it either (it only
        # covers writes that needed the grantee's ack UNDER THIS
        # grant's window) — the follower would then serve a read
        # missing an acked write.  The leader read path has always
        # waited for the blank (read(): wait_idx >= term_start + 1);
        # grants must too.
        if self.log.commit <= self._term_start_idx:
            self.bump("flr_grant_refusals")
            return None
        if not self.cfg.flr_bucket_leases:
            buckets = None
        floor = self._grant_floor(buckets)
        # Liveness guards: only a follower caught up ON THE REQUESTED
        # READ SET may hold a lease — a laggard holding one would
        # stall commit (blocker rule) for the whole window while never
        # serving a read.  For a whole-log grant the set floor IS
        # log.commit (the pre-bucket rule); a bucket-scoped grant only
        # requires the holder to have replicated everything committed
        # to its buckets (all it can serve, and all its window can
        # block — commits outside the set bypass it), so replication-
        # link lag on an unrelated hot stream no longer starves cold
        # readers of leases.  A holder that RECENTLY blocked commit
        # must fully catch up before it renews (see _flr_blocked_at:
        # without this, an asymmetric partition that drops our entries
        # but delivers its requests would let it renew itself into a
        # permanent write stall).
        ack = self.regions.ctrl[Region.REP_ACK][peer]
        if ack is None or ack < floor:
            self.bump("flr_grant_refusals")
            return None
        if ack < self.log.end and \
                fnow - self._flr_blocked_at.get(peer, -1e9) \
                < 2.0 * self.cfg.hb_timeout:
            self.bump("flr_grant_refusals")
            return None
        dur = self._lease_until - fnow
        if dur <= 0:
            self.bump("flr_grant_refusals")
            return None
        until = fnow + dur * (1.0 + self.cfg.lease_margin)
        wins = self._fgrants.setdefault(peer, [])
        had_live = any(u > fnow for u, _ in wins)
        # Prune dead windows in place, then track the new one.  A
        # same-set renewal extends the existing window instead of
        # growing the list (the common steady-state shape).
        wins[:] = [w for w in wins if w[0] > fnow]
        for i, (u, bs) in enumerate(wins):
            if bs == buckets:
                wins[i] = (max(u, until), bs)
                break
        else:
            wins.append((until, buckets))
        self.bump("flr_grants")
        if buckets is not None:
            self.bump("flr_bucket_grants")
        if not had_live:
            self._note("lease", "flr_grant", peer=peer,
                       term=self.current_term, floor=floor,
                       buckets=(-1 if buckets is None else len(buckets)))
        return {"term": self.current_term, "epoch": self.cid.epoch,
                "floor": floor, "dur": dur}

    def _flr_live_windows(self, fnow: float) -> dict:
        """peer -> live granted windows ``[(until, buckets), ...]`` on
        our clock (expired ones pruned in place): commit must not
        advance past an entry a window's read set covers until its
        holder acks it.  Pruned by TIME only — a slot removed from the
        config keeps blocking until its windows expire (its ex-holder
        may not have applied the removal yet and would serve reads
        missing anything we committed without it)."""
        if not self._fgrants:
            return {}
        out = {}
        for p, wins in list(self._fgrants.items()):
            live = [w for w in wins if w[0] > fnow]
            if live:
                self._fgrants[p] = live
                out[p] = live
            else:
                del self._fgrants[p]
        return out

    @staticmethod
    def _windows_cover(wins, fp) -> bool:
        """Does any window's read set intersect footprint ``fp``?
        (fp None = unknown entry = every bucket; a window set of None
        = whole-log grant = every bucket.)"""
        for _, bs in wins:
            if bs is None or fp is None:
                return True
            if not fp.isdisjoint(bs):
                return True
        return False

    def flr_commit_cap(self) -> Optional[int]:
        """Max index commit may advance to under outstanding follower
        leases (None = unconstrained).  Consulted by _advance_commit
        AND by the device plane's commit adoption — grants are refused
        while external_commit is on, but a grant issued just before the
        flip must keep binding until it expires.

        Bucket-granular (NodeConfig.flr_bucket_leases): walking up
        from commit, an entry blocks only the holders whose live
        granted read set INTERSECTS its written buckets — the cap is
        the first index such a holder has not acked.  Unknown
        footprints (CONFIG, migration, non-TM txn records) block on
        every live holder, which IS the whole-log rule; so does every
        entry when the knob is off (every window's set is None)."""
        fnow = self._fresh_now()
        if self._flr_holdoff_until > 0 and fnow < self._flr_holdoff_until:
            # Fresh-leadership hold-off (become_leader).
            return self.log.commit
        wins = self._flr_live_windows(fnow)
        if not wins:
            return None
        acks = self.regions.ctrl[Region.REP_ACK]
        bypassed = False
        for idx in range(self.log.commit, self.log.end):
            fp = self._entry_footprint(idx)
            lagging = []
            skipped = False
            for p, pw in wins.items():
                a = acks[p]
                if a is not None and a >= idx + 1:
                    continue
                if self._windows_cover(pw, fp):
                    lagging.append(p)
                else:
                    skipped = True
            if lagging:
                # Renewal embargo + accounting only when the entry has
                # host-ack MAJORITY (the lease is then really what
                # holds commit back — the pre-bucket rule stamped in
                # exactly that case; a sub-majority entry wasn't going
                # to commit anyway, and under device-owned commit the
                # host ack view legitimately lags).
                mask = 1 << self.idx
                for peer, a in enumerate(acks):
                    if a is not None and a >= idx + 1:
                        mask |= 1 << peer
                if have_majority(mask, self.cid):
                    self.bump("flr_commit_blocked")
                    for p in lagging:
                        self._flr_blocked_at[p] = fnow
                if bypassed:
                    self.bump("flr_commit_bypass")
                return idx
            if skipped:
                # A lagging holder's set was disjoint from this
                # entry's buckets: the whole-log rule would have
                # stopped here — the per-bucket relief, counted.
                bypassed = True
        if bypassed:
            self.bump("flr_commit_bypass")
        return None

    def _flease_ok(self, fnow: float) -> tuple[bool, str]:
        """Is OUR follower lease currently serveable?  Returns
        (ok, reason) with NO side effects (callers bump counters/notes
        so OP_STATUS can probe this freely).  The planted-bug knobs
        (NodeConfig.flr_plant) skip exactly one check each — the
        stale-read harness relies on the audit plane catching what this
        function would otherwise have stopped."""
        plant = self.cfg.flr_plant
        if not self._flr_enabled() or self.draining:
            return False, "disabled"
        if self.role != Role.FOLLOWER:
            return False, "role"
        if self._flease_term != self.current_term:
            return False, "term"
        if self.cid.state != CidState.STABLE:
            return False, "config"
        if self._flease_epoch != self.cid.epoch and "epoch" not in plant:
            return False, "epoch"
        if fnow >= self._flease_until and "expiry" not in plant:
            if fnow - self._flease_until > self._flease_dur:
                # Missed by more than a whole window: the process was
                # paused or the clock jumped — the classic lease
                # killer, surfaced distinctly.
                return False, "pause_or_jump"
            return False, "expired"
        return True, "ok"

    #: Demand-tracking window for the requested read set: a bucket a
    #: follower read touched within this many seconds rides the next
    #: lease request's bitmap (idle buckets decay out, narrowing the
    #: set the leader's writes must invalidate against).
    FLR_WANT_WINDOW = 2.0

    def _read_bucket(self, data: bytes):
        """Hash bucket of a follower read's key; BUCKET_UNROUTABLE for
        payloads with no routable key (serveable only under a full-set
        lease); None when bucket leases are off (no bucket discipline
        — the pre-bucket whole-log behavior)."""
        if not self.cfg.flr_bucket_leases:
            return None
        from apus_tpu.models.kvs import decode_key
        from apus_tpu.runtime.router import bucket_of_key
        k = decode_key(data)
        return (bucket_of_key(k) if k is not None
                else BUCKET_UNROUTABLE)

    def _flease_covers(self, bucket) -> bool:
        """Is ``bucket`` inside the held lease's granted read set?
        (The 'bucket' plant skips this check — the planted-stale
        harness proves the audit checker catches what it guards.)"""
        if self._flease_buckets is None:
            return True
        if "bucket" in self.cfg.flr_plant:
            return True
        if bucket is None or bucket < 0:
            return False
        return bucket in self._flease_buckets

    def _flr_wait_idx(self, bucket) -> int:
        """Apply index a bucket-``bucket`` follower read must wait for.
        Full-set leases keep the whole-log rule (everything in our log
        at registration may have committed via our ack); bucket-scoped
        leases only ever acked-gated writes TOUCHING the granted set,
        so a bucket-b read needs only max(grant floor, b's own log
        tail, the unknown-footprint tail) — the hot-key write stream's
        apply stops gating cold-key reads."""
        if self._flease_buckets is None or bucket is None or bucket < 0:
            return max(self.log.end, self._flease_floor)
        return max(self._flease_floor, self._bucket_tail_all,
                   self._bucket_tails.get(bucket, 0))

    def _flr_want_set(self, fnow: float):
        """Read set for the next lease request (None = full set):
        recently-wanted buckets, decayed past FLR_WANT_WINDOW."""
        if not self.cfg.flr_bucket_leases or self.flr_full_buckets:
            return None
        if fnow < self._flr_want_full_until:
            return None
        cutoff = fnow - self.FLR_WANT_WINDOW
        stale = [b for b, t in self._flr_want.items() if t < cutoff]
        for b in stale:
            del self._flr_want[b]
        return frozenset(self._flr_want)

    def _want_covered(self, fnow: float) -> bool:
        """Does the held lease's set cover current read demand?"""
        if self._flease_buckets is None:
            return True
        if fnow < self._flr_want_full_until:
            return False
        return all(b in self._flease_buckets for b in self._flr_want)

    def follower_read(self, req_id: int, clt_id: int,
                      data: bytes) -> Optional[PendingRead]:
        """Register (and, on the warm path, immediately serve) a
        linearizable read at a FOLLOWER under its read lease.  None
        when follower reads cannot engage at all (not a follower,
        disabled, no live wire) — the caller answers NOT_LEADER with a
        hint.  A returned handle resolves either ``done`` (served from
        local applied state) or ``refused`` (lease lapsed: the caller
        answers NOT_LEADER and the client falls back to the leader).

        Safety of the serve condition (see NodeConfig docstring): with
        the lease live, every write acked to any client BEFORE this
        read's invoke either committed before the governing grant
        (idx <= floor) or required our log ack while the window was
        live (idx < our log end at registration) — so waiting for
        apply >= max(floor, end-at-registration) covers them all."""
        if self.role != Role.FOLLOWER or self.draining:
            return None
        if not self._flr_enabled() or self.lease_requester is None:
            return None
        fnow = self._fresh_now()
        self._flr_hot_until = fnow + 1.0
        bucket = self._read_bucket(data)
        if bucket is None:
            pass
        elif bucket >= 0:
            self._flr_want[bucket] = fnow
        else:
            self._flr_want_full_until = fnow + self.FLR_WANT_WINDOW
        ok, _why = self._flease_ok(fnow)
        covered = ok and self._flease_covers(bucket)
        if not covered:
            # Cold lease (or the held read set misses this bucket):
            # one inline request (lock yielded on the wire) before
            # parking the read — a cold GET then costs one extra
            # roundtrip instead of a leader bounce.
            self._request_flease(fnow)
            fnow = self._fresh_now()
            ok, _why = self._flease_ok(fnow)
            covered = ok and self._flease_covers(bucket)
        wait_idx = self._flr_wait_idx(bucket)
        rr = PendingRead(clt_id, req_id, data, wait_idx=wait_idx,
                         registered_at=fnow, flr=True, bucket=bucket)
        if covered and self.log.apply >= wait_idx:
            try:
                rr.reply = self.sm.query(data)
            except Exception:
                rr.reply = None
                rr.error = True
            rr.done = True
            self.reads_done += 1
            self.bump("flr_local_reads")
            return rr
        self._flr_pending.append(rr)
        return rr

    #: How long a parked follower read waits through an invalid lease
    #: (renewal in flight) before being refused to the leader, in
    #: heartbeat timeouts.
    FLR_REFUSE_AFTER_HB = 2.0

    def _serve_follower_reads(self, now: float) -> None:
        """Resolve parked follower reads (follower tick): serve the
        ones applied state covers while the lease is live; refuse the
        ones a dead lease has stranded (the client retries at the
        leader — the 'forward' path, expressed as a typed bounce)."""
        if not self._flr_pending:
            return
        fnow = self._fresh_now()
        ok, why = self._flease_ok(fnow)
        if not ok and self._flr_noted:
            self._flr_noted = False
            self.bump("flr_lapses")
            if why == "pause_or_jump":
                self.bump("flr_pause_lapses")
            elif why == "epoch":
                # Config-epoch fence tripped: a membership change
                # applied under the lease — reads bounce until a
                # fresh-epoch grant arrives.
                self.bump("flr_epoch_refusals")
            self._note("lease", "flr_lapse", cause=why,
                       term=self.current_term)
        still: list[PendingRead] = []
        for r in self._flr_pending:
            covered = ok and self._flease_covers(r.bucket)
            if covered and self.log.apply >= max(r.wait_idx,
                                                 self._flease_floor):
                try:
                    r.reply = self.sm.query(r.data)
                except Exception:
                    r.reply = None
                    r.error = True
                r.done = True
                self.reads_done += 1
                self.bump("flr_local_reads")
            elif not covered and fnow - r.registered_at \
                    > self.FLR_REFUSE_AFTER_HB * self._hb_timeout:
                # Lease dead, or live but its granted read set still
                # misses this read's bucket after a renewal window:
                # bounce to the leader.
                r.refused = True
                self.reads_done += 1
                self.bump("flr_forwards")
                if ok:
                    self.bump("flr_bucket_refusals")
            else:
                still.append(r)
        self._flr_pending = still

    def _flr_refuse_all(self, why: str) -> None:
        """Refuse every parked follower read (role/term/leader loss)."""
        for r in self._flr_pending:
            r.refused = True
            self.reads_done += 1
            self.bump("flr_forwards")
        self._flr_pending = []
        if self._flr_noted:
            self._flr_noted = False
            self.bump("flr_lapses")
            self._note("lease", "flr_lapse", cause=why,
                       term=self.current_term)

    def _maybe_request_flease(self, now: float) -> None:
        """Keep the lease warm while follower reads are flowing
        (follower tick): request a fresh grant once the held window
        runs low.  Rate-limited to ~one request per heartbeat period."""
        if self.lease_requester is None or not self._flr_enabled() \
                or self.draining:
            return
        fnow = self._fresh_now()
        if fnow >= self._flr_hot_until and not self._flr_pending:
            return
        if self._flease_until - fnow > 0.5 * self._hb_timeout \
                and self._flease_ok(fnow)[0] \
                and self._want_covered(fnow):
            return
        if now < self._flr_next_req:
            return
        self._flr_next_req = now + max(self.cfg.hb_period, 0.001)
        self._request_flease(fnow)

    def _request_flease(self, t_req: float) -> None:
        """One lease-request roundtrip to the known leader.  ``t_req``
        MUST be our fresh-clock stamp from BEFORE the wire call — the
        adopted window is anchored there (see NodeConfig: anchoring at
        delivery would let a delayed grant outlive the guards).  The
        transport yields the node lock on the wire; state is
        re-validated after it returns."""
        leader = self._known_leader
        if leader is None or leader == self.idx \
                or self._flr_req_inflight:
            return
        term0 = self.current_term
        want = self._flr_want_set(t_req)
        self._flr_req_inflight = True
        try:
            self.bump("flr_requests")
            grant = self.lease_requester(leader, want)
        finally:
            self._flr_req_inflight = False
        if not grant:
            return
        # Post-roundtrip validation: same term at both ends, grant from
        # the leader we asked, window still worth adopting.
        if self.role != Role.FOLLOWER or self.current_term != term0 \
                or grant.get("term") != term0:
            return
        until = t_req + float(grant.get("dur", 0.0))
        if until <= self._flease_until and \
                grant.get("epoch") == self._flease_epoch and \
                (self._flease_buckets is None
                 or (want is not None
                     and want <= self._flease_buckets)):
            # Nothing new: shorter window, same epoch, and the held
            # set already covers the requested one.
            return
        self._flease_until = until
        self._flease_term = int(grant["term"])
        self._flease_epoch = int(grant["epoch"])
        self._flease_floor = max(self._flease_floor,
                                 int(grant["floor"]))
        self._flease_dur = float(grant.get("dur", 0.0))
        # The grant binds to the set we REQUESTED (the leader granted
        # exactly it); adopted atomically with the window.
        self._flease_buckets = want
        self.bump("flr_renewals")
        if not self._flr_noted:
            self._flr_noted = True
            self._note("lease", "flr_held", term=self._flease_term,
                       floor=self._flease_floor)

    def _flease_reset(self) -> None:
        """Drop our held lease + parked reads (role/term transitions)."""
        self._flease_until = -1.0
        self._flease_term = -1
        self._flease_epoch = -1
        self._flease_floor = 0
        self._flease_buckets = None
        self._flr_refuse_all("role_change")

    def flush_pending(self) -> None:
        """Admit queued client writes into the log NOW instead of at
        the next tick's drain (leader only; no-op otherwise).  The
        pipelined-burst hook calls this — under the daemon lock — so a
        same-burst read's wait_idx can cover the indices of the writes
        before it.  Identical to the tick-time drain and idempotent
        per handle (drained handles keep their idx).  Declined while
        the term-start blank is deferred (full-ring election corner):
        the blank must stay the term's first entry, so those bursts
        fall back to the tick-time drain."""
        if self.is_leader and not self._term_blank_pending:
            self._drain_pending(self.sid.sid)

    def handle_join(self, addr: str,
                    want_slot: Optional[int] = None) -> Optional[PendingJoin]:
        """Admit a new server (handle_server_join_request analog,
        dare_ibv_ud.c:972-1068): assign the lowest empty slot, or up-size
        the configuration STABLE -> EXTENDED when full.  Returns a handle
        that completes when the CONFIG entry applies; None when not
        leader, mid-resize, at capacity, or when ``want_slot`` cannot be
        honored.

        ``want_slot`` is SLOT AFFINITY for a recovered server re-joining
        after eviction: identity (votes, acks, durable store, peer
        table) is keyed by slot, so a re-joiner must get ITS slot back
        or nothing — admitting it at a different empty slot would bind
        its address to a foreign identity.  (The reference's joiner
        likewise receives its idx in the CFG_REPLY and adopts it,
        dare_ibv_ud.c:1070-1087.)"""
        if not self.is_leader:
            return None
        self.last_join_refusal = None
        pj = self._pending_joins.get(addr)
        if pj is not None:                   # retransmitted join: dedup
            return pj
        # Already a member (its join committed but the reply was lost,
        # e.g. across a leader change): answer idempotently.
        existing = self._member_addrs.get(addr)
        if existing is not None and self.cid.contains(existing):
            return PendingJoin(addr=addr, slot=existing, done=True)
        # One membership change at a time: a CONFIG built from the
        # current cid while another is in flight would conflict with it
        # when both apply (e.g. two joiners assigned the same empty
        # slot, or a join resurrecting a concurrently-removed server).
        # Scan from APPLY, not commit: a committed-but-unapplied CONFIG
        # hasn't updated self.cid yet and is just as conflicting.
        if any(e.type == EntryType.CONFIG
               for e in self.log.entries(self.log.apply)):
            self.last_join_refusal = "config_in_flight"
            return None
        if want_slot is not None:
            if want_slot == self.cid.size \
                    and not self.cid.contains(want_slot):
                # Slot affinity for a slot this group hasn't grown to
                # yet: a multi-group joiner holds group 0's assignment
                # and every other group must admit at the SAME slot —
                # when that slot is exactly the next one, run the same
                # STABLE -> EXTENDED upsize ladder the unsolicited
                # join takes, pinned to it.
                if self.cid.state != CidState.STABLE:
                    self.last_join_refusal = "mid_resize"
                    return None
                if self.cid.size >= MAX_SERVER_COUNT:
                    self.last_join_refusal = "capacity"
                    return None
                if self.log.near_full(1):
                    self.last_join_refusal = "log_full"
                    return None
                new_cid = self.cid.extend(
                    self.cid.size + 1).with_server(want_slot)
                pj = PendingJoin(addr=addr, slot=want_slot)
                pj.entry_idx = self.log.append(
                    self.sid.sid.term, type=EntryType.CONFIG,
                    cid=new_cid, data=f"{want_slot} {addr}".encode())
                self._pending_joins[addr] = pj
                return pj
            if not (0 <= want_slot < self.cid.size):
                self.last_join_refusal = "slot_out_of_range"
                return None
            if self.cid.contains(want_slot):
                # The slot a recovered server wants back is BOUND to a
                # different live address: its identity was reassigned —
                # rejoin at that slot is permanently refused (the
                # typed "removed, rejoin refused" answer).
                self.last_join_refusal = "slot_bound"
                return None
            slot = want_slot
            new_cid = dataclasses.replace(
                self.cid.with_server(slot), epoch=self.cid.epoch + 1)
            if self.log.near_full(1):
                self.last_join_refusal = "log_full"
                return None
            pj = PendingJoin(addr=addr, slot=slot)
            pj.entry_idx = self.log.append(
                self.sid.sid.term, type=EntryType.CONFIG, cid=new_cid,
                data=f"{slot} {addr}".encode())
            self._pending_joins[addr] = pj
            return pj
        slot = self.cid.empty_slot()
        if slot is not None:
            new_cid = dataclasses.replace(
                self.cid.with_server(slot), epoch=self.cid.epoch + 1)
        elif self.cid.state != CidState.STABLE:
            self.last_join_refusal = "mid_resize"
            return None                      # one resize at a time
        elif self.cid.size >= MAX_SERVER_COUNT:
            self.last_join_refusal = "capacity"
            return None                      # at protocol capacity
        else:
            slot = self.cid.size
            new_cid = self.cid.extend(self.cid.size + 1).with_server(slot)
        if self.log.near_full(1):
            self.last_join_refusal = "log_full"
            return None     # reserve the last slot for the HEAD entry
        pj = PendingJoin(addr=addr, slot=slot)
        pj.entry_idx = self.log.append(
            self.sid.sid.term, type=EntryType.CONFIG, cid=new_cid,
            data=f"{slot} {addr}".encode())
        self._pending_joins[addr] = pj
        return pj

    #: handle_join/handle_leave refusal reasons the caller may retry
    #: after backing off (the condition is transient); everything else
    #: is permanent for the current configuration.
    TRANSIENT_REFUSALS = ("config_in_flight", "mid_resize", "log_full")

    def handle_leave(self, slot: int):
        """Operator-initiated graceful removal (OP_LEAVE): append the
        CONFIG entry removing ``slot`` — the drained replica stops
        voting/serving once the removal is committed and exits clean,
        vs. auto-removal's failure-detector-only path.  Returns a
        handle resolved when the entry applies, a refusal-reason string
        (see TRANSIENT_REFUSALS for which are retryable), or None when
        not leader.  Removing the leader itself is allowed: the entry
        is replicated to a quorum before it applies, and the leader
        steps down at the apply (standard C_new-excludes-leader
        handling).  Same guards as auto-removal: STABLE configurations
        only, never below the quorum floor of the unchanged ``size``
        denominator."""
        if not self.is_leader:
            return None
        existing = self._pending_leaves.get(slot)
        if existing is not None:             # retransmitted: dedup
            return existing
        if not self.cid.contains(slot):
            return PendingJoin(addr="", slot=slot, done=True)  # already out
        if self.cid.state != CidState.STABLE:
            return "mid_resize"
        if any(e.type == EntryType.CONFIG
               for e in self.log.entries(self.log.apply)):
            return "config_in_flight"
        if len(self.cid.members()) - 1 < quorum_size(self.cid.size):
            return "quorum_floor"
        if self.log.near_full(1):
            return "log_full"
        pl = PendingJoin(addr="", slot=slot)
        # The "leave" marker makes the removal's REASON replicated
        # state: the drained replica (whichever member it is) learns
        # from applying this entry that its removal was intentional —
        # so it drains and exits instead of re-joining like an evicted
        # member would.  Unparseable as a join payload by construction
        # (join payloads are "<slot> <addr>").
        pl.entry_idx = self.log.append(
            self.sid.sid.term, type=EntryType.CONFIG,
            cid=dataclasses.replace(self.cid.without_server(slot),
                                    epoch=self.cid.epoch + 1),
            data=b"leave %d" % slot)
        self._pending_leaves[slot] = pl
        self.bump("graceful_leaves")
        return pl

    # -- snapshots (SM recovery, §3.4) ---------------------------------

    def make_snapshot(self) -> tuple[Snapshot, list, Cid, dict]:
        """Snapshot at the current apply point: SM state, endpoint-DB
        dump (exactly-once state must travel with the SM state), plus
        the configuration at that point — CONFIG entries inside the
        covered prefix are never applied by the installer, so membership
        must ride with the snapshot or the installer keeps a stale view.

        Cached until pruning moves the head past it — a snapshot stays
        pushable as long as replication can resume at last_idx+1 >= head.
        (Keying on the apply point instead would rebuild the full state
        blob every tick while a lagging peer is unreachable; the
        reference likewise reuses its preregistered snapshot until the
        head moves, dare_server.c:643,2052.)"""
        if self._snap_cache is not None and \
                self._snap_cache[0].last_idx + 1 >= self.log.head:
            return self._snap_cache
        last_idx, last_term = self._applied_det
        snap = self.sm.create_snapshot(last_idx, last_term)
        # Partially-reassembled chunk groups at the apply point ride
        # WITH the snapshot (deterministic function of the applied
        # prefix): an installer can then complete a group whose early
        # chunks lie below the snapshot cut — no mid-group gating, no
        # stranded seg_incomplete finals (core.segment.Reassembler).
        snap = dataclasses.replace(snap, seg=self._seg.dump(),
                                   fence=self._fence_blob())
        self._snap_cache = (snap, self.epdb.dump(), self.cid,
                            dict(self._member_addrs))
        return self._snap_cache

    def _fence_blob(self) -> bytes:
        """Removed-slot fence table at the current apply point, in the
        Snapshot.fence wire form (JSON; empty when no slot was ever
        removed — the overwhelmingly common case costs zero bytes)."""
        if not self.fence_epochs:
            return b""
        import json as _json
        return _json.dumps({str(k): v for k, v
                            in self.fence_epochs.items()}).encode()

    def adopt_fence(self, fence: bytes) -> None:
        """Merge a snapshot's fence table (monotone max per slot)."""
        if not fence:
            return
        import json as _json
        try:
            table = _json.loads(fence.decode())
        except (ValueError, UnicodeDecodeError):
            return
        for k, v in table.items():
            try:
                slot, epoch = int(k), int(v)
            except (TypeError, ValueError):
                continue
            if epoch > self.fence_epochs.get(slot, 0):
                self.fence_epochs[slot] = epoch

    #: Backstop for a background snapshot push whose thread never
    #: completes (every chunk roundtrip is wire-timeout-bounded, so
    #: this should never fire — but a held push slot silently stops
    #: ALL replication to that peer, so a wedge must be bounded).
    SNAP_PUSH_STALL_S = 60.0

    #: Stream (chunked) snapshot pushes instead of one-blob pushes when
    #: the SM's on-disk dump exceeds this.  The one-blob path holds the
    #: whole dump resident on the leader (the _snap_cache blob) for the
    #: life of the head window; at deep history the resulting GC pauses
    #: exceed the production heartbeat timeout and wobble elections.
    SNAP_STREAM_THRESHOLD = 4 << 20

    def make_snapshot_stream_meta(self):
        """Streaming counterpart of make_snapshot: everything EXCEPT the
        data blob — (meta_snap, ep_dump, cid, members, total, gen,
        blob) — for SMs exposing an on-disk dump (snapshot_stream_size
        / read_snapshot_chunk), where ``blob`` is None (chunks pread
        the dump).  SMs WITHOUT a dump file (KVS) still get the
        chunked resumable stream above the threshold: ``blob`` is then
        the cached immutable snapshot bytes and chunks slice it (the
        generation fence is unnecessary — bytes never mutate).
        Returns None when the state is below SNAP_STREAM_THRESHOLD
        (one-blob push is fine there).  Captured atomically under the
        caller's lock: the dump file is append-only and appends happen
        under the same lock, so the [0, total) prefix is exactly the
        state at (last_idx, last_term) and stays immutable while
        chunks are read.  Cached like _snap_cache."""
        if self._snap_stream_cache is not None and \
                self._snap_stream_cache[0].last_idx + 1 >= self.log.head:
            return self._snap_stream_cache
        size_of = getattr(self.sm, "snapshot_stream_size", None)
        total = size_of() if size_of is not None else None
        if total is not None:
            if total < self.SNAP_STREAM_THRESHOLD:
                return None
            last_idx, last_term = self._applied_det
            meta = Snapshot(last_idx, last_term, b"",
                            seg=self._seg.dump(),
                            fence=self._fence_blob())
            gen = getattr(self.sm, "dump_generation", 0)
            self._snap_stream_cache = (meta, self.epdb.dump(), self.cid,
                                       dict(self._member_addrs), total,
                                       gen, None)
            return self._snap_stream_cache
        # Blob fallback (no dump file): reuse the one-blob snapshot
        # cache; the blob is immutable bytes, so off-tick chunk reads
        # need no generation fencing or fd pinning.
        snap, ep_dump, cid, members = self.make_snapshot()
        if len(snap.data) < self.SNAP_STREAM_THRESHOLD:
            return None
        meta = dataclasses.replace(snap, data=b"")
        self._snap_stream_cache = (meta, ep_dump, cid, dict(members),
                                   len(snap.data), 0, snap.data)
        return self._snap_stream_cache

    #: Inline delta pushes are capped here; a delta that would exceed
    #: it falls back to the full chunked stream (which is resumable and
    #: runs off-tick) — an unbounded delta blob would stall the tick
    #: thread exactly like the whole-blob push the stream replaced.
    DELTA_MAX_BYTES = 4 << 20

    def make_snapshot_delta(self, base_idx: int, base_term: int):
        """Delta-snapshot production: everything a rejoiner whose
        applied determinant is (base_idx, base_term) needs — the SM's
        state delta past that point plus the usual snapshot freight
        (epdb dump, seg buffer, fence table, config).  None when the
        SM can't serve the base (below its delta floor / no delta
        support), when our own log still holds a CONFLICTING entry at
        base_idx, or when the delta exceeds DELTA_MAX_BYTES — callers
        fall back to the full push.  Returns (snap, ep_dump, cid,
        member_addrs, (base_idx, base_term))."""
        if base_idx <= 0:
            return None
        last_idx, last_term = self._applied_det
        if last_idx <= base_idx:
            return None                  # nothing past the base
        if self.log.head <= base_idx < self.log.end:
            e = self.log.get(base_idx)
            if e is not None and e.term != base_term:
                return None              # divergent base: full push
        delta_fn = getattr(self.sm, "delta_since", None)
        if delta_fn is None:
            return None
        data = delta_fn(base_idx)
        if data is None or len(data) > self.DELTA_MAX_BYTES:
            return None
        snap = Snapshot(last_idx, last_term, data, seg=self._seg.dump(),
                        fence=self._fence_blob())
        return (snap, self.epdb.dump(), self.cid,
                dict(self._member_addrs), (base_idx, base_term))

    def install_snapshot(self, snap: Snapshot, ep_dump: list,
                         cid: Optional[Cid] = None,
                         member_addrs: Optional[dict] = None,
                         data_path: Optional[str] = None,
                         adopt: bool = False,
                         delta_base: Optional[tuple] = None) -> bool:
        """Install a snapshot pushed by the leader (rc_recover_sm analog,
        dare_ibv_rc.c:603-689): replaces SM + dedup state, re-bases the
        log just past the snapshot, and adopts the snapshot-point
        configuration (synthetic CONFIG upcalls let the runtime learn
        the peer table it would have built from the skipped entries).
        Rejected when stale.

        ``data_path`` installs from a FILE instead of ``snap.data``
        (the streamed-receive path): the SM may ADOPT the file
        (``adopt=True`` — rename, no copy, nothing materialized), and
        the upcall snapshot carries (path, immutable-prefix length,
        dump generation) so persistence can stream its copy later
        (the prefix stays valid until another install bumps the
        generation)."""
        if snap.last_idx < self.log.commit:
            return False                     # we already have more
        if delta_base is not None:
            # DELTA install: snap.data is the state delta past
            # (base_idx, base_term).  Exact iff our applied
            # determinant still equals the base the sender read —
            # committed prefixes at equal determinants are identical,
            # so merge-on-match reconstructs the full state.  Any
            # mismatch (we applied more meanwhile, or were reset)
            # refuses; the sender falls back to a full image.
            if self._applied_det != tuple(delta_base):
                self.bump("delta_refused")
                return False
            apply_delta = getattr(self.sm, "apply_snapshot_delta", None)
            if apply_delta is None:
                return False
            try:
                apply_delta(snap)
            except NotImplementedError:
                return False
            snap = dataclasses.replace(snap,
                                       delta_base=tuple(delta_base))
            self.bump("delta_installs")
        elif data_path is not None:
            import os as _os
            stable = self.sm.apply_snapshot_file(snap, data_path,
                                                 adopt=adopt)
            if stable is None:
                # SM without a stable dump file (materializing
                # fallback — small states by construction): carry the
                # blob in the upcall so persistence still records the
                # full install; the caller's temp file is about to be
                # unlinked and must NOT be referenced.
                with open(data_path, "rb") as f:
                    snap = dataclasses.replace(snap, data=f.read())
            else:
                snap = dataclasses.replace(
                    snap, data=b"", data_path=stable,
                    data_len=_os.path.getsize(stable),
                    data_gen=getattr(self.sm, "dump_generation", 0))
            self.bump("snapshots_file_installed")
        else:
            self.sm.apply_snapshot(snap)
        self.epdb.load(ep_dump)
        # Adopt the snapshot point's partial chunk groups: finals
        # applying above the snapshot find their early chunks here.
        self._seg = segment.Reassembler.load(snap.seg)
        self.log.reset(snap.last_idx + 1)
        self._applied_det = (snap.last_idx, snap.last_term)
        self._snap_cache = None
        self._snap_stream_cache = None
        self.adopt_fence(snap.fence)
        if cid is not None and cid.epoch >= self.cid.epoch:
            self.cid = cid
            if cid.contains(self.idx):
                # Adopting a configuration that includes us attests our
                # tenancy at least to its epoch (safe to inflate: any
                # config >= a removal epoch that still contains us
                # means we were legitimately re-admitted).
                self.incarnation = max(self.incarnation, cid.epoch)
            for addr, slot in (member_addrs or {}).items():
                if not cid.contains(slot):
                    continue
                self._member_addrs[addr] = slot
                self.config_upcalls.append(LogEntry(
                    idx=snap.last_idx, term=snap.last_term,
                    type=EntryType.CONFIG, cid=cid,
                    data=f"{slot} {addr}".encode()))
        self.snapshot_upcalls.append((snap, ep_dump))
        self.bump("snapshots_installed")
        return True

    def tick(self, now: float) -> None:
        """One poll-loop iteration (polling(), dare_server.c:1013-1152)."""
        self._now = now
        # Mirror our SID into remotely-readable memory (the rsid[] slot
        # peers read during leadership verification).
        self.regions.ctrl[Region.RSID][self.idx] = self.sid.word
        self._poll_vote_requests(now)
        if self.role == Role.LEADER:
            self._leader_tick(now)
        elif self.role == Role.CANDIDATE:
            self._candidate_tick(now)
        else:
            self._follower_tick(now)
        self._apply_committed(now)

    # ------------------------------------------------------------------
    # role transitions
    # ------------------------------------------------------------------

    def _prevote_tick(self, now: float) -> None:
        """PreVote (Raft §9.6; an addition over the reference): probe
        whether a majority would elect us at term+1 BEFORE bumping any
        real term.  Pre-grants are non-binding, so a flapping or
        partitioned replica can never inflate terms or depose a healthy
        leader — real elections start only with majority evidence that
        the leader is gone."""
        target = self.sid.sid.term + 1
        if self._prevote_deadline is not None:
            acks = self.regions.ctrl[Region.PREVOTE_ACK]
            mask = 0
            for peer, a in enumerate(acks):
                if a == target:
                    mask |= 1 << peer
            if have_majority(mask, self.cid, include_self=self.idx):
                self._prevote_deadline = None
                self.start_election(now)
                return
        if self._prevote_deadline is None or now >= self._prevote_deadline:
            self.regions.ctrl[Region.PREVOTE_ACK] = \
                [None] * MAX_SERVER_COUNT
            last_idx, last_term = self._last_det()
            req = VoteRequest(Sid(target, False, self.idx).word,
                              last_idx, last_term, self.cid.epoch,
                              prevote=True)
            for peer in self.cid.members():
                if peer != self.idx:
                    self.t.ctrl_write(peer, Region.VOTE_REQ, self.idx, req)
            self._prevote_deadline = now + random_election_timeout(
                self.rng, self.cfg.elect_low, self.cfg.elect_high)
            self.bump("prevotes")

    def _last_det(self) -> tuple:
        """Last-entry determinant for election up-to-dateness.  An
        EMPTY log whose base is the apply point (snapshot install, or
        restart replay re-basing) answers with the APPLIED determinant
        instead of a term-0 placeholder — a replica that holds the
        full committed state must not look maximally stale to voters
        (liveness after whole-group restart from stores)."""
        e = self.log.last_entry()
        if e is not None:
            return e.determinant()
        li, lt = self._applied_det
        if li == self.log.end - 1:
            return (li, lt)
        return (self.log.end - 1, 0)

    def start_election(self, now: float) -> None:
        """start_election analog (dare_server.c:1264-1322)."""
        if self.pre_election_hook is not None \
                and self.pre_election_hook() is False:
            # Hook veto: device-plane windows this replica dispatched
            # are not yet executed+absorbed, so its log cannot yet
            # speak for everything its shard may have acked (mesh_plane
            # election safety).  Campaigning is DEFERRED a tick — never
            # blocked in place, which would wedge the whole daemon.
            return
        my = self.sid.sid
        new = Sid(my.term + 1, False, self.idx)
        self.sid.update(new.word)
        self.role = Role.CANDIDATE
        self._known_leader = None
        # Candidates serve no follower reads: resolve parked ones so
        # their handlers bounce to wherever leadership lands.
        self._flease_reset()
        self.bump("elections")
        self._note("election", term=new.term)
        # Fence: revoke everyone's access to our log during the vote
        # (dare_server.c:1290), then vote for ourselves durably.
        self.regions.grant_log_access(None, new.term)
        self.regions.ctrl[Region.VOTE_ACK] = [None] * len(self.regions.ctrl[Region.VOTE_ACK])
        self._replicate_vote(new)
        last_idx, last_term = self._last_det()
        req = VoteRequest(new.word, last_idx, last_term, self.cid.epoch)
        for peer in self.cid.members():
            if peer != self.idx:
                self.t.ctrl_write(peer, Region.VOTE_REQ, self.idx, req)
        self._election_deadline = now + random_election_timeout(
            self.rng, self.cfg.elect_low, self.cfg.elect_high)

    def become_leader(self, now: float) -> None:
        """become_leader analog (dare_server.c:1493-1517)."""
        my = self.sid.sid
        self.sid.update(my.with_leader(True).word)
        self.role = Role.LEADER
        self._known_leader = self.idx
        self.external_commit = False       # host rules until a driver re-arms
        self.device_covered_from = None
        self._drain_wait = {}
        self._lease_until = -1.0           # no lease carries across terms
        self._lease_noted = False
        # Follower-lease state dies with the role: grants we issued in
        # an earlier leadership are safe to drop — the election that
        # made us leader again completed after every outstanding window
        # (lease_guard quorum intersection) — and any lease WE held as
        # a follower is term-dead.
        self._fgrants.clear()
        self._flr_blocked_at.clear()
        self._flease_reset()
        # PREDECESSOR-GRANT hold-off: the quorum-intersection argument
        # above assumes the election quorum and the predecessor's
        # lease-renewal quorum are measured against the SAME
        # configuration, with every voter remembering the live leader.
        # Config churn (a lease holder's group evicting/re-admitting
        # members mid-window) or freshly-restarted voters can break
        # both, electing us INSIDE a predecessor-granted follower
        # window we know nothing about — its grant table died with the
        # old leader, so our commits would outrun that holder's acks
        # and it could serve a local read missing a client-acked write
        # (the elastic campaign caught exactly this: one-write-stale
        # follower reads, seeds 27100/27103).  Hold commit advancement
        # for one maximal follower-lease window from election, so
        # every such unknown window provably expires first.  Engaged
        # only where follower leases can engage at all (live runtime —
        # the sim never installs a lease requester).
        if self._flr_enabled() and self.lease_requester is not None:
            self._flr_holdoff_until = (
                self._fresh_now()
                + self._hb_timeout * (1.0
                                      + 2.0 * self.cfg.lease_margin))
        else:
            self._flr_holdoff_until = -1.0
        self._election_deadline = None
        self._next_hb_send = now           # heartbeat immediately
        self._next_idx = {}
        self._commit_sent = {}
        self._adjusted = {}
        self._ack_progress = {}
        self._fail_count = {}
        self._fail_last = {}
        self._pending_head = None
        self._pending_joins.clear()
        self._pending_leaves.clear()
        self._transit_pending = False
        self._resize_stall = None
        self.regions.grant_log_access(self.idx, my.term)
        # A fresh leader may not know its own tail if it recovered; our
        # absolute-index log always does.  Append a blank entry so commit
        # can advance in the new term (NOOP/CONFIG append on win,
        # dare_server.c:1412-1491): if a resize is mid-flight, continue it.
        self._append_term_start(my)

    def _append_term_start(self, my: Sid) -> None:
        """Blank/CONFIG entry opening our term.  Deferred (retried each
        leader tick) when the log is transiently full at election — the
        old term's HEAD entry may still be in flight; reads stay gated
        on _term_start_idx + 1 until the blank lands."""
        if self.log.is_full:
            # A full ring at election is the one place deferral could
            # wedge forever: with an OLD-term tail filling the log, no
            # current-term entry can land, and commit (which only
            # advances on a current-term entry) never moves.  Free the
            # locally-applied prefix without consensus (safe: see
            # _emergency_free) and append the blank into the space.
            self._emergency_free()
        if self.log.is_full:
            # Nothing applied to free (apply == head): wait for apply
            # to progress and retry every leader tick.
            self._term_start_idx = self.log.end
            self._term_blank_pending = True
            return
        if self.cid.state == CidState.EXTENDED:
            self._term_start_idx = self.log.append(
                my.term, type=EntryType.CONFIG,
                cid=self.cid.to_transit())
        elif self.cid.state == CidState.TRANSIT:
            self._term_start_idx = self.log.append(
                my.term, type=EntryType.CONFIG,
                cid=self.cid.stabilize())
        else:
            self._term_start_idx = self.log.append(
                my.term, type=EntryType.NOOP)
        self._term_blank_pending = False

    def become_follower(self, leader_sid: Sid, now: float) -> None:
        """server_to_follower analog (dare_server.h:200)."""
        self.role = Role.FOLLOWER
        self._known_leader = leader_sid.idx if leader_sid.leader else None
        self.external_commit = False       # host rules until a driver re-arms
        self.device_covered_from = None
        self._lease_until = -1.0
        self._lease_noted = False
        # A term/leader move invalidates our held follower lease (term
        # check would refuse anyway); grants we issued while leading
        # must KEEP blocking nothing — we no longer advance commit at
        # all — so clearing them is safe.
        self._fgrants.clear()
        self._flr_blocked_at.clear()
        self._flease_reset()
        self._election_deadline = None
        self._last_hb_seen = now
        self.group_contact = True
        self._pending.clear()
        self._inflight.clear()
        self._pending_reads.clear()    # clients retry against the new leader
        self._pending_joins.clear()    # joiners retry against the new leader
        self._pending_leaves.clear()   # operators retry against the new leader
        self._leader_verified_seq = -1

    # ------------------------------------------------------------------
    # voting
    # ------------------------------------------------------------------

    def _poll_vote_requests(self, now: float) -> None:
        """poll_vote_requests analog (dare_server.c:1526-1743)."""
        slots = self.regions.ctrl[Region.VOTE_REQ]
        if self.draining:
            # Graceful leave, removal committed: grant nothing — a
            # drained replica's vote must never count toward any
            # election (it is leaving the voter set).
            for i in range(len(slots)):
                slots[i] = None
            return
        # Non-members cannot campaign: an evicted/stale server's vote
        # requests must not even bump our term, or it can depose live
        # leaders forever (the disruptive-server problem; the reference
        # only processes votes from configuration members).
        reqs = [r for r in slots
                if r is not None and self.cid.contains(r.sid.idx)]
        if not any(r is not None for r in slots):
            return
        for i in range(len(slots)):
            slots[i] = None
        if not reqs:
            return
        self._await_contact = False         # group contact established
        # PreVote probes: answered without ANY voter state change.  An
        # acting leader always refuses (its authority is attested by the
        # quorum acks it keeps receiving, not by its hb timer).
        prevotes = [r for r in reqs if r.prevote]
        reqs = [r for r in reqs if not r.prevote]
        if prevotes:
            my = self.sid.sid
            last_idx, last_term = self._last_det()
            alive = (self.role == Role.LEADER
                     or (self._known_leader is not None
                         and now - self._last_hb_seen < self._hb_timeout))
            # Refuse UNCONDITIONALLY while we believe the leader is alive
            # (or are it): should_grant's known-leader rule only covers
            # cand.term <= ours, but prevote probes are always term+1 —
            # without this check a flapping follower still collects
            # pre-grants and deposes a healthy leader.
            if not alive:
                for r in prevotes:
                    if should_grant(r, my, last_idx, last_term, False):
                        self.t.ctrl_write(r.sid.idx, Region.PREVOTE_ACK,
                                          self.idx, r.sid.term)
        if not reqs:
            return
        if self.pre_election_hook is not None \
                and self.pre_election_hook() is False:
            # Hook veto (see start_election): refuse to vote THIS tick
            # rather than wedge the daemon; the candidate re-sends its
            # request every retry period.
            return
        best = best_vote_request(reqs)
        my = self.sid.sid
        # A higher term demotes a leader/candidate to follower BEFORE the
        # vote decision (Raft §5.1) — but WITHOUT adopting the term yet:
        # writing (best.term, own_idx) here would trip the no-vote-switch
        # rule below (same term, different idx) and refuse the very vote
        # we are about to consider, leaving the requester one term ahead
        # and us demoted — a dueling livelock where terms escalate
        # forever and no election ever completes.  The grant path adopts
        # the candidate's full SID; the refuse path bumps the bare term.
        if best.sid.term > my.term and self.role != Role.FOLLOWER:
            self.role = Role.FOLLOWER
            self._known_leader = None
            self._election_deadline = None
        last_idx, last_term = self._last_det()
        leader_alive = (self._known_leader is not None and
                        now - self._last_hb_seen < self._hb_timeout)
        # lease_guard is UNCONDITIONAL, not cfg.read_lease: the guard
        # protects the LEADER's lease, whose config this voter cannot
        # see — keying it on our own flag meant one skewed voter
        # (launched with read_lease=False) silently voided the cluster
        # lease safety argument by granting higher-term votes while the
        # leader's lease was live.  Liveness is unaffected: a dead
        # leader stops being leader_alive after hb_timeout, and PreVote
        # already refuses probes while the leader is alive.
        if not should_grant(best, my, last_idx, last_term, leader_alive,
                            lease_guard=True):
            # A stale candidate: our term may still need to advance so it
            # can retry (higher term observed).
            if best.sid.term > my.term:
                self.sid.update(Sid(best.sid.term, False, my.idx).word)
            return
        cand = best.sid
        # Adopt the candidate's SID (vote = our SID equals their [term|idx]).
        self.sid.update(Sid(cand.term, False, cand.idx).word)
        self.role = Role.FOLLOWER
        self._known_leader = None
        self._last_hb_seen = now          # give the candidate time to win
        self.group_contact = True
        self.bump("votes_granted")
        # Fence our log for the candidate BEFORE the vote leaves this
        # replica (restore_log_access grants the candidate's QP only,
        # dare_ibv_rc.c:2195-2255 — the reference likewise revokes
        # before votes).  ORDER IS SAFETY-CRITICAL: _replicate_vote
        # blocks on the wire with the node lock YIELDED, and an
        # un-fenced deposed leader could land a log write in that
        # window — the up-to-dateness decision above would then be
        # STALE, and its entry could COMMIT via our synchronous ack
        # while our vote elects a leader that lacks it (a committed
        # write the new leader then truncates).  Found live by the
        # adversarial-time nemesis (seed 94500): a SIGSTOPped leader
        # resumed into exactly this window and the linearizability
        # checker caught the lost write as a stale read.
        self.regions.grant_log_access(cand.idx, cand.term)
        # Durable vote: replicate to a majority (rc_replicate_vote,
        # dare_ibv_rc.c:1049-1109).
        self._replicate_vote(Sid(cand.term, False, cand.idx))
        # Ack: write our commit index into the candidate's vote_ack slot.
        self.t.ctrl_write(cand.idx, Region.VOTE_ACK, self.idx, self.log.commit)

    def _replicate_vote(self, vote: Sid) -> None:
        self.regions.ctrl[Region.PRV][self.idx] = vote.word
        for peer in self.cid.members():
            if peer != self.idx:
                self.t.ctrl_write(peer, Region.PRV, self.idx, vote.word)

    def _candidate_tick(self, now: float) -> None:
        """poll_vote_count analog (dare_server.c:1327-1518)."""
        my = self.sid.sid
        if my.idx != self.idx or my.leader:
            # Someone moved our SID — we granted a vote or saw a leader.
            self.role = Role.FOLLOWER
            return
        acks = self.regions.ctrl[Region.VOTE_ACK]
        mask = 0
        for peer, ack in enumerate(acks):
            if ack is not None:
                mask |= 1 << peer
        if have_majority(mask, self.cid, include_self=self.idx):
            # Followers' commit indices tell us the cluster commit floor.
            floor = max([a for a in acks if a is not None], default=0)
            self.log.advance_commit(min(floor, self.log.end))
            self.become_leader(now)
            return
        if self._election_deadline is not None and now >= self._election_deadline:
            # Election failed (split vote / lost majority): return to
            # follower and requalify through PreVote rather than blindly
            # escalating terms against a possibly-recovered leader.
            self.role = Role.FOLLOWER
            self._election_deadline = None
            self._prevote_deadline = None

    # ------------------------------------------------------------------
    # follower
    # ------------------------------------------------------------------

    def _follower_tick(self, now: float) -> None:
        """hb_receive_cb + replication-ack + apply reporting
        (dare_server.c:822-922, persist_new_entries :1792-1810)."""
        if self.draining:
            # Drained: no acks, no campaigns, no reports — and any
            # parked follower reads resolve as refusals (this replica
            # is leaving; clients re-find the group).
            self._flr_refuse_all("draining")
            return
        self._scan_heartbeats(now)
        self._serve_follower_reads(now)
        if now - self._last_hb_seen > self._hb_timeout:
            # Leader contact lost: the lease is not renewable and a
            # fresh election may be forming — bounce parked follower
            # reads to the (next) leader rather than stranding them.
            self._flr_refuse_all("no_leader")
            if self._await_contact:
                # No campaigning before group contact; fall back to
                # normal elections if nobody reaches us for a long time
                # (the whole group may have restarted together).
                if self._contact_deadline is None:
                    self._contact_deadline = now + 10 * self.cfg.elect_high
                if now < self._contact_deadline:
                    return
                self._await_contact = False
            self._prevote_tick(now)
            return
        self._prevote_deadline = None   # leader alive: abandon prevote
        leader = self._known_leader
        if leader is None or leader == self.idx:
            return
        # Ack replication: tell the leader how far our log extends
        # (rc_send_entries_reply analog, dare_ibv_rc.c:1828-1863).
        r = self.t.ctrl_write(leader, Region.REP_ACK, self.idx, self.log.end)
        # Report apply progress for pruning (apply_offsets slot).
        if now >= self._next_apply_report and r == WriteResult.OK:
            self.t.ctrl_write(leader, Region.APPLY_IDX, self.idx, self.log.apply)
            self._next_apply_report = now + self.cfg.apply_report_period
        # Keep the follower read lease warm while reads are flowing
        # (after the REP_ACK write above, so the leader's caught-up
        # check sees our freshest ack).
        self._maybe_request_flease(now)

    def _scan_heartbeats(self, now: float) -> None:
        hb = self.regions.ctrl[Region.HB]
        my = self.sid.sid
        best: Optional[Sid] = None
        for peer, word in enumerate(hb):
            if word is None:
                continue
            hb[peer] = None  # read-and-zero (__sync_fetch_and_and analog,
                             # dare_server.c:782)
            s = Sid.unpack(word)
            if not s.leader or s.idx != peer:
                continue
            if s.term < my.term:
                # Outdated leader: nudge it to step down by heartbeating
                # back our SID (rc_send_hb_reply, dare_ibv_rc.c:928-958).
                self.t.ctrl_write(peer, Region.HB, self.idx, my.word)
                continue
            if best is None or s.term > best.term:
                best = s
        if best is not None:
            self._await_contact = False     # group contact established
            if best.term > my.term or self._known_leader != best.idx:
                self.sid.update(Sid(best.term, False, best.idx).word)
                self.regions.grant_log_access(best.idx, best.term)
                self.become_follower(best.with_leader(True), now)
            elif self._hb_adapt is not None and self._last_hb_seen > 0:
                # Same leader, steady state: feed the observed gap to the
                # failure detector (gaps beyond the current timeout are
                # the false positives it widens itself over).
                self._hb_adapt.observe(now - self._last_hb_seen)
                self._hb_timeout = max(self.cfg.hb_timeout,
                                       self._hb_adapt.timeout)
            self._last_hb_seen = now
            self.group_contact = True

    # ------------------------------------------------------------------
    # leader
    # ------------------------------------------------------------------

    def _leader_tick(self, now: float) -> None:
        my = self.sid.sid
        if not self.cid.contains(self.idx):
            # Our own committed removal applied (graceful leave of the
            # leader, or an operator removal): C_new excludes us, so we
            # replicated it to a quorum of C_new before apply — step
            # down now instead of zombie-serving a group that will
            # elect without us (the classic leader-removal rule; the
            # reference's DIE_AF_COMMIT, dare_server.c:1870-1874).
            self.become_follower(Sid(my.term, False, self.idx), now)
            return
        if self._term_blank_pending:
            self._append_term_start(my)
        # Step down if a higher term appeared (hb_send_cb step-down check,
        # dare_server.c:927-993).
        hb = self.regions.ctrl[Region.HB]
        for peer, word in enumerate(hb):
            if word is None:
                continue
            hb[peer] = None
            s = Sid.unpack(word)
            if s.term > my.term:
                self.become_follower(s, now)
                return
        self._drain_pending(my)
        self._replicate(my, now)
        self._advance_commit(my)
        self._maybe_advance_resize(my, now)
        if now >= self._next_hb_send:
            self._send_heartbeats(my, now)
            self._next_hb_send = now + self.cfg.hb_period
        if now >= self._next_prune:
            self._maybe_prune(my)
            self._next_prune = now + self.cfg.prune_period
        self._serve_reads(now)

    def _drain_pending(self, my: Sid) -> None:
        """tailq drain -> log append (get_tailq_message,
        dare_ibv_ud.c:780-790).  This is the group-commit admission
        point: every op submitted since the last tick lands in the log
        HERE, in one pass, so K concurrent writers share the same
        replication windows (up to max_batch entries per log_write)
        instead of paying K rounds."""
        appended = 0
        for pr in self._pending:
            if pr.idx is not None:
                continue
            # Segmented record: earlier chunks first, as anonymous
            # entries ((0,0) skips per-entry dedup/reply — those fire
            # once, on the final chunk which carries the real ids).
            # Consumed destructively so a log-full pause resumes where
            # it left off instead of re-appending chunks.  near_full
            # (not is_full): client entries must leave slots for the
            # HEAD entry pruning appends, or a filled log can never be
            # pruned again.
            while pr.chunks and not self.log.near_full(3):
                self.log.append(my.term, data=pr.chunks.pop(0))
            if pr.chunks or self.log.near_full(3):
                continue
            pr.idx = self.log.append(my.term, req_id=pr.req_id,
                                     clt_id=pr.clt_id, data=pr.data)
            appended += 1
            # Stage span: the sampled op now holds a log index (the
            # group-commit admission hop).  Unsampled ops pay one
            # attribute test + one masked compare.
            if self.obs is not None \
                    and self.obs.spans.sampled(pr.req_id):
                self.obs.spans.stamp(pr.clt_id, pr.req_id, "append",
                                     idx=pr.idx, term=my.term)
        if appended:
            # Group-commit observability: one drain window per tick
            # that admitted entries; entries/windows is the achieved
            # coalescing factor.
            self.bump("drain_windows")
            self.bump("drain_entries", appended)
        self._pending = [p for p in self._pending
                         if p.idx is None or p.idx >= self.log.commit]

    def _replicate(self, my: Sid, now: float) -> None:
        """rc_write_remote_logs analog (dare_ibv_rc.c:1870-1948): adjust
        diverged followers, then write entry ranges."""
        for peer in self._replication_targets():
            # Stale-match detection: followers ack their log end every
            # tick (REP_ACK).  A follower that restarted with an empty
            # log still looks "adjusted" to us — our writes land
            # non-contiguously as silent no-ops — so if its acked end
            # sits below our next_idx without progressing for a
            # heartbeat timeout, the match state is stale: re-adjust.
            # (The reference re-reads follower state on every commit
            # loop instead, rc_write_remote_logs dare_ibv_rc.c:1883-1945.)
            # Background stream in flight: the tick thread must not
            # touch this peer AT ALL — its per-peer transport lock is
            # held frame-by-frame by the push thread, so even a
            # watchdog log_read_state here would park heartbeats behind
            # a (up to SNAP_END-cap) wire wait.  Checked BEFORE the
            # completion pop: the push thread writes _snap_push_done
            # and THEN leaves _snap_pushing, so passing this check
            # guarantees any completion is fully recorded — popping
            # first could miss both and launch a duplicate full push.
            if peer in self._snap_pushing:
                started = self._snap_push_started.get(peer, now)
                if now - started <= self.SNAP_PUSH_STALL_S:
                    continue
                # Wedged push (the stream normally errors out within a
                # few bounded chunk roundtrips when the receiver dies —
                # this is the backstop): abandon the slot so the next
                # incarnation of the peer is served, bump the push
                # generation so the late completion is ignored, and
                # re-adjust from scratch.
                self._snap_push_gen[peer] = \
                    self._snap_push_gen.get(peer, 0) + 1
                self._snap_pushing.discard(peer)
                self._snap_push_started.pop(peer, None)
                self._adjusted[peer] = False
                self.bump("snap_push_abandoned")
                self._note("watchdog", "snap_push_abandoned", peer=peer)
            # Consume a background snapshot-push completion: once the
            # peer installed, its acks fast-forward next_idx past our
            # head and the push branch below never runs again for it —
            # the completion (stats + cursor/failure bookkeeping) must
            # not strand.  Stale-term and abandoned-generation
            # completions are dropped.
            done = self._snap_push_done.pop(peer, None)
            if done is not None and done[0] == my.term \
                    and done[3] == self._snap_push_gen.get(peer, 0):
                self._finish_snap_push(peer, done[1], done[2], now,
                                       streamed=True)
            ack = self.regions.ctrl[Region.REP_ACK][peer]
            if (self._adjusted.get(peer, False) and ack is not None
                    and ack < self._next_idx.get(peer, 0)):
                prev_ack, since = self._ack_progress.get(peer, (None, now))
                if ack != prev_ack:
                    self._ack_progress[peer] = (ack, now)
                elif now - since > self.cfg.hb_timeout:
                    self._adjusted[peer] = False
                    self._ack_progress.pop(peer, None)
            else:
                self._ack_progress.pop(peer, None)
            just_adjusted = False
            if not self._adjusted.get(peer, False):
                state = self.t.log_read_state(peer)
                if state is None:
                    self._note_failure(peer, now)
                    continue
                # Remember the peer's applied determinant: the base a
                # delta snapshot can build on (the rejoiner "presents
                # its last applied (epoch, index)" via LogState).
                self._peer_applied[peer] = (state.applied_idx,
                                            state.applied_term)
                div = self.log.find_divergence(state.nc_determinants,
                                               state.commit)
                if div < state.end:
                    if self.t.log_set_end(peer, my, div) != WriteResult.OK:
                        self._note_failure(peer, now)
                        continue
                self._next_idx[peer] = div
                self._adjusted[peer] = True
                just_adjusted = True
            nxt = self._next_idx.get(peer, self.log.commit)
            # Fast-forward past entries the peer already holds: with the
            # device plane delivering entries directly into follower
            # logs (runtime.device_plane drain), the acked end routinely
            # runs AHEAD of our TCP write cursor — re-sending that span
            # would be pure idempotent waste.  Never on the iteration
            # that just (re)adjusted the peer: ``ack`` was read BEFORE
            # the adjustment truncated the follower to ``div``, so a
            # stale ack > div would skip entries the follower no longer
            # holds and stall replication until the watchdog re-adjusts.
            if (not just_adjusted and self._adjusted.get(peer, False)
                    and ack is not None and nxt < ack <= self.log.end):
                nxt = self._next_idx[peer] = ack
            if nxt < self.log.head:
                # Peer is behind our pruned head: push a snapshot
                # (leader-driven form of rc_recover_sm, the reference's
                # joiner instead RDMA-reads it, dare_ibv_rc.c:603-689),
                # then resume log replication just past it.
                #
                # DELTA FIRST: a rejoiner that presented a usable
                # applied determinant (durable-store replay primes it)
                # receives only the state delta past that point when
                # the SM's tracked history (its compaction floor)
                # permits — O(recent churn) instead of O(state).  Any
                # refusal (determinant moved, base below floor,
                # oversized delta) falls through to the full push in
                # this same pass.
                # Fresh determinant read: the adjustment-time capture
                # can predate the peer's whole lagging episode (a
                # still-"adjusted" peer reaches here via the stale
                # next_idx alone), and a stale base would silently
                # forfeit the delta path.  One cheap roundtrip before
                # a potentially O(state) push.
                det = self._peer_applied.get(peer)
                st_now = self.t.log_read_state(peer)
                if st_now is not None:
                    det = (st_now.applied_idx, st_now.applied_term)
                    self._peer_applied[peer] = det
                if det is not None and det[0] > 0:
                    d = self.make_snapshot_delta(det[0], det[1])
                    if d is not None:
                        dsnap, dep, dcid, dmembers, base = d
                        res = self.t.snap_push(peer, my, dsnap, dep,
                                               dcid, dmembers,
                                               delta_base=base)
                        if res == WriteResult.OK:
                            self.bump("delta_snapshots")
                            self._finish_snap_push(peer, res,
                                                   dsnap.last_idx, now)
                            continue
                        if res == WriteResult.FENCED:
                            self._adjusted[peer] = False
                            continue
                        if res == WriteResult.DROPPED:
                            self._note_failure(peer, now)
                            continue
                        # REFUSED: base no longer matches — the next
                        # adjustment refreshes the determinant; ship
                        # the full image below meanwhile.
                        self._peer_applied.pop(peer, None)
                # Large dumps stream in CRC'd resumable chunks (the
                # pusher holds one chunk, not the whole history);
                # small/in-memory dumps take the one-blob push.
                stream = (self.make_snapshot_stream_meta()
                          if hasattr(self.t, "snap_push_stream") else None)
                if stream is not None:
                    meta, ep_dump, snap_cid, members, total, gen, blob \
                        = stream

                    def read_chunk(off, n, _gen=gen, _blob=blob):
                        # Frozen-prefix fence: the dump is append-only
                        # UNLESS apply_snapshot replaced it (we were
                        # deposed and re-primed mid-stream) — then the
                        # prefix no longer matches the captured meta
                        # and the stream must abort, not ship bytes of
                        # someone else's history.  A captured BLOB
                        # (dump-less SMs) is immutable bytes: no fence
                        # needed.
                        if _blob is not None:
                            return _blob[off:off + n]
                        if getattr(self.sm, "dump_generation", 0) != _gen:
                            return b""
                        return self.sm.read_snapshot_chunk(off, n)

                    if self.async_snap_push:
                        # Off-tick streaming: BEGIN/CHUNK.../END run on
                        # a dedicated thread so this tick thread (and
                        # its heartbeats) never waits on a multi-second
                        # transfer OR the receiver's install.
                        #
                        # Concurrency safety of the chunk reads: the
                        # generation check alone is NOT atomic with the
                        # pread once they run off-tick — an install
                        # could replace the dump between them.  So the
                        # thread reads through a fd DUPLICATED NOW
                        # (under the lock, generation verified):
                        # installs give the dump a fresh inode
                        # (RelayStateMachine replace-never-truncate),
                        # so the pinned fd serves the immutable
                        # captured prefix forever; the generation check
                        # remains only as an early-abort optimization.
                        if blob is None and \
                                getattr(self.sm, "dump_generation",
                                        0) != gen:
                            self._snap_stream_cache = None
                            continue       # stale meta: retry next pass
                        dup_fd = None
                        pinned = None
                        if blob is None:
                            dupper = getattr(self.sm, "dup_dump_fd",
                                             None)
                            if dupper is not None:
                                dup_fd = dupper()
                            else:
                                # Ropes (dump-less SMs): pin the frozen
                                # capture — immune to rebuilds, like
                                # the dup'd fd pins the old inode.
                                pinner = getattr(self.sm,
                                                 "pin_dump_reader",
                                                 None)
                                if pinner is not None:
                                    pinned = pinner()
                        self._snap_pushing.add(peer)
                        self._snap_push_started[peer] = now
                        push_gen = self._snap_push_gen.get(peer, 0)
                        import os as _os
                        import threading as _threading

                        def _read_pinned(off, n, _gen=gen, _fd=dup_fd,
                                         _blob=blob, _pin=pinned):
                            if _blob is not None:
                                return _blob[off:off + n]  # immutable
                            if _pin is not None:
                                return _pin(off, n)        # frozen rope
                            if getattr(self.sm, "dump_generation",
                                       0) != _gen:
                                return b""        # early abort
                            if _fd is not None:
                                return _os.pread(_fd, n, off)
                            return self.sm.read_snapshot_chunk(off, n)

                        def _push(peer=peer, my=my, meta=meta,
                                  ep_dump=ep_dump, snap_cid=snap_cid,
                                  members=members, total=total,
                                  read_chunk=_read_pinned,
                                  dup_fd=dup_fd, push_gen=push_gen):
                            try:
                                r = self.t.snap_push_stream(
                                    peer, my, meta, ep_dump, snap_cid,
                                    members, total, read_chunk)
                            except Exception:        # noqa: BLE001
                                r = WriteResult.DROPPED
                            finally:
                                if dup_fd is not None:
                                    try:
                                        _os.close(dup_fd)
                                    except OSError:
                                        pass
                            self._record_push_done(
                                peer, my.term, r, meta.last_idx,
                                push_gen)

                        _threading.Thread(
                            target=_push, daemon=True,
                            name=f"apus-snappush-{self.idx}-{peer}"
                        ).start()
                        continue
                    res = self.t.snap_push_stream(
                        peer, my, meta, ep_dump, snap_cid, members,
                        total, read_chunk)
                    pushed_last_idx = meta.last_idx
                else:
                    snap, ep_dump, snap_cid, members = self.make_snapshot()
                    res = self.t.snap_push(peer, my, snap, ep_dump,
                                           snap_cid, members)
                    pushed_last_idx = snap.last_idx
                self._finish_snap_push(peer, res, pushed_last_idx, now,
                                       streamed=stream is not None)
                continue
            covered = (self.external_commit
                       and self.device_covered_from is not None
                       and nxt >= self.device_covered_from)
            if covered and not self._drain_stalled(peer, ack, now):
                batch = []     # entries ride the device plane; TCP
                               # carries only the commit offset
            else:
                batch = list(self.log.entries(nxt, nxt + self.cfg.max_batch))
            if not batch and self._commit_sent.get(peer, 0) >= self.log.commit:
                continue   # nothing new and remote commit is current
            if batch and self.obs is not None:
                # Stage span: replication fan-out shipping these
                # indices (first peer wins; later peers are no-ops).
                self.obs.spans.stamp_range("repl", batch[0].idx,
                                           batch[-1].idx + 1,
                                           term=my.term)
            res, acked_end = self.t.log_write(peer, my, batch,
                                              self.log.commit)
            if res == WriteResult.OK:
                if batch:
                    self._next_idx[peer] = batch[-1].idx + 1
                    self.bump("entries_replicated", len(batch))
                    # Per-peer replication windows (group-commit
                    # invariant: K concurrent ops ship in
                    # ceil(K/max_batch) windows per peer, not K).
                    self.bump("repl_windows")
                self._commit_sent[peer] = self.log.commit
                self._fail_count[peer] = 0
                if acked_end is not None and self.is_leader \
                        and self.current_term == my.term \
                        and self.cid.contains(peer):
                    # Synchronous ack (DCN transport): the reply carried
                    # the peer's authoritative post-write log end, so
                    # _advance_commit sees it THIS tick instead of after
                    # a follower REP_ACK tick + our next tick (~2 tick
                    # periods of commit latency at the production
                    # envelope).  Plain overwrite, not max: after a
                    # peer restart the smaller fresh end must land or
                    # the stale-match watchdog never fires.  Guarded on
                    # still-leader-at-my-term AND peer-still-a-member:
                    # the roundtrip released the node lock for up to the
                    # wire cap, during which a CONFIG apply may have
                    # cleared this slot (a removed member's REP_ACK must
                    # not be repopulated with the old occupant's end —
                    # a joiner reusing the slot would inherit a phantom
                    # ack) or leadership may have moved.
                    self.regions.ctrl[Region.REP_ACK][peer] = acked_end
                    # clock-exempt: region touch stamps feed the
                    # device-plane liveness mask, which compares them
                    # against ITS OWN time.monotonic() reads — both
                    # sides must stay in the REAL clock domain, outside
                    # the skewable lease/failure-detector seam
                    # (scripts/check_clock.py).
                    self.regions.touch(Region.REP_ACK, peer,
                                       time.monotonic())
            elif res == WriteResult.FENCED:
                self._adjusted[peer] = False   # lost access: re-adjust later
            else:
                self._note_failure(peer, now)

    def _record_push_done(self, peer: int, term: int, res,
                          pushed_last_idx: int, push_gen: int) -> None:
        """Background push thread -> tick thread handoff.  Drops by
        GENERATION before touching ANY per-peer push state: after a
        stall abandonment a SUCCESSOR push may own the slot, and a
        late completion from a dead generation overwriting
        ``_snap_push_done`` would discard the successor's pending
        completion (stranding its cursor/stats bookkeeping) — the PR 5
        backstop edge.  Runs WITHOUT the node lock, so generations
        being monotone is the belt against the check-then-write race:
        a NEWER pending completion is never clobbered."""
        if self._snap_push_gen.get(peer, 0) != push_gen:
            self.bump("snap_push_stale_done")
            return
        prev = self._snap_push_done.get(peer)
        if prev is not None and prev[3] > push_gen:
            return
        self._snap_push_done[peer] = \
            (term, res, pushed_last_idx, push_gen)
        self._snap_pushing.discard(peer)
        self._snap_push_started.pop(peer, None)

    def _finish_snap_push(self, peer: int, res: "WriteResult",
                          pushed_last_idx: int, now: float,
                          streamed: bool = False) -> None:
        """Common completion bookkeeping for snapshot pushes, inline or
        background (the async thread only records its result; all state
        mutation happens here, on the tick thread, under the lock)."""
        self._note("snap_push", str(res), peer=peer,
                   last_idx=pushed_last_idx, streamed=streamed)
        if res == WriteResult.OK:
            if streamed:
                self.bump("snapshots_streamed")
            self._next_idx[peer] = pushed_last_idx + 1
            self.bump("snapshots_pushed")
        elif res in (WriteResult.FENCED, WriteResult.REFUSED):
            # REFUSED: the peer's commit is already past the snapshot
            # (our view of it was stale) — re-read its real log state
            # instead of assuming the push landed.
            self._adjusted[peer] = False
        else:
            self._note_failure(peer, now)

    def _drain_stalled(self, peer: int, ack: Optional[int],
                       now: float) -> bool:
        """Is the peer's acked end failing to advance while entries it
        should be draining from its device shard are outstanding?  If
        so, TCP entry shipping must resume for it."""
        if ack is None:
            return True               # no evidence the drain works: ship
        if ack >= self.log.end:
            self._drain_wait.pop(peer, None)
            return False
        prev, since = self._drain_wait.get(peer, (None, now))
        if ack != prev:
            self._drain_wait[peer] = (ack, now)
            return False
        return now - since > self._hb_timeout

    def _replication_targets(self) -> list[int]:
        members = set(self.cid.members())
        if self.cid.state != CidState.STABLE:
            members.update(range(self.cid.extended_group_size))
            members &= {i for i in range(self.cid.extended_group_size)
                        if self.cid.contains(i)}
        return sorted(m for m in members if m != self.idx)

    def _advance_commit(self, my: Sid) -> None:
        """Commit rule from ack indices (the host mirror of the device
        psum; cf. dare_ibv_rc.c:1725-1758)."""
        if self.external_commit:
            return          # the device-plane quorum owns commit
        if self._flr_holdoff_until > 0:
            # Fresh-leadership hold-off (become_leader): predecessor-
            # granted follower-lease windows we cannot know about must
            # expire before our first commit.
            if self._fresh_now() < self._flr_holdoff_until:
                return
            self._flr_holdoff_until = -1.0
        acks = self.regions.ctrl[Region.REP_ACK]
        # Follower-lease write invalidation (Hermes, quantized to the
        # 840-bucket shard map): while a granted read-lease window is
        # live, commit must not advance past an entry WHOSE WRITTEN
        # BUCKETS its holder's granted read set covers until that
        # holder acks it — otherwise the holder could serve a local
        # read missing a client-acked write.  Entries outside every
        # live read set commit freely past a lagging holder (the
        # per-key relief; whole-log grants and unknown footprints
        # block on everyone, the pre-bucket rule).  flr_commit_cap
        # walks (commit, end] and returns the first blocked index;
        # blocked candidates fall through to smaller ones, so commit
        # still advances as far as the leases allow, and an
        # unreachable holder stalls a covered write for at most one
        # lease window.
        cap = self.flr_commit_cap() if self._fgrants else None
        candidates = sorted({a for a in acks if a is not None} | {self.log.end},
                            reverse=True)
        for c in candidates:
            if c <= self.log.commit:
                break
            if cap is not None and c > cap:
                continue        # lease-blocked: try a smaller candidate
            mask = 1 << self.idx
            for peer, a in enumerate(acks):
                if a is not None and a >= c:
                    mask |= 1 << peer
            if have_majority(mask, self.cid):
                # Raft safety: only commit prefixes ending in our own term
                # (the blank entry from become_leader guarantees progress).
                last = self.log.get(c - 1)
                if last is not None and last.term == my.term:
                    before = self.log.commit
                    if self.log.advance_commit(c) == c:
                        self.bump("commits")
                        if self.obs is not None:
                            # Stage span: quorum acked these indices.
                            self.obs.spans.stamp_range(
                                "quorum", before, c, term=my.term)
                break

    #: How long an EXTENDED resize tolerates a new slot with zero ack
    #: progress AND failure-detector evidence of death before the
    #: resize is ABORTED back to STABLE (see _maybe_advance_resize).
    #: A multiple of the eviction delay so a merely-slow joiner
    #: (snapshot install, cold boot) is never aborted.
    def _resize_abort_after(self) -> float:
        return max(2.0 * PERMANENT_FAILURE * self.cfg.fail_window,
                   20 * self._hb_timeout)

    def _maybe_advance_resize(self, my: Sid, now: float) -> None:
        """EXTENDED -> TRANSIT once every new slot has caught up
        (the reference moves to TRANSIT when the joiner's recovery
        completes; cf. dare_ibv_ud.c:1024-1037).  TRANSIT -> STABLE then
        happens on TRANSIT's apply (_apply_config).

        ABORT arm: a joiner that dies before catching up would pin the
        configuration in EXTENDED forever — TRANSIT waits on its acks
        and auto-removal refuses non-STABLE configs — wedging all
        future membership changes (the cluster still commits under the
        old majority, but can never resize or evict again).  When a
        new slot shows failure-detector evidence of death
        (PERMANENT_FAILURE strikes) and no ack progress for
        _resize_abort_after, the resize is cleanly aborted: one CONFIG
        entry back to STABLE at the old size (Cid.abort_extend), and
        the joiner — if it ever returns — re-runs the join protocol."""
        if self.cid.state != CidState.EXTENDED or self._transit_pending:
            self._resize_stall = None
            return
        # Another CONFIG in flight (e.g. an auto-removal built from the
        # same cid): appending TRANSIT now would apply after it at the
        # same epoch and resurrect the removed member.
        if any(e.type == EntryType.CONFIG
               for e in self.log.entries(self.log.apply)):
            return
        acks = self.regions.ctrl[Region.REP_ACK]
        new_members = [m for m in self.cid.members() if m >= self.cid.size]
        if not new_members:
            return
        ready = True
        for m in new_members:
            a = acks[m]
            if a is None or a < self.log.commit:
                ready = False
        if not ready:
            snap = tuple(acks[m] for m in new_members)
            prev = self._resize_stall
            if prev is None or prev[0] != snap:
                self._resize_stall = (snap, now)
            elif now - prev[1] > self._resize_abort_after() and any(
                    self._fail_count.get(m, 0) >= PERMANENT_FAILURE
                    and m not in self._snap_pushing
                    and not self.t.peer_failure_was_timeout(m)
                    for m in new_members) and not self.log.near_full(1):
                self.log.append(my.term, type=EntryType.CONFIG,
                                cid=self.cid.abort_extend())
                self._resize_stall = None
                self.bump("resize_aborts")
                self._note("config", "resize_abort",
                           epoch=self.cid.epoch)
            return
        self._resize_stall = None
        if self.log.near_full(1):
            return          # reserve the last slot for the HEAD entry
        self.log.append(my.term, type=EntryType.CONFIG,
                        cid=self.cid.to_transit())
        self._transit_pending = True

    def _send_heartbeats(self, my: Sid, now: float) -> None:
        """rc_send_hb analog (dare_ibv_rc.c:868-926).  Doubles as the
        read-lease renewal round (NodeConfig.read_lease): a quorum of
        acknowledged HB writes — each ack's echoed SID proving the peer
        was still at our term when it replied, and the peer server
        having stamped its _last_hb_seen at delivery — extends the
        lease to t0 + hb_timeout*(1 - lease_margin), anchored at the
        round's START so the wire time is never credited."""
        if self.hb_sink is not None:
            # Multi-group runtime: register with the daemon's HB
            # coalescer; ONE OP_HB_MULTI frame per peer will carry
            # every registered group, and hb_round_finish is called
            # back per group with the per-peer results.
            self.hb_sink(self, my, now)
            return
        t0 = now
        # Reply-time SID echoes recorded by the transport per peer
        # ((sid_word, monotonic) — NetTransport.peer_sid_seen); absent
        # on transports that don't echo (the deterministic sim), where
        # multi-member leases simply never engage.
        hints = getattr(self.t, "peer_sid_seen", None)
        results: dict[int, tuple] = {}
        for peer in self._replication_targets():
            res = self.t.ctrl_write(peer, Region.HB, self.idx, my.word)
            if res == WriteResult.FENCED:
                # The peer's fence table says our slot's incarnation
                # was removed (incarnation fencing): affirmative
                # removal evidence, counted in hb_round_finish.
                results[peer] = ("fenced", None)
                continue
            if res != WriteResult.OK:
                results[peer] = ("fail", None)
                continue
            echo = None
            if hints is not None:
                seen = hints.get(peer)
                if seen is not None and seen[1] >= t0:
                    echo = seen[0]
            results[peer] = ("ok", echo)
        self.hb_round_finish(my, t0, results)

    def hb_round_finish(self, my: Sid, t0: float,
                        results: dict[int, tuple]) -> None:
        """Account one heartbeat round — direct fan-out and coalesced
        (OP_HB_MULTI) alike.  ``results[peer] = (status, echo_word)``
        with status in {"ok", "fenced", "fail"}; ``echo_word`` is the
        peer's reply-time SID from THIS round (None = no echo — the
        peer never counts toward the lease quorum).  Runs under the
        node lock; the wire work already happened (and yielded the
        lock), so leadership is re-validated before the lease renews."""
        mask = 1 << self.idx
        fenced = 0
        for peer, (status, echo) in results.items():
            if status == "fenced":
                fenced += 1
                continue
            if status != "ok":
                self._note_failure(peer, t0)
                continue
            # A reachable peer is not failing: reset the counter so
            # sporadic drops (async dial, transient congestion) far
            # apart never accumulate to PERMANENT_FAILURE.
            self._fail_count[peer] = 0
            if echo is not None and Sid.unpack(echo).term <= my.term:
                mask |= 1 << peer
        self.bump("hb_sent")
        now = t0
        if fenced >= quorum_size(self.cid.size):
            # A quorum of peers affirms our slot was removed at an
            # epoch past our incarnation — we are a zombie ex-leader
            # that never applied its own removal (partitioned through
            # it).  Step down; the runtime's exclusion watchdog owns
            # re-admission.  Without this, such a leader idles forever
            # (nobody heartbeats a non-member, so its hb-age never
            # grows and the watchdog never fires) while client
            # requests burn timeouts against it.
            self.bump("fenced_stepdowns")
            self.become_follower(Sid(my.term, False, self.idx), now)
            return
        if not self.cfg.read_lease or self.cid.state != CidState.STABLE:
            return      # no lease across joint-consensus quorums
        # The fan-out yields the node lock on the wire: renew only if
        # still leading the SAME term (a lease for a term we no longer
        # lead would outlive our authority).
        cur = self.sid.sid
        if not (self.role == Role.LEADER and cur.leader
                and cur.term == my.term and cur.idx == self.idx):
            return
        if have_majority(mask, self.cid):
            self._lease_until = max(
                self._lease_until,
                t0 + self.cfg.hb_timeout * (1.0 - self.cfg.lease_margin))
            self.bump("lease_renewals")
            if not self._lease_noted:
                # Grant edge only (per-renewal notes would flood the
                # flight ring at heartbeat rate).
                self._lease_noted = True
                self._note("lease", "grant", term=my.term)

    def _serve_reads(self, now: float) -> None:
        """Answer pending linearizable reads (ep_dp_reply_read_req
        analog): requires apply >= wait_idx and a leadership proof
        obtained AFTER the read was registered (Raft read-index rule —
        a proof predating the read could miss a concurrent election)."""
        if not self._pending_reads:
            return
        if not any(self.log.apply >= r.wait_idx for r in self._pending_reads):
            return
        # Fresh clock, not the tick-start ``now``: the heartbeat
        # fan-out earlier this tick blocks on wire roundtrips (lock
        # yielded), so by the time reads are served the stamp can be
        # arbitrarily stale — and stale-small is the UNSAFE direction
        # for ``now < _lease_until``.
        if self._lease_valid(self._fresh_now()):
            # Lease path: the quorum-acked heartbeat round IS the
            # leadership proof for every read registered before it —
            # serve all ready reads from local state, no majority round.
            for r in self._pending_reads:
                if self.log.apply < r.wait_idx:
                    continue
                try:
                    r.reply = self.sm.query(r.data)
                except Exception:
                    r.reply = None
                    r.error = True
                r.done = True
                self.reads_done += 1
                self.bump("lease_reads")
            self._pending_reads = [r for r in self._pending_reads
                                   if not r.done]
            return
        if self._lease_noted:
            # A read is paying the majority round though a lease was
            # previously held: the lease lapsed (black-box edge).
            self._lease_noted = False
            self._note("lease", "lapse", term=self.current_term)
        newest = max(r.registered_at for r in self._pending_reads
                     if self.log.apply >= r.wait_idx)
        if self._leader_verified_seq < newest:
            self.bump("readindex_verifies")
            if not self._verify_leadership(now):
                return
        # Re-derive the ready set AFTER verification: the transport
        # yields the node lock on the wire, so _pending_reads (and our
        # role) may have changed mid-verification.
        for r in self._pending_reads:
            if self.log.apply < r.wait_idx                     or r.registered_at > self._leader_verified_seq:
                continue               # needs a fresher proof: next tick
            try:
                r.reply = self.sm.query(r.data)
            except Exception:
                # A malformed read must fail that read, not the replica.
                r.reply = None
                r.error = True
            r.done = True
            self.reads_done += 1
        self._pending_reads = [r for r in self._pending_reads if not r.done]

    def _verify_leadership(self, now: float) -> bool:
        """rc_verify_leadership analog (dare_ibv_rc.c:1182-1280): read a
        majority of remote SIDs and confirm they still follow us in our
        term.  The proof covers reads registered up to the sequence
        captured BEFORE the remote reads begin."""
        my = self.sid.sid
        seq_at_start = self._reg_seq
        mask = 1 << self.idx
        for peer in self.cid.members():
            if peer == self.idx:
                continue
            word = self.t.ctrl_read(peer, Region.RSID, peer)
            if word is None:
                continue
            s = Sid.unpack(word)
            if s.term > my.term:
                return False           # we are deposed
            if s.term == my.term and s.idx == self.idx:
                mask |= 1 << peer      # peer's SID records following us
        # The remote reads yield the node lock: we may have stepped down
        # (or been re-elected in a later term) mid-verification.  The
        # proof is only valid if we are STILL the leader of ``my.term``.
        cur = self.sid.sid
        if not (self.role == Role.LEADER and cur.leader
                and cur.term == my.term and cur.idx == self.idx):
            return False
        if have_majority(mask, self.cid):
            self._leader_verified_seq = seq_at_start
            return True
        return False

    def _note_failure(self, peer: int, now: float) -> None:
        """check_failure_count analog (dare_server.c:1189-1227): after
        PERMANENT_FAILURE failures — counted at most once per fail_window —
        the leader removes the peer via a CONFIG entry.  The COUNTING
        always runs (the resize-abort watchdog consumes the counter
        even with auto_remove off); only the removal itself is gated
        on cfg.auto_remove."""
        if not self.t.peer_established(peer):
            # Never reached at its current address: a cold-starting or
            # still-joining member, not a failed one.  The reference can
            # only see WC errors on QPs that completed connection setup;
            # counting pre-establishment failures here would auto-remove
            # slow-booting replicas (first dial + backoff can outlast
            # PERMANENT_FAILURE * fail_window on process launch).
            return
        if self.t.peer_failure_was_timeout(peer):
            # Timeout on an established connection: the peer's process
            # is alive (it holds the connection open) but busy — e.g.
            # installing a multi-second snapshot after a deep-history
            # restart.  The reference's counter only sees WC errors,
            # which require connection-level death; a busy-but-connected
            # peer is never auto-removed (dare_ibv_rc.c:3202-3314).
            # Counting these here produced an evict/rejoin LIVELOCK: the
            # leader evicted a joiner mid-install, it rejoined still
            # behind, the next install blocked it again (observed in a
            # 30-minute soak, epochs climbing 2 per ~4 s until a kill
            # during the churn stalled the group).
            return
        if now - self._fail_last.get(peer, -1e9) < self.cfg.fail_window:
            return
        self._fail_last[peer] = now
        n = self._fail_count.get(peer, 0) + 1
        self._fail_count[peer] = n
        if not self.cfg.auto_remove:
            return
        if n >= PERMANENT_FAILURE and self.cid.contains(peer):
            # Reference guards (check_failure_count): removal only from
            # a STABLE configuration (dare_server.c:1202), and never so
            # deep that the remaining member count drops below the
            # quorum the unchanged ``size`` denominator demands —
            # removal does not relax quorum (get_group_size returns the
            # size field, wait_for_majority thresholds on size/2), so a
            # config with fewer members than quorum_size(size) could
            # never commit or elect again: a permanent wedge no heal or
            # restart repairs.  The reference avoids it by dying at
            # connections <= size/2 before appending such a removal
            # (:1213-1217); refusing the removal keeps the same floor
            # without the suicide.
            if self.cid.state != CidState.STABLE:
                return
            if len(self.cid.members()) - 1 < quorum_size(self.cid.size):
                return
            in_flight = any(e.type == EntryType.CONFIG
                            for e in self.log.entries(self.log.apply))
            if not in_flight and not self.log.near_full(1):
                # Epoch bump: every membership-changing CONFIG must be
                # ordered; an unbumped removal would share an epoch with
                # a later join and leave replicas with incomparable cids.
                self.log.append(
                    self.sid.sid.term, type=EntryType.CONFIG,
                    cid=dataclasses.replace(
                        self.cid.without_server(peer),
                        epoch=self.cid.epoch + 1))
                self.bump("auto_removes")
                self._note("config", "auto_remove", peer=peer,
                           epoch=self.cid.epoch + 1)

    def _maybe_prune(self, my: Sid) -> None:
        """log_pruning analog (dare_server.c:1996-2067).  P1: only applied
        entries; P2: every live member has applied them; P3: head advance
        is itself committed (HEAD entry) before the leader prunes."""
        if self._pending_head is not None:
            return  # HEAD in flight; applied in _apply_committed
        floor = self.log.apply
        for peer in self.cid.members():
            if peer == self.idx:
                continue
            a = self.regions.ctrl[Region.APPLY_IDX][peer]
            if a is None:
                return
            floor = min(floor, a)
        if self.log.is_full:
            # The slot classes (clients 3, device drain / CONFIG 1)
            # normally leave room for the HEAD entry; a ring that
            # filled anyway (e.g. a term blank took the last slot) is
            # relieved by dropping the locally-applied prefix.
            self._emergency_free()
        if floor > self.log.head and not self.log.is_empty \
                and not self.log.is_full:
            self.log.append(my.term, type=EntryType.HEAD, head=floor)
            self._pending_head = floor

    # ------------------------------------------------------------------
    # apply
    # ------------------------------------------------------------------

    def _emergency_free(self) -> None:
        """Last-resort LOCAL pruning when the ring is completely full:
        drop the locally-APPLIED prefix without a HEAD entry.  Safe on
        any role: applied state lives in the SM (+ snapshot cache +
        durable store), repair/adjustment reads start at the commit
        point, and a peer that later needs a dropped entry is served by
        snapshot push (the nxt < head path).  Windowed pruning (P1-P3
        HEAD entries) remains the steady-state mechanism; this only
        breaks full-ring deadlocks — e.g. a new leader whose log is
        full of old-term entries could otherwise never append the
        current-term entry that lets commit advance."""
        if self.log.is_full and self.log.apply > self.log.head:
            self.log.advance_head(self.log.apply)
            self._pending_head = None
            self.bump("emergency_prunes")

    def _apply_committed(self, now: float) -> None:
        """apply_committed_entries analog (dare_server.c:1815-1974)."""
        while self.log.apply < self.log.commit:
            e = self.log.get(self.log.apply)
            assert e is not None
            if e.type == EntryType.CSM:
                # Apply-time dedup: a failover retry can legally append
                # a second entry with the same (clt_id, req_id) — e.g.
                # the old leader's entry survives the election and the
                # client's retry lands on the new leader before apply
                # catches up.  Only the first execution runs; duplicates
                # are skipped (client req_ids are per-client monotone,
                # starting at 1).
                dup = (e.req_id > 0 and
                       self.epdb.duplicate_of_applied(e.clt_id, e.req_id))
                data = e.data
                if segment.is_chunk(data):
                    if dup:
                        # Logical record already applied in a previous
                        # incarnation: discard any buffered chunks.
                        self._seg.prune(e.clt_id, e.req_id)
                        data = None
                    else:
                        final, full = self._seg.feed(data)
                        if not final:
                            # Intermediate chunk: buffered only; the SM,
                            # dedup, reply, and upcalls all fire on the
                            # final chunk with the reassembled record.
                            self._applied_det = e.determinant()
                            self.log.advance_apply(e.idx + 1)
                            self.bump("applied")
                            continue
                        if full is None:
                            # The group was evicted under the orphan
                            # bound (Reassembler.MAX_GROUPS/MAX_BYTES)
                            # — deterministically, so every replica
                            # answers this final identically (empty
                            # reply).  Loud: >4096 concurrent partial
                            # groups means something is very wrong.
                            self.bump("seg_incomplete")
                            data = None
                        else:
                            data = full
                if dup:
                    reply = dup.last_reply
                elif data is None:
                    reply = b""
                else:
                    reply = self.sm.apply(e.idx, data)
                    # Deterministic REFUSED applies (elastic-group
                    # bucket fences: a write into a frozen/departed
                    # migration bucket no-ops identically on every
                    # replica) are never dedup-noted — the op did not
                    # take effect, so the client's re-routed retry
                    # must re-enter admission fresh instead of being
                    # answered from a cached refusal (or, worse, a
                    # LATER req_id's cached reply via the monotone
                    # dedup rule).
                    if reply is None or not reply.startswith(
                            REFUSED_REPLY_PREFIX):
                        self.epdb.note_applied(e.clt_id, e.req_id,
                                               e.idx, reply)
                    # Upcalls observe the LOGICAL record (reassembled
                    # payload), never envelope chunks — persistence and
                    # proxy replay stay segmentation-oblivious.
                    self.committed_upcalls.append(
                        e if data is e.data
                        else dataclasses.replace(e, data=data))
                if self.obs is not None and e.req_id > 0 \
                        and self.obs.spans.sampled(e.req_id):
                    # Stage span: applied on THIS replica (leader opens
                    # the op; followers ring-only, keyed (req, term,
                    # idx) for the cross-replica stitch).
                    self.obs.spans.stamp(e.clt_id, e.req_id, "apply",
                                         idx=e.idx, term=e.term,
                                         open_new=False)
                pr = self._inflight.pop((e.clt_id, e.req_id), None)
                if pr is not None:
                    # Sentinel contract: reply stays None until THIS
                    # client's entry applied, then is always bytes — the
                    # client service acks only on it (never inferred
                    # from apply position, which a truncated entry's
                    # index could falsely satisfy).
                    pr.reply = reply if reply is not None else b""
            elif e.type == EntryType.CONFIG:
                self._apply_config(e, now)
            elif e.type == EntryType.HEAD:
                self._applied_det = e.determinant()
                self.log.advance_apply(e.idx + 1)
                self.log.advance_head(min(e.head, self.log.apply))
                if self.is_leader:
                    self._pending_head = None
                continue
            self._applied_det = e.determinant()
            self.log.advance_apply(e.idx + 1)
            self.bump("applied")
        if self.log.is_full:
            # Followers never run _maybe_prune; a ring filled by
            # replicated writes/drains frees its applied prefix here.
            self._emergency_free()

    def _apply_config(self, e: LogEntry, now: float) -> None:
        """CONFIG application incl. resize progression
        (dare_server.c:1888-1930)."""
        assert e.cid is not None
        new_cid = e.cid
        if new_cid.epoch < self.cid.epoch:
            return
        # Newly-added members: (a) failure-count grace — their endpoint
        # needs (re)dialing, and counting those initial drops would evict
        # a joiner the moment it was admitted; (b) reset per-peer
        # replication state — a reused slot (rejoin after removal) must
        # be re-adjusted from scratch, or the stale next_idx silently
        # stops the new occupant from ever receiving the log.
        for m in new_cid.members():
            if not self.cid.contains(m) and m != self.idx:
                self._fail_count.pop(m, None)
                self._fail_last[m] = now + 10 * self.cfg.hb_timeout
                self._adjusted.pop(m, None)
                self._next_idx.pop(m, None)
                self._commit_sent.pop(m, None)
                self.regions.ctrl[Region.REP_ACK][m] = None
                self.regions.ctrl[Region.APPLY_IDX][m] = None
        # Removed slots: record the removal epoch as the slot's fence —
        # the peer server then drops inbound ctrl writes (REP_ACK,
        # votes, heartbeats) from any incarnation admitted before it,
        # so a stale ex-occupant can never be credited to the slot's
        # next tenant nor count while the slot is empty.  Also clear
        # the region slots NOW: a phantom REP_ACK/APPLY_IDX left from
        # the old occupant must not survive into an empty slot (it
        # doesn't count toward quorum while non-member, but a pruning
        # floor read or a stale-looking ack at readmission would see
        # it).
        for m in self.cid.members():
            if not new_cid.contains(m):
                if new_cid.epoch > self.fence_epochs.get(m, 0):
                    self.fence_epochs[m] = new_cid.epoch
                self.regions.ctrl[Region.REP_ACK][m] = None
                self.regions.ctrl[Region.APPLY_IDX][m] = None
        if new_cid.contains(self.idx):
            # A configuration that includes us attests our tenancy to
            # its epoch (monotone; see install_snapshot for why
            # inflating past the admission epoch is safe).
            self.incarnation = max(self.incarnation, new_cid.epoch)
        self._note("config", epoch=new_cid.epoch,
                   state=new_cid.state.name, size=new_cid.size,
                   bitmask=new_cid.bitmask, idx=e.idx, term=e.term)
        self.cid = new_cid
        # Learn the joiner's address (idempotent-join dedup).  A reused
        # slot evicts the previous occupant's address claim, and slots
        # leaving the configuration drop theirs — a stale claim would
        # answer a removed-then-rejoining address "already member" for a
        # slot now owned by a DIFFERENT server, spawning two daemons
        # with the same replica idx.
        if e.data:
            try:
                slot_s, addr_s = e.data.decode().split(" ", 1)
                slot = int(slot_s)
            except ValueError:
                pass
            else:
                self._member_addrs = {a: s for a, s
                                      in self._member_addrs.items()
                                      if s != slot}
                self._member_addrs[addr_s] = slot
        self._member_addrs = {a: s for a, s in self._member_addrs.items()
                              if new_cid.contains(s)}
        # Runtime notification (peer-table update on join, role of the
        # CFG_REPLY + poll_config_entries pair, dare_server.c:2133-2187).
        self.config_upcalls.append(e)
        # Resolve join handles waiting on this entry.  "Applied" is not
        # "admitted": a resize ABORT that raced the join also satisfies
        # entry_idx <= e.idx — the joiner's slot is then absent from
        # the applied configuration and the handle resolves REFUSED
        # (the joiner backs off and retries) instead of done.
        for addr, pj in list(self._pending_joins.items()):
            if pj.entry_idx is not None and pj.entry_idx <= e.idx:
                if new_cid.contains(pj.slot):
                    pj.done = True
                else:
                    pj.refused = True
                del self._pending_joins[addr]
        # Resolve graceful-leave handles (OP_LEAVE waits on these).
        for slot, pl in list(self._pending_leaves.items()):
            if pl.entry_idx is not None and pl.entry_idx <= e.idx:
                pl.done = True
                del self._pending_leaves[slot]
        if self.is_leader:
            # Drive the joint-consensus ladder forward.
            if new_cid.state == CidState.EXTENDED:
                pass  # wait: new servers must catch up before TRANSIT
                      # (_maybe_advance_resize)
            elif new_cid.state == CidState.TRANSIT:
                self._transit_pending = False
                if not self.log.near_full(1):
                    self.log.append(self.sid.sid.term,
                                    type=EntryType.CONFIG,
                                    cid=new_cid.stabilize())
        # Suicide path: removed from the configuration (DIE_AF_COMMIT
        # analog, dare_server.c:1870-1874) — handled by the runtime
        # observing cid.contains(self.idx) == False.
