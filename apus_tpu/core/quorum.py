"""Quorum / commit rules, including dual-majority transitional configs.

The reference computes commit as "quorum of per-entry reply[] acks"
scattered into the leader's log by followers (dare_ibv_rc.c:1650-1758),
with the dual-majority j-loop for TRANSIT configurations
(wait_for_majority, dare_ibv_rc.c:2799-2957).  Here the same rule is a
pure function over ack bitmasks — the exact computation the device plane
runs as a psum over a replica-axis vote mask (apus_tpu.ops.commit).
"""

from __future__ import annotations

from apus_tpu.core.cid import Cid, CidState


def quorum_size(n: int) -> int:
    return n // 2 + 1


def popcount_masked(ack_mask: int, member_mask: int) -> int:
    return bin(ack_mask & member_mask).count("1")


def have_majority(ack_mask: int, cid: Cid, include_self: int | None = None) -> bool:
    """True iff ``ack_mask`` satisfies *every* majority the configuration
    requires.  ``include_self`` adds the caller's own implicit ack (the
    leader/candidate counts itself: cf. vote counting dare_server.c:1340-1373).

    STABLE/EXTENDED: majority of the old ``size`` voting slots only.
    TRANSIT: majority of both the old-size and the new-size slot sets.
    """
    if include_self is not None:
        ack_mask |= 1 << include_self
    old_mask = cid.bitmask & ((1 << cid.size) - 1)
    if popcount_masked(ack_mask, old_mask) < quorum_size(cid.size):
        return False
    if cid.state == CidState.TRANSIT:
        new_mask = cid.bitmask & ((1 << cid.new_size) - 1)
        if popcount_masked(ack_mask, new_mask) < quorum_size(cid.new_size):
            return False
    return True


def commit_index(acks_by_idx: dict[int, int], commit: int, end: int,
                 cid: Cid, leader_idx: int) -> int:
    """New commit index given per-entry ack bitmasks.

    Commit advances over the longest *prefix* of [commit, end) whose every
    entry has majority acks (the reference advances commit entry-by-entry
    in order, dare_ibv_rc.c:1725-1758).  The leader's own ack is implicit.
    """
    new_commit = commit
    for idx in range(commit, end):
        if have_majority(acks_by_idx.get(idx, 0), cid, include_self=leader_idx):
            new_commit = idx + 1
        else:
            break
    return new_commit
