"""Record segmentation: oversized commands as device-eligible chunks.

The reference's request envelope is TCP-rcvbuf-sized — records up to
87,380 B (/root/reference/src/include/dare/message.h:7; apus_wire.h
keeps the constant) ride whole through its byte-ring log.  Our fixed-
slot device log carries at most ``slot_bytes`` (4 KiB) of wire-encoded
entry per row (ops.logplane), so a large record must be CUT into chunk
entries at submit and REASSEMBLED into one logical record at apply:

- ``split()`` wraps each chunk in a small envelope carrying the real
  ``(clt_id, req_id)`` of the logical record plus ``(seq, total)``;
  every chunk then travels as an ordinary log entry — replicated,
  quorum-committed, and device-plane-eligible like any other.  Chunk
  entries other than the last carry ``(clt_id=0, req_id=0)`` so the
  endpoint-DB dedup and reply machinery fire exactly once, on the FINAL
  chunk, which carries the real ids (core.node.submit).
- ``Reassembler.feed()`` buffers chunks by ``(clt_id, req_id)`` and
  yields the full payload when the final chunk applies.  Chunks
  overwrite by ``seq``, which makes a group idempotent across the
  failover-retry shape: a half-appended group truncated by an election
  is simply overwritten by the client's retry at the new leader — and
  exactly-once still holds because the dedup decision rides the final
  chunk's real ``(clt_id, req_id)`` (apply-time dedup, node.py).

Any payload that happens to START with the envelope magic is escaped by
wrapping it as a single-chunk group (``maybe_wrap``) so the apply path
can treat the magic prefix as authoritative.
"""

from __future__ import annotations

import struct
from typing import Optional

#: Envelope magic: an improbable prefix for real client payloads
#: (escaped via maybe_wrap when it does occur).
MAGIC = b"\xa5SG1"
_HDR = struct.Struct("<4sQQII")      # magic | clt_id | req_id | seq | total
OVERHEAD = _HDR.size

#: The reference's maximum request record (message.h:7).
MAX_RECORD = 87380


def is_chunk(payload: bytes) -> bool:
    return payload.startswith(MAGIC) and len(payload) >= _HDR.size


def parse(payload: bytes) -> tuple[int, int, int, int, bytes]:
    """-> (clt_id, req_id, seq, total, piece)."""
    magic, clt, req, seq, total = _HDR.unpack_from(payload, 0)
    return clt, req, seq, total, payload[_HDR.size:]


def _wrap(clt_id: int, req_id: int, seq: int, total: int,
          piece: bytes) -> bytes:
    return _HDR.pack(MAGIC, clt_id, req_id, seq, total) + piece


def split(data: bytes, chunk: int, clt_id: int,
          req_id: int) -> list[bytes]:
    """Cut ``data`` into envelope-wrapped pieces of at most ``chunk``
    payload bytes each (at least one)."""
    assert chunk > 0
    pieces = [data[o:o + chunk] for o in range(0, len(data), chunk)] \
        or [b""]
    total = len(pieces)
    return [_wrap(clt_id, req_id, k, total, p)
            for k, p in enumerate(pieces)]


def maybe_wrap(data: bytes, clt_id: int, req_id: int) -> Optional[bytes]:
    """Escape a real payload that collides with the magic prefix by
    wrapping it as a single-chunk group; None when no escape needed."""
    if data.startswith(MAGIC):
        return _wrap(clt_id, req_id, 0, 1, data)
    return None


class Reassembler:
    """Apply-side chunk buffer.  Deterministic across replicas: all
    replicas apply the same entries in the same order, so every replica
    holds the SAME buffer after the same applied prefix — which is what
    lets the buffer travel inside snapshots (``dump``/``load``,
    models.sm.Snapshot.seg): an installer resumes groups whose early
    chunks lie below the snapshot point.

    A group whose final chunk was truncated by an election is orphaned
    (its client's retry runs under a new capture id); orphans are
    bounded by ``MAX_GROUPS``/``MAX_BYTES`` eviction in feed order — a
    deterministic sequence number that ``dump`` PRESERVES, so replicas
    that installed a snapshot evict the same groups as replicas that
    applied the prefix natively (eviction order is part of the
    replicated state: evicting differently would diverge the SMs when
    an evicted group's final applies)."""

    MAX_GROUPS = 4096
    #: Byte cap on buffered pieces: bounds Snapshot.seg (orphans could
    #: otherwise bloat every snapshot push / store record unboundedly).
    MAX_BYTES = 16 * 1024 * 1024

    def __init__(self) -> None:
        #: key -> (seq -> piece, feed_seq)
        self._groups: dict[tuple[int, int],
                           tuple[dict[int, bytes], int]] = {}
        self._feed_seq = 0
        self._bytes = 0

    @property
    def pending(self) -> int:
        return len(self._groups)

    def _evict(self) -> None:
        while self._groups and (len(self._groups) > self.MAX_GROUPS
                                or self._bytes > self.MAX_BYTES):
            oldest = min(self._groups, key=lambda k: self._groups[k][1])
            group, _ = self._groups.pop(oldest)
            self._bytes -= sum(len(p) for p in group.values())

    def feed(self, payload: bytes) -> tuple[bool, Optional[bytes]]:
        """Absorb one applied chunk.  Returns (final, full_payload):
        ``final`` is True when this chunk closes its group — then
        ``full_payload`` is the reassembled record, or None if earlier
        chunks are missing (the group was evicted under the
        MAX_GROUPS/MAX_BYTES orphan bound — deterministically, on every
        replica alike; counted loudly by the caller)."""
        clt, req, seq, total, piece = parse(payload)
        key = (clt, req)
        entry = self._groups.get(key)
        group = entry[0] if entry is not None else {}
        if seq in group:
            self._bytes -= len(group[seq])
        group[seq] = piece
        if seq != total - 1:
            self._feed_seq += 1
            self._bytes += len(piece)
            self._groups[key] = (group, self._feed_seq)
            self._evict()
            return False, None
        if key in self._groups:
            self._groups.pop(key)
            self._bytes -= sum(len(p) for p in group.values()) - len(piece)
        if len(group) != total:
            return True, None
        return True, b"".join(group[k] for k in range(total))

    def prune(self, clt_id: int, req_id: int) -> None:
        """Drop a buffered group (its final chunk was deduplicated —
        the logical record already applied in a previous incarnation)."""
        entry = self._groups.pop((clt_id, req_id), None)
        if entry is not None:
            self._bytes -= sum(len(p) for p in entry[0].values())

    # -- snapshot transport ------------------------------------------------

    def dump(self) -> bytes:
        """Serialize the partial groups WITH their feed sequence
        numbers: eviction order is part of the replicated state (see
        class docstring), so an installer must continue evicting in the
        same order a natively-caught-up replica would."""
        out = [struct.pack("<IQ", len(self._groups), self._feed_seq)]
        for (clt, req) in sorted(self._groups):
            group, fseq = self._groups[(clt, req)]
            out.append(struct.pack("<QQQI", clt, req, fseq, len(group)))
            for seq in sorted(group):
                piece = group[seq]
                out.append(struct.pack("<II", seq, len(piece)))
                out.append(piece)
        return b"".join(out)

    @staticmethod
    def load(blob: bytes) -> "Reassembler":
        r = Reassembler()
        if not blob:
            return r
        ngroups, feed_seq = struct.unpack_from("<IQ", blob, 0)
        r._feed_seq = feed_seq
        off = 12
        for _ in range(ngroups):
            clt, req, fseq, npieces = struct.unpack_from("<QQQI", blob, off)
            off += 28
            group: dict[int, bytes] = {}
            for _ in range(npieces):
                seq, n = struct.unpack_from("<II", blob, off)
                off += 8
                group[seq] = blob[off:off + n]
                off += n
            r._groups[(clt, req)] = (group, fseq)
            r._bytes += sum(len(p) for p in group.values())
        return r
