"""Record segmentation: oversized commands as device-eligible chunks.

The reference's request envelope is TCP-rcvbuf-sized — records up to
87,380 B (/root/reference/src/include/dare/message.h:7; apus_wire.h
keeps the constant) ride whole through its byte-ring log.  Our fixed-
slot device log carries at most ``slot_bytes`` (4 KiB) of wire-encoded
entry per row (ops.logplane), so a large record must be CUT into chunk
entries at submit and REASSEMBLED into one logical record at apply:

- ``split()`` wraps each chunk in a small envelope carrying the real
  ``(clt_id, req_id)`` of the logical record plus ``(seq, total)``;
  every chunk then travels as an ordinary log entry — replicated,
  quorum-committed, and device-plane-eligible like any other.  Chunk
  entries other than the last carry ``(clt_id=0, req_id=0)`` so the
  endpoint-DB dedup and reply machinery fire exactly once, on the FINAL
  chunk, which carries the real ids (core.node.submit).
- ``Reassembler.feed()`` buffers chunks by ``(clt_id, req_id)`` and
  yields the full payload when the final chunk applies.  Chunks
  overwrite by ``seq``, which makes a group idempotent across the
  failover-retry shape: a half-appended group truncated by an election
  is simply overwritten by the client's retry at the new leader — and
  exactly-once still holds because the dedup decision rides the final
  chunk's real ``(clt_id, req_id)`` (apply-time dedup, node.py).

Any payload that happens to START with the envelope magic is escaped by
wrapping it as a single-chunk group (``maybe_wrap``) so the apply path
can treat the magic prefix as authoritative.
"""

from __future__ import annotations

import struct
from typing import Optional

#: Envelope magic: an improbable prefix for real client payloads
#: (escaped via maybe_wrap when it does occur).
MAGIC = b"\xa5SG1"
_HDR = struct.Struct("<4sQQII")      # magic | clt_id | req_id | seq | total
OVERHEAD = _HDR.size

#: The reference's maximum request record (message.h:7).
MAX_RECORD = 87380


def is_chunk(payload: bytes) -> bool:
    return payload.startswith(MAGIC) and len(payload) >= _HDR.size


def parse(payload: bytes) -> tuple[int, int, int, int, bytes]:
    """-> (clt_id, req_id, seq, total, piece)."""
    magic, clt, req, seq, total = _HDR.unpack_from(payload, 0)
    return clt, req, seq, total, payload[_HDR.size:]


def _wrap(clt_id: int, req_id: int, seq: int, total: int,
          piece: bytes) -> bytes:
    return _HDR.pack(MAGIC, clt_id, req_id, seq, total) + piece


def split(data: bytes, chunk: int, clt_id: int,
          req_id: int) -> list[bytes]:
    """Cut ``data`` into envelope-wrapped pieces of at most ``chunk``
    payload bytes each (at least one)."""
    assert chunk > 0
    pieces = [data[o:o + chunk] for o in range(0, len(data), chunk)] \
        or [b""]
    total = len(pieces)
    return [_wrap(clt_id, req_id, k, total, p)
            for k, p in enumerate(pieces)]


def maybe_wrap(data: bytes, clt_id: int, req_id: int) -> Optional[bytes]:
    """Escape a real payload that collides with the magic prefix by
    wrapping it as a single-chunk group; None when no escape needed."""
    if data.startswith(MAGIC):
        return _wrap(clt_id, req_id, 0, 1, data)
    return None


class Reassembler:
    """Apply-side chunk buffer.  Deterministic across replicas: all
    replicas apply the same entries in the same order, so all complete
    groups at the same final-chunk index.

    A group whose final chunk was truncated by an election is orphaned
    (its client's retry runs under a new capture id); orphans are
    bounded by ``MAX_GROUPS`` LRU eviction and, being stale, stop
    blocking snapshots once the apply point moves past them
    (``active_since``)."""

    MAX_GROUPS = 4096

    def __init__(self) -> None:
        #: key -> (seq -> piece, last_fed_tick_time)
        self._groups: dict[tuple[int, int],
                           tuple[dict[int, bytes], float]] = {}

    @property
    def pending(self) -> int:
        return len(self._groups)

    def active_within(self, now: float, window: float) -> bool:
        """True if some group was fed within the last ``window`` seconds
        of tick time — an in-flight group.  Snapshot gating
        (core.node.make_snapshot): a snapshot cut mid-group would strand
        the installer with finals whose early chunks are below the
        snapshot point.  A group can only complete-from-the-log shortly
        after its last chunk applied (chunks append contiguously), so
        TIME-aging lets stale orphans (final truncated by an election,
        client gone) stop blocking snapshots even on a quiescent cluster
        — where apply-progress-based aging would block forever."""
        return any(last > now - window
                   for _, last in self._groups.values())

    def feed(self, payload: bytes,
             now: float = 0.0) -> tuple[bool, Optional[bytes]]:
        """Absorb one applied chunk (``now`` = the tick clock).  Returns
        (final, full_payload): ``final`` is True when this chunk closes
        its group — then ``full_payload`` is the reassembled record, or
        None if earlier chunks are missing (only possible after an
        ill-gated snapshot install; counted by the caller)."""
        clt, req, seq, total, piece = parse(payload)
        key = (clt, req)
        entry = self._groups.get(key)
        group = entry[0] if entry is not None else {}
        group[seq] = piece
        if seq != total - 1:
            self._groups[key] = (group, now)
            if len(self._groups) > self.MAX_GROUPS:
                oldest = min(self._groups, key=lambda k: self._groups[k][1])
                self._groups.pop(oldest, None)
            return False, None
        self._groups.pop(key, None)
        if len(group) != total:
            return True, None
        return True, b"".join(group[k] for k in range(total))

    def prune(self, clt_id: int, req_id: int) -> None:
        """Drop a buffered group (its final chunk was deduplicated —
        the logical record already applied in a previous incarnation)."""
        self._groups.pop((clt_id, req_id), None)
