"""Server identifier (SID): packed ``[term | leader-bit | server-idx]``.

The reference packs the protocol's entire "who leads, what term" state into
one 64-bit word updated with compare-and-swap (dare_server.h:46-72,
server_update_sid dare_server.c:2288-2297) so that remote one-sided writes
can race safely with local updates.  We keep the same packed representation:
it is exactly what the device plane wants too — a single uint64 scalar that
can live in a control array, be compared inside a jitted step for term
fencing, and be updated atomically host-side.

Layout (64 bits)::

    [ term : 55 bits ][ L : 1 bit ][ idx : 8 bits ]

``L`` set means "the server ``idx`` claims leadership of ``term``".
"""

from __future__ import annotations

import dataclasses
import threading

_IDX_BITS = 8
_L_SHIFT = _IDX_BITS
_TERM_SHIFT = _IDX_BITS + 1
_IDX_MASK = (1 << _IDX_BITS) - 1
_L_MASK = 1 << _L_SHIFT


@dataclasses.dataclass(frozen=True)
class Sid:
    """Immutable unpacked view of a packed SID word."""

    term: int
    leader: bool
    idx: int

    @staticmethod
    def pack(term: int, leader: bool, idx: int) -> int:
        if not 0 <= idx <= _IDX_MASK:
            raise ValueError(f"server idx {idx} out of range")
        return (term << _TERM_SHIFT) | (int(leader) << _L_SHIFT) | idx

    @staticmethod
    def unpack(word: int) -> "Sid":
        return Sid(term=word >> _TERM_SHIFT,
                   leader=bool(word & _L_MASK),
                   idx=word & _IDX_MASK)

    @property
    def word(self) -> int:
        return Sid.pack(self.term, self.leader, self.idx)

    def with_leader(self, leader: bool = True) -> "Sid":
        return Sid(self.term, leader, self.idx)

    def __repr__(self) -> str:  # debug banner parity: "[T<t>] LEADER"
        return f"Sid(T{self.term}{'|L' if self.leader else ''}|p{self.idx})"


class AtomicSid:
    """CAS-updated SID cell.

    Local updates race with "remote" control-plane writes (delivered on a
    different thread by the transport), mirroring the reference's
    ``__sync_bool_compare_and_swap`` update (dare_server.c:2288-2297).
    """

    def __init__(self, word: int = 0):
        self._word = word
        self._lock = threading.Lock()

    @property
    def word(self) -> int:
        return self._word

    @property
    def sid(self) -> Sid:
        return Sid.unpack(self._word)

    def cas(self, expect: int, new: int) -> bool:
        with self._lock:
            if self._word != expect:
                return False
            self._word = new
            return True

    def update(self, new: int) -> bool:
        """CAS loop: install ``new`` unless someone already moved past it."""
        while True:
            cur = self._word
            if cur == new:
                return False
            if self.cas(cur, new):
                return True
