"""Protocol-wide constants and enums.

Reference points (cited for parity, not copied):
- entry types NOOP/CSM/CONFIG/HEAD: dare_log.h:22-25
- capacity envelope (13 servers, 64 clients): dare.h:25-26
- server start modes start|join|loggp: dare_server.h:22-28
"""

from __future__ import annotations

import enum

# Capacity envelope — matches the reference protocol envelope (dare.h:25-26).
MAX_SERVER_COUNT = 13
MAX_CLIENT_COUNT = 64

# Fixed-width slot geometry (TPU-first redesign of the reference's 64 MB
# byte-addressed circular buffer, dare_log.h:76).  A log *index* is a
# monotonically increasing uint64; its slot is ``idx % n_slots``.  Static
# shapes let XLA keep the whole log HBM-resident with O(1) addressing and
# no wrap-around entry splitting (cf. dare_ibv_rc.c:1532-1545).
DEFAULT_LOG_SLOTS = 4096
DEFAULT_SLOT_BYTES = 4096  # payload bytes per slot; large requests segment

# Max raw request record size accepted from the interposer, matching the
# reference's TCP-rcvbuf-sized command buffer (message.h:7).
MAX_REQUEST_BYTES = 87380


class EntryType(enum.IntEnum):
    """Log entry types (parity with dare_log.h:22-25)."""

    NOOP = 0     # blank entry appended by a fresh leader
    CSM = 1      # client state-machine command (opaque bytes)
    CONFIG = 2   # membership change (carries a Cid)
    HEAD = 3     # log-pruning head advance (carries a log index)


class Role(enum.IntEnum):
    """Server roles (parity with the SID role macros, dare_server.c:42-53)."""

    FOLLOWER = 0
    CANDIDATE = 1
    LEADER = 2


class ServerType(enum.IntEnum):
    """Start modes (parity with dare_server.h:22-28)."""

    START = 0   # founding member of a fresh group
    JOIN = 1    # joins an existing group (recovery path)
    LOGGP = 2   # microbenchmark mode (ICI step-parameter estimation)


class ProxyAction(enum.IntEnum):
    """Replicated request record kinds captured by the proxy
    (parity with the CONNECT/SEND/CLOSE actions, proxy.h / proxy.c:341-439)."""

    CONNECT = 0
    SEND = 1
    CLOSE = 2
    #: proxy -> daemon verdict frame (never logged): the app's read
    #: covering a record range was FAILED; committed members must be
    #: locally replayed (apus_wire.h APUS_ACT_NACK).
    NACK = 3


# Failure detector: consecutive control-plane failures before the leader
# removes a server (parity with PERMANENT_FAILURE, dare_server.h:74-76).
PERMANENT_FAILURE = 2
