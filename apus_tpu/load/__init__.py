"""Open-loop SLO load harness (the "millions of users" evaluation).

The serving-surface benchmark methodology, in three deterministic
primitives plus one engine:

- :mod:`apus_tpu.load.zipf` — seeded zipfian key-popularity sampler
  (hot-key skew; YCSB/redis-benchmark methodology);
- :mod:`apus_tpu.load.schedule` — OPEN-LOOP arrival schedules (fixed
  arrival rate, Poisson or uniform gaps, optional fan-in bursts):
  arrivals are decided BEFORE the run and never slowed by the server;
- :mod:`apus_tpu.load.latency` — coordinated-omission-safe latency
  accounting: every op's latency is measured from its SCHEDULED
  arrival, so a server stall surfaces as the queueing delay every
  virtual user would have seen (a closed-loop client silently stops
  sampling exactly while the server is at its worst — the classic
  p999 lie), plus p50/p99/p999 + windowed SLO-degradation reporting;
- :mod:`apus_tpu.load.openloop` — the many-hundred-connection engine
  (non-blocking sockets, one selector loop) speaking the KVS client
  wire or RESP at an app gateway, with seeded connection churn;
- :mod:`apus_tpu.load.ramp` — the overload campaigns on top: the
  saturation staircase (find the goodput knee), the metastability
  probe (overload hold + bounded-recovery verdict), and multi-process
  load sharding with sample-level CO-safe merging.

``python -m apus_tpu.load --help`` runs it standalone; bench.py --slo
is the banked entry point.
"""

from apus_tpu.load.latency import LatencyRecorder, percentile
from apus_tpu.load.openloop import OpenLoopConfig, run_open_loop
from apus_tpu.load.ramp import (run_metastability, run_saturation_ramp,
                                run_sharded)
from apus_tpu.load.schedule import (burst_schedule, poisson_schedule,
                                    uniform_schedule)
from apus_tpu.load.zipf import ZipfKeys

__all__ = ["LatencyRecorder", "percentile", "OpenLoopConfig",
           "run_open_loop", "run_saturation_ramp", "run_metastability",
           "run_sharded", "poisson_schedule", "uniform_schedule",
           "burst_schedule", "ZipfKeys"]
