from apus_tpu.load.openloop import main

raise SystemExit(main())
