"""Coordinated-omission-safe latency accounting + SLO reporting.

Every op records ``(scheduled_arrival, completion, ok)``; its latency
is ``completion - scheduled_arrival`` — service time PLUS the queueing
delay the open-loop schedule accumulated while the server was slow.
Ops still unresolved when the run ends are completed AT the cutoff
(their latency is a LOWER bound, counted as censored), so a stall near
the end cannot vanish from the tail.

The windowed view buckets samples by scheduled arrival and reports a
per-window p99 plus the SLO verdict, from which the chaos-composed
runs quantify the DEGRADATION WINDOW around a fault (first degraded
window .. last degraded window).
"""

from __future__ import annotations

import dataclasses


def percentile(sorted_vals: "list[float]", q: float) -> float:
    """Nearest-rank percentile of an already-sorted list (q in [0,1])."""
    if not sorted_vals:
        return 0.0
    i = int(q * (len(sorted_vals) - 1) + 0.5)
    return sorted_vals[min(i, len(sorted_vals) - 1)]


@dataclasses.dataclass
class SloReport:
    ops: int
    errors: int
    censored: int                 # unresolved at cutoff (latency = lower bound)
    duration_s: float
    achieved_rate: float          # completed ops / duration
    p50_ms: float
    p90_ms: float
    p99_ms: float
    p999_ms: float
    max_ms: float
    #: typed load sheds (ST_OVERLOAD / -BUSY): deterministic refusals,
    #: distinct from errors AND from censored ambiguity — a shed op
    #: provably never applied, so it is not a correctness event, only
    #: capacity the server declined.  Sheds never count toward latency
    #: percentiles or degraded verdicts.
    sheds: int = 0
    #: ok-completions / duration — the saturation campaigns' knee axis
    #: (achieved_rate counts errors and censored completions too).
    goodput_rate: float = 0.0
    #: per-window rows: (window_start_s, ops, p99_ms, degraded, sheds)
    windows: "list[tuple]" = dataclasses.field(default_factory=list)
    slo_ms: float = 0.0
    #: contiguous degraded spans [(start_s, end_s), ...] on the
    #: scheduled-arrival axis
    degraded_spans: "list[tuple]" = dataclasses.field(default_factory=list)

    @property
    def degraded_s(self) -> float:
        return sum(b - a for a, b in self.degraded_spans)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["degraded_s"] = self.degraded_s
        return d


class LatencyRecorder:
    """Lock-free-enough sample sink (single driver thread)."""

    def __init__(self) -> None:
        #: (scheduled_t, latency_s, ok) triples
        self.samples: "list[tuple[float, float, bool]]" = []
        #: (scheduled_t, turnaround_s) for typed sheds — kept OUT of
        #: ``samples`` so a shed can never inflate a latency percentile
        #: or flip a window degraded (it is the server keeping its
        #: tail honest, not missing it).
        self.shed_samples: "list[tuple[float, float]]" = []
        self.errors = 0
        self.censored = 0
        self.sheds = 0

    def record(self, sched_t: float, done_t: float,
               ok: bool = True) -> None:
        self.samples.append((sched_t, done_t - sched_t, ok))
        if not ok:
            self.errors += 1

    def record_shed(self, sched_t: float, done_t: float) -> None:
        """A typed overload refusal (ST_OVERLOAD / -BUSY): resolved,
        never applied, classified apart from errors and censored."""
        self.shed_samples.append((sched_t, done_t - sched_t))
        self.sheds += 1

    def censor(self, sched_t: float, cutoff_t: float) -> None:
        """An op still unresolved at the run cutoff: latency >= the
        recorded value.  Counted in the tail, flagged in the report."""
        self.samples.append((sched_t, max(0.0, cutoff_t - sched_t),
                             False))
        self.errors += 1
        self.censored += 1

    def report(self, duration_s: float, slo_ms: float = 0.0,
               window_s: float = 0.5) -> SloReport:
        lats = sorted(l for _, l, _ in self.samples)
        n = len(lats)
        rep = SloReport(
            ops=n, errors=self.errors, censored=self.censored,
            sheds=self.sheds,
            duration_s=duration_s,
            achieved_rate=(n / duration_s if duration_s > 0 else 0.0),
            goodput_rate=((n - self.errors) / duration_s
                          if duration_s > 0 else 0.0),
            p50_ms=percentile(lats, 0.50) * 1e3,
            p90_ms=percentile(lats, 0.90) * 1e3,
            p99_ms=percentile(lats, 0.99) * 1e3,
            p999_ms=percentile(lats, 0.999) * 1e3,
            max_ms=(lats[-1] * 1e3 if lats else 0.0),
            slo_ms=slo_ms)
        if window_s <= 0 or not (self.samples or self.shed_samples):
            return rep
        buckets: dict[int, list] = {}
        bad: dict[int, int] = {}
        shed_w: dict[int, int] = {}
        for t, lat, ok in self.samples:
            w = int(t / window_s)
            buckets.setdefault(w, []).append(lat)
            if not ok:
                bad[w] = bad.get(w, 0) + 1
        for t, _ in self.shed_samples:
            w = int(t / window_s)
            buckets.setdefault(w, [])
            shed_w[w] = shed_w.get(w, 0) + 1
        span_start = None
        prev_end = None
        for w in sorted(buckets):
            ls = sorted(buckets[w])
            p99 = percentile(ls, 0.99) * 1e3
            degraded = bool(bad.get(w)) or (slo_ms > 0 and p99 > slo_ms)
            rep.windows.append((w * window_s, len(ls), p99, degraded,
                                shed_w.get(w, 0)))
            if degraded:
                if span_start is None:
                    span_start = w * window_s
                prev_end = (w + 1) * window_s
            elif span_start is not None:
                rep.degraded_spans.append((span_start, prev_end))
                span_start = None
        if span_start is not None:
            rep.degraded_spans.append((span_start, prev_end))
        return rep
