"""The open-loop load engine: many hundred non-blocking connections,
one selector loop, a pre-computed arrival schedule.

Protocols:

- ``kvs`` — the daemons' client wire (OP_CLT_WRITE/OP_CLT_READ frames,
  exactly the ApusClient protocol): writes chase the per-group leader
  via NOT_LEADER hints, GETs rotate across replicas (follower-lease
  spread), multi-group keys route through the pinned key->group hash;
- ``resp`` — redis protocol SET/GET at an app serving gateway
  (runtime/serve.py) or any RESP server: the gateway does its own
  routing, the engine just paces, pairs FIFO replies, and measures.

Identity discipline (kvs): every logical connection SLOT owns a client
id and a req_id sequence; an op binds to its slot at first dispatch
and a slot's identities only ever travel on that slot's socket, so
reply pairing by echoed req_id cannot collide.  Socket death/churn
reopens the slot's socket and resends its in-flight ops under their
ORIGINAL identities (the server-side exact-window dedup keeps writes
exactly-once, as for ApusClient failover).  An op that must MOVE to a
different peer (leader bounce) re-dispatches under a fresh identity
from a slot bound there — safe for refused ops, and for maybe-applied
SETs the duplicate re-applies the same value (this harness measures
latency; the audited linearizability campaigns use ApusClient).

Coordinated-omission safety: every op's latency anchors at its
SCHEDULED arrival (latency.py), retries included; ops unresolved at
the cutoff are censored into the tail, never dropped.
"""

from __future__ import annotations

import dataclasses
import secrets
import selectors
import socket
import struct
import time
from collections import deque
from typing import Optional

from apus_tpu.load.latency import LatencyRecorder, SloReport
from apus_tpu.load.schedule import (burst_schedule, poisson_schedule,
                                    uniform_schedule)
from apus_tpu.load.zipf import ZipfKeys

OP_CLT_WRITE = 16
OP_CLT_READ = 17
ST_OK = 0
ST_NOT_LEADER = 4
ST_TIMEOUT = 5
ST_WRONG_GROUP = 8
ST_MIGRATING = 9
ST_OVERLOAD = 10
OP_GROUP = 25

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def raise_fd_limit(want: int) -> int:
    """Best-effort RLIMIT_NOFILE raise (hundreds of sockets + the
    server side share one box in the harness runs)."""
    try:
        import resource
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft < want:
            resource.setrlimit(resource.RLIMIT_NOFILE,
                               (min(want, hard), hard))
            soft = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
        return soft
    except Exception:                                    # noqa: BLE001
        return -1


@dataclasses.dataclass
class OpenLoopConfig:
    peers: "list[str]"            # host:port targets
    connections: int = 512
    rate: float = 2000.0          # arrivals/s (open loop)
    duration: float = 10.0
    seed: int = 0
    nkeys: int = 10000
    theta: float = 0.99           # zipfian skew (0 = uniform)
    get_fraction: float = 0.9
    value_size: int = 64
    groups: int = 1
    proto: str = "kvs"            # kvs | resp
    arrival: str = "poisson"      # poisson | uniform
    burst_every: float = 0.0      # fan-in bursts (schedule.py)
    burst_size: int = 0
    churn_every: float = 0.0      # close+reopen a slice of connections
    churn_fraction: float = 0.05
    slo_ms: float = 50.0
    window_s: float = 0.5
    read_spread: bool = True      # kvs GETs rotate across replicas
    grace: float = 5.0            # post-deadline drain for stragglers
    key_prefix: bytes = b"lk"
    scramble: bool = True
    max_attempts: int = 64


class _Op:
    __slots__ = ("sched", "key", "is_get", "gid", "clt", "req",
                 "slot", "attempts", "done")

    def __init__(self, sched: float, key: bytes, is_get: bool,
                 gid: int):
        self.sched = sched
        self.key = key
        self.is_get = is_get
        self.gid = gid
        self.clt = 0
        self.req = 0
        self.slot = -1
        self.attempts = 0
        self.done = False


class _Slot:
    """One logical connection: identity + socket + buffers."""

    __slots__ = ("idx", "peer", "clt_id", "req_seq", "sock", "inbuf",
                 "outbuf", "inflight", "fifo", "alive", "connected")

    def __init__(self, idx: int, peer: int, clt_id: int):
        self.idx = idx
        self.peer = peer
        self.clt_id = clt_id
        self.req_seq = 0
        self.sock: Optional[socket.socket] = None
        self.inbuf = b""
        self.outbuf = bytearray()
        self.inflight: dict[int, _Op] = {}    # kvs: req -> op
        self.fifo: deque = deque()            # resp: FIFO op order
        self.alive = False
        self.connected = False


class OpenLoopEngine:
    def __init__(self, cfg: OpenLoopConfig):
        self.cfg = cfg
        self.addrs = [(p.rsplit(":", 1)[0], int(p.rsplit(":", 1)[1]))
                      for p in cfg.peers]
        self.rec = LatencyRecorder()
        self.sel = selectors.DefaultSelector()
        self.slots: list[_Slot] = []
        self.leaders: dict[int, Optional[int]] = {}
        self.stats = {"sent": 0, "retries": 0, "bounces": 0,
                      "reconnects": 0, "churns": 0, "conn_errors": 0,
                      "wrong_group": 0, "sheds": 0}
        self._peer_slots: dict[int, list[int]] = {}
        self._rotors: dict[int, int] = {}
        self._read_rotor = 0
        self._resolved = 0
        self._t0 = 0.0
        import random
        self._rng = random.Random(cfg.seed ^ 0x10AD)
        base = secrets.randbits(40) << 20
        for i in range(cfg.connections):
            s = _Slot(i, i % len(self.addrs),
                      (base + i) & ((1 << 63) - 1))
            self.slots.append(s)
            self._peer_slots.setdefault(s.peer, []).append(i)

    # -- plan ----------------------------------------------------------

    def _plan(self) -> "list[_Op]":
        cfg = self.cfg
        if cfg.arrival == "uniform":
            sched = uniform_schedule(cfg.rate, cfg.duration)
        else:
            sched = poisson_schedule(cfg.rate, cfg.duration,
                                     seed=cfg.seed)
        if cfg.burst_every > 0 and cfg.burst_size > 0:
            sched = burst_schedule(sched, cfg.burst_every,
                                   cfg.burst_size, cfg.duration)
        zipf = ZipfKeys(cfg.nkeys, theta=cfg.theta, seed=cfg.seed,
                        scramble=cfg.scramble, prefix=cfg.key_prefix)
        if cfg.groups > 1:
            from apus_tpu.runtime.router import group_of_key
        ops = []
        for t in sched:
            key = zipf.key()
            gid = (group_of_key(key, cfg.groups)
                   if cfg.groups > 1 else 0)
            ops.append(_Op(t, key, self._rng.random()
                           < cfg.get_fraction, gid))
        return ops

    # -- sockets -------------------------------------------------------

    def _open(self, slot: _Slot) -> None:
        self._close(slot)
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            sock.connect_ex(self.addrs[slot.peer])
        except OSError:
            sock.close()
            slot.alive = False
            return
        slot.sock = sock
        slot.inbuf = b""
        slot.alive = True
        slot.connected = False
        self.sel.register(sock, selectors.EVENT_READ
                          | selectors.EVENT_WRITE, slot)

    def _close(self, slot: _Slot) -> None:
        if slot.sock is not None:
            try:
                self.sel.unregister(slot.sock)
            except (KeyError, ValueError):
                pass
            try:
                slot.sock.close()
            except OSError:
                pass
        slot.sock = None
        slot.alive = False
        slot.connected = False

    def _rebind(self, slot: _Slot, peer: int) -> None:
        self._peer_slots[slot.peer].remove(slot.idx)
        slot.peer = peer
        self._peer_slots.setdefault(peer, []).append(slot.idx)

    def _reconnect(self, slot: _Slot, rebind: bool = True) -> None:
        """Reopen a dead slot (next peer if its own keeps failing) and
        resend its unresolved ops under their original identities."""
        self.stats["reconnects"] += 1
        if rebind and not slot.connected and slot.sock is None:
            self._rebind(slot, (slot.peer + 1) % len(self.addrs))
        self._open(slot)
        if not slot.alive:
            return
        slot.outbuf = bytearray()
        if self.cfg.proto == "kvs":
            for op in list(slot.inflight.values()):
                slot.outbuf += self._encode(slot, op)
        else:
            for op in list(slot.fifo):
                slot.outbuf += self._encode(slot, op)

    def _pick_slot(self, peer: Optional[int]) -> _Slot:
        """A live slot bound to ``peer`` (any live slot when None or
        none bound there is alive)."""
        if peer is not None:
            idxs = self._peer_slots.get(peer, [])
            if idxs:
                r = self._rotors.get(peer, 0)
                for k in range(len(idxs)):
                    s = self.slots[idxs[(r + k) % len(idxs)]]
                    if s.alive:
                        self._rotors[peer] = (r + k + 1) % len(idxs)
                        return s
        for k in range(len(self.slots)):
            s = self.slots[(self._read_rotor + k) % len(self.slots)]
            if s.alive:
                self._read_rotor = (self._read_rotor + k + 1) \
                    % len(self.slots)
                return s
        # Nothing alive: revive slot 0 and hope.
        self._reconnect(self.slots[0], rebind=True)
        return self.slots[0]

    # -- encode --------------------------------------------------------

    def _encode(self, slot: _Slot, op: _Op) -> bytes:
        if self.cfg.proto == "resp":
            if op.is_get:
                return (b"*2\r\n$3\r\nGET\r\n$%d\r\n%s\r\n"
                        % (len(op.key), op.key))
            val = self._value(op)
            return (b"*3\r\n$3\r\nSET\r\n$%d\r\n%s\r\n$%d\r\n%s\r\n"
                    % (len(op.key), op.key, len(val), val))
        from apus_tpu.models.kvs import encode_get, encode_put
        data = (encode_get(op.key) if op.is_get
                else encode_put(op.key, self._value(op)))
        payload = (bytes([OP_CLT_READ if op.is_get else OP_CLT_WRITE])
                   + _U64.pack(op.req) + _U64.pack(op.clt)
                   + _U32.pack(len(data)) + data)
        if op.gid:
            payload = bytes([OP_GROUP, op.gid]) + payload
        return _U32.pack(len(payload)) + payload

    def _value(self, op: _Op) -> bytes:
        n = self.cfg.value_size
        return (op.key * (n // max(1, len(op.key)) + 1))[:n]

    # -- dispatch ------------------------------------------------------

    def _dispatch(self, op: _Op, fresh: bool = True) -> None:
        """Assign the op a target slot (+ identity on first/refreshed
        dispatch) and queue its frame."""
        cfg = self.cfg
        if cfg.proto == "resp":
            peer = None
        elif op.is_get and cfg.read_spread:
            peer = None                      # rotate across replicas
        else:
            peer = self.leaders.get(op.gid)
        slot = self._pick_slot(peer)
        if fresh or op.slot != slot.idx:
            # (Re)bind identity to the carrying slot: a slot's ids only
            # ever travel on its own socket (pairing cannot collide).
            old = self.slots[op.slot] if op.slot >= 0 else None
            if old is not None:
                old.inflight.pop(op.req, None)
            slot.req_seq += 1
            op.clt, op.req, op.slot = slot.clt_id, slot.req_seq, slot.idx
        if cfg.proto == "kvs":
            slot.inflight[op.req] = op
        else:
            slot.fifo.append(op)
        if slot.alive:
            slot.outbuf += self._encode(slot, op)
        self.stats["sent"] += 1

    def _retry(self, op: _Op, now: float, move_peer: bool) -> None:
        op.attempts += 1
        if op.attempts >= self.cfg.max_attempts:
            op.done = True
            self._resolved += 1
            self.rec.record(op.sched, now - self._t0, ok=False)
            return
        self.stats["retries"] += 1
        self._dispatch(op, fresh=move_peer)

    # -- replies -------------------------------------------------------

    def _on_kvs_frame(self, slot: _Slot, frame: bytes,
                      now: float) -> None:
        if len(frame) < 9:
            return
        st = frame[0]
        req = _U64.unpack_from(frame, 1)[0]
        op = slot.inflight.pop(req, None)
        if op is None or op.done:
            return
        if st == ST_OK:
            op.done = True
            self._resolved += 1
            self.rec.record(op.sched, now - self._t0, ok=True)
            return
        if st == ST_NOT_LEADER:
            self.stats["bounces"] += 1
            hint = b""
            if len(frame) >= 13:
                n = _U32.unpack_from(frame, 9)[0]
                hint = frame[13:13 + n]
            if hint:
                try:
                    h, p = hint.decode().rsplit(":", 1)
                    target = self.addrs.index((h, int(p)))
                    self.leaders[op.gid] = target
                except (ValueError, IndexError):
                    self.leaders[op.gid] = None
            elif not op.is_get:
                self.leaders[op.gid] = None
            # Reads fall back to the (hinted) leader; writes chase it.
            self._retry(op, now, move_peer=True)
            return
        if st == ST_TIMEOUT:
            self.leaders[op.gid] = None
            self._retry(op, now, move_peer=True)
            return
        if st == ST_MIGRATING:
            self._retry(op, now, move_peer=False)
            return
        if st == ST_OVERLOAD:
            # Typed shed: the server refused BEFORE admission, so the
            # op provably never applied.  The open loop does NOT retry
            # it — a retrying load generator silently converts refused
            # load into MORE offered load (the metastable amplification
            # these campaigns exist to measure).  Record the shed in
            # its own bucket and keep the offered schedule honest.
            op.done = True
            self._resolved += 1
            self.stats["sheds"] += 1
            self.rec.record_shed(op.sched, now - self._t0)
            return
        if st == ST_WRONG_GROUP:
            # Learn the owner gid from the bounce (offset 9: u8 owner
            # + shard-map blob) and re-route under a fresh identity
            # (the refusal is deterministic — it never applied here).
            self.stats["wrong_group"] += 1
            if len(frame) >= 10:
                op.gid = frame[9]
            self._retry(op, now, move_peer=True)
            return
        op.done = True
        self._resolved += 1
        self.rec.record(op.sched, now - self._t0, ok=False)

    def _on_resp_data(self, slot: _Slot, now: float) -> None:
        """Pop complete RESP replies off slot.inbuf, FIFO-paired."""
        while slot.fifo:
            used = _resp_reply_len(slot.inbuf)
            if used <= 0:
                return
            reply = slot.inbuf[:used]
            slot.inbuf = slot.inbuf[used:]
            op = slot.fifo.popleft()
            if op.done:
                continue
            op.done = True
            self._resolved += 1
            if reply.startswith(b"-BUSY"):
                # Gateway-translated shed (runtime/serve.py): same
                # typed-refusal classification as a KVS ST_OVERLOAD.
                self.stats["sheds"] += 1
                self.rec.record_shed(op.sched, now - self._t0)
            else:
                self.rec.record(op.sched, now - self._t0,
                                ok=not reply.startswith(b"-"))
        # Replies with no waiter (post-reconnect stragglers): drop.
        if not slot.fifo and slot.inbuf:
            used = _resp_reply_len(slot.inbuf)
            while used > 0:
                slot.inbuf = slot.inbuf[used:]
                used = _resp_reply_len(slot.inbuf)

    def _pump(self, slot: _Slot, writable: bool, readable: bool,
              now: float) -> None:
        if slot.sock is None:
            return
        if writable:
            slot.connected = True
            if slot.outbuf:
                try:
                    n = slot.sock.send(
                        memoryview(slot.outbuf)[:1 << 18])
                    del slot.outbuf[:n]
                except (BlockingIOError, InterruptedError):
                    pass
                except OSError:
                    self.stats["conn_errors"] += 1
                    self._reconnect(slot)
                    return
        if readable:
            try:
                chunk = slot.sock.recv(1 << 18)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self.stats["conn_errors"] += 1
                self._reconnect(slot)
                return
            if not chunk:
                self.stats["conn_errors"] += 1
                self._reconnect(slot)
                return
            slot.inbuf += chunk
            if self.cfg.proto == "resp":
                self._on_resp_data(slot, now)
                return
            while True:
                if len(slot.inbuf) < 4:
                    return
                n = _U32.unpack_from(slot.inbuf)[0]
                if len(slot.inbuf) < 4 + n:
                    return
                frame = slot.inbuf[4:4 + n]
                slot.inbuf = slot.inbuf[4 + n:]
                self._on_kvs_frame(slot, frame, now)

    # -- run -----------------------------------------------------------

    def run(self) -> "tuple[SloReport, dict]":
        cfg = self.cfg
        raise_fd_limit(cfg.connections + 256)
        ops = self._plan()
        for s in self.slots:
            self._open(s)
        t0 = time.monotonic()
        self._t0 = t0
        deadline = t0 + cfg.duration
        drain_by = deadline + cfg.grace
        next_i = 0
        next_churn = (t0 + cfg.churn_every if cfg.churn_every > 0
                      else float("inf"))
        next_revive = t0 + 0.25
        while True:
            now = time.monotonic()
            # Send everything due.
            while next_i < len(ops) and t0 + ops[next_i].sched <= now:
                self._dispatch(ops[next_i])
                next_i += 1
            if now >= next_churn:
                self.stats["churns"] += 1
                k = max(1, int(cfg.connections * cfg.churn_fraction))
                for idx in self._rng.sample(range(len(self.slots)), k):
                    self._reconnect(self.slots[idx], rebind=False)
                next_churn = now + cfg.churn_every
            if now >= next_revive:
                # Dead slots with stranded ops (killed replica, refused
                # connect): keep trying, rebinding to the next peer.
                for s in self.slots:
                    if not s.alive and (s.inflight or s.fifo):
                        self._reconnect(s)
                next_revive = now + 0.25
            if next_i >= len(ops) and self._resolved >= len(ops):
                break
            if now >= drain_by:
                break
            timeout = 0.002
            if next_i < len(ops):
                timeout = min(timeout,
                              max(0.0, t0 + ops[next_i].sched - now))
            for key, mask in self.sel.select(timeout):
                self._pump(key.data,
                           bool(mask & selectors.EVENT_WRITE),
                           bool(mask & selectors.EVENT_READ), now)
        cut = time.monotonic()
        for op in ops:
            if not op.done:
                self.rec.censor(op.sched, cut - t0)
        for s in self.slots:
            self._close(s)
        self.sel.close()
        rep = self.rec.report(cfg.duration, slo_ms=cfg.slo_ms,
                              window_s=cfg.window_s)
        return rep, dict(self.stats)


def _resp_reply_len(buf: bytes) -> int:
    """Bytes consumed by one complete RESP reply at the head of
    ``buf`` (0 = incomplete).  Handles the simple/bulk/int/error
    shapes the SET/GET workload sees, plus arrays for safety."""
    eol = buf.find(b"\r\n")
    if eol < 0 or not buf:
        return 0
    t = buf[:1]
    if t in (b"+", b"-", b":"):
        return eol + 2
    if t == b"$":
        try:
            n = int(buf[1:eol])
        except ValueError:
            return eol + 2
        if n < 0:
            return eol + 2
        total = eol + 2 + n + 2
        return total if len(buf) >= total else 0
    if t == b"*":
        try:
            cnt = int(buf[1:eol])
        except ValueError:
            return eol + 2
        off = eol + 2
        for _ in range(max(0, cnt)):
            used = _resp_reply_len(buf[off:])
            if used <= 0:
                return 0
            off += used
        return off
    return eol + 2


def run_open_loop(cfg: OpenLoopConfig) -> "tuple[SloReport, dict]":
    return OpenLoopEngine(cfg).run()


def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        description="open-loop SLO load harness (coordinated-omission-"
                    "safe; see apus_tpu/load/__init__.py)")
    ap.add_argument("--peers", required=True,
                    help="comma-separated host:port targets")
    ap.add_argument("--connections", type=int, default=512)
    ap.add_argument("--rate", type=float, default=2000.0)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--nkeys", type=int, default=10000)
    ap.add_argument("--theta", type=float, default=0.99)
    ap.add_argument("--get-fraction", type=float, default=0.9)
    ap.add_argument("--value-size", type=int, default=64)
    ap.add_argument("--groups", type=int, default=1)
    ap.add_argument("--proto", choices=("kvs", "resp"), default="kvs")
    ap.add_argument("--arrival", choices=("poisson", "uniform"),
                    default="poisson")
    ap.add_argument("--burst-every", type=float, default=0.0)
    ap.add_argument("--burst-size", type=int, default=0)
    ap.add_argument("--churn-every", type=float, default=0.0)
    ap.add_argument("--slo-ms", type=float, default=50.0)
    ap.add_argument("--procs", type=int, default=1,
                    help="shard the offered load across N worker "
                         "processes (samples merged into ONE CO-safe "
                         "report)")
    ap.add_argument("--mode", choices=("fixed", "ramp", "meta"),
                    default="fixed",
                    help="fixed = one run at --rate; ramp = "
                         "saturation staircase; meta = metastability "
                         "probe (step to --overload-x, step back)")
    ap.add_argument("--ramp-step", type=float, default=0.0,
                    help="ramp: rate increment per step "
                         "(default --rate/2)")
    ap.add_argument("--ramp-steps", type=int, default=6)
    ap.add_argument("--step-duration", type=float, default=5.0)
    ap.add_argument("--overload-x", type=float, default=5.0,
                    help="meta: overload-hold multiplier over --rate")
    ap.add_argument("--base-s", type=float, default=5.0)
    ap.add_argument("--overload-s", type=float, default=5.0)
    ap.add_argument("--recover-s", type=float, default=10.0)
    args = ap.parse_args(argv)
    cfg = OpenLoopConfig(
        peers=args.peers.split(","), connections=args.connections,
        rate=args.rate, duration=args.duration, seed=args.seed,
        nkeys=args.nkeys, theta=args.theta,
        get_fraction=args.get_fraction, value_size=args.value_size,
        groups=args.groups, proto=args.proto, arrival=args.arrival,
        burst_every=args.burst_every, burst_size=args.burst_size,
        churn_every=args.churn_every, slo_ms=args.slo_ms)
    repro = (f"python -m apus_tpu.load --peers {args.peers} "
             f"--mode {args.mode} --rate {args.rate:g} "
             f"--duration {args.duration:g} --procs {args.procs} "
             f"--seed {args.seed} --proto {args.proto} "
             f"--connections {args.connections}")
    if args.mode == "ramp":
        from apus_tpu.load.ramp import run_saturation_ramp
        out = run_saturation_ramp(
            cfg, start_rate=args.rate,
            step_rate=(args.ramp_step or args.rate / 2),
            steps=args.ramp_steps, step_duration=args.step_duration,
            procs=args.procs, log=lambda m: print(m, flush=True))
        out["repro"] = (f"{repro} --ramp-steps {args.ramp_steps} "
                        f"--step-duration {args.step_duration:g}")
        print(json.dumps(out, indent=2, default=str))
        return 0 if out["total_censored"] == 0 else 1
    if args.mode == "meta":
        from apus_tpu.load.ramp import run_metastability
        out = run_metastability(
            cfg, overload_x=args.overload_x, base_s=args.base_s,
            overload_s=args.overload_s, recover_s=args.recover_s,
            log=lambda m: print(m, flush=True))
        out["repro"] = (f"{repro} --overload-x {args.overload_x:g} "
                        f"--base-s {args.base_s:g} --overload-s "
                        f"{args.overload_s:g} --recover-s "
                        f"{args.recover_s:g}")
        print(json.dumps(out, indent=2, default=str))
        return 0 if out["recovered"] and out["censored"] == 0 else 1
    if args.procs > 1:
        from apus_tpu.load.ramp import run_sharded
        rep, stats = run_sharded(cfg, args.procs)
    else:
        rep, stats = run_open_loop(cfg)
    print(json.dumps({"report": rep.to_dict(), "stats": stats,
                      "repro": repro},
                     indent=2, default=str))
    return 0 if rep.censored == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
