"""Saturation + metastability campaign drivers over the open-loop
engine, plus multi-process load sharding.

Three entry points, all built on :mod:`apus_tpu.load.openloop`:

- :func:`run_sharded` — split one offered-load schedule across N
  worker processes (fork), then merge every shard's raw samples into
  ONE coordinated-omission-safe recorder before reporting.  A single
  Python selector loop saturates around a few tens of thousands of
  arrivals/s; finding a server's knee needs offered load past that,
  and merging at the SAMPLE level (not averaging per-shard reports)
  keeps the percentile math exact.

- :func:`run_saturation_ramp` — the staircase: fixed-duration steps at
  increasing offered rate until goodput (ok-completions/s) stops
  tracking the offer.  The KNEE is the step with peak goodput; the
  campaign's verdict is that past the knee the server sheds typed
  refusals rather than stalling (`sheds` climbs, `censored` stays 0).

- :func:`run_metastability` — the recovery probe: baseline at a
  comfortable rate, step to a multiple of it (the overload hold),
  step BACK to baseline, and measure how long the tail stays degraded
  after the offer drops.  A metastable server (retry storms, queues
  that never drain) stays degraded after the load is gone; a server
  with admission control recovers within a bounded settle window.
  One CONTINUOUS engine run — same sockets, same schedule axis — so
  recovery is observed through the connections that lived the
  overload, not through a fresh cohort.
"""

from __future__ import annotations

import dataclasses
import multiprocessing

from apus_tpu.load.latency import LatencyRecorder
from apus_tpu.load.openloop import OpenLoopConfig, OpenLoopEngine
from apus_tpu.load.schedule import poisson_schedule, uniform_schedule
from apus_tpu.load.zipf import ZipfKeys


# -- multi-process sharding -------------------------------------------


def _shard_worker(cfg_kw: dict, idx: int, q) -> None:
    """Top-level (picklable) shard body: run one engine, ship the RAW
    samples back so the parent merges one CO-safe recorder."""
    eng = OpenLoopEngine(OpenLoopConfig(**cfg_kw))
    try:
        _, stats = eng.run()
    except Exception as e:                               # noqa: BLE001
        q.put((idx, None, None, 0, {"shard_error": repr(e)}))
        return
    q.put((idx, eng.rec.samples, eng.rec.shed_samples,
           eng.rec.censored, stats))


def run_sharded(cfg: OpenLoopConfig, procs: int):
    """Run ``cfg``'s offered load split across ``procs`` forked
    workers; -> (SloReport, stats) merged at the sample level."""
    if procs <= 1:
        return OpenLoopEngine(cfg).run()
    ctx = multiprocessing.get_context("fork")
    q = ctx.Queue()
    kids = []
    for i in range(procs):
        kw = dataclasses.asdict(cfg)
        kw["rate"] = cfg.rate / procs
        kw["connections"] = max(8, cfg.connections // procs)
        kw["seed"] = cfg.seed + 7919 * (i + 1)   # distinct schedules
        p = ctx.Process(target=_shard_worker, args=(kw, i, q),
                        daemon=True)
        p.start()
        kids.append(p)
    rec = LatencyRecorder()
    stats: dict = {"procs": procs}
    for _ in kids:
        _, samples, sheds, censored, st = q.get()
        if samples is None:
            stats["shard_errors"] = stats.get("shard_errors", 0) + 1
            stats.setdefault("shard_error", st.get("shard_error"))
            continue
        rec.samples.extend(samples)
        rec.shed_samples.extend(sheds)
        rec.censored += censored
        rec.sheds += len(sheds)
        for k, v in st.items():
            stats[k] = stats.get(k, 0) + v
    for p in kids:
        p.join(timeout=10.0)
    rec.errors = sum(1 for _, _, ok in rec.samples if not ok)
    rep = rec.report(cfg.duration, slo_ms=cfg.slo_ms,
                     window_s=cfg.window_s)
    return rep, stats


# -- saturation staircase ---------------------------------------------


def run_saturation_ramp(cfg: OpenLoopConfig, start_rate: float,
                        step_rate: float, steps: int,
                        step_duration: float, procs: int = 1,
                        log=None) -> dict:
    """Staircase the offered rate and locate the goodput knee.

    Each step is an independent run (fresh schedule, fresh sockets) at
    ``start_rate + i*step_rate`` for ``step_duration`` seconds.  The
    knee is the peak-goodput step; ``saturated`` is True once a later
    step's goodput fell measurably below the peak OR typed sheds
    appeared (the server is refusing load instead of queueing it).
    """
    rows = []
    for i in range(max(1, steps)):
        rate = start_rate + i * step_rate
        c = dataclasses.replace(cfg, rate=rate, duration=step_duration,
                                seed=cfg.seed + 31 * i)
        rep, stats = run_sharded(c, procs)
        row = {"offered_rate": rate,
               "goodput_rate": rep.goodput_rate,
               "achieved_rate": rep.achieved_rate,
               "p50_ms": rep.p50_ms, "p99_ms": rep.p99_ms,
               "sheds": rep.sheds, "errors": rep.errors,
               "censored": rep.censored}
        rows.append(row)
        if log is not None:
            log(f"[ramp] step {i}: offered {rate:.0f}/s -> goodput "
                f"{rep.goodput_rate:.0f}/s p99 {rep.p99_ms:.1f}ms "
                f"sheds {rep.sheds}")
    best = max(rows, key=lambda r: r["goodput_rate"])
    saturated = (rows[-1]["goodput_rate"] < 0.95 * best["goodput_rate"]
                 or any(r["sheds"] > 0 for r in rows))
    return {"steps": rows,
            "knee_rate": best["offered_rate"],
            "knee_goodput": best["goodput_rate"],
            "saturated": saturated,
            "total_sheds": sum(r["sheds"] for r in rows),
            "total_censored": sum(r["censored"] for r in rows)}


# -- metastability probe ----------------------------------------------


class _PhasedEngine(OpenLoopEngine):
    """OpenLoopEngine driven by an explicit arrival schedule (the
    three-phase baseline/overload/recovery composite)."""

    def __init__(self, cfg: OpenLoopConfig, sched: "list[float]"):
        super().__init__(cfg)
        self._sched = sched

    def _plan(self):
        cfg = self.cfg
        zipf = ZipfKeys(cfg.nkeys, theta=cfg.theta, seed=cfg.seed,
                        scramble=cfg.scramble, prefix=cfg.key_prefix)
        if cfg.groups > 1:
            from apus_tpu.runtime.router import group_of_key
        from apus_tpu.load.openloop import _Op
        ops = []
        for t in self._sched:
            key = zipf.key()
            gid = (group_of_key(key, cfg.groups)
                   if cfg.groups > 1 else 0)
            ops.append(_Op(t, key, self._rng.random()
                           < cfg.get_fraction, gid))
        return ops


def _phase_sched(rate: float, duration: float, seed: int,
                 arrival: str, offset: float) -> "list[float]":
    s = (uniform_schedule(rate, duration) if arrival == "uniform"
         else poisson_schedule(rate, duration, seed=seed))
    return [offset + t for t in s]


def run_metastability(cfg: OpenLoopConfig, overload_x: float = 5.0,
                      base_s: float = 5.0, overload_s: float = 5.0,
                      recover_s: float = 10.0, log=None) -> dict:
    """Step to ``overload_x`` times the baseline rate, step back, and
    verify the tail recovers within a bounded settle window.

    -> dict with per-phase goodput/p99, ``recovery_settle_s`` (time
    from the step-down edge to the LAST degraded window), and
    ``recovered`` (recovery-phase goodput back within 80% of baseline
    and the run's final window clean).
    """
    total = base_s + overload_s + recover_s
    sched = (_phase_sched(cfg.rate, base_s, cfg.seed, cfg.arrival, 0.0)
             + _phase_sched(cfg.rate * overload_x, overload_s,
                            cfg.seed + 1, cfg.arrival, base_s)
             + _phase_sched(cfg.rate, recover_s, cfg.seed + 2,
                            cfg.arrival, base_s + overload_s))
    c = dataclasses.replace(cfg, duration=total)
    eng = _PhasedEngine(c, sched)
    rep, stats = eng.run()
    edges = (base_s, base_s + overload_s)

    def phase_of(t: float) -> int:
        return 0 if t < edges[0] else (1 if t < edges[1] else 2)

    ok_by = [0, 0, 0]
    lat_by: "list[list[float]]" = [[], [], []]
    for t, lat, ok in eng.rec.samples:
        p = phase_of(t)
        lat_by[p].append(lat)
        if ok:
            ok_by[p] += 1
    shed_by = [0, 0, 0]
    for t, _ in eng.rec.shed_samples:
        shed_by[phase_of(t)] += 1
    from apus_tpu.load.latency import percentile
    spans = [base_s, overload_s, recover_s]
    phases = []
    for p, name in enumerate(("baseline", "overload", "recovery")):
        ls = sorted(lat_by[p])
        phases.append({"phase": name,
                       "offered_rate": (cfg.rate * overload_x
                                        if p == 1 else cfg.rate),
                       "goodput_rate": ok_by[p] / spans[p],
                       "p99_ms": percentile(ls, 0.99) * 1e3,
                       "sheds": shed_by[p]})
    # Settle time: the last degraded window at-or-after the step-down
    # edge bounds how long the overload's wake lasted.
    settle = 0.0
    for row in rep.windows:
        if row[0] >= edges[1] - 1e-9 and row[3]:
            settle = max(settle, row[0] + cfg.window_s - edges[1])
    last_clean = not (rep.windows and rep.windows[-1][3])
    base_good, rec_good = phases[0]["goodput_rate"], \
        phases[2]["goodput_rate"]
    recovered = (rec_good >= 0.8 * base_good and last_clean)
    out = {"phases": phases, "overload_x": overload_x,
           "recovery_settle_s": settle, "recovered": recovered,
           "censored": rep.censored, "sheds": rep.sheds,
           "report": rep.to_dict(), "stats": stats}
    if log is not None:
        log(f"[meta] baseline {base_good:.0f}/s -> overload x"
            f"{overload_x:g} (sheds {phases[1]['sheds']}) -> recovery "
            f"{rec_good:.0f}/s, settle {settle:.2f}s, "
            f"recovered={recovered}")
    return out
