"""Open-loop arrival schedules.

An OPEN-LOOP load generator decides every op's arrival time BEFORE the
run from a fixed arrival-rate process, and the schedule never slows
down because the server did — the defining property that makes the
latency accounting coordinated-omission-safe (latency.py anchors each
op at its scheduled arrival).  A closed-loop client (fixed concurrency,
next op after the previous reply) inhales exactly the samples a stalled
server would have made slow, and its p999 measures the CLIENT's
politeness, not the user's experience.

All schedules are offsets in seconds from the run start, sorted,
deterministic in their arguments.
"""

from __future__ import annotations

import math
import random


def poisson_schedule(rate: float, duration: float,
                     seed: int = 0) -> "list[float]":
    """Poisson arrivals at ``rate``/s for ``duration`` s (exponential
    inter-arrival gaps) — the standard open-loop arrival process
    (independent users don't coordinate their clicks)."""
    if rate <= 0 or duration <= 0:
        return []
    rng = random.Random(seed)
    out: list[float] = []
    t = 0.0
    while True:
        t += -math.log(1.0 - rng.random()) / rate
        if t >= duration:
            return out
        out.append(t)


def uniform_schedule(rate: float, duration: float) -> "list[float]":
    """Evenly spaced arrivals (the redis-benchmark/wrk2 fixed-rate
    shape): exactly ``floor(rate*duration)`` ops, gap 1/rate."""
    n = int(rate * duration)
    gap = 1.0 / rate
    return [i * gap for i in range(n)]


def burst_schedule(base: "list[float]", burst_every: float,
                   burst_size: int, duration: float) -> "list[float]":
    """Overlay FAN-IN bursts on a base schedule: every ``burst_every``
    seconds, ``burst_size`` arrivals at the SAME instant (a thundering
    herd — cache expiry, push notification, synchronized retry).  The
    burst ops are part of the open-loop contract like any other
    arrival: their latency anchors at the burst instant, so the queue
    they build is measured, not excused."""
    if burst_every <= 0 or burst_size <= 0:
        return list(base)
    out = list(base)
    t = burst_every
    while t < duration:
        out.extend([t] * burst_size)
        t += burst_every
    out.sort()
    return out
