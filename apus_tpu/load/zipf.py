"""Seeded zipfian key sampler (hot-key skew for the SLO harness).

The YCSB ZipfianGenerator closed form (Gray et al., "Quickly
Generating Billion-Record Synthetic Databases"): rank popularity
follows ``P(rank=k) ~ 1/k^theta`` with one uniform draw per sample —
no per-sample search — after an O(n) zeta precompute.  theta=0.99 is
the YCSB default ("zipfian constant"); theta=0 degenerates to uniform.

Ranks are SCRAMBLED onto the keyspace by default (FNV-1a), so the
hottest keys are spread across hash buckets / consensus groups instead
of clustering at one end — exactly how YCSB's ScrambledZipfian keeps a
skewed workload from aliasing with the store's own layout.  With
``scramble=False`` rank r maps to key index r directly (rank 0 = the
single hottest key), which the hot/cold split benches rely on.

Deterministic: same (n, theta, seed) -> same key sequence, forever
(pinned by tests/test_load.py).
"""

from __future__ import annotations

import random

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def _fnv64(v: int) -> int:
    h = _FNV_OFFSET
    for _ in range(8):
        h = ((h ^ (v & 0xFF)) * _FNV_PRIME) & _MASK64
        v >>= 8
    return h


class ZipfKeys:
    """Zipfian sampler over ``n`` keys; ``sample()`` returns a key
    index in [0, n), ``key()`` a formatted key."""

    def __init__(self, n: int, theta: float = 0.99, seed: int = 0,
                 scramble: bool = True, prefix: bytes = b"lk"):
        if n <= 0:
            raise ValueError("n must be positive")
        self.n = n
        self.theta = theta
        self.scramble = scramble
        self.prefix = prefix
        self.rng = random.Random(seed)
        if theta <= 0:
            self._uniform = True
            return
        self._uniform = False
        zetan = 0.0
        for i in range(1, n + 1):
            zetan += 1.0 / (i ** theta)
        self._zetan = zetan
        self._zeta2 = 1.0 + 0.5 ** theta
        self._alpha = 1.0 / (1.0 - theta)
        self._eta = ((1.0 - (2.0 / n) ** (1.0 - theta))
                     / (1.0 - self._zeta2 / zetan))

    def sample(self) -> int:
        if self._uniform:
            return self.rng.randrange(self.n)
        u = self.rng.random()
        uz = u * self._zetan
        if uz < 1.0:
            rank = 0
        elif uz < self._zeta2:
            rank = 1
        else:
            rank = int(self.n * (self._eta * u - self._eta + 1.0)
                       ** self._alpha)
            if rank >= self.n:
                rank = self.n - 1
        if not self.scramble:
            return rank
        return _fnv64(rank) % self.n

    def key(self) -> bytes:
        return b"%s%08d" % (self.prefix, self.sample())
