"""Replicated state machines ("models" of this framework).

The reference abstracts the replicated application behind a tiny vtable
(dare_sm_t, dare_sm.h:49-60) plus proxy callbacks; commands are opaque
bytes (dare_sm.h:23-27).  Same here: anything implementing
``StateMachine`` can be replicated — the built-in KVS
(dare_kvs_sm.c analog), the app-replay SM driven by the native proxy,
or test doubles.
"""

from apus_tpu.models.sm import StateMachine, Snapshot
from apus_tpu.models.kvs import KvsStateMachine

__all__ = ["StateMachine", "Snapshot", "KvsStateMachine"]
