"""Built-in key-value state machine (dare_kvs_sm.c analog).

The reference ships a chained-hash KVS with PUT/GET/RM
(dare_kvs_sm.c:157-202) used by DARE's native client path.  Ours speaks a
trivial length-prefixed command encoding and doubles as the demo app for
end-to-end tests when no real server binary (Redis etc.) is present.

Command wire format (ascii-ish, newline-free):
    b"P<klen>:<key><value>"  put
    b"G<klen>:<key>"         get (reply = value or empty)
    b"D<klen>:<key>"         delete
"""

from __future__ import annotations

from apus_tpu.models.sm import Snapshot, StateMachine


def encode_put(key: bytes, value: bytes) -> bytes:
    return b"P%d:%s%s" % (len(key), key, value)


def encode_get(key: bytes) -> bytes:
    return b"G%d:%s" % (len(key), key)


def encode_delete(key: bytes) -> bytes:
    return b"D%d:%s" % (len(key), key)


class KvsStateMachine(StateMachine):
    def __init__(self) -> None:
        self.store: dict[bytes, bytes] = {}
        # Delta-snapshot bookkeeping (models.sm delta contract): the
        # log index that last MODIFIED each key (puts and deletes), so
        # ``delta_since(base)`` can ship only the keys touched after a
        # rejoiner's applied determinant.  ``delta_floor`` is the
        # earliest base the history covers: 0 from a fresh boot (we
        # saw every apply), bumped to the snapshot point on a full
        # install (per-key history before it is unknown).
        self._mod_idx: dict[bytes, int] = {}
        self._del_idx: dict[bytes, int] = {}
        self.delta_floor = 0
        # Streamable snapshot "rope" (snapshot_stream_size /
        # read_snapshot_chunk): the snapshot image as a FROZEN list of
        # byte frames REFERENCING the live value objects — capture is
        # O(#keys) with zero value copies, so a 100 MB snapshot never
        # materializes (the b"".join under the node lock stalled
        # heartbeats for hundreds of ms at that scale and deposed the
        # leader on every capture).  ``dump_generation`` bumps on every
        # rebuild; ``pin_dump_reader`` hands out a reader over the
        # frozen rope for off-tick streams and compaction.
        self._mutations = 0
        self.dump_generation = 0
        self._rope = None          # (frames, starts, total, mutations)

    def apply(self, idx: int, cmd: bytes) -> bytes | None:
        op = cmd[:1]
        klen_s, rest = cmd[1:].split(b":", 1)
        klen = int(klen_s)
        key, payload = rest[:klen], rest[klen:]
        if op == b"P":
            self.store[key] = payload
            self._mutations += 1
            if idx:
                self._mod_idx[key] = idx
                self._del_idx.pop(key, None)
            return b"OK"
        if op == b"G":
            return self.store.get(key, b"")
        if op == b"D":
            self.store.pop(key, None)
            self._mutations += 1
            if idx:
                self._mod_idx.pop(key, None)
                self._del_idx[key] = idx
            return b"OK"
        raise ValueError(f"bad kvs op {op!r}")

    # -- streamable snapshot rope (zero-copy capture) ----------------------

    def _build_rope(self) -> tuple:
        """Byte-identical to ``create_snapshot().data`` as a frame
        list: per item ``<klen>:<key><vlen>:<value>`` with the VALUE
        frames aliasing the live (immutable) bytes objects.  Frozen
        once built — later mutations replace the rope, never edit it."""
        frames: list[bytes] = []
        starts: list[int] = []
        total = 0
        for k, v in sorted(self.store.items()):
            for f in (b"%d:%s%d:" % (len(k), k, len(v)), v):
                frames.append(f)
                starts.append(total)
                total += len(f)
        return frames, starts, total, self._mutations

    def _fresh_rope(self) -> tuple:
        if self._rope is None or self._rope[3] != self._mutations:
            self._rope = self._build_rope()
            self.dump_generation += 1
        return self._rope

    def snapshot_stream_size(self) -> int:
        """Chunked-stream capture hook (see core.node
        make_snapshot_stream_meta): the image size at the current
        apply point.  Called under the node lock, like every apply —
        the rope the size refers to is frozen at this moment."""
        return self._fresh_rope()[2]

    @staticmethod
    def _rope_read(rope: tuple, off: int, n: int) -> bytes:
        import bisect
        frames, starts, total, _ = rope
        if off >= total:
            return b""
        n = min(n, total - off)
        i = bisect.bisect_right(starts, off) - 1
        out = []
        got = 0
        while got < n and i < len(frames):
            f = frames[i]
            lo = off + got - starts[i]
            take = f[lo:lo + (n - got)]
            out.append(take)
            got += len(take)
            i += 1
        return b"".join(out)

    def read_snapshot_chunk(self, off: int, n: int) -> bytes:
        # Serve the EXISTING rope, never rebuild here: a rebuild would
        # bump the generation AFTER the caller's fence check passed and
        # hand it bytes of a different capture (torn stream).  The
        # generation fence upstream aborts streams whose rope was
        # replaced by a later capture.
        rope = self._rope if self._rope is not None \
            else self._fresh_rope()
        return self._rope_read(rope, off, n)

    def pin_dump_reader(self):
        """Reader over the CURRENT frozen rope, immune to later
        rebuilds — the off-tick stream/compaction pin (the fd-dup
        analog of dump-file SMs).  Pins the EXISTING rope (no rebuild:
        the caller just generation-checked it against its capture —
        rebuilding here would pin a newer image than the captured
        metadata)."""
        rope = self._rope if self._rope is not None \
            else self._fresh_rope()
        return lambda off, n: self._rope_read(rope, off, n)

    # -- delta snapshots (models.sm contract) ------------------------------

    def delta_since(self, base_idx: int) -> bytes | None:
        """Keys modified after ``base_idx``, as ``u8 kind | key blob
        [| value blob]`` records (kind P=put, D=delete), or None when
        the base predates our tracked history."""
        import struct
        if base_idx < self.delta_floor:
            return None
        out = []
        for k, i in self._mod_idx.items():
            if i > base_idx:
                v = self.store[k]
                out.append(b"P" + struct.pack("<I", len(k)) + k
                           + struct.pack("<I", len(v)) + v)
        for k, i in self._del_idx.items():
            if i > base_idx:
                out.append(b"D" + struct.pack("<I", len(k)) + k)
        return b"".join(out)

    def apply_snapshot_delta(self, snap: Snapshot) -> None:
        """Merge a delta produced by ``delta_since`` into the live
        store (the receiver half; base-determinant equality is checked
        by the caller, Node.install_snapshot)."""
        import struct
        self._mutations += 1
        buf = snap.data
        off = 0
        while off < len(buf):
            kind = buf[off:off + 1]
            off += 1
            (klen,) = struct.unpack_from("<I", buf, off)
            off += 4
            k = buf[off:off + klen]
            off += klen
            if kind == b"P":
                (vlen,) = struct.unpack_from("<I", buf, off)
                off += 4
                self.store[k] = buf[off:off + vlen]
                off += vlen
                self._mod_idx[k] = snap.last_idx
                self._del_idx.pop(k, None)
            elif kind == b"D":
                self.store.pop(k, None)
                self._mod_idx.pop(k, None)
                self._del_idx[k] = snap.last_idx
            else:
                raise ValueError(f"bad delta record kind {kind!r}")
        # Stamping merged keys at snap.last_idx is conservative-exact:
        # their true modification indices lie in (base, last_idx], so
        # any later delta_since(b >= delta_floor) still includes every
        # key modified after b (at worst a few extra).  The floor is
        # unchanged — history below it was already unknown.

    def query(self, cmd: bytes) -> bytes | None:
        """GET without logging (linearizable-read path).  GET is
        side-effect-free, so it shares apply's decode+lookup."""
        if cmd[:1] != b"G":
            raise ValueError("only GET is a read-only command")
        return self.apply(0, cmd)

    def create_snapshot(self, last_idx: int, last_term: int) -> Snapshot:
        items = b"".join(b"%d:%s%d:%s" % (len(k), k, len(v), v)
                         for k, v in sorted(self.store.items()))
        return Snapshot(last_idx, last_term, items)

    def apply_snapshot(self, snap: Snapshot) -> None:
        self.store = {}
        # Full replace: per-key modification history before the
        # snapshot point is unknown — deltas can only build on bases at
        # or past it.  The rope is stale too.
        self._mod_idx = {}
        self._del_idx = {}
        self.delta_floor = snap.last_idx
        self._mutations += 1
        # Index-based parse, O(total): the old split-and-reslice loop
        # copied the remaining buffer per item — O(items x size), which
        # at a 100 MB image turned the receiver's install into minutes
        # of memcpy under its lock (peers then evicted it as dead).
        buf = snap.data
        off = 0
        end = len(buf)
        while off < end:
            j = buf.index(b":", off)
            klen = int(buf[off:j])
            k = buf[j + 1:j + 1 + klen]
            off = j + 1 + klen
            j = buf.index(b":", off)
            vlen = int(buf[off:j])
            v = buf[j + 1:j + 1 + vlen]
            off = j + 1 + vlen
            self.store[k] = v
