"""Built-in key-value state machine (dare_kvs_sm.c analog).

The reference ships a chained-hash KVS with PUT/GET/RM
(dare_kvs_sm.c:157-202) used by DARE's native client path.  Ours speaks a
trivial length-prefixed command encoding and doubles as the demo app for
end-to-end tests when no real server binary (Redis etc.) is present.

Command wire format (ascii-ish, newline-free):
    b"P<klen>:<key><value>"  put
    b"G<klen>:<key>"         get (reply = value or empty)
    b"D<klen>:<key>"         delete
"""

from __future__ import annotations

from apus_tpu.models.sm import Snapshot, StateMachine


def encode_put(key: bytes, value: bytes) -> bytes:
    return b"P%d:%s%s" % (len(key), key, value)


def encode_get(key: bytes) -> bytes:
    return b"G%d:%s" % (len(key), key)


def encode_delete(key: bytes) -> bytes:
    return b"D%d:%s" % (len(key), key)


class KvsStateMachine(StateMachine):
    def __init__(self) -> None:
        self.store: dict[bytes, bytes] = {}

    def apply(self, idx: int, cmd: bytes) -> bytes | None:
        op = cmd[:1]
        klen_s, rest = cmd[1:].split(b":", 1)
        klen = int(klen_s)
        key, payload = rest[:klen], rest[klen:]
        if op == b"P":
            self.store[key] = payload
            return b"OK"
        if op == b"G":
            return self.store.get(key, b"")
        if op == b"D":
            self.store.pop(key, None)
            return b"OK"
        raise ValueError(f"bad kvs op {op!r}")

    def query(self, cmd: bytes) -> bytes | None:
        """GET without logging (linearizable-read path).  GET is
        side-effect-free, so it shares apply's decode+lookup."""
        if cmd[:1] != b"G":
            raise ValueError("only GET is a read-only command")
        return self.apply(0, cmd)

    def create_snapshot(self, last_idx: int, last_term: int) -> Snapshot:
        items = b"".join(b"%d:%s%d:%s" % (len(k), k, len(v), v)
                         for k, v in sorted(self.store.items()))
        return Snapshot(last_idx, last_term, items)

    def apply_snapshot(self, snap: Snapshot) -> None:
        self.store = {}
        buf = snap.data
        while buf:
            klen_s, buf = buf.split(b":", 1)
            k, buf = buf[:int(klen_s)], buf[int(klen_s):]
            vlen_s, buf = buf.split(b":", 1)
            v, buf = buf[:int(vlen_s)], buf[int(vlen_s):]
            self.store[k] = v
