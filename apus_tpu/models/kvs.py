"""Built-in key-value state machine (dare_kvs_sm.c analog).

The reference ships a chained-hash KVS with PUT/GET/RM
(dare_kvs_sm.c:157-202) used by DARE's native client path.  Ours speaks a
trivial length-prefixed command encoding and doubles as the demo app for
end-to-end tests when no real server binary (Redis etc.) is present.

Command wire format (ascii-ish, newline-free):
    b"P<klen>:<key><value>"  put
    b"G<klen>:<key>"         get (reply = value or empty)
    b"D<klen>:<key>"         delete
"""

from __future__ import annotations

import json
import struct

from apus_tpu.models.sm import REFUSED_REPLY_PREFIX, Snapshot, StateMachine


def encode_put(key: bytes, value: bytes) -> bytes:
    return b"P%d:%s%s" % (len(key), key, value)


def encode_get(key: bytes) -> bytes:
    return b"G%d:%s" % (len(key), key)


def encode_delete(key: bytes) -> bytes:
    return b"D%d:%s" % (len(key), key)


def decode_key(cmd: bytes) -> "bytes | None":
    """Key of a P/G/D command, or None for any other payload (the
    elastic-group admission check routes on it; non-KVS payloads are
    never bucket-routed)."""
    if cmd[:1] not in (b"P", b"G", b"D"):
        return None
    try:
        klen_s, rest = cmd[1:].split(b":", 1)
        return rest[:int(klen_s)]
    except (ValueError, IndexError):
        return None


# -- elastic-group migration commands (replicated in the groups' own
#    logs; see runtime/elastic.py for the protocol walkthrough) -----------
#
#   MB  (src log)  freeze a bucket set for migration ``mig_id`` to
#                  ``dst_gid`` at shard-map epoch ``epoch``; from its
#                  apply on, writes into those buckets deterministically
#                  no-op with a REFUSED sentinel, so the capture any
#                  later driver attempt takes is stable.
#   MI  (dst log)  install the captured pairs (idempotent by mig_id —
#                  a resumed driver may deliver it twice).
#   MC  (src log)  commit the migration: delete the moved keys, flip
#                  bucket ownership to dst, bump the shard-map epoch.
#
# State rides the RESERVED key below so it survives snapshot/delta
# catch-up exactly like ordinary keys (a replica primed by snapshot
# never re-applies the M entries themselves).

MIG_STATE_KEY = b"\x00apus.migs"
RESERVED_PREFIX = b"\x00apus."

REFUSED_FROZEN = REFUSED_REPLY_PREFIX + b"frozen"
REFUSED_DEPARTED = REFUSED_REPLY_PREFIX + b"departed"

_U16 = struct.Struct("<H")


def _enc_buckets(buckets) -> bytes:
    bs = sorted(set(buckets))
    return _U16.pack(len(bs)) + b"".join(_U16.pack(b) for b in bs)


def _dec_buckets(buf: bytes, off: int) -> "tuple[list[int], int]":
    (n,) = _U16.unpack_from(buf, off)
    off += 2
    out = [_U16.unpack_from(buf, off + 2 * i)[0] for i in range(n)]
    return out, off + 2 * n


def encode_mig_begin(mig_id: int, dst_gid: int, epoch: int,
                     buckets, cid_size: int = 0,
                     cid_mask: int = 0) -> bytes:
    """``cid_size``/``cid_mask`` are the DST group's genesis
    configuration (the src group's member set at split time), decided
    ONCE here and replicated with the record — every daemon creates
    the new group from the same bytes, so genesis cids can never
    diverge (locally-projected cids did, and same-epoch disagreement
    has no reconciliation path)."""
    return (b"MB" + struct.pack("<QBIBH", mig_id, dst_gid, epoch,
                                cid_size, cid_mask)
            + _enc_buckets(buckets))


def decode_mig_begin(cmd: bytes):
    """-> (mig_id, dst_gid, epoch, cid_size, cid_mask, buckets)."""
    mig_id, dst, epoch, size, mask = struct.unpack_from("<QBIBH",
                                                        cmd, 2)
    buckets, _ = _dec_buckets(cmd, 18)
    return mig_id, dst, epoch, size, mask, buckets


def encode_mig_install(mig_id: int, src_gid: int, epoch: int, buckets,
                       pairs) -> bytes:
    out = [b"MI", struct.pack("<QBI", mig_id, src_gid, epoch),
           _enc_buckets(buckets), struct.pack("<I", len(pairs))]
    for k, v in pairs:
        out.append(struct.pack("<I", len(k)) + k
                   + struct.pack("<I", len(v)) + v)
    return b"".join(out)


def encode_mig_commit(mig_id: int) -> bytes:
    return b"MC" + struct.pack("<Q", mig_id)


class KvsStateMachine(StateMachine):
    def __init__(self) -> None:
        self.store: dict[bytes, bytes] = {}
        # Delta-snapshot bookkeeping (models.sm delta contract): the
        # log index that last MODIFIED each key (puts and deletes), so
        # ``delta_since(base)`` can ship only the keys touched after a
        # rejoiner's applied determinant.  ``delta_floor`` is the
        # earliest base the history covers: 0 from a fresh boot (we
        # saw every apply), bumped to the snapshot point on a full
        # install (per-key history before it is unknown).
        self._mod_idx: dict[bytes, int] = {}
        self._del_idx: dict[bytes, int] = {}
        self.delta_floor = 0
        # Streamable snapshot "rope" (snapshot_stream_size /
        # read_snapshot_chunk): the snapshot image as a FROZEN list of
        # byte frames REFERENCING the live value objects — capture is
        # O(#keys) with zero value copies, so a 100 MB snapshot never
        # materializes (the b"".join under the node lock stalled
        # heartbeats for hundreds of ms at that scale and deposed the
        # leader on every capture).  ``dump_generation`` bumps on every
        # rebuild; ``pin_dump_reader`` hands out a reader over the
        # frozen rope for off-tick streams and compaction.
        self._mutations = 0
        self.dump_generation = 0
        self._rope = None          # (frames, starts, total, mutations)
        # Elastic-group migration bookkeeping (mirrored into the
        # reserved MIG_STATE_KEY so it rides snapshots and deltas like
        # any other key; _mig_reload rebuilds these after an install).
        # migs_out: mig_id(str) -> [dst_gid, epoch, state, buckets]
        #   with state "frozen" -> "committed"; migs_in: mig_id(str) ->
        #   [src_gid, epoch, buckets] (install dedup).
        self.migs_out: dict[str, list] = {}
        self.migs_in: dict[str, list] = {}
        self._frozen: set[int] = set()
        self._departed: dict[int, tuple[int, int]] = {}

    # -- internal mutation helpers (delta bookkeeping in one place) --------

    def _put_internal(self, idx: int, key: bytes, value: bytes) -> None:
        self.store[key] = value
        self._mutations += 1
        if idx:
            self._mod_idx[key] = idx
            self._del_idx.pop(key, None)

    def _del_internal(self, idx: int, key: bytes) -> None:
        self.store.pop(key, None)
        self._mutations += 1
        if idx:
            self._mod_idx.pop(key, None)
            self._del_idx[key] = idx

    def apply(self, idx: int, cmd: bytes) -> bytes | None:
        op = cmd[:1]
        if op == b"M":
            return self._apply_mig(idx, cmd)
        klen_s, rest = cmd[1:].split(b":", 1)
        klen = int(klen_s)
        key, payload = rest[:klen], rest[klen:]
        if op == b"P" or op == b"D":
            # Elastic-group fence: a decided write into a FROZEN bucket
            # (migration capture in flight) or a DEPARTED one (already
            # owned by another group) deterministically no-ops with a
            # REFUSED sentinel on every replica — admission refuses
            # these up front; only entries that raced a leader change
            # past an unapplied MB/MC reach here.  The refusal is never
            # dedup-cached (see sm.REFUSED_REPLY_PREFIX), so the
            # client's re-routed retry executes exactly once at the
            # owner.
            if (self._frozen or self._departed) \
                    and not key.startswith(RESERVED_PREFIX):
                from apus_tpu.runtime.router import bucket_of_key
                b = bucket_of_key(key)
                if b in self._departed:
                    return REFUSED_DEPARTED
                if b in self._frozen:
                    return REFUSED_FROZEN
        if op == b"P":
            self._put_internal(idx, key, payload)
            return b"OK"
        if op == b"G":
            return self.store.get(key, b"")
        if op == b"D":
            self._del_internal(idx, key)
            return b"OK"
        raise ValueError(f"bad kvs op {op!r}")

    # -- elastic-group migration ops ---------------------------------------

    def _apply_mig(self, idx: int, cmd: bytes) -> bytes:
        from apus_tpu.runtime.router import bucket_of_key
        sub = cmd[1:2]
        if sub == b"B":
            mig_id, dst, epoch, size, mask, buckets = \
                decode_mig_begin(cmd)
            if str(mig_id) not in self.migs_out:
                self.migs_out[str(mig_id)] = [dst, epoch, "frozen",
                                              buckets, size, mask]
                self._mig_commit_state(idx)
            return b"OK"
        if sub == b"I":
            mig_id, src, epoch = struct.unpack_from("<QBI", cmd, 2)
            buckets, off = _dec_buckets(cmd, 15)
            if str(mig_id) in self.migs_in:
                return b"OK"                  # resumed-driver duplicate
            (npairs,) = struct.unpack_from("<I", cmd, off)
            off += 4
            # Replace bucket contents (exact even if an aborted earlier
            # attempt of a DIFFERENT mig left strays): delete, then
            # install the frozen capture.
            bset = set(buckets)
            for k in [k for k in self.store
                      if not k.startswith(RESERVED_PREFIX)
                      and bucket_of_key(k) in bset]:
                self._del_internal(idx, k)
            for _ in range(npairs):
                (klen,) = struct.unpack_from("<I", cmd, off)
                off += 4
                k = cmd[off:off + klen]
                off += klen
                (vlen,) = struct.unpack_from("<I", cmd, off)
                off += 4
                self._put_internal(idx, k, cmd[off:off + vlen])
                off += vlen
            self.migs_in[str(mig_id)] = [src, epoch, buckets]
            self._mig_commit_state(idx)
            return b"OK"
        if sub == b"C":
            (mig_id,) = struct.unpack_from("<Q", cmd, 2)
            rec = self.migs_out.get(str(mig_id))
            if rec is None:
                return b"NOMIG"
            if rec[2] != "committed":
                bset = set(rec[3])
                for k in [k for k in self.store
                          if not k.startswith(RESERVED_PREFIX)
                          and bucket_of_key(k) in bset]:
                    self._del_internal(idx, k)
                rec[2] = "committed"
                self._mig_commit_state(idx)
            return b"OK"
        raise ValueError(f"bad kvs migration op {cmd[:2]!r}")

    def _mig_rederive(self) -> None:
        """Per-bucket fence from the migration event history.  A bucket
        is DEPARTED only while its latest event is an OUTBOUND commit —
        a later inbound install (the bucket returned, e.g. split then
        merged back) clears the fence; epochs strictly increase along a
        bucket's ownership chain, so the max-epoch event decides."""
        self._frozen = set()
        self._departed = {}
        out_ev: dict[int, tuple[int, int]] = {}
        in_ev: dict[int, int] = {}
        for rec in self.migs_out.values():
            dst, epoch, state, buckets = rec[:4]
            if state == "frozen":
                self._frozen.update(buckets)
            elif state == "committed":
                for b in buckets:
                    if epoch > out_ev.get(b, (0, -1))[1]:
                        out_ev[b] = (dst, epoch)
        for rec in self.migs_in.values():
            src, epoch = rec[0], rec[1]
            for b in (rec[2] if len(rec) > 2 else []):
                in_ev[b] = max(in_ev.get(b, -1), epoch)
        for b, (dst, epoch) in out_ev.items():
            if epoch > in_ev.get(b, -1):
                self._departed[b] = (dst, epoch)

    def _mig_commit_state(self, idx: int) -> None:
        """Re-derive the bucket fences and mirror the migration tables
        into the reserved key (deterministic bytes: sorted keys), so
        they survive snapshot/delta catch-up like ordinary state."""
        self._mig_rederive()
        blob = json.dumps({"out": self.migs_out, "in": self.migs_in},
                          sort_keys=True,
                          separators=(",", ":")).encode()
        self._put_internal(idx, MIG_STATE_KEY, blob)

    def _mig_reload(self) -> None:
        """Rebuild the in-memory migration tables from the reserved key
        after a snapshot/delta install replaced or merged state."""
        blob = self.store.get(MIG_STATE_KEY)
        if not blob:
            if self.migs_out or self.migs_in:
                self.migs_out, self.migs_in = {}, {}
                self._frozen, self._departed = set(), {}
            return
        st = json.loads(blob.decode())
        self.migs_out = {k: list(v) for k, v in st.get("out",
                                                       {}).items()}
        self.migs_in = {k: list(v) for k, v in st.get("in", {}).items()}
        self._mig_rederive()

    # -- streamable snapshot rope (zero-copy capture) ----------------------

    def _build_rope(self) -> tuple:
        """Byte-identical to ``create_snapshot().data`` as a frame
        list: per item ``<klen>:<key><vlen>:<value>`` with the VALUE
        frames aliasing the live (immutable) bytes objects.  Frozen
        once built — later mutations replace the rope, never edit it."""
        frames: list[bytes] = []
        starts: list[int] = []
        total = 0
        for k, v in sorted(self.store.items()):
            for f in (b"%d:%s%d:" % (len(k), k, len(v)), v):
                frames.append(f)
                starts.append(total)
                total += len(f)
        return frames, starts, total, self._mutations

    def _fresh_rope(self) -> tuple:
        if self._rope is None or self._rope[3] != self._mutations:
            self._rope = self._build_rope()
            self.dump_generation += 1
        return self._rope

    def snapshot_stream_size(self) -> int:
        """Chunked-stream capture hook (see core.node
        make_snapshot_stream_meta): the image size at the current
        apply point.  Called under the node lock, like every apply —
        the rope the size refers to is frozen at this moment."""
        return self._fresh_rope()[2]

    @staticmethod
    def _rope_read(rope: tuple, off: int, n: int) -> bytes:
        import bisect
        frames, starts, total, _ = rope
        if off >= total:
            return b""
        n = min(n, total - off)
        i = bisect.bisect_right(starts, off) - 1
        out = []
        got = 0
        while got < n and i < len(frames):
            f = frames[i]
            lo = off + got - starts[i]
            take = f[lo:lo + (n - got)]
            out.append(take)
            got += len(take)
            i += 1
        return b"".join(out)

    def read_snapshot_chunk(self, off: int, n: int) -> bytes:
        # Serve the EXISTING rope, never rebuild here: a rebuild would
        # bump the generation AFTER the caller's fence check passed and
        # hand it bytes of a different capture (torn stream).  The
        # generation fence upstream aborts streams whose rope was
        # replaced by a later capture.
        rope = self._rope if self._rope is not None \
            else self._fresh_rope()
        return self._rope_read(rope, off, n)

    def pin_dump_reader(self):
        """Reader over the CURRENT frozen rope, immune to later
        rebuilds — the off-tick stream/compaction pin (the fd-dup
        analog of dump-file SMs).  Pins the EXISTING rope (no rebuild:
        the caller just generation-checked it against its capture —
        rebuilding here would pin a newer image than the captured
        metadata)."""
        rope = self._rope if self._rope is not None \
            else self._fresh_rope()
        return lambda off, n: self._rope_read(rope, off, n)

    # -- delta snapshots (models.sm contract) ------------------------------

    def delta_since(self, base_idx: int) -> bytes | None:
        """Keys modified after ``base_idx``, as ``u8 kind | key blob
        [| value blob]`` records (kind P=put, D=delete), or None when
        the base predates our tracked history."""
        import struct
        if base_idx < self.delta_floor:
            return None
        out = []
        for k, i in self._mod_idx.items():
            if i > base_idx:
                v = self.store[k]
                out.append(b"P" + struct.pack("<I", len(k)) + k
                           + struct.pack("<I", len(v)) + v)
        for k, i in self._del_idx.items():
            if i > base_idx:
                out.append(b"D" + struct.pack("<I", len(k)) + k)
        return b"".join(out)

    def apply_snapshot_delta(self, snap: Snapshot) -> None:
        """Merge a delta produced by ``delta_since`` into the live
        store (the receiver half; base-determinant equality is checked
        by the caller, Node.install_snapshot)."""
        import struct
        self._mutations += 1
        buf = snap.data
        off = 0
        while off < len(buf):
            kind = buf[off:off + 1]
            off += 1
            (klen,) = struct.unpack_from("<I", buf, off)
            off += 4
            k = buf[off:off + klen]
            off += klen
            if kind == b"P":
                (vlen,) = struct.unpack_from("<I", buf, off)
                off += 4
                self.store[k] = buf[off:off + vlen]
                off += vlen
                self._mod_idx[k] = snap.last_idx
                self._del_idx.pop(k, None)
            elif kind == b"D":
                self.store.pop(k, None)
                self._mod_idx.pop(k, None)
                self._del_idx[k] = snap.last_idx
            else:
                raise ValueError(f"bad delta record kind {kind!r}")
        # Stamping merged keys at snap.last_idx is conservative-exact:
        # their true modification indices lie in (base, last_idx], so
        # any later delta_since(b >= delta_floor) still includes every
        # key modified after b (at worst a few extra).  The floor is
        # unchanged — history below it was already unknown.
        self._mig_reload()

    def query(self, cmd: bytes) -> bytes | None:
        """GET without logging (linearizable-read path).  GET is
        side-effect-free, so it shares apply's decode+lookup."""
        if cmd[:1] != b"G":
            raise ValueError("only GET is a read-only command")
        return self.apply(0, cmd)

    def create_snapshot(self, last_idx: int, last_term: int) -> Snapshot:
        items = b"".join(b"%d:%s%d:%s" % (len(k), k, len(v), v)
                         for k, v in sorted(self.store.items()))
        return Snapshot(last_idx, last_term, items)

    def apply_snapshot(self, snap: Snapshot) -> None:
        self.store = {}
        # Full replace: per-key modification history before the
        # snapshot point is unknown — deltas can only build on bases at
        # or past it.  The rope is stale too.
        self._mod_idx = {}
        self._del_idx = {}
        self.delta_floor = snap.last_idx
        self._mutations += 1
        # Index-based parse, O(total): the old split-and-reslice loop
        # copied the remaining buffer per item — O(items x size), which
        # at a 100 MB image turned the receiver's install into minutes
        # of memcpy under its lock (peers then evicted it as dead).
        buf = snap.data
        off = 0
        end = len(buf)
        while off < end:
            j = buf.index(b":", off)
            klen = int(buf[off:j])
            k = buf[j + 1:j + 1 + klen]
            off = j + 1 + klen
            j = buf.index(b":", off)
            vlen = int(buf[off:j])
            v = buf[j + 1:j + 1 + vlen]
            off = j + 1 + vlen
            self.store[k] = v
        # A snapshot-primed replica never applies the covered M entries
        # — the migration tables ride the reserved key instead.
        self._mig_reload()
