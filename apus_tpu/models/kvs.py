"""Built-in key-value state machine (dare_kvs_sm.c analog).

The reference ships a chained-hash KVS with PUT/GET/RM
(dare_kvs_sm.c:157-202) used by DARE's native client path.  Ours speaks a
trivial length-prefixed command encoding and doubles as the demo app for
end-to-end tests when no real server binary (Redis etc.) is present.

Command wire format (ascii-ish, newline-free):
    b"P<klen>:<key><value>"  put
    b"G<klen>:<key>"         get (reply = value or empty)
    b"D<klen>:<key>"         delete

Typed replicated-data-type commands (PR 12; SafarDB's typed-op half —
mergeable counters and sets instead of opaque blobs, riding the same
log/snapshot/delta machinery because their state IS an ordinary store
value in a canonical encoding):
    b"C<klen>:<key><delta>"   counter add (delta = ascii signed int);
                              reply = the NEW value, ascii
    b"X<klen>:<key><value>"   getset; reply = the OLD value
    b"SA<klen>:<key><member>" set add; reply b"1" added / b"0" present
    b"SR<klen>:<key><member>" set remove; reply b"1" / b"0"
    b"SM<klen>:<key>"         set members; reply = canonical encoding

Transactions (PR 12; ``runtime/txn.py`` has the protocol walkthrough):
    b"TM..."  single-group MULTI batch — N sub-commands applied
              atomically at ONE log index (atomic visibility for free
              from log order); reply = packed per-sub replies
    b"TB/TP/TC/TA/TD/TF"  cross-group atomic-commit records (begin /
              prepare / commit / abort / decide / finish) — see the
              encoders below and runtime/txn.py
"""

from __future__ import annotations

import json
import struct

from apus_tpu.models.sm import REFUSED_REPLY_PREFIX, Snapshot, StateMachine


def encode_put(key: bytes, value: bytes) -> bytes:
    return b"P%d:%s%s" % (len(key), key, value)


def encode_get(key: bytes) -> bytes:
    return b"G%d:%s" % (len(key), key)


def encode_delete(key: bytes) -> bytes:
    return b"D%d:%s" % (len(key), key)


def encode_incr(key: bytes, delta: int = 1) -> bytes:
    return b"C%d:%s%d" % (len(key), key, delta)


def encode_getset(key: bytes, value: bytes) -> bytes:
    return b"X%d:%s%s" % (len(key), key, value)


def encode_sadd(key: bytes, member: bytes) -> bytes:
    return b"SA%d:%s%s" % (len(key), key, member)


def encode_srem(key: bytes, member: bytes) -> bytes:
    return b"SR%d:%s%s" % (len(key), key, member)


def encode_smembers(key: bytes) -> bytes:
    return b"SM%d:%s" % (len(key), key)


#: single-key command tags -> (header length, is_read, is_write)
_KEYED_TAGS = {b"P": (1, False, True), b"G": (1, True, False),
               b"D": (1, False, True), b"C": (1, False, True),
               b"X": (1, False, True), b"SA": (2, False, True),
               b"SR": (2, False, True), b"SM": (2, True, False)}


def _parse_keyed(cmd: bytes):
    """-> (tag, key, payload) for any single-key command, else None."""
    tag = cmd[:2] if cmd[:1] == b"S" else cmd[:1]
    info = _KEYED_TAGS.get(tag)
    if info is None:
        return None
    try:
        klen_s, rest = cmd[info[0]:].split(b":", 1)
        klen = int(klen_s)
        return tag, rest[:klen], rest[klen:]
    except (ValueError, IndexError):
        return None


def decode_key(cmd: bytes) -> "bytes | None":
    """Key of a single-key KVS command (P/G/D and the typed RDT ops),
    or None for any other payload (the elastic-group admission check
    routes on it; non-keyed payloads are never bucket-routed)."""
    p = _parse_keyed(cmd)
    return p[1] if p is not None else None


def decode_keys(cmd: bytes) -> "list[bytes] | None":
    """EVERY key a command touches: [key] for single-key commands, all
    sub-command keys for TM/TP transaction records (admission must
    check each), [] for keyless records (TB/TC/TA/TD/TF — reserved,
    never bucket-routed), None for non-KVS payloads."""
    if cmd[:2] in (b"TM", b"TP"):
        try:
            subs = (decode_txn_multi(cmd) if cmd[:2] == b"TM"
                    else decode_txn_prepare(cmd)[4])
        except (ValueError, IndexError, _struct_error):
            return None
        out = []
        for sub in subs:
            c = sub if isinstance(sub, bytes) else sub[1]
            k = decode_key(c)
            if k is None:
                return None
            out.append(k)
        return out
    if cmd[:1] == b"T":
        return []
    k = decode_key(cmd)
    return [k] if k is not None else None


def cmd_is_read(cmd: bytes) -> bool:
    """True for side-effect-free single-key commands (G, SM)."""
    tag = cmd[:2] if cmd[:1] == b"S" else cmd[:1]
    info = _KEYED_TAGS.get(tag)
    return info is not None and info[1]


# -- canonical set encoding (the set RDT's stored representation) ----------

SET_MAGIC = b"S!"


def set_decode(value: bytes) -> "set[bytes]":
    """Canonical stored value -> member set.  b"" (absent) and any
    non-set value decode as the empty set (set ops overwrite plain
    values deterministically; the checker uses this SAME function, so
    model and SM can never disagree)."""
    if not value.startswith(SET_MAGIC):
        return set()
    out = set()
    off = 2
    try:
        while off < len(value):
            (n,) = _U32.unpack_from(value, off)
            off += 4
            out.add(value[off:off + n])
            off += n
    except _struct_error:
        return set()
    return out


def set_encode(members) -> bytes:
    return SET_MAGIC + b"".join(_U32.pack(len(m)) + m
                                for m in sorted(members))


def eval_subop(view, cmd: bytes):
    """Pure single-key command semantics, shared by THREE consumers so
    they cannot drift: the SM apply path, the transaction prepare
    simulation (models the op against store + txn scratch), and the
    strict-serializability checker (models it against search state).

    ``view(key) -> bytes`` is the current value (b"" absent).  Returns
    ``(key, reply, write)`` with write None (read) or ("P", value) /
    ("D",) — the mutation to install if the command takes effect."""
    p = _parse_keyed(cmd)
    if p is None:
        raise ValueError(f"bad kvs op {cmd[:2]!r}")
    tag, key, payload = p
    if tag == b"P":
        return key, b"OK", ("P", payload)
    if tag == b"G":
        return key, view(key), None
    if tag == b"D":
        return key, b"OK", ("D",)
    if tag == b"C":
        cur = view(key)
        try:
            base = int(cur) if cur else 0
            delta = int(payload)
        except ValueError:
            return key, b"!notint", None
        new = b"%d" % (base + delta)
        return key, new, ("P", new)
    if tag == b"X":
        return key, view(key), ("P", payload)
    if tag == b"SA":
        s = set_decode(view(key))
        if payload in s:
            return key, b"0", None
        s.add(payload)
        return key, b"1", ("P", set_encode(s))
    if tag == b"SR":
        s = set_decode(view(key))
        if payload not in s:
            return key, b"0", None
        s.discard(payload)
        return key, b"1", ("P", set_encode(s))
    if tag == b"SM":
        return key, set_encode(set_decode(view(key))), None
    raise ValueError(f"bad kvs op {tag!r}")


# -- elastic-group migration commands (replicated in the groups' own
#    logs; see runtime/elastic.py for the protocol walkthrough) -----------
#
#   MB  (src log)  freeze a bucket set for migration ``mig_id`` to
#                  ``dst_gid`` at shard-map epoch ``epoch``; from its
#                  apply on, writes into those buckets deterministically
#                  no-op with a REFUSED sentinel, so the capture any
#                  later driver attempt takes is stable.
#   MI  (dst log)  install the captured pairs (idempotent by mig_id —
#                  a resumed driver may deliver it twice).
#   MC  (src log)  commit the migration: delete the moved keys, flip
#                  bucket ownership to dst, bump the shard-map epoch.
#
# State rides the RESERVED key below so it survives snapshot/delta
# catch-up exactly like ordinary keys (a replica primed by snapshot
# never re-applies the M entries themselves).

MIG_STATE_KEY = b"\x00apus.migs"
TXN_STATE_KEY = b"\x00apus.txns"
RESERVED_PREFIX = b"\x00apus."

REFUSED_FROZEN = REFUSED_REPLY_PREFIX + b"frozen"
REFUSED_DEPARTED = REFUSED_REPLY_PREFIX + b"departed"

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_struct_error = struct.error


def _enc_buckets(buckets) -> bytes:
    bs = sorted(set(buckets))
    return _U16.pack(len(bs)) + b"".join(_U16.pack(b) for b in bs)


def _dec_buckets(buf: bytes, off: int) -> "tuple[list[int], int]":
    (n,) = _U16.unpack_from(buf, off)
    off += 2
    out = [_U16.unpack_from(buf, off + 2 * i)[0] for i in range(n)]
    return out, off + 2 * n


def encode_mig_begin(mig_id: int, dst_gid: int, epoch: int,
                     buckets, cid_size: int = 0,
                     cid_mask: int = 0) -> bytes:
    """``cid_size``/``cid_mask`` are the DST group's genesis
    configuration (the src group's member set at split time), decided
    ONCE here and replicated with the record — every daemon creates
    the new group from the same bytes, so genesis cids can never
    diverge (locally-projected cids did, and same-epoch disagreement
    has no reconciliation path)."""
    return (b"MB" + struct.pack("<QBIBH", mig_id, dst_gid, epoch,
                                cid_size, cid_mask)
            + _enc_buckets(buckets))


def decode_mig_begin(cmd: bytes):
    """-> (mig_id, dst_gid, epoch, cid_size, cid_mask, buckets)."""
    mig_id, dst, epoch, size, mask = struct.unpack_from("<QBIBH",
                                                        cmd, 2)
    buckets, _ = _dec_buckets(cmd, 18)
    return mig_id, dst, epoch, size, mask, buckets


def encode_mig_install(mig_id: int, src_gid: int, epoch: int, buckets,
                       pairs) -> bytes:
    out = [b"MI", struct.pack("<QBI", mig_id, src_gid, epoch),
           _enc_buckets(buckets), struct.pack("<I", len(pairs))]
    for k, v in pairs:
        out.append(struct.pack("<I", len(k)) + k
                   + struct.pack("<I", len(v)) + v)
    return b"".join(out)


def encode_mig_commit(mig_id: int) -> bytes:
    return b"MC" + struct.pack("<Q", mig_id)


# -- transaction records (PR 12; runtime/txn.py drives the protocol) -------
#
# A transaction's identity is the ORIGINATING CLIENT's (clt_id,
# req_id) pair — 16 bytes on the wire, "clt.req" as the SM table key —
# so the coordinator group's ordinary endpoint-DB dedup gives the
# whole cross-group transaction exactly-once semantics: the TD record
# is submitted under the CLIENT's identity and its apply-time reply is
# epdb-noted like any single op's (abort outcomes return a REFUSED-
# prefixed sentinel, which the apply path never notes — a retried
# transaction re-enters fresh under a new req_id).

REFUSED_LOCKED = REFUSED_REPLY_PREFIX + b"locked"
#: prepare/decide refusals that must reach the txn DRIVER verbatim
#: (the client service passes them through as OK-status replies
#: instead of translating them into typed bounces)
REFUSED_TX = REFUSED_REPLY_PREFIX + b"tx:"
REFUSED_TX_ABORTED = REFUSED_TX + b"aborted"

#: transaction reply blobs lead with this tag (never collides with the
#: REFUSED prefix or a bare b"OK")
TXN_REPLY_MAGIC = b"TR"


def txn_key(clt_id: int, req_id: int) -> str:
    return "%d.%d" % (clt_id, req_id)


def parse_txn_key(tk: str) -> "tuple[int, int]":
    c, r = tk.split(".")
    return int(c), int(r)


def _enc_subs(subs) -> bytes:
    """[(pos, cmd)] -> packed bytes."""
    return _U16.pack(len(subs)) + b"".join(
        _U16.pack(p) + _U32.pack(len(c)) + c for p, c in subs)


def _dec_subs(buf: bytes, off: int):
    (n,) = _U16.unpack_from(buf, off)
    off += 2
    out = []
    for _ in range(n):
        (p,) = _U16.unpack_from(buf, off)
        (ln,) = _U32.unpack_from(buf, off + 2)
        off += 6
        out.append((p, buf[off:off + ln]))
        off += ln
    return out, off


def pack_replies(replies) -> bytes:
    """[(pos, reply_bytes)] -> the TR reply blob (position-keyed so the
    coordinator reassembles cross-group replies in client sub order)."""
    return TXN_REPLY_MAGIC + _enc_subs(sorted(replies))


def unpack_replies(blob: bytes) -> "list[tuple[int, bytes]]":
    if not blob.startswith(TXN_REPLY_MAGIC):
        raise ValueError(f"bad txn reply blob {blob[:2]!r}")
    out, _ = _dec_subs(blob, 2)
    return out


def encode_txn_multi(cmds) -> bytes:
    """Single-group MULTI/EXEC batch: sub-commands applied atomically
    at one log index."""
    return b"TM" + _enc_subs(list(enumerate(cmds)))


def decode_txn_multi(cmd: bytes) -> "list[bytes]":
    subs, _ = _dec_subs(cmd, 2)
    return [c for _p, c in sorted(subs)]


_TXNID = struct.Struct("<QQ")


def _enc_txnid(clt_id: int, req_id: int) -> bytes:
    return _TXNID.pack(clt_id, req_id)


def encode_txn_begin(clt_id: int, req_id: int, epoch: int,
                     groups: "dict[int, list]") -> bytes:
    """TB (coordinator group's log): the durable 2PC intent record —
    replicated BEFORE any prepare is sent, so whoever comes to lead
    the coordinator group can resume/decide the transaction.
    ``groups``: gid -> [(pos, subcmd)]."""
    out = [b"TB", _enc_txnid(clt_id, req_id), _U32.pack(epoch),
           struct.pack("<B", len(groups))]
    for gid in sorted(groups):
        out.append(struct.pack("<B", gid) + _enc_subs(groups[gid]))
    return b"".join(out)


def decode_txn_begin(cmd: bytes):
    """-> (clt_id, req_id, epoch, {gid: [(pos, subcmd)]})."""
    clt, req = _TXNID.unpack_from(cmd, 2)
    (epoch,) = _U32.unpack_from(cmd, 18)
    ngroups = cmd[22]
    off = 23
    groups = {}
    for _ in range(ngroups):
        gid = cmd[off]
        subs, off = _dec_subs(cmd, off + 1)
        groups[gid] = subs
    return clt, req, epoch, groups


def encode_txn_prepare(clt_id: int, req_id: int, coord_gid: int,
                       epoch: int, subs) -> bytes:
    """TP (participant group's log): lock this group's keys, evaluate
    the sub-ops against the locked state (replies + buffered writes
    recorded, so the later TC is a pure install), survive leader kills
    by living in the group's own log."""
    return (b"TP" + _enc_txnid(clt_id, req_id)
            + struct.pack("<BI", coord_gid, epoch) + _enc_subs(subs))


def decode_txn_prepare(cmd: bytes):
    """-> (clt_id, req_id, coord_gid, epoch, [(pos, subcmd)])."""
    clt, req = _TXNID.unpack_from(cmd, 2)
    coord, epoch = struct.unpack_from("<BI", cmd, 18)
    subs, _ = _dec_subs(cmd, 23)
    return clt, req, coord, epoch, subs


def encode_txn_commit(clt_id: int, req_id: int) -> bytes:
    return b"TC" + _enc_txnid(clt_id, req_id)


def encode_txn_abort(clt_id: int, req_id: int) -> bytes:
    return b"TA" + _enc_txnid(clt_id, req_id)


def encode_txn_finish(clt_id: int, req_id: int) -> bytes:
    return b"TF" + _enc_txnid(clt_id, req_id)


def encode_txn_decide(clt_id: int, req_id: int, commit: bool,
                      reply: bytes = b"") -> bytes:
    """TD (coordinator group's log): THE single decision point.  The
    first TD for a transaction in the coordinator log's order wins on
    every replica; it is submitted under the CLIENT's identity so a
    commit's apply-time reply lands in the endpoint DB (exactly-once
    for the whole transaction), while an abort returns a REFUSED
    sentinel that is never noted."""
    return (b"TD" + _enc_txnid(clt_id, req_id)
            + struct.pack("<B", 1 if commit else 0)
            + struct.pack("<I", len(reply)) + reply)


def decode_txn_decide(cmd: bytes):
    clt, req = _TXNID.unpack_from(cmd, 2)
    commit = cmd[18] != 0
    (ln,) = _U32.unpack_from(cmd, 19)
    return clt, req, commit, cmd[23:23 + ln]


def _dec_txnid(cmd: bytes) -> "tuple[int, int]":
    return _TXNID.unpack_from(cmd, 2)


# writes_blob codec: the buffered mutations a prepared txn installs at
# commit — [(key, ("P", value) | ("D",))] packed.

def _enc_writes(writes) -> bytes:
    out = [_U16.pack(len(writes))]
    for key, w in writes:
        kind = w[0].encode()
        val = w[1] if len(w) > 1 else b""
        out.append(_U32.pack(len(key)) + key + kind
                   + _U32.pack(len(val)) + val)
    return b"".join(out)


def _dec_writes(buf: bytes):
    (n,) = _U16.unpack_from(buf, 0)
    off = 2
    out = []
    for _ in range(n):
        (klen,) = _U32.unpack_from(buf, off)
        off += 4
        key = buf[off:off + klen]
        off += klen
        kind = buf[off:off + 1].decode()
        (vlen,) = _U32.unpack_from(buf, off + 1)
        off += 5
        val = buf[off:off + vlen]
        off += vlen
        out.append((key, ("P", val) if kind == "P" else ("D",)))
    return out


class KvsStateMachine(StateMachine):
    def __init__(self) -> None:
        self.store: dict[bytes, bytes] = {}
        # Delta-snapshot bookkeeping (models.sm delta contract): the
        # log index that last MODIFIED each key (puts and deletes), so
        # ``delta_since(base)`` can ship only the keys touched after a
        # rejoiner's applied determinant.  ``delta_floor`` is the
        # earliest base the history covers: 0 from a fresh boot (we
        # saw every apply), bumped to the snapshot point on a full
        # install (per-key history before it is unknown).
        self._mod_idx: dict[bytes, int] = {}
        self._del_idx: dict[bytes, int] = {}
        self.delta_floor = 0
        # Streamable snapshot "rope" (snapshot_stream_size /
        # read_snapshot_chunk): the snapshot image as a FROZEN list of
        # byte frames REFERENCING the live value objects — capture is
        # O(#keys) with zero value copies, so a 100 MB snapshot never
        # materializes (the b"".join under the node lock stalled
        # heartbeats for hundreds of ms at that scale and deposed the
        # leader on every capture).  ``dump_generation`` bumps on every
        # rebuild; ``pin_dump_reader`` hands out a reader over the
        # frozen rope for off-tick streams and compaction.
        self._mutations = 0
        self.dump_generation = 0
        self._rope = None          # (frames, starts, total, mutations)
        # Elastic-group migration bookkeeping (mirrored into the
        # reserved MIG_STATE_KEY so it rides snapshots and deltas like
        # any other key; _mig_reload rebuilds these after an install).
        # migs_out: mig_id(str) -> [dst_gid, epoch, state, buckets]
        #   with state "frozen" -> "committed"; migs_in: mig_id(str) ->
        #   [src_gid, epoch, buckets] (install dedup).
        self.migs_out: dict[str, list] = {}
        self.migs_in: dict[str, list] = {}
        self._frozen: set[int] = set()
        self._departed: dict[int, tuple[int, int]] = {}
        # Transaction bookkeeping (PR 12; mirrored into TXN_STATE_KEY
        # so it survives snapshot/delta catch-up AND restart replay).
        # txns_in: txn_key -> [coord_gid, epoch, state, subs_s,
        #   replies_s, writes_s, last_idx] with state
        #   "prepared" -> "done" | "aborted" (latin-1 strings — the
        #   JSON mirror roundtrips bytes losslessly).
        # txns_coord: txn_key -> [state, epoch, groups_s, reply_s,
        #   last_idx] with state "open" -> "committed"|"aborted" ->
        #   "done".
        # _locks (derived): key -> (txn_key, "r"|"w") for every key a
        #   PREPARED txn touches — exclusive 2PL; write-locked keys
        #   refuse reads too (a committed-but-uninstalled write must
        #   never be read around), read-locked keys serve reads.
        self.txns_in: dict[str, list] = {}
        self.txns_coord: dict[str, list] = {}
        self._locks: dict[bytes, tuple] = {}

    # -- internal mutation helpers (delta bookkeeping in one place) --------

    def _put_internal(self, idx: int, key: bytes, value: bytes) -> None:
        self.store[key] = value
        self._mutations += 1
        if idx:
            self._mod_idx[key] = idx
            self._del_idx.pop(key, None)

    def _del_internal(self, idx: int, key: bytes) -> None:
        self.store.pop(key, None)
        self._mutations += 1
        if idx:
            self._mod_idx.pop(key, None)
            self._del_idx[key] = idx

    def apply(self, idx: int, cmd: bytes) -> bytes | None:
        op = cmd[:1]
        if op == b"M":
            return self._apply_mig(idx, cmd)
        if op == b"T":
            return self._apply_txn(idx, cmd)
        p = _parse_keyed(cmd)
        if p is None:
            raise ValueError(f"bad kvs op {cmd[:2]!r}")
        _tag, key, _payload = p
        is_read = cmd_is_read(cmd)
        if not key.startswith(RESERVED_PREFIX):
            # Elastic-group fence: a decided write into a FROZEN bucket
            # (migration capture in flight) or a DEPARTED one (already
            # owned by another group) deterministically no-ops with a
            # REFUSED sentinel on every replica — admission refuses
            # these up front; only entries that raced a leader change
            # past an unapplied MB/MC reach here.  The refusal is never
            # dedup-cached (see sm.REFUSED_REPLY_PREFIX), so the
            # client's re-routed retry executes exactly once at the
            # owner.
            if not is_read and (self._frozen or self._departed):
                from apus_tpu.runtime.router import bucket_of_key
                b = bucket_of_key(key)
                if b in self._departed:
                    return REFUSED_DEPARTED
                if b in self._frozen:
                    return REFUSED_FROZEN
            # Transaction lock fence (exclusive 2PL): writes refuse on
            # ANY lock; reads refuse only on WRITE locks (a prepared
            # txn's buffered write must not be read around — between
            # the coordinator's decided-commit and the participant's
            # TC apply, the old value is a stale read).
            if self._locks:
                lk = self._locks.get(key)
                if lk is not None and (not is_read or lk[1] == "w"):
                    return REFUSED_LOCKED
        key2, reply, write = eval_subop(
            lambda k: self.store.get(k, b""), cmd)
        if write is not None:
            if write[0] == "P":
                self._put_internal(idx, key2, write[1])
            else:
                self._del_internal(idx, key2)
        return reply

    # -- elastic-group migration ops ---------------------------------------

    def _apply_mig(self, idx: int, cmd: bytes) -> bytes:
        from apus_tpu.runtime.router import bucket_of_key
        sub = cmd[1:2]
        if sub == b"B":
            mig_id, dst, epoch, size, mask, buckets = \
                decode_mig_begin(cmd)
            if self._locks:
                # A WRITE-locked key (open prepared transaction) in the
                # requested bucket set defers the freeze: the txn's
                # buffered writes must land HERE before the capture, or
                # the migration would ship a value the committed txn
                # then overwrites only at src (lost update at dst).
                # Deterministic REFUSED — the elastic driver retries
                # the split after the txn resolves.  Read locks don't
                # defer: a migration moves the value unchanged.
                bset = set(buckets)
                from apus_tpu.runtime.router import bucket_of_key
                for k, lk in self._locks.items():
                    if lk[1] == "w" and bucket_of_key(k) in bset:
                        return REFUSED_LOCKED
            if str(mig_id) not in self.migs_out:
                self.migs_out[str(mig_id)] = [dst, epoch, "frozen",
                                              buckets, size, mask]
                self._mig_commit_state(idx)
            return b"OK"
        if sub == b"I":
            mig_id, src, epoch = struct.unpack_from("<QBI", cmd, 2)
            buckets, off = _dec_buckets(cmd, 15)
            if str(mig_id) in self.migs_in:
                return b"OK"                  # resumed-driver duplicate
            (npairs,) = struct.unpack_from("<I", cmd, off)
            off += 4
            # Replace bucket contents (exact even if an aborted earlier
            # attempt of a DIFFERENT mig left strays): delete, then
            # install the frozen capture.
            bset = set(buckets)
            for k in [k for k in self.store
                      if not k.startswith(RESERVED_PREFIX)
                      and bucket_of_key(k) in bset]:
                self._del_internal(idx, k)
            for _ in range(npairs):
                (klen,) = struct.unpack_from("<I", cmd, off)
                off += 4
                k = cmd[off:off + klen]
                off += klen
                (vlen,) = struct.unpack_from("<I", cmd, off)
                off += 4
                self._put_internal(idx, k, cmd[off:off + vlen])
                off += vlen
            self.migs_in[str(mig_id)] = [src, epoch, buckets]
            self._mig_commit_state(idx)
            return b"OK"
        if sub == b"C":
            (mig_id,) = struct.unpack_from("<Q", cmd, 2)
            rec = self.migs_out.get(str(mig_id))
            if rec is None:
                return b"NOMIG"
            if rec[2] != "committed":
                bset = set(rec[3])
                for k in [k for k in self.store
                          if not k.startswith(RESERVED_PREFIX)
                          and bucket_of_key(k) in bset]:
                    self._del_internal(idx, k)
                rec[2] = "committed"
                self._mig_commit_state(idx)
            return b"OK"
        raise ValueError(f"bad kvs migration op {cmd[:2]!r}")

    def _mig_rederive(self) -> None:
        """Per-bucket fence from the migration event history.  A bucket
        is DEPARTED only while its latest event is an OUTBOUND commit —
        a later inbound install (the bucket returned, e.g. split then
        merged back) clears the fence; epochs strictly increase along a
        bucket's ownership chain, so the max-epoch event decides."""
        self._frozen = set()
        self._departed = {}
        out_ev: dict[int, tuple[int, int]] = {}
        in_ev: dict[int, int] = {}
        for rec in self.migs_out.values():
            dst, epoch, state, buckets = rec[:4]
            if state == "frozen":
                self._frozen.update(buckets)
            elif state == "committed":
                for b in buckets:
                    if epoch > out_ev.get(b, (0, -1))[1]:
                        out_ev[b] = (dst, epoch)
        for rec in self.migs_in.values():
            src, epoch = rec[0], rec[1]
            for b in (rec[2] if len(rec) > 2 else []):
                in_ev[b] = max(in_ev.get(b, -1), epoch)
        for b, (dst, epoch) in out_ev.items():
            if epoch > in_ev.get(b, -1):
                self._departed[b] = (dst, epoch)

    def _mig_commit_state(self, idx: int) -> None:
        """Re-derive the bucket fences and mirror the migration tables
        into the reserved key (deterministic bytes: sorted keys), so
        they survive snapshot/delta catch-up like ordinary state."""
        self._mig_rederive()
        blob = json.dumps({"out": self.migs_out, "in": self.migs_in},
                          sort_keys=True,
                          separators=(",", ":")).encode()
        self._put_internal(idx, MIG_STATE_KEY, blob)

    def _mig_reload(self) -> None:
        """Rebuild the in-memory migration tables from the reserved key
        after a snapshot/delta install replaced or merged state."""
        blob = self.store.get(MIG_STATE_KEY)
        if not blob:
            if self.migs_out or self.migs_in:
                self.migs_out, self.migs_in = {}, {}
                self._frozen, self._departed = set(), {}
            return
        st = json.loads(blob.decode())
        self.migs_out = {k: list(v) for k, v in st.get("out",
                                                       {}).items()}
        self.migs_in = {k: list(v) for k, v in st.get("in", {}).items()}
        self._mig_rederive()

    # -- transactions (PR 12; runtime/txn.py drives the protocol) ----------

    #: completed-transaction tombstones retained for late-duplicate
    #: idempotence (a TP/TC/TA from an abandoned earlier driver attempt
    #: may commit after the txn resolved); beyond this, oldest pruned.
    TXN_TOMBSTONES = 128

    def _view_with(self, scratch: dict):
        """Store view overlaid with a txn's in-flight scratch writes —
        sub-op i observes sub-ops < i of the same transaction."""
        def view(k: bytes) -> bytes:
            if k in scratch:
                w = scratch[k]
                return w[1] if w[0] == "P" else b""
            return self.store.get(k, b"")
        return view

    def _simulate_subs(self, subs):
        """Evaluate [(pos, cmd)] in position order against store +
        scratch.  -> (replies [(pos, bytes)], writes [(key, w)])."""
        scratch: dict[bytes, tuple] = {}
        view = self._view_with(scratch)
        replies = []
        for pos, c in sorted(subs):
            key, reply, write = eval_subop(view, c)
            replies.append((pos, reply))
            if write is not None:
                scratch[key] = write
        return replies, list(scratch.items())

    def _txn_fence(self, subs, tk: "str | None" = None):
        """Deterministic admission fence for a txn's key set: departed
        / frozen (elastic) and lock conflicts (other open txns).
        Returns None (clear) or the REFUSED reason tag bytes."""
        from apus_tpu.runtime.router import bucket_of_key
        for _pos, c in subs:
            key = decode_key(c)
            if key is None or key.startswith(RESERVED_PREFIX):
                continue
            if self._frozen or self._departed:
                b = bucket_of_key(key)
                if b in self._departed:
                    return b"departed"
                if not cmd_is_read(c) and b in self._frozen:
                    return b"frozen"
            lk = self._locks.get(key)
            if lk is not None and (tk is None or lk[0] != tk):
                return b"locked"
        return None

    def _apply_txn(self, idx: int, cmd: bytes) -> bytes:
        sub = cmd[1:2]
        if sub == b"M":
            return self._apply_txn_multi(idx, cmd)
        if sub == b"P":
            return self._apply_txn_prepare(idx, cmd)
        if sub == b"C":
            return self._apply_txn_close(idx, cmd, commit=True)
        if sub == b"A":
            return self._apply_txn_close(idx, cmd, commit=False)
        if sub == b"B":
            return self._apply_txn_begin(idx, cmd)
        if sub == b"D":
            return self._apply_txn_decide(idx, cmd)
        if sub == b"F":
            return self._apply_txn_finish(idx, cmd)
        raise ValueError(f"bad kvs txn op {cmd[:2]!r}")

    def _apply_txn_multi(self, idx: int, cmd: bytes) -> bytes:
        """TM: within-group atomic batch — ONE log entry, sub-ops
        evaluated in order (later subs observe earlier ones), all
        mutations installed at this index.  Atomic visibility is free
        from log order; the whole batch refuses deterministically when
        any key is fenced (frozen/departed/locked), so the client's
        retry re-enters admission fresh, exactly-once intact."""
        subs = list(enumerate(decode_txn_multi(cmd)))
        why = self._txn_fence(subs)
        if why == b"departed":
            return REFUSED_DEPARTED
        if why == b"frozen":
            return REFUSED_FROZEN
        if why is not None:
            return REFUSED_LOCKED
        replies, writes = self._simulate_subs(subs)
        for key, w in writes:
            if w[0] == "P":
                self._put_internal(idx, key, w[1])
            else:
                self._del_internal(idx, key)
        return pack_replies(replies)

    def _apply_txn_prepare(self, idx: int, cmd: bytes) -> bytes:
        """TP: lock the keys, evaluate the sub-ops against the locked
        state (replies AND final writes recorded — TC is then a pure
        install, so the value a prepare computed is exactly the value
        commit publishes), all replicated in THIS group's log so a
        leader kill moves the prepared state with the leadership.
        Idempotent by txn id; refusals are REFUSED_TX-prefixed
        (epdb-note skipped, passed through to the driver verbatim)."""
        clt, req, coord, epoch, subs = decode_txn_prepare(cmd)
        tk = txn_key(clt, req)
        rec = self.txns_in.get(tk)
        if rec is not None:
            if rec[2] in ("prepared", "done"):
                return rec[4].encode("latin-1")   # stored TR replies
            return REFUSED_TX_ABORTED             # aborted tombstone
        why = self._txn_fence(subs, tk=tk)
        if why is not None:
            return REFUSED_TX + why
        replies, writes = self._simulate_subs(subs)
        reply_blob = pack_replies(replies)
        self.txns_in[tk] = [
            coord, epoch, "prepared",
            _enc_subs(subs).decode("latin-1"),
            reply_blob.decode("latin-1"),
            _enc_writes(writes).decode("latin-1"), idx]
        self._txn_commit_state(idx)
        return reply_blob

    def _apply_txn_close(self, idx: int, cmd: bytes,
                         commit: bool) -> bytes:
        """TC/TA: resolve a prepared transaction — install the buffered
        writes (commit) or drop them (abort), release the locks either
        way.  A TA for an UNKNOWN txn records an aborted tombstone so a
        straggler TP from an abandoned driver attempt can never lock
        keys after the decision (the tombstone refuses it)."""
        clt, req = _dec_txnid(cmd)
        tk = txn_key(clt, req)
        rec = self.txns_in.get(tk)
        if rec is None:
            if not commit:
                self.txns_in[tk] = [0, 0, "aborted", "", "", "", idx]
                self._txn_commit_state(idx)
            return b"OK"
        if rec[2] != "prepared":
            return b"OK"                          # duplicate close
        if commit:
            for key, w in _dec_writes(rec[5].encode("latin-1")):
                if w[0] == "P":
                    self._put_internal(idx, key, w[1])
                else:
                    self._del_internal(idx, key)
            rec[2] = "done"
        else:
            rec[2] = "aborted"
        rec[5] = ""                               # writes installed/dropped
        rec[6] = idx
        self._txn_commit_state(idx)
        return b"OK"

    def _apply_txn_begin(self, idx: int, cmd: bytes) -> bytes:
        clt, req, epoch, groups = decode_txn_begin(cmd)
        tk = txn_key(clt, req)
        if tk not in self.txns_coord:
            groups_s = json.dumps(
                {str(g): _enc_subs(s).decode("latin-1")
                 for g, s in groups.items()}, sort_keys=True)
            self.txns_coord[tk] = ["open", epoch, groups_s, None, idx]
            self._txn_commit_state(idx)
        return b"OK"

    def _apply_txn_decide(self, idx: int, cmd: bytes) -> bytes:
        """TD: the decision point.  First TD in this group's log order
        wins on every replica; its reply is what the apply path
        epdb-notes under the CLIENT's identity (commit) or skips
        (abort — REFUSED sentinel)."""
        clt, req, commit, reply = decode_txn_decide(cmd)
        tk = txn_key(clt, req)
        rec = self.txns_coord.get(tk)
        if rec is None:
            rec = self.txns_coord[tk] = ["open", 0, "{}", None, idx]
        if rec[0] == "open":
            rec[0] = "committed" if commit else "aborted"
            rec[3] = reply.decode("latin-1") if commit else None
            rec[4] = idx
            self._txn_commit_state(idx)
        if rec[0] in ("committed", "done") and rec[3] is not None:
            return rec[3].encode("latin-1")
        return REFUSED_TX_ABORTED

    def _apply_txn_finish(self, idx: int, cmd: bytes) -> bytes:
        """TF: every participant acked its TC/TA — stop re-driving."""
        clt, req = _dec_txnid(cmd)
        rec = self.txns_coord.get(txn_key(clt, req))
        if rec is not None and rec[0] in ("committed", "aborted"):
            rec[0] = "done"
            rec[4] = idx
            self._txn_commit_state(idx)
        return b"OK"

    def _txn_rederive(self) -> None:
        """Lock table from the open-prepared transactions."""
        self._locks = {}
        for tk, rec in self.txns_in.items():
            if rec[2] != "prepared":
                continue
            try:
                subs, _ = _dec_subs(rec[3].encode("latin-1"), 0)
            except (ValueError, IndexError, _struct_error):
                continue
            for _pos, c in subs:
                k = decode_key(c)
                if k is None:
                    continue
                kind = "r" if cmd_is_read(c) else "w"
                prev = self._locks.get(k)
                if prev is None or kind == "w":
                    self._locks[k] = (tk, kind)

    def _txn_prune(self) -> None:
        """Bound the completed-txn tombstone tables (oldest-resolved
        first, by completion index)."""
        for table, done_states in ((self.txns_in, ("done", "aborted")),
                                   (self.txns_coord, ("done",))):
            done = [(rec[-1], tk) for tk, rec in table.items()
                    if rec[2 if table is self.txns_in else 0]
                    in done_states]
            if len(done) > self.TXN_TOMBSTONES:
                done.sort()
                for _i, tk in done[:len(done) - self.TXN_TOMBSTONES]:
                    table.pop(tk, None)

    def _txn_commit_state(self, idx: int) -> None:
        """Re-derive locks and mirror the txn tables into the reserved
        key (deterministic bytes), so they survive snapshot/delta
        catch-up and restart replay like ordinary state."""
        self._txn_prune()
        self._txn_rederive()
        blob = json.dumps({"in": self.txns_in,
                           "coord": self.txns_coord},
                          sort_keys=True,
                          separators=(",", ":")).encode()
        self._put_internal(idx, TXN_STATE_KEY, blob)

    def _txn_reload(self) -> None:
        """Rebuild the in-memory txn tables from the reserved key after
        a snapshot/delta install replaced or merged state."""
        blob = self.store.get(TXN_STATE_KEY)
        if not blob:
            if self.txns_in or self.txns_coord:
                self.txns_in, self.txns_coord = {}, {}
                self._locks = {}
            return
        st = json.loads(blob.decode())
        self.txns_in = {k: list(v) for k, v in st.get("in",
                                                      {}).items()}
        self.txns_coord = {k: list(v)
                           for k, v in st.get("coord", {}).items()}
        self._txn_rederive()

    # -- streamable snapshot rope (zero-copy capture) ----------------------

    def _build_rope(self) -> tuple:
        """Byte-identical to ``create_snapshot().data`` as a frame
        list: per item ``<klen>:<key><vlen>:<value>`` with the VALUE
        frames aliasing the live (immutable) bytes objects.  Frozen
        once built — later mutations replace the rope, never edit it."""
        frames: list[bytes] = []
        starts: list[int] = []
        total = 0
        for k, v in sorted(self.store.items()):
            for f in (b"%d:%s%d:" % (len(k), k, len(v)), v):
                frames.append(f)
                starts.append(total)
                total += len(f)
        return frames, starts, total, self._mutations

    def _fresh_rope(self) -> tuple:
        if self._rope is None or self._rope[3] != self._mutations:
            self._rope = self._build_rope()
            self.dump_generation += 1
        return self._rope

    def snapshot_stream_size(self) -> int:
        """Chunked-stream capture hook (see core.node
        make_snapshot_stream_meta): the image size at the current
        apply point.  Called under the node lock, like every apply —
        the rope the size refers to is frozen at this moment."""
        return self._fresh_rope()[2]

    @staticmethod
    def _rope_read(rope: tuple, off: int, n: int) -> bytes:
        import bisect
        frames, starts, total, _ = rope
        if off >= total:
            return b""
        n = min(n, total - off)
        i = bisect.bisect_right(starts, off) - 1
        out = []
        got = 0
        while got < n and i < len(frames):
            f = frames[i]
            lo = off + got - starts[i]
            take = f[lo:lo + (n - got)]
            out.append(take)
            got += len(take)
            i += 1
        return b"".join(out)

    def read_snapshot_chunk(self, off: int, n: int) -> bytes:
        # Serve the EXISTING rope, never rebuild here: a rebuild would
        # bump the generation AFTER the caller's fence check passed and
        # hand it bytes of a different capture (torn stream).  The
        # generation fence upstream aborts streams whose rope was
        # replaced by a later capture.
        rope = self._rope if self._rope is not None \
            else self._fresh_rope()
        return self._rope_read(rope, off, n)

    def pin_dump_reader(self):
        """Reader over the CURRENT frozen rope, immune to later
        rebuilds — the off-tick stream/compaction pin (the fd-dup
        analog of dump-file SMs).  Pins the EXISTING rope (no rebuild:
        the caller just generation-checked it against its capture —
        rebuilding here would pin a newer image than the captured
        metadata)."""
        rope = self._rope if self._rope is not None \
            else self._fresh_rope()
        return lambda off, n: self._rope_read(rope, off, n)

    # -- delta snapshots (models.sm contract) ------------------------------

    def delta_since(self, base_idx: int) -> bytes | None:
        """Keys modified after ``base_idx``, as ``u8 kind | key blob
        [| value blob]`` records (kind P=put, D=delete), or None when
        the base predates our tracked history."""
        import struct
        if base_idx < self.delta_floor:
            return None
        out = []
        for k, i in self._mod_idx.items():
            if i > base_idx:
                v = self.store[k]
                out.append(b"P" + struct.pack("<I", len(k)) + k
                           + struct.pack("<I", len(v)) + v)
        for k, i in self._del_idx.items():
            if i > base_idx:
                out.append(b"D" + struct.pack("<I", len(k)) + k)
        return b"".join(out)

    def apply_snapshot_delta(self, snap: Snapshot) -> None:
        """Merge a delta produced by ``delta_since`` into the live
        store (the receiver half; base-determinant equality is checked
        by the caller, Node.install_snapshot)."""
        import struct
        self._mutations += 1
        buf = snap.data
        off = 0
        while off < len(buf):
            kind = buf[off:off + 1]
            off += 1
            (klen,) = struct.unpack_from("<I", buf, off)
            off += 4
            k = buf[off:off + klen]
            off += klen
            if kind == b"P":
                (vlen,) = struct.unpack_from("<I", buf, off)
                off += 4
                self.store[k] = buf[off:off + vlen]
                off += vlen
                self._mod_idx[k] = snap.last_idx
                self._del_idx.pop(k, None)
            elif kind == b"D":
                self.store.pop(k, None)
                self._mod_idx.pop(k, None)
                self._del_idx[k] = snap.last_idx
            else:
                raise ValueError(f"bad delta record kind {kind!r}")
        # Stamping merged keys at snap.last_idx is conservative-exact:
        # their true modification indices lie in (base, last_idx], so
        # any later delta_since(b >= delta_floor) still includes every
        # key modified after b (at worst a few extra).  The floor is
        # unchanged — history below it was already unknown.
        self._mig_reload()
        self._txn_reload()

    def query(self, cmd: bytes) -> bytes | None:
        """Read without logging (linearizable-read path): GET and
        SMEMBERS are side-effect-free, so they share apply's
        decode+lookup — including the txn WRITE-lock fence (a locked
        key's read refuses with the REFUSED sentinel; the client
        service bounces it as a transient retry)."""
        if not cmd_is_read(cmd):
            raise ValueError("only GET/SMEMBERS are read-only commands")
        return self.apply(0, cmd)

    def create_snapshot(self, last_idx: int, last_term: int) -> Snapshot:
        items = b"".join(b"%d:%s%d:%s" % (len(k), k, len(v), v)
                         for k, v in sorted(self.store.items()))
        return Snapshot(last_idx, last_term, items)

    def apply_snapshot(self, snap: Snapshot) -> None:
        self.store = {}
        # Full replace: per-key modification history before the
        # snapshot point is unknown — deltas can only build on bases at
        # or past it.  The rope is stale too.
        self._mod_idx = {}
        self._del_idx = {}
        self.delta_floor = snap.last_idx
        self._mutations += 1
        # Index-based parse, O(total): the old split-and-reslice loop
        # copied the remaining buffer per item — O(items x size), which
        # at a 100 MB image turned the receiver's install into minutes
        # of memcpy under its lock (peers then evicted it as dead).
        buf = snap.data
        off = 0
        end = len(buf)
        while off < end:
            j = buf.index(b":", off)
            klen = int(buf[off:j])
            k = buf[j + 1:j + 1 + klen]
            off = j + 1 + klen
            j = buf.index(b":", off)
            vlen = int(buf[off:j])
            v = buf[j + 1:j + 1 + vlen]
            off = j + 1 + vlen
            self.store[k] = v
        # A snapshot-primed replica never applies the covered M/T
        # entries — the migration and txn tables ride reserved keys.
        self._mig_reload()
        self._txn_reload()
