"""StateMachine interface (dare_sm_t vtable analog, dare_sm.h:49-60)."""

from __future__ import annotations

import dataclasses

#: Deterministic REFUSED-apply reply marker (elastic groups): an SM
#: whose apply deterministically REFUSES a decided command (a write
#: into a frozen/departed migration bucket — every replica no-ops it
#: identically) returns a reply with this prefix.  The apply path then
#: SKIPS the endpoint-DB dedup note for the entry (core/node.py and
#: the restart replay, runtime/persist.py): the op never took effect,
#: so a retry must re-enter admission fresh — caching the refusal
#: would wedge the client's re-routed attempt behind the dedup, and
#: letting a LATER req_id's cached reply answer it is the exact
#: monotone-dedup hazard the prefix exists to avoid.  The client
#: service translates the sentinel into a typed bounce
#: (MIGRATING / WRONG_GROUP), never into an OK reply.
REFUSED_REPLY_PREFIX = b"\x00!"


@dataclasses.dataclass
class Snapshot:
    """SM snapshot (snapshot_t analog, dare_log.h:107-112): the state
    blob plus the determinant of the last applied entry.

    ``seg`` carries the partially-reassembled chunk groups at the
    snapshot point (core.segment.Reassembler.dump): the buffer is a
    deterministic function of the applied prefix, so it travels WITH
    the prefix — an installer can then complete a group whose early
    chunks lie below the snapshot and whose final applies above it."""

    last_idx: int
    last_term: int
    data: bytes
    seg: bytes = b""
    #: Removed-slot fence table at the snapshot point (JSON
    #: ``{slot: last-removal-epoch}``; core.node incarnation fencing).
    #: Derived from the CONFIG entries inside the covered prefix — the
    #: installer never applies those, so the fence must travel with the
    #: snapshot or a freshly-primed member would accept ctrl writes
    #: from a stale ex-occupant of a removed-then-reused slot.
    fence: bytes = b""
    #: LOCAL-ONLY fields for file-backed installs (never wire-encoded —
    #: wire.encode_value serializes the five fields above only).  A
    #: streamed install sets ``data_path``/``data_len``/``data_gen`` so
    #: downstream consumers (persistence) can stream the immutable
    #: [0, data_len) prefix of that file instead of a blob that was
    #: never materialized; ``data_gen`` is the SM dump generation at
    #: install time — a later install replaces the file, and consumers
    #: must skip a stale capture (its successor's record covers).
    data_path: str | None = None
    data_len: int = 0
    data_gen: int = 0
    #: LOCAL-ONLY delta marker: when set, ``data`` is a state DELTA on
    #: top of this (idx, term) applied determinant, not a full image —
    #: persistence must record it as a delta record (replayed via
    #: ``apply_snapshot_delta``), never as a full snapshot record.
    delta_base: "tuple[int, int] | None" = None


class StateMachine:
    """Commands are opaque bytes; ``apply`` may return a reply blob."""

    def apply(self, idx: int, cmd: bytes) -> bytes | None:
        raise NotImplementedError

    def query(self, cmd: bytes) -> bytes | None:
        """Read-only command, never logged — the linearizable-read path
        (ud_clt_answer_read_request analog, dare_ibv_ud.c:1424-1449).
        Default: not supported."""
        raise NotImplementedError(f"{type(self).__name__} has no query path")

    def create_snapshot(self, last_idx: int, last_term: int) -> Snapshot:
        raise NotImplementedError

    def apply_snapshot(self, snap: Snapshot) -> None:
        raise NotImplementedError

    # -- delta snapshots (large-state recovery plane) ---------------------
    #
    # A rejoining member that presents its last applied (idx, term)
    # can be primed with only the STATE DELTA past that point instead
    # of the full image, when the SM's tracked history permits.
    # Contract: ``delta_since(base_idx)`` returns an opaque delta blob
    # covering (base_idx, current apply point], or None when base_idx
    # predates ``delta_floor`` (history not tracked that far back —
    # the caller falls back to a full push).
    # ``apply_snapshot_delta(snap)`` merges such a blob into live
    # state; the base-determinant equality check is the CALLER's job
    # (Node.install_snapshot) — two committed prefixes at the same
    # determinant are identical, so merge-on-match is exact.

    #: Earliest base index ``delta_since`` can serve (the compaction
    #: floor of the SM's tracked modification history).
    delta_floor: int = 0

    def delta_since(self, base_idx: int) -> bytes | None:
        """Default: no delta support — always a full push."""
        return None

    def apply_snapshot_delta(self, snap: Snapshot) -> None:
        raise NotImplementedError(
            f"{type(self).__name__} has no delta-install path")

    def apply_snapshot_file(self, snap: Snapshot, path: str,
                            adopt: bool = False) -> str | None:
        """Install a snapshot whose data lives in a FILE (the receiver
        half of the chunked snapshot stream; the reference installs
        from its disk-backed BDB dump the same way, proxy.c:306-339).
        Returns a STABLE path downstream consumers (persistence) may
        stream the dump from after this call — one that outlives the
        caller's temp file — or None if the SM keeps no such file (the
        caller must then fall back to the in-memory blob for
        persistence).  ``adopt=True`` offers ownership of ``path``: an
        adopting SM renames instead of copying, so a multi-GB dump is
        installed without materializing OR duplicating it.

        Default: materialize and delegate to ``apply_snapshot`` — fine
        for SMs whose states are small by construction (KVS); SMs with
        on-disk dumps (RelayStateMachine) override with true adoption."""
        with open(path, "rb") as f:
            data = f.read()
        self.apply_snapshot(dataclasses.replace(snap, data=data))
        return None


class RecordingStateMachine(StateMachine):
    """Test double: records applied (idx, cmd) pairs verbatim."""

    def __init__(self) -> None:
        self.applied: list[tuple[int, bytes]] = []

    def apply(self, idx: int, cmd: bytes) -> bytes | None:
        self.applied.append((idx, cmd))
        return None

    def create_snapshot(self, last_idx: int, last_term: int) -> Snapshot:
        blob = b"\n".join(b"%d:%s" % (i, c) for i, c in self.applied)
        return Snapshot(last_idx, last_term, blob)

    def apply_snapshot(self, snap: Snapshot) -> None:
        self.applied = []
        if snap.data:
            for line in snap.data.split(b"\n"):
                i, c = line.split(b":", 1)
                self.applied.append((int(i), c))
