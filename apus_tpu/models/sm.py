"""StateMachine interface (dare_sm_t vtable analog, dare_sm.h:49-60)."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class Snapshot:
    """SM snapshot (snapshot_t analog, dare_log.h:107-112): the state
    blob plus the determinant of the last applied entry.

    ``seg`` carries the partially-reassembled chunk groups at the
    snapshot point (core.segment.Reassembler.dump): the buffer is a
    deterministic function of the applied prefix, so it travels WITH
    the prefix — an installer can then complete a group whose early
    chunks lie below the snapshot and whose final applies above it."""

    last_idx: int
    last_term: int
    data: bytes
    seg: bytes = b""


class StateMachine:
    """Commands are opaque bytes; ``apply`` may return a reply blob."""

    def apply(self, idx: int, cmd: bytes) -> bytes | None:
        raise NotImplementedError

    def query(self, cmd: bytes) -> bytes | None:
        """Read-only command, never logged — the linearizable-read path
        (ud_clt_answer_read_request analog, dare_ibv_ud.c:1424-1449).
        Default: not supported."""
        raise NotImplementedError(f"{type(self).__name__} has no query path")

    def create_snapshot(self, last_idx: int, last_term: int) -> Snapshot:
        raise NotImplementedError

    def apply_snapshot(self, snap: Snapshot) -> None:
        raise NotImplementedError


class RecordingStateMachine(StateMachine):
    """Test double: records applied (idx, cmd) pairs verbatim."""

    def __init__(self) -> None:
        self.applied: list[tuple[int, bytes]] = []

    def apply(self, idx: int, cmd: bytes) -> bytes | None:
        self.applied.append((idx, cmd))
        return None

    def create_snapshot(self, last_idx: int, last_term: int) -> Snapshot:
        blob = b"\n".join(b"%d:%s" % (i, c) for i, c in self.applied)
        return Snapshot(last_idx, last_term, blob)

    def apply_snapshot(self, snap: Snapshot) -> None:
        self.applied = []
        if snap.data:
            for line in snap.data.split(b"\n"):
                i, c = line.split(b":", 1)
                self.applied.append((int(i), c))
