"""Unified observability plane: metrics registry, per-op stage spans,
and the black-box flight recorder, bundled per process as an ObsHub.

One hub per replica daemon (and optionally per client):

- ``hub.registry`` — the MetricsRegistry every legacy stats dict now
  rides (namespaced views: node_*, net_*, fault_*, srv_*), plus the
  span-stage histograms; exposed over the wire via OP_METRICS and
  scraped by ``python -m apus_tpu.obs.scrape``.
- ``hub.spans`` — SpanRecorder: per-op stage stamps for req_id-sampled
  ops (default 1/64; APUS_OBS_SAMPLE overrides the period).
- ``hub.flight`` — FlightRecorder: the always-on bounded ring of
  state-transition events, dumped via OP_OBS_DUMP and automatically by
  fuzz/soak on failure; rendered by ``python -m apus_tpu.obs.timeline``.

``APUS_OBS=0`` disables the whole plane (make_hub returns None and the
daemon falls back to private per-component registries, keeping the
legacy stats surface alive with zero span/flight overhead).

Deterministic-simulator nodes never get a hub: the sim stays clock-pure.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from apus_tpu.obs import catalog
from apus_tpu.obs.flight import FlightRecorder
from apus_tpu.obs.metrics import (Counter, Gauge, Histogram,
                                  MetricsRegistry, StatsView, bump,
                                  render_prometheus)
from apus_tpu.obs.spans import (STAGE_DURATIONS, STAGE_ORDER,
                                SpanRecorder)

__all__ = ["ObsHub", "make_hub", "MetricsRegistry", "StatsView",
           "SpanRecorder", "FlightRecorder", "Counter", "Gauge",
           "Histogram", "bump", "render_prometheus", "STAGE_ORDER",
           "STAGE_DURATIONS", "DEFAULT_SAMPLE_PERIOD"]

DEFAULT_SAMPLE_PERIOD = 64


class ObsHub:
    """One process/replica's observability state."""

    def __init__(self, ident: str = "",
                 sample_period: Optional[int] = None,
                 span_capacity: int = 8192,
                 flight_capacity: int = 2048):
        if sample_period is None:
            try:
                sample_period = int(os.environ.get(
                    "APUS_OBS_SAMPLE", DEFAULT_SAMPLE_PERIOD))
            except ValueError:
                sample_period = DEFAULT_SAMPLE_PERIOD
        self.ident = ident
        self.registry = MetricsRegistry()
        # Pre-register the full catalog: a scrape sees every metric
        # from the first reply (zeros included), and the drift lint's
        # "cataloged => reachable via OP_METRICS" contract holds by
        # construction.
        for name in catalog.COUNTERS:
            self.registry.counter(name)
        for name in catalog.GAUGES:
            self.registry.gauge(name)
        for name in catalog.HISTOGRAMS:
            self.registry.histogram(name)
        self.spans = SpanRecorder(self.registry,
                                  sample_period=sample_period,
                                  capacity=span_capacity)
        self.flight = FlightRecorder(flight_capacity)

    def view(self, namespace: str) -> StatsView:
        return self.registry.view(namespace)

    def dump(self) -> dict:
        """JSON-able full dump: metrics snapshot + flight + span rings,
        with a wall/mono anchor so cross-process timelines align on
        wall time (per-event stamps are monotonic µs, which are only
        comparable within one process)."""
        return {
            "ident": self.ident,
            "pid": os.getpid(),
            "anchor": {"wall_us": time.time_ns() // 1000,
                       "mono_us": time.monotonic_ns() // 1000},
            "sample_period": self.spans.sample_period,
            "metrics": self.registry.snapshot(),
            "flight": self.flight.events(),
            "flight_dropped": self.flight.dropped,
            "spans": self.spans.events(),
            "spans_dropped": self.spans.dropped,
        }


def obs_enabled(env: Optional[dict] = None) -> bool:
    e = os.environ if env is None else env
    return e.get("APUS_OBS", "1").lower() not in ("0", "false", "off")


def make_hub(ident: str = "", **kwargs) -> Optional[ObsHub]:
    """The daemon's single construction point: a hub, or None when the
    plane is disabled via APUS_OBS=0."""
    if not obs_enabled():
        return None
    return ObsHub(ident, **kwargs)
