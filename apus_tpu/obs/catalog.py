"""The metrics catalog: every registry metric the runtime may emit.

This is the drift gate's source of truth (scripts/check_metrics.py):

- every counter bumped in source (the ``.bump("name")`` spelling) must
  be cataloged here under its namespace,
- every cataloged name must be documented in DESIGN.md's
  "Observability plane" section (as a backticked literal),
- every cataloged name is pre-registered by ObsHub, so it is reachable
  through OP_METRICS from the first scrape (zeros included) — the
  roundtrip test asserts that.

Names are the FULL registry names (``<namespace>_<metric>``).
"""

from __future__ import annotations

COUNTERS: dict[str, str] = {
    # -- node_*: protocol core (core/node.py, parallel/onesided.py,
    #    runtime/bridge.py, runtime/device_plane.py) -------------------
    "node_elections": "elections started by this replica",
    "node_prevotes": "prevote rounds opened",
    "node_votes_granted": "real votes granted to candidates",
    "node_commits": "commit-index advances observed as leader",
    "node_applied": "entries applied to the state machine",
    "node_hb_sent": "leader heartbeat rounds fanned out",
    "node_entries_replicated": "entries shipped in replication writes",
    "node_repl_windows": "replication fan-out windows shipped",
    "node_drain_windows": "group-commit drain windows formed",
    "node_drain_entries": "client entries admitted through drain windows",
    "node_seg_split": "oversized commands split into segment chunks",
    "node_seg_incomplete": "applies deferred on an incomplete segment",
    "node_lease_reads": "linearizable reads served from the leader lease",
    "node_lease_renewals": "leader lease renewals (quorum-acked HB rounds)",
    "node_readindex_verifies": "reads that paid the read-index majority round",
    # Follower read leases (read scale-out; core/node.py flr_*).
    "node_flr_grants": "follower read leases granted by this leader",
    "node_flr_grant_refusals": "follower lease requests refused (typed guards)",
    "node_flr_requests": "lease requests this follower sent to the leader",
    "node_flr_renewals": "lease grants adopted by this follower",
    "node_flr_local_reads": "linearizable reads served from a follower lease",
    "node_flr_forwards": "follower reads bounced to the leader (lease dead)",
    "node_flr_lapses": "follower lease lapse edges (any cause)",
    "node_flr_pause_lapses": "lapses missed by a whole window (pause/clock jump)",
    "node_flr_epoch_refusals": "lapses on the config-epoch fence (membership moved)",
    "node_flr_commit_blocked": "commit advances held for a live lease holder's ack",
    # Bucket-granular follower leases (per-key Hermes invalidation).
    "node_flr_bucket_grants": "bucket-scoped (partial read set) lease grants",
    "node_flr_bucket_refusals": "follower reads bounced: bucket outside the granted set",
    "node_flr_commit_bypass": "commit advances a whole-log lease rule would have blocked",
    "node_graceful_leaves": "OP_LEAVE removals committed",
    "node_auto_removes": "failure-detector evictions committed",
    "node_resize_aborts": "EXTENDED-resize aborts (joiner died mid-catch-up)",
    "node_emergency_prunes": "emergency log prunes under ring pressure",
    "node_fenced_stepdowns": "leaderships dropped on a fenced HB quorum",
    "node_fenced_ctrl_writes": "stale-incarnation ctrl writes dropped",
    "node_snapshots_pushed": "whole-blob snapshot pushes completed",
    "node_snapshots_streamed": "chunked snapshot streams completed",
    "node_snapshots_installed": "snapshots installed on this replica",
    "node_snapshots_file_installed": "file-adopted (streamed) installs",
    "node_snap_push_abandoned": "wedged push threads abandoned by the watchdog",
    "node_snap_push_stale_done": "stale push completions dropped by generation",
    "node_snap_chunk_quarantines": "damaged partial chunk files quarantined",
    "node_snap_stream_resumes": "inbound snapshot streams resumed mid-file",
    "node_delta_snapshots": "delta snapshots served to lagging peers",
    "node_delta_installs": "delta snapshots installed",
    "node_delta_refused": "delta installs refused on a base mismatch",
    "node_devplane_commits": "commit advances adopted from the device quorum",
    # Multi-group sharded consensus (runtime/groupset.py).
    "node_hb_coalesced_groups": "groups carried by coalesced OP_HB_MULTI flushes",
    # Elastic groups (runtime/elastic.py): online split/merge.
    "node_migrations": "bucket migrations committed (split/merge flips)",
    "node_wrong_group_hints": "ops bounced with a typed WRONG_GROUP + shard map",
    "node_migrating_refusals": "writes refused on a frozen mid-migration bucket",
    # Cross-group transactions (runtime/txn.py 2PC coordinator).
    "node_txn_prepared": "participant prepares collected by this coordinator",
    "node_txn_decided": "transactions decided COMMIT (TD records applied)",
    "node_txn_aborted": "transactions decided ABORT",
    "node_txn_resumed": "open transactions adopted by a driver that did not begin them",
    "node_txn_lock_conflicts": "prepares refused on a lock conflict (txn aborted)",
    "node_txn_epoch_aborts": "prepares refused on the frozen/departed epoch fence",
    "node_txn_batches": "within-group TM MULTI batches served",
    "node_devplane_own_flips": "device-plane commit ownership flips (own/release)",
    "node_nack_ranges_dropped": "proxy NACK ranges dropped by the bridge",
    "node_proxy_spin_timeouts": "proxy spin-wait timeouts observed",
    "node_replay_reprimes": "bridge replay re-primes after reconnect",
    # -- net_*: initiator transport (parallel/net.py) ------------------
    "net_retries": "in-op connection-fault retries attempted",
    "net_retries_ok": "in-op retries that succeeded",
    "net_snap_chunks_sent": "snapshot chunks sent",
    "net_snap_chunks_acked": "snapshot chunks acked durable",
    "net_snap_resumes": "outbound snapshot streams resumed past byte 0",
    "net_snap_resumed_bytes": "bytes skipped by stream resumes",
    # -- fault_*: injected-fault plane (parallel/faults.py) ------------
    "fault_drops": "ops dropped by the fault plane",
    "fault_delays": "ops delayed by the fault plane",
    "fault_dups": "ops duplicated by the fault plane",
    "fault_reorders": "ops held for reordering",
    "fault_blocked": "ops refused by partitions/crash state",
    "fault_throttles": "ops stalled by a slow-peer throttle",
    "fault_inbound_drops": "inbound handler messages dropped",
    "fault_inbound_delays": "inbound handler messages delayed",
    "fault_clock_cmds": "adversarial-time commands applied (rate/jump/reset)",
    # -- srv_*: passive peer server (parallel/net.py PeerServer) -------
    "srv_ingest_batches": "multi-frame bursts drained off one connection",
    "srv_ingest_frames": "frames ingested through burst drains",
    "srv_ingest_solo": "single-frame (non-burst) requests served",
    # Overload control plane (runtime/overload.py policy, enforced in
    # parallel/net.py admission + the group-commit drain deadline
    # check): typed ST_OVERLOAD sheds, classified by cause.
    "srv_ovl_admitted": "client ops admitted through the overload gate",
    "srv_ovl_shed_global": "client ops shed: global in-flight budget full",
    "srv_ovl_shed_conn": "client ops shed: per-connection budget full",
    "srv_ovl_shed_deadline": "client ops shed at the drain: client deadline already expired",
    # Native serving data plane, Python-side events (parallel/
    # native_plane.py; the C loop's own counters are the srv_native_*
    # GAUGES below, mirrored at scrape time).
    "srv_native_adopted": "client connections adopted by the native plane",
    "srv_native_fallbacks": "native bursts the batch hook declined (sequential dispatch)",
    "srv_native_errors": "native upcall batches that raised (answered ST_ERROR)",
    "srv_native_unavailable": "native plane requested but extension absent (Python fallback)",
    "srv_native_view_poisoned": "applied-view mirrors poisoned (untrackable op / oversized)",
    "srv_native_merged_bursts": "connection bursts coalesced into shared admission calls",
    # Protocol-aware app serving surface (runtime/serve.py AppServer):
    # RESP + memcached-text commands mapped onto the replicated KVS.
    "srv_app_conns": "app-protocol client connections accepted by the gateway",
    "srv_app_resp_cmds": "RESP commands parsed by the gateway",
    "srv_app_mc_cmds": "memcached-text commands parsed by the gateway",
    "srv_app_kvs_ops": "KVS ops the gateway pipelined into the cluster",
    "srv_app_local_cmds": "commands answered locally (PING/ECHO/version...)",
    "srv_app_errors": "protocol errors answered (unmapped, no relay backend)",
    "srv_app_fallback_conns": "connections flipped to the opaque relay fallback",
    "srv_app_fallback_bytes": "bytes carried through the opaque relay fallback",
    "srv_app_busy_replies": "app bursts answered protocol-native busy (cluster shed, retry budget dry)",
    # -- dev_*: device-plane engine (runtime/device_plane.py runner;
    #    process-wide registry merged into every replica's scrape) ----
    "dev_rounds": "device commit rounds executed",
    "dev_resets": "device-log resets (fresh leaderships)",
    "dev_quorum_fail_rounds": "rounds whose device quorum vote failed",
    "dev_entries_devplane": "entries carried by device commit rounds",
    "dev_pipelined_dispatches": "multi-round windows dispatched (async/deep)",
    "dev_window_dispatches": "single-window engine dispatches",
    "dev_deep_dispatches": "deep-rung (>= DEEP_DEPTH) window dispatches",
    "dev_early_exits": "windowed dispatches cut short by device-side early exit",
    "dev_recompiles": "post-warmup XLA recompiles on live executables",
    # Group-major dispatch (runtime/group_plane.py).
    "dev_group_major_windows": "group-major device dispatches (many groups per window)",
    "dev_async_overlap_windows": "group-major windows staged while the previous window was still executing (async-beat overlap)",
}

GAUGES: dict[str, str] = {
    # Mirrored from daemon/persistence state at OP_METRICS scrape time.
    "daemon_persist_errors": "I/O errors seen on the persistence path",
    "daemon_persist_disabled": "1 when persistence is disabled for the session",
    "daemon_persist_syncs": "fdatasync calls issued by the batch policy",
    "daemon_compactions": "store compactions completed",
    "daemon_compaction_floor": "first log index covered by the base image",
    "daemon_store_records_since_base": "records appended past the base image",
    # Device-plane gauges: dev_* mirrors runner scalars, devd_* mirrors
    # the per-daemon driver's stats dict at OP_METRICS scrape time.
    "dev_max_dispatch_ms": "slowest blocked device-result wait observed (ms)",
    "dev_devices": "devices in the group-major runner's (group, replica) mesh",
    "devd_rounds": "device rounds this daemon's driver dispatched",
    "devd_drained": "device rows drained into the host log (follower path)",
    "devd_holes": "device-ineligible spans handed to the host path",
    "devd_fallbacks": "commit ownership handed back to the host path",
    "devd_quorum_gated": "dispatches skipped: live mask below quorum",
    "devd_qfail_timeouts": "quorum-fail streak timeouts (dispatch paused)",
    "devd_async_windows": "deep windows enqueued without blocking",
    "devd_partial_deferrals": "partial windows deferred for queued admissions",
    "devd_group_windows": "per-group windows carried by this daemon's group-major dispatches",
    # Native serving data plane: the C++ loop's atomics, mirrored as
    # gauges at OP_METRICS scrape / OP_STATUS time (the loop itself
    # never touches the registry — it never holds the GIL).
    "srv_native_ingest_batches": "recv bursts the native epoll loop drained",
    "srv_native_ingest_frames": "frames the native loop parsed off the wire",
    "srv_native_replies": "replies flushed by the native loop (all paths)",
    "srv_native_dedup_hits": "duplicate writes answered from the native reply cache",
    "srv_native_get_serves": "GETs served from the native applied view",
    "srv_native_upcall_batches": "bursts handed across the GIL admission boundary",
    "srv_native_upcall_frames": "frames in those upcall bursts",
    "srv_native_raw_batches": "upcall bursts demoted to raw-frame mode (non-client op seen)",
    "srv_native_bytes_in": "bytes the native loop read off client sockets",
    "srv_native_bytes_out": "bytes the native loop flushed to client sockets",
    "srv_native_conns_adopted": "connections the native loop has ever owned",
    "srv_native_gil_released_ns": "native loop busy time (all of it GIL-free), ns",
    "srv_native_gate_misses": "GETs that fell to Python on a closed read gate",
    "srv_native_view_poisons": "applied views the native side marked stale",
    "srv_native_sheds": "client frames the native loop shed pre-GIL (ST_OVERLOAD, budget full)",
}

HISTOGRAMS: dict[str, str] = {
    "stage_lock_wait_us": "ingest -> node lock acquired",
    "stage_dedup_admit_us": "lock -> submit returned (dedup + enqueue)",
    "stage_append_us": "admit -> entry holds a log index",
    "stage_repl_fanout_us": "append -> first replication write shipped",
    "stage_quorum_ack_us": "repl -> commit advanced past the index",
    "stage_apply_us": "quorum -> entry applied to the SM",
    "stage_fsync_us": "apply -> drain-window fdatasync covered it",
    "stage_reply_flush_us": "fsync/apply -> reply bytes built",
    "stage_wire_out_us": "reply -> client parsed the reply frame",
    "op_server_us": "server end-to-end: ingest -> reply (telescoped stages)",
    "op_client_us": "client end-to-end: send -> reply parsed",
    # Device-plane dispatch/occupancy distributions (runner registry).
    "dev_dispatch_wait_us": "blocked device->host result wait per dispatch",
    "dev_window_wall_us": "whole sync window dispatch wall (encode+stage+wait)",
    "dev_window_depth": "requested rounds per window dispatch",
    "dev_window_rounds_run": "rounds actually executed per resolved window",
    "dev_staging_wait_us": "HostStagingRing acquire consumer-edge block",
    "dev_groups_per_dispatch": "consensus groups carried per group-major dispatch",
    "dev_groups_per_device_max": "groups landing on the busiest device shard per group-major dispatch",
}

CATALOG: dict[str, str] = {**COUNTERS, **GAUGES, **HISTOGRAMS}

#: Flight-recorder event categories — the black-box ring's taxonomy.
#: scripts/check_metrics.py lints every ``_note("...")`` /
#: ``flight.note("...")`` literal in the runtime against this table
#: (and requires each category documented in DESIGN.md), so a new
#: event class cannot ship undocumented.
FLIGHT_CATEGORIES: dict[str, str] = {
    "role": "role/term transitions (edge-triggered, daemon tick)",
    "election": "elections opened by this replica",
    "config": "CONFIG applies: joins, auto-removes, resize aborts, leaves",
    "lease": "leader read-lease grant/lapse edges",
    "snap_push": "snapshot push completions (per peer, with result)",
    "snap_stream": "chunked snapshot stream begin/resume/quarantine/end",
    "watchdog": "watchdog fires: snap-push abandon, devplane stall, rejoin",
    "persist": "persistence disablement (first I/O error of the session)",
    "fault": "scripted fault-plane commands landing on this replica",
    "devplane": "device-plane ownership flips (cause-tagged) + recompiles",
    "elastic": "elastic-group migrations: begin/capture/committed edges",
    "txn": "cross-group transactions: begin/resumed/decided/closed edges",
    "native": "native data plane activation / loud fallback edges",
    "overload": "shed-burst edges: first shed after an admitted span (reason + queue depth)",
}
