"""The metrics catalog: every registry metric the runtime may emit.

This is the drift gate's source of truth (scripts/check_metrics.py):

- every counter bumped in source (the ``.bump("name")`` spelling) must
  be cataloged here under its namespace,
- every cataloged name must be documented in DESIGN.md's
  "Observability plane" section (as a backticked literal),
- every cataloged name is pre-registered by ObsHub, so it is reachable
  through OP_METRICS from the first scrape (zeros included) — the
  roundtrip test asserts that.

Names are the FULL registry names (``<namespace>_<metric>``).
"""

from __future__ import annotations

COUNTERS: dict[str, str] = {
    # -- node_*: protocol core (core/node.py, parallel/onesided.py,
    #    runtime/bridge.py, runtime/device_plane.py) -------------------
    "node_elections": "elections started by this replica",
    "node_prevotes": "prevote rounds opened",
    "node_votes_granted": "real votes granted to candidates",
    "node_commits": "commit-index advances observed as leader",
    "node_applied": "entries applied to the state machine",
    "node_hb_sent": "leader heartbeat rounds fanned out",
    "node_entries_replicated": "entries shipped in replication writes",
    "node_repl_windows": "replication fan-out windows shipped",
    "node_drain_windows": "group-commit drain windows formed",
    "node_drain_entries": "client entries admitted through drain windows",
    "node_seg_split": "oversized commands split into segment chunks",
    "node_seg_incomplete": "applies deferred on an incomplete segment",
    "node_lease_reads": "linearizable reads served from the leader lease",
    "node_lease_renewals": "leader lease renewals (quorum-acked HB rounds)",
    "node_readindex_verifies": "reads that paid the read-index majority round",
    "node_graceful_leaves": "OP_LEAVE removals committed",
    "node_auto_removes": "failure-detector evictions committed",
    "node_resize_aborts": "EXTENDED-resize aborts (joiner died mid-catch-up)",
    "node_emergency_prunes": "emergency log prunes under ring pressure",
    "node_fenced_stepdowns": "leaderships dropped on a fenced HB quorum",
    "node_fenced_ctrl_writes": "stale-incarnation ctrl writes dropped",
    "node_snapshots_pushed": "whole-blob snapshot pushes completed",
    "node_snapshots_streamed": "chunked snapshot streams completed",
    "node_snapshots_installed": "snapshots installed on this replica",
    "node_snapshots_file_installed": "file-adopted (streamed) installs",
    "node_snap_push_abandoned": "wedged push threads abandoned by the watchdog",
    "node_snap_push_stale_done": "stale push completions dropped by generation",
    "node_snap_chunk_quarantines": "damaged partial chunk files quarantined",
    "node_snap_stream_resumes": "inbound snapshot streams resumed mid-file",
    "node_delta_snapshots": "delta snapshots served to lagging peers",
    "node_delta_installs": "delta snapshots installed",
    "node_delta_refused": "delta installs refused on a base mismatch",
    "node_devplane_commits": "commit advances adopted from the device quorum",
    "node_nack_ranges_dropped": "proxy NACK ranges dropped by the bridge",
    "node_proxy_spin_timeouts": "proxy spin-wait timeouts observed",
    "node_replay_reprimes": "bridge replay re-primes after reconnect",
    # -- net_*: initiator transport (parallel/net.py) ------------------
    "net_retries": "in-op connection-fault retries attempted",
    "net_retries_ok": "in-op retries that succeeded",
    "net_snap_chunks_sent": "snapshot chunks sent",
    "net_snap_chunks_acked": "snapshot chunks acked durable",
    "net_snap_resumes": "outbound snapshot streams resumed past byte 0",
    "net_snap_resumed_bytes": "bytes skipped by stream resumes",
    # -- fault_*: injected-fault plane (parallel/faults.py) ------------
    "fault_drops": "ops dropped by the fault plane",
    "fault_delays": "ops delayed by the fault plane",
    "fault_dups": "ops duplicated by the fault plane",
    "fault_reorders": "ops held for reordering",
    "fault_blocked": "ops refused by partitions/crash state",
    "fault_throttles": "ops stalled by a slow-peer throttle",
    "fault_inbound_drops": "inbound handler messages dropped",
    "fault_inbound_delays": "inbound handler messages delayed",
    # -- srv_*: passive peer server (parallel/net.py PeerServer) -------
    "srv_ingest_batches": "multi-frame bursts drained off one connection",
    "srv_ingest_frames": "frames ingested through burst drains",
    "srv_ingest_solo": "single-frame (non-burst) requests served",
}

GAUGES: dict[str, str] = {
    # Mirrored from daemon/persistence state at OP_METRICS scrape time.
    "daemon_persist_errors": "I/O errors seen on the persistence path",
    "daemon_persist_disabled": "1 when persistence is disabled for the session",
    "daemon_persist_syncs": "fdatasync calls issued by the batch policy",
    "daemon_compactions": "store compactions completed",
    "daemon_compaction_floor": "first log index covered by the base image",
    "daemon_store_records_since_base": "records appended past the base image",
}

HISTOGRAMS: dict[str, str] = {
    "stage_lock_wait_us": "ingest -> node lock acquired",
    "stage_dedup_admit_us": "lock -> submit returned (dedup + enqueue)",
    "stage_append_us": "admit -> entry holds a log index",
    "stage_repl_fanout_us": "append -> first replication write shipped",
    "stage_quorum_ack_us": "repl -> commit advanced past the index",
    "stage_apply_us": "quorum -> entry applied to the SM",
    "stage_fsync_us": "apply -> drain-window fdatasync covered it",
    "stage_reply_flush_us": "fsync/apply -> reply bytes built",
    "stage_wire_out_us": "reply -> client parsed the reply frame",
    "op_server_us": "server end-to-end: ingest -> reply (telescoped stages)",
    "op_client_us": "client end-to-end: send -> reply parsed",
}

CATALOG: dict[str, str] = {**COUNTERS, **GAUGES, **HISTOGRAMS}
