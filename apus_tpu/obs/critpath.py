"""Critical-path attribution: where does a client op's time go?

    python -m apus_tpu.obs.critpath DUMP.json [DUMP2.json ...]
    python -m apus_tpu.obs.critpath --addrs host:p0,host:p1 [--json]

Folds stitched span dumps (OP_OBS_DUMP fetches, or a harness failure
dump) into a per-op dominant-stage table: each sampled op's stage
durations are computed from its cross-replica hop chain (device window
events included), aggregated into per-stage p50/p99/mean, and every op
is attributed to the stage that DOMINATED it.  The stages then roll up
into buckets — host CPU (framing/dedup/locks), replication roundtrip,
device dispatch, durability, apply — and the tool answers ROADMAP's
standing question quantitatively: is the hot path Python-CPU-bound or
roundtrip-bound?  (BENCH_r07 answered it by process-of-elimination
benchmarking; this reads it off any live cluster or failure dump.)

The per-op durations telescope (each is the gap to the previous
present stamp in canonical order), so bucket shares sum to ~100% of
the server end-to-end and the verdict is an identity, not a model.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from apus_tpu.obs.timeline import load_dumps, merge_dumps, stitch_ops

#: Canonical stamp order with the device window hops interleaved where
#: they sit on the wall (dispatch after the fan-out, ready before the
#: commit adoption).  Durations are named by the LATER stamp of each
#: adjacent present pair.
ORDER = ("client_send", "ingest", "lock", "admit", "append", "repl",
         "dev_dispatch", "dev_ready", "quorum", "apply", "fsync",
         "reply", "client_reply")

DUR_NAMES = {
    "ingest": "wire_in",
    "lock": "lock_wait",
    "admit": "dedup_admit",
    "append": "append",
    "repl": "repl_fanout",
    "dev_dispatch": "dev_dispatch_wait",
    "dev_ready": "dev_execute",
    "quorum": "quorum_ack",
    "apply": "apply",
    "fsync": "fsync",
    "reply": "reply_flush",
    "client_reply": "wire_out",
}

#: Stage -> attribution bucket.  host_cpu is the Python data-plane
#: work the native-hot-path ROADMAP item would absorb; replication +
#: device are the roundtrip-shaped waits it would not.
BUCKETS = {
    "wire_in": "host_cpu",
    "lock_wait": "host_cpu",
    "dedup_admit": "host_cpu",
    "append": "host_cpu",
    "reply_flush": "host_cpu",
    "repl_fanout": "replication",
    "quorum_ack": "replication",
    "dev_dispatch_wait": "device",
    "dev_execute": "device",
    "fsync": "durability",
    "apply": "apply",
    "wire_out": "client_wire",
}

#: Stages outside the server bracket (ingest..reply): excluded from
#: dominance/verdict math, reported in the stage table only.
_CLIENT_SIDE = ("wire_in", "wire_out")

_ORDER_IDX = {s: i for i, s in enumerate(ORDER)}


def op_durations(stamps: dict) -> dict:
    """{duration_name: µs} for one op's {stage: t} stamp dict —
    adjacent gaps over the present stages in canonical order."""
    present = sorted((s for s in stamps if s in _ORDER_IDX),
                     key=_ORDER_IDX.__getitem__)
    out = {}
    for a, b in zip(present, present[1:]):
        name = DUR_NAMES.get(b)
        if name is not None:
            out[name] = max(0, stamps[b] - stamps[a])
    return out


def _pcts(vals: list) -> dict:
    vs = sorted(vals)
    n = len(vs)
    return {"n": n,
            "p50": round(vs[n // 2], 1),
            "p99": round(vs[min(n - 1, int(0.99 * n))], 1),
            "mean": round(sum(vs) / n, 1),
            "total": round(sum(vs), 1)}


def attribute(dumps: list[dict]) -> dict:
    """The attribution report for a set of per-replica dumps:

    - ``stages``: per-duration n/p50/p99/mean/total (µs),
    - ``dominant``: how many ops each SERVER stage dominated,
    - ``buckets``: share of total server time per bucket,
    - ``verdict``: the one-line answer ("host-CPU-bound ...").
    """
    merged = merge_dumps(dumps)
    ops = stitch_ops(merged)           # device windows attached
    stage_vals: dict[str, list] = {}
    dominant: dict[str, int] = {}
    n_ops = 0
    for o in ops.values():
        stamps: dict[str, int] = {}
        for ev in o["stamps"]:
            s = ev.get("stage")
            if s in _ORDER_IDX and s not in stamps:
                stamps[s] = ev.get("wall_us", ev.get("t_us", 0))
        durs = op_durations(stamps)
        if not durs:
            continue
        n_ops += 1
        for name, v in durs.items():
            stage_vals.setdefault(name, []).append(v)
        server = {k: v for k, v in durs.items()
                  if k not in _CLIENT_SIDE}
        if server:
            top = max(server, key=server.get)
            dominant[top] = dominant.get(top, 0) + 1

    stages = {name: _pcts(vals) for name, vals in stage_vals.items()}
    bucket_tot: dict[str, float] = {}
    for name, st in stages.items():
        if name in _CLIENT_SIDE:
            continue
        b = BUCKETS.get(name, "other")
        bucket_tot[b] = bucket_tot.get(b, 0.0) + st["total"]
    total = sum(bucket_tot.values())
    buckets = {b: {"total_us": round(t, 1),
                   "share": round(t / total, 3) if total else 0.0}
               for b, t in sorted(bucket_tot.items(),
                                  key=lambda kv: -kv[1])}

    verdict = "no sampled ops with stitched durations"
    if total:
        host = buckets.get("host_cpu", {}).get("share", 0.0)
        rtt = (buckets.get("replication", {}).get("share", 0.0)
               + buckets.get("device", {}).get("share", 0.0))
        top_b = next(iter(buckets))
        if host >= 0.5:
            verdict = (f"host-CPU-bound: {host:.0%} of server time in "
                       f"Python framing/dedup/locks "
                       f"(roundtrip {rtt:.0%}) — the native-hot-path "
                       f"item pays off")
        elif rtt >= 0.5:
            verdict = (f"roundtrip-bound: {rtt:.0%} of server time in "
                       f"replication/device waits (host CPU "
                       f"{host:.0%}) — batching/pipelining depth is "
                       f"the lever")
        else:
            verdict = (f"mixed: dominant bucket {top_b} "
                       f"({buckets[top_b]['share']:.0%}); host CPU "
                       f"{host:.0%}, roundtrip {rtt:.0%}")
    return {"ops": n_ops, "stages": stages, "dominant": dominant,
            "buckets": buckets, "verdict": verdict}


def render_table(rep: dict) -> str:
    lines = [f"critical-path attribution over {rep['ops']} sampled "
             f"op(s)", "",
             f"{'stage':<18} {'n':>6} {'p50us':>9} {'p99us':>10} "
             f"{'meanus':>9} {'dominates':>10}"]
    order = [DUR_NAMES[s] for s in ORDER if s in DUR_NAMES]
    for name in order:
        st = rep["stages"].get(name)
        if st is None:
            continue
        dom = rep["dominant"].get(name, 0)
        lines.append(f"{name:<18} {st['n']:>6} {st['p50']:>9,.1f} "
                     f"{st['p99']:>10,.1f} {st['mean']:>9,.1f} "
                     f"{dom:>10}")
    lines += ["", f"{'bucket':<14} {'share':>7} {'total_us':>12}"]
    for b, rec in rep["buckets"].items():
        lines.append(f"{b:<14} {rec['share']:>6.1%} "
                     f"{rec['total_us']:>12,.1f}")
    lines += ["", f"verdict: {rep['verdict']}"]
    return "\n".join(lines) + "\n"


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apus_tpu.obs.critpath",
        description="Fold stitched span dumps into a per-op "
                    "dominant-stage attribution table.")
    ap.add_argument("files", nargs="*",
                    help="dump JSON files (OP_OBS_DUMP fetches or a "
                         "harness failure dump)")
    ap.add_argument("--addrs", default="",
                    help="fetch live dumps from these replica "
                         "endpoints (comma-separated host:port)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of a table")
    args = ap.parse_args(argv)

    dumps: list[dict] = []
    for path in args.files:
        dumps.extend(load_dumps(path))
    if args.addrs:
        from apus_tpu.obs.service import collect_cluster_dumps
        dumps.extend(collect_cluster_dumps(
            [a for a in args.addrs.split(",") if a]))
    if not dumps:
        print("no dumps (give files and/or --addrs)", file=sys.stderr)
        return 1
    rep = attribute(dumps)
    if args.json:
        print(json.dumps(rep, indent=2))
    else:
        sys.stdout.write(render_table(rep))
    return 0


if __name__ == "__main__":
    sys.exit(main())
