"""Black-box flight recorder: a bounded ring of state-transition events.

When a fuzz campaign shrinks a linearizability violation to a minimal
failing window, the question that remains is "what was the CLUSTER
doing in the 200 ms before it" — which role flips, CONFIG applies,
lease lapses, snapshot streams, fault injections, and watchdog firings
surrounded the bad read.  Those events are rare (Hz, not kHz), so an
always-on ring is effectively free; like an aircraft recorder it keeps
only the last N events and is read out on demand (OP_OBS_DUMP) or
automatically when a harness fails.

Each event: (monotonic µs, category, fields).  Wall-clock alignment
across processes rides the ObsHub dump anchor, not per-event stamps.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class FlightRecorder:
    """Bounded event ring; `note()` is safe from any thread."""

    def __init__(self, capacity: int = 2048):
        self.capacity = max(16, int(capacity))
        self._ring: list = [None] * self.capacity
        self._seq = 0
        self.dropped = 0
        self._lock = threading.Lock()

    def note(self, category: str, msg: str = "", **fields) -> None:
        t = time.monotonic_ns() // 1000
        ev = (t, category, msg, fields or None)
        with self._lock:
            if self._seq >= self.capacity:
                self.dropped += 1
            self._ring[self._seq % self.capacity] = ev
            self._seq += 1

    def events(self) -> list[dict]:
        """Chronological snapshot (oldest retained first)."""
        with self._lock:
            n = min(self._seq, self.capacity)
            start = self._seq - n
            evs = [self._ring[(start + i) % self.capacity]
                   for i in range(n)]
            dropped = self.dropped
        out = []
        for ev in evs:
            if ev is None:
                continue
            t, cat, msg, fields = ev
            d = {"t_us": t, "cat": cat}
            if msg:
                d["msg"] = msg
            if fields:
                d.update(fields)
            out.append(d)
        if dropped and out:
            out[0] = dict(out[0], wrapped=dropped)
        return out

    def __len__(self) -> int:
        with self._lock:
            return min(self._seq, self.capacity)


def note(flight: Optional[FlightRecorder], category: str,
         msg: str = "", **fields) -> None:
    """None-tolerant helper for call sites that may run without a
    recorder (sim nodes, raw transports)."""
    if flight is not None:
        flight.note(category, msg, **fields)
