"""Lock-cheap metrics registry: counters, gauges, log2 histograms.

The reference prints ad-hoc counters when servers exit; our runtime had
grown the same shape — a dozen scattered ``stats`` dicts (Node,
NetTransport, FaultPlane, PeerServer, device runners) each with its own
``d[k] = d.get(k, 0) + 1`` plumbing, readable only through OP_STATUS
fields added one by one.  This module is the single namespace those
dicts collapse into:

- ``Counter`` / ``Gauge`` — one mutable slot each, bumped with plain
  int/float ops.  No lock on the increment path: CPython's GIL makes a
  single ``+=`` effectively atomic for our purposes, and a metrics race
  that loses one increment under free-threading is an accepted error
  bar (the hot path must never serialize on observability).
- ``Histogram`` — FIXED log2 buckets (64 slots, value -> bucket by bit
  length), preallocated at registration: observing a sample is two int
  ops and two list updates, no per-sample allocation — the property
  DXRAM found non-negotiable for always-on instrumentation of a µs
  data plane (PAPERS.md).
- ``MetricsRegistry`` — name -> metric, namespaced ``<ns>_<name>``.
  Structure changes (first registration of a name) take a small lock;
  reads/bumps never do.  ``snapshot()``/``render_prometheus()`` feed
  the OP_METRICS wire op and the scrape CLI.
- ``StatsView`` — a dict-compatible view over one namespace, so the
  legacy ``node.stats["commits"] += 1`` call sites migrate onto the
  registry without rewriting every consumer: reads of unregistered
  names return 0 (counters are born at zero), writes register.

Sim nodes keep plain dicts (no registry, no clock calls): determinism
of the virtual-time simulator is untouched.
"""

from __future__ import annotations

import threading
from typing import Iterator, Optional

#: number of log2 buckets: covers [0, 2^62) µs — wider than any op.
HIST_BUCKETS = 64


class Counter:
    """Monotone (by convention) integer counter."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Last-write-wins numeric gauge (floats allowed)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def set(self, v) -> None:
        self.value = v


class Histogram:
    """Fixed-bucket log2 histogram.

    Bucket b holds samples with ``int(x).bit_length() == b``, i.e.
    bucket 0 is exactly 0, bucket b >= 1 covers [2^(b-1), 2^b).  The
    bucket of a sample is one ``bit_length()`` call — no search, no
    float math, no allocation.  Percentiles interpolate inside the
    selected bucket (geometric midpoint), which is exact to within the
    2x bucket width — the right fidelity for "where did the time go"
    breakdowns, at hot-path cost."""

    __slots__ = ("name", "counts", "count", "sum")

    def __init__(self, name: str):
        self.name = name
        self.counts = [0] * HIST_BUCKETS
        self.count = 0
        self.sum = 0

    @staticmethod
    def bucket_of(x) -> int:
        xi = int(x)
        if xi <= 0:
            return 0
        b = xi.bit_length()
        return b if b < HIST_BUCKETS else HIST_BUCKETS - 1

    @staticmethod
    def bucket_hi(b: int) -> int:
        """Exclusive upper bound of bucket ``b`` (its ``le`` edge)."""
        return 1 if b == 0 else 1 << b

    def observe(self, x) -> None:
        self.counts[self.bucket_of(x)] += 1
        self.count += 1
        self.sum += int(x)

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile (q in [0, 1])."""
        if self.count == 0:
            return 0.0
        target = max(1, int(q * self.count + 0.5))
        seen = 0
        for b, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                if b == 0:
                    return 0.0
                lo = 1 << (b - 1)
                # Geometric midpoint of [2^(b-1), 2^b).
                return lo * 1.5
        return float(self.bucket_hi(HIST_BUCKETS - 1))

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0


class MetricsRegistry:
    """Namespaced metric store: ``<ns>_<name>`` -> metric object."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get_or_create(self, full: str, cls):
        m = self._metrics.get(full)
        if m is None:
            with self._lock:
                m = self._metrics.get(full)
                if m is None:
                    m = cls(full)
                    self._metrics[full] = m
        if not isinstance(m, cls):
            raise TypeError(f"metric {full!r} is {type(m).__name__}, "
                            f"wanted {cls.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get_or_create(name, Histogram)

    def view(self, namespace: str) -> "StatsView":
        return StatsView(self, namespace)

    def names(self) -> list[str]:
        return sorted(self._metrics)

    def snapshot(self) -> dict:
        """JSON-able {name: {type, ...}} of every registered metric —
        the OP_METRICS payload."""
        out = {}
        for name in self.names():
            m = self._metrics[name]
            if isinstance(m, Counter):
                out[name] = {"type": "counter", "value": m.value}
            elif isinstance(m, Gauge):
                out[name] = {"type": "gauge", "value": m.value}
            else:
                h: Histogram = m          # type: ignore[assignment]
                nz = {str(b): c for b, c in enumerate(h.counts) if c}
                out[name] = {"type": "histogram", "count": h.count,
                             "sum": h.sum, "buckets": nz,
                             "p50": round(h.percentile(0.50), 1),
                             "p99": round(h.percentile(0.99), 1)}
        return out

    def render_prometheus(self, prefix: str = "apus",
                          labels: Optional[dict] = None) -> str:
        return render_prometheus(self.snapshot(), prefix=prefix,
                                 labels=labels)


def render_prometheus(snapshot: dict, prefix: str = "apus",
                      labels: Optional[dict] = None) -> str:
    """Prometheus text exposition of a registry ``snapshot()`` (shared
    by the in-process registry and the scrape CLI, which only holds
    the JSON that crossed the wire).  Histograms emit cumulative
    ``_bucket{le=...}`` series on the log2 edges."""
    lab = ""
    if labels:
        lab = "{" + ",".join(f'{k}="{v}"'
                             for k, v in sorted(labels.items())) + "}"

    def bucket_lab(le) -> str:
        return (lab[:-1] + f',le="{le}"}}') if lab else f'{{le="{le}"}}'

    lines: list[str] = []
    for name in sorted(snapshot):
        rec = snapshot[name]
        full = f"{prefix}_{name}"
        kind = rec.get("type", "counter")
        if kind in ("counter", "gauge"):
            lines += [f"# TYPE {full} {kind}",
                      f"{full}{lab} {rec.get('value', 0)}"]
            continue
        lines.append(f"# TYPE {full} histogram")
        cum = 0
        buckets = rec.get("buckets", {})
        for b in sorted(buckets, key=int):
            c = buckets[b]
            if not c:
                continue
            cum += c
            lines.append(f"{full}_bucket{bucket_lab(Histogram.bucket_hi(int(b)))}"
                         f" {cum}")
        lines.append(f"{full}_bucket{bucket_lab('+Inf')} "
                     f"{rec.get('count', 0)}")
        lines += [f"{full}_sum{lab} {rec.get('sum', 0)}",
                  f"{full}_count{lab} {rec.get('count', 0)}"]
    return "\n".join(lines) + "\n"


class StatsView:
    """Dict-compatible view over one registry namespace.

    Backwards compatibility with the legacy ad-hoc stats dicts:
    ``view[k]`` and ``view.get(k)`` read 0 for names never bumped
    (counters are born at zero), ``view[k] = v`` registers-and-sets,
    ``bump(k)`` is the one-call increment that replaces the
    ``d[k] = d.get(k, 0) + 1`` plumbing.  Iteration and membership
    reflect only names actually registered in this namespace."""

    __slots__ = ("_reg", "_ns", "_prefix")

    def __init__(self, registry: MetricsRegistry, namespace: str):
        self._reg = registry
        self._ns = namespace
        self._prefix = namespace + "_" if namespace else ""

    @property
    def namespace(self) -> str:
        return self._ns

    def bump(self, name: str, n: int = 1) -> int:
        c = self._reg.counter(self._prefix + name)
        c.value += n
        return c.value

    def __getitem__(self, name: str):
        m = self._reg._metrics.get(self._prefix + name)
        return 0 if m is None else m.value

    def get(self, name: str, default=0):
        m = self._reg._metrics.get(self._prefix + name)
        return default if m is None else m.value

    def __setitem__(self, name: str, value) -> None:
        self._reg.counter(self._prefix + name).value = int(value)

    def setdefault(self, name: str, default=0):
        full = self._prefix + name
        m = self._reg._metrics.get(full)
        if m is None:
            self._reg.counter(full).value = int(default)
            return default
        return m.value

    def __contains__(self, name: str) -> bool:
        return (self._prefix + name) in self._reg._metrics

    def _names(self) -> list[str]:
        p = self._prefix
        return [n[len(p):] for n in self._reg.names() if n.startswith(p)]

    def __iter__(self) -> Iterator[str]:
        return iter(self._names())

    def __len__(self) -> int:
        return len(self._names())

    def keys(self):
        return self._names()

    def items(self):
        return [(n, self[n]) for n in self._names()]

    def __repr__(self) -> str:
        return f"StatsView({self._ns!r}, {dict(self.items())!r})"


def bump(stats, name: str, n: int = 1) -> None:
    """Increment ``name`` on either a StatsView or a plain dict — the
    shared helper for code paths (onesided, node) that run both under
    the registry-backed daemon and the dict-backed sim."""
    b = getattr(stats, "bump", None)
    if b is not None:
        b(name, n)
    else:
        stats[name] = stats.get(name, 0) + n
