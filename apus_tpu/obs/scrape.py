"""Scrape CLI: OP_METRICS from live replicas, as Prometheus text or JSON.

    python -m apus_tpu.obs.scrape HOST:PORT[,HOST:PORT...] [--json]

Each replica's registry snapshot renders with ``replica`` and ``addr``
labels, so one invocation against the whole peer table emits a single
Prometheus exposition covering the cluster (or one JSON object keyed
by address with ``--json``).  Exit status 0 when at least one replica
answered, 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from apus_tpu.obs.metrics import render_prometheus
from apus_tpu.obs.service import fetch_metrics


def scrape(addrs: list[str], timeout: float = 2.0) -> dict:
    """addr -> OP_METRICS payload (only replicas that answered)."""
    out = {}
    for addr in addrs:
        rec = fetch_metrics(addr, timeout=timeout)
        if rec is not None:
            out[addr] = rec
    return out


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apus_tpu.obs.scrape",
        description="Scrape OP_METRICS from live apus replicas.")
    ap.add_argument("addrs", nargs="+",
                    help="replica control endpoints (host:port); "
                         "comma-separated lists are flattened")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object keyed by address "
                         "instead of Prometheus text")
    ap.add_argument("--timeout", type=float, default=2.0)
    args = ap.parse_args(argv)

    addrs = [a for chunk in args.addrs for a in chunk.split(",") if a]
    got = scrape(addrs, timeout=args.timeout)
    if args.json:
        print(json.dumps(got, indent=2, sort_keys=True))
    else:
        for addr, rec in got.items():
            sys.stdout.write(render_prometheus(
                rec.get("metrics", {}),
                labels={"replica": rec.get("replica", ""),
                        "addr": addr}))
    if not got:
        print("no replica answered OP_METRICS "
              f"({', '.join(addrs)})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
