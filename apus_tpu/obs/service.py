"""Wire surface of the observability plane: OP_METRICS + OP_OBS_DUMP.

Both are extra PeerServer ops on the replica's existing control port —
the same transport OP_STATUS rides — so scraping a production cluster
needs no new listener.  OP_METRICS answers the registry snapshot (with
daemon/persistence gauges refreshed at scrape time); OP_OBS_DUMP
answers the full hub dump (metrics + flight ring + span ring + the
wall/mono anchor the timeline renderer aligns on).
"""

from __future__ import annotations

import json
import socket
from typing import Optional

from apus_tpu.parallel import wire

OP_METRICS = 22
OP_OBS_DUMP = 23


def _refresh_daemon_gauges(daemon) -> None:
    """Mirror daemon/persistence scalars into the registry as gauges —
    the scattered OP_STATUS-only stats absorbed behind one namespace."""
    hub = daemon.obs
    if hub is None:
        return
    g = hub.registry.gauge
    g("daemon_persist_errors").set(getattr(daemon, "persist_errors", 0))
    g("daemon_persist_disabled").set(
        1 if getattr(daemon, "persist_disabled", False) else 0)
    p = getattr(daemon, "persistence", None)
    g("daemon_persist_syncs").set(getattr(p, "syncs", 0) if p else 0)
    g("daemon_compactions").set(getattr(p, "compactions", 0) if p else 0)
    g("daemon_compaction_floor").set(
        getattr(p, "compaction_floor", 0) if p else 0)
    g("daemon_store_records_since_base").set(
        getattr(p, "entries_since_base", 0) if p else 0)


def make_obs_ops(daemon) -> dict:
    """Extra PeerServer ops for a ReplicaDaemon with an ObsHub."""

    def metrics_op(r: wire.Reader) -> bytes:
        hub = daemon.obs
        if hub is None:
            return wire.u8(wire.ST_ERROR)
        with daemon.lock:
            _refresh_daemon_gauges(daemon)
            payload = {"replica": daemon.idx,
                       "role": daemon.node.role.name,
                       "term": daemon.node.current_term,
                       "metrics": hub.registry.snapshot()}
        return wire.u8(wire.ST_OK) + wire.blob(
            json.dumps(payload).encode())

    def dump_op(r: wire.Reader) -> bytes:
        hub = daemon.obs
        if hub is None:
            return wire.u8(wire.ST_ERROR)
        _refresh_daemon_gauges(daemon)
        d = hub.dump()
        d["replica"] = daemon.idx
        with daemon.lock:
            d["role"] = daemon.node.role.name
            d["term"] = daemon.node.current_term
        return wire.u8(wire.ST_OK) + wire.blob(json.dumps(d).encode())

    return {OP_METRICS: metrics_op, OP_OBS_DUMP: dump_op}


def _one_shot(addr: str, op: int, timeout: float) -> Optional[dict]:
    host, port = addr.rsplit(":", 1)
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as conn:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(timeout)
            conn.sendall(wire.frame(wire.u8(op)))
            resp = wire.read_frame(conn)
    except (OSError, ConnectionError, ValueError):
        return None
    if not resp or resp[0] != wire.ST_OK:
        return None
    try:
        return json.loads(wire.Reader(resp[1:]).blob().decode())
    except (ValueError, KeyError):
        return None


def fetch_metrics(addr: str, timeout: float = 2.0) -> Optional[dict]:
    """One OP_METRICS scrape: {"replica", "role", "term", "metrics"}
    or None when unreachable / obs disabled."""
    return _one_shot(addr, OP_METRICS, timeout)


def fetch_obs_dump(addr: str, timeout: float = 5.0) -> Optional[dict]:
    """One OP_OBS_DUMP fetch: the full hub dump, or None."""
    return _one_shot(addr, OP_OBS_DUMP, timeout)


def collect_cluster_dumps(peers: list[str],
                          timeout: float = 5.0) -> list[dict]:
    """Best-effort OP_OBS_DUMP across a peer table — the harnesses'
    failure-dump primitive (unreachable replicas are skipped; whatever
    answered is still a usable timeline)."""
    out = []
    for addr in peers:
        if not addr:
            continue
        d = fetch_obs_dump(addr, timeout=timeout)
        if d is not None:
            out.append(d)
    return out
