"""Wire surface of the observability plane: OP_METRICS + OP_OBS_DUMP.

Both are extra PeerServer ops on the replica's existing control port —
the same transport OP_STATUS rides — so scraping a production cluster
needs no new listener.  OP_METRICS answers the registry snapshot (with
daemon/persistence gauges refreshed at scrape time); OP_OBS_DUMP
answers the full hub dump (metrics + flight ring + span ring + the
wall/mono anchor the timeline renderer aligns on).
"""

from __future__ import annotations

import json
import socket
import time
from typing import Optional

from apus_tpu.parallel import wire

OP_METRICS = 22
OP_OBS_DUMP = 23


def _refresh_daemon_gauges(daemon) -> None:
    """Mirror daemon/persistence scalars into the registry as gauges —
    the scattered OP_STATUS-only stats absorbed behind one namespace."""
    hub = daemon.obs
    if hub is None:
        return
    g = hub.registry.gauge
    g("daemon_persist_errors").set(getattr(daemon, "persist_errors", 0))
    g("daemon_persist_disabled").set(
        1 if getattr(daemon, "persist_disabled", False) else 0)
    p = getattr(daemon, "persistence", None)
    g("daemon_persist_syncs").set(getattr(p, "syncs", 0) if p else 0)
    g("daemon_compactions").set(getattr(p, "compactions", 0) if p else 0)
    g("daemon_compaction_floor").set(
        getattr(p, "compaction_floor", 0) if p else 0)
    g("daemon_store_records_since_base").set(
        getattr(p, "entries_since_base", 0) if p else 0)
    # Multi-group dimension: per-group namespaced gauges
    # (``nodeg<gid>_*`` — term/commit/apply/end/is_leader/epoch per
    # consensus group), mirrored at scrape time like everything here.
    gs = getattr(daemon, "groupset", None)
    if gs is not None:
        gs.scrape_gauges(hub.registry)
    # Device-plane driver stats (per-daemon dict) mirrored as devd_*
    # gauges — the driver's half of the device telemetry; the runner's
    # half (dev_*) is merged from its own registry by _merged_snapshot.
    drv = getattr(daemon, "device_driver", None)
    if drv is not None:
        for k in ("rounds", "drained", "holes", "fallbacks",
                  "quorum_gated", "qfail_timeouts", "async_windows",
                  "partial_deferrals", "group_windows"):
            g(f"devd_{k}").set(drv.stats.get(k, 0))
    # Native data plane: the C loop's atomics mirrored as srv_native_*
    # gauges (the loop never holds the GIL, so it cannot touch the
    # registry itself).
    native = getattr(daemon, "native", None)
    if native is not None:
        native.sync_gauges(hub.registry)


def _merged_snapshot(daemon) -> dict:
    """Registry snapshot with the device runner's process-wide
    registry merged over it: in-process clusters share ONE runner, so
    every replica's scrape reports the same (true) device-plane
    numbers; the hub's pre-registered zeros keep the catalog reachable
    when no device plane is attached."""
    snap = daemon.obs.registry.snapshot()
    drv = getattr(daemon, "device_driver", None)
    runner = getattr(drv, "runner", None) if drv is not None else None
    rmetrics = getattr(runner, "metrics", None)
    if rmetrics is not None:
        snap.update(rmetrics.snapshot())
    return snap


def _metric_value(metrics: dict, name: str, default=0):
    rec = metrics.get(name)
    if not isinstance(rec, dict):
        return default
    return rec.get("value", rec.get("count", default))


def health_verdict(daemon, metrics: dict) -> dict:
    """Derived per-replica health summary: the degradation signals that
    otherwise hide in counter noise, folded into one scrapeable verdict
    (fuzz/soak assert on it at teardown so silent degradation fails
    loudly).  ``flags`` lists every degradation signal present;
    ``verdict`` is "ok" iff none fired.  Flags can be LEGITIMATE under
    injected faults (a chaos campaign expects fallbacks), so harnesses
    assert on the subset their fault schedule cannot explain —
    ``dev_recompiles`` is never explainable."""
    flags = []
    if _metric_value(metrics, "daemon_persist_disabled"):
        flags.append("persist_disabled")
    if _metric_value(metrics, "dev_recompiles"):
        flags.append("dev_recompiles")
    if _metric_value(metrics, "node_snap_push_abandoned"):
        flags.append("snap_push_abandoned")
    if _metric_value(metrics, "devd_qfail_timeouts"):
        flags.append("devplane_qfail_timeout")
    if _metric_value(metrics, "devd_fallbacks"):
        flags.append("devplane_fallbacks")
    if _metric_value(metrics, "node_delta_refused"):
        flags.append("delta_refused")
    if _metric_value(metrics, "node_snap_chunk_quarantines"):
        flags.append("snap_chunk_quarantines")
    uptime = time.monotonic() - getattr(daemon, "started_mono",
                                        time.monotonic())
    elections = _metric_value(metrics, "node_elections")
    return {
        "verdict": "ok" if not flags else "degraded",
        "flags": flags,
        "leader_flaps": elections,
        "leader_flap_rate_per_min": round(
            elections / (uptime / 60.0), 3) if uptime > 1.0 else 0.0,
        "persist_errors": _metric_value(metrics,
                                        "daemon_persist_errors"),
        "quorum_fail_rounds": _metric_value(metrics,
                                            "dev_quorum_fail_rounds"),
        "quorum_fail_streaks": _metric_value(metrics,
                                             "devd_qfail_timeouts"),
        "snap_push_abandons": _metric_value(metrics,
                                            "node_snap_push_abandoned"),
        "recompiles": _metric_value(metrics, "dev_recompiles"),
    }


def make_obs_ops(daemon) -> dict:
    """Extra PeerServer ops for a ReplicaDaemon with an ObsHub."""

    def metrics_op(r: wire.Reader) -> bytes:
        hub = daemon.obs
        if hub is None:
            return wire.u8(wire.ST_ERROR)
        with daemon.lock:
            _refresh_daemon_gauges(daemon)
            metrics = _merged_snapshot(daemon)
            payload = {"replica": daemon.idx,
                       "role": daemon.node.role.name,
                       "term": daemon.node.current_term,
                       "metrics": metrics,
                       "health": health_verdict(daemon, metrics)}
        return wire.u8(wire.ST_OK) + wire.blob(
            json.dumps(payload).encode())

    def dump_op(r: wire.Reader) -> bytes:
        hub = daemon.obs
        if hub is None:
            return wire.u8(wire.ST_ERROR)
        _refresh_daemon_gauges(daemon)
        d = hub.dump()
        d["replica"] = daemon.idx
        d["metrics"] = _merged_snapshot(daemon)
        d["health"] = health_verdict(daemon, d["metrics"])
        with daemon.lock:
            d["role"] = daemon.node.role.name
            d["term"] = daemon.node.current_term
        return wire.u8(wire.ST_OK) + wire.blob(json.dumps(d).encode())

    return {OP_METRICS: metrics_op, OP_OBS_DUMP: dump_op}


def _one_shot(addr: str, op: int, timeout: float) -> Optional[dict]:
    host, port = addr.rsplit(":", 1)
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as conn:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(timeout)
            conn.sendall(wire.frame(wire.u8(op)))
            resp = wire.read_frame(conn)
    except (OSError, ConnectionError, ValueError):
        return None
    if not resp or resp[0] != wire.ST_OK:
        return None
    try:
        return json.loads(wire.Reader(resp[1:]).blob().decode())
    except (ValueError, KeyError):
        return None


def fetch_metrics(addr: str, timeout: float = 2.0) -> Optional[dict]:
    """One OP_METRICS scrape: {"replica", "role", "term", "metrics"}
    or None when unreachable / obs disabled."""
    return _one_shot(addr, OP_METRICS, timeout)


def fetch_obs_dump(addr: str, timeout: float = 5.0) -> Optional[dict]:
    """One OP_OBS_DUMP fetch: the full hub dump, or None."""
    return _one_shot(addr, OP_OBS_DUMP, timeout)


def collect_cluster_dumps(peers: list[str],
                          timeout: float = 5.0) -> list[dict]:
    """Best-effort OP_OBS_DUMP across a peer table — the harnesses'
    failure-dump primitive (unreachable replicas are skipped; whatever
    answered is still a usable timeline)."""
    out = []
    for addr in peers:
        if not addr:
            continue
        d = fetch_obs_dump(addr, timeout=timeout)
        if d is not None:
            out.append(d)
    return out
