"""Per-op stage spans: where a client op's time went, hop by hop.

BENCH_r07's uncomfortable finding — the pipelined path is CPU-bound in
Python framing/dedup/locks, not roundtrip-bound — was reached by
process-of-elimination benchmarking.  This module makes that question
answerable directly: a SAMPLED client op (by req_id, default 1 in 64,
so every replica and the client pick the same ops with no propagated
flag) is timestamped at each hop of the replication path, the stamps
are kept in a bounded per-process ring, and at reply time the leader
folds the stage-to-stage durations into the metrics registry's log2
histograms — per-stage p50/p99 with no per-sample allocation.

Stage taxonomy (write path; the canonical order is STAGE_ORDER):

    client_send   client: request framed and handed to the socket
    ingest        server: burst read off the wire (FrameStream drain)
    lock          server: daemon node lock acquired for admission
    admit         leader: submit() returned (dedup + enqueue done)
    append        leader: entry holds a log index (group-commit drain)
    repl          leader: first replication fan-out shipping the index
    quorum        leader: commit advanced past the index (quorum ack)
    apply         every replica: the entry applied to the SM
    fsync         leader: the drain window's batch fdatasync covered it
    reply         leader: reply bytes built for the flush
    client_reply  client: reply frame parsed
    follower_append  follower: one-sided log write landed the index
    dev_dispatch / dev_ready  device plane: window dispatched/resolved
                     (idx-range ring events, not per-op stamps)

Stage durations are named for the later stamp of each adjacent pair
(STAGE_DURATIONS); their per-op sum telescopes to reply - ingest,
which is also observed as ``op_server_us`` — so summed stage p50s
land within a few percent of the end-to-end p50 by construction.

Timestamps are monotonic µs (comparable within a process; the ObsHub
dump carries a wall/mono anchor so cross-process timelines align on
wall time).  All mutation takes a small internal lock — acceptable
because only sampled ops (1/64) ever reach it; the UNSAMPLED fast path
is a single ``req_id & mask`` test.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from apus_tpu.obs.metrics import MetricsRegistry

STAGE_ORDER = ("client_send", "ingest", "lock", "admit", "append",
               "repl", "quorum", "apply", "fsync", "reply",
               "client_reply")

#: duration name of each adjacent (earlier-stage -> later-stage) pair,
#: keyed by the LATER stage; observed into ``stage_<name>_us``.
STAGE_DURATIONS = {
    "lock": "lock_wait",
    "admit": "dedup_admit",
    "append": "append",
    "repl": "repl_fanout",
    "quorum": "quorum_ack",
    "apply": "apply",
    "fsync": "fsync",
    "reply": "reply_flush",
    "client_reply": "wire_out",
}

_ORDER_IDX = {s: i for i, s in enumerate(STAGE_ORDER)}


def now_us() -> int:
    return time.monotonic_ns() // 1000


class SpanRecorder:
    """Sampled per-op stage stamps + bounded event ring.

    ``sample_period`` must be a power of two (rounded up otherwise);
    an op is sampled iff ``req_id & (period - 1) == 0``.  Client
    req_ids are per-client monotone from 1, so period 64 samples every
    64th op of every client — and every process (client, leader,
    followers) independently selects the SAME ops."""

    OPEN_CAP = 1024

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 sample_period: int = 64, capacity: int = 8192):
        self._reg = registry
        period = max(1, int(sample_period))
        if period & (period - 1):
            period = 1 << period.bit_length()
        self.sample_period = period
        self._mask = period - 1
        self.capacity = max(16, int(capacity))
        self._lock = threading.Lock()
        self._ring: list = [None] * self.capacity
        self._seq = 0
        self.dropped = 0
        # (clt_id, req_id) -> {"stamps": {stage: t_us}, "idx", "term"}
        self._open: dict[tuple, dict] = {}

    # -- the hot-path gate -------------------------------------------------

    def sampled(self, req_id: int) -> bool:
        return (req_id & self._mask) == 0

    @staticmethod
    def now() -> int:
        return now_us()

    # -- stamping ----------------------------------------------------------

    def _push(self, ev: tuple) -> None:
        # Caller holds self._lock.
        if self._seq >= self.capacity:
            self.dropped += 1
        self._ring[self._seq % self.capacity] = ev
        self._seq += 1

    def stamp(self, clt_id: int, req_id: int, stage: str,
              t: Optional[int] = None, idx: Optional[int] = None,
              term: Optional[int] = None, open_new: bool = True) -> None:
        """Record one stage stamp for a sampled op.  ``open_new=False``
        (follower-side stages) rings the event without tracking the op
        in the open table — followers never see the reply, so their
        opens would leak."""
        if t is None:
            t = now_us()
        key = (clt_id, req_id)
        with self._lock:
            self._push((t, clt_id, req_id, stage, idx, term, None))
            o = self._open.get(key)
            if o is None:
                if not open_new:
                    return
                if len(self._open) >= self.OPEN_CAP:
                    # Evict the oldest abandoned op (lost leadership,
                    # dead client): bounded memory beats completeness.
                    self._open.pop(next(iter(self._open)))
                o = self._open[key] = {"stamps": {}, "idx": idx,
                                       "term": term}
            o["stamps"].setdefault(stage, t)
            if idx is not None:
                o["idx"] = idx
            if term is not None:
                o["term"] = term

    def stamp_range(self, stage: str, lo: int, hi: int,
                    t: Optional[int] = None,
                    term: Optional[int] = None) -> None:
        """Stamp ``stage`` on every OPEN op whose log index falls in
        [lo, hi) and lacks it — window-granular events (replication
        fan-out, quorum ack) attributed to the sampled ops they
        carried.  O(open) = O(sampled in flight), a handful."""
        if lo >= hi:
            return
        if t is None:
            t = now_us()
        with self._lock:
            for (clt_id, req_id), o in self._open.items():
                oidx = o.get("idx")
                if oidx is None or not (lo <= oidx < hi) \
                        or stage in o["stamps"]:
                    continue
                o["stamps"][stage] = t
                self._push((t, clt_id, req_id, stage, oidx,
                            term if term is not None else o.get("term"),
                            None))

    def stamp_have(self, stage: str, require: str,
                   t: Optional[int] = None) -> None:
        """Stamp ``stage`` on every open op that already carries stamp
        ``require`` but not ``stage`` (e.g. fsync covers everything
        applied this drain window)."""
        if t is None:
            t = now_us()
        with self._lock:
            for (clt_id, req_id), o in self._open.items():
                st = o["stamps"]
                if require in st and stage not in st:
                    st[stage] = t
                    self._push((t, clt_id, req_id, stage, o.get("idx"),
                                o.get("term"), None))

    def window_event(self, stage: str, lo: int, hi: int,
                     t: Optional[int] = None) -> None:
        """Ring-only idx-range event (device dispatch/ready): no open
        table, stitched into timelines by index overlap."""
        if t is None:
            t = now_us()
        with self._lock:
            self._push((t, 0, 0, stage, lo, None, hi))

    # -- completion --------------------------------------------------------

    def finish(self, clt_id: int, req_id: int) -> Optional[dict]:
        """Close a sampled op: fold its stage-to-stage durations into
        the registry histograms (``stage_<name>_us``) plus the
        telescoped server end-to-end (``op_server_us``).  Returns the
        stamps dict (tests/bench stitching) or None if unknown."""
        with self._lock:
            o = self._open.pop((clt_id, req_id), None)
        if o is None:
            return None
        if self._reg is not None:
            stamps = o["stamps"]
            present = sorted((s for s in stamps if s in _ORDER_IDX),
                             key=_ORDER_IDX.__getitem__)
            for a, b in zip(present, present[1:]):
                name = STAGE_DURATIONS.get(b)
                if name is None:
                    continue
                self._reg.histogram(f"stage_{name}_us").observe(
                    max(0, stamps[b] - stamps[a]))
            if "ingest" in stamps and "reply" in stamps:
                self._reg.histogram("op_server_us").observe(
                    max(0, stamps["reply"] - stamps["ingest"]))
            if "client_send" in stamps and "client_reply" in stamps:
                self._reg.histogram("op_client_us").observe(
                    max(0, stamps["client_reply"]
                        - stamps["client_send"]))
        return o

    # -- export ------------------------------------------------------------

    def events(self) -> list[dict]:
        """Chronological snapshot of the ring as JSON-able dicts."""
        with self._lock:
            n = min(self._seq, self.capacity)
            start = self._seq - n
            evs = [self._ring[(start + i) % self.capacity]
                   for i in range(n)]
        out = []
        for ev in evs:
            if ev is None:
                continue
            t, clt_id, req_id, stage, idx, term, hi = ev
            d = {"t_us": t, "clt": clt_id, "req": req_id,
                 "stage": stage}
            if idx is not None:
                d["idx"] = idx
            if term is not None:
                d["term"] = term
            if hi is not None:
                d["hi"] = hi
            out.append(d)
        return out

    def open_count(self) -> int:
        with self._lock:
            return len(self._open)
