"""Cross-replica timeline: merge OP_OBS_DUMP rings and render them.

    python -m apus_tpu.obs.timeline DUMP.json [DUMP2.json ...]
    python -m apus_tpu.obs.timeline --addrs host:p0,host:p1 [-o DIR]

Every per-replica dump carries monotonic-µs event stamps plus one
wall/mono anchor; merging converts each event to wall time
(ev_mono + (anchor_wall - anchor_mono)), so rings from different
processes interleave correctly to within NTP-class skew — on one host
(the harnesses' shape) they are microsecond-comparable.

Two event kinds interleave:

- flight events (role/term changes, CONFIG applies, lease grant/lapse,
  snapshot stream begin/resume/end, fault injections, watchdog fires),
- span stamps (sampled per-op stage hops), additionally STITCHED into
  per-op groups keyed by (clt_id, req_id) and labeled with the op's
  (term, idx) once known — the cross-replica trace of one client op.

This module is also the harnesses' failure-dump library:
``write_dump(dir, dumps)`` persists the raw dumps + rendered timeline
(fuzz/soak call it when a violation or wedge ships a repro).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional


def _wall(ev_t_us: int, anchor: dict) -> int:
    return ev_t_us + (anchor.get("wall_us", 0)
                      - anchor.get("mono_us", 0))


def merge_dumps(dumps: list[dict]) -> list[dict]:
    """Flatten per-replica dumps into one wall-clock-sorted event list.
    Each event gains ``wall_us``, ``src`` (replica ident) and ``kind``
    ("flight" | "span")."""
    merged = []
    for d in dumps:
        anchor = d.get("anchor", {})
        src = d.get("ident") or f"r{d.get('replica', '?')}"
        for ev in d.get("flight", []):
            e = dict(ev)
            e["wall_us"] = _wall(ev.get("t_us", 0), anchor)
            e["src"] = src
            e["kind"] = "flight"
            merged.append(e)
        for ev in d.get("spans", []):
            e = dict(ev)
            e["wall_us"] = _wall(ev.get("t_us", 0), anchor)
            e["src"] = src
            # Device window events (dev_dispatch/dev_ready) are
            # idx-RANGE events, not per-op stamps: they ride the span
            # ring with req=0 and an exclusive upper index in "hi".
            # Tag them "dev" so the renderer and the stitcher treat
            # them as windows, interleaved with host spans.
            e["kind"] = "dev" if ev.get("hi") is not None else "span"
            merged.append(e)
    merged.sort(key=lambda e: e["wall_us"])
    return merged


def stitch_ops(merged: list[dict],
               attach_device: bool = True) -> dict:
    """Group span stamps by (clt_id, req_id) across every source —
    the cross-replica trace of one sampled client op.  Returns
    {(clt, req): {"term", "idx", "stamps": [event...]}} with stamps in
    wall order.  With ``attach_device`` (default), device window
    events whose [idx, hi) range covers an op's log index are STITCHED
    into that op's hop chain — the device dispatch/ready hops of the
    window that carried the op, interleaved at their wall position."""
    ops: dict = {}
    for ev in merged:
        if ev.get("kind") != "span" or not ev.get("req"):
            continue
        key = (ev.get("clt", 0), ev["req"])
        o = ops.setdefault(key, {"term": None, "idx": None,
                                 "stamps": []})
        o["stamps"].append(ev)
        if ev.get("idx") is not None:
            o["idx"] = ev["idx"]
        if ev.get("term") is not None:
            o["term"] = ev["term"]
    if attach_device:
        attach_device_windows(ops, merged)
    return ops


def attach_device_windows(ops: dict, merged: list[dict]) -> None:
    """Interleave device window events into the stitched per-op
    chains: a dev event covers every op whose log index falls in
    [ev["idx"], ev["hi"]).  First covering event per (op, stage) wins
    (the dispatch that actually carried the index); stamps are
    re-sorted so the chain stays in wall order."""
    devs = [ev for ev in merged if ev.get("kind") == "dev"
            and ev.get("idx") is not None]
    if not devs:
        return
    for o in ops.values():
        idx = o.get("idx")
        if idx is None:
            continue
        seen = {s.get("stage") for s in o["stamps"]}
        touched = False
        for ev in devs:
            if ev["idx"] <= idx < ev.get("hi", ev["idx"]) \
                    and ev.get("stage") not in seen:
                o["stamps"].append(ev)
                seen.add(ev.get("stage"))
                touched = True
        if touched:
            o["stamps"].sort(key=lambda e: e.get("wall_us", 0))


def render(merged: list[dict], last_s: Optional[float] = None,
           max_events: int = 2000) -> str:
    """Human-readable timeline, relative to the last event ("-12.345ms"
    = that long before the end — the shape of a black-box readout)."""
    if not merged:
        return "(no events)\n"
    if last_s is not None:
        cutoff = merged[-1]["wall_us"] - int(last_s * 1e6)
        merged = [e for e in merged if e["wall_us"] >= cutoff]
    if len(merged) > max_events:
        merged = merged[-max_events:]
    end = merged[-1]["wall_us"]
    lines = []
    for ev in merged:
        dt_ms = (ev["wall_us"] - end) / 1000.0
        src = ev.get("src", "?")
        if ev.get("kind") == "dev":
            lines.append(
                f"[{dt_ms:>10.3f}ms] {src:<6} dev    "
                f"{ev.get('stage', '?'):<16} "
                f"idx=[{ev.get('idx')},{ev.get('hi')})")
        elif ev.get("kind") == "span":
            extra = " ".join(
                f"{k}={ev[k]}" for k in ("req", "idx", "term", "hi")
                if ev.get(k) is not None)
            lines.append(f"[{dt_ms:>10.3f}ms] {src:<6} span   "
                         f"{ev.get('stage', '?'):<16} {extra}")
        else:
            extra = " ".join(
                f"{k}={v}" for k, v in sorted(ev.items())
                if k not in ("t_us", "wall_us", "src", "kind", "cat",
                             "msg"))
            msg = ev.get("msg", "")
            lines.append(f"[{dt_ms:>10.3f}ms] {src:<6} flight "
                         f"{ev.get('cat', '?'):<16} {msg} {extra}"
                         .rstrip())
    ops = stitch_ops(merged)
    if ops:
        lines.append("")
        lines.append(f"-- {len(ops)} sampled op(s) stitched "
                     f"(clt/req -> term,idx: stage@src...) --")
        for (clt, req), o in sorted(ops.items(),
                                    key=lambda kv: kv[1]["stamps"][0]
                                    ["wall_us"]):
            hops = " -> ".join(
                f"{s.get('stage')}@{s.get('src')}"
                for s in o["stamps"])
            lines.append(f"  req={req} clt={clt & 0xFFFF:04x} "
                         f"term={o['term']} idx={o['idx']}: {hops}")
    return "\n".join(lines) + "\n"


def write_dump(out_dir: str, dumps: list[dict],
               tag: str = "obs") -> str:
    """Persist raw dumps + rendered timeline; returns the timeline
    path.  The harnesses' failure-dump entry point."""
    os.makedirs(out_dir, exist_ok=True)
    raw = os.path.join(out_dir, f"{tag}-dumps.json")
    with open(raw, "w") as f:
        json.dump({"dumps": dumps}, f)
    txt = os.path.join(out_dir, f"{tag}-timeline.txt")
    with open(txt, "w") as f:
        f.write(render(merge_dumps(dumps)))
    return txt


def load_dumps(path: str) -> list[dict]:
    """Load one dump file: a bare per-replica dump, a list of them, or
    the ``write_dump`` envelope."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and "dumps" in data:
        return list(data["dumps"])
    if isinstance(data, list):
        return data
    return [data]


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apus_tpu.obs.timeline",
        description="Merge + render cross-replica observability dumps.")
    ap.add_argument("files", nargs="*",
                    help="dump JSON files (from OP_OBS_DUMP fetches or "
                         "a harness failure dump)")
    ap.add_argument("--addrs", default="",
                    help="fetch live dumps from these replica "
                         "endpoints (comma-separated host:port)")
    ap.add_argument("--last", type=float, default=None,
                    help="render only the last N seconds")
    ap.add_argument("-o", "--out", default=None,
                    help="also persist raw dumps + timeline into this "
                         "directory")
    args = ap.parse_args(argv)

    dumps: list[dict] = []
    for path in args.files:
        dumps.extend(load_dumps(path))
    if args.addrs:
        from apus_tpu.obs.service import collect_cluster_dumps
        dumps.extend(collect_cluster_dumps(
            [a for a in args.addrs.split(",") if a]))
    if not dumps:
        print("no dumps (give files and/or --addrs)", file=sys.stderr)
        return 1
    if args.out:
        path = write_dump(args.out, dumps)
        print(f"wrote {path}", file=sys.stderr)
    sys.stdout.write(render(merge_dumps(dumps), last_s=args.last))
    return 0


if __name__ == "__main__":
    sys.exit(main())
