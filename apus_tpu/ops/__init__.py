"""JAX device data plane: the consensus hot path as jitted mesh steps.

This package is the TPU re-expression of the reference's RDMA data plane
(src/dare/dare_ibv_rc.c).  The mapping (BASELINE.json north star):

| reference (RDMA)                          | here (JAX/XLA on ICI)        |
|-------------------------------------------|------------------------------|
| leader RDMA WRITEs entries into followers'| pmax broadcast of the        |
| logs (update_remote_logs :1460-1644)      | batch over the replica axis  |
| followers poke 1-byte acks into the       | per-replica ack index,       |
| leader's entry reply[] (:1828-1863)       | all_gather'ed                |
| leader spin-polls reply[] for quorum      | closed-form quorum over the  |
| (:1650-1758, loop_for_commit :1883-1945)  | gathered ack vector — the    |
|                                           | collective IS the barrier    |
| QP-reset fencing (:2156-2255)             | in-step term/grant masking   |
| LogGP microbenchmark (:3322-3749)         | benchmarks/loggp.py probe    |

All state lives in HBM as fixed-width arrays sharded over a ``replica``
mesh axis (ops.logplane).  One ``commit_step`` call performs: scatter of
a 64-entry batch, fence check, slot writes, quorum reduction, and commit
advance — entirely inside XLA, no host round-trips mid-protocol.
"""

from apus_tpu.ops.mesh import replica_mesh
from apus_tpu.ops.logplane import DeviceLog, make_device_log
from apus_tpu.ops.commit import (CommitControl, build_commit_step,
                                 build_pipelined_commit_step,
                                 build_pipelined_commit_step_fused)

__all__ = ["replica_mesh", "DeviceLog", "make_device_log",
           "build_commit_step", "build_pipelined_commit_step",
           "build_pipelined_commit_step_fused", "CommitControl"]
