"""The jitted consensus commit step — the north-star hot path.

One call replicates a batch of log entries from the leader to every
replica, fences stale writers, collects acknowledgements, evaluates the
(possibly dual-) majority commit rule, and advances commit offsets —
entirely inside a single XLA program over the replica mesh axis.  This
collapses the reference's whole commit machinery — the adjust/update/
poll ``loop_for_commit`` (dare_ibv_rc.c:1870-1948), per-entry remote ack
bytes (:1828-1863) and quorum scan (:1650-1758) — into the synchronous
semantics of collectives: when the step returns, the batch IS committed
(or the quorum wasn't reachable and commit simply doesn't advance;
retries are a host-control-plane decision).

Collective choreography (per replica shard):
1. batch broadcast: the input batch rows are nonzero only on the leader's
   replica row, so an elementwise ``pmax`` over the replica axis IS the
   leader->all scatter (one ICI collective; the RDMA-WRITE fan-out
   analog, update_remote_logs dare_ibv_rc.c:1460-1644).
2. fence mask: a replica accepts the write only if its ``(granted_to,
   fence_term)`` admits the claimed leader+term — the in-step
   re-expression of QP-reset fencing (dare_ibv_rc.c:2156-2255) — and the
   batch extends its log contiguously (divergence repair happens on the
   host path, not here).
3. slot write: accepted rows scatter into ``idx % n_slots`` positions
   (static shapes; no wrap-around splitting).
4. ack + quorum: each replica's new ``end`` is its ack index;
   ``all_gather`` yields the ack vector, and the commit index is the
   largest candidate with majority support in the old config mask and —
   during TRANSIT — the new mask too (dual-majority,
   dare_ibv_rc.c:2799-2957).

The mesh axis size may be smaller than the replica count (e.g. a
single-chip bench folds all replicas onto one device): the body operates
on a block of ``K = R / axis_size`` replica rows, reducing locally over
the block before the cross-device collective, so the same program text
serves 1-chip benches, 8-device CPU test meshes, and real multi-chip.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apus_tpu.core.cid import Cid, CidState
from apus_tpu.core.quorum import quorum_size
from apus_tpu.ops.logplane import (FENCE_GRANTED, FENCE_TERM, META_COLS,
                                   OFF_COMMIT, OFF_END, DeviceLog)
from apus_tpu.ops.mesh import REPLICA_AXIS, shard_map


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CommitControl:
    """Replicated control scalars for one commit step.

    ``mask_old``/``mask_new`` are [R] 0/1 membership vectors; ``q_new=0``
    means single-majority (STABLE/EXTENDED), nonzero means TRANSIT
    dual-majority.
    """

    leader: jax.Array    # i32 scalar
    term: jax.Array      # i32 scalar
    end0: jax.Array      # i32 scalar: first index of the batch
    mask_old: jax.Array  # [R] i32
    mask_new: jax.Array  # [R] i32
    q_old: jax.Array     # i32 scalar
    q_new: jax.Array     # i32 scalar

    @staticmethod
    def from_cid(cid: Cid, n_replicas: int, leader: int, term: int,
                 end0: int) -> "CommitControl":
        mask_old = np.array([1 if (cid.contains(i) and i < cid.size) else 0
                             for i in range(n_replicas)], np.int32)
        if cid.state == CidState.TRANSIT:
            mask_new = np.array(
                [1 if (cid.contains(i) and i < cid.new_size) else 0
                 for i in range(n_replicas)], np.int32)
            q_new = quorum_size(cid.new_size)
        else:
            mask_new = np.zeros(n_replicas, np.int32)
            q_new = 0
        i32 = lambda v: jnp.asarray(v, jnp.int32)
        return CommitControl(i32(leader), i32(term), i32(end0),
                             jnp.asarray(mask_old), jnp.asarray(mask_new),
                             i32(quorum_size(cid.size)), i32(q_new))


def _commit_body(log_data, log_meta, offs, fence, bdata, bmeta, ctrl,
                 *, batch: int, n_slots: int, verify_round: bool = False):
    """Per-shard body.  Shapes: log_data [K,S+B,SB], log_meta [K,S+B,6],
    offs [K,4], fence [K,2], bdata [K,B,SB], bmeta [K,B,4].

    The batch is always a full B entries (short batches arrive NOOP-
    padded), end0 is batch-aligned ((end0-1) % B == 0) and S % B == 0,
    so the write is ONE contiguous dynamic_update_slice per array;
    replicas that reject the batch (fence/contiguity) redirect the slice
    into the scratch rows [S, S+B) instead of predicating per-row —
    see ops.logplane docstring for why this matters on TPU.

    ``verify_round``: in MULTI-CONTROLLER deployments (one process per
    replica, apus_tpu.runtime.mesh_plane) each process supplies its own
    ``ctrl`` from a descriptor it received over the control plane.  If a
    deposed leader and a new leader dispatch concurrently, the backend
    pairs their (byte-identical) programs by arrival order, so one
    collective can mix two different logical rounds — the broadcast
    payload would then be an elementwise max of two leaders' batches.
    The round-identity check all-gathers each participant's claimed
    (term, leader, end0) and refuses the write everywhere unless all
    agree — the in-step analog of the QP-reset fencing the reference
    uses to physically block a deposed leader's RDMA writes
    (dare_ibv_rc.c:2156-2255).  Single-controller callers pass one ctrl
    to every shard, so the check is vacuous there (default off)."""
    K, rows, SB = log_data.shape
    S, B = n_slots, batch
    a = lax.axis_index(REPLICA_AXIS)
    rid = a * K + jnp.arange(K, dtype=jnp.int32)            # [K] global ids
    is_leader = rid == ctrl.leader                          # [K]

    # (1) leader->all batch broadcast via pmax.  Host contract
    # (place_batch): non-leader rows of bdata/bmeta are all-zero, and
    # payloads are unsigned — so a plain max-reduce over the block plus a
    # pmax over the axis IS the leader's batch.  (No mask multiply: a
    # [K,1,1]-broadcast mask over the u8 batch lowers ~3000x slower than
    # the pure reduce on v5e.)
    bcast_d = lax.pmax(jnp.max(bdata, axis=0), REPLICA_AXIS)   # [B,SB]
    bcast_m = lax.pmax(jnp.max(bmeta, axis=0), REPLICA_AXIS)   # [B,4]

    # (2) fence + contiguity mask.
    fence_ok = ((fence[:, FENCE_GRANTED] == ctrl.leader)
                & (ctrl.term >= fence[:, FENCE_TERM])) | is_leader
    own_end = offs[:, OFF_END]                              # [K]
    contig = own_end == ctrl.end0
    do_write = fence_ok & contig                            # [K]
    if verify_round:
        # Round-identity agreement (see docstring): every participant
        # must claim the same (term, leader, end0) or nobody writes and
        # the round decides nothing (commit sentinel 0).
        ident = jnp.stack([ctrl.term, ctrl.leader, ctrl.end0])   # [3]
        idents = lax.all_gather(ident, REPLICA_AXIS)       # [axis,3]
        coherent = jnp.all(idents == ident[None])
        do_write = do_write & coherent

    # (3) slot writes: one contiguous span per replica row; rejected
    # writes land in the scratch region.
    span = (ctrl.end0 - 1) % S                              # aligned start
    start = jnp.where(do_write, span, S)                    # [K]
    j = jnp.arange(B, dtype=jnp.int32)
    entry_idx = ctrl.end0 + j                               # [B]
    fresh_meta = jnp.stack([
        entry_idx,
        jnp.full((B,), ctrl.term, jnp.int32),
        bcast_m[:, 0], bcast_m[:, 1], bcast_m[:, 2], bcast_m[:, 3],
    ], axis=-1)                                             # [B,6]
    # Unrolled over the replica block (K <= MAX_SERVER_COUNT = 13): a
    # vmap'd DUS with varying starts lowers to scatter, which is ~1000x
    # slower on TPU than K plain dynamic_update_slice ops.
    zero = jnp.int32(0)
    for k in range(K):
        log_data = lax.dynamic_update_slice(
            log_data, bcast_d[None], (jnp.int32(k), start[k], zero))
        log_meta = lax.dynamic_update_slice(
            log_meta, fresh_meta[None], (jnp.int32(k), start[k], zero))

    # (4) acks + quorum.
    new_end = jnp.where(do_write, ctrl.end0 + B, own_end)   # [K]
    acks = lax.all_gather(new_end, REPLICA_AXIS).reshape(-1)          # [R]
    leader_ack = ctrl.end0 + B
    cand = jnp.minimum(acks, leader_ack)                    # [R]
    ge = acks[None, :] >= cand[:, None]                     # [R,R]
    n_old = jnp.sum(ge * ctrl.mask_old[None, :], axis=1)
    n_new = jnp.sum(ge * ctrl.mask_new[None, :], axis=1)
    ok = (n_old >= ctrl.q_old) & ((ctrl.q_new == 0) | (n_new >= ctrl.q_new))
    member_any = (ctrl.mask_old | ctrl.mask_new) == 1
    commit_global = jnp.max(jnp.where(ok & member_any, cand, 0))
    if verify_round:
        commit_global = jnp.where(coherent, commit_global, 0)

    # (5) advance offsets (monotone; clamped to own end).  A replica only
    # advances commit if it ACCEPTED this batch: the Raft clamp
    # min(leaderCommit, lastNewEntry) is safe only after the consistency
    # check passes — a fenced/divergent replica must wait for host-side
    # log adjustment, or it could mark conflicting entries committed.
    own_commit = offs[:, OFF_COMMIT]
    new_commit = jnp.where(
        do_write,
        jnp.maximum(own_commit, jnp.minimum(commit_global, new_end)),
        own_commit)
    offs = offs.at[:, OFF_END].set(new_end)
    offs = offs.at[:, OFF_COMMIT].set(new_commit)
    return log_data, log_meta, offs, fence, acks, commit_global


def _check_geometry(mesh: Mesh, n_replicas: int, n_slots: int,
                    batch: int) -> None:
    axis_size = mesh.shape[REPLICA_AXIS]
    if n_replicas % axis_size != 0:
        raise ValueError(f"{n_replicas} replicas on {axis_size}-wide mesh")
    if n_slots % batch != 0:
        raise ValueError(f"n_slots ({n_slots}) must be a multiple of "
                         f"batch ({batch})")


def _assert_devlog_geometry(devlog: DeviceLog, n_slots: int,
                            slot_bytes: int, batch: int) -> None:
    assert devlog.data.shape[1:] == (n_slots + batch, slot_bytes), \
        f"devlog geometry {devlog.data.shape} != step geometry " \
        f"({n_slots}+{batch}, {slot_bytes})"


def build_commit_step(mesh: Mesh, n_replicas: int, n_slots: int,
                      slot_bytes: int, batch: int, auto_advance: bool = False,
                      verify_round: bool = False):
    """Compile-ready commit step bound to a mesh + static geometry.

    Returns ``step(devlog, batch_data [R,B,SB] u8, batch_meta [R,B,4] i32,
    ctrl: CommitControl) -> (devlog', acks [R] i32, commit i32)``.
    ``batch_data``/``batch_meta`` rows must be zero except the leader's.

    Every step appends a full batch of B entries (short batches are
    NOOP-padded — zero meta rows already encode NOOP), and ``ctrl.end0``
    must be batch-aligned: ``(end0 - 1) % batch == 0``.  The input devlog
    is donated (in-place HBM update).

    With ``auto_advance=True`` the step additionally returns a rolled-
    forward control block (``end0 += B``) so a steady-state pipeline can
    loop device-side values without host reconstruction.

    ``verify_round=True`` adds the multi-controller round-identity check
    (see ``_commit_body``) — required whenever different processes
    supply their own ``ctrl`` (runtime.mesh_plane).
    """
    _check_geometry(mesh, n_replicas, n_slots, batch)
    body = functools.partial(_commit_body, batch=batch, n_slots=n_slots,
                             verify_round=verify_round)
    sharded = P(REPLICA_AXIS)
    repl = P()
    ctrl_specs = CommitControl(*([repl] * 7))
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(sharded, sharded, sharded, sharded, sharded, sharded,
                  ctrl_specs),
        out_specs=(sharded, sharded, sharded, sharded, repl, repl))

    @functools.partial(jax.jit, donate_argnums=0)
    def step(devlog: DeviceLog, batch_data, batch_meta, ctrl: CommitControl):
        _assert_devlog_geometry(devlog, n_slots, slot_bytes, batch)
        d, m, o, f, acks, commit = fn(devlog.data, devlog.meta, devlog.offs,
                                      devlog.fence, batch_data, batch_meta,
                                      ctrl)
        out = DeviceLog(d, m, o, f), acks, commit
        if auto_advance:
            nxt = dataclasses.replace(ctrl, end0=ctrl.end0 + batch)
            return out + (nxt,)
        return out

    return step


def build_pipelined_commit_step(mesh: Mesh, n_replicas: int, n_slots: int,
                                slot_bytes: int, batch: int, depth: int,
                                staged_depth: int | None = None,
                                verify_round: bool = False,
                                donate: bool = True):
    """Device-resident pipelined commit: ``depth`` consecutive commit
    rounds execute inside ONE XLA program (a ``lax.scan`` over staged
    batches), so host dispatch cost is paid once per ``depth`` rounds.

    This is the TPU re-expression of the reference's pipelining — many
    outstanding unsignaled WRs with selective signaling (post_send,
    dare_ibv_rc.c:2552-2568): the RDMA path overlaps rounds by keeping
    the NIC queue full; the XLA path overlaps them by keeping the whole
    round loop on-device.  Semantics per round are identical to
    ``build_commit_step`` (same body), with ``end0`` rolled forward
    round over round.

    Returns ``step(devlog, staged_data [SD,R,B,SB] u8, staged_meta
    [SD,R,B,4] i32, ctrl) -> (devlog', commits [D] i32, ctrl')`` where
    ``commits[i]`` is the global commit index after round i and ``ctrl'``
    has ``end0`` advanced by ``D*B`` (steady-state loops feed it back).

    ``staged_depth`` (SD, default = depth) is how many distinct staged
    batches are provided; round i consumes batch ``i % SD``.  SD=1 with
    a large depth is the steady-state throughput shape: one resident
    batch re-committed round after round with no staging cost.

    ``donate=False`` keeps the input devlog's buffers VALID after the
    call (one extra ring resident transiently).  Multi-threaded
    drivers whose shard readers run concurrently with dispatch need
    this: with donation, a reader must either risk materializing a
    deleted buffer or hold the driver lock across an unbounded device
    sync (runtime.mesh_plane).
    """
    staged_depth = depth if staged_depth is None else staged_depth
    _check_geometry(mesh, n_replicas, n_slots, batch)
    # The identity check is loop-invariant, so it is hoisted out of the
    # scan: one tiny all_gather per WINDOW (rounds share the dispatch's
    # descriptor).  On incoherence, leader=-2 fails both the is_leader
    # and fence tests on every shard (no row writes anywhere), AND the
    # per-round commit outputs are zeroed — the ack gather mixes devlog
    # generations in a mismatched pairing, so its quorum boundary is
    # meaningless and must not be adopted.
    body = functools.partial(_commit_body, batch=batch, n_slots=n_slots)

    def _round_coherent(ctrl):
        ident = jnp.stack([ctrl.term, ctrl.leader, ctrl.end0])
        idents = lax.all_gather(ident, REPLICA_AXIS)
        return jnp.all(idents == ident[None])

    sharded = P(REPLICA_AXIS)
    staged = P(None, REPLICA_AXIS)
    repl = P()
    ctrl_specs = CommitControl(*([repl] * 7))

    def pipe(log_data, log_meta, offs, fence, sdata, smeta, ctrl):
        if verify_round:
            coherent = _round_coherent(ctrl)
            ctrl = dataclasses.replace(
                ctrl, leader=jnp.where(coherent, ctrl.leader, jnp.int32(-2)))

        def one(carry, i):
            log_data, log_meta, offs, fence, ctrl = carry
            bdata = lax.dynamic_index_in_dim(sdata, i % staged_depth,
                                             axis=0, keepdims=False)
            bmeta = lax.dynamic_index_in_dim(smeta, i % staged_depth,
                                             axis=0, keepdims=False)
            log_data, log_meta, offs, fence, _, commit = body(
                log_data, log_meta, offs, fence, bdata, bmeta, ctrl)
            ctrl = dataclasses.replace(ctrl, end0=ctrl.end0 + batch)
            return (log_data, log_meta, offs, fence, ctrl), commit
        (log_data, log_meta, offs, fence, ctrl), commits = lax.scan(
            one, (log_data, log_meta, offs, fence, ctrl),
            jnp.arange(depth, dtype=jnp.int32))
        if verify_round:
            commits = jnp.where(coherent, commits, 0)
        return log_data, log_meta, offs, fence, commits, ctrl

    fn = shard_map(
        pipe, mesh=mesh,
        in_specs=(sharded, sharded, sharded, sharded, staged, staged,
                  ctrl_specs),
        out_specs=(sharded, sharded, sharded, sharded, repl, ctrl_specs))

    @functools.partial(jax.jit,
                       **({"donate_argnums": 0} if donate else {}))
    def step(devlog: DeviceLog, staged_data, staged_meta,
             ctrl: CommitControl):
        _assert_devlog_geometry(devlog, n_slots, slot_bytes, batch)
        assert staged_data.shape[0] == staged_depth
        d, m, o, f, commits, ctrl = fn(devlog.data, devlog.meta,
                                       devlog.offs, devlog.fence,
                                       staged_data, staged_meta, ctrl)
        return DeviceLog(d, m, o, f), commits, ctrl

    return step


_PALLAS_PROBED: bool | None = None


def _pallas_ring_mode(mode: str, batch: int, slot_bytes: int,
                      mesh: Mesh) -> str:
    """Resolve the fused step's pallas knob to 'compiled', 'interpret',
    or 'off'.  'auto' requires the MESH devices to be TPUs (the
    in-place blocked ring kernel needs Mosaic; a CPU mesh in a
    TPU-default process must not resolve to 'compiled') and then probes
    once per process; elsewhere the XLA select path is used."""
    if mode not in ("auto", "off", "interpret", "compiled"):
        raise ValueError(f"bad pallas_mode {mode!r}")
    from apus_tpu.ops import pallas_ring
    supported = pallas_ring.geometry_supported(batch, slot_bytes)
    if mode in ("interpret", "compiled"):
        # An explicit request must never silently downgrade: a parity
        # test would compare the XLA path against itself and a caller
        # pinning the kernel would silently lose it.
        if not supported:
            raise ValueError(
                f"pallas_mode={mode!r} but geometry ({batch}x{slot_bytes})"
                " or backend does not support the ring kernel")
        return mode
    if mode == "off" or not supported:
        return "off"
    platform = next(iter(mesh.devices.flat)).platform.lower()
    if "tpu" not in platform and "axon" not in platform:
        return "off"
    global _PALLAS_PROBED
    if _PALLAS_PROBED is None:
        _PALLAS_PROBED = pallas_ring.probe(interpret=False)
    return "compiled" if _PALLAS_PROBED else "off"


def build_pipelined_commit_step_fused(mesh: Mesh, n_replicas: int,
                                      n_slots: int, slot_bytes: int,
                                      batch: int, depth: int,
                                      staged_depth: int | None = None,
                                      pallas_mode: str = "auto",
                                      verify_round: bool = False):
    """Closed-form pipelined commit: same contract as
    ``build_pipelined_commit_step`` but the ``depth`` rounds are computed
    algebraically instead of sequentially scanned.

    Inside one XLA program nothing external can touch the fence or the
    offsets, so whether a replica participates is decided ONCE for the
    whole dispatch: ``accept = fence_ok & (end == end0)``.  From that
    single bit the per-round ack vectors, the (dual-)majority commit
    indices for all ``depth`` rounds, and the final ring state all have
    closed forms — only the writes of the last ``min(depth, S/B)``
    rounds survive in the ring, so the whole window is ONE bulk ring
    update (select against the old ring) instead of ``depth`` slice
    updates.  This is the same strength reduction the reference applies
    when it coalesces a whole span of log entries into a single RDMA
    WRITE (update_remote_logs, dare_ibv_rc.c:1460-1644) rather than one
    WR per entry; here it also deletes the per-round op overhead that
    dominates a ``lax.scan`` on TPU (~25 small ops/round measured ~32 us
    on v5e vs ~0 for the closed form).

    Semantic difference from the scan step, by design: a replica whose
    ``end`` does not equal ``end0`` at dispatch time rejects the WHOLE
    window, even if a later round's ``end0 + i*B`` would line up with
    its end (the scan step would start accepting mid-window).  Window
    alignment is a driver invariant (DeviceCommitRunner tracks
    ``_next_end0`` and resets the device generation on any divergence),
    so mid-window joining only arises for overlapping retransmit
    windows, which the host path owns.  Rejecting replicas' live rows
    are untouched (scratch content is unspecified in both steps).

    Use this for deep steady-state windows (depth >= ~S/B): it reads and
    rewrites the full ring once per dispatch, which beats the scan step
    whenever depth * batch approaches the ring size.  For shallow
    windows the scan step's proportional writes stay cheaper on real
    hardware.
    """
    staged_depth = depth if staged_depth is None else staged_depth
    _check_geometry(mesh, n_replicas, n_slots, batch)
    S, B, D, SD = n_slots, batch, depth, staged_depth
    NB = S // B
    E = min(D, NB)          # rounds whose writes survive in the ring
    i0 = D - E              # first surviving round
    pallas_mode = _pallas_ring_mode(pallas_mode, batch, slot_bytes, mesh)
    sharded = P(REPLICA_AXIS)
    staged = P(None, REPLICA_AXIS)
    repl = P()
    ctrl_specs = CommitControl(*([repl] * 7))

    def pipe(log_data, log_meta, offs, fence, sdata, smeta, ctrl):
        K, rows, SB = log_data.shape
        a = lax.axis_index(REPLICA_AXIS)
        rid = a * K + jnp.arange(K, dtype=jnp.int32)
        is_leader = rid == ctrl.leader

        # Leader's staged batches (same pmax broadcast as the scan body,
        # hoisted out of the round loop): [SD,B,SB] / [SD,B,4].
        sd_l = lax.pmax(jnp.max(sdata, axis=1), REPLICA_AXIS)
        sm_l = lax.pmax(jnp.max(smeta, axis=1), REPLICA_AXIS)

        # Window-level acceptance (see docstring).
        fence_ok = ((fence[:, FENCE_GRANTED] == ctrl.leader)
                    & (ctrl.term >= fence[:, FENCE_TERM])) | is_leader
        own_end = offs[:, OFF_END]
        accept = fence_ok & (own_end == ctrl.end0)          # [K]
        if verify_round:
            # Multi-controller round-identity check (see _commit_body):
            # on any disagreement nobody writes and the window decides
            # nothing — the ack gather below would mix devlog
            # generations, so its quorum boundary must not be adopted.
            ident = jnp.stack([ctrl.term, ctrl.leader, ctrl.end0])
            idents = lax.all_gather(ident, REPLICA_AXIS)
            coherent = jnp.all(idents == ident[None])
            accept = accept & coherent

        # Closed-form per-round commits.  acks[i, r]: an accepting
        # replica's end after round i is end0+(i+1)B; a rejecting one
        # keeps its end for the whole window.
        acc_g = lax.all_gather(accept, REPLICA_AXIS).reshape(-1)   # [R]
        end_g = lax.all_gather(own_end, REPLICA_AXIS).reshape(-1)  # [R]
        i = jnp.arange(D, dtype=jnp.int32)
        leader_ack = ctrl.end0 + (i + 1) * B                # [D]
        acks = jnp.where(acc_g[None, :], leader_ack[:, None],
                         end_g[None, :])                    # [D,R]
        cand = jnp.minimum(acks, leader_ack[:, None])       # [D,R]
        ge = acks[:, None, :] >= cand[:, :, None]           # [D,R,R]
        n_old = jnp.sum(ge * ctrl.mask_old[None, None, :], axis=2)
        n_new = jnp.sum(ge * ctrl.mask_new[None, None, :], axis=2)
        ok = (n_old >= ctrl.q_old) & ((ctrl.q_new == 0)
                                      | (n_new >= ctrl.q_new))
        member_any = (ctrl.mask_old | ctrl.mask_new)[None, :] == 1
        commits = jnp.max(jnp.where(ok & member_any, cand, 0),
                          axis=1)                           # [D]
        if verify_round:
            commits = jnp.where(coherent, commits, 0)

        # Final ring state.  Block b of the ring was last written by
        # surviving round i0 + e_of_b[b] (an arithmetic progression of
        # blocks mod NB); blocks with e_of_b >= E keep their old rows
        # (only possible when D < NB).
        b = jnp.arange(NB, dtype=jnp.int32)
        base = (ctrl.end0 - 1) // B                         # block of round 0
        e_of_b = (b - base - i0) % NB                       # [NB]
        written = e_of_b < E                                # [NB]
        rnd_of_b = i0 + e_of_b                              # [NB] round id
        src_of_b = rnd_of_b % SD                            # staged index
        if SD == 1:
            new_mcols = jnp.broadcast_to(sm_l[0][None], (NB, B, 4))
        else:
            new_mcols = jnp.take(sm_l, src_of_b, axis=0)    # [NB,B,4]

        def _new_blocks():
            # Ring-sized [NB,B,SB] data gather — only the XLA select
            # path needs it materialized; on the pallas hot path it
            # must stay out of the cond operands or every all-accept
            # dispatch would pay the full ring-size HBM traffic the
            # in-place kernel exists to avoid.
            if SD == 1:
                return jnp.broadcast_to(sd_l[0][None], (NB, B, SB))
            return jnp.take(sd_l, src_of_b, axis=0)         # [NB,B,SB]
        j = jnp.arange(B, dtype=jnp.int32)
        idx_of_b = ctrl.end0 + rnd_of_b[:, None] * B + j[None, :]  # [NB,B]
        new_meta = jnp.stack([
            idx_of_b,
            jnp.full((NB, B), ctrl.term, jnp.int32),
            new_mcols[:, :, 0], new_mcols[:, :, 1],
            new_mcols[:, :, 2], new_mcols[:, :, 3],
        ], axis=-1)                                         # [NB,B,6]

        sel = (accept[:, None] & written[None, :])[:, :, None, None]
        live_m = log_meta[:, :S].reshape(K, NB, B, META_COLS)
        live_m = jnp.where(sel, new_meta[None], live_m)
        log_meta = jnp.concatenate(
            [live_m.reshape(K, S, META_COLS), log_meta[:, S:]], axis=1)

        def _data_select(ld):
            live_d = ld[:, :S].reshape(K, NB, B, SB)
            live_d = jnp.where(sel, _new_blocks()[None], live_d)
            return jnp.concatenate(
                [live_d.reshape(K, S, SB), ld[:, S:]], axis=1)

        if pallas_mode == "off":
            log_data = _data_select(log_data)
        else:
            # Hot path: every row accepts (the overwhelmingly common
            # steady state) -> in-place blocked pallas write touching
            # only the E written blocks; any rejection -> the whole-ring
            # select, which preserves rejecting rows' live slots.
            from apus_tpu.ops.pallas_ring import ring_write_all
            e = jnp.arange(E, dtype=jnp.int32)
            pos_e = (base + i0 + e) % NB
            src_e = (i0 + e) % SD
            log_data = lax.cond(
                jnp.all(accept),
                lambda ld: ring_write_all(
                    ld, sd_l, pos_e, src_e,
                    interpret=(pallas_mode == "interpret")),
                _data_select,
                log_data)

        # Final offsets (same clamp discipline as the scan body, folded
        # over the window: commits is nondecreasing, so the fold is just
        # the last round's value).
        new_end = jnp.where(accept, ctrl.end0 + D * B, own_end)
        own_commit = offs[:, OFF_COMMIT]
        new_commit = jnp.where(
            accept,
            jnp.maximum(own_commit, jnp.minimum(commits[D - 1], new_end)),
            own_commit)
        offs = offs.at[:, OFF_END].set(new_end)
        offs = offs.at[:, OFF_COMMIT].set(new_commit)
        ctrl = dataclasses.replace(ctrl, end0=ctrl.end0 + D * B)
        return log_data, log_meta, offs, fence, commits, ctrl

    fn = shard_map(
        pipe, mesh=mesh,
        in_specs=(sharded, sharded, sharded, sharded, staged, staged,
                  ctrl_specs),
        out_specs=(sharded, sharded, sharded, sharded, repl, ctrl_specs))

    @functools.partial(jax.jit, donate_argnums=0)
    def step(devlog: DeviceLog, staged_data, staged_meta,
             ctrl: CommitControl):
        _assert_devlog_geometry(devlog, n_slots, slot_bytes, batch)
        assert staged_data.shape[0] == SD
        d, m, o, f, commits, ctrl = fn(devlog.data, devlog.meta,
                                       devlog.offs, devlog.fence,
                                       staged_data, staged_meta, ctrl)
        return DeviceLog(d, m, o, f), commits, ctrl

    # Which data path the ring rewrite takes ('compiled' pallas kernel,
    # 'interpret', or the XLA whole-ring select 'off') — recorded by
    # bench.py so published numbers are attributable to a kernel.
    step.pallas_mode = pallas_mode
    return step


def build_windowed_commit_step(mesh: Mesh, n_replicas: int, n_slots: int,
                               slot_bytes: int, batch: int, max_depth: int,
                               verify_round: bool = False,
                               donate: bool = True,
                               donate_ctrl: bool = True):
    """Single-window latency engine: ONE compiled program that carries a
    whole small window of up to ``max_depth`` commit rounds per dispatch,
    with a DYNAMIC round count and device-side early exit.

    This is the un-amortized counterpart of the deep pipelined steps: a
    single client request must not pay one host dispatch per round (the
    69 ms single-dispatch wall the r05 bench recorded is pure dispatch
    RTT on a tunneled chip), nor one recompile per window shape.  The
    engine is a ``lax.while_loop`` whose trip count is the RUNTIME
    scalar ``n_rounds`` — depth-1 and depth-4 windows ride the same
    executable — and whose body is exactly ``_commit_body``, so one
    dispatch replicates, fences, votes, and advances commit for every
    staged round, stopping the moment the outcome is decided:

    - the window's staged rounds have all cleared their quorum vote
      (``i == n_rounds``): the padding capacity up to ``max_depth`` is
      never executed, or
    - a round's vote FAILS to clear (``halt_on_fail != 0``): later
      rounds cannot extend commit past the failed one inside this
      dispatch (fence/offs state cannot change mid-program), so the
      engine returns control to the host immediately instead of
      burning the rest of the window — the device-resident analog of
      the reference's commit loop exiting to its adjust path
      (loop_for_commit, dare_ibv_rc.c:1870-1948).  ``halt_on_fail=0``
      reproduces the scan pipeline's run-all-rounds semantics.

    Buffer donation is threaded through BOTH state operands: the devlog
    (ring data/meta, the ``offs`` log-tail and ``fence`` fence-mask
    arrays) and — with ``donate_ctrl`` — the CommitControl pytree, whose
    ``mask_old``/``mask_new`` vote-mask arrays pass through unchanged
    and alias input to output, so a steady-state caller loops entirely
    on device-resident buffers with zero per-round HBM copies.  A
    caller that donates ctrl must treat the INPUT ctrl as consumed and
    carry the returned one (DeviceCommitRunner refreshes its ctrl
    cache this way).

    Returns ``step(devlog, staged_data [MD,R,B,SB] u8, staged_meta
    [MD,R,B,4] i32, ctrl, n_rounds i32, halt_on_fail i32) -> (devlog',
    commits [MD] i32, rounds_run i32, ctrl')`` where ``commits[i]`` is
    the global commit index after round i (0 for rounds never
    executed), ``rounds_run`` is the number of rounds the loop actually
    ran, and ``ctrl'`` has ``end0`` advanced by ``rounds_run * B``
    (feed it straight back).  Round i consumes staged batch i.
    """
    _check_geometry(mesh, n_replicas, n_slots, batch)
    MD, B = max_depth, batch
    body = functools.partial(_commit_body, batch=batch, n_slots=n_slots)
    sharded = P(REPLICA_AXIS)
    staged = P(None, REPLICA_AXIS)
    repl = P()
    ctrl_specs = CommitControl(*([repl] * 7))

    def pipe(log_data, log_meta, offs, fence, sdata, smeta, ctrl,
             n_rounds, halt):
        if verify_round:
            # Hoisted round-identity check (same rationale as the
            # pipelined step): one tiny all_gather per WINDOW; on
            # incoherence leader=-2 blocks every write and the commit
            # outputs are zeroed below.
            ident = jnp.stack([ctrl.term, ctrl.leader, ctrl.end0])
            idents = lax.all_gather(ident, REPLICA_AXIS)
            coherent = jnp.all(idents == ident[None])
            ctrl = dataclasses.replace(
                ctrl, leader=jnp.where(coherent, ctrl.leader,
                                       jnp.int32(-2)))
        commits0 = jnp.zeros((MD,), jnp.int32)

        def cond(carry):
            i, ok = carry[0], carry[1]
            return (i < n_rounds) & ok

        def one(carry):
            i, ok, log_data, log_meta, offs, fence, ctrl, commits = carry
            bdata = lax.dynamic_index_in_dim(sdata, i, axis=0,
                                             keepdims=False)
            bmeta = lax.dynamic_index_in_dim(smeta, i, axis=0,
                                             keepdims=False)
            log_data, log_meta, offs, fence, _, commit = body(
                log_data, log_meta, offs, fence, bdata, bmeta, ctrl)
            commits = lax.dynamic_update_index_in_dim(
                commits, commit, i, axis=0)
            # The vote cleared iff the whole batch reached quorum
            # (cand is clamped to the leader ack, so commit can never
            # exceed end0 + B).
            cleared = commit >= ctrl.end0 + B
            ctrl = dataclasses.replace(ctrl, end0=ctrl.end0 + B)
            return (i + 1, cleared | (halt == 0), log_data, log_meta,
                    offs, fence, ctrl, commits)

        (i, _, log_data, log_meta, offs, fence, ctrl, commits) = \
            lax.while_loop(cond, one,
                           (jnp.int32(0), jnp.bool_(True), log_data,
                            log_meta, offs, fence, ctrl, commits0))
        if verify_round:
            commits = jnp.where(coherent, commits, 0)
        return log_data, log_meta, offs, fence, commits, i, ctrl

    fn = shard_map(
        pipe, mesh=mesh,
        in_specs=(sharded, sharded, sharded, sharded, staged, staged,
                  ctrl_specs, repl, repl),
        out_specs=(sharded, sharded, sharded, sharded, repl, repl,
                   ctrl_specs))

    donate_argnums = (() if not donate else (0,)) + \
        (() if not donate_ctrl else (3,))

    @functools.partial(jax.jit, donate_argnums=donate_argnums)
    def step(devlog: DeviceLog, staged_data, staged_meta,
             ctrl: CommitControl, n_rounds, halt_on_fail):
        _assert_devlog_geometry(devlog, n_slots, slot_bytes, batch)
        assert staged_data.shape[0] == MD
        d, m, o, f, commits, rounds_run, ctrl = fn(
            devlog.data, devlog.meta, devlog.offs, devlog.fence,
            staged_data, staged_meta, ctrl,
            jnp.asarray(n_rounds, jnp.int32),
            jnp.asarray(halt_on_fail, jnp.int32))
        return DeviceLog(d, m, o, f), commits, rounds_run, ctrl

    return step


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class GroupCommitControl:
    """Per-group control vectors for ONE group-major dispatch
    (Multi-Raft): element g of every field is group g's CommitControl
    scalar, plus ``rounds[g]`` — how many of the window's staged rounds
    that group actually runs this dispatch (its PER-GROUP EARLY-EXIT
    mask: rounds beyond it write nothing and vote nothing for that
    group, so groups with shallow backlogs ride the same dispatch as
    deep ones without paying their rounds)."""

    leader: jax.Array    # [G] i32
    term: jax.Array      # [G] i32
    end0: jax.Array      # [G] i32
    rounds: jax.Array    # [G] i32  (0 = group inactive this dispatch)
    mask_old: jax.Array  # [G, R] i32
    mask_new: jax.Array  # [G, R] i32
    q_old: jax.Array     # [G] i32
    q_new: jax.Array     # [G] i32


def build_group_window_step(mesh: Mesh, n_groups: int, n_replicas: int,
                            n_slots: int, slot_bytes: int, batch: int,
                            max_depth: int):
    """GROUP-MAJOR windowed commit: ONE XLA program replicates, fences,
    votes, and advances commit for up to ``max_depth`` rounds of up to
    ``n_groups`` consensus groups' windows — the dispatch-amortization
    axis the Multi-Raft design adds on top of the round axis.  A
    single-group deployment amortizes ROUNDS per dispatch (the windowed
    engine above); this step amortizes GROUPS x rounds: one leader
    broadcast pmax, one ack all_gather, and one vectorized dual-majority
    vote cover every group per round, so device throughput scales with
    group count instead of drowning in per-dispatch overhead.

    MULTI-DEVICE (ROADMAP "multi-device group-major dispatch"): when
    ``mesh`` carries a GROUP axis (ops.mesh.group_replica_mesh), the
    group dimension of every operand is device-SHARDED along it — each
    device shard runs its own block of groups' windows concurrently
    inside the ONE SPMD program.  Groups are mutually independent, so
    no group-axis collective exists anywhere in the body: the program
    text per shard is identical to the 1-device case over a smaller
    group block, which is why the same builder serves a 1-device bench,
    a virtual CPU test mesh, and a TPU pod slice unchanged.  On a mesh
    without a group axis the group dimension stays replicated layout
    (the pre-multi-device behavior, bit-for-bit).

    Semantics per (group, round) are exactly ``_commit_body``'s,
    vectorized over the leading group axis (each group has its OWN
    leader, term, end0, membership masks, and quorum thresholds —
    different groups may have different leaders on different shards of
    the same dispatch).  ``ctrl.rounds[g]`` masks group g out of rounds
    it did not stage (its early-exit mask): an inactive (group, round)
    writes into scratch and reports commit 0.

    Returns ``step(gdevlog, staged_data [MD,G,R,B,SB] u8, staged_meta
    [MD,G,R,B,4] i32, ctrl: GroupCommitControl) -> (gdevlog',
    commits [MD,G] i32)`` where ``commits[i, g]`` is group g's global
    commit index after round i (0 for rounds past ``rounds[g]``).
    The input devlog is donated (in-place HBM update)."""
    from apus_tpu.ops.mesh import GROUP_AXIS
    _check_geometry(mesh, n_replicas, n_slots, batch)
    G, MD, B, S = n_groups, max_depth, batch, n_slots
    group_sharded = GROUP_AXIS in mesh.axis_names
    if group_sharded and n_groups % mesh.shape[GROUP_AXIS] != 0:
        raise ValueError(f"{n_groups} groups on "
                         f"{mesh.shape[GROUP_AXIS]}-wide group axis")

    def pipe(log_data, log_meta, offs, fence, sdata, smeta, ctrl):
        # Gl: this shard's group block (== G on a group-replicated
        # mesh); every per-group computation below runs on the local
        # block only.
        Gl, K, rows, SB = log_data.shape
        a = lax.axis_index(REPLICA_AXIS)
        rid = a * K + jnp.arange(K, dtype=jnp.int32)        # [K]
        is_leader = rid[None, :] == ctrl.leader[:, None]    # [G,K]
        member_any = (ctrl.mask_old | ctrl.mask_new) == 1   # [G,R]

        def one(carry, i):
            log_data, log_meta, offs, fence, end0 = carry
            bd = lax.dynamic_index_in_dim(sdata, i, axis=0,
                                          keepdims=False)  # [G,K,B,SB]
            bm = lax.dynamic_index_in_dim(smeta, i, axis=0,
                                          keepdims=False)  # [G,K,B,4]
            # (1) leader->all broadcast per group (non-leader rows are
            # zero by the host staging contract, payloads unsigned):
            # one max-reduce over the shard block + one pmax covers
            # EVERY group.
            bcast_d = lax.pmax(jnp.max(bd, axis=1), REPLICA_AXIS)
            bcast_m = lax.pmax(jnp.max(bm, axis=1), REPLICA_AXIS)
            # (2) fence + contiguity + per-group round mask.
            active = i < ctrl.rounds                        # [G]
            fence_ok = ((fence[:, :, FENCE_GRANTED]
                         == ctrl.leader[:, None])
                        & (ctrl.term[:, None]
                           >= fence[:, :, FENCE_TERM])) | is_leader
            own_end = offs[:, :, OFF_END]                   # [G,K]
            do_write = (fence_ok & (own_end == end0[:, None])
                        & active[:, None])                  # [G,K]
            # (3) slot writes: one contiguous span per (group, row);
            # rejected/inactive writes land in the scratch rows.
            span = (end0 - 1) % S                           # [G]
            start = jnp.where(do_write, span[:, None], S)   # [G,K]
            j = jnp.arange(B, dtype=jnp.int32)
            entry_idx = end0[:, None] + j[None, :]          # [G,B]
            fresh_meta = jnp.stack([
                entry_idx,
                jnp.broadcast_to(ctrl.term[:, None], (Gl, B)),
                bcast_m[:, :, 0], bcast_m[:, :, 1],
                bcast_m[:, :, 2], bcast_m[:, :, 3],
            ], axis=-1)                                     # [Gl,B,6]
            zero = jnp.int32(0)
            for g in range(Gl):
                for k in range(K):
                    log_data = lax.dynamic_update_slice(
                        log_data, bcast_d[g][None, None],
                        (jnp.int32(g), jnp.int32(k), start[g, k], zero))
                    log_meta = lax.dynamic_update_slice(
                        log_meta, fresh_meta[g][None, None],
                        (jnp.int32(g), jnp.int32(k), start[g, k], zero))
            # (4) acks + per-group (dual-)majority quorum — ONE gather,
            # one vectorized vote for all groups.
            new_end = jnp.where(do_write, end0[:, None] + B, own_end)
            acks = lax.all_gather(new_end, REPLICA_AXIS)   # [axis,Gl,K]
            acks = jnp.moveaxis(acks, 0, 1).reshape(Gl, -1)  # [Gl,R]
            leader_ack = end0 + B                           # [G]
            cand = jnp.minimum(acks, leader_ack[:, None])   # [G,R]
            ge = acks[:, None, :] >= cand[:, :, None]       # [G,R,R]
            n_old = jnp.sum(ge * ctrl.mask_old[:, None, :], axis=2)
            n_new = jnp.sum(ge * ctrl.mask_new[:, None, :], axis=2)
            ok = (n_old >= ctrl.q_old[:, None]) \
                & ((ctrl.q_new[:, None] == 0)
                   | (n_new >= ctrl.q_new[:, None]))
            commit_g = jnp.max(
                jnp.where(ok & member_any, cand, 0), axis=1)  # [G]
            commit_g = jnp.where(active, commit_g, 0)
            # (5) advance offsets (same accepted-only clamp discipline
            # as _commit_body, per group).
            own_commit = offs[:, :, OFF_COMMIT]
            new_commit = jnp.where(
                do_write,
                jnp.maximum(own_commit,
                            jnp.minimum(commit_g[:, None], new_end)),
                own_commit)
            offs = offs.at[:, :, OFF_END].set(new_end)
            offs = offs.at[:, :, OFF_COMMIT].set(new_commit)
            end0 = end0 + B * active.astype(jnp.int32)
            return (log_data, log_meta, offs, fence, end0), commit_g

        (log_data, log_meta, offs, fence, _end0), commits = lax.scan(
            one, (log_data, log_meta, offs, fence, ctrl.end0),
            jnp.arange(MD, dtype=jnp.int32))
        return log_data, log_meta, offs, fence, commits

    if group_sharded:
        # Group axis device-sharded: state [G,R,...] splits its group
        # dim across the mesh's group axis; per-group control vectors
        # ([G] scalars, [G,R] masks) travel with their group shard;
        # the per-round commit outputs come back [MD, G] with the
        # group dim re-assembled from the shards.
        sharded = P(GROUP_AXIS, REPLICA_AXIS)
        staged = P(None, GROUP_AXIS, REPLICA_AXIS)
        gvec = P(GROUP_AXIS)
        gmask = P(GROUP_AXIS, None)
        commits_spec = P(None, GROUP_AXIS)
        ctrl_specs = GroupCommitControl(
            leader=gvec, term=gvec, end0=gvec, rounds=gvec,
            mask_old=gmask, mask_new=gmask, q_old=gvec, q_new=gvec)
    else:
        sharded = P(None, REPLICA_AXIS)
        staged = P(None, None, REPLICA_AXIS)
        commits_spec = P()
        ctrl_specs = GroupCommitControl(*([P()] * 8))
    fn = shard_map(
        pipe, mesh=mesh,
        in_specs=(sharded, sharded, sharded, sharded, staged, staged,
                  ctrl_specs),
        out_specs=(sharded, sharded, sharded, sharded, commits_spec))

    from apus_tpu.ops.logplane import GroupDeviceLog

    @functools.partial(jax.jit, donate_argnums=0)
    def step(gdevlog: GroupDeviceLog, staged_data, staged_meta,
             ctrl: GroupCommitControl):
        assert gdevlog.data.shape == (G, n_replicas, n_slots + batch,
                                      slot_bytes), gdevlog.data.shape
        assert staged_data.shape[0] == MD
        d, m, o, f, commits = fn(gdevlog.data, gdevlog.meta,
                                 gdevlog.offs, gdevlog.fence,
                                 staged_data, staged_meta, ctrl)
        return GroupDeviceLog(d, m, o, f), commits

    return step


def place_batch(mesh: Mesh, n_replicas: int, leader: int,
                batch_data_host: np.ndarray, batch_meta_host: np.ndarray):
    """Expand a host batch [B,SB]/[B,4] into leader-row-only arrays
    [R,B,SB]/[R,B,4] with the replica sharding (each non-leader host
    contributes zeros; on one host this is a simple embed)."""
    B, SB = batch_data_host.shape
    data = np.zeros((n_replicas, B, SB), np.uint8)
    meta = np.zeros((n_replicas, B, 4), np.int32)
    data[leader] = batch_data_host
    meta[leader] = batch_meta_host
    sh = NamedSharding(mesh, P(REPLICA_AXIS))
    return jax.device_put(data, sh), jax.device_put(meta, sh)
