"""Device control-plane steps: election vote round and heartbeat round.

The reference's control plane is a set of per-server RDMA-written slots
(vote_req[], vote_ack[], hb[], prv_data[] — ctrl_data_t,
dare_server.h:123-140) polled by each server.  The *decisions* (whom to
vote for, when to time out) belong on the host control plane
(apus_tpu.core.node); these device steps accelerate the *rounds*: one
collective evaluates every replica's grant/alive predicate and reduces
the quorum, replacing N one-sided writes + a poll loop with a single
jitted program.  They also let the driver validate full-cluster election
math on a mesh (dryrun_multichip) without any host networking.

State arrays (sharded over the replica axis):
    vote_state [R, 3] i32: (voted_term, voted_for, granted_fence_term)
    hb_state   [R, 2] i32: (last_seen_term, last_seen_counter)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from apus_tpu.ops.logplane import META_IDX, META_TERM, OFF_END
from apus_tpu.ops.mesh import REPLICA_AXIS, shard_map

VS_TERM, VS_FOR, VS_FENCE = range(3)
HB_TERM, HB_COUNT = range(2)


def _vote_body(vote_state, offs, log_meta, cand, *, n_slots: int):
    """One vote round.  ``cand`` = [cand_idx, cand_term, cand_last_idx,
    cand_last_term, q_old, q_new] replicated i32[6] packed with the
    membership masks appended: full layout [6 + 2R].

    Per replica: grant iff cand_term > voted_term and the candidate's log
    is up-to-date vs ours (poll_vote_requests check,
    dare_server.c:1591-1652).  Granting updates the durable vote record
    and the fence term (restore_log_access analog).
    """
    K = log_meta.shape[0]
    S = n_slots
    R = (cand.shape[0] - 6) // 2
    a = lax.axis_index(REPLICA_AXIS)
    rid = a * K + jnp.arange(K, dtype=jnp.int32)
    c_idx, c_term, c_lidx, c_lterm, q_old, q_new = (cand[i] for i in range(6))
    mask_old = cand[6:6 + R]
    mask_new = cand[6 + R:6 + 2 * R]

    # Our last determinant from the device log: slot of entry (end-1),
    # slot formula (idx-1) % S (ops.logplane.slot_of).
    own_end = offs[:, OFF_END]                          # [K]
    last_slot = (own_end - 2) % S
    own_last_idx = jnp.take_along_axis(
        log_meta[:, :, META_IDX], last_slot[:, None], axis=1)[:, 0]
    own_last_term = jnp.take_along_axis(
        log_meta[:, :, META_TERM], last_slot[:, None], axis=1)[:, 0]
    # An empty log (end == first index) has no determinant.
    empty = own_last_idx != own_end - 1
    own_last_idx = jnp.where(empty, 0, own_last_idx)
    own_last_term = jnp.where(empty, 0, own_last_term)

    term_ok = c_term > vote_state[:, VS_TERM]
    # Idempotence (Raft: votedFor == candidate at equal term re-grants):
    # a retried round for the same (candidate, term) must count again.
    repeat = ((vote_state[:, VS_TERM] == c_term)
              & (vote_state[:, VS_FOR] == c_idx))
    up_to_date = jnp.where(c_lterm != own_last_term,
                           c_lterm > own_last_term,
                           c_lidx >= own_last_idx)
    # Candidate self-vote skips the log check (its log trivially matches
    # itself) but NOT the term check — a stale self-round must not
    # overwrite a newer durable vote.
    grant = ((term_ok | repeat) & (up_to_date | (rid == c_idx)))

    vote_state = jnp.where(
        grant[:, None],
        jnp.stack([jnp.full((K,), c_term), jnp.full((K,), c_idx),
                   jnp.full((K,), c_term)], axis=-1),
        vote_state)

    grants = lax.all_gather(grant.astype(jnp.int32), REPLICA_AXIS).reshape(-1)
    n_old = jnp.sum(grants * mask_old)
    n_new = jnp.sum(grants * mask_new)
    elected = (n_old >= q_old) & ((q_new == 0) | (n_new >= q_new))
    return vote_state, grants, elected


def _hb_body(hb_state, beat):
    """One heartbeat round.  ``beat`` = [leader_idx, term, counter] i32
    replicated.  The leader's beat fans out (pmax broadcast); each
    replica records the newest (term, counter) it has seen and reports
    whether this round delivered a fresh beat (the hb[] scan analog,
    dare_server.c:822-922)."""
    K = hb_state.shape[0]
    a = lax.axis_index(REPLICA_AXIS)
    rid = a * K + jnp.arange(K, dtype=jnp.int32)
    is_leader = rid == beat[0]
    # Broadcast (term, counter) from the leader row.
    local = jnp.where(is_leader[:, None], beat[None, 1:3], 0).max(axis=0)
    seen = lax.pmax(local, REPLICA_AXIS)                 # [2]
    newer = ((seen[0] > hb_state[:, HB_TERM]) |
             ((seen[0] == hb_state[:, HB_TERM]) &
              (seen[1] > hb_state[:, HB_COUNT])))
    hb_state = jnp.where(newer[:, None], seen[None, :], hb_state)
    fresh = lax.all_gather(newer.astype(jnp.int32), REPLICA_AXIS).reshape(-1)
    return hb_state, fresh


def build_vote_step(mesh: Mesh, n_replicas: int, n_slots: int):
    axis = mesh.shape[REPLICA_AXIS]
    assert n_replicas % axis == 0
    body = functools.partial(_vote_body, n_slots=n_slots)
    s, r = P(REPLICA_AXIS), P()
    fn = shard_map(body, mesh=mesh, in_specs=(s, s, s, r),
                   out_specs=(s, r, r))
    return jax.jit(fn)


def build_hb_step(mesh: Mesh, n_replicas: int):
    axis = mesh.shape[REPLICA_AXIS]
    assert n_replicas % axis == 0
    body = _hb_body
    s, r = P(REPLICA_AXIS), P()
    fn = shard_map(body, mesh=mesh, in_specs=(s, r),
                   out_specs=(s, r))
    return jax.jit(fn)
