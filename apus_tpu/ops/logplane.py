"""HBM-resident replicated-log state arrays.

Device mirror of apus_tpu.core.log.SlotLog: the reference's RDMA-exposed
memory regions (the 64 MB log buffer, dare_log.h:76-103, and ctrl_data_t,
dare_server.h:123-140) become dense, statically-shaped arrays with a
leading replica axis, sharded over the mesh:

    data    [R, S+B, SB] uint8  slot payloads (slot = (idx-1) % S)
    meta    [R, S+B, 6]  int32  per-slot (idx, term, req_id, clt_id, type, len)
    offs    [R, 4]       int32  (head, apply, commit, end) absolute indices
    fence   [R, 2]       int32  (granted_to, fence_term) — explicit fencing,
                                replacing QP-state fencing (dare_ibv_rc.c:2156)

TPU layout decisions (these ARE the performance design):
- **Batch-aligned appends.**  The commit step appends whole batches of B
  entries (partial batches are padded with NOOP entries — the reference
  appends NOOPs too, dare_log.h:22).  With S a multiple of B and 1-based
  indices mapped by ``slot = (idx-1) % S``, a batch always occupies ONE
  contiguous slot span, so the write lowers to a single
  ``lax.dynamic_update_slice`` — dynamic *row scatter* on TPU is
  catastrophically slow for u8 (measured ~70 ms vs ~20 us for a
  contiguous slice update on v5e).
- **Scratch redirect instead of write masks.**  B scratch rows sit past
  the live slots; a replica that must reject the batch (fence/contiguity)
  redirects the slice start to the scratch region instead of predicating
  per-row — no gathers, no selects over the 64 MB buffer.

Everything is int32: log indices in a bench lifetime stay far below 2^31,
and int32 keeps the control math on the TPU's native integer path.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from apus_tpu.core.types import DEFAULT_LOG_SLOTS, DEFAULT_SLOT_BYTES

# meta columns
META_IDX, META_TERM, META_REQ, META_CLT, META_TYPE, META_LEN = range(6)
META_COLS = 6
# offs columns
OFF_HEAD, OFF_APPLY, OFF_COMMIT, OFF_END = range(4)
# fence columns
FENCE_GRANTED, FENCE_TERM = range(2)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DeviceLog:
    """Per-replica log state (pytree; all fields carry the leading
    replica axis)."""

    data: jax.Array    # [R, S, SB] uint8
    meta: jax.Array    # [R, S, 6]  int32
    offs: jax.Array    # [R, 4]     int32
    fence: jax.Array   # [R, 2]     int32

    @property
    def n_replicas(self) -> int:
        return self.data.shape[0]

    @property
    def slot_bytes(self) -> int:
        return self.data.shape[2]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class GroupDeviceLog:
    """Group-major device-log state (Multi-Raft): every field carries a
    leading GROUP axis over the per-replica layout of DeviceLog, so ONE
    dispatch can replicate/vote/commit windows for MANY consensus
    groups — the group-major axis the multi-group throughput design
    amortizes dispatch overhead over.  Sharded on the replica axis
    (axis 1); the group axis is replicated layout, not a mesh axis."""

    data: jax.Array    # [G, R, S+B, SB] uint8
    meta: jax.Array    # [G, R, S+B, 6] int32
    offs: jax.Array    # [G, R, 4]      int32
    fence: jax.Array   # [G, R, 2]      int32

    @property
    def n_groups(self) -> int:
        return self.data.shape[0]

    @property
    def n_replicas(self) -> int:
        return self.data.shape[1]


def make_group_device_log(n_groups: int, n_replicas: int,
                          n_slots: int, slot_bytes: int,
                          batch: int, sharding=None) -> GroupDeviceLog:
    """Fresh group-major logs: every group empty at index 1 with a
    closed fence (granted_to -1 at term 0 — no writer admitted until
    that group's first leadership reset rewrites its fence row)."""
    if n_slots % batch != 0:
        raise ValueError(f"n_slots ({n_slots}) must be a multiple of "
                         f"the batch size ({batch})")
    kw = {} if sharding is None else {"device": sharding}
    rows = n_slots + batch
    data = jnp.zeros((n_groups, n_replicas, rows, slot_bytes),
                     jnp.uint8, **kw)
    meta = jnp.zeros((n_groups, n_replicas, rows, META_COLS),
                     jnp.int32, **kw)
    offs = jnp.ones((n_groups, n_replicas, 4), jnp.int32, **kw)
    fence = jnp.tile(jnp.array([-1, 0], jnp.int32),
                     (n_groups, n_replicas, 1))
    if sharding is not None:
        fence = jax.device_put(fence, sharding)
    return GroupDeviceLog(data=data, meta=meta, offs=offs, fence=fence)


def slot_of(idx, n_slots: int):
    """Device slot of 1-based absolute log index ``idx``."""
    return (idx - 1) % n_slots


def make_device_log(n_replicas: int,
                    n_slots: int = DEFAULT_LOG_SLOTS,
                    slot_bytes: int = DEFAULT_SLOT_BYTES,
                    batch: int = 64,
                    first_idx: int = 1,
                    leader: int = 0,
                    term: int = 1,
                    sharding=None) -> DeviceLog:
    """Fresh logs on all replicas, with log access granted to ``leader``
    at ``term`` (a stable-leader starting point; the host control plane
    rewrites the fence on elections).  ``batch`` rows of scratch are
    appended past the live slots (see module docstring)."""
    if n_slots % batch != 0:
        raise ValueError(f"n_slots ({n_slots}) must be a multiple of the "
                         f"batch size ({batch})")
    kw = {} if sharding is None else {"device": sharding}
    rows = n_slots + batch
    data = jnp.zeros((n_replicas, rows, slot_bytes), jnp.uint8, **kw)
    meta = jnp.zeros((n_replicas, rows, META_COLS), jnp.int32, **kw)
    offs = jnp.full((n_replicas, 4), first_idx, jnp.int32, **kw)
    fence = jnp.tile(jnp.array([leader, term], jnp.int32), (n_replicas, 1))
    if sharding is not None:
        fence = jax.device_put(fence, sharding)
    return DeviceLog(data=data, meta=meta, offs=offs, fence=fence)


class HostStagingRing:
    """Double-buffered host staging for window encoding (the pinned
    send-buffer ring of the reference's RDMA path, re-expressed for the
    host->device transfer edge).

    The old staging path allocated fresh ``np.zeros`` window buffers
    per dispatch and implicitly serialized host packing behind the
    transfer consuming the previous window.  This ring keeps ``nbuf``
    (default two) REUSABLE pinned buffer pairs per window depth:
    ``acquire`` hands out the next pair, blocking ONLY on the consumer
    edge — ``jax.block_until_ready`` of the device arrays staged from
    that same pair ``nbuf`` windows ago — so host-side slot packing
    for window N+1 overlaps device execution of window N.  ``staged``
    records the device arrays a pair was consumed into.

    Slot order is preserved by construction: pairs are handed out
    round-robin and a pair is never rewritten until the transfer that
    read it has completed, so a slow consumer (device executing a deep
    window) delays reuse instead of corrupting in-flight bytes.

    Not re-entrant beyond ``nbuf`` concurrent un-staged acquisitions
    per depth (the drivers are single-dispatcher; the bench loops are
    single-threaded)."""

    def __init__(self, batch: int, slot_bytes: int, nbuf: int = 2):
        self.batch = batch
        self.slot_bytes = slot_bytes
        self.nbuf = nbuf
        self._lock = threading.Lock()
        self._pools: dict[int, list] = {}     # depth -> [_StageSlot]
        self._cursor: dict[int, int] = {}
        #: optional obs Histogram observing the consumer-edge block of
        #: every acquire, in µs (apus_tpu.obs.metrics.Histogram-shaped:
        #: anything with .observe()).  The window-occupancy question
        #: "is staging ever the wait?" becomes a scrapeable
        #: distribution instead of a profiler session.
        self.wait_hist = None

    class _StageSlot:
        __slots__ = ("data", "meta", "inflight")

        def __init__(self, depth, batch, slot_bytes):
            self.data = np.zeros((depth, batch, slot_bytes), np.uint8)
            self.meta = np.zeros((depth, batch, 4), np.int32)
            self.inflight = None      # device arrays staged from here

    def acquire(self, depth: int) -> "HostStagingRing._StageSlot":
        """Next reusable buffer pair for a ``depth``-round window,
        zeroed, with the consumer edge (the device transfer that last
        read it) already awaited."""
        with self._lock:
            pool = self._pools.get(depth)
            if pool is None:
                pool = self._pools[depth] = [
                    self._StageSlot(depth, self.batch, self.slot_bytes)
                    for _ in range(self.nbuf)]
                self._cursor[depth] = 0
            slot = pool[self._cursor[depth]]
            self._cursor[depth] = (self._cursor[depth] + 1) % self.nbuf
        if slot.inflight is not None:
            # Consumer edge: the ONLY blocking point of the pipeline.
            # Ready outputs of the staging transfer imply the host
            # buffer's bytes have been read; rewriting before that
            # would corrupt the in-flight window.
            t0 = time.perf_counter() if self.wait_hist is not None \
                else 0.0
            jax.block_until_ready(slot.inflight)
            if self.wait_hist is not None:
                self.wait_hist.observe(
                    int((time.perf_counter() - t0) * 1e6))
            slot.inflight = None
        # memset, not realloc: encoders only write each entry's wire
        # bytes, so stale tail bytes from the last window must be
        # cleared (zero rows are the NOOP/non-leader contract).
        slot.data.fill(0)
        slot.meta.fill(0)
        return slot

    def staged(self, slot: "HostStagingRing._StageSlot",
               device_arrays) -> None:
        """Record the device arrays ``slot`` was consumed into; the
        pair becomes reusable once they are ready."""
        slot.inflight = device_arrays


class GroupStagingRing:
    """Reusable host staging for GROUP-MAJOR windows ([MD, G, R, B, SB]
    data + [MD, G, R, B, 4] meta pairs) — the HostStagingRing contract
    extended to the group-major dispatch shape, one fixed geometry per
    ring (the group runner's window shape never varies).

    This is what makes the async dispatch beat possible: the driver
    encodes window N+1 into the next ring pair while the device
    executes window N's (donated, device-resident) arrays.  ``acquire``
    blocks ONLY on the consumer edge — readiness of the device arrays
    staged from that same pair ``nbuf`` windows ago — so the ring never
    rewrites bytes an in-flight transfer still reads.  On a sharded
    mesh the staged device arrays are split across every device shard
    (ops.mesh.group_staged_sharding); the host pair serves all shards
    of one window."""

    def __init__(self, max_depth: int, n_groups: int, n_replicas: int,
                 batch: int, slot_bytes: int, nbuf: int = 2):
        self.nbuf = nbuf
        self._lock = threading.Lock()
        shape = (max_depth, n_groups, n_replicas, batch)
        self._slots = [self._StageSlot(shape, slot_bytes)
                       for _ in range(nbuf)]
        self._cursor = 0
        #: optional obs Histogram (anything with .observe()) of the
        #: consumer-edge block per acquire, in µs.
        self.wait_hist = None

    class _StageSlot:
        __slots__ = ("data", "meta", "inflight")

        def __init__(self, shape, slot_bytes):
            self.data = np.zeros(shape + (slot_bytes,), np.uint8)
            self.meta = np.zeros(shape + (4,), np.int32)
            self.inflight = None

    def acquire(self) -> "GroupStagingRing._StageSlot":
        """Next reusable pair, zeroed, consumer edge awaited."""
        with self._lock:
            slot = self._slots[self._cursor]
            self._cursor = (self._cursor + 1) % self.nbuf
        if slot.inflight is not None:
            t0 = time.perf_counter() if self.wait_hist is not None \
                else 0.0
            jax.block_until_ready(slot.inflight)
            if self.wait_hist is not None:
                self.wait_hist.observe(
                    int((time.perf_counter() - t0) * 1e6))
            slot.inflight = None
        # memset, not realloc: encoders only write each entry's wire
        # bytes; zero rows are the NOOP/non-leader broadcast contract.
        slot.data.fill(0)
        slot.meta.fill(0)
        return slot

    def staged(self, slot: "GroupStagingRing._StageSlot",
               device_arrays) -> None:
        slot.inflight = device_arrays


def host_batch_to_device(requests: list[bytes], slot_bytes: int,
                         req_ids: list[int] | None = None,
                         clt_ids: list[int] | None = None,
                         batch_size: int | None = None):
    """Pack raw request payloads into fixed-width batch arrays.

    Returns (batch_data [B, SB] u8, batch_meta [B, 4] i32, n_valid).
    batch_meta columns: (req_id, clt_id, type, len).  Oversized payloads
    must already be segmented (apus_tpu.core.segment, applied in core.node.submit).
    """
    b = len(requests) if batch_size is None else batch_size
    assert len(requests) <= b
    data = np.zeros((b, slot_bytes), np.uint8)
    metadata = np.zeros((b, 4), np.int32)
    for j, r in enumerate(requests):
        if len(r) > slot_bytes:
            raise ValueError(f"request {j} exceeds slot width ({len(r)})")
        data[j, :len(r)] = np.frombuffer(r, np.uint8)
        metadata[j] = (req_ids[j] if req_ids else 0,
                       clt_ids[j] if clt_ids else 0,
                       1,  # EntryType.CSM
                       len(r))
    return data, metadata, len(requests)
