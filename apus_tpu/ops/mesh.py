"""Device-mesh construction for the replica axis.

The reference's "cluster" is N servers on an IB fabric; ours is N replica
shards on a ``jax.sharding.Mesh`` axis named ``"replica"``.  On real
hardware each replica maps to one TPU chip and collectives ride ICI; in
tests the mesh is 8 virtual CPU devices (conftest.py); single-chip
benches fold the replica axis onto one device (XLA still emits the same
program, collectives become local shuffles).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

REPLICA_AXIS = "replica"
GROUP_AXIS = "group"


def shard_map(body, *, mesh: Mesh, in_specs, out_specs):
    """``jax.shard_map`` across the JAX versions this repo runs on.

    Newer releases expose ``jax.shard_map`` (replication checking via
    ``check_vma``); older ones (<= 0.4.x) only have
    ``jax.experimental.shard_map.shard_map`` with the same semantics
    under ``check_rep``.  Every shard_map in the data plane goes
    through here so the ops layer keeps one call shape.  Replication
    checking is disabled either way: the commit-step bodies mix
    replicated control scalars with sharded state, and the checker's
    inference rejects the (correct) mixed returns."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(body, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def replica_mesh(n_replicas: int, devices=None) -> Mesh:
    """A 1-D mesh with ``n_replicas`` entries along the replica axis.

    If fewer physical devices exist than replicas, devices are reused
    (valid for functional testing / single-chip benchmarking: XLA runs
    the identical collective program; inter-replica traffic stays on-chip)."""
    if devices is None:
        devices = jax.devices()
    if len(devices) >= n_replicas:
        devs = np.array(devices[:n_replicas])
        return Mesh(devs, (REPLICA_AXIS,))
    if len(devices) == 1:
        # Single-chip fold: a 1-entry mesh; replica state keeps its leading
        # axis and collectives reduce over a size-1 axis — the protocol
        # math is then vectorized over the replica-batch dim instead.
        return Mesh(np.array(devices), (REPLICA_AXIS,))
    raise ValueError(
        f"need 1 or >= {n_replicas} devices, have {len(devices)}")


def replica_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis sharding for per-replica state arrays."""
    return NamedSharding(mesh, P(REPLICA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _largest_divisor_leq(n: int, cap: int) -> int:
    """Largest divisor of ``n`` that is <= ``cap`` (>= 1)."""
    for d in range(min(n, max(cap, 1)), 0, -1):
        if n % d == 0:
            return d
    return 1


def group_replica_mesh(n_groups: int, n_replicas: int,
                       devices=None) -> Mesh:
    """A 2-D ``(group, replica)`` mesh: consensus GROUPS sharded across
    devices along the leading axis, replicas along the existing replica
    axis — the Multi-Raft device layout (ROADMAP "multi-device
    group-major dispatch").  Groups are mutually independent (no
    cross-group collectives exist in the commit step), so sharding them
    across devices turns the group-major dispatch into G truly
    concurrent windows: the device-mesh analog of the reference's
    passive parallel replication on the NIC.

    Device budgeting (graceful reuse when devices < groups x replicas):
    the group axis takes the largest divisor of ``n_groups`` that fits
    the device count; whatever integer factor remains feeds the replica
    axis (largest divisor of ``n_replicas``).  One device therefore
    always works (1x1 mesh, every axis folded — the single-chip bench
    shape), and a TPU pod slice with >= n_groups chips runs every
    group's window on its own chip by construction."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    g_axis = _largest_divisor_leq(n_groups, len(devices))
    r_axis = _largest_divisor_leq(n_replicas, len(devices) // g_axis)
    devs = np.array(devices[:g_axis * r_axis]).reshape(g_axis, r_axis)
    return Mesh(devs, (GROUP_AXIS, REPLICA_AXIS))


def group_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for group-major state arrays ([G, R, ...]): group axis
    device-sharded when the mesh carries one, replicas along the
    replica axis either way."""
    if GROUP_AXIS in mesh.axis_names:
        return NamedSharding(mesh, P(GROUP_AXIS, REPLICA_AXIS))
    return NamedSharding(mesh, P(None, REPLICA_AXIS))


def group_staged_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for group-major staged windows ([MD, G, R, ...])."""
    if GROUP_AXIS in mesh.axis_names:
        return NamedSharding(mesh, P(None, GROUP_AXIS, REPLICA_AXIS))
    return NamedSharding(mesh, P(None, None, REPLICA_AXIS))
