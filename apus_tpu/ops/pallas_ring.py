"""Pallas TPU kernel for the fused commit window's ring write.

The fused pipelined commit step (ops.commit.build_pipelined_commit_step_fused)
ends a depth-D window with one bulk ring update: the last ``E = min(D, S/B)``
rounds' batches land in E consecutive slot-blocks (mod ring).  The pure-XLA
realization is a whole-ring select (read old ring + write new ring, ~2x the
ring size in HBM traffic).  This kernel does the same update **in place**:

- grid = (K replica rows, E written blocks) — the grid *only visits blocks
  that are actually written*; with the ring buffer aliased input->output,
  untouched rows are never read or written (the RDMA analog: the reference
  writes exactly the entry range, update_remote_logs dare_ibv_rc.c:1460-1644,
  never the whole log buffer).
- scalar-prefetched index vectors choose, per grid step, the destination
  slot-block (``pos[e]``, ring position) and the source staged batch
  (``src[e]``, which staged buffer round ``i0+e`` consumed) — the
  PrefetchScalarGridSpec pattern: block index maps read the scalars.
- the kernel body is a single VMEM copy ``out[:] = staged_block[:]``.

It only covers the all-rows-accept case (every replica row passes the fence
+ contiguity check): the fused step wraps it in ``lax.cond`` and falls back
to the whole-ring select when any row rejects — rejection means leadership
churn or a lagging replica, both rare and host-visible, so the hot path
stays minimal.

TPU tiling: uint8 blocks need (32, 128) min tiles, so the kernel engages
only when ``batch % 32 == 0 and slot_bytes % 128 == 0`` (the production
geometry 64 x 4096 qualifies; tiny test geometries fall back to XLA).
Tests run it in interpreter mode on the CPU mesh; on an unsupported
backend the builder's probe falls back to the XLA path at build time.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

try:                                             # pallas is optional at import
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:                                # noqa: BLE001
    _HAVE_PALLAS = False


def geometry_supported(batch: int, slot_bytes: int) -> bool:
    """uint8 VMEM tiling constraint: (32, 128) min tile."""
    return _HAVE_PALLAS and batch % 32 == 0 and slot_bytes % 128 == 0


def ring_write_all(log_data, staged, pos, src, *, interpret: bool):
    """In-place blocked ring write (all replica rows accept).

    log_data [K, rows, SB] u8 (donated; rows >= S), staged [SD, B, SB] u8,
    pos [E] i32 (destination slot-block per written block, in block units),
    src [E] i32 (source staged index per written block).  Returns the
    updated ring.
    """
    K, rows, SB = log_data.shape
    SD, B, _ = staged.shape
    E = pos.shape[0]

    def kernel(pos_ref, src_ref, ring_ref, staged_ref, out_ref):
        del pos_ref, src_ref, ring_ref          # consumed by the index maps
        out_ref[:] = staged_ref[:]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                   # pos, src
        grid=(K, E),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),        # ring: aliased, unread
            pl.BlockSpec((1, B, SB),
                         lambda k, e, pos, src: (src[e], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, B, SB),
                               lambda k, e, pos, src: (k, pos[e], 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((K, rows, SB), log_data.dtype),
        input_output_aliases={2: 0},             # ring (after 2 scalars) -> out
        interpret=interpret,
    )(pos, src, log_data, staged)


def probe(interpret: bool) -> bool:
    """Build-time self-check: run a tiny instance end to end and verify
    the in-place semantics (written blocks replaced, others untouched).
    Any failure means the backend can't run the kernel — callers fall
    back to the XLA select path."""
    if not _HAVE_PALLAS:
        return False
    try:
        import numpy as np
        K, NB, B, SB = 2, 4, 32, 128
        ring = jnp.asarray(
            np.arange(K * (NB * B + B) * SB, dtype=np.uint8).reshape(
                K, NB * B + B, SB))
        before = np.asarray(ring)
        staged = jnp.asarray(
            np.full((1, B, SB), 7, np.uint8))
        pos = jnp.asarray(np.array([1, 2], np.int32))
        src = jnp.asarray(np.array([0, 0], np.int32))
        out = np.asarray(ring_write_all(ring, staged, pos, src,
                                        interpret=interpret))
        ok = ((out[:, B:3 * B] == 7).all()
              and (out[:, :B] == before[:, :B]).all()
              and (out[:, 3 * B:] == before[:, 3 * B:]).all())
        return bool(ok)
    except Exception:                            # noqa: BLE001
        return False
