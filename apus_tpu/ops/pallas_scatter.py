"""One-sided leader->followers log scatter over ICI (pallas remote DMA).

The reference's replication data plane is one-sided RDMA: the leader
writes the entry range directly into each follower's log memory and
followers are passive on the critical path (update_remote_logs,
dare_ibv_rc.c:1460-1644).  The production commit step re-expresses that
fan-out as a ``pmax`` collective (XLA picks the ICI algorithm); THIS
module is the explicit one-sided form of the same operation, built on
``pltpu.make_async_remote_copy`` — the TPU instruction that IS an RDMA
write over the interconnect.

Topology: the reference posts one RDMA WRITE per follower because an IB
fabric is all-to-all switched; a TPU torus is not — its native shape is
the neighbor RING.  So the kernel pipelines the leader's window around
the ring: every hop is a one-sided write into the RIGHT neighbor's
landing buffer (double-buffered; no handshake beyond the DMA
semaphores), and each replica captures the window into its output when
the leader's bytes reach it (hop distance == (my - leader) mod N).
Every device executes the identical DMA sequence — the structurally
symmetric program a collective fabric wants (and the reason the naive
asymmetric fan-out deadlocks: remote-copy rendezvous needs all
participants).

Scope: a demonstrated alternative data path, not the default.  On the
single-chip bench topology there are no remote peers, so the pmax step
remains the production scatter; this kernel runs on the multi-device
mesh (interpret mode on the CPU test mesh, exercised by
tests/test_ops_commit.py and __graft_entry__.dryrun_multichip; compiled
on a real multi-chip TPU slice, where DeviceIdType.LOGICAL routes over
ICI).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apus_tpu.ops.mesh import REPLICA_AXIS, shard_map

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:                                # noqa: BLE001
    _HAVE_PALLAS = False


def build_one_sided_scatter(mesh, batch: int, slot_bytes: int,
                            interpret: bool = False):
    """Returns ``scatter(local [N,B,SB] u8, leader i32) -> landed
    [N,B,SB] u8``: every shard's landing buffer ends up holding the
    LEADER shard's batch, delivered hop by hop by one-sided remote
    copies.  One replica row per device (N = mesh axis size)."""
    if not _HAVE_PALLAS:
        raise RuntimeError("pallas unavailable")
    N = mesh.shape[REPLICA_AXIS]
    B, SB = batch, slot_bytes

    def kernel(local_ref, leader_ref, out_ref, comm, send_sem, recv_sem):
        my = jax.lax.axis_index(REPLICA_AXIS)
        right = jax.lax.rem(my + 1, jnp.int32(N))
        dist = jax.lax.rem(my - leader_ref[0] + jnp.int32(N), jnp.int32(N))

        comm[0] = local_ref[:]
        for s in range(N):
            slot = s % 2
            # Capture when the leader's window has reached this hop
            # (local predicated copy — no cross-device divergence).
            @pl.when(jnp.int32(s) == dist)
            def _():
                out_ref[:] = comm[slot]
            if s < N - 1:
                # One-sided push of the current buffer into the right
                # neighbor's OTHER slot (double buffering: the slot
                # being sent is never the slot being landed into).
                rdma = pltpu.make_async_remote_copy(
                    src_ref=comm.at[slot],
                    dst_ref=comm.at[1 - slot],
                    send_sem=send_sem.at[slot],
                    recv_sem=recv_sem.at[1 - slot],
                    device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
                rdma.start()
                rdma.wait()

    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, SB), jnp.uint8),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),       # local batch
            pl.BlockSpec(memory_space=pltpu.SMEM),       # leader scalar
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, B, SB), jnp.uint8),           # ring buffers
            pltpu.SemaphoreType.DMA((2,)),               # per-slot send
            pltpu.SemaphoreType.DMA((2,)),               # per-slot recv
        ],
        interpret=interpret,
    )

    from jax.sharding import PartitionSpec as P

    @jax.jit
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(REPLICA_AXIS), P()),
                       out_specs=P(REPLICA_AXIS))
    def scatter(local, leader):
        out = call(local[0], jnp.asarray([leader], jnp.int32))
        return out[None]

    return scatter
