"""Transport abstraction, DCN networking, and the in-process simulator.

Submodules import lazily: ``apus_tpu.parallel.sim``/``.net`` depend on
``apus_tpu.core.node``, which itself imports ``apus_tpu.parallel.transport``
— an eager import here would be circular.
"""

from apus_tpu.parallel.transport import Regions, Transport, WriteResult

__all__ = ["Transport", "Regions", "WriteResult", "Cluster", "SimTransport"]


def __getattr__(name):
    if name in ("Cluster", "SimTransport"):
        from apus_tpu.parallel import sim
        return getattr(sim, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
