"""Transport abstraction, device-mesh helpers, and the in-process simulator."""

from apus_tpu.parallel.transport import Transport, Regions, WriteResult
from apus_tpu.parallel.sim import Cluster, SimTransport

__all__ = ["Transport", "Regions", "WriteResult", "Cluster", "SimTransport"]
