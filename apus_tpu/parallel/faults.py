"""Live-stack fault plane: deterministic fault injection on the REAL
transport.

The reference proves fault tolerance physically: QPs are revoked before
votes (dare_ibv_rc.c:2156-2255) and every reconfiguration scenario is
benchmarked on live hardware (benchmarks/reconf_bench.sh).  Our
virtual-time simulator (apus_tpu.parallel.sim.SimTransport) covers the
consensus core the same way, but the LIVE stack — ``NetTransport``,
the mesh plane's descriptor channel, the daemon/bridge path — has its
own failure modes (half-open sockets, dial backoff, busy-peer
timeouts, feed death) that a memory-access simulator cannot reach.

``FaultPlane`` wraps any live :class:`~apus_tpu.parallel.transport.
Transport` initiator with seeded, schedule-driven fault injection
applied ABOVE the socket layer, so every op still exercises the real
wire codec, the real peer locks, and the real failure-detector
plumbing underneath:

- per-peer **drop** probability (the WC-error analog: the op never
  reaches the wire, the caller sees DROPPED/None exactly as it would
  for a lost datagram);
- per-peer **delay** (uniform extra latency per op, drawn from the
  seeded RNG) and **throttle** (fixed pre-op stall — a slow peer whose
  event loop is starved, not dead);
- per-peer **duplicate** probability (the op is applied twice at the
  target; one-sided region ops are idempotent by design and client ops
  are deduped by the endpoint DB — duplication makes both claims
  testable on the live wire);
- per-peer **reorder** probability (the op is HELD until the next op
  to the same peer completes — the delivery inversion a multi-path
  fabric produces);
- **asymmetric partitions**: ``block(peers)`` severs this initiator's
  OUTBOUND direction only.  A bidirectional partition is composed from
  both sides' planes (each daemon owns one), which is exactly how real
  partitions decompose — and lets tests express one-way loss the
  simulator's pair-blocking cannot.
- **crash/restart hooks**: ``crash()`` fails every op and fires
  registered callbacks (tests park a daemon's outbound plane without
  killing the process — a zombie whose sockets are up but whose ops
  all die); ``restart()`` clears it.

Determinism: every probabilistic draw comes from one seeded
``random.Random``; with a fixed seed and a single driving thread the
fault sequence is bit-identical across runs.  Concurrent callers
(tick thread + client handlers) still share the seeded stream — the
per-op draw ORDER then depends on thread interleaving, so campaigns
that need exact replay drive faults from schedules (below) or
per-peer knobs rather than global probabilities.

Schedules: a list of timed steps, each ``{"at": seconds, "cmd": ...}``
relative to :meth:`FaultPlane.arm`, executed by a timer thread.  The
same JSON shape travels over the wire (OP_FAULT, ``make_fault_ops``)
so tests can script faults INTO live daemon processes (ProcCluster)
— the live-stack analog of the simulator's in-process knobs.

Configuration (utils/config.py ``fault_plane``/``fault_seed``/
``fault_schedule``, or ``APUS_FAULT_*`` environment):

    APUS_FAULT_PLANE=1          enable the wrap (implied by any other
                                APUS_FAULT_* var)
    APUS_FAULT_SEED=42          RNG seed
    APUS_FAULT_DROP=0.05        global drop probability, or per-peer
                                "1:0.2,*:0.02"
    APUS_FAULT_DELAY=0.001:0.01 uniform delay range (s); per-peer
                                "2:0.001:0.01"
    APUS_FAULT_DUP=0.1          duplicate probability (global/per-peer)
    APUS_FAULT_REORDER=0.1      reorder probability (global/per-peer)
    APUS_FAULT_THROTTLE=1:0.05  per-peer fixed pre-op stall (s)
    APUS_FAULT_PARTITION=1,2    peers blocked outbound from the start
    APUS_FAULT_SCHEDULE=...     inline JSON schedule, or @/path/to.json
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading
import time
from typing import Any, Callable, Optional

from apus_tpu.parallel.transport import (LogState, Region, Transport,
                                         WriteResult)

#: PeerServer extra-op for remote fault scripting (tests -> daemon).
OP_FAULT = 20

_WILDCARD = -1        # "every peer" key in the per-peer knob tables


@dataclasses.dataclass
class PeerFaults:
    """Per-peer fault knobs (the ``*`` row holds the defaults)."""

    drop: float = 0.0
    delay_lo: float = 0.0
    delay_hi: float = 0.0
    dup: float = 0.0
    reorder: float = 0.0
    throttle: float = 0.0
    blocked: bool = False

    def any_active(self) -> bool:
        return (self.drop > 0 or self.delay_hi > 0 or self.dup > 0
                or self.reorder > 0 or self.throttle > 0 or self.blocked)


class FaultPlane(Transport):
    """Seeded fault-injecting wrapper around a live ``Transport``.

    All Transport ops delegate to ``inner`` after passing through the
    fault pipeline; non-op surface (``set_peer``, ``close``, stats,
    ``peers`` ...) delegates transparently, so a wrapped NetTransport
    is drop-in for the daemon."""

    #: cap on how long a reorder hold may park an op (a held op must
    #: never outlive the caller's patience; the next op usually
    #: releases it far sooner).
    REORDER_HOLD_S = 0.05

    def __init__(self, inner: Transport, seed: int = 0, logger=None,
                 stats=None):
        self.inner = inner
        self.seed = seed
        self.logger = logger
        self.rng = random.Random(seed)
        self._lock = threading.Lock()
        self._peer: dict[int, PeerFaults] = {}
        self._crashed = False
        self.crash_hooks: list[Callable[[], None]] = []
        self.restart_hooks: list[Callable[[], None]] = []
        #: injected-fault counters (observability + test assertions):
        #: fault_* registry namespace (shared ObsHub view when the
        #: daemon passes one), dict-compatible with the legacy surface.
        if stats is None:
            from apus_tpu.obs.metrics import MetricsRegistry
            stats = MetricsRegistry().view("fault")
        self.stats = stats
        for k in ("drops", "delays", "dups", "reorders", "blocked",
                  "throttles", "inbound_drops", "inbound_delays",
                  "clock_cmds"):
            self.stats.setdefault(k, 0)
        #: black-box hook (ObsHub flight recorder, daemon-installed):
        #: scripted fault commands land in the ring so a failure dump
        #: shows what was injected around the violation.
        self.flight = None
        #: Adversarial-time control (utils.clock.SkewClock), installed
        #: by the daemon: the clock_rate / clock_jump / clock_reset
        #: wire commands skew THIS replica's whole clock seam — lease
        #: math, failure detector, tick stamps — like a machine whose
        #: CLOCK_MONOTONIC drifts.  None on planes without a daemon
        #: (raw-transport tests): clock commands then error loudly.
        self.clock_ctl = None
        # reorder holds: peer -> Event released by the next op
        self._holds: dict[int, threading.Event] = {}
        self._schedule: list[dict] = []
        self._sched_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- knob scripting ---------------------------------------------------

    def _state(self, peer: int, create: bool = False) -> PeerFaults:
        st = self._peer.get(peer)
        if st is None:
            if create:
                st = self._peer.setdefault(peer, PeerFaults())
            else:
                st = self._peer.get(_WILDCARD)
                if st is None:
                    st = self._peer.setdefault(_WILDCARD, PeerFaults())
        return st

    @staticmethod
    def _key(peer) -> int:
        return _WILDCARD if peer in ("*", None, _WILDCARD) else int(peer)

    def set_drop(self, peer, p: float) -> None:
        with self._lock:
            self._state(self._key(peer), create=True).drop = float(p)

    def set_delay(self, peer, lo: float, hi: Optional[float] = None) -> None:
        with self._lock:
            st = self._state(self._key(peer), create=True)
            st.delay_lo = float(lo)
            st.delay_hi = float(hi if hi is not None else lo)

    def set_dup(self, peer, p: float) -> None:
        with self._lock:
            self._state(self._key(peer), create=True).dup = float(p)

    def set_reorder(self, peer, p: float) -> None:
        with self._lock:
            self._state(self._key(peer), create=True).reorder = float(p)

    def set_throttle(self, peer, seconds: float) -> None:
        with self._lock:
            self._state(self._key(peer), create=True).throttle = \
                float(seconds)

    def block(self, peers) -> None:
        """Sever the OUTBOUND direction to ``peers`` (asymmetric
        partition: the reverse direction is the remote plane's call)."""
        with self._lock:
            for p in peers:
                self._state(self._key(p), create=True).blocked = True

    def unblock(self, peers) -> None:
        with self._lock:
            for p in peers:
                self._state(self._key(p), create=True).blocked = False

    def heal(self) -> None:
        """Clear EVERY fault (partitions, probabilities, throttles) and
        any crash — the 'network recovered' step of a schedule."""
        with self._lock:
            self._peer.clear()
            was_crashed, self._crashed = self._crashed, False
        if was_crashed:
            for cb in list(self.restart_hooks):
                cb()

    def crash(self) -> None:
        """Fail every op from now on and fire crash hooks — the
        outbound half of a process crash, without killing the process
        (its PeerServer stays up; inbound behavior is the remote
        planes' drop knobs or a real kill)."""
        with self._lock:
            if self._crashed:
                return
            self._crashed = True
        for cb in list(self.crash_hooks):
            cb()

    def restart(self) -> None:
        with self._lock:
            if not self._crashed:
                return
            self._crashed = False
        for cb in list(self.restart_hooks):
            cb()

    # -- schedule ---------------------------------------------------------

    def load_schedule(self, schedule: list[dict]) -> None:
        """Install (but do not start) a timed fault schedule: a list of
        ``{"at": seconds, "cmd": <name>, ...args}`` steps, sorted by
        ``at`` relative to :meth:`arm`.  Commands are exactly the wire
        commands of :func:`apply_command`."""
        self._schedule = sorted(schedule, key=lambda s: s.get("at", 0.0))

    def arm(self) -> None:
        """Start executing the loaded schedule on a daemon thread."""
        if not self._schedule or self._sched_thread is not None:
            return
        t = threading.Thread(target=self._run_schedule,
                             name="apus-faultplane-sched", daemon=True)
        t.start()
        self._sched_thread = t

    def stop(self) -> None:
        self._stop.set()

    def _run_schedule(self) -> None:
        t0 = time.monotonic()
        for step in self._schedule:
            delay = step.get("at", 0.0) - (time.monotonic() - t0)
            if delay > 0 and self._stop.wait(delay):
                return
            try:
                apply_command(self, step)
            except Exception:                         # noqa: BLE001
                if self.logger is not None:
                    self.logger.exception("fault schedule step %r", step)

    # -- the fault pipeline ----------------------------------------------

    def _sleep_yielding(self, seconds: float) -> None:
        """Sleep with the daemon's node lock RELEASED (when the inner
        transport carries one): injected latency models the wire, and
        NetTransport releases the lock while on the wire — an injected
        delay that held it would stall the whole daemon instead of one
        op, which is a different fault than the one being modeled."""
        lock = getattr(self.inner, "yield_lock", None)
        depth = 0
        if lock is not None:
            while lock._is_owned():     # type: ignore[attr-defined]
                lock.release()
                depth += 1
        try:
            time.sleep(seconds)
        finally:
            for _ in range(depth):
                lock.acquire()          # type: ignore[union-attr]

    def _pre(self, target: int) -> bool:
        """Run the pre-op stages.  Returns False when the op must be
        dropped (blocked / crashed / drop draw)."""
        with self._lock:
            if self._crashed:
                self.stats.bump("blocked")
                return False
            st = self._state(target)
            if st.blocked:
                self.stats.bump("blocked")
                return False
            throttle = st.throttle
            delay = (self.rng.uniform(st.delay_lo, st.delay_hi)
                     if st.delay_hi > 0 else 0.0)
            dropped = st.drop > 0 and self.rng.random() < st.drop
            reorder = (not dropped and st.reorder > 0
                       and self.rng.random() < st.reorder)
            hold = None
            release = self._holds.pop(target, None)
            if reorder:
                hold = self._holds[target] = threading.Event()
                self.stats.bump("reorders")
        # Sleeps OUTSIDE the lock (concurrent peers must not serialize).
        if release is not None:
            release.set()               # we are the "next op": release
        if throttle > 0:
            self.stats.bump("throttles")
            self._sleep_yielding(throttle)
        if delay > 0:
            self.stats.bump("delays")
            self._sleep_yielding(delay)
        if hold is not None:
            # Park until the NEXT op to this peer passes _pre (which
            # pops + sets our event), or the cap expires.  Same lock
            # yield as the sleeps: a held op is an op on the wire.
            lock = getattr(self.inner, "yield_lock", None)
            depth = 0
            if lock is not None:
                while lock._is_owned():   # type: ignore[attr-defined]
                    lock.release()
                    depth += 1
            try:
                hold.wait(self.REORDER_HOLD_S)
            finally:
                for _ in range(depth):
                    lock.acquire()        # type: ignore[union-attr]
            with self._lock:
                if self._holds.get(target) is hold:
                    del self._holds[target]
        if dropped:
            self.stats.bump("drops")
            return False
        return True

    def _dup_draw(self, target: int) -> bool:
        with self._lock:
            st = self._state(target)
            if st.dup > 0 and self.rng.random() < st.dup:
                self.stats.bump("dups")
                return True
        return False

    # -- Transport surface -------------------------------------------------

    def peer_established(self, target: int) -> bool:
        return self.inner.peer_established(target)

    def peer_failure_was_timeout(self, target: int) -> bool:
        return self.inner.peer_failure_was_timeout(target)

    def ctrl_write(self, target: int, region: Region, slot: int,
                   value: Any) -> WriteResult:
        if not self._pre(target):
            return WriteResult.DROPPED
        res = self.inner.ctrl_write(target, region, slot, value)
        if self._dup_draw(target):
            self.inner.ctrl_write(target, region, slot, value)
        return res

    def ctrl_read(self, target: int, region: Region, slot: int) -> Any:
        if not self._pre(target):
            return None
        return self.inner.ctrl_read(target, region, slot)

    def log_write(self, target: int, writer_sid, entries, commit):
        if not self._pre(target):
            return WriteResult.DROPPED, None
        res = self.inner.log_write(target, writer_sid, entries, commit)
        if self._dup_draw(target):
            self.inner.log_write(target, writer_sid, entries, commit)
        return res

    def log_read_state(self, target: int) -> Optional[LogState]:
        if not self._pre(target):
            return None
        return self.inner.log_read_state(target)

    def log_set_end(self, target: int, writer_sid,
                    new_end: int) -> WriteResult:
        if not self._pre(target):
            return WriteResult.DROPPED
        return self.inner.log_set_end(target, writer_sid, new_end)

    def log_bulk_read(self, target: int, start: int, stop: int):
        if not self._pre(target):
            return None
        return self.inner.log_bulk_read(target, start, stop)

    def snap_push(self, target: int, writer_sid, snap, ep_dump,
                  cid=None, member_addrs=None,
                  delta_base=None) -> WriteResult:
        if not self._pre(target):
            return WriteResult.DROPPED
        return self.inner.snap_push(target, writer_sid, snap, ep_dump,
                                    cid, member_addrs,
                                    delta_base=delta_base)

    def snap_push_stream(self, target: int, *args, **kwargs):
        if not self._pre(target):
            return WriteResult.DROPPED
        return self.inner.snap_push_stream(target, *args, **kwargs)

    def request(self, target: int, payload: bytes,
                **kw) -> Optional[bytes]:
        if not self._pre(target):
            return None
        resp = self.inner.request(target, payload, **kw)
        if self._dup_draw(target):
            self.inner.request(target, payload, **kw)
        return resp

    # -- non-op delegation (set_peer, close, peers, stats, ...) -----------

    def __getattr__(self, name: str):
        # Only reached for attributes not defined on FaultPlane.
        return getattr(self.inner, name)

    # -- inbound handler wrapping (mesh descriptor channel etc.) ----------

    def wrap_handler(self, tag: str, handler):
        """Wrap a PeerServer extra-op handler with INBOUND faults,
        keyed by the wildcard row's drop/delay knobs via the dedicated
        ``inbound`` peer key (-2).  A dropped inbound message returns
        ST_ERROR — for the mesh descriptor channel that is a NACK,
        which kills the sender's feed and deterministically exercises
        plane degradation + re-formation."""
        from apus_tpu.parallel import wire

        def wrapped(r):
            with self._lock:
                st = self._peer.get(_INBOUND)
                drop = (st is not None and st.drop > 0
                        and self.rng.random() < st.drop)
                delay = (self.rng.uniform(st.delay_lo, st.delay_hi)
                         if st is not None and st.delay_hi > 0 else 0.0)
            if delay > 0:
                self.stats.bump("inbound_delays")
                time.sleep(delay)
            if drop:
                self.stats.bump("inbound_drops")
                if self.logger is not None:
                    self.logger.warning("faultplane: dropping inbound "
                                        "%s message", tag)
                return wire.u8(wire.ST_ERROR)
            return handler(r)

        return wrapped

    def set_inbound_drop(self, p: float) -> None:
        with self._lock:
            st = self._peer.setdefault(_INBOUND, PeerFaults())
            st.drop = float(p)

    def set_inbound_delay(self, lo: float, hi: Optional[float] = None) \
            -> None:
        with self._lock:
            st = self._peer.setdefault(_INBOUND, PeerFaults())
            st.delay_lo = float(lo)
            st.delay_hi = float(hi if hi is not None else lo)


_INBOUND = -2         # inbound-handler knob row (wrap_handler)


# -- wire scripting (OP_FAULT) ----------------------------------------------


def apply_command(plane: FaultPlane, cmd: dict) -> dict:
    """Apply one scripting command (shared by wire op + schedules).
    Returns a result dict (counters for ``stats``)."""
    c = cmd.get("cmd")
    if plane.flight is not None and c != "stats":
        plane.flight.note("fault", c, **{k: v for k, v in cmd.items()
                                         if k != "cmd"})
    if c == "drop":
        plane.set_drop(cmd.get("peer", "*"), cmd["p"])
    elif c == "delay":
        plane.set_delay(cmd.get("peer", "*"), cmd["lo"],
                        cmd.get("hi"))
    elif c == "dup":
        plane.set_dup(cmd.get("peer", "*"), cmd["p"])
    elif c == "reorder":
        plane.set_reorder(cmd.get("peer", "*"), cmd["p"])
    elif c == "throttle":
        plane.set_throttle(cmd.get("peer", "*"), cmd["seconds"])
    elif c == "block":
        plane.block(cmd["peers"])
    elif c == "unblock":
        plane.unblock(cmd["peers"])
    elif c == "heal":
        plane.heal()
    elif c == "crash":
        plane.crash()
    elif c == "restart":
        plane.restart()
    elif c == "inbound_drop":
        plane.set_inbound_drop(cmd["p"])
    elif c == "inbound_delay":
        plane.set_inbound_delay(cmd["lo"], cmd.get("hi"))
    elif c in ("clock_rate", "clock_jump", "clock_reset"):
        # Adversarial time (the SkewClock seam): rate skew, step jumps,
        # back to real rate.  Scriptable over the wire AND from seeded
        # schedules, like every other fault.
        ctl = getattr(plane, "clock_ctl", None)
        if ctl is None:
            raise ValueError("no clock control on this plane "
                             "(daemon-installed SkewClock required)")
        if c == "clock_rate":
            ctl.set_rate(cmd["rate"])
        elif c == "clock_jump":
            ctl.jump(cmd["seconds"])
        else:
            ctl.reset()
        plane.stats.bump("clock_cmds")
    elif c == "stats":
        pass                            # stats ride every reply
    else:
        raise ValueError(f"unknown fault command {c!r}")
    with plane._lock:
        return dict(plane.stats)


def make_fault_ops(daemon) -> dict:
    """PeerServer extra op: remote fault scripting against a live
    daemon (ProcCluster tests compose cluster-wide partitions by
    scripting each member's plane).  Only registered when the daemon's
    transport IS a FaultPlane — a production daemon without the wrap
    exposes nothing."""
    from apus_tpu.parallel import wire

    def fault_op(r) -> bytes:
        plane = daemon.transport
        if not isinstance(plane, FaultPlane):
            return wire.u8(wire.ST_ERROR)
        try:
            cmd = json.loads(r.blob().decode())
            stats = apply_command(plane, cmd)
        except (ValueError, KeyError) as e:
            return wire.u8(wire.ST_ERROR) + wire.blob(repr(e).encode())
        return wire.u8(wire.ST_OK) + wire.blob(
            json.dumps(stats).encode())

    return {OP_FAULT: fault_op}


def send_fault(addr: str, cmd: dict,
               timeout: float = 2.0) -> Optional[dict]:
    """Script one fault command into a live daemon (test-side client of
    ``make_fault_ops``).  Returns the plane's fault counters, or None
    if the daemon is unreachable / has no fault plane."""
    import socket

    from apus_tpu.parallel import wire
    host, port = addr.rsplit(":", 1)
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as s:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(timeout)
            s.sendall(wire.frame(
                wire.u8(OP_FAULT) + wire.blob(json.dumps(cmd).encode())))
            resp = wire.read_frame(s)
    except (OSError, ConnectionError, ValueError):
        return None
    if not resp or resp[0] != wire.ST_OK:
        return None
    try:
        return json.loads(wire.Reader(resp[1:]).blob().decode())
    except (ValueError, KeyError):
        return None


def isolate(peers: list[str], victim: int,
            timeout: float = 2.0) -> bool:
    """Bidirectionally partition ``victim`` from every other member by
    scripting BOTH directions (victim's outbound + each peer's
    outbound-to-victim).  Client connections are untouched — exactly
    the interesting scenario (an isolated leader still reachable by
    its clients must not ack unreplicatable writes)."""
    ok = True
    others = [i for i, a in enumerate(peers) if a and i != victim]
    ok &= send_fault(peers[victim], {"cmd": "block", "peers": others},
                     timeout=timeout) is not None
    for i in others:
        ok &= send_fault(peers[i], {"cmd": "block", "peers": [victim]},
                         timeout=timeout) is not None
    return bool(ok)


def heal_all(peers: list[str], timeout: float = 2.0) -> bool:
    ok = True
    for a in peers:
        if a:
            ok &= send_fault(a, {"cmd": "heal"},
                             timeout=timeout) is not None
    return bool(ok)


# -- env / config parsing ----------------------------------------------------


def _parse_per_peer(s: str, arity: int) -> list[tuple]:
    """Parse "<peer>:v[,...]" (or bare "v" = wildcard).  ``arity`` is
    how many numeric fields follow the optional peer key."""
    out = []
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) == arity:          # no peer key: wildcard
            out.append(("*", *[float(b) for b in bits]))
        else:
            out.append((bits[0], *[float(b) for b in bits[1:]]))
    return out


def config_from_env(env: Optional[dict] = None) -> Optional[dict]:
    """Collect APUS_FAULT_* settings into a config dict, or None when
    no fault-plane variable is set."""
    e = os.environ if env is None else env
    keys = [k for k in e if k.startswith("APUS_FAULT_")]
    if not keys:
        return None
    cfg: dict = {"seed": int(e.get("APUS_FAULT_SEED", "0") or 0)}
    if e.get("APUS_FAULT_DROP"):
        cfg["drop"] = _parse_per_peer(e["APUS_FAULT_DROP"], 1)
    if e.get("APUS_FAULT_DELAY"):
        cfg["delay"] = _parse_per_peer(e["APUS_FAULT_DELAY"], 2)
    if e.get("APUS_FAULT_DUP"):
        cfg["dup"] = _parse_per_peer(e["APUS_FAULT_DUP"], 1)
    if e.get("APUS_FAULT_REORDER"):
        cfg["reorder"] = _parse_per_peer(e["APUS_FAULT_REORDER"], 1)
    if e.get("APUS_FAULT_THROTTLE"):
        cfg["throttle"] = _parse_per_peer(e["APUS_FAULT_THROTTLE"], 1)
    if e.get("APUS_FAULT_PARTITION"):
        cfg["partition"] = [int(p) for p in
                            e["APUS_FAULT_PARTITION"].split(",") if p]
    sched = e.get("APUS_FAULT_SCHEDULE", "")
    if sched:
        if sched.startswith("@"):
            with open(sched[1:]) as f:
                cfg["schedule"] = json.load(f)
        else:
            cfg["schedule"] = json.loads(sched)
    return cfg


def build_plane(inner: Transport, cfg: dict, logger=None,
                obs=None) -> FaultPlane:
    """Construct + configure a FaultPlane from a config dict (the
    ``config_from_env`` / ClusterSpec shape).  The schedule is loaded
    but NOT armed — the daemon arms it once it serves.  ``obs`` (an
    ObsHub) routes the injected-fault counters into the shared
    registry and scripted commands into the flight recorder."""
    plane = FaultPlane(inner, seed=int(cfg.get("seed", 0)), logger=logger,
                       stats=obs.view("fault") if obs is not None
                       else None)
    if obs is not None:
        plane.flight = obs.flight
    for peer, p in cfg.get("drop", []):
        plane.set_drop(peer, p)
    for peer, lo, hi in cfg.get("delay", []):
        plane.set_delay(peer, lo, hi)
    for peer, p in cfg.get("dup", []):
        plane.set_dup(peer, p)
    for peer, p in cfg.get("reorder", []):
        plane.set_reorder(peer, p)
    for peer, s in cfg.get("throttle", []):
        plane.set_throttle(peer, s)
    if cfg.get("partition"):
        plane.block(cfg["partition"])
    if cfg.get("schedule"):
        plane.load_schedule(cfg["schedule"])
    return plane


def maybe_wrap(inner: Transport, spec=None, logger=None,
               env: Optional[dict] = None, obs=None) -> Transport:
    """The daemon's single integration point: wrap ``inner`` when the
    fault plane is enabled by spec (``fault_plane=True``) or any
    APUS_FAULT_* env var; otherwise return ``inner`` untouched (zero
    overhead for production daemons)."""
    cfg = config_from_env(env)
    spec_on = bool(getattr(spec, "fault_plane", False))
    if cfg is None and not spec_on:
        return inner
    if cfg is None:
        cfg = {}
    if spec is not None:
        cfg.setdefault("seed", getattr(spec, "fault_seed", 0))
        sched = getattr(spec, "fault_schedule", "")
        if sched and "schedule" not in cfg:
            if sched.startswith("@"):
                with open(sched[1:]) as f:
                    cfg["schedule"] = json.load(f)
            else:
                cfg["schedule"] = json.loads(sched)
    return build_plane(inner, cfg, logger=logger, obs=obs)
