"""Native serving data plane: Python control surface (ISSUE 13).

``native/dataplane.cpp`` owns the leader's serving hot path with the
GIL released — epoll frame ingest, OP_GROUP demux, endpoint-DB dedup
fast path, lease-GET serving from a native applied view, and vectored
reply flush.  This module is the ONLY code that talks to it:

- :func:`load_extension` finds/loads the compiled module
  (``native/build/apus_dataplane.so``; ``APUS_DATAPLANE_SO`` overrides
  — the sanitizer test points it at the ASAN flavor);
- :class:`NativePlaneService` glues one plane to one ``ReplicaDaemon``:
  worker threads pull bursts from ``plane.next_work()`` (blocking with
  the GIL released) and run the daemon's group-commit batch hook — the
  node-lock admission boundary is the ONE place the hot path crosses
  back into Python, so election/membership/reconfiguration/txn control
  stay in ``core/node.py`` untouched;
- gate publishing: every daemon tick re-publishes, per consensus
  group, whether the native side may serve GETs (leader lease live or
  follower lease live, log fully applied, no txn locks / elastic
  fences) and whether the dedup fast path may answer (leader as of the
  tick).  Any inbound log write / truncation / snapshot op closes the
  read gate SYNCHRONOUSLY (``on_peer_write`` from the PeerServer) —
  the Hermes-style write invalidation that makes a between-tick
  follower serve impossible; a scripted clock jump closes every gate
  through the SkewClock's ``on_skew`` hook.

Safety argument (DESIGN.md "Native data plane" has the long form):
the native read gate is a CONSERVATIVE projection of exactly the
checks Python's lease read paths make — published under the node lock
each tick with a deadline of at most half the remaining lease window
(so clock-rate skew inside the documented lease_margin envelope cannot
stretch it past the real expiry), and killed synchronously by every
event that could make the applied view stale before the next tick.
Replies are byte-identical to the Python plane's by construction
(``tests/test_native_plane.py`` pins it on live tapes).

Fallback: when the extension is absent (or ``APUS_NATIVE_PLANE=0``)
the daemon keeps the pure-Python plane — same wire behavior, this
module never loads the .so, and enabling the spec knob merely logs
loudly + notes the flight ring.
"""

from __future__ import annotations

import importlib.machinery
import importlib.util
import os
import threading
import time
from typing import Optional

_EXT = None
_EXT_ERR: Optional[str] = None
_EXT_LOCK = threading.Lock()


def _default_so_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "native", "build", "apus_dataplane.so")


def load_extension():
    """The compiled dataplane module, or None (reason in
    :func:`load_error`).  Cached; ``APUS_DATAPLANE_SO`` overrides the
    default build path (the module name follows the file stem, so the
    ASAN flavor coexists with the standard one)."""
    global _EXT, _EXT_ERR
    with _EXT_LOCK:
        if _EXT is not None or _EXT_ERR is not None:
            return _EXT
        path = os.environ.get("APUS_DATAPLANE_SO") or _default_so_path()
        if not os.path.exists(path):
            _EXT_ERR = f"extension not built ({path} missing); " \
                       f"run `make -C native dataplane`"
            return None
        name = os.path.basename(path).split(".")[0]
        try:
            loader = importlib.machinery.ExtensionFileLoader(name, path)
            spec = importlib.util.spec_from_loader(name, loader)
            mod = importlib.util.module_from_spec(spec)
            loader.exec_module(mod)
        except (ImportError, OSError) as e:    # pragma: no cover
            _EXT_ERR = f"extension load failed: {e}"
            return None
        _EXT = mod
        return _EXT


def load_error() -> Optional[str]:
    return _EXT_ERR


def plane_requested(spec) -> bool:
    """Is the native plane requested for this daemon?  The env var
    overrides the spec both ways (``APUS_NATIVE_PLANE=1`` arms it on
    stock specs — the fuzz/soak ``--native-plane`` plumbing — and
    ``=0`` force-disables it)."""
    env = os.environ.get("APUS_NATIVE_PLANE")
    if env is not None and env != "":
        return env not in ("0", "false", "no")
    return bool(getattr(spec, "native_plane", False))


#: SM attributes whose non-emptiness means the applied view cannot be
#: served (txn 2PL locks, elastic migration fences) — mirrors the
#: refusal fences at the top of KvsStateMachine.apply.
_SM_FENCES = ("_locks", "_frozen", "_departed")

#: Rebuild (rather than permanently poison) the applied view after a
#: snapshot install when the store is at most this many items.
_VIEW_REBUILD_MAX = int(os.environ.get("APUS_NATIVE_VIEW_REBUILD_MAX",
                                       "200000"))


class NativePlaneService:
    """One daemon's native data plane: plane object + worker pool +
    gate publishing + applied-view maintenance."""

    def __init__(self, daemon, ext, workers: Optional[int] = None):
        from apus_tpu.parallel.net import PeerServer
        self.daemon = daemon
        self.ext = ext
        self.stats = daemon.server.stats      # srv_* registry view
        # Dedup fast-path answers skip the bench's write-service
        # emulation gate; keep byte-AND-timing parity when that gate
        # is armed by routing every write through Python.
        dedup = not getattr(daemon, "write_svc", 0.0)
        self._reads_ok = (daemon.elastic is None
                          and not getattr(daemon, "read_svc", 0.0))
        self.plane = ext.Plane(max_burst=PeerServer.MAX_BURST,
                               dedup=dedup)
        # Native admission mirror (ISSUE 17): the C++ ingest loop
        # counts in-flight client frames and sheds typed ST_OVERLOAD
        # replies BEFORE crossing the GIL once the budget is hit —
        # same bytes as runtime.overload.shed_reply (the equivalence
        # tape pins it).  hasattr-guarded so an older .so still loads.
        ovl = getattr(daemon, "overload", None)
        if ovl is not None and hasattr(self.plane, "set_overload"):
            self.plane.set_overload(ovl.max_native_inflight,
                                    ovl.retry_after_ms)
        self._workers: list[threading.Thread] = []
        self._nworkers = workers if workers is not None else int(
            os.environ.get("APUS_NATIVE_WORKERS", "16"))
        self._stopped = threading.Event()
        self._gid_reads_seen: dict[int, int] = {}
        self._view_ok: dict[int, bool] = {}
        self.running = False

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self.plane.start()
        self.running = True
        for i in range(max(1, self._nworkers)):
            t = threading.Thread(target=self._worker,
                                 name=f"apus-nplane-{self.daemon.idx}-{i}",
                                 daemon=True)
            t.start()
            self._workers.append(t)
        # Initial applied view (post-replay state) for group 0; extra
        # groups never serve native reads (the elastic plane owns
        # bucket routing there — see publish_gates).
        if self._reads_ok:
            self._load_view(0)
            # The C read gate cannot check per-key bucket membership,
            # so this daemon's followers request FULL-SET leases —
            # publish_gates only opens the follower gate for those
            # (a bucket-scoped lease would let the native side serve
            # keys outside the granted read set).
            node = self.daemon.group_node(0) \
                if hasattr(self.daemon, "group_node") else self.daemon.node
            if node is not None:
                node.flr_full_buckets = True
        # Scripted clock jumps must close the read gates through the
        # same seam the lease math skews on.
        clock = getattr(self.daemon, "clock", None)
        if clock is not None:
            clock.on_skew = self.plane.invalidate
        if self.daemon.obs is not None:
            self.daemon.obs.flight.note(
                "native", "plane_active",
                workers=self._nworkers,
                reads=bool(self._reads_ok))

    def stop(self) -> None:
        self.running = False
        self._stopped.set()
        clock = getattr(self.daemon, "clock", None)
        if clock is not None and getattr(clock, "on_skew", None) \
                == self.plane.invalidate:
            clock.on_skew = None
        self.plane.stop()

    # -- connection adoption (PeerServer hands clients over) -----------

    def adopt_socket(self, conn, first_frame: bytes, stream) -> bool:
        """Take ownership of a client connection: the already-read
        first frame plus whatever the FrameStream had buffered seed the
        native recv buffer; the Python socket object is detached (the
        plane owns the fd from here)."""
        from apus_tpu.parallel import wire
        if not self.running:
            return False
        initial = wire.frame(first_frame) + stream.detach_buffer()
        fd = conn.detach()
        if not self.plane.adopt(fd, initial):
            try:
                os.close(fd)
            except OSError:
                pass
            return True          # plane stopping: the conn dies with it
        self.stats.bump("native_adopted")
        return True

    @staticmethod
    def is_client_frame(req: bytes) -> bool:
        from apus_tpu.runtime.client import OP_CLT_READ, OP_CLT_WRITE
        from apus_tpu.parallel import wire
        if not req:
            return False
        op = req[0]
        if op == wire.OP_GROUP and len(req) >= 3:
            op = req[2]
        return op in (OP_CLT_WRITE, OP_CLT_READ)

    # -- worker pool (the GIL-crossing admission boundary) -------------

    #: cross-connection merge bound: one worker coalesces queued
    #: bursts from SEVERAL connections into one admission call (one
    #: node-lock acquisition + one commit wait for all of them — the
    #: group-commit drain amortized past what the per-connection
    #: Python plane can reach), up to this many frames.
    MERGE_FRAMES = 512

    def _worker(self) -> None:
        plane = self.plane
        daemon = self.daemon
        while not self._stopped.is_set():
            try:
                work = plane.next_work(0.5)
            except Exception:
                return                      # plane torn down
            if work is None:
                continue
            # Cross-conn merge: drain more PARSED bursts non-blocking.
            # Raw bursts never merge (their frames dispatch alone).
            merged = [work]
            if work[1]:
                total = len(work[2])
                while total < self.MERGE_FRAMES:
                    try:
                        more = plane.next_work(0.0)
                    except Exception:
                        more = None
                    if more is None:
                        break
                    merged.append(more)
                    total += len(more[2])
                    if not more[1]:
                        break               # raw burst: stop merging
            for batch_id, parsed, items in self._run_merged(merged):
                try:
                    plane.complete(batch_id, items)
                except Exception:
                    return

    def _run_merged(self, merged):
        """Run a list of (batch_id, parsed, items) through admission —
        parsed bursts concatenated into ONE hook call — and yield
        (batch_id, _, replies) per input batch (reply order within
        each burst preserved; the wire stays byte-identical because
        each connection's replies are exactly its requests', in
        order)."""
        from apus_tpu.parallel import wire
        daemon = self.daemon
        # Arrival stamp for the drain's deadline shed (ISSUE 17): the
        # node-lock wait from HERE counts against the client deadline
        # (the native in-flight budget bounds queueing before this
        # point, so worker-pull time is the dominant seam).
        arrival = time.monotonic()
        parsed_batches = [(bid, items) for bid, p, items in merged if p]
        raw_batches = [(bid, items) for bid, p, items in merged
                       if not p]
        out = []
        if parsed_batches:
            if len(parsed_batches) > 1:
                self.stats.bump("native_merged_bursts",
                                len(parsed_batches))
            all_items = []
            for _bid, items in parsed_batches:
                all_items.extend(items)
            try:
                replies = daemon.server.batch_hook.run_parsed(
                    all_items, arrival)
            except Exception:
                daemon.logger.exception("native-plane batch failed")
                self.stats.bump("native_errors")
                replies = [wire.u8(wire.ST_ERROR) for _ in all_items]
            off = 0
            for bid, items in parsed_batches:
                out.append((bid, None, replies[off:off + len(items)]))
                off += len(items)
        for bid, frames in raw_batches:
            try:
                replies = self._dispatch_raw(frames)
            except Exception:
                daemon.logger.exception("native-plane batch failed")
                self.stats.bump("native_errors")
                replies = [wire.u8(wire.ST_ERROR) for _ in frames]
            out.append((bid, None, replies))
        return out

    def _dispatch_raw(self, frames: list) -> list:
        """Bursts carrying any non-client frame: exactly the Python
        plane's path — the batch hook if it accepts, else sequential
        dispatch (order preserved)."""
        hook = self.daemon.server.batch_hook
        replies = None
        if hook is not None and len(frames) > 1:
            replies = hook(frames)
        if replies is None:
            self.stats.bump("native_fallbacks")
            replies = [self.daemon.server._dispatch(f) for f in frames]
        return replies

    # -- per-tick gate publishing (called under the node lock) ---------

    def publish_gates(self) -> None:
        daemon = self.daemon
        plane = self.plane
        for gid in range(getattr(daemon, "n_groups", 1)):
            node = daemon.group_node(gid)
            if node is None:
                continue
            leaderish = node.is_leader
            valid_ns = 0
            if self._reads_ok and gid == 0 \
                    and self._view_ok.get(gid, gid == 0) \
                    and node.log.apply == node.log.end \
                    and not any(getattr(node.sm, a, None)
                                for a in _SM_FENCES):
                fnow = node._fresh_now()
                if leaderish:
                    if node._lease_valid(fnow):
                        valid_ns = self._deadline(
                            node._lease_until - fnow)
                elif node.role.name == "FOLLOWER" \
                        and not node.draining \
                        and node._flr_enabled() \
                        and node.lease_requester is not None \
                        and node._flease_buckets is None \
                        and node.log.apply >= node._flease_floor:
                    ok, _why = node._flease_ok(fnow)
                    if ok:
                        valid_ns = self._deadline(
                            node._flease_until - fnow)
            plane.publish(gid, leaderish, valid_ns)
            # Fold native read serves into the node's own lease-read
            # accounting (OP_STATUS / campaign coverage pins keep
            # meaning either plane), and keep the follower lease warm
            # while the native side is the one serving.
            served = plane.gid_reads(gid)
            delta = served - self._gid_reads_seen.get(gid, 0)
            if delta:
                self._gid_reads_seen[gid] = served
                node.reads_done += delta
                if leaderish:
                    node.bump("lease_reads", delta)
                else:
                    node.bump("flr_local_reads", delta)
                    node._flr_hot_until = node._fresh_now() + 1.0

    def _deadline(self, remaining_s: float) -> int:
        """Published gate validity: at most HALF the remaining lease
        window (absorbs clock-rate skew far beyond the lease_margin
        envelope) and at most one heartbeat period (so a gate never
        outlives the conditions by more than a tick-ish horizon)."""
        if remaining_s <= 0:
            return 0
        cap = min(remaining_s * 0.5, self.daemon.spec.hb_period)
        return max(0, int(cap * 1e9))

    # -- synchronous invalidation (peer writes, Hermes-style) ----------

    def on_peer_write(self, node) -> None:
        """An inbound log write / truncation / snapshot op landed on
        ``node``: its group's applied view may be about to change —
        close the read gate NOW (re-published next tick once applied
        catches up).  Called from PeerServer handler threads under the
        node lock."""
        self.plane.invalidate(getattr(node, "gid", 0))

    # -- applied-view maintenance (under the node lock, apply time) ----

    def on_entry_applied(self, e) -> None:
        """Group-0 committed-entry observer (daemon.on_commit): mirror
        the applied command into the native view.  Any command the
        mirror cannot track (typed RDT ops, txn/migration records)
        poisons it — the read gate then stays closed for the session
        and GETs simply keep their Python path."""
        if not self._reads_ok or not self._view_ok.get(0, True):
            return
        if self.plane.view_apply(0, e.data):
            self._view_ok[0] = False
            self.stats.bump("native_view_poisoned")

    def on_snapshot_installed(self, snap, ep_dump) -> None:
        """A snapshot replaced group-0 state wholesale: rebuild the
        view from the store (bounded), else poison it."""
        if not self._reads_ok:
            return
        self.plane.invalidate(0)
        self._load_view(0)

    def _load_view(self, gid: int) -> None:
        node = self.daemon.group_node(gid)
        store = getattr(node.sm, "store", None) if node is not None \
            else None
        if store is None or len(store) > _VIEW_REBUILD_MAX \
                or any(getattr(node.sm, a, None) for a in _SM_FENCES):
            self.plane.view_poison(gid)
            self._view_ok[gid] = False
            if store is not None:
                self.stats.bump("native_view_poisoned")
            return
        poisoned = self.plane.view_load(gid, list(store.items()))
        self._view_ok[gid] = not poisoned

    # -- observability -------------------------------------------------

    def sync_gauges(self, registry) -> None:
        """Mirror the plane's C counters as srv_native_* gauges (scrape
        time / OP_STATUS, like the daemon/persistence scalars)."""
        for name, v in self.plane.counters().items():
            registry.gauge(f"srv_native_{name}").set(v)

    def status_view(self) -> dict:
        c = self.plane.counters()
        c["conns"] = self.plane.conn_count()
        c["workers"] = len(self._workers)
        c["reads_enabled"] = bool(self._reads_ok)
        return c


def maybe_build(daemon):
    """Build + install the native plane for a daemon when requested.
    Returns the service or None; an absent extension degrades LOUDLY
    to the Python plane (log + flight note + counter)."""
    if not plane_requested(daemon.spec):
        return None
    ext = load_extension()
    if ext is None:
        daemon.logger.error(
            "NATIVE PLANE REQUESTED BUT UNAVAILABLE (%s); "
            "falling back to the pure-Python serving plane",
            load_error())
        daemon.server.stats.bump("native_unavailable")
        if daemon.obs is not None:
            daemon.obs.flight.note("native", "plane_unavailable",
                                   reason=load_error() or "")
        return None
    svc = NativePlaneService(daemon, ext)
    return svc
