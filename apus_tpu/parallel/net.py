"""DCN transport: one-sided ops over TCP between replica daemons.

The reference's data plane is one-sided RDMA over per-peer RC queue pairs
(dare_ibv_rc.c) and its control plane is UD + IB multicast
(dare_ibv_ud.c).  On TPU pods the analogous host-side fabric is the data
center network; this module is the initiator/target pair:

- ``PeerServer`` — the passive target.  A listener thread accepts peer
  connections; every request frame is applied to the local node's exposed
  regions via apus_tpu.parallel.onesided (the "HCA DMA"), under the
  daemon's node lock, and a response frame is returned.  The protocol
  logic never runs here — exactly as the reference's followers are
  passive on the replication path.
- ``NetTransport`` — the initiator.  One lazily-connected TCP socket per
  peer (the RC QP analog), blocking request/response with a short
  timeout; any socket error marks the peer down for a backoff window and
  surfaces as DROPPED/None, feeding the failure detector the way CTRL-QP
  work-completion errors do (dare_ibv_rc.c:2747-2749).

Locking model: the caller may pass ``yield_lock`` — the daemon's node
lock.  The transport *releases it while blocked on the wire* and
reacquires before returning, mirroring one-sided semantics (remote writes
land in our regions while we wait) and preventing distributed deadlock
between two daemons writing to each other simultaneously.
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time
from typing import Any, Callable, Optional

from apus_tpu.core.cid import Cid
from apus_tpu.core.log import LogEntry
from apus_tpu.core.node import Node
from apus_tpu.core.sid import Sid
from apus_tpu.obs.metrics import MetricsRegistry
from apus_tpu.parallel import onesided, wire
from apus_tpu.parallel.transport import (LogState, Region, Transport,
                                         WriteResult)
#: Client DATA ops — the only frames admission budgets ever count or
#: shed.  Everything else (HB/vote/lease/CONFIG/snapshot/peer region
#: ops) bypasses the gate: strict priority for control traffic, so
#: overload can never burn a leadership.
_CLIENT_OPS = frozenset((16, 17))          # OP_CLT_WRITE / OP_CLT_READ


def _is_client_frame(f: bytes) -> bool:
    if not f:
        return False
    if f[0] == wire.OP_GROUP:
        return len(f) >= 3 and f[2] in _CLIENT_OPS
    return f[0] in _CLIENT_OPS


def _shed_frame_reply(f: bytes, retry_ms: int) -> bytes:
    """Typed ST_OVERLOAD reply for a client frame refused admission
    (echoes the req_id so reply pairing survives, exactly like every
    other typed refusal)."""
    # Late import: runtime/__init__ imports the daemon which imports
    # this module — at module-import time runtime.overload is not yet
    # reachable.  After first use this is one sys.modules lookup, and
    # it only sits on the shed path.
    from apus_tpu.runtime.overload import shed_reply as _shed_reply
    off = 3 if f[0] == wire.OP_GROUP else 1
    req_id = (int.from_bytes(f[off:off + 8], "little")
              if len(f) >= off + 8 else 0)
    return _shed_reply(req_id, retry_ms)


_ST_OF_RESULT = {WriteResult.OK: wire.ST_OK,
                 WriteResult.DROPPED: wire.ST_DROPPED,
                 WriteResult.FENCED: wire.ST_FENCED,
                 WriteResult.REFUSED: wire.ST_REFUSED}
_RESULT_OF_ST = {v: k for k, v in _ST_OF_RESULT.items()}


class PeerServer:
    """Passive target endpoint exposing a node's regions to peers."""

    def __init__(self, node_ref: Callable[[], Node], lock: threading.RLock,
                 host: str = "127.0.0.1", port: int = 0,
                 sock: Optional[socket.socket] = None,
                 extra_ops: Optional[dict] = None, logger=None,
                 stats=None):
        self._node_ref = node_ref
        self._lock = lock
        self._logger = logger
        #: ingest observability (srv_* namespace when the daemon passes
        #: its ObsHub view): how many frames arrive per burst drain —
        #: the direct evidence that pipelined clients coalesce on the
        #: wire (the de-flaked throughput smoke asserts on it).
        self.stats = stats if stats is not None \
            else MetricsRegistry().view("srv")
        # extra_ops: op byte -> handler(body_reader) -> response payload
        # (used by the runtime for JOIN / snapshot-fetch, which are
        # two-sided control messages, not one-sided region ops).
        self._extra_ops = extra_ops if extra_ops is not None else {}
        if sock is not None:
            self._sock = sock
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((host, port))
        self._sock.listen(64)
        self.addr = self._sock.getsockname()
        #: Multi-group demux (runtime/groupset.py): gid -> GroupPort
        #: (``.node`` + ``.extra_ops``) or None for unknown gids.  Left
        #: None on single-group daemons — OP_GROUP / OP_HB_MULTI frames
        #: then answer ST_ERROR and nothing else changes.
        self.group_ref = None
        #: Optional pipelined-burst handler, installed by the daemon:
        #: called with a LIST of already-queued request frames, returns
        #: the reply payloads (same order) or None to decline — the
        #: frames then dispatch sequentially.  Lets K pipelined client
        #: ops share one lock acquisition + one commit wait instead of
        #: serializing: op i+1 is admitted before op i's commit.
        self.batch_hook = None
        #: Native serving data plane (parallel.native_plane), installed
        #: by the daemon when enabled: connections whose FIRST frame is
        #: a client op are handed to its GIL-released C++ loop and
        #: never return to this thread; peer/control connections stay
        #: here.  None (default) = the pure-Python plane, unchanged.
        self.native_plane = None
        #: Overload control plane (runtime.overload.OverloadPolicy),
        #: installed by the daemon: bounded global + per-connection
        #: in-flight budgets for client DATA ops, typed ST_OVERLOAD
        #: sheds for the excess.  Control frames bypass the gate
        #: entirely (strict priority).  None = admission unlimited.
        self.overload = None
        self._stop = threading.Event()
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        self._accept_thread: Optional[threading.Thread] = None

    @staticmethod
    def reserve(host: str = "127.0.0.1") -> socket.socket:
        """Bind an ephemeral port now so a ClusterSpec can be built before
        the servers start (the reference knows peers from nodes.cfg)."""
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s

    def start(self) -> None:
        t = threading.Thread(target=self._accept_loop,
                             name=f"apus-peersrv-{self.addr[1]}", daemon=True)
        t.start()
        self._accept_thread = t

    def stop(self) -> None:
        """Kill the endpoint: listener AND every established connection —
        a stopped replica must not serve or mutate anything afterwards
        (crash-fault fidelity for kill-based tests)."""
        self._stop.set()
        try:
            # shutdown() wakes the thread blocked in accept(); a bare
            # close() would leave the kernel LISTEN socket alive (the
            # blocked accept holds a reference) and the port unbindable.
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns, self._conns = list(self._conns), set()
        for c in conns:
            try:
                # RST-close (linger 0): like a crashed process, and the
                # port is immediately rebindable (a FIN-close parks the
                # accepted sockets in FIN_WAIT, blocking restart binds).
                c.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                if self._stop.is_set():
                    conn.close()
                    continue
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    #: Max frames drained per burst before replying (bounds the reply
    #: latency of the first op in an endless inbound stream).
    MAX_BURST = 256

    def _serve(self, conn: socket.socket) -> None:
        stream = wire.FrameStream(conn)
        try:
            while not self._stop.is_set():
                req = stream.next_frame()
                if req is None or self._stop.is_set():
                    return
                # Native-plane adoption: a connection that OPENS with a
                # client op is a client connection (clients dedicate
                # their sockets to CLT ops) — hand the fd, the frame,
                # and the stream's buffered remainder to the C++ loop
                # and retire this thread.  Decided on the first frame
                # only; peer/control traffic never matches.
                np = self.native_plane
                if np is not None and np.running \
                        and np.is_client_frame(req):
                    if np.adopt_socket(conn, req, stream):
                        with self._conns_lock:
                            self._conns.discard(conn)
                        return
                # Pipelined clients write many frames before reading
                # replies: drain whatever is ALREADY queued (buffered
                # by the stream's large recv, or a zero-wait poll — a
                # lone request never stalls here) and hand the burst to
                # the batch hook, so K ops pay one lock acquisition and
                # one commit wait, with the replies leaving in one
                # vectored flush.
                batch = [req]
                while len(batch) < self.MAX_BURST:
                    more = stream.try_next()
                    if more is None:
                        break
                    batch.append(more)
                eof = stream.at_eof
                if len(batch) == 1:
                    self.stats.bump("ingest_solo")
                else:
                    self.stats.bump("ingest_batches")
                    self.stats.bump("ingest_frames", len(batch))
                ov = self.overload
                if ov is None:
                    if len(batch) == 1:
                        conn.sendall(wire.frame(self._dispatch(req)))
                    else:
                        wire.send_frames(conn, self._run_burst(batch))
                else:
                    self._serve_gated(conn, batch, ov)
                if eof:
                    return
        except (OSError, ConnectionError, ValueError):
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _run_burst(self, batch: list) -> list:
        replies = None
        hook = self.batch_hook
        if hook is not None:
            try:
                replies = hook(batch)
            except Exception:
                if self._logger is not None:
                    self._logger.exception("batch hook failed")
                replies = None
        if replies is None:
            # Sequential fallback preserves request order —
            # the contract peer-transport exchanges rely on.
            replies = [self._dispatch(b) for b in batch]
        return replies

    def _serve_gated(self, conn: socket.socket, batch: list,
                     ov) -> None:
        """Admission-controlled reply path: client DATA frames pass the
        per-connection burst cap, then the global in-flight gate, in
        arrival order (FIFO prefix); the excess is answered with a
        typed ST_OVERLOAD shed WITHOUT ever reaching the consensus
        engine — a shed op is provably never appended, so exactly-once
        and the audit plane's ambiguity rules are untouched.  Control
        frames (everything non-client: HB/vote/lease/CONFIG/snapshot/
        region ops) are never counted or shed — strict priority, so
        overload cannot burn a leadership."""
        n = len(batch)
        replies: list = [None] * n
        clients = [i for i in range(n) if _is_client_frame(batch[i])]
        keep = min(len(clients), ov.max_per_conn)
        granted = ov.gate.acquire(keep) if keep else 0
        try:
            if granted < len(clients):
                shed_g = clients[granted:keep]      # global budget
                shed_c = clients[keep:]             # per-conn cap
                if shed_g:
                    ov.on_shed("global", len(shed_g))
                if shed_c:
                    ov.on_shed("conn", len(shed_c))
                for i in shed_g:
                    replies[i] = _shed_frame_reply(batch[i],
                                                   ov.retry_after_ms)
                for i in shed_c:
                    replies[i] = _shed_frame_reply(batch[i],
                                                   ov.retry_after_ms)
            if granted:
                ov.on_admitted(granted)
            live = [i for i in range(n) if replies[i] is None]
            if len(live) == n:
                out = (self._run_burst(batch) if n > 1
                       else [self._dispatch(batch[0])])
            elif live:
                frames = [batch[i] for i in live]
                out = (self._run_burst(frames) if len(frames) > 1
                       else [self._dispatch(frames[0])])
            else:
                out = []
            for i, rep in zip(live, out):
                replies[i] = rep
            if n == 1:
                conn.sendall(wire.frame(replies[0]))
            else:
                wire.send_frames(conn, replies)
        finally:
            if granted:
                ov.gate.release(granted)

    def _dispatch(self, req: bytes) -> bytes:
        r = wire.Reader(req)
        op = r.u8()
        try:
            if op == wire.OP_GROUP:
                # Multi-group demux: ``u8 gid`` then the inner frame,
                # dispatched against that group's node/handlers (one
                # PeerServer ingest loop serves every group).
                if self.group_ref is None:
                    return wire.u8(wire.ST_ERROR)
                gid = r.u8()
                port = self.group_ref(gid)
                if port is None:
                    return wire.u8(wire.ST_ERROR)
                op = r.u8()
                if op in port.extra_ops:
                    return port.extra_ops[op](r)
                with self._lock:
                    return self._apply(op, r, node=port.node)
            if op == wire.OP_HB_MULTI:
                if self.group_ref is None:
                    return wire.u8(wire.ST_ERROR)
                with self._lock:
                    return self._hb_multi(r)
            if op in self._extra_ops:
                return self._extra_ops[op](r)
            with self._lock:
                return self._apply(op, r)
        except Exception:
            # Server-side protocol/codec bugs must be visible, not
            # laundered into what the initiator sees as a network drop.
            if self._logger is not None:
                self._logger.exception("peer-server op %d failed", op)
            else:
                import traceback
                traceback.print_exc()
            return wire.u8(wire.ST_ERROR)

    def _hb_multi(self, r: wire.Reader) -> bytes:
        """Coalesced per-peer heartbeat (wire.OP_HB_MULTI): ONE frame
        carries every group the sender leads.  Per item, semantics are
        exactly the OP_CTRL_WRITE Region.HB path for that group's node
        — incarnation fence, HB slot deposit, delivery-time
        ``_last_hb_seen`` stamp.  The reply echoes each group's
        CURRENT sid (lease-renewal evidence, per group).

        The carried commit offset is OBSERVABILITY ONLY — it is never
        adopted here.  Commit propagation stays on the per-group
        log-write path, which only reaches ADJUSTED followers: a
        follower holding a divergent unadjusted tail must never clamp
        leader-commit against its own log end (advance_commit(min(
        commit, end)) would mark stale entries committed — the classic
        Raft last-NEW-entry rule).  The first multi-group churn
        campaign (seed 26000) caught exactly that as a batch of stale
        reads when an earlier revision adopted it."""
        sender, items = wire.decode_hb_multi(r)
        echoes = []
        for gid, word, _commit, _lease_us, inc in items:
            port = self.group_ref(gid)
            if port is None:
                echoes.append((wire.ST_ERROR, 0))
                continue
            node = port.node
            if inc < node.fence_epochs.get(sender, 0):
                node.bump("fenced_ctrl_writes")
                echoes.append((wire.ST_FENCED, node.sid.word))
                continue
            onesided.apply_ctrl_write(node, Region.HB, sender, word)
            s = Sid.unpack(word)
            if s.leader and s.idx == sender \
                    and s.term >= node.current_term:
                # Delivery-time stamp, same clock seam as the
                # OP_CTRL_WRITE HB path (lease-safety contract).
                node._last_hb_seen = max(node._last_hb_seen,
                                         node._fresh_now())
                node.group_contact = True
            echoes.append((wire.ST_OK, node.sid.word))
        return wire.encode_hb_echoes(echoes)

    #: ops whose application can change a node's log/applied state —
    #: each closes the native plane's read gate for that group BEFORE
    #: applying (Hermes-style write invalidation: a follower must never
    #: serve a native GET between an inbound write and the tick that
    #: re-validates its lease/applied conditions).
    _GATE_WRITES = frozenset((wire.OP_LOG_WRITE, wire.OP_LOG_SET_END,
                              wire.OP_SNAP_PUSH, wire.OP_SNAP_BEGIN,
                              wire.OP_SNAP_CHUNK, wire.OP_SNAP_END))

    def _apply(self, op: int, r: wire.Reader, node=None) -> bytes:
        if node is None:
            node = self._node_ref()
        if self.native_plane is not None and op in self._GATE_WRITES:
            self.native_plane.on_peer_write(node)
        if op == wire.OP_CTRL_WRITE:
            region = wire.REGION_LIST[r.u8()]
            slot = r.u8()
            value = wire.decode_value(r)
            # Incarnation fencing (core.node fence_epochs): the trailing
            # u32 is the writer's incarnation — the epoch of the CONFIG
            # that admitted its tenancy of ``slot``.  A write below the
            # slot's recorded removal epoch comes from a STALE
            # EX-OCCUPANT (removed, possibly replaced): dropped before
            # it can be credited as the current occupant's REP_ACK /
            # vote / heartbeat.  Absent on old frames (fence passes).
            winc = r.u32() if r.remaining >= 4 else None
            if winc is not None \
                    and winc < node.fence_epochs.get(slot, 0):
                node.bump("fenced_ctrl_writes")
                return wire.u8(wire.ST_FENCED) + wire.u64(node.sid.word)
            res = onesided.apply_ctrl_write(node, region, slot, value)
            # Read-lease support (live stack only — the sim path calls
            # onesided directly and stays clock-pure).  (a) A valid
            # leader heartbeat stamps _last_hb_seen at DELIVERY, under
            # this lock: the no-vote-while-leader-alive promise then
            # starts at delivery time, not at the next tick's region
            # scan — the window the lease-safety proof needs closed.
            # (b) The reply echoes our current SID: the writer counts
            # this peer toward its lease quorum only when the echoed
            # term proves we had not moved past its term at reply time.
            if region is Region.HB and isinstance(value, int):
                s = Sid.unpack(value)
                if s.leader and s.idx == slot \
                        and s.term >= node.current_term:
                    # Stamped from the NODE's clock seam (_fresh_now ->
                    # the daemon's SkewClock): the no-vote-while-
                    # leader-alive window is compared against tick
                    # stamps from the same domain, and the adversarial-
                    # time nemesis must skew both coherently
                    # (scripts/check_clock.py pins this).
                    node._last_hb_seen = max(node._last_hb_seen,
                                             node._fresh_now())
                    node.group_contact = True
            return wire.u8(_ST_OF_RESULT[res]) + wire.u64(node.sid.word)
        if op == wire.OP_CTRL_READ:
            region = wire.REGION_LIST[r.u8()]
            slot = r.u8()
            value = onesided.apply_ctrl_read(node, region, slot)
            return wire.u8(wire.ST_OK) + wire.encode_value(value)
        if op == wire.OP_LOG_WRITE:
            writer = Sid.unpack(r.u64())
            commit = r.u64()
            entries = wire.decode_entries(r)
            res = onesided.apply_log_write(node, writer, entries, commit)
            # Reply carries our log end post-apply (read under the same
            # lock): the writer's synchronous ack.
            return wire.u8(_ST_OF_RESULT[res]) + wire.u64(node.log.end)
        if op == wire.OP_LOG_READ_STATE:
            state = onesided.apply_log_read_state(node)
            return wire.u8(wire.ST_OK) + wire.encode_log_state(state)
        if op == wire.OP_LOG_SET_END:
            writer = Sid.unpack(r.u64())
            new_end = r.u64()
            res = onesided.apply_log_set_end(node, writer, new_end)
            return wire.u8(_ST_OF_RESULT[res])
        if op == wire.OP_LOG_BULK_READ:
            start, stop = r.u64(), r.u64()
            entries = onesided.apply_log_bulk_read(node, start, stop)
            return wire.u8(wire.ST_OK) + wire.encode_entries(entries)
        if op == wire.OP_SNAP_PUSH:
            writer = Sid.unpack(r.u64())
            snap = wire.decode_value(r)
            ep_dump = wire.decode_ep_dump(r)
            cid = wire.decode_cid(r)
            members = wire.decode_members(r)
            # Optional trailing delta header (wire.SNAPF_DELTA): the
            # blob is a state DELTA on top of the receiver's applied
            # determinant, not a full image.  Absent on old frames.
            delta_base = None
            if r.remaining >= 17 and r.u8() & wire.SNAPF_DELTA:
                delta_base = (r.u64(), r.u64())
            res = onesided.apply_snap_push(
                node, writer, snap, ep_dump,
                cid if cid.size > 0 else None, members,
                delta_base=delta_base)
            return wire.u8(_ST_OF_RESULT[res])
        if op == wire.OP_SNAP_BEGIN:
            writer = Sid.unpack(r.u64())
            total = r.u64()
            meta = wire.decode_value(r)
            ep_dump = wire.decode_ep_dump(r)
            cid = wire.decode_cid(r)
            members = wire.decode_members(r)
            res, resume = onesided.apply_snap_begin(
                node, writer, total, meta, ep_dump,
                cid if cid.size > 0 else None, members)
            # Reply carries the RESUME OFFSET: the sender starts its
            # chunk loop there instead of at byte zero (the whole
            # point of the resumable stream).
            return wire.u8(_ST_OF_RESULT[res]) + wire.u64(resume)
        if op == wire.OP_SNAP_CHUNK:
            writer = Sid.unpack(r.u64())
            off = r.u64()
            data = r.blob()
            # Optional trailing CRC32 of the chunk (torn/flipped wire
            # or disk bytes surface here, not at install).
            crc = r.u32() if r.remaining >= 4 else None
            res, acked = onesided.apply_snap_chunk(node, writer, off,
                                                   data, crc=crc)
            return wire.u8(_ST_OF_RESULT[res]) + wire.u64(acked)
        if op == wire.OP_SNAP_END:
            writer = Sid.unpack(r.u64())
            res = onesided.apply_snap_end(node, writer)
            return wire.u8(_ST_OF_RESULT[res])
        return wire.u8(wire.ST_ERROR)


class NetTransport(Transport):
    """Initiator side: per-peer lazily-connected sockets with backoff."""

    def __init__(self, peers: dict[int, tuple[str, int]],
                 timeout: float = 0.2, backoff: float = 0.5,
                 yield_lock: Optional[threading.RLock] = None,
                 retries: int = 1, stats=None):
        self.peers = dict(peers)
        self.timeout = timeout
        self.backoff = backoff
        self.yield_lock = yield_lock
        #: Bounded in-op retry for CONNECTION faults on an established
        #: peer (RST mid-exchange, listener restarted): up to
        #: ``retries`` jittered-backoff redial+resend cycles before the
        #: op surfaces as DROPPED.  Pre-fix a flaky-but-alive peer was
        #: timeout-or-nothing: every transient socket error cost a full
        #: dial-backoff window of DROPPED ops, which the failure
        #: detector counts — enough flakes and a live peer gets
        #: evicted.  TIMEOUTS are never retried (the peer is busy, not
        #: flaky — a retry would double the stall), and a peer with no
        #: established connection fails fast as before (the background
        #: dial owns reconnection).  One-sided ops are idempotent by
        #: design (region writes are last-write-wins, log writes are
        #: fence+idx checked), so a resend after a lost-reply error is
        #: safe.
        self.retries = retries
        self._retry_rng = random.Random(0x5EED ^ len(peers))
        # net_* registry namespace (shared ObsHub view when the daemon
        # passes one; private registry otherwise) — dict-compatible
        # with the legacy ``stats`` surface.
        self.stats = stats if stats is not None \
            else MetricsRegistry().view("net")
        self.stats.setdefault("retries", 0)
        self.stats.setdefault("retries_ok", 0)
        #: Our node's current incarnation (the epoch of the CONFIG that
        #: admitted this tenancy of our slot), stamped onto every
        #: outbound ctrl write for the receiver's removed-slot fence.
        #: The daemon installs a live read (lambda over node state);
        #: None sends 0 — raw-transport tests and fixed-membership
        #: clusters are unaffected (fence tables stay empty).
        self.incarnation_of: Optional[Callable[[], int]] = None
        #: Clock for the reply-echo stamps below — the daemon installs
        #: its per-replica SkewClock so the stamps share the heartbeat
        #: round-start's clock domain (Node._send_heartbeats compares
        #: ``seen[1] >= t0``; mixing domains there would corrupt the
        #: lease-renewal proof exactly when the nemesis skews time).
        #: Wire mechanics (timeouts, backoff) stay on real time.
        self.clock: Callable[[], float] = time.monotonic
        #: peer -> (sid_word, clock-domain arrival time) from ctrl-write
        #: reply echoes (read-lease renewal evidence; see ctrl_write).
        self.peer_sid_seen: dict[int, tuple[int, float]] = {}
        self._conns: dict[int, socket.socket] = {}
        self._down_until: dict[int, float] = {}
        self._peer_locks: dict[int, threading.Lock] = {}
        # Connection setup is asynchronous (the reference pre-establishes
        # RC QPs at bootstrap; data ops never wait for connection setup):
        # ops on an unconnected peer fail fast with DROPPED while a
        # background connector dials.  Otherwise one blackholed peer
        # would stall the tick thread's heartbeat fan-out past
        # hb_timeout and trigger spurious elections.
        self._dialing: set[int] = set()
        self._dial_lock = threading.Lock()
        self._closed = False
        # Peers successfully dialed at least once at their current
        # address: the failure detector's eligibility set (see
        # Transport.peer_established).  _first_dial records when we
        # FIRST tried each address: a peer that stays unreachable past
        # ``establish_grace`` counts as established-for-failure-purposes
        # anyway, so a restarted leader (whose in-memory set starts
        # empty) can still auto-remove a peer that died before the
        # restart — the grace only shields cold-starting processes.
        self._established: set[int] = set()
        self._first_dial: dict[int, float] = {}
        self.establish_grace = 10.0
        #: peer -> monotonic time of the last TIMEOUT-kind failure
        #: (established connection, peer busy); consulted by
        #: peer_failure_was_timeout immediately after a failed op.
        #: The freshness window must outlast one backoff+redial+
        #: retimeout cycle — while the peer stays busy, the hint is
        #: only refreshed when an op reaches it and times out again.
        self._timeout_hint: dict[int, float] = {}
        self._timeout_hint_window = max(2.0, 2.0 * backoff + timeout)

    def peer_established(self, target: int) -> bool:
        if target in self._established:
            return True
        first = self._first_dial.get(target)
        return (first is not None
                and time.monotonic() - first > self.establish_grace)

    def peer_failure_was_timeout(self, target: int) -> bool:
        """True when the failure being reported RIGHT NOW (callers
        consult this immediately after a failed op) was a timeout on an
        established connection — peer alive, event loop busy.  The
        freshness window only needs to cover the gap between the op
        and the failure-detector's check on the same tick."""
        hint = self._timeout_hint.get(target)
        return (hint is not None and
                time.monotonic() - hint < self._timeout_hint_window)

    def set_peer(self, idx: int, addr: tuple[str, int]) -> None:
        """Register/replace a peer endpoint (membership change)."""
        self.peers[idx] = addr
        self._drop_conn(idx)
        self._down_until.pop(idx, None)
        # New address, new eligibility: a member that moved (or a fresh
        # joiner) must be reached once before its failures count.
        self._established.discard(idx)
        self._first_dial.pop(idx, None)

    def close(self) -> None:
        with self._dial_lock:
            self._closed = True
        for idx in list(self._conns):
            self._drop_conn(idx)

    # -- connection management -------------------------------------------

    def _peer_lock(self, target: int) -> threading.Lock:
        lock = self._peer_locks.get(target)
        if lock is None:
            lock = self._peer_locks.setdefault(target, threading.Lock())
        return lock

    def _connect(self, target: int) -> Optional[socket.socket]:
        """Return an established connection or None (kicking off a
        background dial attempt).  Never blocks on connection setup."""
        conn = self._conns.get(target)
        if conn is not None:
            return conn
        now = time.monotonic()
        if now >= self._down_until.get(target, 0.0) \
                and target in self.peers and not self._closed:
            with self._dial_lock:
                dialing = target in self._dialing
                if not dialing:
                    self._dialing.add(target)
            if not dialing:
                threading.Thread(target=self._dial, args=(target,),
                                 daemon=True).start()
        return None

    def _dial(self, target: int) -> None:
        addr = self.peers.get(target)
        self._first_dial.setdefault(target, time.monotonic())
        try:
            conn = socket.create_connection(addr, timeout=self.timeout)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(self.timeout)
            with self._dial_lock:
                # Paired with close(): _closed is set under this lock,
                # so we cannot insert into a closed transport.  Also
                # re-check the peer table: a set_peer() that raced this
                # dial means ``conn`` reaches the OLD address — installing
                # it would both talk to a stale endpoint and wrongly mark
                # the NEW address established.
                if self._closed or self.peers.get(target) != addr:
                    conn.close()
                else:
                    self._conns[target] = conn
                    self._established.add(target)
        except ConnectionRefusedError:
            # Positive evidence of DEATH (no listener at the address):
            # clears any busy-peer timeout hint so the failure detector
            # resumes counting.
            self._timeout_hint.pop(target, None)
            self._down_until[target] = time.monotonic() + self.backoff
        except OSError:
            self._down_until[target] = time.monotonic() + self.backoff
        finally:
            with self._dial_lock:
                self._dialing.discard(target)

    def _dial_inline(self, target: int) -> bool:
        """Synchronous redial for the in-op retry path (the caller
        holds the peer lock and wants to resend NOW).  Reuses _dial's
        install-under-dial-lock protocol; returns True when a fresh
        connection is installed.  A concurrent background dial for the
        same target means someone is already on it — don't stack."""
        with self._dial_lock:
            if self._closed or target in self._dialing \
                    or target not in self.peers:
                return False
            self._dialing.add(target)
        self._dial(target)
        return self._conns.get(target) is not None

    def _drop_conn(self, target: int) -> None:
        conn = self._conns.pop(target, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def _roundtrip(self, target: int, payload: bytes,
                   timeout: Optional[float] = None,
                   cap_s: float = 8.0) -> Optional[bytes]:
        """Send one request frame, await the response frame.  Releases
        the daemon's node lock while blocked (see module docstring).
        ``timeout`` overrides the per-op wire timeout (bulk transfers);
        either way the wait scales with the payload (~1 s per 4 MB,
        capped at ``cap_s``, default 8 s): a multi-MB frame can take seconds to transfer
        AND process on a loaded host, and a too-short wait makes the
        sender misread success as DROPPED and resend — while the cap
        bounds how long a tick-thread caller can stall on one peer."""
        eff = (self.timeout if timeout is None else timeout) \
            + len(payload) / 4e6
        eff = min(cap_s, eff)
        lock = self.yield_lock
        depth = 0
        if lock is not None:
            # Fully release our recursion of the RLock while on the wire.
            while lock._is_owned():            # type: ignore[attr-defined]
                lock.release()
                depth += 1
        try:
            with self._peer_lock(target):
                for attempt in range(1 + max(0, self.retries)):
                    conn = self._connect(target)
                    if conn is None:
                        # No connection (dial in flight / backoff):
                        # leave any busy-peer timeout hint in place — a
                        # conn dropped BECAUSE of a timeout alternates
                        # with this path while the peer is still busy,
                        # and clearing here would let every other
                        # tick's failure count.  The hint is cleared by
                        # evidence instead: op success, an in-op
                        # connection error, or a dial REFUSED (death)
                        # in _dial.  No retry either — the background
                        # dial owns reconnection from cold.
                        return None
                    try:
                        conn.settimeout(eff)
                        conn.sendall(wire.frame(payload))
                        resp = wire.read_frame(conn)
                        if resp is None:
                            raise ConnectionError("peer closed")
                        self._timeout_hint.pop(target, None)
                        if attempt > 0:
                            self.stats.bump("retries_ok")
                        return resp
                    except TimeoutError:
                        # Timeout on an ESTABLISHED connection: the
                        # peer's process holds the socket open but its
                        # event loop is busy (e.g. a multi-second
                        # snapshot install).  Record the kind so the
                        # failure detector can skip it (Transport.
                        # peer_failure_was_timeout) — the reference's
                        # WC-error counter never sees a busy-but-
                        # connected peer, and counting these evicted
                        # mid-install joiners in an endless evict/
                        # rejoin livelock (observed in a 30-min soak at
                        # deep history).  Never retried: the peer is
                        # busy, not flaky, and a resend would double
                        # the caller's stall.
                        self._timeout_hint[target] = time.monotonic()
                        self._drop_conn(target)
                        self._down_until[target] = \
                            time.monotonic() + self.backoff
                        return None
                    except (OSError, ConnectionError, ValueError):
                        self._timeout_hint.pop(target, None)
                        self._drop_conn(target)
                        if attempt < self.retries and not self._closed:
                            # Transient connection fault on a peer we
                            # HAD reached: jittered backoff, then one
                            # inline redial+resend before giving up —
                            # bounded (a fraction of one dial backoff),
                            # and safe because one-sided ops are
                            # idempotent (module docstring).
                            self.stats.bump("retries")
                            time.sleep(
                                self._retry_rng.uniform(0.25, 0.75)
                                * min(self.backoff, 0.05))
                            if self._dial_inline(target):
                                continue
                        self._down_until[target] = \
                            time.monotonic() + self.backoff
                        return None
                    finally:
                        if timeout is not None:
                            try:
                                conn.settimeout(self.timeout)
                            except OSError:
                                pass
                return None
        finally:
            for _ in range(depth):
                lock.acquire()     # type: ignore[union-attr]

    # -- one-sided ops ----------------------------------------------------

    def ctrl_write(self, target: int, region: Region, slot: int,
                   value: Any) -> WriteResult:
        inc = self.incarnation_of() if self.incarnation_of is not None \
            else 0
        payload = (wire.u8(wire.OP_CTRL_WRITE)
                   + wire.u8(wire.REGION_INDEX[region]) + wire.u8(slot)
                   + wire.encode_value(value) + wire.u32(inc))
        resp = self._roundtrip(target, payload)
        if resp is None:
            return WriteResult.DROPPED
        if len(resp) >= 9:
            # The reply echoes the target's current SID word: recorded
            # per peer with its arrival time — the read-lease renewal
            # proof (Node._send_heartbeats counts a peer toward the
            # lease quorum only when the echo is from THIS round and
            # its term has not moved past ours).
            self.peer_sid_seen[target] = \
                (wire.Reader(resp[1:9]).u64(), self.clock())
        return _RESULT_OF_ST.get(resp[0], WriteResult.DROPPED)

    def ctrl_read(self, target: int, region: Region, slot: int) -> Any:
        payload = (wire.u8(wire.OP_CTRL_READ)
                   + wire.u8(wire.REGION_INDEX[region]) + wire.u8(slot))
        resp = self._roundtrip(target, payload)
        if resp is None or resp[0] != wire.ST_OK:
            return None
        return wire.decode_value(wire.Reader(resp[1:]))

    def log_write(self, target: int, writer_sid: Sid,
                  entries: list[LogEntry], commit: int):
        payload = (wire.u8(wire.OP_LOG_WRITE) + wire.u64(writer_sid.word)
                   + wire.u64(commit) + wire.encode_entries(entries))
        resp = self._roundtrip(target, payload)
        if resp is None:
            return WriteResult.DROPPED, None
        res = _RESULT_OF_ST.get(resp[0], WriteResult.DROPPED)
        # The reply's trailing u64 is the target's log end AFTER the
        # write (applied under the server lock before responding): the
        # authoritative ack, one round trip earlier than waiting for
        # the follower's next REP_ACK tick.
        end = None
        if res == WriteResult.OK and len(resp) >= 9:
            end = wire.Reader(resp[1:9]).u64()
        return res, end

    def log_read_state(self, target: int) -> Optional[LogState]:
        resp = self._roundtrip(target, wire.u8(wire.OP_LOG_READ_STATE))
        if resp is None or resp[0] != wire.ST_OK:
            return None
        return wire.decode_log_state(wire.Reader(resp[1:]))

    def log_set_end(self, target: int, writer_sid: Sid,
                    new_end: int) -> WriteResult:
        payload = (wire.u8(wire.OP_LOG_SET_END) + wire.u64(writer_sid.word)
                   + wire.u64(new_end))
        resp = self._roundtrip(target, payload)
        if resp is None:
            return WriteResult.DROPPED
        return _RESULT_OF_ST.get(resp[0], WriteResult.DROPPED)

    def log_bulk_read(self, target: int, start: int,
                      stop: int) -> Optional[list[LogEntry]]:
        payload = (wire.u8(wire.OP_LOG_BULK_READ) + wire.u64(start)
                   + wire.u64(stop))
        resp = self._roundtrip(target, payload)
        if resp is None or resp[0] != wire.ST_OK:
            return None
        return wire.decode_entries(wire.Reader(resp[1:]))

    def snap_push(self, target: int, writer_sid: Sid, snap,
                  ep_dump: list, cid=None, member_addrs=None,
                  delta_base=None) -> WriteResult:
        payload = (wire.u8(wire.OP_SNAP_PUSH) + wire.u64(writer_sid.word)
                   + wire.encode_value(snap) + wire.encode_ep_dump(ep_dump)
                   + wire.encode_cid(cid if cid is not None
                                     else Cid.initial(0))
                   + wire.encode_members(member_addrs or {}))
        if delta_base is not None:
            # Delta snapshot (see wire.SNAPF_DELTA): snap.data is the
            # state delta past the receiver's applied determinant.
            payload += (wire.u8(wire.SNAPF_DELTA)
                        + wire.u64(delta_base[0])
                        + wire.u64(delta_base[1]))
        # Snapshots get a 2 s floor on top of _roundtrip's generic
        # payload scaling: the receiver persists the whole state before
        # replying, which costs more than the transfer alone.
        resp = self._roundtrip(target, payload,
                               timeout=max(self.timeout, 2.0))
        if resp is None:
            return WriteResult.DROPPED
        return _RESULT_OF_ST.get(resp[0], WriteResult.DROPPED)

    #: bytes per SNAP_CHUNK frame — the pusher's resident snapshot
    #: footprint during a stream.
    SNAP_CHUNK_BYTES = 1 << 20

    def snap_push_stream(self, target: int, writer_sid: Sid, meta_snap,
                         ep_dump: list, cid, member_addrs, total: int,
                         read_chunk) -> WriteResult:
        """Chunked RESUMABLE form of snap_push for large dumps: BEGIN
        (metadata) -> N x CHUNK (read_chunk(off, n) supplies bytes,
        typically a pread of the SM's on-disk record dump) -> END
        (installs with snap_push's exact fence/staleness semantics).
        The pusher never holds more than one chunk in RAM — the
        whole-blob snap_push materializes O(history) on the leader,
        whose GC pauses then wobble elections at deep history.

        Resume: BEGIN's reply carries the receiver's verified progress
        for this stream identity — after a sender restart, receiver
        restart, or transient partition the chunk loop STARTS THERE
        instead of at byte zero (stats: snap_resumes, resumed_bytes).
        Each chunk ships with its CRC32 and the reply acks the
        receiver's durable progress (stats: snap_chunks_sent/acked)."""
        import zlib
        payload = (wire.u8(wire.OP_SNAP_BEGIN) + wire.u64(writer_sid.word)
                   + wire.u64(total) + wire.encode_value(meta_snap)
                   + wire.encode_ep_dump(ep_dump)
                   + wire.encode_cid(cid if cid is not None
                                     else Cid.initial(0))
                   + wire.encode_members(member_addrs or {}))
        resp = self._roundtrip(target, payload,
                               timeout=max(self.timeout, 2.0))
        if resp is None:
            return WriteResult.DROPPED
        res = _RESULT_OF_ST.get(resp[0], WriteResult.DROPPED)
        if res != WriteResult.OK:
            return res
        rr = wire.Reader(resp[1:])
        off = rr.u64() if rr.remaining >= 8 else 0
        if off:
            if off > total:              # corrupt reply: start over
                off = 0
            else:
                self.stats.bump("snap_resumes")
                self.stats.bump("snap_resumed_bytes", off)
        while off < total:
            n = min(self.SNAP_CHUNK_BYTES, total - off)
            data = read_chunk(off, n)
            if len(data) != n:           # dump shrank?! protocol bug
                return WriteResult.DROPPED
            payload = (wire.u8(wire.OP_SNAP_CHUNK)
                       + wire.u64(writer_sid.word) + wire.u64(off)
                       + wire.blob(data)
                       + wire.u32(zlib.crc32(data) & 0xFFFFFFFF))
            self.stats.bump("snap_chunks_sent")
            resp = self._roundtrip(target, payload)
            if resp is None:
                return WriteResult.DROPPED
            res = _RESULT_OF_ST.get(resp[0], WriteResult.DROPPED)
            if res != WriteResult.OK:
                return res
            self.stats.bump("snap_chunks_acked")
            rr = wire.Reader(resp[1:])
            acked = rr.u64() if rr.remaining >= 8 else off + n
            # The receiver acks its durable progress: normally off+n;
            # a duplicate-span retry acks FORWARD past our cursor.
            off = acked if off < acked <= total else off + n
        # END: the receiver reads, installs, and persists the whole
        # assembled state before replying — allow well beyond the
        # normal cap (heartbeats pause for the duration on the pusher's
        # tick thread; an async install on the receiver is the named
        # next step for multi-GB dumps).
        resp = self._roundtrip(
            target, wire.u8(wire.OP_SNAP_END) + wire.u64(writer_sid.word),
            timeout=max(self.timeout, 2.0 + total / 2e6), cap_s=30.0)
        if resp is None:
            return WriteResult.DROPPED
        return _RESULT_OF_ST.get(resp[0], WriteResult.DROPPED)

    # -- generic request (two-sided control messages: join, snapshots) ----

    def request(self, target: int, payload: bytes,
                timeout: Optional[float] = None,
                cap_s: float = 8.0) -> Optional[bytes]:
        return self._roundtrip(target, payload, timeout=timeout,
                               cap_s=cap_s)


class GroupTransport(NetTransport):
    """A per-group VIEW of a shared transport (Multi-Raft): every
    outbound frame is wrapped ``OP_GROUP | gid`` and lands on the
    receiver's same-gid node, while the sockets, dial/backoff state,
    failure evidence, and (when armed) the fault plane are all the
    SHARED inner transport's — one connection set serves every group.

    Implementation: the op methods are inherited verbatim from
    NetTransport (payload build + reply parse), but the single
    ``_roundtrip`` choke point delegates to ``inner.request`` with the
    group prefix — so when ``inner`` is a FaultPlane, group traffic is
    attacked exactly like group-0 traffic.  Per-GROUP protocol state
    (reply-echo sids for lease renewal, the group node's incarnation
    stamp) lives here; everything connection-shaped delegates."""

    def __init__(self, inner, gid: int):
        # Deliberately NOT calling NetTransport.__init__: this view
        # owns no sockets.  Only the attributes the inherited op
        # methods read are bound here; connection state delegates.
        self._inner = inner
        self.gid = gid
        self._prefix = wire.u8(wire.OP_GROUP) + wire.u8(gid)
        self.peer_sid_seen = {}
        self.incarnation_of = None
        self.stats = getattr(inner, "stats",
                             MetricsRegistry().view("net"))

    # Shared-transport delegation.  ``clock``/``timeout``/``peers`` are
    # read dynamically (the daemon installs its SkewClock on the RAW
    # transport after construction; a copy here would miss it).  A
    # FaultPlane inner forwards unknown attributes to the raw transport.
    @property
    def clock(self):
        return self._inner.clock

    @property
    def timeout(self):
        return self._inner.timeout

    @property
    def peers(self):
        return self._inner.peers

    def peer_established(self, target: int) -> bool:
        return self._inner.peer_established(target)

    def peer_failure_was_timeout(self, target: int) -> bool:
        return self._inner.peer_failure_was_timeout(target)

    def set_peer(self, idx: int, addr) -> None:
        # The shared peer table is owned by the primary transport
        # (group 0's config path updates it); per-group set_peer is a
        # no-op so CONFIG applies in extra groups cannot double-reset
        # the shared connection state.
        pass

    def close(self) -> None:
        pass                      # the owner closes the shared transport

    def _roundtrip(self, target: int, payload: bytes,
                   timeout: Optional[float] = None,
                   cap_s: float = 8.0) -> Optional[bytes]:
        return self._inner.request(target, self._prefix + payload,
                                   timeout=timeout, cap_s=cap_s)

    def request(self, target: int, payload: bytes,
                timeout: Optional[float] = None,
                cap_s: float = 8.0) -> Optional[bytes]:
        return self._inner.request(target, self._prefix + payload,
                                   timeout=timeout, cap_s=cap_s)
