"""Passive-target semantics of one-sided operations.

In the reference, the target CPU is not involved in one-sided accesses:
the HCA DMAs directly into the exposed ctrl/log regions
(update_remote_logs dare_ibv_rc.c:1460-1644, hb/vote writes throughout).
The *semantics* of those accesses — fence checks via QP state, idempotent
entry placement, commit clamping — live partly in hardware (QP
RESET/RTS) and partly in careful protocol layout.

Here those semantics are ONE shared module applied by every backend's
target side: the deterministic simulator (apus_tpu.parallel.sim) and the
DCN peer server (apus_tpu.parallel.net) call these functions so a log
write behaves bit-identically under test and in production.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional

from apus_tpu.core.log import LogEntry
from apus_tpu.core.node import Node
from apus_tpu.core.sid import Sid
from apus_tpu.parallel.transport import LogState, Region, WriteResult


def apply_ctrl_write(node: Node, region: Region, slot: int,
                     value: Any) -> WriteResult:
    """Deposit a value in a control slot (ctrl_data_t write)."""
    node.regions.ctrl[region][slot] = value
    node.regions.touch(region, slot, time.monotonic())
    return WriteResult.OK


def apply_ctrl_read(node: Node, region: Region, slot: int) -> Any:
    return node.regions.ctrl[region][slot]


def apply_log_write(node: Node, writer_sid: Sid, entries: list[LogEntry],
                    commit: int) -> WriteResult:
    """Leader's one-sided log write into a follower (update_remote_logs
    analog): fence-checked, idempotent for already-present entries,
    stops at the first non-contiguous index (the leader re-adjusts)."""
    if not node.regions.log_write_allowed(writer_sid):
        return WriteResult.FENCED
    for e in entries:
        if e.idx < node.log.end:
            continue              # idempotent re-write
        if e.idx > node.log.end:
            break                 # non-contiguous: stop
        if node.log.is_full:
            break
        node.log.write(dataclasses.replace(e))
    node.log.advance_commit(min(commit, node.log.end))
    return WriteResult.OK


def apply_log_read_state(node: Node) -> LogState:
    log = node.log
    return LogState(commit=log.commit, end=log.end,
                    nc_determinants=log.nc_determinants())


def apply_log_set_end(node: Node, writer_sid: Sid,
                      new_end: int) -> WriteResult:
    if not node.regions.log_write_allowed(writer_sid):
        return WriteResult.FENCED
    # Fail fast on new_end < commit: the adjustment algorithm never asks a
    # follower to truncate committed entries (NC determinants start at
    # commit, dare_log.h:339-359) — reaching here is a protocol bug that
    # must surface loudly, not be clamped away.
    node.log.truncate(new_end)
    return WriteResult.OK


def apply_log_bulk_read(node: Node, start: int,
                        stop: int) -> list[LogEntry]:
    return [dataclasses.replace(e) for e in node.log.entries(start, stop)]


def apply_snap_push(node: Node, writer_sid: Sid, snap: Any,
                    ep_dump: list, cid: Any = None,
                    member_addrs: dict | None = None) -> WriteResult:
    """Install a leader-pushed snapshot.  Fence-checked exactly like log
    writes (it rewrites the log base); staleness is rejected inside
    install_snapshot."""
    if not node.regions.log_write_allowed(writer_sid):
        return WriteResult.FENCED
    if not node.install_snapshot(snap, ep_dump, cid, member_addrs):
        # Stale snapshot (target's commit is already past it): surface
        # the refusal so the pusher re-reads our real state instead of
        # assuming we now sit at snap.last_idx.
        return WriteResult.REFUSED
    return WriteResult.OK


# -- chunked snapshot stream (OP_SNAP_BEGIN/CHUNK/END) --------------------
# One in-flight assembly per node; a new BEGIN replaces a stale session
# (the pusher serializes its own stream, and a leadership change mid-
# stream surfaces as FENCED on the next chunk/end).  The blob assembles
# into a temp file so the receiver too holds at most one chunk in RAM
# until install time.

def _snap_session_drop(node: Node) -> None:
    sess = getattr(node, "_snap_stream_in", None)
    if sess is not None:
        try:
            sess["f"].close()
        except OSError:
            pass
        try:
            import os
            os.unlink(sess["path"])
        except OSError:
            pass
    node._snap_stream_in = None


def apply_snap_begin(node: Node, writer_sid: Sid, total: int,
                     meta_snap: Any, ep_dump: list, cid: Any,
                     member_addrs: dict | None) -> WriteResult:
    """Open an assembly session.  Same fence gate as SNAP_PUSH — a
    deposed leader cannot even begin a stream."""
    import tempfile

    if not node.regions.log_write_allowed(writer_sid):
        return WriteResult.FENCED
    _snap_session_drop(node)
    # Assemble NEXT TO the SM's own dump when it has one: adoption is
    # then a same-filesystem rename (os.replace raises EXDEV across
    # filesystems — the default TMPDIR is commonly tmpfs while the
    # spill lives on disk, and assembling a multi-GB dump on tmpfs
    # would also re-consume the RAM the streaming avoids).
    spool_dir = None
    spool = getattr(node.sm, "snapshot_spool_dir", None)
    if spool is not None:
        spool_dir = spool()
    f = tempfile.NamedTemporaryFile(prefix="apus-snap-in-", delete=False,
                                    dir=spool_dir)
    node._snap_stream_in = {
        "sid": writer_sid.word, "total": total, "got": 0,
        "meta": meta_snap, "ep_dump": ep_dump, "cid": cid,
        "members": member_addrs, "f": f, "path": f.name,
    }
    return WriteResult.OK


def apply_snap_chunk(node: Node, writer_sid: Sid, off: int,
                     data: bytes) -> WriteResult:
    if not node.regions.log_write_allowed(writer_sid):
        _snap_session_drop(node)
        return WriteResult.FENCED
    sess = getattr(node, "_snap_stream_in", None)
    if sess is None or sess["sid"] != writer_sid.word \
            or off != sess["got"] or off + len(data) > sess["total"]:
        _snap_session_drop(node)
        return WriteResult.REFUSED          # no/foreign/torn session
    sess["f"].write(data)
    sess["got"] += len(data)
    return WriteResult.OK


def apply_snap_end(node: Node, writer_sid: Sid) -> WriteResult:
    """Close the stream and install FROM THE FILE: the assembled dump
    is handed to the SM for adoption (RelayStateMachine renames it into
    place and scans it chunk-buffered), so the receiver never holds
    more than one chunk resident — completing what the pusher-side
    streaming started.  The reference installs from its disk-backed
    BDB dump the same way (proxy.c:306-339)."""
    sess = getattr(node, "_snap_stream_in", None)
    if sess is None or sess["sid"] != writer_sid.word \
            or sess["got"] != sess["total"]:
        _snap_session_drop(node)
        return WriteResult.REFUSED
    if not node.regions.log_write_allowed(writer_sid):
        _snap_session_drop(node)
        return WriteResult.FENCED
    sess["f"].flush()
    sess["f"].close()
    ok = node.install_snapshot(sess["meta"], sess["ep_dump"],
                               sess["cid"], sess["members"],
                               data_path=sess["path"], adopt=True)
    # _snap_session_drop's unlink is a no-op if the SM adopted (renamed)
    # the file, and the needed cleanup otherwise.
    _snap_session_drop(node)
    return WriteResult.OK if ok else WriteResult.REFUSED
