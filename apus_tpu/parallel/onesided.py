"""Passive-target semantics of one-sided operations.

In the reference, the target CPU is not involved in one-sided accesses:
the HCA DMAs directly into the exposed ctrl/log regions
(update_remote_logs dare_ibv_rc.c:1460-1644, hb/vote writes throughout).
The *semantics* of those accesses — fence checks via QP state, idempotent
entry placement, commit clamping — live partly in hardware (QP
RESET/RTS) and partly in careful protocol layout.

Here those semantics are ONE shared module applied by every backend's
target side: the deterministic simulator (apus_tpu.parallel.sim) and the
DCN peer server (apus_tpu.parallel.net) call these functions so a log
write behaves bit-identically under test and in production.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional

from apus_tpu.core.log import LogEntry
from apus_tpu.core.node import Node
from apus_tpu.core.sid import Sid
from apus_tpu.parallel.transport import LogState, Region, WriteResult


def apply_ctrl_write(node: Node, region: Region, slot: int,
                     value: Any) -> WriteResult:
    """Deposit a value in a control slot (ctrl_data_t write)."""
    node.regions.ctrl[region][slot] = value
    node.regions.touch(region, slot, time.monotonic())
    return WriteResult.OK


def apply_ctrl_read(node: Node, region: Region, slot: int) -> Any:
    return node.regions.ctrl[region][slot]


def apply_log_write(node: Node, writer_sid: Sid, entries: list[LogEntry],
                    commit: int) -> WriteResult:
    """Leader's one-sided log write into a follower (update_remote_logs
    analog): fence-checked, idempotent for already-present entries,
    stops at the first non-contiguous index (the leader re-adjusts)."""
    if not node.regions.log_write_allowed(writer_sid):
        return WriteResult.FENCED
    for e in entries:
        if e.idx < node.log.end:
            continue              # idempotent re-write
        if e.idx > node.log.end:
            break                 # non-contiguous: stop
        if node.log.is_full:
            break
        node.log.write(dataclasses.replace(e))
        # Stage span (cross-replica stitch): the sampled entry landed
        # in THIS follower's log (ring-only — followers never see the
        # reply, so no open-table entry).
        if node.obs is not None and e.req_id > 0 \
                and node.obs.spans.sampled(e.req_id):
            node.obs.spans.stamp(e.clt_id, e.req_id, "follower_append",
                                 idx=e.idx, term=e.term,
                                 open_new=False)
    node.log.advance_commit(min(commit, node.log.end))
    return WriteResult.OK


def apply_log_read_state(node: Node) -> LogState:
    log = node.log
    ai, at = node._applied_det
    return LogState(commit=log.commit, end=log.end,
                    nc_determinants=log.nc_determinants(),
                    applied_idx=ai, applied_term=at)


def apply_log_set_end(node: Node, writer_sid: Sid,
                      new_end: int) -> WriteResult:
    if not node.regions.log_write_allowed(writer_sid):
        return WriteResult.FENCED
    # Fail fast on new_end < commit: the adjustment algorithm never asks a
    # follower to truncate committed entries (NC determinants start at
    # commit, dare_log.h:339-359) — reaching here is a protocol bug that
    # must surface loudly, not be clamped away.
    node.log.truncate(new_end)
    return WriteResult.OK


def apply_log_bulk_read(node: Node, start: int,
                        stop: int) -> list[LogEntry]:
    return [dataclasses.replace(e) for e in node.log.entries(start, stop)]


def apply_snap_push(node: Node, writer_sid: Sid, snap: Any,
                    ep_dump: list, cid: Any = None,
                    member_addrs: dict | None = None,
                    delta_base: "tuple[int, int] | None" = None
                    ) -> WriteResult:
    """Install a leader-pushed snapshot.  Fence-checked exactly like log
    writes (it rewrites the log base); staleness is rejected inside
    install_snapshot.  ``delta_base`` marks snap.data as a state DELTA
    on top of the receiver's applied determinant — refused (sender
    falls back to a full image) unless the determinant still matches
    exactly."""
    if not node.regions.log_write_allowed(writer_sid):
        return WriteResult.FENCED
    if not node.install_snapshot(snap, ep_dump, cid, member_addrs,
                                 delta_base=delta_base):
        # Stale snapshot (target's commit is already past it) or a
        # delta whose base no longer matches: surface the refusal so
        # the pusher re-reads our real state / falls back to a full
        # image instead of assuming we now sit at snap.last_idx.
        return WriteResult.REFUSED
    return WriteResult.OK


# -- chunked RESUMABLE snapshot stream (OP_SNAP_BEGIN/CHUNK/END) ----------
# One in-flight assembly per node.  The partial blob assembles into a
# DETERMINISTICALLY-NAMED file in the spool dir plus a checkpoint
# sidecar (JSON: stream identity + cumulative CRC32 at every received
# chunk boundary), so the receiver holds at most one chunk in RAM until
# install time AND a stream interrupted by sender restart, receiver
# restart, or a transient partition RESUMES from the last acked chunk:
# a new BEGIN with the same identity (sender slot, last_idx, last_term,
# total) verifies the partial file against its checkpoints, truncates
# to the longest clean prefix, and answers the resume offset — never a
# restart from byte zero.  A torn or bit-flipped partial file
# quarantines (fresh start, counted) instead of wedging or installing
# garbage.  Identity safety: our SM dumps are deterministic functions
# of the applied prefix and the captured [0, total) prefix of a given
# sender is immutable (append-only dump / immutable blob), so equal
# identity => byte-identical stream; per-chunk CRCs guard the wire.

def _snap_spool_path(node: Node) -> "tuple[str | None, str | None]":
    """(part_path, meta_path) in the spool dir, or (None, None) when no
    spool dir exists (in-memory/in-process clusters: the session is
    then resumable only within this process' lifetime, via tempfile).
    Preference: the SM's own dump directory (adoption is then a
    same-filesystem rename), else the runtime-provided spool
    (``node.snap_spool_dir`` — the daemon points it at its db dir)."""
    import os
    spool_dir = None
    spool = getattr(node.sm, "snapshot_spool_dir", None)
    if spool is not None:
        spool_dir = spool()
    if spool_dir is None:
        spool_dir = getattr(node, "snap_spool_dir", None)
    if spool_dir is None:
        return None, None
    base = os.path.join(spool_dir, f"apus-snap-in-{node.idx}.part")
    return base, base + ".meta"


def _snap_session_close(node: Node) -> None:
    """Close the in-memory session but KEEP the partial file + meta on
    disk — the resume anchor for the next BEGIN."""
    sess = getattr(node, "_snap_stream_in", None)
    if sess is not None:
        try:
            sess["f"].close()
        except OSError:
            pass
    node._snap_stream_in = None


def _snap_session_drop(node: Node) -> None:
    """Discard the session AND its on-disk partial (fresh start:
    foreign identity, corruption quarantine, or successful install)."""
    import os
    sess = getattr(node, "_snap_stream_in", None)
    paths = []
    if sess is not None:
        try:
            sess["f"].close()
        except OSError:
            pass
        paths = [sess["path"], sess.get("meta_path")]
    else:
        part, meta = _snap_spool_path(node)
        paths = [part, meta]
    for p in paths:
        if not p:
            continue
        try:
            os.unlink(p)
        except OSError:
            pass
    node._snap_stream_in = None


def _snap_meta_write(sess: dict) -> None:
    """Checkpoint the session's progress next to the partial file
    (atomic replace): identity + cumulative CRC at each chunk boundary.
    Best-effort — a lost checkpoint only shrinks the resumable prefix."""
    import json
    import os
    mp = sess.get("meta_path")
    if not mp:
        return
    tmp = mp + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump({"ident": sess["ident"], "got": sess["got"],
                       "crcs": sess["crcs"]}, f)
        os.replace(tmp, mp)
    except OSError:
        pass


def _snap_resume_offset(node: Node, part: str, meta_path: str,
                        ident: list) -> int:
    """Longest clean resumable prefix of an on-disk partial: identity
    must match, and the file's cumulative CRC32 must agree with the
    recorded checkpoint at each boundary (computed in one streaming
    pass).  A torn tail resumes from the last intact boundary; a
    bit-flip inside the prefix fails every later checkpoint and
    quarantines down to the last boundary BEFORE the damage (possibly
    0 — a full re-fetch, never an install of damaged bytes)."""
    import json
    import os
    import zlib
    try:
        with open(meta_path) as f:
            rec = json.load(f)
        if rec.get("ident") != ident:
            return 0
        crcs = [(int(o), int(c)) for o, c in rec.get("crcs", [])]
    except (OSError, ValueError, TypeError):
        return 0
    if not crcs:
        return 0
    try:
        size = os.path.getsize(part)
    except OSError:
        return 0
    good = 0
    crc = 0
    pos = 0
    try:
        with open(part, "rb") as f:
            for off, want in crcs:
                if off > size:
                    break
                while pos < off:
                    chunk = f.read(min(1 << 20, off - pos))
                    if not chunk:
                        return good
                    crc = zlib.crc32(chunk, crc)
                    pos += len(chunk)
                if (crc & 0xFFFFFFFF) != (want & 0xFFFFFFFF):
                    break
                good = off
    except OSError:
        return 0
    return good


def apply_snap_begin(node: Node, writer_sid: Sid, total: int,
                     meta_snap: Any, ep_dump: list, cid: Any,
                     member_addrs: dict | None
                     ) -> "tuple[WriteResult, int]":
    """Open (or RESUME) an assembly session; returns (result,
    resume_offset) — the sender starts its chunk loop at the offset.
    Same fence gate as SNAP_PUSH — a deposed leader cannot even begin a
    stream."""
    import os
    import tempfile

    if not node.regions.log_write_allowed(writer_sid):
        return WriteResult.FENCED, 0
    ident = [writer_sid.idx, meta_snap.last_idx, meta_snap.last_term,
             total]
    sess = getattr(node, "_snap_stream_in", None)
    part, meta_path = _snap_spool_path(node)
    resume = 0
    if sess is not None and sess["ident"] == ident:
        # Same stream re-opened (sender-side retry after a transient
        # failure): keep the bytes, hand back the progress.  The
        # session's own paths win — they may be a tempfile when no
        # spool dir exists.
        resume = sess["got"]
        part, meta_path = sess["path"], sess.get("meta_path")
        _snap_session_close(node)
    elif part is not None and os.path.exists(part) \
            and os.path.exists(meta_path):
        # Receiver restarted (or session closed) mid-stream: the
        # partial file survived in the spool dir — verify and resume.
        _snap_session_close(node)
        resume = _snap_resume_offset(node, part, meta_path, ident)
        if resume == 0:
            # Foreign identity or damaged prefix: quarantine (count
            # the damage case loudly) and start over.
            try:
                with open(meta_path) as f:
                    import json as _json
                    stale = _json.load(f).get("ident")
            except (OSError, ValueError):
                stale = None
            if stale == ident:
                node.bump("snap_chunk_quarantines")
            _snap_session_drop(node)
    else:
        _snap_session_drop(node)

    node._note("snap_stream", "begin", sender=writer_sid.idx,
               total=total, resume=resume)
    crcs: list = []
    if resume:
        import zlib
        node.bump("snap_stream_resumes")
        with open(part, "r+b") as tf:
            tf.truncate(resume)
        f = open(part, "r+b")
        f.seek(resume)
        # Rebuild the cumulative-CRC chain root so later checkpoints
        # extend the verified prefix.
        crc = 0
        with open(part, "rb") as rf:
            left = resume
            while left:
                chunk = rf.read(min(1 << 20, left))
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
                left -= len(chunk)
        crcs = [(resume, crc & 0xFFFFFFFF)]
    elif part is not None:
        f = open(part, "w+b")
    else:
        # No spool dir: assemble next to nothing — tempfile (resumable
        # only while this process lives, via the in-memory session).
        f = tempfile.NamedTemporaryFile(prefix="apus-snap-in-",
                                        delete=False)
        part, meta_path = f.name, None
    node._snap_stream_in = {
        "sid": writer_sid.word, "ident": ident, "total": total,
        "got": resume, "meta": meta_snap, "ep_dump": ep_dump,
        "cid": cid, "members": member_addrs, "f": f, "path": part,
        "meta_path": meta_path, "crcs": crcs,
    }
    _snap_meta_write(node._snap_stream_in)
    return WriteResult.OK, resume


def apply_snap_chunk(node: Node, writer_sid: Sid, off: int,
                     data: bytes, crc: "int | None" = None
                     ) -> "tuple[WriteResult, int]":
    """Append one chunk; returns (result, acked_offset).  A duplicate
    of an already-received span (sender retry after a lost reply) acks
    forward instead of failing; a CRC mismatch quarantines the partial
    and refuses (the sender's next BEGIN re-fetches from byte zero —
    never wedges, never installs flipped bits)."""
    sess = getattr(node, "_snap_stream_in", None)
    if not node.regions.log_write_allowed(writer_sid):
        _snap_session_close(node)
        return WriteResult.FENCED, 0
    if sess is None or sess["sid"] != writer_sid.word:
        return WriteResult.REFUSED, 0       # no/foreign session
    if crc is not None:
        import zlib
        if (zlib.crc32(data) & 0xFFFFFFFF) != (crc & 0xFFFFFFFF):
            node.bump("snap_chunk_quarantines")
            node._note("snap_stream", "chunk_quarantine", off=off)
            _snap_session_drop(node)
            return WriteResult.REFUSED, 0   # damaged on the wire
    if off + len(data) <= sess["got"]:
        return WriteResult.OK, sess["got"]  # duplicate: ack forward
    if off != sess["got"] or off + len(data) > sess["total"]:
        # Out-of-order / overlong: close (keep bytes for resume).
        _snap_session_close(node)
        return WriteResult.REFUSED, 0
    import zlib
    sess["f"].write(data)
    sess["f"].flush()
    sess["got"] += len(data)
    prev = sess["crcs"][-1][1] if sess["crcs"] else 0
    sess["crcs"].append((sess["got"],
                         zlib.crc32(data, prev) & 0xFFFFFFFF))
    _snap_meta_write(sess)
    return WriteResult.OK, sess["got"]


def apply_snap_end(node: Node, writer_sid: Sid) -> WriteResult:
    """Close the stream and install FROM THE FILE: the assembled dump
    is handed to the SM for adoption (RelayStateMachine renames it into
    place and scans it chunk-buffered), so the receiver never holds
    more than one chunk resident — completing what the pusher-side
    streaming started.  The reference installs from its disk-backed
    BDB dump the same way (proxy.c:306-339)."""
    import os
    sess = getattr(node, "_snap_stream_in", None)
    if sess is None or sess["sid"] != writer_sid.word \
            or sess["got"] != sess["total"]:
        _snap_session_close(node)
        return WriteResult.REFUSED
    if not node.regions.log_write_allowed(writer_sid):
        _snap_session_close(node)
        return WriteResult.FENCED
    sess["f"].flush()
    sess["f"].close()
    ok = node.install_snapshot(sess["meta"], sess["ep_dump"],
                               sess["cid"], sess["members"],
                               data_path=sess["path"], adopt=True)
    node._note("snap_stream", "end", sender=writer_sid.idx,
               installed=bool(ok), total=sess["total"])
    # The checkpoint sidecar is dead either way; _snap_session_drop's
    # unlink of the part file is a no-op if the SM adopted (renamed)
    # it, and the needed cleanup otherwise.
    mp = sess.get("meta_path")
    if mp:
        try:
            os.unlink(mp)
        except OSError:
            pass
    _snap_session_drop(node)
    return WriteResult.OK if ok else WriteResult.REFUSED
