"""Deterministic in-process multi-replica simulator.

The reference's only "test harness" is a real InfiniBand cluster driven by
shell scripts (benchmarks/run.sh, reconf_bench.sh) — there are no unit
tests, mocks, or fake backends (SURVEY.md §4).  This module is the fake
backend: N ``Node`` instances wired through a ``SimTransport`` that
performs one-sided region accesses directly on the peers' memory (the
"HCA DMA" — no target CPU involvement), with deterministic, seeded fault
injection:

- per-link message drop probability (WC-error analog),
- partitions (set of blocked node pairs),
- crashed nodes (all ops to/from them fail; they stop ticking),
- fencing enforced exactly as the device plane enforces it (term-masked
  log writes; see apus_tpu.parallel.transport docstring).

Time is simulated: ``Cluster.run`` advances a virtual clock in fixed
steps, ticking every live node each step, so every run with the same seed
is bit-identical — election races, leader crashes, and log divergence
become replayable unit tests.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import random

from apus_tpu.core.cid import Cid
from apus_tpu.core.log import LogEntry
from apus_tpu.core.node import Node, NodeConfig
from apus_tpu.core.sid import Sid
from apus_tpu.core.types import Role
from apus_tpu.models.sm import RecordingStateMachine, StateMachine
from apus_tpu.parallel import onesided
from apus_tpu.parallel.transport import (LogState, Region, Transport,
                                         WriteResult)


class SimTransport(Transport):
    def __init__(self, seed: int = 0, drop_rate: float = 0.0):
        self.nodes: list[Node] = []
        self.rng = random.Random(seed)
        self.drop_rate = drop_rate
        self.crashed: set[int] = set()
        self.blocked: set[tuple[int, int]] = set()   # directed pairs
        self.initiator: Optional[int] = None         # set by Cluster per tick
        self.op_count = 0

    def attach(self, nodes: list[Node]) -> None:
        self.nodes = nodes

    # -- fault injection --------------------------------------------------

    def partition(self, group_a: set[int], group_b: set[int]) -> None:
        for a in group_a:
            for b in group_b:
                self.blocked.add((a, b))
                self.blocked.add((b, a))

    def heal(self) -> None:
        self.blocked.clear()

    def _reachable(self, target: int) -> bool:
        self.op_count += 1
        src = self.initiator
        if target in self.crashed or (src is not None and src in self.crashed):
            return False
        if src is not None and (src, target) in self.blocked:
            return False
        if self.drop_rate and self.rng.random() < self.drop_rate:
            return False
        return True

    # -- one-sided ops ----------------------------------------------------

    def ctrl_write(self, target: int, region: Region, slot: int,
                   value) -> WriteResult:
        if not self._reachable(target):
            return WriteResult.DROPPED
        return onesided.apply_ctrl_write(self.nodes[target], region, slot,
                                         value)

    def ctrl_read(self, target: int, region: Region, slot: int):
        if not self._reachable(target):
            return None
        return onesided.apply_ctrl_read(self.nodes[target], region, slot)

    def log_write(self, target: int, writer_sid: Sid,
                  entries: list[LogEntry], commit: int):
        if not self._reachable(target):
            return WriteResult.DROPPED, None
        # acked_end stays None: the sim models the one-sided RDMA shape,
        # where a WRITE completion carries no remote-CPU acknowledgment
        # — acks arrive via the follower's own REP_ACK path, keeping the
        # simulator's protocol timing reference-faithful.
        return onesided.apply_log_write(self.nodes[target], writer_sid,
                                        entries, commit), None

    def log_read_state(self, target: int) -> Optional[LogState]:
        if not self._reachable(target):
            return None
        return onesided.apply_log_read_state(self.nodes[target])

    def log_set_end(self, target: int, writer_sid: Sid,
                    new_end: int) -> WriteResult:
        if not self._reachable(target):
            return WriteResult.DROPPED
        return onesided.apply_log_set_end(self.nodes[target], writer_sid,
                                          new_end)

    def log_bulk_read(self, target: int, start: int,
                      stop: int) -> Optional[list[LogEntry]]:
        if not self._reachable(target):
            return None
        return onesided.apply_log_bulk_read(self.nodes[target], start, stop)

    def snap_push(self, target: int, writer_sid: Sid, snap,
                  ep_dump: list, cid=None, member_addrs=None,
                  delta_base=None) -> WriteResult:
        if not self._reachable(target):
            return WriteResult.DROPPED
        return onesided.apply_snap_push(self.nodes[target], writer_sid,
                                        snap, ep_dump, cid, member_addrs,
                                        delta_base=delta_base)


class Cluster:
    """N-replica simulated cluster with a virtual clock."""

    def __init__(self, n: int, seed: int = 0, drop_rate: float = 0.0,
                 sm_factory: Callable[[], StateMachine] = RecordingStateMachine,
                 **cfg_overrides):
        self.n = n
        self.now = 0.0
        self.dt = 0.001
        self.transport = SimTransport(seed=seed, drop_rate=drop_rate)
        cid = Cid.initial(n)
        self.nodes = [
            Node(NodeConfig(idx=i, seed=seed, **cfg_overrides), cid,
                 sm_factory(), self.transport)
            for i in range(n)
        ]
        self.transport.attach(self.nodes)
        # Stagger initial election timers so a fresh start elects cleanly
        # (randomized timeouts, dare_server.c:1237).
        for node in self.nodes:
            node._last_hb_seen = node.rng.random() * node.cfg.elect_high

    # -- stepping ---------------------------------------------------------

    def step(self) -> None:
        self.now += self.dt
        for node in self.nodes:
            if node.idx in self.transport.crashed:
                continue
            self.transport.initiator = node.idx
            node.tick(self.now)
        self.transport.initiator = None

    def run(self, duration: float) -> None:
        steps = int(duration / self.dt)
        for _ in range(steps):
            self.step()

    def run_until(self, pred: Callable[[], bool], timeout: float = 10.0) -> bool:
        deadline = self.now + timeout
        while self.now < deadline:
            self.step()
            if pred():
                return True
        return False

    # -- queries ----------------------------------------------------------

    def leader(self) -> Optional[Node]:
        leaders = [n for n in self.nodes
                   if n.is_leader and n.idx not in self.transport.crashed]
        if not leaders:
            return None
        return max(leaders, key=lambda n: n.current_term)

    def wait_for_leader(self, timeout: float = 10.0) -> Node:
        ok = self.run_until(lambda: self.leader() is not None, timeout)
        assert ok, "no leader elected within timeout"
        leader = self.leader()
        assert leader is not None
        return leader

    # -- client ops -------------------------------------------------------

    _req_seq = 0

    def submit(self, data: bytes, timeout: float = 5.0):
        """Submit via the current leader and wait for commit (the proxy
        spin-wait analog, proxy.c:160)."""
        Cluster._req_seq += 1
        leader = self.wait_for_leader(timeout)
        pr = leader.submit(Cluster._req_seq, 0, data)
        assert pr is not None
        ok = self.run_until(
            lambda: pr.idx is not None and leader.log.commit > pr.idx,
            timeout)
        assert ok, f"request not committed within {timeout}s"
        return pr

    # -- fault injection --------------------------------------------------

    def crash(self, idx: int) -> None:
        self.transport.crashed.add(idx)

    def recover(self, idx: int) -> None:
        """Restart a crashed node with empty volatile state (the log is
        volatile in the reference too — durability is BDB + replication,
        SURVEY.md §5.4).  Recovery/catch-up is driven by the leader's
        adjustment + snapshot path."""
        self.transport.crashed.discard(idx)
        old = self.nodes[idx]
        node = Node(old.cfg, old.cid, type(old.sm)(), self.transport)
        prv = old.regions.ctrl[Region.PRV][idx]
        if prv is not None:
            node.regions.ctrl[Region.PRV][idx] = prv   # durable vote survives
        node._last_hb_seen = self.now  # grace period before electioneering
        self.nodes[idx] = node
        self.transport.attach(self.nodes)

    # -- invariants -------------------------------------------------------

    def check_logs_consistent(self) -> None:
        """Safety: committed prefixes agree across all replicas."""
        for node in self.nodes:
            node.log.check()
        min_commit = min(n.log.commit for n in self.nodes
                         if n.idx not in self.transport.crashed)
        for i in range(1, min_commit):
            dets = {n.log.get(i).determinant() for n in self.nodes
                    if n.idx not in self.transport.crashed
                    and n.log.head <= i < n.log.commit}
            assert len(dets) <= 1, f"divergent committed entry at idx {i}: {dets}"
