"""One-sided transport abstraction.

The reference's entire protocol is expressed as one-sided RDMA accesses to
two remotely-exposed regions per server: the control data (ctrl_data_t,
dare_server.h:123-140 — per-peer slots for vote requests, heartbeats,
acks, offsets) and the log (dare_log.h).  We preserve that model as the
*abstract interface* because it maps cleanly onto all three of our
backends:

- ``SimTransport`` (apus_tpu.parallel.sim): direct memory access with
  deterministic fault injection — the in-process test backend the
  reference never had.
- the JAX device plane (apus_tpu.ops): control slots and log slots become
  sharded arrays; "writes" are collective permutes/reductions inside a
  jitted step.
- the DCN control plane (apus_tpu.proxy.net): slots become RPC'd mailbox
  writes between hosts.

Fencing redesign: the reference physically blocks a deposed leader's
one-sided writes by resetting QPs (rc_revoke_log_access
dare_ibv_rc.c:2156-2255).  Collectives have no such mechanism — every
replica participates in every step — so fencing is explicit: each node's
log region carries ``(granted_to, fence_term)`` and the target applies a
log write only if the writer's SID passes the fence.  The same check runs
inside the jitted device step (term-masked writes, apus_tpu.ops.commit).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional

from apus_tpu.core.log import LogEntry
from apus_tpu.core.sid import Sid
from apus_tpu.core.types import MAX_SERVER_COUNT


class Region(str, enum.Enum):
    """Named control slots, one array per node, indexed by peer
    (ctrl_data_t parity, dare_server.h:123-140)."""

    VOTE_REQ = "vote_req"     # VoteRequest from candidate peer
    VOTE_ACK = "vote_ack"     # voter's commit idx, written to candidate
    HB = "hb"                 # SID word heartbeats
    PRV = "prv"               # replicated (durable) votes: sid words
    APPLY_IDX = "apply_idx"   # peers' apply indices (for pruning)
    REP_ACK = "rep_ack"       # follower -> leader: highest replicated idx
    SM_REQ = "sm_req"         # snapshot request flags
    SM_REP = "sm_rep"         # snapshot replies {sid_word, snapshot}
    RSID = "rsid"             # each node mirrors its own SID in slot[own]
                              # for remote leadership verification
                              # (rc_verify_leadership reads, dare_ibv_rc.c
                              # :1182-1280; new regions append — the wire
                              # indexes positionally)
    PREVOTE_ACK = "prevote_ack"  # voter -> precandidate: pre-granted term
                              # (PreVote, Raft §9.6 — see VoteRequest)


class Regions:
    """A node's remotely-writable memory: control slots + log fence."""

    def __init__(self) -> None:
        self.ctrl: dict[Region, list[Any]] = {
            r: [None] * MAX_SERVER_COUNT for r in Region
        }
        # Log-access fence (replaces QP-state fencing).
        self.granted_to: Optional[int] = None
        self.fence_term: int = 0
        # Wall-clock of the last remote write per (region, slot) — the
        # liveness evidence the device-plane quorum mask consumes (a
        # peer whose control writes stopped arriving is not counted;
        # see runtime.device_plane safety argument 3).  Unused by the
        # virtual-time simulator.
        self.touched: dict[tuple[Region, int], float] = {}

    def touch(self, region: Region, slot: int, now: float) -> None:
        self.touched[(region, slot)] = now

    def grant_log_access(self, idx: Optional[int], term: int) -> None:
        """restore/revoke analog (dare_ibv_rc.c:2156-2255): ``idx=None``
        revokes all access; otherwise only ``idx`` at ``term`` may write."""
        self.granted_to = idx
        self.fence_term = max(self.fence_term, term)

    def log_write_allowed(self, writer_sid: Sid) -> bool:
        return (self.granted_to == writer_sid.idx
                and writer_sid.term >= self.fence_term)


class WriteResult(enum.Enum):
    OK = 0
    DROPPED = 1     # network loss / partition (WC error analog)
    FENCED = 2      # log fence rejected the write
    REFUSED = 3     # target rejected as stale (e.g. snapshot older than
                    # its commit): not a failure, re-read its state


@dataclasses.dataclass
class LogState:
    """Snapshot of a remote log's offsets + NC determinants, as read by
    the leader during adjustment (LR_GET_WRITE/NCE steps,
    dare_ibv_rc.c:1292-1451).

    ``applied_idx``/``applied_term`` carry the target's last APPLIED
    determinant — the base a delta snapshot can build on (the rejoining
    member "presents its last applied (epoch, index)"; the leader ships
    only the state delta past it when its compaction floor permits).
    (0, 0) from pre-delta peers: delta-ineligible, full push."""

    commit: int
    end: int
    nc_determinants: list[tuple[int, int]]
    applied_idx: int = 0
    applied_term: int = 0


class Transport:
    """Initiator-side one-sided operations.  All may fail (None/DROPPED)
    — failures feed the failure detector exactly like CTRL-QP work-
    completion errors do in the reference (dare_ibv_rc.c:2747-2749)."""

    def peer_established(self, target: int) -> bool:
        """Whether this transport has EVER reached ``target`` at its
        current address.  The failure detector only counts failures for
        established peers — the reference's analog is that WC errors can
        only occur on QPs that completed bootstrap connection setup
        (dare_ibv_rc.c:2747-2749); a cold-starting cluster member that
        has not come up yet must not be auto-removed as "failed"."""
        return True

    def peer_failure_was_timeout(self, target: int) -> bool:
        """Whether the MOST RECENT failed op to ``target`` was a timeout
        on an ESTABLISHED connection — the peer's process is alive (it
        holds the TCP connection open) but its event loop is busy, e.g.
        installing a multi-second snapshot.  The reference's failure
        counter only sees WC errors, which require connection-level
        death (dare_ibv_rc.c:3202-3314 classifies them off the QP) — a
        busy-but-connected peer generates none, so it is never
        auto-removed.  Transports that cannot distinguish return False
        (every failure counts, the pre-r4 behavior)."""
        return False

    # control plane -------------------------------------------------------
    def ctrl_write(self, target: int, region: Region, slot: int,
                   value: Any) -> WriteResult:
        raise NotImplementedError

    def ctrl_read(self, target: int, region: Region, slot: int) -> Any:
        raise NotImplementedError

    # log data plane ------------------------------------------------------
    def log_write(self, target: int, writer_sid: Sid,
                  entries: list[LogEntry],
                  commit: int) -> "tuple[WriteResult, Optional[int]]":
        """Replicate ``entries`` into target's log and advance its commit
        (update_remote_logs analog, dare_ibv_rc.c:1460-1826).  Returns
        (result, acked_end): ``acked_end`` is the target's authoritative
        log end AFTER the write when the transport's reply carries it
        (the synchronous DCN request/response does — the handler applies
        under the server lock before replying), or None for transports
        with true one-sided completion semantics (the simulator models
        the RDMA shape, where a WRITE completion says nothing about the
        remote log and acks arrive via the follower's own REP_ACK
        writes, rc_send_entries_reply dare_ibv_rc.c:1828-1863)."""
        raise NotImplementedError

    def log_read_state(self, target: int) -> Optional[LogState]:
        """Read target's offsets + NC buffer (adjustment read)."""
        raise NotImplementedError

    def log_set_end(self, target: int, writer_sid: Sid,
                    new_end: int) -> WriteResult:
        """Truncate target's log (LR_SET_END, dare_ibv_rc.c:1292-1451)."""
        raise NotImplementedError

    def log_bulk_read(self, target: int, start: int,
                      stop: int) -> Optional[list[LogEntry]]:
        """Bulk-fetch entries for recovery (rc_recover_log analog,
        dare_ibv_rc.c:726-856)."""
        raise NotImplementedError

    def snap_push(self, target: int, writer_sid: Sid, snap: Any,
                  ep_dump: list, cid: Any = None,
                  member_addrs: Optional[dict] = None,
                  delta_base: Optional[tuple] = None) -> WriteResult:
        """Install a snapshot on a lagging/joining peer (leader-driven
        form of the reference's snapshot recovery, rc_recover_sm
        dare_ibv_rc.c:603-689).  Fence-checked like log writes.
        ``cid``/``member_addrs`` carry the snapshot-point configuration
        (CONFIG entries inside the covered prefix are never applied by
        the installer).  ``delta_base=(idx, term)`` marks snap.data as
        a state DELTA on top of the receiver's applied determinant —
        the receiver refuses (REFUSED) unless its determinant still
        matches exactly, and the sender then falls back to a full
        image."""
        raise NotImplementedError
