"""Binary wire codec for the DCN control plane.

The reference's control plane rides raw IB messages with packed C structs
(ud_hdr_t / rc_syn_t / client_req_t, dare_ibv_ud.h:29-81) and its data
plane writes raw log bytes.  Our DCN analog speaks a compact framed
binary protocol over TCP sockets: every message is ``u32 length`` +
payload, with fixed little-endian struct layouts below.  The same layouts
are shared by the native C++ proxy (native/apus_wire.h) so host tools and
the Python runtime interoperate.

Struct layouts (little endian):

    Cid        = epoch:u32 state:u8 size:u8 new_size:u8 bitmask:u16
    LogEntry   = idx:u64 term:u64 req_id:u64 clt_id:u64 type:u8 head:u64
                 flags:u8 [cid if flags&1] dlen:u32 data
    VoteReq    = sid:u64 last_idx:u64 last_term:u64 epoch:u32 prevote:u8
    Snapshot   = last_idx:u64 last_term:u64 dlen:u32 data

One-sided RPC requests are ``op:u8`` + body; responses are ``status:u8``
+ body (see OP_* / ST_* constants).  Control-slot values are a tagged
variant (VAR_*).
"""

from __future__ import annotations

import struct
from typing import Any, Optional

from apus_tpu.core.cid import Cid, CidState
from apus_tpu.core.election import VoteRequest
from apus_tpu.core.log import LogEntry
from apus_tpu.core.types import EntryType
from apus_tpu.models.sm import Snapshot
from apus_tpu.parallel.transport import LogState, Region

# -- ops (initiator -> target) -------------------------------------------
OP_CTRL_WRITE = 1
OP_CTRL_READ = 2
OP_LOG_WRITE = 3
OP_LOG_READ_STATE = 4
OP_LOG_SET_END = 5
OP_LOG_BULK_READ = 6
OP_JOIN = 7          # membership join request (ud_join_cluster analog)
OP_SNAP_FETCH = 8    # snapshot fetch for recovery (rc_recover_sm analog)
OP_SNAP_PUSH = 9     # leader-pushed snapshot install (lagging peer/joiner)
# Chunked snapshot stream (large dumps): BEGIN carries the metadata of
# a SNAP_PUSH minus the blob; CHUNKs carry the blob; END installs with
# SNAP_PUSH's exact fence/staleness semantics.  Bounds the pusher's RAM
# to one chunk — the whole-blob SNAP_PUSH materializes O(history) on
# the leader, whose GC pauses then wobble elections at deep history.
OP_SNAP_BEGIN = 10
OP_SNAP_CHUNK = 11
OP_SNAP_END = 12

# -- multi-group (Multi-Raft) envelope ------------------------------------
# OP_GROUP wraps any other op for a NON-ZERO consensus group sharing the
# same daemon/socket set: ``u8 OP_GROUP | u8 gid | <inner frame>``.  The
# receiver demuxes on gid to that group's node/handlers.  Group 0 (and
# EVERYTHING when groups == 1) is never wrapped, so single-group wire
# frames stay byte-identical to the pre-multi-group protocol.
OP_GROUP = 25
# OP_HB_MULTI: one coalesced heartbeat frame per peer carrying ALL
# groups this daemon currently leads — the (term, commit, lease)
# vector of the Multi-Raft design, replacing per-group HB ctrl writes:
#   request: u8 op | u8 sender_slot | u8 n |
#            n x (u8 gid | u64 sid_word | u64 commit | u32 lease_us
#                 | u32 incarnation)
#   reply:   u8 ST_OK | n x (u8 status | u64 echo_sid_word)
# Per-item status is ST_OK / ST_FENCED (stale incarnation for that
# group's fence table) / ST_ERROR (unknown gid); the echoed SID is the
# receiver's CURRENT sid for that group — the per-group lease-renewal
# evidence (same contract as the OP_CTRL_WRITE reply echo).
OP_HB_MULTI = 26

_HB_ITEM = struct.Struct("<BQQII")
_HB_ECHO = struct.Struct("<BQ")


def encode_hb_multi(sender: int, items: list) -> bytes:
    """``items`` = [(gid, sid_word, commit, lease_us, incarnation)]."""
    out = [bytes([OP_HB_MULTI, sender, len(items)])]
    for gid, word, commit, lease_us, inc in items:
        out.append(_HB_ITEM.pack(gid, word, commit, lease_us, inc))
    return b"".join(out)


def decode_hb_multi(r: "Reader") -> tuple[int, list]:
    sender = r.u8()
    n = r.u8()
    items = [_HB_ITEM.unpack(r.take(_HB_ITEM.size)) for _ in range(n)]
    return sender, items


def encode_hb_echoes(echoes: list) -> bytes:
    """``echoes`` = [(status, sid_word)] in request item order."""
    return bytes([ST_OK]) + b"".join(_HB_ECHO.pack(s, w)
                                     for s, w in echoes)


def decode_hb_echoes(resp: bytes, n: int) -> Optional[list]:
    """Parse a multi-HB reply into n (status, echo_word) pairs; None on
    a malformed/short frame (treated as a wire drop by the sender)."""
    if not resp or resp[0] != ST_OK \
            or len(resp) < 1 + n * _HB_ECHO.size:
        return None
    return [_HB_ECHO.unpack_from(resp, 1 + i * _HB_ECHO.size)
            for i in range(n)]


#: SNAP_PUSH trailing-flags bit: the payload is a DELTA on top of the
#: receiver's applied determinant (u64 base_idx + u64 base_term follow
#: the flag byte); the receiver refuses unless its applied determinant
#: matches exactly — the sender then falls back to a full image.
SNAPF_DELTA = 1

# -- response status ------------------------------------------------------
ST_OK = 0
ST_DROPPED = 1
ST_FENCED = 2
ST_ERROR = 3
ST_REFUSED = 4

# -- ctrl value variants --------------------------------------------------
VAR_NONE = 0
VAR_U64 = 1
VAR_VOTEREQ = 2
VAR_BYTES = 3
VAR_SNAPSHOT = 4

# Stable region indices for the wire (Region is a str enum).
REGION_LIST = list(Region)
REGION_INDEX = {r: i for i, r in enumerate(REGION_LIST)}

_CID = struct.Struct("<IBBBH")
_ENTRY_FIXED = struct.Struct("<QQQQBQB")
_VOTEREQ = struct.Struct("<QQQIB")
_SNAP_FIXED = struct.Struct("<QQI")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


class Reader:
    """Cursor over a bytes buffer."""

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise ValueError("short buffer")
        self.pos += n
        return b

    def u8(self) -> int:
        return self.take(1)[0]

    def u32(self) -> int:
        return _U32.unpack(self.take(4))[0]

    def u64(self) -> int:
        return _U64.unpack(self.take(8))[0]

    def blob(self) -> bytes:
        return self.take(self.u32())

    @property
    def remaining(self) -> int:
        return len(self.buf) - self.pos


def u8(v: int) -> bytes:
    return bytes([v])


def u32(v: int) -> bytes:
    return _U32.pack(v)


def u64(v: int) -> bytes:
    return _U64.pack(v)


def blob(b: bytes) -> bytes:
    return _U32.pack(len(b)) + b


# -- Cid ------------------------------------------------------------------

def encode_cid(c: Cid) -> bytes:
    return _CID.pack(c.epoch, int(c.state), c.size, c.new_size, c.bitmask)


def decode_cid(r: Reader) -> Cid:
    epoch, state, size, new_size, bitmask = _CID.unpack(r.take(_CID.size))
    return Cid(epoch=epoch, state=CidState(state), size=size,
               new_size=new_size, bitmask=bitmask)


# -- LogEntry -------------------------------------------------------------

def encode_entry(e: LogEntry) -> bytes:
    flags = 1 if e.cid is not None else 0
    out = [_ENTRY_FIXED.pack(e.idx, e.term, e.req_id, e.clt_id,
                             int(e.type), e.head, flags)]
    if e.cid is not None:
        out.append(encode_cid(e.cid))
    out.append(blob(e.data))
    return b"".join(out)


def entry_wire_size(e: LogEntry) -> int:
    """``len(encode_entry(e))`` without encoding: fixed header +
    optional cid + u32-length-prefixed data.  The device-plane driver
    sizes whole windows per round with this gate (thousands of entries
    — re-encoding each just to measure it cost ~3 ms/window)."""
    return _ENTRY_FIXED.size + (_CID.size if e.cid is not None else 0) \
        + 4 + len(e.data)


def encode_entry_into(e: LogEntry, buf, off: int) -> int:
    """Encode directly into a writable 1-D byte buffer at ``off``;
    returns the wire size.  Byte-identical to writing
    ``encode_entry(e)`` there, but with no intermediate bytes objects —
    the device-plane staging encodes thousands of entries per deep
    window and the allocation/join overhead dominated its cost.  The
    caller guarantees ``entry_wire_size(e)`` bytes of room."""
    flags = 1 if e.cid is not None else 0
    _ENTRY_FIXED.pack_into(buf, off, e.idx, e.term, e.req_id, e.clt_id,
                           int(e.type), e.head, flags)
    pos = off + _ENTRY_FIXED.size
    if e.cid is not None:
        c = e.cid
        _CID.pack_into(buf, pos, c.epoch, int(c.state), c.size,
                       c.new_size, c.bitmask)
        pos += _CID.size
    n = len(e.data)
    struct.pack_into("<I", buf, pos, n)
    pos += 4
    buf[pos:pos + n] = e.data
    return pos + n - off


def decode_entry(r: Reader) -> LogEntry:
    idx, term, req_id, clt_id, etype, head, flags = \
        _ENTRY_FIXED.unpack(r.take(_ENTRY_FIXED.size))
    cid = decode_cid(r) if flags & 1 else None
    data = r.blob()
    return LogEntry(idx=idx, term=term, req_id=req_id, clt_id=clt_id,
                    type=EntryType(etype), head=head, cid=cid, data=data)


def encode_entries(entries: list[LogEntry]) -> bytes:
    return struct.pack("<H", len(entries)) + \
        b"".join(encode_entry(e) for e in entries)


def decode_entries(r: Reader) -> list[LogEntry]:
    n = struct.unpack("<H", r.take(2))[0]
    return [decode_entry(r) for _ in range(n)]


# -- ctrl variants --------------------------------------------------------

def encode_value(v: Any) -> bytes:
    if v is None:
        return u8(VAR_NONE)
    if isinstance(v, int):
        return u8(VAR_U64) + u64(v)
    if isinstance(v, VoteRequest):
        return u8(VAR_VOTEREQ) + _VOTEREQ.pack(v.sid_word, v.last_idx,
                                               v.last_term, v.cid_epoch,
                                               1 if v.prevote else 0)
    if isinstance(v, bytes):
        return u8(VAR_BYTES) + blob(v)
    if isinstance(v, Snapshot):
        return (u8(VAR_SNAPSHOT) + _SNAP_FIXED.pack(
            v.last_idx, v.last_term, len(v.data)) + v.data + blob(v.seg)
            + blob(v.fence))
    raise TypeError(f"unencodable ctrl value {type(v)}")


def decode_value(r: Reader) -> Any:
    tag = r.u8()
    if tag == VAR_NONE:
        return None
    if tag == VAR_U64:
        return r.u64()
    if tag == VAR_VOTEREQ:
        sid, li, lt, ep, pv = _VOTEREQ.unpack(r.take(_VOTEREQ.size))
        return VoteRequest(sid_word=sid, last_idx=li, last_term=lt,
                           cid_epoch=ep, prevote=bool(pv))
    if tag == VAR_BYTES:
        return r.blob()
    if tag == VAR_SNAPSHOT:
        li, lt, n = _SNAP_FIXED.unpack(r.take(_SNAP_FIXED.size))
        data = r.take(n)
        seg = r.blob()
        # Fence blob appended by newer senders; absent frames decode
        # with an empty fence (pre-fence stores / peers).
        fence = r.blob() if r.remaining else b""
        return Snapshot(li, lt, data, seg=seg, fence=fence)
    raise ValueError(f"bad variant tag {tag}")


# -- endpoint-DB dump (travels with snapshots for exactly-once) -----------

def encode_ep_dump(entries: list) -> bytes:
    # Each record carries the endpoint's exact applied window (req_id,
    # idx, reply triples) alongside the highwater: the installer must
    # distinguish in-window holes (never applied -> fresh) from true
    # duplicates, so the window travels with every snapshot.
    out = [u32(len(entries))]
    for rec in entries:
        if len(rec) >= 5:
            clt_id, req_id, idx, reply, window = rec[:5]
        else:                     # legacy 4-tuple record (no window)
            clt_id, req_id, idx, reply = rec
            window = [(req_id, idx, reply)] if req_id else []
        out.append(_U64.pack(clt_id) + _U64.pack(req_id) + _U64.pack(idx))
        out.append(u8(1) + blob(reply) if reply is not None else u8(0))
        out.append(u32(len(window)))
        for wreq, widx, wreply in window:
            out.append(_U64.pack(wreq) + _U64.pack(widx))
            out.append(u8(1) + blob(wreply) if wreply is not None
                       else u8(0))
    return b"".join(out)


def decode_ep_dump(r: Reader) -> list:
    n = r.u32()
    out = []
    for _ in range(n):
        clt_id, req_id, idx = r.u64(), r.u64(), r.u64()
        reply = r.blob() if r.u8() else None
        window = []
        for _w in range(r.u32()):
            wreq, widx = r.u64(), r.u64()
            wreply = r.blob() if r.u8() else None
            window.append((wreq, widx, wreply))
        out.append((clt_id, req_id, idx, reply, window))
    return out


# -- member address table (travels with snapshots: the installer never
# applies the covered CONFIG entries, so membership rides alongside) ------

def encode_members(members: dict) -> bytes:
    out = [u32(len(members))]
    for addr, slot in members.items():
        out.append(u8(slot) + blob(addr.encode()))
    return b"".join(out)


def decode_members(r: Reader) -> dict:
    n = r.u32()
    out = {}
    for _ in range(n):
        slot = r.u8()
        out[r.blob().decode()] = slot
    return out


# -- log state ------------------------------------------------------------

def encode_log_state(s: LogState) -> bytes:
    out = [u64(s.commit), u64(s.end), struct.pack("<H", len(s.nc_determinants))]
    for idx, term in s.nc_determinants:
        out.append(u64(idx))
        out.append(u64(term))
    # Applied determinant (delta-snapshot base; see transport.LogState).
    # Trailing so pre-delta readers simply stop before it.
    out.append(u64(s.applied_idx))
    out.append(u64(s.applied_term))
    return b"".join(out)


def decode_log_state(r: Reader) -> LogState:
    commit, end = r.u64(), r.u64()
    n = struct.unpack("<H", r.take(2))[0]
    nc = [(r.u64(), r.u64()) for _ in range(n)]
    # Absent on frames from pre-delta peers: (0, 0) = delta-ineligible.
    applied_idx = r.u64() if r.remaining >= 16 else 0
    applied_term = r.u64() if r.remaining >= 8 else 0
    return LogState(commit=commit, end=end, nc_determinants=nc,
                    applied_idx=applied_idx, applied_term=applied_term)


# -- framing --------------------------------------------------------------

def frame(payload: bytes) -> bytes:
    return _U32.pack(len(payload)) + payload


def frames(payloads: list[bytes]) -> bytes:
    """Coalesce many frames into one contiguous buffer (multi-frame
    write coalescing for the pipelined client/server paths: one kernel
    write instead of 2*N tiny ones riding individual TCP pushes)."""
    return b"".join(_U32.pack(len(p)) + p for p in payloads)


def send_frames(sock, payloads: list[bytes]) -> None:
    """Vectored flush of many frames: one ``sendmsg`` with a gathered
    iovec (the sendmsg-style write the reference gets from its doorbell
    batching), falling back to a coalesced ``sendall`` where sendmsg is
    unavailable or the iovec exceeds the platform's IOV_MAX.  With
    TCP_NODELAY on the socket this is what keeps a pipelined burst from
    paying one segment per tiny frame."""
    if not payloads:
        return
    if len(payloads) == 1:
        sock.sendall(_U32.pack(len(payloads[0])) + payloads[0])
        return
    iov = []
    for p in payloads:
        iov.append(_U32.pack(len(p)))
        iov.append(p)
    sendmsg = getattr(sock, "sendmsg", None)
    if sendmsg is None or len(iov) > 512:
        sock.sendall(b"".join(iov))
        return
    total = sum(len(b) for b in iov)
    sent = sendmsg(iov)
    while sent < total:
        # Partial vectored write: skip the fully-sent prefix and resume.
        rest = []
        skip = sent
        for b in iov:
            if skip >= len(b):
                skip -= len(b)
                continue
            rest.append(b[skip:] if skip else b)
            skip = 0
        iov = rest
        total = sum(len(b) for b in iov)
        sent = sendmsg(iov)


def read_frame(sock) -> Optional[bytes]:
    """Read one length-prefixed frame; None on clean EOF."""
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = _U32.unpack(hdr)
    if n > 1 << 27:          # 128 MB sanity cap
        raise ValueError(f"oversized frame {n}")
    body = _recv_exact(sock, n)
    if body is None:
        raise ConnectionError("truncated frame")
    return body


class FrameStream:
    """Buffered frame reader over a socket: one large ``recv`` services
    many frames, so a pipelined 64-frame burst costs ~1 syscall to
    ingest instead of 128 (read_frame pays 2 recvs per frame, plus the
    server's readiness poll).  All reads on a connection must go
    through ONE stream once it exists — bytes buffered here are
    invisible to direct ``read_frame`` calls on the socket."""

    RECV = 1 << 16

    def __init__(self, sock):
        self._sock = sock
        self._buf = bytearray()
        self._eof = False

    def _parse(self) -> Optional[bytes]:
        buf = self._buf
        if len(buf) < 4:
            return None
        (n,) = _U32.unpack_from(buf)
        if n > 1 << 27:
            raise ValueError(f"oversized frame {n}")
        if len(buf) < 4 + n:
            return None
        frame = bytes(buf[4:4 + n])
        del buf[:4 + n]
        return frame

    def _fill(self) -> bool:
        chunk = self._sock.recv(self.RECV)
        if not chunk:
            self._eof = True
            return False
        self._buf += chunk
        return True

    def next_frame(self) -> Optional[bytes]:
        """Blocking read of one frame (the socket's timeout governs);
        None on clean EOF at a frame boundary."""
        while True:
            f = self._parse()
            if f is not None:
                return f
            if self._eof or not self._fill():
                if self._buf:
                    raise ConnectionError("truncated frame")
                return None

    def try_next(self) -> Optional[bytes]:
        """A complete frame if one is buffered or immediately readable
        (zero-wait poll); None otherwise.  Never blocks."""
        f = self._parse()
        if f is not None:
            return f
        if self._eof:
            return None
        import select as _select
        readable, _, _ = _select.select([self._sock], [], [], 0)
        if not readable:
            return None
        if not self._fill():
            return None
        return self._parse()

    @property
    def at_eof(self) -> bool:
        return self._eof and not self._buf

    def detach_buffer(self) -> bytes:
        """Hand off every buffered-but-unparsed byte and retire this
        stream (native-plane connection adoption: the C++ loop owns
        the socket from here, so bytes buffered in Python must move
        with it — they are invisible to any other reader)."""
        buf = bytes(self._buf)
        self._buf = bytearray()
        self._eof = True
        return buf


def _recv_exact(sock, n: int) -> Optional[bytes]:
    chunks = []
    got = 0
    while got < n:
        c = sock.recv(n - got)
        if not c:
            if got == 0:
                return None
            raise ConnectionError("truncated frame")
        chunks.append(c)
        got += len(c)
    return b"".join(chunks)
