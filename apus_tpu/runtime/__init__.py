"""Host runtime: replica daemons, client service, proxy bridge.

This package is the live counterpart of the reference's in-process DARE
thread (proxy.c:76-81 spawns dare_server_init): each replica runs a
``ReplicaDaemon`` that ticks the pure protocol Node over a DCN
NetTransport, persists committed records, serves client sessions, and
feeds the native proxy/interposer pair.
"""

from apus_tpu.runtime.daemon import ReplicaDaemon

__all__ = ["ReplicaDaemon"]
