"""ProxiedCluster: replicated *unmodified applications*.

The full APUS deployment shape (benchmarks/run.sh:23-31): every replica
runs (a) a consensus daemon and (b) an unmodified TCP server launched
under ``LD_PRELOAD=interpose.so`` with env vars pointing at its local
bridge.  Clients talk TCP to the leader's app; every inbound byte-stream
is replicated through the log before the app sees it, and followers'
apps are fed the same stream by replay — so any replica's app can answer
reads and any replica can take over as leader.
"""

from __future__ import annotations

import os
import socket
import subprocess
import tempfile
import time
from typing import Optional, Sequence

from apus_tpu.runtime.bridge import (INTERPOSE_SO, NATIVE_BUILD, REPO_ROOT,
                                     Bridge, RelayStateMachine, proxy_env)
from apus_tpu.runtime.cluster import LocalCluster
from apus_tpu.utils.config import ClusterSpec

#: Timing envelope for proxied clusters — the reference's DEBUG config
#: (hb=10 ms, elect=100-300 ms, nodes.local.cfg:22-37).  Python daemons
#: sharing cores with app processes and replay threads get GIL-starved
#: at tighter timeouts, which shows up as spurious elections mid-bench.
PROXIED_SPEC = ClusterSpec(hb_period=0.010, hb_timeout=0.100,
                           elect_low=0.150, elect_high=0.400)

TOYSERVER = os.path.join(NATIVE_BUILD, "toyserver")


def build_native() -> None:
    """Ensure the native artifacts exist AND are current: always run
    make (its dependency tracking makes the up-to-date case a no-op).
    An exists-only check once let a stale interpose.so (built before an
    shm layout bump) fail the magic check at runtime and silently
    deactivate the proxy — every app would then serve raw, unreplicated
    traffic while the benchmarks read plausible-looking numbers."""
    subprocess.run(["make", "-C", os.path.join(REPO_ROOT, "native")],
                   check=True, capture_output=True, timeout=180)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ProxiedCluster:
    """N replicas, each = daemon + bridge + app-under-interposer."""

    def __init__(self, n: int, app_argv: Optional[Sequence[str]] = None,
                 workdir: Optional[str] = None, spin_timeout_ms: int = 8000,
                 device_plane: bool = False,
                 follower_reads: Optional[bool] = True,
                 **cluster_kwargs):
        build_native()
        if device_plane:
            cluster_kwargs["device_plane"] = True
        self.n = n
        self.workdir = workdir or tempfile.mkdtemp(prefix="apus-proxied-")
        self.app_ports = [free_port() for _ in range(n)]
        self._app_argv = app_argv       # None -> toyserver
        self._spin_timeout_ms = spin_timeout_ms
        cluster_kwargs.setdefault("spec", PROXIED_SPEC)
        # Hermetic test rig: replica-state verification reads follower
        # apps directly, so stale follower reads default ON here; the
        # production deployments (ProcCluster/daemon CLI) default to
        # the REFUSE posture (ClusterSpec.follower_reads).  Pass
        # follower_reads=None to keep the supplied spec's own setting.
        if follower_reads is not None:
            import dataclasses as _dc
            cluster_kwargs["spec"] = _dc.replace(
                cluster_kwargs["spec"], follower_reads=follower_reads)
        self.cluster = LocalCluster(n, sm_factory=RelayStateMachine,
                                    **cluster_kwargs)
        self.bridges: list[Optional[Bridge]] = [
            Bridge(d, self.workdir, app_port=self.app_ports[i])
            for i, d in enumerate(self.cluster.daemons)
        ]
        self.apps: list[Optional[subprocess.Popen]] = [None] * n
        self._app_logs: list = [None] * n

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self.cluster.start()
        for i in range(self.n):
            self.bridges[i].start()
            self.apps[i] = self._launch_app(i)
        for i in range(self.n):
            self._wait_app(i)

    def stop(self) -> None:
        for p in self.apps:
            if p is not None and p.poll() is None:
                p.terminate()
        for p in self.apps:
            if p is not None:
                try:
                    p.wait(timeout=3.0)
                except subprocess.TimeoutExpired:
                    p.kill()
        for b in self.bridges:
            if b is not None:
                b.stop()
        self.cluster.stop()
        for i, f in enumerate(self._app_logs):
            if f is not None:
                f.close()
                self._app_logs[i] = None

    def __enter__(self) -> "ProxiedCluster":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def _launch_app(self, i: int) -> subprocess.Popen:
        argv = (list(self._app_argv) if self._app_argv is not None
                else [TOYSERVER]) + [str(self.app_ports[i])]
        env = dict(os.environ)
        env.update(proxy_env(
            self.bridges[i],
            log_path=os.path.join(self.workdir, f"proxy{i}.log"),
            spin_timeout_ms=self._spin_timeout_ms))
        if self._app_logs[i] is None:
            self._app_logs[i] = open(
                os.path.join(self.workdir, f"app{i}.out"), "ab")
        return subprocess.Popen(argv, env=env, stdout=self._app_logs[i],
                                stderr=subprocess.STDOUT)

    def _wait_app(self, i: int, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                with socket.create_connection(
                        ("127.0.0.1", self.app_ports[i]), timeout=0.5):
                    return
            except OSError:
                time.sleep(0.05)
        raise AssertionError(f"app {i} did not come up")

    # -- fault injection --------------------------------------------------

    def kill(self, idx: int) -> None:
        """Crash one replica: app + bridge + daemon (the reconf_bench
        kill -2 analog, reconf_bench.sh:100-117)."""
        p = self.apps[idx]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait(timeout=3.0)
        self.apps[idx] = None
        b = self.bridges[idx]
        if b is not None:
            b.stop()
        self.bridges[idx] = None
        self.cluster.kill(idx)

    # -- queries ----------------------------------------------------------

    def leader_idx(self, timeout: float = 15.0) -> int:
        d = self.cluster.wait_for_leader(timeout)
        return d.idx

    def app_addr(self, idx: int) -> tuple[str, int]:
        return ("127.0.0.1", self.app_ports[idx])

    # -- leader-aware client helper ---------------------------------------

    def write_round(self, cmds: Sequence[str],
                    attempts: int = 5) -> tuple[int, list[str]]:
        """Issue commands to the leader's app, retrying the whole round
        if leadership moved mid-round.  Real APUS clients chase the
        leader the same way: capture is leader-gated (proxy.c:108), so
        bytes written to a deposed leader's app bypass replication and
        the round must be re-issued against the new leader."""
        for _ in range(attempts):
            leader = self.leader_idx()
            try:
                with LineClient(self.app_addr(leader)) as c:
                    replies = [c.cmd(cmd) for cmd in cmds]
            except OSError:
                continue
            d = self.cluster.daemons[leader]
            if d is not None and d.node.is_leader:
                return leader, replies
        raise AssertionError("no stable leadership for a full write round")


#: Pinned unmodified redis (the reference's flagship app, apps/redis/mk)
#: built by apps/redis/mk; ./run launches it under the interposer.
REDIS_RUN = os.path.join(REPO_ROOT, "apps", "redis", "run")
REDIS_SERVER = os.path.join(REPO_ROOT, "apps", "redis", "build",
                            "redis-2.8.17", "src", "redis-server")
#: Default tarball location (apps/redis/mk reads the same env knob).
REDIS_TARBALL = os.environ.get(
    "APUS_REDIS_TARBALL",
    "/root/reference/apps/redis/redis-2.8.17.tar.gz")

#: Pinned unmodified ssdb (the reference's third app, apps/ssdb/mk) —
#: speaks the redis protocol, so RespClient drives it too.
SSDB_RUN = os.path.join(REPO_ROOT, "apps", "ssdb", "run")
SSDB_SERVER = os.path.join(REPO_ROOT, "apps", "ssdb", "build",
                           "ssdb-master", "ssdb-server")
SSDB_TARBALL = os.environ.get(
    "APUS_SSDB_TARBALL", "/root/reference/apps/ssdb/master.tar.gz")

#: Pinned unmodified memcached (the reference's second app,
#: apps/memcached/mk,run) — built against the libevent compat shim
#: when the image lacks libevent-dev (apps/memcached/compat).
MEMCACHED_RUN = os.path.join(REPO_ROOT, "apps", "memcached", "run")
#: Stock load generator (apps/memcached/run:22-28 parity), built from
#: the vendored libmemcached tarball by apps/memcached/mk.
MEMSLAP = os.path.join(REPO_ROOT, "apps", "memcached", "build",
                       "libmemcached-1.0.18", "clients", "memslap")
MEMCACHED_SERVER = os.path.join(REPO_ROOT, "apps", "memcached", "build",
                                "memcached-1.4.21", "memcached")
MEMCACHED_TARBALL = os.environ.get(
    "APUS_MEMCACHED_TARBALL",
    "/root/reference/apps/memcached/memcached-1.4.21.tar.gz")


def build_ssdb() -> bool:
    return _build_app(SSDB_SERVER, "ssdb", timeout=600)


def build_memcached() -> bool:
    # memslap (the stock benchmark client) is built by the same mk; a
    # tree where only the server exists (pre-memslap build, or a failed
    # clients build) must re-run mk or the stock-client rung silently
    # never executes.  The mk's own early-exit keeps the rebuilt case
    # cheap, and memslap stays best-effort (server presence decides).
    if os.path.exists(MEMCACHED_SERVER) and not os.path.exists(MEMSLAP):
        mk = os.path.join(REPO_ROOT, "apps", "memcached", "mk")
        try:
            subprocess.run([mk], check=False, capture_output=True,
                           timeout=600)
        except (subprocess.TimeoutExpired, OSError):
            pass
    return _build_app(MEMCACHED_SERVER, "memcached", timeout=300)


def _build_app(server_path: str, app_dir: str, timeout: float) -> bool:
    """Build a pinned third-party app via its apps/<name>/mk script.
    Returns False when the binary can't be produced (no tarball /
    missing build deps) — callers skip app-specific paths."""
    if os.path.exists(server_path):
        return True
    mk = os.path.join(REPO_ROOT, "apps", app_dir, "mk")
    try:
        subprocess.run([mk], check=True, capture_output=True,
                       timeout=timeout)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            OSError):
        return False
    return os.path.exists(server_path)


def build_redis() -> bool:
    return _build_app(REDIS_SERVER, "redis", timeout=300)


class _CrlfClient:
    """Shared buffered-TCP plumbing for the CRLF-framed app clients
    (RESP and memcached text protocol)."""

    proto = "app"

    def __init__(self, addr: tuple[str, int], timeout: float = 10.0):
        self.sock = socket.create_connection(addr, timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = b""

    def _line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError(f"{self.proto} closed connection")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError(f"{self.proto} closed connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def close(self) -> None:
        self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RespClient(_CrlfClient):
    """Minimal RESP (redis protocol) client — the redis-benchmark stand-
    in for driving SET/GET at a replicated redis (run.sh:70-80)."""

    proto = "redis"

    def cmd(self, *args: str | bytes):
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            b = a.encode() if isinstance(a, str) else a
            out.append(b"$%d\r\n%s\r\n" % (len(b), b))
        self.sock.sendall(b"".join(out))
        return self._reply()

    def pipeline_cmds(self, cmds: list[tuple]) -> list:
        """redis-benchmark -P analog: write every command in one
        coalesced flush, then read all replies — through the
        interposer this lands a burst of captured records at the
        leader in one go, exercising the daemon's group-commit drain."""
        out = []
        for args in cmds:
            out.append(b"*%d\r\n" % len(args))
            for a in args:
                b = a.encode() if isinstance(a, str) else a
                out.append(b"$%d\r\n%s\r\n" % (len(b), b))
        self.sock.sendall(b"".join(out))
        return [self._reply() for _ in cmds]

    def _reply(self):
        line = self._line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            raise RuntimeError(rest.decode())
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            if n < 0:
                return None
            data = self._exact(n)
            self._exact(2)                       # trailing CRLF
            return data
        if t == b"*":
            return [self._reply() for _ in range(int(rest))]
        raise RuntimeError(f"bad RESP type byte {t!r}")


class McClient(_CrlfClient):
    """Minimal memcached text-protocol client — the memslap stand-in
    for driving set/get at a replicated memcached (the reference
    drives it with memslap --concurrency=10 --execute-number=5000,
    apps/memcached/run:22-28)."""

    proto = "memcached"

    def set(self, key: str, value: str | bytes) -> bool:
        v = value.encode() if isinstance(value, str) else value
        self.sock.sendall(b"set %s 0 0 %d\r\n%s\r\n"
                          % (key.encode(), len(v), v))
        reply = self._line()
        if reply.startswith((b"ERROR", b"CLIENT_ERROR", b"SERVER_ERROR")):
            raise RuntimeError(reply.decode())
        return reply == b"STORED"

    def get(self, key: str) -> bytes | None:
        self.sock.sendall(b"get %s\r\n" % key.encode())
        line = self._line()
        if line == b"END":
            return None
        if not line.startswith(b"VALUE "):
            raise RuntimeError(f"bad get reply {line!r}")
        n = int(line.rsplit(b" ", 1)[1])
        data = self._exact(n)
        self._exact(2)                           # trailing CRLF
        end = self._line()
        if end != b"END":
            raise RuntimeError(f"bad get terminator {end!r}")
        return data

    def stat(self, name: str) -> int:
        """One numeric field from ``stats`` (e.g. curr_items)."""
        self.sock.sendall(b"stats\r\n")
        value = -1
        while True:
            line = self._line()
            if line == b"END":
                return value
            parts = line.split()
            if len(parts) == 3 and parts[1] == name.encode():
                value = int(parts[2])


class LineClient:
    """Tiny line-protocol client for toyserver-style apps."""

    def __init__(self, addr: tuple[str, int], timeout: float = 10.0):
        self.sock = socket.create_connection(addr, timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = b""

    def cmd(self, line: str) -> str:
        self.sock.sendall(line.encode() + b"\n")
        return self._reply()

    def pipeline_cmds(self, lines: list[str]) -> list[str]:
        """Pipelined line-protocol burst: one coalesced write, then all
        replies (see RespClient.pipeline_cmds)."""
        self.sock.sendall(b"".join(ln.encode() + b"\n" for ln in lines))
        return [self._reply() for _ in lines]

    def _reply(self) -> str:
        while b"\n" not in self._buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("app closed connection")
            self._buf += chunk
        out, self._buf = self._buf.split(b"\n", 1)
        return out.decode()

    def close(self) -> None:
        self.sock.close()

    def __enter__(self) -> "LineClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
